"""Shared full-stack test harness: simulated cluster + metrics pipeline +
monitor + executor + facade (SURVEY.md §4 tier-3 "embedded cluster"
equivalent — everything in-process and deterministic)."""

import numpy as np

from cruise_control_tpu.executor.backend import SimulatedClusterBackend
from cruise_control_tpu.executor.executor import Executor, ExecutorConfig
from cruise_control_tpu.facade import CruiseControl
from cruise_control_tpu.monitor.load_monitor import (
    BackendMetadataClient,
    LoadMonitor,
)
from cruise_control_tpu.monitor.sampling import (
    MetricsReporterSampler,
    MetricsTopic,
    SimulatedMetricsReporter,
    WorkloadModel,
)

WINDOW = 1000


def skewed_workload(num_partitions=24, num_brokers=4, rf=2, seed=11,
                    extra_brokers=()):
    """All leaders piled onto broker 0 — plenty for goals to fix."""
    rng = np.random.default_rng(seed)
    assignment = {
        p: [0, 1 + p % (num_brokers - 1)][:rf] for p in range(num_partitions)
    }
    leaders = {p: assignment[p][0] for p in range(num_partitions)}
    w = WorkloadModel(
        bytes_in=rng.uniform(100, 1000, num_partitions),
        bytes_out=rng.uniform(100, 2000, num_partitions),
        size_mb=rng.uniform(10, 500, num_partitions),
        assignment=assignment,
        leaders=leaders,
    )
    brokers = set(range(num_brokers)) | set(extra_brokers)
    return w, brokers


def full_stack(
    num_partitions=24,
    num_brokers=4,
    rf=2,
    windows=3,
    extra_brokers=(),
    failed_brokers=None,
    engine="greedy",
    executor_config=None,
    jbod_disks=None,
    registry=None,
):
    """Build the whole system over a skewed simulated cluster.

    ``jbod_disks``: dict of dir name → capacity MB to give EVERY broker a
    JBOD layout; initial replicas all land on the first dir (skewed).
    ``registry``: a private MetricRegistry for tests that assert exact
    metric values — the default shares the process-wide registry, whose
    counters accumulate across every test in the run.
    Returns (cruise_control, backend, reporter).
    """
    w, brokers = skewed_workload(
        num_partitions, num_brokers, rf, extra_brokers=extra_brokers
    )
    backend = SimulatedClusterBackend(
        {p: list(r) for p, r in w.assignment.items()},
        dict(w.leaders),
        brokers=brokers,
        failed_brokers=failed_brokers,
    )
    capacity_resolver = None
    if jbod_disks:
        from cruise_control_tpu.common.resources import Resource
        from cruise_control_tpu.monitor.capacity import StaticCapacityResolver

        first = sorted(jbod_disks)[0]
        for p, reps in w.assignment.items():
            for b in reps:
                backend.replica_dir[(p, b)] = first
        capacity_resolver = StaticCapacityResolver(
            {Resource.CPU: 100.0, Resource.NW_IN: 1e5, Resource.NW_OUT: 1e5},
            disk_capacities=dict(jbod_disks),
        )
    broker_rack = {b: b % 2 for b in sorted(brokers)}
    topic = MetricsTopic()
    reporter = SimulatedMetricsReporter(w, topic)
    monitor = LoadMonitor(
        BackendMetadataClient(backend, broker_rack),
        MetricsReporterSampler(topic),
        capacity_resolver=capacity_resolver,
        window_ms=WINDOW,
        num_windows=5,
    )
    for wdx in range(windows):
        reporter.report(time_ms=wdx * WINDOW + 500)
        monitor.run_sampling_iteration((wdx + 1) * WINDOW)
    executor = Executor(backend, executor_config or ExecutorConfig())
    cc = CruiseControl(monitor, executor, engine=engine, registry=registry)
    return cc, backend, reporter
