"""Telemetry subsystem tests: span nesting + ring bounds, disabled-mode
no-op behavior, phase-tree aggregation determinism, Prometheus exposition
format, and the ``GET /metrics`` + ``/state?verbose`` server contracts
(test_ui_contract.py style — raw HTTP, exactly as a scraper sees it)."""

import json
import re
import time
import urllib.request

import pytest

from cruise_control_tpu.server import CruiseControlHttpServer
from cruise_control_tpu.telemetry import profile, tracing
from cruise_control_tpu.telemetry.exposition import render_prometheus
from cruise_control_tpu.utils.metrics import MetricRegistry

from harness import full_stack


@pytest.fixture
def tel():
    """Isolated Telemetry instance (the module singleton stays untouched)."""
    return tracing.Telemetry(enabled=True, ring_size=4)


@pytest.fixture
def global_tracing():
    """Enable the process-wide tracer for server-path tests; restore after."""
    tracing.configure(enabled=True, ring_size=64)
    yield tracing.TELEMETRY
    tracing.configure(enabled=False)
    tracing.reset()


# ---- span mechanics -----------------------------------------------------------
def test_span_nesting_and_paths(tel):
    with tel.span("op") as root:
        root.set("k", "v")
        with tel.span("child", sub="x"):
            pass
        with tel.span("child", sub="y"):
            pass
    roots = tel.recent_roots()
    assert len(roots) == 1
    assert roots[0]["name"] == "op"
    assert roots[0]["attrs"] == {"k": "v"}
    assert [c["name"] for c in roots[0]["children"]] == ["child.x", "child.y"]
    agg = tel.aggregates()
    assert set(agg) == {"op", "op/child.x", "op/child.y"}
    assert agg["op"][0] == 1


def test_ring_buffer_is_bounded(tel):
    for i in range(11):
        with tel.span("root"):
            pass
    assert len(tel.recent_roots(100)) == tel.ring_size == 4
    # aggregation still counts every completed span
    assert tel.aggregates()["root"][0] == 11


def test_nested_spans_roll_up_to_direct_parent_only(tel):
    with tel.span("a"):
        with tel.span("b"):
            with tel.span("c"):
                time.sleep(0.002)
    tree = profile.phase_tree(tel)
    assert set(tree) == {"a", "a/b", "a/b/c"}
    # self time excludes only DIRECT children; c's time shows in b's
    # children roll-up, not a's
    assert tree["a/b"]["self_s"] <= tree["a/b"]["total_s"]
    assert tree["a"]["total_s"] >= tree["a/b"]["total_s"]


def test_disabled_mode_is_noop():
    t = tracing.Telemetry(enabled=False)
    s = t.span("never", sub="formatted")
    assert s is tracing.NOOP
    with s as sp:
        sp.set("ignored", 1)
        assert sp.block("value") == "value"
    assert t.device_span("never") is tracing.NOOP
    t.annotate("ignored", 2)
    assert t.recent_roots() == []
    assert t.aggregates() == {}


def test_exception_inside_span_still_closes_and_tags(tel):
    with pytest.raises(ValueError):
        with tel.span("boom"):
            raise ValueError("x")
    roots = tel.recent_roots()
    assert roots[0]["attrs"]["error"] == "ValueError"
    # the stack is clean: the next span is a fresh root
    with tel.span("after"):
        pass
    assert tel.recent_roots()[0]["name"] == "after"


def test_phase_tree_aggregation_determinism(tel):
    def workload(t):
        for _ in range(3):
            with t.span("req"):
                with t.span("model"):
                    pass
                with t.span("optimize"):
                    with t.span("score"):
                        pass

    workload(tel)
    other = tracing.Telemetry(enabled=True)
    workload(other)
    t1, t2 = profile.phase_tree(tel), profile.phase_tree(other)
    assert list(t1) == list(t2)  # sorted, identical structure
    assert [v["count"] for v in t1.values()] == [
        v["count"] for v in t2.values()
    ]
    assert t1["req"]["count"] == 3
    assert t1["req/optimize/score"]["count"] == 3
    for ent in t1.values():
        assert 0.0 <= ent["self_s"] <= ent["total_s"]


def test_artifact_schema(tel, tmp_path):
    with tel.span("phase"):
        pass
    out = tmp_path / "profile.json"
    written = profile.write_artifact(str(out), extra={"total_s": 1.0},
                                     tel=tel)
    loaded = json.loads(out.read_text())
    assert loaded == written
    assert loaded["schema"] == profile.SCHEMA
    assert loaded["total_s"] == 1.0
    assert loaded["phases"]["phase"]["count"] == 1


# ---- Prometheus exposition ------------------------------------------------------
_METRIC_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"                      # metric name
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'    # first label
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})?'
    r" [-+]?(\d+\.?\d*([eE][-+]?\d+)?|NaN|Inf)$"
)
_COMMENT_LINE = re.compile(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+$")


def _assert_valid_exposition(text: str) -> int:
    """Validate every line against the text-format grammar; returns the
    number of sample lines."""
    samples = 0
    assert text.endswith("\n")
    for line in text.rstrip("\n").split("\n"):
        if line.startswith("#"):
            assert _COMMENT_LINE.match(line), line
        else:
            assert _METRIC_LINE.match(line), line
            samples += 1
    return samples


def test_prometheus_exposition_format(tel):
    reg = MetricRegistry()
    reg.counter("ops").inc(2)
    reg.meter("http.GET.state").mark(3)
    with reg.timer("proposal-computation-timer"):
        pass
    reg.gauge("up", lambda: 1.0)
    reg.gauge("broken", lambda: "error: nope")  # must be skipped, not fatal
    with tel.span('weird"phase\\name'):
        pass
    text = render_prometheus(reg, tel)
    assert _assert_valid_exposition(text) >= 10
    assert "cc_ops_total 2.0" in text
    assert "cc_http_GET_state_total 3.0" in text
    assert "cc_proposal_computation_timer_seconds_count 1.0" in text
    # timers are true histograms now: log-spaced buckets + +Inf catch-all
    assert "# TYPE cc_proposal_computation_timer_seconds histogram" in text
    assert ('cc_proposal_computation_timer_seconds_bucket{le="+Inf"} 1.0'
            in text)
    assert "cc_up 1.0" in text
    assert "broken" not in text
    # label escaping keeps the scrape parseable
    assert '\\"' in text and "\\\\" in text


def test_exposition_without_telemetry_still_valid():
    reg = MetricRegistry()
    reg.counter("only").inc()
    assert _assert_valid_exposition(render_prometheus(reg)) == 1


# ---- server contract ------------------------------------------------------------
@pytest.fixture
def server(global_tracing):
    cc, backend, _ = full_stack()
    srv = CruiseControlHttpServer(cc, port=0)
    srv.start()
    yield srv, cc
    srv.stop()


def _get_raw(srv, path):
    with urllib.request.urlopen(f"{srv.url}/{path}") as r:
        return r.read().decode(), r.status, dict(r.headers)


def test_metrics_endpoint_serves_prometheus_text(server):
    srv, _ = server
    # generate traffic so meters + request spans exist; the request span
    # closes a hair after the response flushes, so poll for its phase line
    _get_raw(srv, "state")
    deadline = time.monotonic() + 10
    body, status, headers = _get_raw(srv, "metrics")
    while (time.monotonic() < deadline
           and 'cc_phase_seconds_total{phase="http.GET.state"}' not in body):
        time.sleep(0.05)
        body, status, headers = _get_raw(srv, "metrics")
    assert status == 200
    assert headers["Content-Type"].startswith("text/plain")
    assert _assert_valid_exposition(body) > 0
    # servlet request meter for the state hit
    assert "cc_http_GET_state_total" in body
    # span-derived phase timers with the request-span phase label
    assert 'cc_phase_seconds_total{phase="http.GET.state"}' in body
    # the shared registry's operation timer family is exposed once used
    json.loads(urllib.request.urlopen(
        f"{srv.url}/proposals").read())  # drives proposal-computation-timer
    body2, _, _ = _get_raw(srv, "metrics")
    assert "cc_proposal_computation_timer_seconds_count" in body2
    # operation span nests under the request span in the phase path
    assert "/facade.proposals/facade.optimize" in body2


def test_state_verbose_exposes_recent_spans(server):
    srv, _ = server
    _get_raw(srv, "state")
    # the request span closes a hair after the response flushes — poll
    # instead of racing it
    deadline = time.monotonic() + 10
    names = []
    while time.monotonic() < deadline:
        body, _, _ = _get_raw(srv, "state?verbose=true")
        st = json.loads(body)
        tele = st["Telemetry"]
        assert tele["enabled"] is True
        names = [s["name"] for s in tele["recentSpans"]]
        if any(n.startswith("http.GET.state") for n in names):
            break
        time.sleep(0.05)
    assert any(n.startswith("http.GET.state") for n in names), names
    # non-verbose stays lean: no span payload in the 5s-poll response
    lean = json.loads(_get_raw(srv, "state")[0])
    assert "Telemetry" not in lean


def test_request_span_carries_user_task_id(server):
    srv, _ = server
    req = urllib.request.Request(
        f"{srv.url}/rebalance?dryrun=true", method="POST"
    )
    with urllib.request.urlopen(req) as r:
        task_id = r.headers.get("User-Task-ID")
        json.loads(r.read())
    assert task_id
    deadline = time.monotonic() + 30
    correlated = False
    while time.monotonic() < deadline and not correlated:
        spans = tracing.recent_roots(64)
        correlated = any(
            s["name"] == "http.POST.rebalance"
            and s.get("attrs", {}).get("user_task_id") == task_id
            for s in spans
        )
        if not correlated:
            time.sleep(0.1)
    assert correlated, "request span must carry the submitted User-Task-ID"
