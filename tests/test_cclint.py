"""cclint framework tests (ISSUE 4; whole-program phase ISSUE 10).

Five contracts:

* **rules** — every registered rule catches its positive fixtures and
  stays quiet on its negatives; a meta-test proves the fixture tables
  (per-file snippets AND cross-module fixture packages) cover the whole
  registry, so adding a rule without fixtures fails CI;
* **suppressions** — ``# cclint: disable=rule -- reason`` is honored,
  a reasonless or unknown-rule suppression is itself a finding, and
  every suppression checked into the package is load-bearing (stripping
  any one of them re-surfaces its finding at the same file:line);
* **output** — the JSON format matches the checked-in
  ``tests/schemas/lint.schema.json`` contract (closed finding record)
  and ``--format sarif`` matches ``tests/schemas/sarif.schema.json``;
* **the whole-program phase** — the symbol graph / call graph resolve
  the repo's idioms, ``--changed-only`` re-lints reverse-dependents via
  the import graph, and the incremental cache short-circuits parses on
  warm runs without changing findings;
* **the tree is clean** — the full pass over ``cruise_control_tpu/``
  yields zero findings in < 5 s, cold AND cache-warm (single parse per
  file).
"""

import json
import pathlib
import re

import pytest

from cruise_control_tpu.devtools.lint import (
    BAD_SUPPRESSION,
    FileContext,
    RULES,
    parse_suppressions,
    render,
    run_lint,
)
from cruise_control_tpu.devtools.lint.__main__ import main as cclint_main
from cruise_control_tpu.devtools.lint.rules_config import (
    doc_keys,
    used_keys,
)
from test_artifact_schemas import validate

PKG = pathlib.Path(__file__).resolve().parent.parent / "cruise_control_tpu"

#: rules that run in phase 2 over the project graph (no check_file)
PROJECT_RULES = {
    rule_id for rule_id, rule in RULES.items()
    if getattr(rule, "project_rule", False)
}


@pytest.fixture(autouse=True)
def _isolated_cache(tmp_path_factory, monkeypatch):
    """Every test runs against its own .cclint_cache so fixture entries
    never leak into the repo store (and vice versa)."""
    monkeypatch.setenv(
        "CCLINT_CACHE_DIR",
        str(tmp_path_factory.mktemp("cclint_cache")),
    )


def findings_for(rule_id: str, code: str):
    ctx = FileContext.parse("fixture.py", code)
    return RULES[rule_id].check_file(ctx)


# ---- per-rule fixtures ----------------------------------------------------------
# rule id -> (positive snippets that MUST flag, negative snippets that
# must NOT).  config-key-drift is a project rule; its fixtures run
# through its pure helpers below but are listed here so the meta-test
# sees full registry coverage.
RULE_FIXTURES = {
    "lock-discipline": {
        "positive": [
            # lockset inconsistency: guarded in one method, naked in another
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._items = []\n"
            "    def add(self, x):\n"
            "        with self._lock:\n"
            "            self._items.append(x)\n"
            "    def drop_all(self):\n"
            "        self._items.clear()\n",
            # cross-thread write: daemon loop writes, public method reads
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._last = None\n"
            "    def start(self):\n"
            "        def loop():\n"
            "            self._last = 1\n"
            "        threading.Thread(target=loop).start()\n"
            "    def summary(self):\n"
            "        return {'last': self._last}\n",
        ],
        "negative": [
            # everything under the lock (helper called only while held)
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._items = []\n"
            "    def add(self, x):\n"
            "        with self._lock:\n"
            "            self._record(x)\n"
            "    def _record(self, x):\n"
            "        self._items.append(x)\n"
            "    def snapshot(self):\n"
            "        with self._lock:\n"
            "            return list(self._items)\n",
            # thread-safe primitives are out of scope; __init__ is exempt
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._stop = threading.Event()\n"
            "        self._data = {}\n"
            "    def start(self):\n"
            "        self._stop.clear()\n"
            "    def stop(self):\n"
            "        self._stop.set()\n",
            # no lock attribute -> class out of scope entirely
            "class C:\n"
            "    def set(self, x):\n"
            "        self._x = x\n"
            "    def get(self):\n"
            "        return self._x\n",
        ],
    },
    "jax-hot-path": {
        "positive": [
            # host sync inside a decorated jit function
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return float(x.item())\n",
            # print inside a function passed to jax.jit by name
            "import jax\n"
            "def make():\n"
            "    def run(m):\n"
            "        print(m)\n"
            "        return m\n"
            "    return jax.jit(run)\n",
            # branching on a traced parameter
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    if x > 0:\n"
            "        return x\n"
            "    return -x\n",
            # np.asarray materializes on host
            "import jax, numpy as np\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return np.asarray(x)\n",
            # retrace risk: f-string argument to a jitted callable
            "import jax\n"
            "@jax.jit\n"
            "def f(x, tag):\n"
            "    return x\n"
            "def caller(x, name):\n"
            "    return f(x, f'tag-{name}')\n",
            # concretizing a traced parameter
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return [0.0] * int(x)\n",
        ],
        "negative": [
            # the structural-None default idiom is NOT data branching
            "import jax\n"
            "import jax.numpy as jnp\n"
            "@jax.jit\n"
            "def f(x, t_cap=None):\n"
            "    if t_cap is None:\n"
            "        t_cap = jnp.int32(8)\n"
            "    return x * t_cap\n",
            # static args may branch (resolved at trace time)
            "import functools, jax\n"
            "@functools.partial(jax.jit, static_argnums=(1,))\n"
            "def f(x, mode):\n"
            "    if mode:\n"
            "        return x + 1\n"
            "    return x\n",
            # host syncs OUTSIDE jit are fine
            "import numpy as np\n"
            "def fetch(x):\n"
            "    print(x)\n"
            "    return float(np.asarray(x).sum())\n",
        ],
    },
    "config-key-drift": {
        # project rule: exercised via used-key extraction against the
        # live registry and doc-table parsing (see tests below)
        "positive": ["cfg.get_int('no.such.key')\n"],
        "negative": ["cfg.get_int('tpu.search.max.rounds')\n"],
    },
    "obs-dynamic-name": {
        "positive": [
            # unguarded f-string span name
            "def f(m):\n"
            "    with tracing.span(f'http.{m}'):\n"
            "        pass\n",
            # dynamic event kind
            "def f(op):\n"
            "    events.emit(f'optimize.{op}')\n",
            # dynamic metric name (no enabled() escape)
            "def f(registry, name):\n"
            "    registry.counter(f'ops.{name}').inc()\n",
        ],
        "negative": [
            # guarded span, static metric, static kind
            "def f(registry, m, op):\n"
            "    if tracing.enabled():\n"
            "        s = tracing.span('http', sub=f'{m}')\n"
            "    registry.counter('ops').inc()\n"
            "    events.emit('optimize.start', operation=op)\n",
            # dict .get homonym is not a metric call
            "def f(d, k):\n"
            "    return d.counter(f'x.{k}') if hasattr(d, 'x') else None\n",
        ],
    },
    "retry-discipline": {
        "positive": [
            # constant backoff + unbounded: hammers the dependency forever
            "import time\n"
            "def fetch(conn):\n"
            "    while True:\n"
            "        try:\n"
            "            return conn.read()\n"
            "        except Exception:\n"
            "            time.sleep(5)\n",
            # bounded, but still a fixed cadence — no backoff, no jitter
            "import time\n"
            "def poll(conn):\n"
            "    for _ in range(3):\n"
            "        try:\n"
            "            return conn.read()\n"
            "        except OSError:\n"
            "            time.sleep(1.0)\n",
            # unbounded even with a computed delay: no exit on failure
            "import time\n"
            "def settle(conn, backoff):\n"
            "    while True:\n"
            "        try:\n"
            "            return conn.read()\n"
            "        except OSError:\n"
            "            time.sleep(backoff())\n",
        ],
        "negative": [
            # exponential backoff with a bounded attempt budget
            "import time\n"
            "def fetch(conn):\n"
            "    delay = 0.1\n"
            "    for attempt in range(5):\n"
            "        try:\n"
            "            return conn.read()\n"
            "        except OSError:\n"
            "            time.sleep(delay)\n"
            "            delay = min(delay * 2, 2.0)\n"
            "    raise TimeoutError('gave up')\n",
            # while True, but the failure path escalates (raise bound)
            "import time\n"
            "def fetch(conn, deadline, backoff):\n"
            "    while True:\n"
            "        try:\n"
            "            return conn.read()\n"
            "        except OSError:\n"
            "            if time.time() > deadline:\n"
            "                raise\n"
            "            time.sleep(backoff())\n",
            # daemon service loop without a sleep: swallowed-exception's
            # beat, not a retry loop
            "def loop(stop, work):\n"
            "    while not stop.is_set():\n"
            "        try:\n"
            "            work()\n"
            "        except Exception:\n"
            "            LOG.exception('tick failed')\n",
            # sleep in a loop without exception handling: a poll pace,
            # not a retry
            "import time\n"
            "def wait_for(cond):\n"
            "    while not cond():\n"
            "        time.sleep(0.5)\n",
        ],
    },
    "bounded-resource": {
        "positive": [
            # unbounded deque: overload becomes memory growth, not
            # backpressure
            "from collections import deque\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self.buffer = deque()\n",
            # Queue() with no maxsize (module-qualified)
            "import queue\n"
            "def make():\n"
            "    return queue.Queue()\n",
            # SimpleQueue has no bound at all
            "import queue\n"
            "def make():\n"
            "    return queue.SimpleQueue()\n",
            # pool with the implicit cpu-scaled default worker count
            "from concurrent.futures import ThreadPoolExecutor\n"
            "def pool():\n"
            "    return ThreadPoolExecutor()\n",
            # an explicit None bound is still unbounded
            "from collections import deque\n"
            "def ring():\n"
            "    return deque([], None)\n",
        ],
        "negative": [
            # bounds as keywords (values may be variables)
            "from collections import deque\n"
            "def ring(n):\n"
            "    return deque(maxlen=n)\n",
            "import queue\n"
            "def make(cap):\n"
            "    return queue.Queue(maxsize=cap)\n",
            "from concurrent.futures import ThreadPoolExecutor\n"
            "def pool(n):\n"
            "    return ThreadPoolExecutor(max_workers=n)\n",
            # positional bounds count too
            "import queue\n"
            "def make():\n"
            "    return queue.Queue(128)\n",
            "from concurrent.futures import ThreadPoolExecutor\n"
            "def pool():\n"
            "    return ThreadPoolExecutor(4)\n",
            # **kwargs may carry the bound — benefit of the doubt
            "from collections import deque\n"
            "def ring(**kw):\n"
            "    return deque(**kw)\n",
            # attribute chains that merely end in a matching name are
            # out of scope (factory.pools.Queue() is not queue.Queue)
            "def make(factory):\n"
            "    return factory.pools.Queue()\n",
        ],
    },
    "cache-key-discipline": {
        "positive": [
            # keyed cache, no generation term, no invalidate path: a
            # stale plan is served as fresh forever
            "class PlanCache:\n"
            "    def __init__(self):\n"
            "        self._plan_cache = {}\n"
            "    def put(self, topic, plan):\n"
            "        self._plan_cache[topic] = plan\n",
            # attribute cache with no freshness companion at all
            "class C:\n"
            "    def refresh(self, model):\n"
            "        self._cached_plan = self._compute(model)\n",
            # memo keyed on a raw tuple without a version component
            "class C:\n"
            "    def __init__(self):\n"
            "        self._memo = {}\n"
            "    def bounds(self, b, r):\n"
            "        self._memo[(b, r)] = self._derive(b, r)\n",
        ],
        "negative": [
            # generation term in the key
            "class C:\n"
            "    def __init__(self):\n"
            "        self._plan_cache = {}\n"
            "    def put(self, topic, generation, plan):\n"
            "        self._plan_cache[(topic, generation)] = plan\n",
            # clear-on-mutation: invalidate() empties the memo
            "class C:\n"
            "    def __init__(self):\n"
            "        self._memo = {}\n"
            "    def memo(self, key, fn):\n"
            "        self._memo[key] = fn()\n"
            "    def invalidate(self):\n"
            "        self._memo.clear()\n",
            # TTL sibling store records when the cache was filled
            "import time\n"
            "class C:\n"
            "    def refresh(self, model):\n"
            "        self._cached_plan = self._compute(model)\n"
            "        self._cached_at = time.time()\n",
            # the cached value itself carries its generation
            "class C:\n"
            "    def refresh(self, model, gen):\n"
            "        self._cached_plan = CachedPlan(plan=model,\n"
            "                                       generation=gen)\n",
            # locks named like caches are infrastructure, not caches
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._cache_lock = threading.Lock()\n",
            # storing None/empty IS the invalidation, never flagged
            "class C:\n"
            "    def invalidate_cache(self):\n"
            "        self._cached_plan = None\n",
        ],
    },
    "swallowed-exception": {
        "positive": [
            "def loop(work):\n"
            "    while True:\n"
            "        try:\n"
            "            work()\n"
            "        except Exception:\n"
            "            pass\n",
            "def drain(items):\n"
            "    for it in items:\n"
            "        try:\n"
            "            it.close()\n"
            "        except:\n"
            "            continue\n",
        ],
        "negative": [
            # logged -> fine
            "def loop(work):\n"
            "    while True:\n"
            "        try:\n"
            "            work()\n"
            "        except Exception:\n"
            "            LOG.exception('tick failed')\n",
            # narrow catch -> fine
            "def loop(work):\n"
            "    while True:\n"
            "        try:\n"
            "            work()\n"
            "        except KeyError:\n"
            "            pass\n",
            # not in a loop -> out of scope
            "def once(work):\n"
            "    try:\n"
            "        work()\n"
            "    except Exception:\n"
            "        pass\n",
        ],
    },
}


# ---- cross-module fixture packages ----------------------------------------------
# rule id -> positive/negative lists of fixture PACKAGES: {relpath: code}
# written under one tmp root; the package dir "pkg/" is the lint target,
# sibling paths (tests/schemas/...) let journal-schema resolve its
# registry exactly like the real tree does.
_XLOCK_STORE = (
    "import threading\n"
    "class Store:\n"
    "    def __init__(self):\n"
    "        self._lock = threading.Lock()\n"
    "        self.items = []\n"
    "    def add(self, x):\n"
    "        with self._lock:\n"
    "            self.items.append(x)\n"
)

_DEADLINE_WORKER = (
    "import threading\n"
    "class Worker:\n"
    "    def __init__(self):\n"
    "        self.done_event = threading.Event()\n"
    "    def finish(self):\n"
    "        self.done_event.wait({wait_args})\n"
)
_DEADLINE_SERVER = (
    "from http.server import BaseHTTPRequestHandler\n"
    "from pkg.worker import Worker\n"
    "class App:\n"
    "    def __init__(self):\n"
    "        self.worker = Worker()\n"
    "    def start(self):\n"
    "        app = self\n"
    "        class Handler(BaseHTTPRequestHandler):\n"
    "            def do_GET(self):\n"
    "                app.worker.finish()\n"
    "        return Handler\n"
)

_SCHEMA_REGISTRY = json.dumps({
    "cc-tpu-events/1": {
        "properties": {"severity": {
            "enum": ["DEBUG", "INFO", "WARNING", "ERROR"]}},
        "x-kinds": {
            "optimize.start": {"fields": ["engine"]},
            "optimize.end": {"fields": ["durationS"]},
        },
    }
})
_SCHEMA_EVENTS_STUB = "def emit(kind, severity='INFO', **payload):\n    pass\n"

# a named-lock stub: the concurrency rules key on the CONSTRUCTOR NAME
# (`InstrumentedLock("...")` literals anchor the vocabulary), so fixture
# packages carry their own minimal class instead of importing the real
# utils/locks (fixtures must lint in isolation)
_ILOCK_STUB = (
    "import threading\n"
    "class InstrumentedLock:\n"
    "    def __init__(self, name):\n"
    "        self.name = name\n"
    "        self._inner = threading.Lock()\n"
    "    def acquire(self, blocking=True, timeout=-1):\n"
    "        return self._inner.acquire(blocking, timeout)\n"
    "    def release(self):\n"
    "        self._inner.release()\n"
    "    def __enter__(self):\n"
    "        return self._inner.__enter__()\n"
    "    def __exit__(self, *exc):\n"
    "        return self._inner.__exit__(*exc)\n"
)

PACKAGE_FIXTURES = {
    "cross-module-lock": {
        "positive": [
            # off-lock write from ANOTHER module to a guarded attribute
            {
                "pkg/store.py": _XLOCK_STORE,
                "pkg/other.py": (
                    "from pkg.store import Store\n"
                    "class Holder:\n"
                    "    def __init__(self):\n"
                    "        self._store = Store()\n"
                    "    def reset_all(self):\n"
                    "        self._store.items = []\n"
                ),
            },
            # helper function writes through a parameter; its one call
            # site does NOT hold the lock
            {
                "pkg/store.py": _XLOCK_STORE + (
                    "    def drop(self):\n"
                    "        _clear(self)\n"
                    "def _clear(store):\n"
                    "    store.items = []\n"
                ),
            },
        ],
        "negative": [
            # external write WITH the owning object's lock held
            {
                "pkg/store.py": _XLOCK_STORE,
                "pkg/other.py": (
                    "from pkg.store import Store\n"
                    "class Holder:\n"
                    "    def __init__(self):\n"
                    "        self._store = Store()\n"
                    "    def reset_all(self):\n"
                    "        with self._store._lock:\n"
                    "            self._store.items = []\n"
                ),
            },
            # helper write, but every call site holds the lock (the
            # cross-module generalization of held-only helpers)
            {
                "pkg/store.py": _XLOCK_STORE + (
                    "    def drop(self):\n"
                    "        with self._lock:\n"
                    "            _clear(self)\n"
                    "def _clear(store):\n"
                    "    store.items = []\n"
                ),
            },
            # pre-publication: freshly constructed receiver is private
            {
                "pkg/store.py": _XLOCK_STORE,
                "pkg/build.py": (
                    "from pkg.store import Store\n"
                    "def make():\n"
                    "    s = Store()\n"
                    "    s.items = [1]\n"
                    "    return s\n"
                ),
            },
        ],
    },
    "jax-transitive": {
        "positive": [
            # host sync one call away from a jit context, cross-module
            {
                "pkg/helpers.py": (
                    "import numpy as np\n"
                    "def score(x):\n"
                    "    return np.asarray(x).sum()\n"
                ),
                "pkg/engine.py": (
                    "import jax\n"
                    "from pkg.helpers import score\n"
                    "@jax.jit\n"
                    "def step(x):\n"
                    "    return score(x)\n"
                ),
            },
            # compile-cache-key leak: a normalized-out key read in trace
            {
                "pkg/engine.py": (
                    "import dataclasses, jax\n"
                    "def _cached_scan_fn(cfg, k):\n"
                    "    @jax.jit\n"
                    "    def run(x):\n"
                    "        return x * cfg.pipeline_depth\n"
                    "    return run\n"
                    "def drive(cfg, k):\n"
                    "    fn = _cached_scan_fn(\n"
                    "        dataclasses.replace(cfg, pipeline_depth=0), k)\n"
                    "    return fn(1.0)\n"
                ),
            },
        ],
        "negative": [
            # same helper, only ever called from host code
            {
                "pkg/helpers.py": (
                    "import numpy as np\n"
                    "def score(x):\n"
                    "    return np.asarray(x).sum()\n"
                ),
                "pkg/engine.py": (
                    "import jax\n"
                    "from pkg.helpers import score\n"
                    "@jax.jit\n"
                    "def step(x):\n"
                    "    return x + 1\n"
                    "def host_drive(x):\n"
                    "    return score(step(x))\n"
                ),
            },
            # normalized key read on the HOST side stays legal
            {
                "pkg/engine.py": (
                    "import dataclasses, jax\n"
                    "def _cached_scan_fn(cfg, k):\n"
                    "    @jax.jit\n"
                    "    def run(x):\n"
                    "        return x * 2\n"
                    "    return run\n"
                    "def drive(cfg, k):\n"
                    "    fn = _cached_scan_fn(\n"
                    "        dataclasses.replace(cfg, pipeline_depth=0), k)\n"
                    "    for _ in range(cfg.pipeline_depth):\n"
                    "        fn(1.0)\n"
                ),
            },
        ],
    },
    "deadline-propagation": {
        "positive": [
            # Event.wait() with no timeout, two modules below do_GET
            {
                "pkg/worker.py": _DEADLINE_WORKER.format(wait_args=""),
                "pkg/server.py": _DEADLINE_SERVER,
            },
            # queue.get() with no timeout on the handler path
            {
                "pkg/worker.py": (
                    "import queue\n"
                    "class Worker:\n"
                    "    def __init__(self):\n"
                    "        self.job_queue = queue.Queue(8)\n"
                    "    def finish(self):\n"
                    "        return self.job_queue.get()\n"
                ),
                "pkg/server.py": _DEADLINE_SERVER,
            },
        ],
        "negative": [
            # the same wait, bounded by a timeout argument
            {
                "pkg/worker.py": _DEADLINE_WORKER.format(wait_args="2.0"),
                "pkg/server.py": _DEADLINE_SERVER,
            },
            # unbounded wait NOT reachable from any handler
            {
                "pkg/worker.py": _DEADLINE_WORKER.format(wait_args=""),
                "pkg/daemon.py": (
                    "from pkg.worker import Worker\n"
                    "def daemon_loop(w: Worker):\n"
                    "    w.finish()\n"
                ),
            },
        ],
    },
    "wall-clock-discipline": {
        "positive": [
            # sim/ modules run on the scenario clock — a bare wall read
            # anywhere in them is drift
            {
                "pkg/sim/__init__.py": "",
                "pkg/sim/driver.py": (
                    "import time\n"
                    "def tick(backend):\n"
                    "    return time.time()\n"
                ),
            },
            # clock-param scope, outside sim/: the injected now exists,
            # reading the host clock next to it is the bug
            {
                "pkg/evalmod.py": (
                    "import time\n"
                    "def evaluate(journal, now_ms):\n"
                    "    return now_ms - time.monotonic()\n"
                ),
            },
            # argless datetime.now() counts too
            {
                "pkg/sim/__init__.py": "",
                "pkg/sim/clockmod.py": (
                    "import datetime\n"
                    "def stamp(rec):\n"
                    "    rec['at'] = datetime.datetime.now()\n"
                    "    return rec\n"
                ),
            },
        ],
        "negative": [
            # the same read in a plain module without a clock parameter
            # is out of scope (production wall-clock code is everywhere)
            {
                "pkg/plain.py": (
                    "import time\n"
                    "def uptime(start):\n"
                    "    return time.time() - start\n"
                ),
            },
            # the documented fallback idiom: wall time only when no
            # clock was injected
            {
                "pkg/evalmod.py": (
                    "import time\n"
                    "def evaluate(journal, now=None):\n"
                    "    now = time.time() if now is None else now\n"
                    "    return now\n"
                ),
            },
            # simulator.py's real-server hold loops are allowlisted
            {
                "pkg/sim/__init__.py": "",
                "pkg/sim/simulator.py": (
                    "import time\n"
                    "def _slow_client_probe(hold_s):\n"
                    "    t0 = time.monotonic()\n"
                    "    return time.monotonic() - t0 < hold_s\n"
                ),
            },
            # references (injectable defaults) never call — out of scope
            {
                "pkg/sim/__init__.py": "",
                "pkg/sim/engine.py": (
                    "import time\n"
                    "def make(clock=None):\n"
                    "    return clock or time.time\n"
                ),
            },
        ],
    },
    "profiler-discipline": {
        "positive": [
            # the raw dotted call anywhere outside the observatory
            {
                "pkg/engine.py": (
                    "import jax\n"
                    "def search(trace_dir):\n"
                    "    with jax.profiler.trace(trace_dir):\n"
                    "        return 1\n"
                ),
            },
            # module alias + session start/stop
            {
                "pkg/bench.py": (
                    "import jax.profiler as prof\n"
                    "def run(d):\n"
                    "    prof.start_trace(d)\n"
                    "    prof.stop_trace()\n"
                ),
            },
            # direct-name import of the session API
            {
                "pkg/probe.py": (
                    "from jax.profiler import start_trace\n"
                    "def go(d):\n"
                    "    start_trace(d)\n"
                ),
            },
        ],
        "negative": [
            # the single entry point itself is exempt by path
            {
                "pkg/telemetry/__init__.py": "",
                "pkg/telemetry/kernel_budget.py": (
                    "import jax\n"
                    "def profiler_session(trace_dir):\n"
                    "    return jax.profiler.trace(trace_dir)\n"
                ),
            },
            # non-session profiler helpers are out of scope
            {
                "pkg/spans.py": (
                    "import jax\n"
                    "def note(name):\n"
                    "    jax.profiler.annotate_trace_event(name)\n"
                ),
            },
            # routing through the observatory is the prescribed shape
            {
                "pkg/driver.py": (
                    "from pkg.telemetry import kernel_budget\n"
                    "def capture(n):\n"
                    "    return kernel_budget.arm(scans=n)\n"
                ),
                "pkg/telemetry/__init__.py": "",
                "pkg/telemetry/kernel_budget.py": (
                    "def arm(scans):\n"
                    "    return {'scans': scans}\n"
                ),
            },
        ],
    },
    "journal-schema": {
        "positive": [
            # unregistered kind + undeclared field + bad severity
            {
                "tests/schemas/artifacts.schema.json": _SCHEMA_REGISTRY,
                "pkg/events.py": _SCHEMA_EVENTS_STUB,
                "pkg/prod.py": (
                    "from pkg import events\n"
                    "def go():\n"
                    "    events.emit('optimize.start', engine='g', extra=1)\n"
                    "    events.emit('unknown.kind')\n"
                    "    events.emit('optimize.end', severity='FATAL',\n"
                    "                durationS=1.0)\n"
                ),
            },
            # reverse direction: a registered kind nobody emits
            {
                "tests/schemas/artifacts.schema.json": _SCHEMA_REGISTRY,
                "pkg/events.py": _SCHEMA_EVENTS_STUB,
                "pkg/prod.py": (
                    "from pkg import events\n"
                    "def go():\n"
                    "    events.emit('optimize.start', engine='g')\n"
                ),
            },
        ],
        "negative": [
            # both directions closed: kinds registered, fields declared
            {
                "tests/schemas/artifacts.schema.json": _SCHEMA_REGISTRY,
                "pkg/events.py": _SCHEMA_EVENTS_STUB,
                "pkg/prod.py": (
                    "from pkg import events\n"
                    "def go():\n"
                    "    events.emit('optimize.start', engine='g')\n"
                    "    events.emit('optimize.end', severity='WARNING',\n"
                    "                durationS=1.0)\n"
                ),
            },
            # no registry next to the package → the rule stays silent
            {
                "pkg/events.py": _SCHEMA_EVENTS_STUB,
                "pkg/prod.py": (
                    "from pkg import events\n"
                    "def go():\n"
                    "    events.emit('anything.goes', field=1)\n"
                ),
            },
        ],
    },
    "fenced-backend-discipline": {
        "positive": [
            # a raw backend reference mutating outside the implementations
            {
                "pkg/healer.py": (
                    "def heal(backend, plan):\n"
                    "    backend.alter_partition_reassignments(plan)\n"
                ),
            },
            # aliasing past the fence: the wrapper's inner leaks out
            {
                "pkg/driveloop.py": (
                    "class Driver:\n"
                    "    def drive(self, reassignments):\n"
                    "        raw = self.backend.inner\n"
                    "        raw.cancel_reassignments(list(reassignments))\n"
                    "        self.backend.inner.alter_partition_"
                    "reassignments(reassignments)\n"
                ),
            },
            # direct-name import of a backend class, unbound-method call
            {
                "pkg/tools.py": (
                    "from pkg.executor.backend import "
                    "SimulatedClusterBackend\n"
                    "def throttle_off(b):\n"
                    "    SimulatedClusterBackend.clear_throttles(b)\n"
                ),
                "pkg/executor/__init__.py": "",
                "pkg/executor/backend.py": (
                    "class SimulatedClusterBackend:\n"
                    "    def clear_throttles(self):\n"
                    "        pass\n"
                ),
            },
        ],
        "negative": [
            # the executor shape: self.backend IS the fenced wrapper
            {
                "pkg/executor/__init__.py": "",
                "pkg/executor/executor.py": (
                    "class Executor:\n"
                    "    def drive(self, reassignments, elections):\n"
                    "        self.backend.alter_partition_reassignments("
                    "reassignments)\n"
                    "        self.backend.elect_leaders(elections)\n"
                    "        self.throttle_helper.clear_throttles()\n"
                ),
            },
            # the implementations themselves are exempt by path
            {
                "pkg/executor/__init__.py": "",
                "pkg/executor/backend.py": (
                    "class FencedClusterBackend:\n"
                    "    def elect_leaders(self, partitions):\n"
                    "        self.inner.elect_leaders(partitions)\n"
                ),
                "pkg/kafka/__init__.py": "",
                "pkg/kafka/backend.py": (
                    "class KafkaClusterBackend:\n"
                    "    def elect_leaders(self, partitions):\n"
                    "        self.wire.elect_leaders(partitions)\n"
                ),
                "pkg/sim/__init__.py": "",
                "pkg/sim/backend.py": (
                    "class ScriptedClusterBackend:\n"
                    "    def foreign_reassign(self, p, target):\n"
                    "        self.alter_partition_reassignments("
                    "{p: target})\n"
                ),
            },
            # non-mutating reads on a raw reference stay out of scope
            {
                "pkg/detector.py": (
                    "def watch(backend):\n"
                    "    return backend.ongoing_reassignments()\n"
                ),
            },
        ],
    },
    "transfer-discipline": {
        "positive": [
            # a raw jax.device_put outside the sanctioned modules
            {
                "pkg/drive.py": (
                    "import jax\n"
                    "def upload(x):\n"
                    "    return jax.device_put(x)\n"
                ),
            },
            # direct-name import dodging the dotted form
            {
                "pkg/loader.py": (
                    "from jax import device_put\n"
                    "def up(arrs):\n"
                    "    return device_put(arrs)\n"
                ),
            },
            # implicit D2H: np.asarray on a provable device array
            {
                "pkg/fetcher.py": (
                    "import jax\n"
                    "import numpy as np\n"
                    "def pull(packed: jax.Array):\n"
                    "    return np.asarray(packed)\n"
                ),
            },
        ],
        "negative": [
            # the sanctioned modules move bytes raw by design
            {
                "pkg/telemetry/__init__.py": "",
                "pkg/telemetry/mesh_budget.py": (
                    "import jax\n"
                    "import numpy as np\n"
                    "def device_put(x, fn='unlabeled'):\n"
                    "    return jax.device_put(x)\n"
                    "def fetch(x: jax.Array, fn='unlabeled'):\n"
                    "    return np.asarray(x)\n"
                ),
                "pkg/ops/__init__.py": "",
                "pkg/ops/grid.py": (
                    "import jax\n"
                    "import numpy as np\n"
                    "def gather(idx: jax.Array):\n"
                    "    return np.asarray(idx)\n"
                ),
                "pkg/models/__init__.py": "",
                "pkg/models/builder.py": (
                    "import jax\n"
                    "def build(arrays):\n"
                    "    return jax.device_put(arrays)\n"
                ),
            },
            # the ledger route IS the fix — stays silent
            {
                "pkg/telemetry/__init__.py": "",
                "pkg/telemetry/mesh_budget.py": (
                    "def device_put(x, fn='unlabeled'):\n"
                    "    return x\n"
                ),
                "pkg/drive.py": (
                    "from pkg.telemetry import mesh_budget\n"
                    "def upload(x):\n"
                    "    return mesh_budget.device_put(x, fn='upload')\n"
                ),
            },
            # host-side numpy stays out of scope: np.ndarray params and
            # unannotated locals prove nothing about device residency
            {
                "pkg/stats.py": (
                    "import numpy as np\n"
                    "def norm(v: np.ndarray, w):\n"
                    "    return np.asarray(v) + np.asarray(w)\n"
                ),
            },
        ],
    },
    "sharding-discipline": {
        "positive": [
            # an unplaced upload in a mesh-enabled module (ops/)
            {
                "pkg/ops/__init__.py": "",
                "pkg/ops/pools.py": (
                    "import jax\n"
                    "def upload_tables(size):\n"
                    "    return jax.device_put(size)\n"
                ),
            },
            # the ledger route is still an upload: placement required
            # in mesh scope even through mesh_budget.device_put
            {
                "pkg/models/__init__.py": "",
                "pkg/models/builder.py": (
                    "from pkg.telemetry import mesh_budget\n"
                    "def build(arrays):\n"
                    "    return mesh_budget.device_put(arrays, "
                    "fn='models.upload')\n"
                ),
                "pkg/telemetry/__init__.py": "",
                "pkg/telemetry/mesh_budget.py": (
                    "def device_put(x, device=None, fn='unlabeled'):\n"
                    "    return x\n"
                ),
            },
            # a literal device=None states nothing — still unplaced
            {
                "pkg/analyzer/__init__.py": "",
                "pkg/analyzer/tpu_optimizer.py": (
                    "from jax import device_put\n"
                    "def upload(m):\n"
                    "    return device_put(m, device=None)\n"
                ),
            },
        ],
        "negative": [
            # explicit NamedSharding placement (kwarg or positional)
            {
                "pkg/ops/__init__.py": "",
                "pkg/ops/pools.py": (
                    "import jax\n"
                    "from jax.sharding import NamedSharding, "
                    "PartitionSpec\n"
                    "def upload_tables(size, mesh, axis):\n"
                    "    tsh = NamedSharding(mesh, PartitionSpec(axis))\n"
                    "    a = jax.device_put(size, tsh)\n"
                    "    return jax.device_put(size, device=tsh)\n"
                ),
            },
            # outside the mesh-enabled modules the rule stays silent
            # (transfer-discipline owns raw-copy hygiene there)
            {
                "pkg/server/__init__.py": "",
                "pkg/server/handler.py": (
                    "import jax\n"
                    "def upload(x):\n"
                    "    return jax.device_put(x)\n"
                ),
            },
            # reviewed suppression: deliberate single-device placement
            {
                "pkg/ops/__init__.py": "",
                "pkg/ops/grid.py": (
                    "import jax\n"
                    "def upload(x):\n"
                    "    return jax.device_put(x)"
                    "  # cclint: disable=sharding-discipline -- "
                    "single-device micro-bench\n"
                ),
            },
        ],
    },
    "lock-instrumentation-discipline": {
        "positive": [
            # raw Lock on a serving-path coordination point (hot dir)
            {
                "pkg/server/__init__.py": "",
                "pkg/server/handler.py": (
                    "import threading\n"
                    "class Queue:\n"
                    "    def __init__(self):\n"
                    "        self._lock = threading.Lock()\n"
                ),
            },
            # from-import direct name, in the hot facade module
            {
                "pkg/facade.py": (
                    "from threading import RLock\n"
                    "class Facade:\n"
                    "    def __init__(self):\n"
                    "        self._cache_lock = RLock()\n"
                ),
            },
            # module-aliased import still resolves
            {
                "pkg/analyzer/__init__.py": "",
                "pkg/analyzer/degradation.py": (
                    "import threading as th\n"
                    "class Window:\n"
                    "    def make(self):\n"
                    "        return th.Lock()\n"
                ),
            },
        ],
        "negative": [
            # the blessed idiom: Condition wrapping an injected
            # (instrumented) lock — Condition itself is exempt
            {
                "pkg/server/__init__.py": "",
                "pkg/server/handler.py": (
                    "import threading\n"
                    "class Queue:\n"
                    "    def __init__(self, lk):\n"
                    "        self._cond = threading.Condition(lk)\n"
                ),
            },
            # cold modules keep stdlib freedom (per-metric nanosecond
            # holds would drown in wrapper overhead)
            {
                "pkg/telemetry/__init__.py": "",
                "pkg/telemetry/agg.py": (
                    "import threading\n"
                    "class Agg:\n"
                    "    def __init__(self):\n"
                    "        self._lock = threading.Lock()\n"
                ),
            },
            # non-lock threading ctors in hot modules stay silent
            {
                "pkg/executor/__init__.py": "",
                "pkg/executor/drive.py": (
                    "import threading\n"
                    "class Drive:\n"
                    "    def __init__(self):\n"
                    "        self._stop = threading.Event()\n"
                ),
            },
        ],
    },
    "lock-order": {
        "positive": [
            # direct inversion: two named locks nested in both orders
            {
                "pkg/locks.py": _ILOCK_STUB,
                "pkg/ab.py": (
                    "from pkg.locks import InstrumentedLock\n"
                    "class Pair:\n"
                    "    def __init__(self):\n"
                    "        self._a = InstrumentedLock('order.a')\n"
                    "        self._b = InstrumentedLock('order.b')\n"
                    "    def forward(self):\n"
                    "        with self._a:\n"
                    "            with self._b:\n"
                    "                pass\n"
                    "    def backward(self):\n"
                    "        with self._b:\n"
                    "            with self._a:\n"
                    "                pass\n"
                ),
            },
            # projected inversion: each leg acquires its second lock one
            # CALL away — only the callgraph fixpoint sees the cycle
            {
                "pkg/locks.py": _ILOCK_STUB,
                "pkg/m.py": (
                    "from pkg.locks import InstrumentedLock\n"
                    "class M:\n"
                    "    def __init__(self):\n"
                    "        self._a = InstrumentedLock('order.a')\n"
                    "        self._b = InstrumentedLock('order.b')\n"
                    "    def _grab_a(self):\n"
                    "        with self._a:\n"
                    "            pass\n"
                    "    def _grab_b(self):\n"
                    "        with self._b:\n"
                    "            pass\n"
                    "    def forward(self):\n"
                    "        with self._a:\n"
                    "            self._grab_b()\n"
                    "    def backward(self):\n"
                    "        with self._b:\n"
                    "            self._grab_a()\n"
                ),
            },
        ],
        "negative": [
            # globally consistent order (one leg projected) — acyclic
            {
                "pkg/locks.py": _ILOCK_STUB,
                "pkg/ab.py": (
                    "from pkg.locks import InstrumentedLock\n"
                    "class Pair:\n"
                    "    def __init__(self):\n"
                    "        self._a = InstrumentedLock('order.a')\n"
                    "        self._b = InstrumentedLock('order.b')\n"
                    "    def _grab_b(self):\n"
                    "        with self._b:\n"
                    "            pass\n"
                    "    def one(self):\n"
                    "        with self._a:\n"
                    "            with self._b:\n"
                    "                pass\n"
                    "    def two(self):\n"
                    "        with self._a:\n"
                    "            self._grab_b()\n"
                ),
            },
            # inversion against an UNNAMED lock: invisible to the
            # ordering vocabulary (documented blind spot, not a cycle)
            {
                "pkg/locks.py": _ILOCK_STUB,
                "pkg/ab.py": (
                    "import threading\n"
                    "from pkg.locks import InstrumentedLock\n"
                    "class Pair:\n"
                    "    def __init__(self):\n"
                    "        self._a = InstrumentedLock('order.a')\n"
                    "        self._raw = threading.Lock()\n"
                    "    def forward(self):\n"
                    "        with self._a:\n"
                    "            with self._raw:\n"
                    "                pass\n"
                    "    def backward(self):\n"
                    "        with self._raw:\n"
                    "            with self._a:\n"
                    "                pass\n"
                ),
            },
        ],
    },
    "blocking-under-lock": {
        "positive": [
            # intra: a sleep on the line where the named lock is held
            {
                "pkg/locks.py": _ILOCK_STUB,
                "pkg/svc.py": (
                    "import time\n"
                    "from pkg.locks import InstrumentedLock\n"
                    "class Svc:\n"
                    "    def __init__(self):\n"
                    "        self._lock = InstrumentedLock('svc.state')\n"
                    "    def tick(self):\n"
                    "        with self._lock:\n"
                    "            time.sleep(0.5)\n"
                ),
            },
            # projected: the call under the lock reaches a flush() one
            # module away (witness chain in the finding)
            {
                "pkg/locks.py": _ILOCK_STUB,
                "pkg/sink.py": (
                    "class Sink:\n"
                    "    def __init__(self, fh):\n"
                    "        self._fh = fh\n"
                    "    def push(self, rows):\n"
                    "        self._fh.flush()\n"
                ),
                "pkg/svc.py": (
                    "from pkg.locks import InstrumentedLock\n"
                    "from pkg.sink import Sink\n"
                    "class Svc:\n"
                    "    def __init__(self, fh):\n"
                    "        self._lock = InstrumentedLock('svc.state')\n"
                    "        self._sink = Sink(fh)\n"
                    "        self._rows = []\n"
                    "    def tick(self):\n"
                    "        with self._lock:\n"
                    "            self._sink.push(self._rows)\n"
                ),
            },
        ],
        "negative": [
            # the PR-18 /metrics shape: snapshot under the lock, render
            # and write OFF it — the canonical fix this rule enforces
            {
                "pkg/locks.py": _ILOCK_STUB,
                "pkg/svc.py": (
                    "from pkg.locks import InstrumentedLock\n"
                    "class Svc:\n"
                    "    def __init__(self, fh):\n"
                    "        self._lock = InstrumentedLock('svc.state')\n"
                    "        self._rows = []\n"
                    "        self._fh = fh\n"
                    "    def render(self):\n"
                    "        with self._lock:\n"
                    "            rows = list(self._rows)\n"
                    "        self._fh.write(str(rows))\n"
                    "        self._fh.flush()\n"
                ),
            },
            # Condition.wait on the HELD lock itself: wait releases it
            # while sleeping, so it is not blocking-under-that-lock
            {
                "pkg/locks.py": _ILOCK_STUB,
                "pkg/q.py": (
                    "import threading\n"
                    "from pkg.locks import InstrumentedLock\n"
                    "class Q:\n"
                    "    def __init__(self):\n"
                    "        self._cond = threading.Condition(\n"
                    "            InstrumentedLock('q.state'))\n"
                    "    def take(self):\n"
                    "        with self._cond:\n"
                    "            self._cond.wait()\n"
                ),
            },
        ],
    },
    "lock-release-safety": {
        "positive": [
            # bare acquire; the call between it and release() can
            # raise, exiting with the lock held
            {
                "pkg/r.py": (
                    "import threading\n"
                    "class R:\n"
                    "    def __init__(self):\n"
                    "        self._lock = threading.Lock()\n"
                    "        self.n = 0\n"
                    "    def poke(self):\n"
                    "        self._lock.acquire()\n"
                    "        self.refresh()\n"
                    "        self._lock.release()\n"
                    "    def refresh(self):\n"
                    "        self.n += 1\n"
                ),
            },
            # early return path that skips the release entirely
            {
                "pkg/r.py": (
                    "import threading\n"
                    "class R:\n"
                    "    def __init__(self):\n"
                    "        self._lock = threading.Lock()\n"
                    "        self.n = 0\n"
                    "    def poke(self, flag):\n"
                    "        self._lock.acquire()\n"
                    "        if flag:\n"
                    "            return None\n"
                    "        self._lock.release()\n"
                    "        return self.n\n"
                ),
            },
        ],
        "negative": [
            # try/finally: the release is on every path by construction
            {
                "pkg/r.py": (
                    "import threading\n"
                    "class R:\n"
                    "    def __init__(self):\n"
                    "        self._lock = threading.Lock()\n"
                    "        self.n = 0\n"
                    "    def poke(self):\n"
                    "        self._lock.acquire()\n"
                    "        try:\n"
                    "            self.n += 1\n"
                    "        finally:\n"
                    "            self._lock.release()\n"
                ),
            },
            # with statement: exempt by construction
            {
                "pkg/r.py": (
                    "import threading\n"
                    "class R:\n"
                    "    def __init__(self):\n"
                    "        self._lock = threading.Lock()\n"
                    "        self.n = 0\n"
                    "    def poke(self):\n"
                    "        with self._lock:\n"
                    "            self.n += 1\n"
                ),
            },
            # assigned timeout acquire with conditional release (the
            # facade single-flight shape): exempt — ownership flows
            # through the boolean (documented blind spot)
            {
                "pkg/r.py": (
                    "import threading\n"
                    "class R:\n"
                    "    def __init__(self):\n"
                    "        self._lock = threading.Lock()\n"
                    "        self.n = 0\n"
                    "    def try_poke(self):\n"
                    "        ok = self._lock.acquire(timeout=1.0)\n"
                    "        if not ok:\n"
                    "            return False\n"
                    "        try:\n"
                    "            self.n += 1\n"
                    "        finally:\n"
                    "            self._lock.release()\n"
                    "        return True\n"
                ),
            },
        ],
    },
}


def materialize_package(root: pathlib.Path, files: dict) -> pathlib.Path:
    """Write a fixture package under ``root``; returns the lint target
    (the ``pkg/`` dir).  Every ``pkg/`` file gets an __init__.py-backed
    package so import resolution works exactly as in the real tree."""
    for rel, code in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(code)
    pkg = root / "pkg"
    init = pkg / "__init__.py"
    if not init.exists():
        init.write_text("")
    return pkg


@pytest.mark.parametrize("rule_id", sorted(PACKAGE_FIXTURES))
def test_package_fixture_rules(rule_id, tmp_path):
    """Interprocedural rules: every positive fixture package flags (with
    only this rule's id), every negative stays silent."""
    for i, files in enumerate(PACKAGE_FIXTURES[rule_id]["positive"]):
        target = materialize_package(tmp_path / f"pos{i}", files)
        result = run_lint(paths=[str(target)], rules=[rule_id])
        assert result.findings, (
            f"{rule_id} missed positive package fixture #{i}: {files}"
        )
        assert all(f.rule == rule_id for f in result.findings)
        assert all(f.line >= 1 for f in result.findings)
    for i, files in enumerate(PACKAGE_FIXTURES[rule_id]["negative"]):
        target = materialize_package(tmp_path / f"neg{i}", files)
        result = run_lint(paths=[str(target)], rules=[rule_id])
        assert not result.findings, (
            f"{rule_id} false positive on negative package fixture #{i}:\n"
            + "\n".join(f.render() for f in result.findings)
        )


def test_every_registered_rule_has_fixtures():
    """Registry ↔ fixture-table closure: a rule without a positive
    fixture is an untested rule.  Per-file rules live in RULE_FIXTURES
    (code snippets); interprocedural rules live in PACKAGE_FIXTURES
    (multi-file fixture packages).  Together they cover the registry
    exactly, with no rule in both tables."""
    assert set(RULE_FIXTURES) | set(PACKAGE_FIXTURES) == set(RULES)
    assert not set(RULE_FIXTURES) & set(PACKAGE_FIXTURES)
    assert set(PACKAGE_FIXTURES) <= PROJECT_RULES
    for table in (RULE_FIXTURES, PACKAGE_FIXTURES):
        for rule_id, cases in table.items():
            assert cases["positive"], f"{rule_id}: no positive fixture"
            assert cases["negative"], f"{rule_id}: no negative fixture"


@pytest.mark.parametrize(
    "rule_id", sorted(set(RULES) - PROJECT_RULES - {"config-key-drift"}))
def test_rule_fixtures(rule_id):
    for code in RULE_FIXTURES[rule_id]["positive"]:
        found = findings_for(rule_id, code)
        assert found, f"{rule_id} missed a positive fixture:\n{code}"
        assert all(f.rule == rule_id for f in found)
        assert all(f.line >= 1 for f in found)
    for code in RULE_FIXTURES[rule_id]["negative"]:
        found = findings_for(rule_id, code)
        assert not found, (
            f"{rule_id} false positive:\n{code}\n"
            + "\n".join(f.render() for f in found)
        )


# ---- config-key-drift (project rule) --------------------------------------------
def test_config_rule_flags_undefined_used_key(tmp_path):
    bad = tmp_path / "uses_bad_key.py"
    bad.write_text(RULE_FIXTURES["config-key-drift"]["positive"][0])
    result = run_lint(paths=[str(bad)], rules=["config-key-drift"])
    assert any(
        f.rule == "config-key-drift" and "no.such.key" in f.message
        for f in result.findings
    )
    good = tmp_path / "uses_good_key.py"
    good.write_text(RULE_FIXTURES["config-key-drift"]["negative"][0])
    result = run_lint(paths=[str(good)], rules=["config-key-drift"])
    assert not [f for f in result.findings if "key" in f.message]


def test_config_used_key_extraction():
    import ast

    tree = ast.parse(
        "x = cfg.get('webserver.http.port')\n"          # config receiver
        "y = config.get_int('simulation.seed')\n"       # typed getter
        "z = some_dict.get('not.config')\n"             # plain dict .get
        "w = cfg.get(key_var)\n"                        # non-literal
    )
    keys = {k for k, _ in used_keys(tree)}
    assert keys == {"webserver.http.port", "simulation.seed"}


def test_config_doc_table_parsing_and_drift_detection():
    doc = (
        "# Configuration keys\n"
        "| key | type |\n"
        "|---|---|\n"
        "| `alpha.beta` | INT |\n"
        "| `gamma.delta` | STRING |\n"
    )
    table = doc_keys(doc)
    assert set(table) == {"alpha.beta", "gamma.delta"}
    assert table["alpha.beta"] == 4  # line anchor for the finding
    # both drift directions are set differences over these views — prove
    # the live pass sees the real registry and doc agreeing
    result = run_lint(paths=[str(PKG / "config")],
                      rules=["config-key-drift"])
    assert not result.findings, "\n".join(
        f.render() for f in result.findings)


# ---- suppressions ---------------------------------------------------------------
SWALLOW = (
    "def loop(work):\n"
    "    while True:\n"
    "        try:\n"
    "            work()\n"
    "        except Exception:{comment}\n"
    "            pass\n"
)


def _lint_file(tmp_path, code, name="mod.py", rules=None):
    path = tmp_path / name
    path.write_text(code)
    return run_lint(paths=[str(path)], rules=rules)


def test_suppression_with_reason_is_honored(tmp_path):
    result = _lint_file(
        tmp_path,
        SWALLOW.format(
            comment="  # cclint: disable=swallowed-exception -- fixture: "
                    "deliberately silent"),
    )
    assert not result.findings
    assert result.suppressions_used == 1


def test_suppression_without_reason_fails(tmp_path):
    result = _lint_file(
        tmp_path, SWALLOW.format(
            comment="  # cclint: disable=swallowed-exception"),
    )
    rules = {f.rule for f in result.findings}
    # the original finding survives AND the reasonless suppression is
    # itself flagged
    assert rules == {"swallowed-exception", BAD_SUPPRESSION}


def test_suppression_with_unknown_rule_fails(tmp_path):
    result = _lint_file(
        tmp_path, SWALLOW.format(
            comment="  # cclint: disable=swalowed-exception -- typo"),
    )
    assert {f.rule for f in result.findings} == {
        "swallowed-exception", BAD_SUPPRESSION}


def test_bad_suppression_cannot_be_suppressed(tmp_path):
    code = ("x = 1  # cclint: disable=bad-suppression,"
            "swallowed-exception\n")
    result = _lint_file(tmp_path, code)
    assert [f.rule for f in result.findings] == [BAD_SUPPRESSION]


def test_suppression_in_string_literal_is_ignored():
    supp = parse_suppressions(
        "doc.py",
        'DOC = """example:\n'
        '    x()  # cclint: disable=swallowed-exception -- example\n'
        '"""\n',
        set(RULES),
    )
    assert not supp.by_line and not supp.malformed


def test_unused_suppression_is_reported_as_note(tmp_path):
    result = _lint_file(
        tmp_path,
        "x = 1  # cclint: disable=swallowed-exception -- nothing here\n",
    )
    assert not result.findings
    assert result.unused_suppressions
    assert "unused suppression" in result.render_text()


def test_checked_in_suppressions_are_load_bearing(tmp_path):
    """Stripping every suppression re-surfaces each finding at the same
    file:line (the acceptance criterion for zero-findings-by-suppression
    honesty).  The whole package is copied and linted as ONE program:
    interprocedural findings (a blocking-under-lock witness chain that
    crosses into executor/journal.py) cannot fire on a single file in
    isolation, so per-file stripping would call their suppressions
    stale."""
    marker = re.compile(r"\s*# cclint: disable=[^\n]*")
    # the copy keeps the real package name: absolute imports
    # (`from cruise_control_tpu.x import y`) must keep resolving inside
    # the copied tree or every cross-module witness chain goes dark
    target = (tmp_path / "cruise_control_tpu").resolve()
    expected = []  # (rel path, line, rule id)
    for path in sorted(PKG.rglob("*.py")):
        rel = path.relative_to(PKG)
        dst = target / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        text = path.read_text()
        supp = parse_suppressions(str(path), text, set(RULES))
        if supp.by_line:
            # strip only on the suppressing lines — a marker quoted in a
            # string literal (rule docs) must survive untouched
            lines = text.splitlines(keepends=True)
            for line_no, rule_ids in supp.by_line.items():
                lines[line_no - 1] = marker.sub("", lines[line_no - 1])
                for rule_id in rule_ids:
                    expected.append((str(rel), line_no, rule_id))
            text = "".join(lines)
        dst.write_text(text)
    assert len(expected) >= 4  # the suppressions this PR checked in
    result = run_lint(paths=[str(target)])
    surfaced = {
        (str(pathlib.Path(f.path).resolve().relative_to(target)),
         f.line, f.rule)
        for f in result.findings
        if pathlib.Path(f.path).is_absolute()
    }
    for rel, line, rule_id in expected:
        assert (rel, line, rule_id) in surfaced, (
            f"{rel}:{line} suppression for '{rule_id}' is stale — the "
            "finding no longer fires without it"
        )


# ---- output contracts -----------------------------------------------------------
LINT_SCHEMAS = json.loads(
    (pathlib.Path(__file__).parent / "schemas" / "lint.schema.json")
    .read_text()
)


def test_json_output_matches_checked_in_schema(tmp_path):
    result = _lint_file(tmp_path, SWALLOW.format(comment=""))
    assert result.findings  # a non-trivial payload
    payload = json.loads(render(result, "json"))
    validate(json.loads(json.dumps(payload)),
             LINT_SCHEMAS["cc-tpu-lint/1"])
    assert payload["counts"]["swallowed-exception"] == 1


def test_text_output_format(tmp_path):
    result = _lint_file(tmp_path, SWALLOW.format(comment=""))
    line = result.findings[0].render()
    # the clickable anchor contract: file:line · rule-id · message
    assert re.match(r"^.+\.py:\d+ · swallowed-exception · ", line)


def test_parse_error_is_a_finding(tmp_path):
    result = _lint_file(tmp_path, "def broken(:\n")
    assert [f.rule for f in result.findings] == ["parse-error"]


# ---- the CLI --------------------------------------------------------------------
def test_cli_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(SWALLOW.format(comment=""))
    assert cclint_main([str(bad)]) == 1
    assert cclint_main([str(bad), "--rule=lock-discipline"]) == 0
    assert cclint_main([str(bad), "--rule=not-a-rule"]) == 2
    assert cclint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in RULES:
        assert rule_id in out


def test_cli_json_format(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(SWALLOW.format(comment=""))
    assert cclint_main([str(bad), "--format=json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["schema"] == "cc-tpu-lint/1"
    validate(payload, LINT_SCHEMAS["cc-tpu-lint/1"])


# ---- the whole-program phase ----------------------------------------------------
SWALLOW_IN_B = (
    "from pkg.a import helper\n"
    "def loop(work):\n"
    "    while True:\n"
    "        try:\n"
    "            helper(work)\n"
    "        except Exception:\n"
    "            pass\n"
)


def test_changed_only_relints_reverse_dependents(tmp_path):
    """Editing a module re-lints every module that imports it (via the
    import graph), so a per-file finding in an untouched dependent
    cannot be dodged by a partial diff."""
    target = materialize_package(tmp_path, {
        "pkg/a.py": "def helper(work):\n    return work()\n",
        "pkg/b.py": SWALLOW_IN_B,
        "pkg/unrelated.py": (
            "def loop(work):\n"
            "    while True:\n"
            "        try:\n"
            "            work()\n"
            "        except Exception:\n"
            "            pass\n"
        ),
    })
    # only a.py "changed" — b.py imports it, unrelated.py does not
    result = run_lint(paths=[str(target)], changed_only=True,
                      changed_paths={(target / "a.py").resolve()})
    flagged = {pathlib.Path(f.path).name for f in result.findings
               if f.rule == "swallowed-exception"}
    assert "b.py" in flagged, (
        "reverse dependent b.py was not re-linted:\n"
        + "\n".join(f.render() for f in result.findings))
    assert "unrelated.py" not in flagged
    assert result.files_scanned == 2  # a.py + its dependent b.py
    # nothing changed → pre-commit no-op (no findings, nothing scanned)
    result = run_lint(paths=[str(target)], changed_only=True,
                      changed_paths=set())
    assert not result.findings
    assert result.files_scanned == 0


def test_changed_only_cannot_dodge_interprocedural_findings(tmp_path):
    """A cross-module-lock finding lands in the HELPER file even when
    only the caller changed: project rules run over the full graph."""
    files = PACKAGE_FIXTURES["cross-module-lock"]["positive"][0]
    target = materialize_package(tmp_path, files)
    result = run_lint(paths=[str(target)], changed_only=True,
                      changed_paths={(target / "store.py").resolve()})
    assert any(f.rule == "cross-module-lock"
               and pathlib.Path(f.path).name == "other.py"
               for f in result.findings), "\n".join(
        f.render() for f in result.findings)


SARIF_SCHEMAS = json.loads(
    (pathlib.Path(__file__).parent / "schemas" / "sarif.schema.json")
    .read_text()
)


def test_sarif_output_matches_checked_in_schema(tmp_path):
    result = _lint_file(tmp_path, SWALLOW.format(comment=""))
    assert result.findings
    payload = json.loads(render(result, "sarif"))
    validate(payload, SARIF_SCHEMAS["sarif-2.1.0-min"])
    run = payload["runs"][0]
    assert {r["id"] for r in run["tool"]["driver"]["rules"]} >= set(RULES)
    res = run["results"][0]
    assert res["ruleId"] == "swallowed-exception"
    assert res["locations"][0]["physicalLocation"]["region"][
        "startLine"] >= 1


def test_sarif_cli(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(SWALLOW.format(comment=""))
    assert cclint_main([str(bad), "--format=sarif"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["version"] == "2.1.0"
    validate(payload, SARIF_SCHEMAS["sarif-2.1.0-min"])


def test_incremental_cache_short_circuits_parses(tmp_path, monkeypatch):
    """Warm runs parse nothing, reuse summaries AND findings, and stay
    bit-identical to the cold run; editing one file re-parses exactly
    the files whose content changed."""
    monkeypatch.setenv("CCLINT_CACHE_DIR", str(tmp_path / "cache"))
    target = materialize_package(tmp_path, {
        "pkg/a.py": "def helper(work):\n    return work()\n",
        "pkg/b.py": SWALLOW_IN_B,
    })
    cold = run_lint(paths=[str(target)])
    assert cold.stats["filesParsed"] == 3  # a, b, __init__
    warm = run_lint(paths=[str(target)])
    assert warm.stats["filesParsed"] == 0
    assert warm.stats["cacheHits"] == 3
    assert [f.to_json() for f in warm.findings] == \
        [f.to_json() for f in cold.findings]
    # touch ONE file: exactly one re-parse
    (target / "a.py").write_text(
        "def helper(work):\n    return work() + 0\n")
    edited = run_lint(paths=[str(target)])
    assert edited.stats["filesParsed"] == 1
    assert edited.stats["cacheHits"] == 2


def test_cache_is_disposable(tmp_path, monkeypatch):
    """A deleted or corrupted store degrades to a cold run, never an
    error (the .cclint_cache/ 'safe to delete' contract)."""
    cache = tmp_path / "cache"
    monkeypatch.setenv("CCLINT_CACHE_DIR", str(cache))
    target = materialize_package(
        tmp_path, {"pkg/a.py": "x = 1\n"})
    run_lint(paths=[str(target)])
    store = cache / "store.pkl"
    assert store.exists()
    store.write_bytes(b"not a pickle")
    result = run_lint(paths=[str(target)])
    assert not result.findings
    assert result.stats["filesParsed"] == 2  # cold again, no crash


# ---- in-situ sensitivity: mutations of the REAL tree must be caught -------------
# Zero findings on a clean tree is only meaningful if the analysis still
# penetrates the tree's layers — a silent regression in receiver typing
# or import resolution would keep the package "clean" vacuously.  Each
# case plants one bug at a stable anchor in the live source (restored in
# a finally) and asserts its rule reports it at that exact site.
MUTATIONS = {
    "deadline-propagation": (
        "deadline-propagation",
        "cruise_control_tpu/server/admission.py",
        "self._cond.wait(left)",
        "self._cond.wait()",
    ),
    "cross-module-lock": (
        "cross-module-lock",
        "cruise_control_tpu/facade.py",
        '            self.replanner.record_mode("warm", "zero-delta")',
        '            self.replanner.record_mode("warm", "zero-delta")\n'
        "            self.replanner.snapshot = None",
    ),
    "jax-transitive": (
        "jax-transitive",
        "cruise_control_tpu/models/cluster_state.py",
        "    return _segment_sum_by_broker(rload, state.assignment, "
        "state.num_brokers)",
        "    np.asarray(rload)\n"
        "    return _segment_sum_by_broker(rload, state.assignment, "
        "state.num_brokers)",
    ),
    "journal-schema": (
        "journal-schema",
        "cruise_control_tpu/executor/executor.py",
        'events.emit("executor.dest_excluded", severity="WARNING",',
        'events.emit("executor.dest_banned", severity="WARNING",',
    ),
    # ISSUE 11 satellite: an SLO breach emitted under an unregistered
    # kind must be caught — proving the closed registry still reaches
    # the observatory layer of the live tree
    "journal-schema-slo-kind": (
        "journal-schema",
        "cruise_control_tpu/telemetry/slo.py",
        '"slo.breach", severity="WARNING", slo=row.name,',
        '"slo.breach_unregistered", severity="WARNING", slo=row.name,',
    ),
    # ISSUE 12 satellite: dropping the SLO evaluator's is-None fallback
    # guard (wall clock ALWAYS, injected now ignored) must be caught —
    # the exact window-eviction drift class the soak surfaced
    "wall-clock-slo-fallback": (
        "wall-clock-discipline",
        "cruise_control_tpu/telemetry/slo.py",
        "now = time.time() if now is None else now",
        "now = time.time()",
    ),
    # and a host-clock read planted in the scenario driver's tick loop
    # (the virtual clock's own assignment site) must be caught
    "wall-clock-sim-tick": (
        "wall-clock-discipline",
        "cruise_control_tpu/sim/simulator.py",
        "sim.now_ms = now  # injected clocks (the breaker) read this",
        "sim.now_ms = int(time.time() * 1000)",
    ),
    # ISSUE 15 satellite: the executor's batch dispatch rewritten to go
    # around the fenced wrapper (the exact zombie-write hole execution
    # fencing closed) must be caught at the real drive-loop site
    "fenced-backend-dispatch": (
        "fenced-backend-discipline",
        "cruise_control_tpu/executor/executor.py",
        "                    self.backend.alter_partition_reassignments("
        "reassignments)",
        "                    self.backend.inner.alter_partition_"
        "reassignments(reassignments)",
    ),
    # ISSUE 14 satellite: a raw profiler-session call planted back into
    # the optimizer's drive loop — the exact ad-hoc hole the kernel
    # observatory's single entry point closed — must be caught
    "profiler-discipline-optimizer": (
        "profiler-discipline",
        "cruise_control_tpu/analyzer/tpu_optimizer.py",
        "                if inflight:\n"
        "                    packed, m_new, tab_new = inflight.pop(0)",
        "                jax.profiler.start_trace(\"/tmp/cc-mutation\")\n"
        "                if inflight:\n"
        "                    packed, m_new, tab_new = inflight.pop(0)",
    ),
    # ISSUE 18 satellite: the admission queue's instrumented lock
    # reverted to a raw stdlib lock — the exact attribution hole the
    # lock observatory closed (waits nobody can name) — must be caught
    "lock-instrumentation-admission": (
        "lock-instrumentation-discipline",
        "cruise_control_tpu/server/admission.py",
        "self._cond = threading.Condition("
        'InstrumentedLock("admission.queue"))',
        "self._cond = threading.Condition(threading.Lock())",
    ),
    # ISSUE 17 satellite: the constraint upload rewritten as a stray
    # jax.device_put in the drive loop — the exact ledger-blind copy
    # the mesh observatory's transfer discipline closed — must be caught
    "transfer-discipline-optimizer": (
        "transfer-discipline",
        "cruise_control_tpu/analyzer/tpu_optimizer.py",
        "        ca = {k: jnp.asarray(v) for k, v in can.items()}",
        "        ca = {k: jax.device_put(v) for k, v in can.items()}",
    ),
    # ISSUE 20 satellite: the sharded pool-table carry's cold upload
    # rewritten as an unplaced device_put in the scan factory — the
    # exact silent-replication hole the round-20 sharding deleted
    # (every lane would hold the full [Pg, S] tables again) — must be
    # caught at the planted site
    "sharding-discipline-optimizer": (
        "sharding-discipline",
        "cruise_control_tpu/analyzer/tpu_optimizer.py",
        "        return (jnp.zeros((rows, S), jnp.float32, device=tsh),\n"
        "                jnp.zeros((rows, S), jnp.float32, device=tsh),\n"
        "                jnp.zeros(P, bool, device=rsh), np.False_)",
        "        return (jax.device_put("
        "jnp.zeros((rows, S), jnp.float32)),\n"
        "                jnp.zeros((rows, S), jnp.float32, device=tsh),\n"
        "                jnp.zeros(P, bool, device=rsh), np.False_)",
    ),
    # ISSUE 19 satellite: a real lock inversion planted in the facade —
    # cache-lock outside, single-flight inside, the exact opposite of
    # the committed proposal.single_flight → proposal.cache edge — must
    # close a cycle in the global order graph and be caught
    "lock-order-inversion": (
        "lock-order",
        "cruise_control_tpu/facade.py",
        "        with self._cache_lock:\n"
        "            self._cached_proposals = None",
        "        with self._cache_lock:\n"
        "            with self._compute_lock:\n"
        "                pass\n"
        "            self._cached_proposals = None",
    ),
    # a journal flush planted under the metric-registry lock — the
    # exact scrape-vs-serve convoy the PR-18 snapshot-then-render fix
    # removed — must be caught at the planted site
    "journal-flush-under-registry-lock": (
        "blocking-under-lock",
        "cruise_control_tpu/utils/metrics.py",
        "        with self._lock:\n"
        "            timers = dict(self._timers)",
        "        with self._lock:\n"
        "            journal.flush()\n"
        "            timers = dict(self._timers)",
    ),
    # a bare acquire() with no try/finally replacing the progress log's
    # `with` — any raise between acquire and release exits holding
    # operation.progress forever — must be caught
    "release-safety-no-finally": (
        "lock-release-safety",
        "cruise_control_tpu/server/progress.py",
        "        with self._lock:\n"
        "            # finish any still-open step: steps are sequential"
        " by contract\n"
        "            if self._steps and self._steps[-1].end_s is None:\n"
        "                self._steps[-1].end_s = step.start_s\n"
        "            self._steps.append(step)",
        "        self._lock.acquire()\n"
        "        if self._steps and self._steps[-1].end_s is None:\n"
        "            self._steps[-1].end_s = step.start_s\n"
        "        self._steps.append(step)\n"
        "        self._lock.release()",
    ),
}


@pytest.mark.parametrize("case", sorted(MUTATIONS))
def test_interprocedural_rules_catch_planted_bugs_in_situ(case):
    rule_id, rel, needle, replacement = MUTATIONS[case]
    path = PKG.parent / rel
    orig = path.read_text()
    assert needle in orig, (
        f"mutation anchor for {rule_id} vanished from {rel} — update "
        "MUTATIONS to a current equivalent site (this test is load-"
        "bearing: it proves the whole-program pass still reaches that "
        "layer of the real tree)"
    )
    try:
        path.write_text(orig.replace(needle, replacement, 1))
        result = run_lint(paths=[str(PKG)], rules=[rule_id])
        assert any(
            f.rule == rule_id and pathlib.Path(f.path).name == path.name
            for f in result.findings
        ), (
            f"{rule_id} missed a planted bug in {rel}:\n"
            + "\n".join(f.render() for f in result.findings)
        )
    finally:
        path.write_text(orig)


# ---- the tree is clean ----------------------------------------------------------
def test_sim_package_is_scanned_and_clean():
    """The fault-injection simulator (sim/) is part of the linted tree and
    carries zero findings of its own (ISSUE 6 satellite)."""
    result = run_lint(paths=[str(PKG / "sim")])
    assert result.files_scanned >= 7
    assert not result.findings, "\n".join(
        f.render() for f in result.findings
    )


def test_package_lints_clean_within_budget():
    """The tier-1 wrapper: the whole package, every rule (per-file AND
    whole-program), zero findings, single parse per file, < 5 s wall
    clock COLD — and the cache-warm rerun parses nothing, changes no
    finding, and stays inside the same budget."""
    cold = run_lint(paths=[str(PKG)])
    assert not cold.findings, (
        "cclint found new violations — fix them or add a reviewed "
        "suppression with a reason (docs/STATIC_ANALYSIS.md):\n"
        + "\n".join(f.render() for f in cold.findings)
    )
    assert cold.files_scanned > 50
    if cold.duration_s >= 5.0:
        # This guest has sustained multi-second interference windows that
        # can double a wall-clock draw (see bench.py's interleaved-gate
        # rationale).  One retry separates "the box was busy" from "the
        # single-parse budget regressed": a real regression fails both
        # draws, a noise window doesn't.  The structural single-parse
        # asserts below are unaffected.
        import shutil

        from cruise_control_tpu.devtools.lint.driver import cache_dir

        cd = cache_dir()
        if cd is not None and cd.exists():
            shutil.rmtree(cd)
        cold = run_lint(paths=[str(PKG)])
        assert not cold.findings
    assert cold.duration_s < 5.0, (
        f"cold lint pass took {cold.duration_s:.2f}s twice — the "
        "single-parse budget regressed"
    )
    # the whole-program phase really ran (the graph is not optional),
    # CFG dataflow included (lockflow is the ISSUE 19 engine)
    assert cold.stats["graphBuildMs"] > 0.0
    assert cold.stats["lockflowMs"] > 0.0
    warm = run_lint(paths=[str(PKG)])
    assert not warm.findings
    assert warm.stats["filesParsed"] == 0, (
        "warm run re-parsed files — the content-hash cache regressed"
    )
    assert warm.stats["cacheHits"] >= warm.files_scanned
    assert warm.duration_s < 5.0
    assert warm.duration_s <= cold.duration_s * 1.5  # warm must not cost more
