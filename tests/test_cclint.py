"""cclint framework tests (ISSUE 4).

Four contracts:

* **rules** — every registered rule catches its positive fixtures and
  stays quiet on its negatives; a meta-test proves the fixture table
  covers the whole registry, so adding a rule without fixtures fails CI;
* **suppressions** — ``# cclint: disable=rule -- reason`` is honored,
  a reasonless or unknown-rule suppression is itself a finding, and
  every suppression checked into the package is load-bearing (stripping
  any one of them re-surfaces its finding at the same file:line);
* **output** — the JSON format matches the checked-in
  ``tests/schemas/lint.schema.json`` contract (closed finding record);
* **the tree is clean** — the full pass over ``cruise_control_tpu/``
  yields zero findings in < 5 s (single parse per file).
"""

import json
import pathlib
import re

import pytest

from cruise_control_tpu.devtools.lint import (
    BAD_SUPPRESSION,
    FileContext,
    RULES,
    parse_suppressions,
    render,
    run_lint,
)
from cruise_control_tpu.devtools.lint.__main__ import main as cclint_main
from cruise_control_tpu.devtools.lint.rules_config import (
    doc_keys,
    used_keys,
)
from test_artifact_schemas import validate

PKG = pathlib.Path(__file__).resolve().parent.parent / "cruise_control_tpu"


def findings_for(rule_id: str, code: str):
    ctx = FileContext.parse("fixture.py", code)
    return RULES[rule_id].check_file(ctx)


# ---- per-rule fixtures ----------------------------------------------------------
# rule id -> (positive snippets that MUST flag, negative snippets that
# must NOT).  config-key-drift is a project rule; its fixtures run
# through its pure helpers below but are listed here so the meta-test
# sees full registry coverage.
RULE_FIXTURES = {
    "lock-discipline": {
        "positive": [
            # lockset inconsistency: guarded in one method, naked in another
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._items = []\n"
            "    def add(self, x):\n"
            "        with self._lock:\n"
            "            self._items.append(x)\n"
            "    def drop_all(self):\n"
            "        self._items.clear()\n",
            # cross-thread write: daemon loop writes, public method reads
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._last = None\n"
            "    def start(self):\n"
            "        def loop():\n"
            "            self._last = 1\n"
            "        threading.Thread(target=loop).start()\n"
            "    def summary(self):\n"
            "        return {'last': self._last}\n",
        ],
        "negative": [
            # everything under the lock (helper called only while held)
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._items = []\n"
            "    def add(self, x):\n"
            "        with self._lock:\n"
            "            self._record(x)\n"
            "    def _record(self, x):\n"
            "        self._items.append(x)\n"
            "    def snapshot(self):\n"
            "        with self._lock:\n"
            "            return list(self._items)\n",
            # thread-safe primitives are out of scope; __init__ is exempt
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._stop = threading.Event()\n"
            "        self._data = {}\n"
            "    def start(self):\n"
            "        self._stop.clear()\n"
            "    def stop(self):\n"
            "        self._stop.set()\n",
            # no lock attribute -> class out of scope entirely
            "class C:\n"
            "    def set(self, x):\n"
            "        self._x = x\n"
            "    def get(self):\n"
            "        return self._x\n",
        ],
    },
    "jax-hot-path": {
        "positive": [
            # host sync inside a decorated jit function
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return float(x.item())\n",
            # print inside a function passed to jax.jit by name
            "import jax\n"
            "def make():\n"
            "    def run(m):\n"
            "        print(m)\n"
            "        return m\n"
            "    return jax.jit(run)\n",
            # branching on a traced parameter
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    if x > 0:\n"
            "        return x\n"
            "    return -x\n",
            # np.asarray materializes on host
            "import jax, numpy as np\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return np.asarray(x)\n",
            # retrace risk: f-string argument to a jitted callable
            "import jax\n"
            "@jax.jit\n"
            "def f(x, tag):\n"
            "    return x\n"
            "def caller(x, name):\n"
            "    return f(x, f'tag-{name}')\n",
            # concretizing a traced parameter
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return [0.0] * int(x)\n",
        ],
        "negative": [
            # the structural-None default idiom is NOT data branching
            "import jax\n"
            "import jax.numpy as jnp\n"
            "@jax.jit\n"
            "def f(x, t_cap=None):\n"
            "    if t_cap is None:\n"
            "        t_cap = jnp.int32(8)\n"
            "    return x * t_cap\n",
            # static args may branch (resolved at trace time)
            "import functools, jax\n"
            "@functools.partial(jax.jit, static_argnums=(1,))\n"
            "def f(x, mode):\n"
            "    if mode:\n"
            "        return x + 1\n"
            "    return x\n",
            # host syncs OUTSIDE jit are fine
            "import numpy as np\n"
            "def fetch(x):\n"
            "    print(x)\n"
            "    return float(np.asarray(x).sum())\n",
        ],
    },
    "config-key-drift": {
        # project rule: exercised via used-key extraction against the
        # live registry and doc-table parsing (see tests below)
        "positive": ["cfg.get_int('no.such.key')\n"],
        "negative": ["cfg.get_int('tpu.search.max.rounds')\n"],
    },
    "obs-dynamic-name": {
        "positive": [
            # unguarded f-string span name
            "def f(m):\n"
            "    with tracing.span(f'http.{m}'):\n"
            "        pass\n",
            # dynamic event kind
            "def f(op):\n"
            "    events.emit(f'optimize.{op}')\n",
            # dynamic metric name (no enabled() escape)
            "def f(registry, name):\n"
            "    registry.counter(f'ops.{name}').inc()\n",
        ],
        "negative": [
            # guarded span, static metric, static kind
            "def f(registry, m, op):\n"
            "    if tracing.enabled():\n"
            "        s = tracing.span('http', sub=f'{m}')\n"
            "    registry.counter('ops').inc()\n"
            "    events.emit('optimize.start', operation=op)\n",
            # dict .get homonym is not a metric call
            "def f(d, k):\n"
            "    return d.counter(f'x.{k}') if hasattr(d, 'x') else None\n",
        ],
    },
    "retry-discipline": {
        "positive": [
            # constant backoff + unbounded: hammers the dependency forever
            "import time\n"
            "def fetch(conn):\n"
            "    while True:\n"
            "        try:\n"
            "            return conn.read()\n"
            "        except Exception:\n"
            "            time.sleep(5)\n",
            # bounded, but still a fixed cadence — no backoff, no jitter
            "import time\n"
            "def poll(conn):\n"
            "    for _ in range(3):\n"
            "        try:\n"
            "            return conn.read()\n"
            "        except OSError:\n"
            "            time.sleep(1.0)\n",
            # unbounded even with a computed delay: no exit on failure
            "import time\n"
            "def settle(conn, backoff):\n"
            "    while True:\n"
            "        try:\n"
            "            return conn.read()\n"
            "        except OSError:\n"
            "            time.sleep(backoff())\n",
        ],
        "negative": [
            # exponential backoff with a bounded attempt budget
            "import time\n"
            "def fetch(conn):\n"
            "    delay = 0.1\n"
            "    for attempt in range(5):\n"
            "        try:\n"
            "            return conn.read()\n"
            "        except OSError:\n"
            "            time.sleep(delay)\n"
            "            delay = min(delay * 2, 2.0)\n"
            "    raise TimeoutError('gave up')\n",
            # while True, but the failure path escalates (raise bound)
            "import time\n"
            "def fetch(conn, deadline, backoff):\n"
            "    while True:\n"
            "        try:\n"
            "            return conn.read()\n"
            "        except OSError:\n"
            "            if time.time() > deadline:\n"
            "                raise\n"
            "            time.sleep(backoff())\n",
            # daemon service loop without a sleep: swallowed-exception's
            # beat, not a retry loop
            "def loop(stop, work):\n"
            "    while not stop.is_set():\n"
            "        try:\n"
            "            work()\n"
            "        except Exception:\n"
            "            LOG.exception('tick failed')\n",
            # sleep in a loop without exception handling: a poll pace,
            # not a retry
            "import time\n"
            "def wait_for(cond):\n"
            "    while not cond():\n"
            "        time.sleep(0.5)\n",
        ],
    },
    "bounded-resource": {
        "positive": [
            # unbounded deque: overload becomes memory growth, not
            # backpressure
            "from collections import deque\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self.buffer = deque()\n",
            # Queue() with no maxsize (module-qualified)
            "import queue\n"
            "def make():\n"
            "    return queue.Queue()\n",
            # SimpleQueue has no bound at all
            "import queue\n"
            "def make():\n"
            "    return queue.SimpleQueue()\n",
            # pool with the implicit cpu-scaled default worker count
            "from concurrent.futures import ThreadPoolExecutor\n"
            "def pool():\n"
            "    return ThreadPoolExecutor()\n",
            # an explicit None bound is still unbounded
            "from collections import deque\n"
            "def ring():\n"
            "    return deque([], None)\n",
        ],
        "negative": [
            # bounds as keywords (values may be variables)
            "from collections import deque\n"
            "def ring(n):\n"
            "    return deque(maxlen=n)\n",
            "import queue\n"
            "def make(cap):\n"
            "    return queue.Queue(maxsize=cap)\n",
            "from concurrent.futures import ThreadPoolExecutor\n"
            "def pool(n):\n"
            "    return ThreadPoolExecutor(max_workers=n)\n",
            # positional bounds count too
            "import queue\n"
            "def make():\n"
            "    return queue.Queue(128)\n",
            "from concurrent.futures import ThreadPoolExecutor\n"
            "def pool():\n"
            "    return ThreadPoolExecutor(4)\n",
            # **kwargs may carry the bound — benefit of the doubt
            "from collections import deque\n"
            "def ring(**kw):\n"
            "    return deque(**kw)\n",
            # attribute chains that merely end in a matching name are
            # out of scope (factory.pools.Queue() is not queue.Queue)
            "def make(factory):\n"
            "    return factory.pools.Queue()\n",
        ],
    },
    "cache-key-discipline": {
        "positive": [
            # keyed cache, no generation term, no invalidate path: a
            # stale plan is served as fresh forever
            "class PlanCache:\n"
            "    def __init__(self):\n"
            "        self._plan_cache = {}\n"
            "    def put(self, topic, plan):\n"
            "        self._plan_cache[topic] = plan\n",
            # attribute cache with no freshness companion at all
            "class C:\n"
            "    def refresh(self, model):\n"
            "        self._cached_plan = self._compute(model)\n",
            # memo keyed on a raw tuple without a version component
            "class C:\n"
            "    def __init__(self):\n"
            "        self._memo = {}\n"
            "    def bounds(self, b, r):\n"
            "        self._memo[(b, r)] = self._derive(b, r)\n",
        ],
        "negative": [
            # generation term in the key
            "class C:\n"
            "    def __init__(self):\n"
            "        self._plan_cache = {}\n"
            "    def put(self, topic, generation, plan):\n"
            "        self._plan_cache[(topic, generation)] = plan\n",
            # clear-on-mutation: invalidate() empties the memo
            "class C:\n"
            "    def __init__(self):\n"
            "        self._memo = {}\n"
            "    def memo(self, key, fn):\n"
            "        self._memo[key] = fn()\n"
            "    def invalidate(self):\n"
            "        self._memo.clear()\n",
            # TTL sibling store records when the cache was filled
            "import time\n"
            "class C:\n"
            "    def refresh(self, model):\n"
            "        self._cached_plan = self._compute(model)\n"
            "        self._cached_at = time.time()\n",
            # the cached value itself carries its generation
            "class C:\n"
            "    def refresh(self, model, gen):\n"
            "        self._cached_plan = CachedPlan(plan=model,\n"
            "                                       generation=gen)\n",
            # locks named like caches are infrastructure, not caches
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._cache_lock = threading.Lock()\n",
            # storing None/empty IS the invalidation, never flagged
            "class C:\n"
            "    def invalidate_cache(self):\n"
            "        self._cached_plan = None\n",
        ],
    },
    "swallowed-exception": {
        "positive": [
            "def loop(work):\n"
            "    while True:\n"
            "        try:\n"
            "            work()\n"
            "        except Exception:\n"
            "            pass\n",
            "def drain(items):\n"
            "    for it in items:\n"
            "        try:\n"
            "            it.close()\n"
            "        except:\n"
            "            continue\n",
        ],
        "negative": [
            # logged -> fine
            "def loop(work):\n"
            "    while True:\n"
            "        try:\n"
            "            work()\n"
            "        except Exception:\n"
            "            LOG.exception('tick failed')\n",
            # narrow catch -> fine
            "def loop(work):\n"
            "    while True:\n"
            "        try:\n"
            "            work()\n"
            "        except KeyError:\n"
            "            pass\n",
            # not in a loop -> out of scope
            "def once(work):\n"
            "    try:\n"
            "        work()\n"
            "    except Exception:\n"
            "        pass\n",
        ],
    },
}


def test_every_registered_rule_has_fixtures():
    """Registry ↔ fixture-table closure: a rule without a positive
    fixture is an untested rule."""
    assert set(RULE_FIXTURES) == set(RULES)
    for rule_id, cases in RULE_FIXTURES.items():
        assert cases["positive"], f"{rule_id}: no positive fixture"
        assert cases["negative"], f"{rule_id}: no negative fixture"


@pytest.mark.parametrize("rule_id", sorted(set(RULES) - {"config-key-drift"}))
def test_rule_fixtures(rule_id):
    for code in RULE_FIXTURES[rule_id]["positive"]:
        found = findings_for(rule_id, code)
        assert found, f"{rule_id} missed a positive fixture:\n{code}"
        assert all(f.rule == rule_id for f in found)
        assert all(f.line >= 1 for f in found)
    for code in RULE_FIXTURES[rule_id]["negative"]:
        found = findings_for(rule_id, code)
        assert not found, (
            f"{rule_id} false positive:\n{code}\n"
            + "\n".join(f.render() for f in found)
        )


# ---- config-key-drift (project rule) --------------------------------------------
def test_config_rule_flags_undefined_used_key(tmp_path):
    bad = tmp_path / "uses_bad_key.py"
    bad.write_text(RULE_FIXTURES["config-key-drift"]["positive"][0])
    result = run_lint(paths=[str(bad)], rules=["config-key-drift"])
    assert any(
        f.rule == "config-key-drift" and "no.such.key" in f.message
        for f in result.findings
    )
    good = tmp_path / "uses_good_key.py"
    good.write_text(RULE_FIXTURES["config-key-drift"]["negative"][0])
    result = run_lint(paths=[str(good)], rules=["config-key-drift"])
    assert not [f for f in result.findings if "key" in f.message]


def test_config_used_key_extraction():
    import ast

    tree = ast.parse(
        "x = cfg.get('webserver.http.port')\n"          # config receiver
        "y = config.get_int('simulation.seed')\n"       # typed getter
        "z = some_dict.get('not.config')\n"             # plain dict .get
        "w = cfg.get(key_var)\n"                        # non-literal
    )
    keys = {k for k, _ in used_keys(tree)}
    assert keys == {"webserver.http.port", "simulation.seed"}


def test_config_doc_table_parsing_and_drift_detection():
    doc = (
        "# Configuration keys\n"
        "| key | type |\n"
        "|---|---|\n"
        "| `alpha.beta` | INT |\n"
        "| `gamma.delta` | STRING |\n"
    )
    table = doc_keys(doc)
    assert set(table) == {"alpha.beta", "gamma.delta"}
    assert table["alpha.beta"] == 4  # line anchor for the finding
    # both drift directions are set differences over these views — prove
    # the live pass sees the real registry and doc agreeing
    result = run_lint(paths=[str(PKG / "config")],
                      rules=["config-key-drift"])
    assert not result.findings, "\n".join(
        f.render() for f in result.findings)


# ---- suppressions ---------------------------------------------------------------
SWALLOW = (
    "def loop(work):\n"
    "    while True:\n"
    "        try:\n"
    "            work()\n"
    "        except Exception:{comment}\n"
    "            pass\n"
)


def _lint_file(tmp_path, code, name="mod.py", rules=None):
    path = tmp_path / name
    path.write_text(code)
    return run_lint(paths=[str(path)], rules=rules)


def test_suppression_with_reason_is_honored(tmp_path):
    result = _lint_file(
        tmp_path,
        SWALLOW.format(
            comment="  # cclint: disable=swallowed-exception -- fixture: "
                    "deliberately silent"),
    )
    assert not result.findings
    assert result.suppressions_used == 1


def test_suppression_without_reason_fails(tmp_path):
    result = _lint_file(
        tmp_path, SWALLOW.format(
            comment="  # cclint: disable=swallowed-exception"),
    )
    rules = {f.rule for f in result.findings}
    # the original finding survives AND the reasonless suppression is
    # itself flagged
    assert rules == {"swallowed-exception", BAD_SUPPRESSION}


def test_suppression_with_unknown_rule_fails(tmp_path):
    result = _lint_file(
        tmp_path, SWALLOW.format(
            comment="  # cclint: disable=swalowed-exception -- typo"),
    )
    assert {f.rule for f in result.findings} == {
        "swallowed-exception", BAD_SUPPRESSION}


def test_bad_suppression_cannot_be_suppressed(tmp_path):
    code = ("x = 1  # cclint: disable=bad-suppression,"
            "swallowed-exception\n")
    result = _lint_file(tmp_path, code)
    assert [f.rule for f in result.findings] == [BAD_SUPPRESSION]


def test_suppression_in_string_literal_is_ignored():
    supp = parse_suppressions(
        "doc.py",
        'DOC = """example:\n'
        '    x()  # cclint: disable=swallowed-exception -- example\n'
        '"""\n',
        set(RULES),
    )
    assert not supp.by_line and not supp.malformed


def test_unused_suppression_is_reported_as_note(tmp_path):
    result = _lint_file(
        tmp_path,
        "x = 1  # cclint: disable=swallowed-exception -- nothing here\n",
    )
    assert not result.findings
    assert result.unused_suppressions
    assert "unused suppression" in result.render_text()


def test_checked_in_suppressions_are_load_bearing(tmp_path):
    """Flipping any one suppression off re-surfaces its finding at the
    same file:line (the acceptance criterion for zero-findings-by-
    suppression honesty)."""
    marker = re.compile(r"\s*# cclint: disable=[^\n]*")
    checked = 0
    for path in sorted(PKG.rglob("*.py")):
        text = path.read_text()
        if "cclint: disable=" not in text:
            continue
        supp = parse_suppressions(str(path), text, set(RULES))
        if not supp.by_line:
            continue  # marker only appears inside a string literal (docs)
        stripped = tmp_path / path.name
        stripped.write_text(marker.sub("", text))
        result = run_lint(paths=[str(stripped)])
        surfaced = {(f.line, f.rule) for f in result.findings}
        for line, rule_ids in supp.by_line.items():
            for rule_id in rule_ids:
                assert (line, rule_id) in surfaced, (
                    f"{path}:{line} suppression for '{rule_id}' is stale "
                    "— the finding no longer fires without it"
                )
                checked += 1
    assert checked >= 4  # the suppressions this PR checked in


# ---- output contracts -----------------------------------------------------------
LINT_SCHEMAS = json.loads(
    (pathlib.Path(__file__).parent / "schemas" / "lint.schema.json")
    .read_text()
)


def test_json_output_matches_checked_in_schema(tmp_path):
    result = _lint_file(tmp_path, SWALLOW.format(comment=""))
    assert result.findings  # a non-trivial payload
    payload = json.loads(render(result, "json"))
    validate(json.loads(json.dumps(payload)),
             LINT_SCHEMAS["cc-tpu-lint/1"])
    assert payload["counts"]["swallowed-exception"] == 1


def test_text_output_format(tmp_path):
    result = _lint_file(tmp_path, SWALLOW.format(comment=""))
    line = result.findings[0].render()
    # the clickable anchor contract: file:line · rule-id · message
    assert re.match(r"^.+\.py:\d+ · swallowed-exception · ", line)


def test_parse_error_is_a_finding(tmp_path):
    result = _lint_file(tmp_path, "def broken(:\n")
    assert [f.rule for f in result.findings] == ["parse-error"]


# ---- the CLI --------------------------------------------------------------------
def test_cli_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(SWALLOW.format(comment=""))
    assert cclint_main([str(bad)]) == 1
    assert cclint_main([str(bad), "--rule=lock-discipline"]) == 0
    assert cclint_main([str(bad), "--rule=not-a-rule"]) == 2
    assert cclint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in RULES:
        assert rule_id in out


def test_cli_json_format(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(SWALLOW.format(comment=""))
    assert cclint_main([str(bad), "--format=json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["schema"] == "cc-tpu-lint/1"
    validate(payload, LINT_SCHEMAS["cc-tpu-lint/1"])


# ---- the tree is clean ----------------------------------------------------------
def test_sim_package_is_scanned_and_clean():
    """The fault-injection simulator (sim/) is part of the linted tree and
    carries zero findings of its own (ISSUE 6 satellite)."""
    result = run_lint(paths=[str(PKG / "sim")])
    assert result.files_scanned >= 7
    assert not result.findings, "\n".join(
        f.render() for f in result.findings
    )


def test_package_lints_clean_within_budget():
    """The tier-1 wrapper: the whole package, every rule, zero findings,
    single parse per file, < 5 s wall clock."""
    result = run_lint(paths=[str(PKG)])
    assert not result.findings, (
        "cclint found new violations — fix them or add a reviewed "
        "suppression with a reason (docs/STATIC_ANALYSIS.md):\n"
        + "\n".join(f.render() for f in result.findings)
    )
    assert result.files_scanned > 50
    assert result.duration_s < 5.0, (
        f"lint pass took {result.duration_s:.2f}s — the single-parse "
        "budget regressed"
    )
