"""Test harness config: force a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in the build environment; sharding
paths are validated on 8 virtual CPU devices (same XLA SPMD partitioner), per
SURVEY.md §4's test-strategy mapping.

Gotcha: the ambient environment's ``sitecustomize`` imports jax at interpreter
startup and registers the real-TPU (axon) backend, so ``JAX_PLATFORMS`` set
here via ``os.environ`` is read too late.  ``jax.config.update`` works
post-import as long as no backend has initialized yet — and keeps the tests
off the single shared TPU chip (dialing it can block on another process's
session).
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
# Tests keep persistent caching of XLA:CPU executables (suite is ~2× faster
# with it).  Driver entry points (bench.py, __graft_entry__) leave this
# unset, so their artifacts never contain the spurious cpu_aot_loader
# feature-mismatch error wall — see utils/jit_cache._exclude_cpu_executables.
os.environ.setdefault("CC_TPU_CACHE_CPU_EXECUTABLES", "1")

import jax

jax.config.update("jax_platforms", "cpu")


def pytest_sessionfinish(session, exitstatus):
    """Record the session verdict for the teardown guard below."""
    session.config._cc_exitstatus = int(exitstatus)


def pytest_unconfigure(config):
    """Exit without running interpreter teardown.

    The suite spins hundreds of short-lived XLA compilations and HTTP
    servers; on this jaxlib, C++ static destruction at interpreter exit
    can intermittently `terminate called without an active exception`
    (SIGABRT) AFTER pytest has already printed its summary and computed
    its exit status — turning a fully green run into rc=134.  Nothing
    after this point affects the test verdict, so flush and leave via
    ``os._exit`` with the real status, skipping the destructor race
    entirely."""
    import sys

    status = getattr(config, "_cc_exitstatus", None)
    if status is None:  # collection-only/plugin paths: normal exit
        return
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(status)
