"""Artifact-schema contracts: every telemetry artifact producer is built
LIVE and validated against the checked-in JSON-schema contract
(tests/schemas/artifacts.schema.json), so silent field drift — a renamed,
dropped, or retyped field — fails CI with the offending path instead of
breaking postmortem tooling that reads committed artifacts.

The validator implements the JSON-Schema subset the contract uses
(type / properties / required / items / additionalProperties / enum);
the build environment ships no ``jsonschema`` package and the subset
keeps the contract readable.
"""

import json
import pathlib

import pytest

SCHEMAS = json.loads(
    (pathlib.Path(__file__).parent / "schemas" / "artifacts.schema.json")
    .read_text()
)

_TYPES = {
    "object": dict,
    "array": list,
    "string": str,
    "boolean": bool,
    "null": type(None),
}


def validate(value, schema, path="$"):
    """Raise AssertionError with the offending path on any mismatch."""
    if "enum" in schema:
        assert value in schema["enum"], \
            f"{path}: {value!r} not in {schema['enum']}"
    t = schema.get("type")
    if t == "number":
        assert isinstance(value, (int, float)) and not isinstance(
            value, bool), f"{path}: expected number, got {type(value).__name__}"
    elif t == "integer":
        assert isinstance(value, int) and not isinstance(value, bool), \
            f"{path}: expected integer, got {type(value).__name__}"
    elif t is not None:
        assert isinstance(value, _TYPES[t]), \
            f"{path}: expected {t}, got {type(value).__name__}"
    if isinstance(value, dict):
        props = schema.get("properties", {})
        for req in schema.get("required", ()):
            assert req in value, f"{path}: missing required field {req!r}"
        extra = schema.get("additionalProperties", True)
        for k, v in value.items():
            if k in props:
                validate(v, props[k], f"{path}.{k}")
            elif isinstance(extra, dict):
                validate(v, extra, f"{path}.{k}")
            else:
                assert extra is not False, \
                    f"{path}: unexpected field {k!r} (closed schema — " \
                    f"extend tests/schemas/artifacts.schema.json first)"
    if isinstance(value, list) and "items" in schema:
        for i, v in enumerate(value):
            validate(v, schema["items"], f"{path}[{i}]")


# ---- live producers --------------------------------------------------------------
def _phase_profile_artifact():
    from cruise_control_tpu.telemetry import profile
    from cruise_control_tpu.telemetry.tracing import Telemetry

    tel = Telemetry(enabled=True)
    with tel.span("facade.rebalance"):
        with tel.span("analyzer.scan"):
            pass
        with tel.span("analyzer.apply"):
            pass
    return [profile.make_artifact(tel=tel),
            profile.make_artifact(extra={"fixture": "50b/1k"}, tel=tel)]


def _flight_recorder_artifacts():
    from cruise_control_tpu.telemetry.events import EventJournal
    from cruise_control_tpu.telemetry.recorder import FlightRecorder
    from cruise_control_tpu.utils.metrics import MetricRegistry

    reg = MetricRegistry()
    reg.counter("ops").inc(3)
    reg.gauge("depth", lambda: 7.0)
    reg.timer("op-timer").update(0.01)
    journal = EventJournal(enabled=True)
    journal.emit("optimize.start", operation="REBALANCE", engine="greedy")
    rec = FlightRecorder(
        reg, interval_s=60.0, retention=16,
        journal_source=lambda: [{"timeMs": 1, "action": "IGNORE"}],
        events_source=lambda: journal.recent(),
    )
    rec.sample_once(now=100.0)
    rec.sample_once(now=105.0)
    return [rec.artifact(), rec.artifact(extra={"dumpReason": "FIX_FAILED"})]


def _event_records(tmp_path):
    from cruise_control_tpu.telemetry.events import EventJournal

    path = tmp_path / "events.jsonl"
    journal = EventJournal(enabled=True, path=str(path))
    journal.emit("optimize.start", operation="REBALANCE",
                 engine="GoalOptimizer", dryrun=True)
    journal.emit("executor.task_dead", severity="WARNING", task_id="t-1",
                 partition=3, reason="timeout")
    journal.emit("detector.anomaly")  # minimal record: no optional fields
    ring = journal.recent()
    on_disk = [json.loads(line) for line in
               path.read_text().strip().splitlines()]
    journal.close()
    assert len(ring) == len(on_disk) == 3
    return ring + on_disk


def _checkpoint_records(tmp_path):
    """Run a real execution with the write-ahead journal attached and
    validate every record it persisted (plus a torn-tail read-back)."""
    from cruise_control_tpu.analyzer.goal_optimizer import ExecutionProposal
    from cruise_control_tpu.executor.backend import SimulatedClusterBackend
    from cruise_control_tpu.executor.executor import Executor
    from cruise_control_tpu.executor.journal import ExecutionJournal

    path = tmp_path / "execution.ckpt.jsonl"
    backend = SimulatedClusterBackend(
        {0: [0, 1], 1: [1, 2]}, {0: 0, 1: 1}, brokers={0, 1, 2, 3},
    )
    journal = ExecutionJournal(str(path))
    recorded = []
    original = journal._write_line

    def capture(line):
        recorded.append(json.loads(line))
        original(line)

    journal._write_line = capture
    ex = Executor(backend, journal=journal)
    result = ex.execute_proposals([
        ExecutionProposal(partition=0, topic=0, old_leader=0, new_leader=2,
                          old_replicas=(0, 1), new_replicas=(2, 3)),
    ])
    assert result.succeeded
    assert {r["kind"] for r in recorded} >= {"start", "batch", "task", "end"}
    # the end record truncated the file: nothing left to recover
    assert journal.load() is None
    return recorded


def _slo_artifacts():
    """Both live producers: the engine's GET /slo payload (hysteresis
    attached) and the scenario-mode pure evaluation."""
    from cruise_control_tpu.telemetry.events import EventJournal
    from cruise_control_tpu.telemetry.slo import SloEngine, evaluate_slos
    from cruise_control_tpu.utils.metrics import MetricRegistry

    journal = EventJournal(enabled=True)
    journal.emit("replan.end", mode="warm")
    journal.emit("detector.anomaly", anomalyType="BROKER_FAILURE",
                 timeMs=120_000, fixStarted=True, action="FIX")
    reg = MetricRegistry()
    reg.timer("http.GET.proposals").update(0.005)
    engine = SloEngine(registry=reg,
                       events_reader=lambda: journal.recent(),
                       window_ms=1e12)
    engine.evaluate()
    scenario = evaluate_slos(journal.recent(), source="scenario",
                             horizon_ms=600_000)
    return [engine.report(),
            scenario.to_artifact(extra={"scenario": {"name": "probe"}})]


def _trace_artifact():
    from cruise_control_tpu.telemetry.events import EventJournal
    from cruise_control_tpu.telemetry.trace import TraceStore, chrome_trace
    from cruise_control_tpu.telemetry.tracing import Telemetry

    tel = Telemetry(enabled=True)
    store = TraceStore()
    tel.root_sink = store.on_root
    journal = EventJournal(enabled=True)
    with tel.trace_scope("probe-trace"), journal.trace_scope("probe-trace"):
        with tel.span("http.GET.proposals"):
            with tel.device_span("analyzer.scan"):
                pass
            journal.emit("replan.end", mode="warm")
    evs = [e for e in journal.recent()
           if e.get("traceId") == "probe-trace"]
    assert evs, "trace scope failed to stamp the journal"
    return [chrome_trace("probe-trace", store.spans("probe-trace"), evs)]


def _soak_artifact():
    """A micro soak (12 virtual minutes, 8 brokers) through the REAL
    driver: the cc-tpu-soak/1 producer exercised end to end."""
    from cruise_control_tpu.sim.fault_schedule import FaultScheduleConfig
    from cruise_control_tpu.sim.soak import (
        MIN_MS,
        SoakSpec,
        make_soak_artifact,
        run_soak,
    )

    spec = SoakSpec(
        name="soak_probe", seed=3,
        num_brokers=8, num_racks=2, num_partitions=24, num_topics=2,
        engine="greedy",
        duration_ms=12 * MIN_MS, diurnal_period_ms=12 * MIN_MS,
        detection_interval_ms=2 * MIN_MS, fix_cooldown_ms=MIN_MS,
        precompute_interval_ticks=3,
        journal_ring_size=4096, journal_max_bytes=65536,
        sample_interval_ticks=2, slo_interval_ticks=4,
        slo_window_ms=6 * MIN_MS,
        schedule=FaultScheduleConfig(
            seed=3, duration_ms=12 * MIN_MS,
            num_brokers=8, num_racks=2, num_partitions=24,
            broker_deaths=0, rack_losses=0, disk_failures=1,
            hot_skews=0, load_perturbations=0, metric_gaps=0,
            process_crashes=0, broker_flaps=0, analyzer_outages=0,
            execution_stalls=0, request_storms=0,
            settle_ms=3 * MIN_MS, quiet_tail_ms=4 * MIN_MS,
            min_spacing_ms=2 * MIN_MS, heal_ms=2 * MIN_MS,
            http_poll_interval_ms=4 * MIN_MS,
        ),
    )
    return [make_soak_artifact(run_soak(spec))]


def _scenario_artifact():
    from cruise_control_tpu.sim import ScenarioSpec, make_artifact, run_scenario
    from cruise_control_tpu.sim.timeline import Timeline, disk_failure

    spec = ScenarioSpec(
        name="schema_probe",
        description="minimal live run for the artifact contract",
        timeline=Timeline([disk_failure(2 * 60_000, broker=1)]),
        self_healing={"disk_failure": True},
        num_brokers=4, num_racks=2, num_partitions=12,
        duration_ms=6 * 60_000,
    )
    return [make_artifact([run_scenario(spec)])]


def _whatif_artifact():
    """The live cc-tpu-whatif/1 producer: a small-scale batched sweep
    (8 futures, one dispatch) + the real proactive-vs-reactive scenario
    twins through the actual measurement functions."""
    from cruise_control_tpu.whatif.artifact import (
        make_artifact,
        measure_batch,
        measure_proactive,
    )

    batch = measure_batch(num_futures=8, best_of=1, num_brokers=6,
                          num_racks=3, num_partitions=24)
    return [make_artifact(batch, measure_proactive())]


def _host_profile_artifacts():
    """The live producer: a deterministic capture over a synthetic
    frame stream (the same ingest() surface the sampler daemon uses),
    built through the real off-thread parse path."""
    from cruise_control_tpu.telemetry.host_profile import HostProfiler

    p = HostProfiler(interval_ms=10.0, clock=lambda: 1000.0,
                     id_factory=lambda: "host-capture-probe")
    p.arm(samples=3, reason="schema-probe")
    for _ in range(3):
        p.ingest([
            ("Thread-4", "server/http_server:_dispatch;facade:serve"),
            ("cc-slo-engine", "telemetry/slo:_tick"),
            ("user-task_0", "executor/executor:execute_proposals"),
        ])
    assert p.parse_pending() == 1
    art = p.latest()
    assert art is not None
    return [art]


def _critical_path_artifacts():
    """The live producer: real request_scope clocks + a real journal
    heal episode through heal_episodes(), assembled by build_artifact."""
    from cruise_control_tpu.telemetry import critical_path as cp

    store = cp.CriticalPathStore()
    ticks = iter([i * 0.001 for i in range(1000)])
    for _ in range(20):
        clock = cp.PhaseClock(clock=lambda: next(ticks))
        clock.endpoint = "proposals"
        for phase in ("parse", "auth", "admissionQueue", "facade",
                      "handler", "serialize", "flush"):
            clock.mark(phase)
        store.record(clock)
    serve = store.decompose("proposals")
    heal = cp.heal_episodes([
        {"ts": 100.0, "kind": "sim.fault"},
        {"ts": 101.5, "kind": "detector.anomaly"},
        {"ts": 101.6, "kind": "detector.recovery_cooldown"},
        {"ts": 103.0, "kind": "optimize.start"},
        {"ts": 105.0, "kind": "optimize.end"},
        {"ts": 105.2, "kind": "executor.start"},
        {"ts": 109.0, "kind": "executor.end"},
    ])
    assert serve is not None and len(heal) == 1
    return [cp.build_artifact(serve=serve, heal=heal,
                              metrics_scrape={"beforeWaitMs": 10.0,
                                              "afterWaitMs": 1.0},
                              now=1000.0)]


def _kernel_budget_artifacts():
    """The live producer: a REAL capture of the scan program at the tiny
    pinned fixture (shared — and session-cached — with
    tests/test_kernel_budget.py, so one capture serves both suites)."""
    import test_kernel_budget as tkb

    art = tkb._live_capture()["artifact"]
    assert art is not None
    return [art]


def _mesh_budget_artifacts():
    """The live producer: the mesh observatory rides the SAME session
    capture (tests/test_mesh_budget.py attaches it at import, before any
    test runs ``tkb._live_capture()``)."""
    import test_mesh_budget as tmb

    art = tmb._live_mesh()["artifact"]
    assert art is not None
    return [art]


def _sharded_scaling_artifacts():
    """Live producer at micro scale — the full three-leg matrix at
    10b/80p plus the placement leg at 24b/600p (the committed r20
    artifact runs the advertised scales; the contract is
    shape-independent) — AND the committed artifact itself, so the file
    postmortem tooling reads is held to the same contract."""
    import pathlib
    import sys

    sys.path.insert(0, str(
        pathlib.Path(__file__).parent.parent / "benchmarks"))
    from sharded_large_dryrun import measure_scaling

    live = measure_scaling(devices=8, seed=13, scales=[(10, 80, 4)],
                           placement=(24, 600, 6), replicated_max_p=80)
    assert live["headline"]["ok"]
    committed = json.loads(
        (pathlib.Path(__file__).parent.parent / "benchmarks"
         / "SHARDED_SCALING_r20.json").read_text())
    return [live, committed]


@pytest.mark.parametrize("producer", ["phase-profile", "flight-recorder",
                                      "events", "scenarios", "checkpoint",
                                      "slo", "trace", "soak",
                                      "kernel-budget", "mesh-budget",
                                      "sharded-scaling",
                                      "whatif", "host-profile",
                                      "critical-path"])
def test_artifact_producers_match_checked_in_contract(producer, tmp_path):
    if producer == "phase-profile":
        arts = _phase_profile_artifact()
        schema = SCHEMAS["cc-tpu-phase-profile/1"]
    elif producer == "flight-recorder":
        arts = _flight_recorder_artifacts()
        schema = SCHEMAS["cc-tpu-flight-recorder/1"]
    elif producer == "scenarios":
        arts = _scenario_artifact()
        schema = SCHEMAS["cc-tpu-scenarios/1"]
    elif producer == "checkpoint":
        arts = _checkpoint_records(tmp_path)
        schema = SCHEMAS["cc-tpu-execution-checkpoint/1"]
    elif producer == "slo":
        arts = _slo_artifacts()
        schema = SCHEMAS["cc-tpu-slo/1"]
    elif producer == "trace":
        arts = _trace_artifact()
        schema = SCHEMAS["cc-tpu-trace/1"]
    elif producer == "kernel-budget":
        arts = _kernel_budget_artifacts()
        schema = SCHEMAS["cc-tpu-kernel-budget/2"]
    elif producer == "mesh-budget":
        arts = _mesh_budget_artifacts()
        schema = SCHEMAS["cc-tpu-mesh-budget/1"]
    elif producer == "sharded-scaling":
        arts = _sharded_scaling_artifacts()
        schema = SCHEMAS["cc-tpu-sharded-scaling/1"]
    elif producer == "whatif":
        arts = _whatif_artifact()
        schema = SCHEMAS["cc-tpu-whatif/1"]
    elif producer == "host-profile":
        arts = _host_profile_artifacts()
        schema = SCHEMAS["cc-tpu-host-profile/1"]
    elif producer == "critical-path":
        arts = _critical_path_artifacts()
        schema = SCHEMAS["cc-tpu-critical-path/1"]
    elif producer == "soak":
        arts = _soak_artifact()
        schema = SCHEMAS["cc-tpu-soak/1"]
        # the embedded gate table is itself a valid cc-tpu-slo/1
        validate(json.loads(json.dumps(arts[0]["slo"])),
                 SCHEMAS["cc-tpu-slo/1"])
    else:
        arts = _event_records(tmp_path)
        schema = SCHEMAS["cc-tpu-events/1"]
    for art in arts:
        # every artifact must round-trip as plain JSON (numpy scalars or
        # other non-JSON types in a payload are drift too)
        validate(json.loads(json.dumps(art)), schema)


def test_validator_catches_drift():
    """The contract has teeth: drop / retype / extend each fails."""
    schema = SCHEMAS["cc-tpu-events/1"]
    good = {"schema": "cc-tpu-events/1", "ts": 1.0, "kind": "a.b",
            "severity": "INFO"}
    validate(good, schema)
    with pytest.raises(AssertionError, match="missing required"):
        validate({k: v for k, v in good.items() if k != "ts"}, schema)
    with pytest.raises(AssertionError, match="expected number"):
        validate({**good, "ts": "yesterday"}, schema)
    with pytest.raises(AssertionError, match="closed schema"):
        validate({**good, "novel_field": 1}, schema)
    with pytest.raises(AssertionError, match="not in"):
        validate({**good, "severity": "FATAL"}, schema)
