"""utils.metrics registry tests (upstream MetricRegistry/JMX analog, §5.1)."""

import threading

from cruise_control_tpu.utils.metrics import MetricRegistry

from harness import full_stack


def test_timer_meter_counter_gauge_snapshot():
    reg = MetricRegistry()
    with reg.timer("op"):
        pass
    reg.timer("op").update(0.5)
    reg.meter("reqs").mark(3)
    reg.counter("errs").inc()
    reg.gauge("depth", lambda: 7)
    snap = reg.snapshot()
    assert snap["timers"]["op"]["count"] == 2
    assert snap["timers"]["op"]["maxSec"] >= 0.5
    assert snap["meters"]["reqs"]["count"] == 3
    assert snap["counters"]["errs"]["count"] == 1
    assert snap["gauges"]["depth"] == 7


def test_registry_thread_safety():
    reg = MetricRegistry()

    def work():
        for _ in range(500):
            reg.meter("m").mark()
            reg.timer("t").update(0.001)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = reg.snapshot()
    assert snap["meters"]["m"]["count"] == 4000
    assert snap["timers"]["t"]["count"] == 4000


def test_facade_wires_registry_into_state():
    cc, backend, _ = full_stack()
    cc.rebalance(dryrun=True)
    metrics = cc.state()["Metrics"]
    assert metrics["timers"]["proposal-computation-timer"]["count"] >= 1
    assert metrics["meters"]["operation.rebalance"]["count"] >= 1
