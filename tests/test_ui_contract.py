"""Operator-UI contract tests (round-2 VERDICT weak #4 / next-round #7).

The dashboard's in-page JS is a thin fetch layer (``jget`` / ``post`` /
``opQuery`` / ``opForm`` / ``review``) over the REST API.  These tests pin
the CONTRACT that layer relies on, server-side, exactly as the browser
exercises it (raw HTTP, no long-poll client):

* every GET the page renders returns the keys the JS dereferences;
* every mutating form's endpoint+params round-trip through the async
  202 + ``User-Task-ID`` + ``user_tasks`` poll loop the page implements;
* errors surface as JSON the page can render (the commit-4b6f814 class of
  silently-swallowed review errors cannot recur);
* the review-board two-step flow works end to end;
* a vocabulary scan of ``ui.html`` fails this file when the page grows a
  fetch call whose endpoint has no contract coverage here.
"""

import json
import re
import time
import urllib.error
import urllib.request
from pathlib import Path

import pytest

from cruise_control_tpu.server import CruiseControlHttpServer

from harness import full_stack

UI_HTML = (
    Path(__file__).resolve().parent.parent
    / "cruise_control_tpu" / "server" / "ui.html"
)

#: endpoint vocabulary the dashboard uses (kept in lockstep with ui.html by
#: test_ui_vocabulary_is_covered)
UI_GET_ENDPOINTS = {
    "state", "load", "user_tasks", "kafka_cluster_state",
    "partition_load", "proposals", "review_board",
}
UI_POST_ENDPOINTS = {
    "rebalance", "add_broker", "remove_broker", "demote_broker",
    "topic_configuration", "fix_offline_replicas", "rightsize",
    "pause_sampling", "resume_sampling", "stop_proposal_execution",
    "review",
}


@pytest.fixture
def server():
    cc, backend, _ = full_stack()
    srv = CruiseControlHttpServer(cc, port=0)
    srv.start()
    yield srv, cc, backend
    srv.stop()


def _get(srv, path):
    with urllib.request.urlopen(f"{srv.url}/{path}") as r:
        return json.loads(r.read()), r.status, dict(r.headers)


def _post(srv, path):
    req = urllib.request.Request(f"{srv.url}/{path}", method="POST")
    try:
        with urllib.request.urlopen(req) as r:
            return json.loads(r.read()), r.status, dict(r.headers)
    except urllib.error.HTTPError as e:
        return json.loads(e.read()), e.code, dict(e.headers)


def _poll_task(srv, task_id, timeout_s=30.0):
    """The page's opQuery loop: poll user_tasks?user_task_ids=ID."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        body, status, _ = _get(srv, f"user_tasks?user_task_ids={task_id}")
        tasks = body.get("userTasks", [])
        if tasks and tasks[0]["Status"] != "Active":
            return tasks[0]
        time.sleep(0.1)
    raise AssertionError(f"task {task_id} never completed")


def test_ui_vocabulary_is_covered():
    """Every endpoint ui.html's JS fetches must appear in the contract
    tables above — adding a UI call without contract coverage fails here."""
    js = UI_HTML.read_text()
    gets = set(re.findall(r"jget\(\s*[`\"']([a-z_]+)", js))
    # endpoints routed through post()/op()/opForm()/topicConfig in markup
    posts = set(re.findall(r"(?:post|op|opForm)\('([a-z_]+)'", js))
    posts |= set(re.findall(r"opQuery\(\"([a-z_]+)\"", js))
    # raw fetch calls that bypass the helpers (e.g. review's own fetch)
    posts |= set(re.findall(r"fetch\(`\$\{apiBase\(\)\}/([a-z_]+)[?`]", js))
    assert gets <= UI_GET_ENDPOINTS, gets - UI_GET_ENDPOINTS
    assert posts <= UI_POST_ENDPOINTS, posts - UI_POST_ENDPOINTS
    assert "review" in posts  # the raw-fetch scan actually fires


def test_state_keys_the_overview_renders(server):
    srv, _, _ = server
    st, status, _ = _get(srv, "state")
    assert status == 200
    # rendered RAW by the page (undefined would show literally)
    assert "upTimeSeconds" in st
    assert "state" in st["MonitorState"]
    assert "state" in st["ExecutorState"]
    # tolerant reads (?.): key may be absent, but when present must have
    # the shape the page dereferences
    if "AnomalyDetectorState" in st:
        assert isinstance(
            st["AnomalyDetectorState"].get("recentAnomalies", []), list
        )


def test_load_keys_the_bars_render(server):
    srv, _, _ = server
    body, _, _ = _get(srv, "load")
    brokers = body["brokers"]
    assert brokers
    for key in ("Broker", "BrokerState", "Rack", "CpuPct", "DiskMB",
                "DiskCapacityMB", "NwInRate", "NwOutRate"):
        assert key in brokers[0], (key, sorted(brokers[0]))


def test_kafka_cluster_state_keys(server):
    srv, _, _ = server
    k, _, _ = _get(srv, "kafka_cluster_state")
    parts = k["KafkaPartitionState"]["partitions"]
    assert parts and {"topic", "partition", "leader", "replicas",
                      "in-sync"} <= set(parts[0])
    assert k["KafkaBrokerState"]["Brokers"]
    assert "AliveBrokers" in k["KafkaBrokerState"]


@pytest.mark.parametrize("resource,field", [
    ("DISK", "disk"), ("CPU", "cpu"),
    ("NW_IN", "networkInbound"), ("NW_OUT", "networkOutbound"),
])
def test_partition_load_field_per_resource(server, resource, field):
    """The page's PL_FIELD mapping: each resource's records carry the field
    the table reads."""
    srv, _, _ = server
    body, _, _ = _get(srv, f"partition_load?resource={resource}&entries=25")
    recs = body["records"]
    assert recs and field in recs[0], (resource, sorted(recs[0]))


def test_proposals_keys_the_tab_renders(server):
    """The proposals tab reads movement stats top-level and the proposal
    rows' partition/oldReplicas/newReplicas (this test originally caught
    the tab reading a non-existent `summary` sub-object and rendering
    blanks — the server now carries the upstream movement stats)."""
    srv, _, _ = server
    body, _, _ = _get(srv, "proposals")
    for key in ("numReplicaMovements", "numLeaderMovements",
                "dataToMoveMB", "engine", "violationsAfter", "proposals"):
        assert key in body, (key, sorted(body))
    assert body["numReplicaMovements"] > 0
    assert body["dataToMoveMB"] > 0
    pr = body["proposals"][0]
    assert {"partition", "oldReplicas", "newReplicas"} <= set(pr)
    body2, _, _ = _get(srv, "proposals?ignore_proposal_cache=true")
    assert "proposals" in body2


def test_opquery_async_protocol_rebalance_form(server):
    """The rebalance form: POST → 202 + User-Task-ID → poll to completion
    with a result — the exact loop opQuery implements."""
    srv, _, _ = server
    body, status, headers = _post(
        srv, "rebalance?dryrun=true&goals=ReplicaDistributionGoal"
        "&engine=greedy")
    assert status == 202, body
    tid = headers.get("User-Task-ID")
    assert tid
    task = _poll_task(srv, tid)
    assert task["Status"] == "Completed"
    assert task.get("result", {}).get("numProposals", 0) >= 0


@pytest.mark.parametrize("query", [
    "add_broker?dryrun=true&brokerid=9",
    "remove_broker?dryrun=true&brokerid=3",
    "demote_broker?dryrun=true&brokerid=0",
    "topic_configuration?dryrun=true&replication_factor=2",
    "fix_offline_replicas?dryrun=true",
    "rightsize?dryrun=true",
])
def test_every_mutating_form_completes(query):
    """Each operations-tab form issues its endpoint+params and the async
    loop reaches a terminal state (Completed or a rendered error)."""
    cc, backend, _ = full_stack(extra_brokers=(9,))
    srv = CruiseControlHttpServer(cc, port=0)
    srv.start()
    try:
        body, status, headers = _post(srv, query)
        if status == 202:
            task = _poll_task(srv, headers["User-Task-ID"])
            assert task["Status"] in ("Completed", "CompletedWithError")
        else:
            assert status == 200, (query, status, body)
    finally:
        srv.stop()


def test_simple_posts_return_json(server):
    srv, _, _ = server
    for ep in ("pause_sampling", "resume_sampling",
               "stop_proposal_execution"):
        body, status, _ = _post(srv, ep)
        assert status == 200 and isinstance(body, dict), (ep, status)


def test_review_two_step_flow_and_error_surfacing():
    """The review tab end to end: submit → board lists it → approve →
    execute with review_id; a bad review id surfaces a JSON error the page
    renders (the commit-4b6f814 regression class)."""
    cc, _, _ = full_stack()
    srv = CruiseControlHttpServer(cc, port=0, two_step_verification=True)
    srv.start()
    try:
        body, status, _ = _post(srv, "rebalance?dryrun=true")
        assert "reviewId" in body, (status, body)
        rid = body["reviewId"]
        board, _, _ = _get(srv, "review_board")
        reqs = board["requestInfo"]
        mine = [r for r in reqs if r.get("Id", r.get("review_id")) == rid]
        assert mine and mine[0]["Status"] == "PENDING_REVIEW"
        # bad id → JSON error with a message, not a silent 200
        err, code, _ = _post(srv, "review?approve=99999")
        assert code >= 400 and isinstance(err, dict) and err, (code, err)
        # approve + execute
        ok, code, _ = _post(srv, f"review?approve={rid}&reason=lgtm")
        assert code == 200, ok
        body, status, headers = _post(
            srv, f"rebalance?dryrun=true&review_id={rid}")
        if status == 202:
            task = _poll_task(srv, headers["User-Task-ID"])
            assert task["Status"] == "Completed"
        else:
            assert status == 200
    finally:
        srv.stop()


def test_ui_page_served_with_api_prefix(server):
    srv, _, _ = server
    req = urllib.request.Request(srv.url.rsplit("/kafkacruisecontrol", 1)[0]
                                 + "/ui")
    with urllib.request.urlopen(req) as r:
        page = r.read().decode()
    assert "__API_PREFIX__" not in page  # prefix substituted
    assert "opQuery" in page


def test_history_charts_read_per_resource_capacities(server):
    """The history tab charts utilization % for EVERY resource — the load
    response must carry each resource's capacity, not just disk's."""
    srv, _, _ = server
    body, _, _ = _get(srv, "load")
    b0 = body["brokers"][0]
    for key in ("CpuCapacityPct", "NwInCapacity", "NwOutCapacity",
                "DiskCapacityMB"):
        assert key in b0 and b0[key] > 0, (key, sorted(b0))
    js = UI_HTML.read_text()
    for needle in ("pushHistory", "renderHistory", 'id="ch-disk"',
                   'id="ch-cpu"', 'id="ch-nwin"', 'id="ch-nwout"',
                   "tab-history"):
        assert needle in js, needle


def test_executor_history_drill_in_contract(server):
    """The tasks tab's executor-history card: after an execution,
    ExecutorState.recentExecutions carries the summary row and the
    per-move drill-in rows the JS dereferences."""
    srv, cc, _ = server
    body, status, headers = _post(srv, "rebalance?dryrun=false")
    if status == 202:
        task = _poll_task(srv, headers["User-Task-ID"])
        assert task["Status"] == "Completed", task
    st, _, _ = _get(srv, "state")
    execs = st["ExecutorState"]["recentExecutions"]
    assert execs, "no execution recorded"
    # the 5s-poll payload carries summaries ONLY — no per-move arrays
    assert all("tasks" not in e for e in execs)
    for key in ("executionId", "strategy", "numProposals", "completed",
                "dead", "aborted", "ticks", "stopped"):
        assert key in execs[-1], (key, sorted(execs[-1]))
    # the drill-in fetches state?verbose=true for the task arrays
    st, _, _ = _get(srv, "state?verbose=true")
    execs = st["ExecutorState"]["recentExecutions"]
    e = execs[-1]
    assert e["completed"] > 0 and e["tasks"]
    t0 = e["tasks"][0]
    for key in ("taskId", "type", "partition", "state", "from", "to",
                "startedTick", "finishedTick"):
        assert key in t0, (key, sorted(t0))
    assert "numFinishedMovements" in st["ExecutorState"]
    js = UI_HTML.read_text()
    for needle in ("renderExecHistory", "execDetail", 'id="exec-list"',
                   'id="exec-moves"', "state?verbose=true"):
        assert needle in js, needle


def test_proposal_diff_view_contract(server):
    """The proposals tab's broker-load-diff card: per-broker before→after
    deltas with the keys the JS dereferences, consistent with the plan's
    own movement accounting."""
    srv, _, _ = server
    body, _, _ = _get(srv, "proposals")
    diff = body["brokerLoadDiff"]
    assert diff, "plan moves replicas but brokerLoadDiff is empty"
    for key in ("broker", "replicaDelta", "leaderDelta", "diskDeltaMB"):
        assert key in diff[0], (key, sorted(diff[0]))
    # truncation indicator: totals let the UI label the table partial
    assert body["numBrokersChanged"] == len(diff)  # no truncation here
    # conservation: every replica/leader/byte added somewhere is removed
    # somewhere (no truncation at this fixture's broker count)
    assert sum(d["replicaDelta"] for d in diff) == 0
    assert sum(d["leaderDelta"] for d in diff) == 0
    assert sum(d["diskDeltaMB"] for d in diff) == pytest.approx(0, abs=1.0)
    # per-broker NET gains are bounded by the plan's GROSS data movement
    # (a broker that both gains and sheds nets below its gross adds)
    gains = sum(d["diskDeltaMB"] for d in diff if d["diskDeltaMB"] > 0)
    assert 0 < gains <= body["dataToMoveMB"] * 1.001
    js = UI_HTML.read_text()
    assert 'id="prop-diff"' in js and "brokerLoadDiff" in js


def test_multi_cluster_switcher_and_cors():
    """Upstream-UI parity: the dashboard can switch between Cruise
    Control servers.  The switcher is client-side (localStorage), every
    fetch routes through apiBase(), and a cross-origin target works when
    that server enables CORS — pin both halves."""
    js = UI_HTML.read_text()
    for needle in ('id="cluster-sel"', "switchCluster", "addCluster",
                   "removeCluster", "cc_clusters", "apiBase"):
        assert needle in js, needle
    # every fetch goes through the switchable base — live, or pinned at
    # task submission (opQuery's poll must not retarget mid-flight) —
    # none bypass it with the raw same-origin prefix
    assert "${API}/" not in js
    routed = (js.count("${apiBase()}/") + js.count("${base}/")
              + js.count("${base ?? apiBase()}/"))
    assert routed >= 4, routed
    # the server side of cross-origin: CORS headers when enabled
    cc, _, _ = full_stack()
    srv = CruiseControlHttpServer(cc, port=0, cors_enabled=True,
                                  cors_origin="https://ops.example")
    srv.start()
    try:
        _, status, headers = _get(srv, "state")
        assert status == 200
        assert headers.get("Access-Control-Allow-Origin") == \
            "https://ops.example"
        # without exposing it, the async 202 protocol's task id is
        # unreadable cross-origin and the remote poll loop never starts
        assert "User-Task-ID" in headers.get(
            "Access-Control-Expose-Headers", "")
        body, status, headers = _post(srv, "rebalance?dryrun=true")
        assert status == 202 and headers.get("User-Task-ID")
        assert "User-Task-ID" in headers.get(
            "Access-Control-Expose-Headers", "")
        _poll_task(srv, headers["User-Task-ID"])
    finally:
        srv.stop()


def test_expanded_dashboard_structure_and_data():
    """Round-3 UI expansion: the utilization rollup + sparkline, topic
    summary, and task drill-down exist in the page, and the endpoints they
    read carry the keys their JS dereferences."""
    js = UI_HTML.read_text()
    for needle in ("renderClusterUtil", "taskDetail", 'id="cluster-util"',
                   'id="spark"', 'id="topics"', 'id="task-steps"'):
        assert needle in js, needle
    cc, _, _ = full_stack()
    srv = CruiseControlHttpServer(cc, port=0)
    srv.start()
    try:
        # task drill-down reads operationProgress[].{step,timeInMs,completed}
        body, status, headers = _post(srv, "rebalance?dryrun=true")
        assert status == 202
        task = _poll_task(srv, headers["User-Task-ID"])
        steps = task["operationProgress"]
        assert steps and {"step", "timeInMs", "completed"} <= set(steps[0])
        # topic rollup reads partitions[].{topic,replicas,in-sync}
        k, _, _ = _get(srv, "kafka_cluster_state")
        p0 = k["KafkaPartitionState"]["partitions"][0]
        assert {"topic", "replicas", "in-sync"} <= set(p0)
    finally:
        srv.stop()


def test_goal_stats_view_contract(server):
    """The proposals tab's per-goal and cluster-stats cards (reference-UI
    goal readiness / ClusterModelStats parity): every key the JS
    dereferences is present and shaped as rendered."""
    srv, _, _ = server
    body, _, _ = _get(srv, "proposals")
    vb, va = body["violationsBefore"], body["violationsAfter"]
    assert vb and set(vb) == set(va)
    sb, sa = body["statsBefore"], body["statsAfter"]
    for st in (sb, sa):
        for r in ("CPU", "NW_IN", "NW_OUT", "DISK"):
            for key in ("mean", "std", "cv", "utilizationMean",
                        "utilizationStd"):
                assert key in st["resources"][r], (r, key)
        assert "std" in st["replicaCount"] and "std" in st["leaderCount"]
        assert "std" in st["potentialNwOut"]
    # the plan must not report worse balance than it started with on the
    # optimizer's primary axes (sanity tying the two snapshots together)
    assert sa["numAliveBrokers"] == sb["numAliveBrokers"]
    js = UI_HTML.read_text()
    for needle in ('id="prop-goals"', 'id="prop-stats"', "violationsBefore",
                   "statsBefore", "statsAfter"):
        assert needle in js, needle
