"""Kafka adapter (VERDICT round-1 item #2): the executor/monitor/detector
stack runs against KafkaClusterBackend over a scripted FakeKafkaWire with
the same assertions as the simulated backend, and the metrics/sample-store
paths round-trip through wire topics."""

import numpy as np
import pytest

from cruise_control_tpu.analyzer.goal_optimizer import ExecutionProposal
from cruise_control_tpu.executor.backend import SimulatedClusterBackend
from cruise_control_tpu.executor.executor import Executor, ExecutorConfig
from cruise_control_tpu.kafka import (
    FakeKafkaWire,
    KafkaClusterBackend,
    KafkaMetadataClient,
    KafkaMetricsReporter,
    KafkaMetricsReporterSampler,
    KafkaSampleStore,
)
from cruise_control_tpu.kafka.backend import (
    FOLLOWER_RATE,
    LEADER_RATE,
    LEADER_REPLICAS,
)
from cruise_control_tpu.monitor.sampling import (
    CruiseControlMetric,
    RawMetricType,
)

TOPIC = "t0"


def make_backend(kind: str, n_partitions: int = 6, rf: int = 2,
                 brokers=(0, 1, 2, 3), failed=None):
    """Same initial placement on both backend kinds: partition p on brokers
    (p % B, (p+1) % B), leader first."""
    B = len(brokers)
    assign = {
        p: [brokers[p % B], brokers[(p + 1) % B]] for p in range(n_partitions)
    }
    leaders = {p: a[0] for p, a in assign.items()}
    if kind == "simulated":
        return SimulatedClusterBackend(
            assign, leaders, brokers=set(brokers),
            failed_brokers=set(failed or ()),
        )
    wire = FakeKafkaWire(
        assignment={(TOPIC, p): reps for p, reps in assign.items()},
        leaders={(TOPIC, p): l for p, l in leaders.items()},
        broker_racks={b: f"rack_{b % 2}" for b in brokers},
        failed_brokers=set(failed or ()),
    )
    return KafkaClusterBackend(wire)


@pytest.mark.parametrize("kind", ["simulated", "kafka"])
def test_executor_moves_and_leadership(kind):
    """The core executor integration assertions, identical on both backends:
    replica moves land, leadership lands, ongoing set drains."""
    backend = make_backend(kind)
    ex = Executor(backend, ExecutorConfig(
        num_concurrent_partition_movements_per_broker=2,
    ))
    proposals = [
        # move p0's follower 1 -> 3 and hand p1's leadership to its follower
        ExecutionProposal(0, 0, old_leader=0, new_leader=0,
                          old_replicas=(0, 1), new_replicas=(0, 3)),
        ExecutionProposal(1, 0, old_leader=1, new_leader=2,
                          old_replicas=(1, 2), new_replicas=(2, 1)),
    ]
    result = ex.execute_proposals(proposals)
    assert result.succeeded, result
    st0 = backend.partition_state(0)
    assert sorted(st0.replicas) == [0, 3]
    st1 = backend.partition_state(1)
    assert st1.leader == 2
    assert backend.ongoing_reassignments() == set()


@pytest.mark.parametrize("kind", ["simulated", "kafka"])
def test_executor_throttle_set_and_cleared(kind):
    backend = make_backend(kind)
    ex = Executor(backend, ExecutorConfig(replication_throttle=12_500.0))
    proposals = [ExecutionProposal(0, 0, 0, 0, (0, 1), (0, 2))]
    result = ex.execute_proposals(proposals)
    assert result.succeeded
    # throttles must be gone after execution on either backend
    if kind == "kafka":
        for b in backend.alive_brokers():
            cfg = backend.describe_config("broker", b)
            assert LEADER_RATE not in cfg and FOLLOWER_RATE not in cfg
        assert LEADER_REPLICAS not in backend.describe_config("topic", TOPIC)
    else:
        assert backend.throttle_rate is None
        assert ("set", 12_500.0) in backend.throttle_history


def test_kafka_throttle_preserves_user_configs():
    """User-set dynamic configs survive the throttle set/clear cycle (the
    upstream ReplicationThrottleHelper contract)."""
    backend = make_backend("kafka")
    backend.wire.incremental_alter_configs(
        "broker", "0", {"log.cleaner.threads": "4"}
    )
    ex = Executor(backend, ExecutorConfig(replication_throttle=1000.0))
    ex.execute_proposals([ExecutionProposal(0, 0, 0, 0, (0, 1), (0, 2))])
    assert backend.describe_config("broker", "0") == {
        "log.cleaner.threads": "4"
    }


@pytest.mark.parametrize("kind", ["simulated", "kafka"])
def test_executor_dead_task_on_failed_broker(kind):
    """A destination that never catches up times out -> DEAD, not success
    (same observable behavior over the wire as in the simulation)."""
    backend = make_backend(kind, failed=(3,))
    ex = Executor(backend, ExecutorConfig(task_timeout_ticks=5))
    proposals = [ExecutionProposal(0, 0, 0, 0, (0, 1), (0, 3))]
    result = ex.execute_proposals(proposals, max_ticks=50)
    assert result.dead == 1 and not result.succeeded


@pytest.mark.parametrize("kind", ["simulated", "kafka"])
def test_executor_startup_recovery_detects_ongoing(kind):
    backend = make_backend(kind)
    backend.alter_partition_reassignments({0: [0, 3]})
    ex = Executor(backend)
    ongoing = ex.detect_ongoing_at_startup(stop=True)
    assert ongoing == {0}
    assert backend.ongoing_reassignments() == set()


def test_kafka_metrics_roundtrip_through_wire_topic():
    """Reporter -> __CruiseControlMetrics -> sampler -> processed samples,
    byte-identical processing to the in-process path."""
    backend = make_backend("kafka")
    wire = backend.wire
    reporter = KafkaMetricsReporter(wire)
    reporter.report([
        CruiseControlMetric(RawMetricType.BROKER_CPU_UTIL, 500, 0, 42.0),
        CruiseControlMetric(RawMetricType.PARTITION_BYTES_IN, 500, 0, 100.0,
                            partition=0),
        CruiseControlMetric(RawMetricType.PARTITION_BYTES_OUT, 500, 0, 50.0,
                            partition=0),
        CruiseControlMetric(RawMetricType.PARTITION_SIZE, 500, 0, 900.0,
                            partition=0),
    ])
    assert wire.logs["__CruiseControlMetrics"]
    sampler = KafkaMetricsReporterSampler(wire)
    psamples, bsamples = sampler.get_samples(0, 1000)
    assert len(psamples) == 1 and psamples[0].partition == 0
    assert len(bsamples) == 1 and bsamples[0].broker_id == 0
    # offset-tracked: a second poll returns nothing new
    p2, b2 = sampler.get_samples(1000, 2000)
    assert not p2 and not b2


def test_kafka_sample_store_replay():
    backend = make_backend("kafka")
    store = KafkaSampleStore(backend.wire)
    from cruise_control_tpu.monitor.sampling import (
        BrokerMetricSample,
        PartitionMetricSample,
    )

    ps = [PartitionMetricSample(3, 500, (1.0, 2.0, 3.0, 4.0))]
    bs = [BrokerMetricSample(1, 500, (9.0, 8.0, 7.0, 6.0))]
    store.store_samples(ps, bs)
    # a fresh store instance (fresh process) replays everything
    p2, b2 = KafkaSampleStore(backend.wire).load_samples()
    assert p2 == ps and b2 == bs


def test_kafka_metadata_topology():
    backend = make_backend("kafka")
    topo = KafkaMetadataClient(backend).refresh()
    assert topo.num_partitions == 6
    assert set(topo.broker_rack) == {0, 1, 2, 3}
    assert topo.partition_topic[0] == TOPIC
    assert topo.alive_brokers == {0, 1, 2, 3}


def test_end_to_end_rebalance_over_fake_kafka():
    """Full slice on the Kafka stack: wire metrics feed the monitor, the
    TPU engine plans, the executor lands the plan back on the wire."""
    from cruise_control_tpu.executor.executor import Executor, ExecutorConfig
    from cruise_control_tpu.facade import CruiseControl
    from cruise_control_tpu.monitor.capacity import StaticCapacityResolver
    from cruise_control_tpu.common.resources import Resource
    from cruise_control_tpu.monitor.load_monitor import LoadMonitor

    rng = np.random.default_rng(7)
    P, B = 40, 6
    wire = FakeKafkaWire(
        assignment={
            (TOPIC, p): [p % B, (p + 1) % B] for p in range(P)
        },
        broker_racks={b: f"rack_{b % 3}" for b in range(B)},
    )
    backend = KafkaClusterBackend(wire)
    reporter = KafkaMetricsReporter(wire)
    # skewed workload: brokers 0/1 lead the hot partitions
    WINDOW = 3_600_000
    for w in range(3):
        records = []
        t = w * WINDOW + 500
        for p in range(P):
            rate = 300.0 if p % B in (0, 1) else 20.0
            records += [
                CruiseControlMetric(RawMetricType.PARTITION_BYTES_IN, t,
                                    p % B, rate, partition=p),
                CruiseControlMetric(RawMetricType.PARTITION_BYTES_OUT, t,
                                    p % B, rate / 2, partition=p),
                CruiseControlMetric(RawMetricType.PARTITION_SIZE, t, p % B,
                                    rate * 3, partition=p),
            ]
        for b in range(B):
            records.append(CruiseControlMetric(
                RawMetricType.BROKER_CPU_UTIL, t, b, 30.0))
        reporter.report(records)
    monitor = LoadMonitor(
        KafkaMetadataClient(backend),
        KafkaMetricsReporterSampler(wire),
        capacity_resolver=StaticCapacityResolver({
            Resource.CPU: 1e3, Resource.NW_IN: 1e4, Resource.NW_OUT: 1e4,
            Resource.DISK: 1e6,
        }),
        window_ms=WINDOW, num_windows=5,
    )
    for w in range(3):
        monitor.run_sampling_iteration((w + 1) * WINDOW)
    cc = CruiseControl(monitor, Executor(backend, ExecutorConfig()),
                       engine="tpu")
    result = cc.rebalance(dryrun=False)
    assert result.execution is not None and result.execution.succeeded
    # the plan landed on the WIRE: placement differs from the original
    moved = sum(
        1 for p in range(P)
        if sorted(backend.partition_state(p).replicas) != sorted(
            [p % B, (p + 1) % B])
    )
    assert moved > 0
    assert backend.ongoing_reassignments() == set()


def test_build_app_boots_on_kafka_stack(tmp_path):
    """bootstrap.servers / an injected wire switches the WHOLE server onto
    the Kafka stack: metadata, sampler, and sample store come from the
    wire, and a REST-path rebalance lands its plan back on the wire."""
    import json
    import urllib.request

    from cruise_control_tpu.bootstrap import build_app
    from cruise_control_tpu.config.cruise_control_config import (
        ConfigException,
        CruiseControlConfig,
    )

    P, B = 24, 4
    wire = FakeKafkaWire(
        assignment={("t0", p): [p % B, (p + 1) % B] for p in range(P)},
        broker_racks={b: f"rack_{b % 2}" for b in range(B)},
    )
    cap_file = tmp_path / "capacity.json"
    cap_file.write_text(json.dumps({
        "brokerCapacities": [{
            "brokerId": "-1", "capacity": {
                "CPU": "1000", "DISK": "100000",
                "NW_IN": "100000", "NW_OUT": "100000"},
        }],
    }))
    # capacity file is mandatory on Kafka
    with pytest.raises(ConfigException, match="capacity.config.file"):
        build_app(CruiseControlConfig({}), port=0, kafka_wire=wire)

    cfg = CruiseControlConfig({
        "capacity.config.file": str(cap_file),
        "use.tpu.optimizer": "false",
    })
    app = build_app(cfg, port=0, kafka_wire=wire)
    try:
        assert app.reporter is None            # real brokers report
        assert isinstance(app.backend, KafkaClusterBackend)
        # broker-side reporter twin feeds the wire topic; monitor samples it
        reporter = KafkaMetricsReporter(wire)
        records = []
        for p in range(P):
            records += [
                CruiseControlMetric(RawMetricType.PARTITION_BYTES_IN, 500,
                                    p % B, 200.0 if p % B == 0 else 20.0,
                                    partition=p),
                CruiseControlMetric(RawMetricType.PARTITION_BYTES_OUT, 500,
                                    p % B, 50.0, partition=p),
                CruiseControlMetric(RawMetricType.PARTITION_SIZE, 500,
                                    p % B, 500.0, partition=p),
            ]
        reporter.report(records)
        app.cruise_control.load_monitor.run_sampling_iteration(3_600_000)
        app.server.start()
        req = urllib.request.Request(
            app.server.url + "/rebalance?dryrun=false", method="POST")
        tid = urllib.request.urlopen(req).headers["User-Task-ID"]
        import time as _t
        for _ in range(120):
            body = json.loads(urllib.request.urlopen(
                app.server.url + "/user_tasks").read())
            mine = [t for t in body["userTasks"]
                    if t["UserTaskId"] == tid]
            if mine and mine[0]["Status"] != "Active":
                break
            _t.sleep(0.25)
        assert mine and mine[0]["Status"] == "Completed", mine
        # the plan LANDED ON THE WIRE
        moved = sum(
            1 for p in range(P)
            if sorted(app.backend.partition_state(p).replicas)
            != sorted([p % B, (p + 1) % B])
        )
        assert moved > 0
        # samples persisted to the wire-backed store topics
        assert wire.logs.get(
            "__KafkaCruiseControlPartitionMetricSamples")
    finally:
        app.shutdown()


def test_build_app_kafka_mode_multi_fetcher(tmp_path):
    """num.metric.fetchers > 1 on the Kafka stack builds one reporter-topic
    consumer PER FETCHER (advisor round-2 medium finding): each needs its
    own offset cursor, and none may be the simulated-topic sampler (which
    would dereference a None topic on every iteration)."""
    import json

    from cruise_control_tpu.bootstrap import build_app
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )

    P, B = 12, 3
    wire = FakeKafkaWire(
        assignment={("t0", p): [p % B, (p + 1) % B] for p in range(P)},
    )
    cap_file = tmp_path / "capacity.json"
    cap_file.write_text(json.dumps({
        "brokerCapacities": [{
            "brokerId": "-1", "capacity": {
                "CPU": "1000", "DISK": "100000",
                "NW_IN": "100000", "NW_OUT": "100000"},
        }],
    }))
    cfg = CruiseControlConfig({
        "capacity.config.file": str(cap_file),
        "use.tpu.optimizer": "false",
        "num.metric.fetchers": "3",
    })
    app = build_app(cfg, port=0, kafka_wire=wire)
    try:
        samplers = [f.sampler for f in app.fetcher_manager.fetchers]
        assert len(samplers) == 3
        assert all(
            isinstance(s, KafkaMetricsReporterSampler) for s in samplers
        )
        assert len({id(s) for s in samplers}) == 3  # distinct cursors
        # a full multi-fetcher sampling pass ingests wire-topic records
        reporter = KafkaMetricsReporter(wire)
        reporter.report([
            CruiseControlMetric(RawMetricType.PARTITION_BYTES_IN, 500,
                                p % B, 10.0, partition=p)
            for p in range(P)
        ] + [
            CruiseControlMetric(RawMetricType.PARTITION_BYTES_OUT, 500,
                                p % B, 5.0, partition=p)
            for p in range(P)
        ] + [
            CruiseControlMetric(RawMetricType.PARTITION_SIZE, 500,
                                p % B, 50.0, partition=p)
            for p in range(P)
        ])
        assert app.fetcher_manager.fetch_once(3_600_000) > 0
    finally:
        app.shutdown()


def test_kafka_sample_store_parallel_replay():
    """num.sample.loading.threads > 1 replays the two store topics on
    concurrent consumers and returns the same samples as serial replay."""
    wire = FakeKafkaWire(assignment={("t0", 0): [0, 1]})
    serial = KafkaSampleStore(wire, loading_threads=1)
    parallel = KafkaSampleStore(wire, loading_threads=4)
    from cruise_control_tpu.monitor.sampling import (
        BrokerMetricSample,
        PartitionMetricSample,
    )

    serial.store_samples(
        [PartitionMetricSample(p, 100 * p, (1.0, 2.0, 3.0, 4.0))
         for p in range(8)],
        [BrokerMetricSample(b, 50 * b, (1.0,) * 4) for b in range(3)],
    )
    assert parallel.load_samples() == serial.load_samples()
