"""SLO observatory (ISSUE 11): the declarative SLO engine (hysteresis,
window eviction, duty-cycle math over a scripted journal), per-executable
device-cost capture, and end-to-end trace correlation — including THE
acceptance test: one rebalance driven through the real HTTP server and
reconstructed from its trace id alone as valid Chrome-trace JSON."""

import json
import time
import urllib.error
import urllib.request

import pytest

from cruise_control_tpu.telemetry import device_cost, device_stats, events
from cruise_control_tpu.telemetry import trace as trace_mod
from cruise_control_tpu.telemetry import tracing
from cruise_control_tpu.telemetry.events import EventJournal
from cruise_control_tpu.telemetry.slo import (
    SloEngine,
    evaluate_slos,
    heal_latencies_ms,
    parse_objectives,
)
from cruise_control_tpu.telemetry.trace import TraceStore, chrome_trace
from cruise_control_tpu.utils.metrics import MetricRegistry
from harness import full_stack
from test_artifact_schemas import SCHEMAS, validate


# ---- scripted-journal helpers ---------------------------------------------------
def _fault(ts, virtual_ms, fault="rack_loss"):
    return {"schema": "cc-tpu-events/1", "ts": ts, "kind": "sim.fault",
            "severity": "INFO",
            "payload": {"fault": fault, "virtualMs": virtual_ms}}


def _fix(ts, time_ms, atype="BROKER_FAILURE", started=True):
    return {"schema": "cc-tpu-events/1", "ts": ts,
            "kind": "detector.anomaly", "severity": "INFO",
            "payload": {"anomalyType": atype, "timeMs": time_ms,
                        "fixStarted": started, "action": "FIX"}}


def _replan(ts, mode):
    return {"schema": "cc-tpu-events/1", "ts": ts, "kind": "replan.end",
            "severity": "INFO", "payload": {"mode": mode}}


# ---- heal-latency + duty-cycle math ---------------------------------------------
def test_heal_latency_pairs_faults_with_fixes():
    journal = [
        _fault(1.0, 300_000),
        _fix(2.0, 420_000),                       # 120s after the fault
        _fault(3.0, 600_000, fault="disk_failure"),
        _fix(4.0, 540_000, started=False),        # delayed, no sample
        _fix(5.0, 900_000, atype="DISK_FAILURE"),  # 300s after its fault
    ]
    assert heal_latencies_ms(journal) == [120_000, 300_000]


def test_heal_latency_without_fault_markers_uses_first_detection():
    # live mode: no sim.fault records — the episode starts at the first
    # detection of the type (a cooldown-delayed fix charges its wait)
    journal = [
        _fix(1.0, 100_000, started=False),
        _fix(2.0, 400_000),
    ]
    assert heal_latencies_ms(journal) == [300_000]


def test_duty_cycle_math_on_scripted_journal():
    journal = [_replan(1.0, "cold"), _replan(2.0, "warm"),
               _replan(3.0, "warm"), _replan(4.0, "warm")]
    rep = evaluate_slos(journal, source="scenario", horizon_ms=60_000)
    assert rep.slo("replan.warm.duty.cycle").measured == pytest.approx(0.75)
    assert rep.slo("replan.warm.duty.cycle").ok is True
    # all-cold breaches the objective
    rep = evaluate_slos([_replan(1.0, "cold"), _replan(2.0, "cold")],
                        source="scenario", horizon_ms=60_000)
    assert rep.slo("replan.warm.duty.cycle").ok is False
    # no replans at all: NO_DATA, not a breach
    rep = evaluate_slos([], source="scenario", horizon_ms=60_000)
    assert rep.slo("replan.warm.duty.cycle").state == "NO_DATA"


def test_window_eviction_drops_old_records():
    now = time.time()
    old = [_replan(now - 3600.0, "cold") for _ in range(4)]
    fresh = [_replan(now - 10.0, "warm"), _replan(now - 5.0, "warm")]
    rep = evaluate_slos(old + fresh, window_ms=60_000.0, now=now)
    # only the two in-window warm replans count: duty cycle 1.0, and the
    # journal-growth rate sees 2 events over the 1-minute window
    assert rep.slo("replan.warm.duty.cycle").measured == pytest.approx(1.0)
    assert rep.slo("journal.growth.per.min").measured == pytest.approx(2.0)
    # widen the window: the cold replans return
    rep = evaluate_slos(old + fresh, window_ms=7_200_000.0, now=now)
    assert rep.slo("replan.warm.duty.cycle").measured == pytest.approx(
        2.0 / 6.0)


def test_window_follows_an_injected_virtual_clock():
    """ISSUE 12 satellite: a journal whose ``ts`` is the scenario's
    VIRTUAL clock (the sim's EventJournal clock injection) windows
    correctly against a virtual ``now`` — evaluating 'the last virtual
    hour' of a simulated day must not consult the host clock (which
    would evict everything: virtual ts are decades before wall time)."""
    day = [_replan(hour * 3600.0, "cold" if hour < 12 else "warm")
           for hour in range(24)]
    # at virtual hour 23.5, a 2h window sees only the warm tail
    rep = evaluate_slos(day, window_ms=2 * 3_600_000.0, now=23.5 * 3600.0)
    assert rep.slo("replan.warm.duty.cycle").measured == pytest.approx(1.0)
    assert rep.slo("journal.growth.per.min").measured == pytest.approx(
        2.0 / 120.0)
    # the same journal against the HOST clock would window to nothing —
    # the drift this satellite fixed
    rep = evaluate_slos(day, window_ms=2 * 3_600_000.0, now=time.time())
    assert rep.slo("replan.warm.duty.cycle").state == "NO_DATA"
    # the engine form: hysteresis driven on the virtual clock, the
    # journal growing as virtual time advances (a real soak's shape)
    view = day[:12]  # the cold morning so far
    vnow = [11.5 * 3600.0]
    eng = SloEngine(events_reader=lambda: view, window_ms=3_600_000.0,
                    breach_cycles=1, recover_cycles=1, objectives={},
                    clock=lambda: vnow[0])
    rep = eng.evaluate()
    assert rep.slo("replan.warm.duty.cycle").measured == pytest.approx(0.0)
    assert rep.slo("replan.warm.duty.cycle").state == "BREACHED"
    view = day  # the warm afternoon arrives; the window slides with it
    vnow[0] = 23.5 * 3600.0
    rep = eng.evaluate()
    assert rep.slo("replan.warm.duty.cycle").measured == pytest.approx(1.0)
    assert rep.slo("replan.warm.duty.cycle").state == "OK"


def test_registry_snapshot_feeds_serve_and_5xx_slos():
    reg = MetricRegistry()
    for ms in (5, 7, 9, 120):
        reg.timer("http.GET.proposals").update(ms / 1000.0)
    reg.meter("http.unhandled.error").mark(2)
    rep = evaluate_slos([], snapshot=reg.snapshot(), window_ms=60_000.0)
    assert rep.slo("serve.cached_get.p99.ms").measured == pytest.approx(
        120.0, rel=0.01)
    assert rep.slo("serve.cached_get.p99.ms").ok is False  # > 50ms
    assert rep.slo("http.unhandled.5xx").measured == 2.0
    assert rep.slo("http.unhandled.5xx").ok is False
    assert rep.all_ok() is False


def test_parse_objectives():
    assert parse_objectives(None) == {}
    assert parse_objectives(" serve.cached_get.p99.ms=25, "
                            "replan.warm.duty.cycle=0.8 ") == {
        "serve.cached_get.p99.ms": 25.0,
        "replan.warm.duty.cycle": 0.8,
    }


# ---- hysteresis ------------------------------------------------------------------
def _engine(journal, **kwargs):
    kwargs.setdefault("window_ms", 1e12)
    return SloEngine(events_reader=lambda: journal.recent(), **kwargs)


def test_breach_requires_consecutive_bad_cycles(monkeypatch):
    journal = EventJournal(enabled=True)
    monkeypatch.setattr(events, "JOURNAL", journal)
    eng = _engine(journal, breach_cycles=3, recover_cycles=2,
                  objectives={"replan.warm.duty.cycle": 1.0})
    journal.emit("replan.end", mode="cold")
    eng.evaluate()
    eng.evaluate()
    assert not journal.recent(kind="slo.breach")  # 2 < breach_cycles
    eng.evaluate()
    (breach,) = journal.recent(kind="slo.breach")
    assert breach["payload"]["slo"] == "replan.warm.duty.cycle"
    assert breach["severity"] == "WARNING"
    assert breach["payload"]["consecutive"] == 3
    # still breached: no duplicate event on further bad cycles
    eng.evaluate()
    assert len(journal.recent(kind="slo.breach")) == 1
    state = eng.report()["hysteresis"]["perSlo"]["replan.warm.duty.cycle"]
    assert state["state"] == "BREACHED"
    assert state["breachedSince"] is not None


def test_recover_requires_consecutive_good_cycles(monkeypatch):
    journal = EventJournal(enabled=True)
    monkeypatch.setattr(events, "JOURNAL", journal)
    eng = _engine(journal, breach_cycles=1, recover_cycles=2,
                  objectives={"replan.warm.duty.cycle": 1.0})
    journal.emit("replan.end", mode="cold")
    eng.evaluate()
    assert journal.recent(kind="slo.breach")
    # flip the measurement to passing: warm replans dominate
    for _ in range(9):
        journal.emit("replan.end", mode="warm")
    eng.objectives["replan.warm.duty.cycle"] = 0.5
    eng.evaluate()
    assert not journal.recent(kind="slo.recovered")  # 1 < recover_cycles
    eng.evaluate()
    (rec,) = journal.recent(kind="slo.recovered")
    assert rec["payload"]["slo"] == "replan.warm.duty.cycle"
    state = eng.report()["hysteresis"]["perSlo"]["replan.warm.duty.cycle"]
    assert state["state"] == "OK" and state["breachedSince"] is None


def test_no_data_freezes_hysteresis(monkeypatch):
    journal = EventJournal(enabled=True)
    monkeypatch.setattr(events, "JOURNAL", journal)
    eng = _engine(journal, breach_cycles=2,
                  objectives={"replan.warm.duty.cycle": 1.0})
    journal.emit("replan.end", mode="cold")
    eng.evaluate()                      # bad #1
    journal.reset()                     # journal empty → NO_DATA
    eng.evaluate()
    journal.emit("replan.end", mode="cold")
    eng.evaluate()                      # bad #2 (the NO_DATA didn't reset)
    assert journal.recent(kind="slo.breach")


def test_breach_hook_dumps_flight_recorder(tmp_path, monkeypatch):
    """Satellite: a breach self-captures its diagnostic context via the
    same dump plumbing FIX_FAILED uses."""
    from cruise_control_tpu.telemetry.recorder import FlightRecorder

    journal = EventJournal(enabled=True)
    monkeypatch.setattr(events, "JOURNAL", journal)
    recorder = FlightRecorder(MetricRegistry(), dump_dir=str(tmp_path),
                              events_source=lambda: journal.recent())
    pumped = []
    eng = _engine(
        journal, breach_cycles=1,
        objectives={"replan.warm.duty.cycle": 1.0},
        on_breach=[lambda name, row: recorder.dump(f"slo.breach:{name}")],
        maintenance_hooks=[lambda: pumped.append(1)],
    )
    journal.emit("replan.end", mode="cold")
    eng.evaluate()
    dumps = list(tmp_path.glob("flight-recorder-*.json"))
    assert len(dumps) == 1
    art = json.loads(dumps[0].read_text())
    assert art["dumpReason"] == "slo.breach:replan.warm.duty.cycle"
    validate(art, SCHEMAS["cc-tpu-flight-recorder/1"])
    # the breach event itself reached the journal the artifact merged
    assert any(e["kind"] == "slo.breach" for e in art["journal"])
    assert pumped  # maintenance hooks ran on the evaluation tick


# ---- device-cost capture ---------------------------------------------------------
def test_device_cost_capture_and_hbm_estimate():
    import jax
    import jax.numpy as jnp

    mon = device_cost.DeviceCostMonitor(enabled=True, hbm_gbps=1.0)
    stats_mon = device_stats.DeviceStatsMonitor(enabled=True)
    fn = stats_mon.instrument("test.cost_fn", jax.jit(
        lambda x: (x @ x).sum()))
    # route the wrapper's hooks at our private monitor
    real = device_cost.MONITOR
    device_cost.MONITOR = mon
    try:
        x = jnp.ones((64, 64))
        fn(x)
        fn(x)
    finally:
        device_cost.MONITOR = real
    assert mon.pending() == 1
    assert mon.capture_pending(max_captures=4) == 1
    assert mon.pending() == 0
    summary = mon.summary()
    entry = summary["functions"]["test.cost_fn"]
    assert entry["flops"] > 0
    assert entry["bytesAccessed"] > 0
    assert entry["argBytes"] >= 64 * 64 * 4
    assert entry["calls"] == 2
    # 2 calls within the window at bandwidth 1 GB/s → utilization > 0
    assert mon.hbm_utilization() > 0.0
    fams = dict((f[0], f) for f in mon.families())
    assert "cc_device_flops" in fams
    assert "cc_device_hbm_utilization_estimate" in fams
    # a second identical call queues nothing (signature already captured)
    device_cost.MONITOR = mon
    try:
        fn(jnp.ones((64, 64)))
    finally:
        device_cost.MONITOR = real
    assert mon.pending() == 0


def test_device_cost_disabled_is_inert():
    mon = device_cost.DeviceCostMonitor(enabled=False)
    mon.note_call("x")
    mon.note_compile("x", None, ("sig",), (), {})
    assert mon.pending() == 0
    assert mon.capture_pending() == 0
    assert mon.summary()["functions"] == {}


# ---- trace store + exporter ------------------------------------------------------
def test_trace_scope_stamps_spans_and_events(monkeypatch):
    journal = EventJournal(enabled=True)
    monkeypatch.setattr(events, "JOURNAL", journal)
    tel = tracing.TELEMETRY
    store = TraceStore()
    prev_sink, prev_enabled = tel.root_sink, tel.enabled
    tel.root_sink, tel.enabled = store.on_root, True
    try:
        with trace_mod.trace_scope("t-123"):
            with tel.span("outer"):
                with tel.span("inner"):
                    events.emit("optimize.start", operation="REBALANCE")
        with tel.span("untraced"):
            pass
    finally:
        tel.root_sink, tel.enabled = prev_sink, prev_enabled
    (rec,) = journal.recent()
    assert rec["traceId"] == "t-123"
    (root,) = store.spans("t-123")
    assert root["name"] == "outer" and root["traceId"] == "t-123"
    assert root["children"][0]["name"] == "inner"
    assert store.spans("other") == []
    assert store.index()[0]["numRoots"] == 1


def test_trace_store_evicts_oldest():
    store = TraceStore(max_traces=2)

    class Rec:
        def __init__(self, tid):
            self.trace_id = tid

        def to_json(self):
            return {"name": "r", "startUnix": 1.0, "durationSec": 0.1}

    for tid in ("a", "b", "c"):
        store.on_root(Rec(tid))
    assert [t["traceId"] for t in store.index()] == ["b", "c"]


def test_chrome_trace_export_shape():
    spans = [{
        "name": "http.GET.proposals", "startUnix": 10.0,
        "durationSec": 0.5, "traceId": "t",
        "children": [{"name": "analyzer.scan", "startUnix": 10.1,
                      "durationSec": 0.2, "kind": "device"}],
    }]
    evs = [{"schema": "cc-tpu-events/1", "ts": 10.2, "kind": "replan.end",
            "severity": "INFO", "traceId": "t", "payload": {"mode": "warm"}}]
    art = json.loads(json.dumps(chrome_trace("t", spans, evs)))
    validate(art, SCHEMAS["cc-tpu-trace/1"])
    by_name = {e["name"]: e for e in art["traceEvents"]}
    assert by_name["analyzer.scan"]["cat"] == "device"
    assert by_name["replan.end"]["ph"] == "i"
    assert by_name["http.GET.proposals"]["dur"] == pytest.approx(5e5)
    # events are time-ordered for the viewer
    ts = [e["ts"] for e in art["traceEvents"]]
    assert ts == sorted(ts)


# ---- THE acceptance test: reconstruct a rebalance from one trace id -------------
def _get(url, headers=None):
    req = urllib.request.Request(url, headers=headers or {})
    with urllib.request.urlopen(req, timeout=60) as resp:
        return resp.status, dict(resp.headers), json.loads(resp.read())


def _post(url, headers=None):
    req = urllib.request.Request(url, method="POST", data=b"",
                                 headers=headers or {})
    with urllib.request.urlopen(req, timeout=120) as resp:
        return resp.status, dict(resp.headers), json.loads(resp.read())


def _wait_indexed(server, trace_id, timeout_s=5.0):
    """Bounded poll for a trace id to land in the store: completed roots
    flow tracing.root_sink → TraceStore in the handler's ``finally``,
    AFTER the response bytes flush — an immediate follow-up GET can race
    it on a contended box (same class as test_observability's documented
    bucket race)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        _, _, body = _get(f"{server.url}/trace")
        if any(t["traceId"] == trace_id for t in body["traces"]):
            return
        time.sleep(0.05)
    raise AssertionError(f"trace {trace_id!r} never reached the store")


@pytest.fixture
def traced_server(monkeypatch):
    from cruise_control_tpu.replan import DeltaReplanner
    from cruise_control_tpu.server.http_server import CruiseControlHttpServer

    journal = EventJournal(enabled=True)
    monkeypatch.setattr(events, "JOURNAL", journal)
    cc, backend, reporter = full_stack(engine="tpu",
                                       registry=MetricRegistry())
    cc.replanner = DeltaReplanner(cc.load_monitor)
    store = TraceStore()
    server = CruiseControlHttpServer(cc, port=0, access_log=False,
                                     trace_store=store)
    prev_enabled = tracing.TELEMETRY.enabled
    tracing.TELEMETRY.enabled = True
    server.start()
    try:
        yield server, journal, store
    finally:
        server.stop()
        tracing.TELEMETRY.enabled = prev_enabled
        tracing.TELEMETRY.root_sink = None


def test_rebalance_reconstructs_from_trace_id_alone(traced_server):
    """Acceptance criterion (ISSUE 11): drive one rebalance through the
    real HTTP server under one correlation id — the proposal computation
    routes through the delta replanner, the execution through the real
    executor — then reconstruct it from ``GET /trace?id=`` alone: valid
    Chrome-trace JSON carrying the request spans, the replan phase, at
    least one device-phase slice, and at least one executor batch, all
    sharing the id that is also on the journal records."""
    server, journal, store = traced_server
    tid = "e2e-rebalance-1"
    headers = {"X-Trace-Id": tid}

    status, hdrs, body = _get(f"{server.url}/proposals", headers)
    assert status == 200
    assert hdrs["X-Trace-Id"] == tid  # echoed for client-side correlation
    status, hdrs, body = _post(
        f"{server.url}/rebalance?allow_cached=true&dryrun=false"
        "&get_response_timeout_s=90", headers,
    )
    assert status == 200
    assert body["cached"] is True

    # root spans land post-flush (see _wait_indexed); both requests'
    # roots must be in the store before reconstruction is complete
    deadline = time.monotonic() + 5.0
    while True:
        try:
            status, _, art = _get(f"{server.url}/trace?id={tid}", headers)
        except urllib.error.HTTPError:
            status, art = 404, {}
        have = {e.get("name") for e in art.get("traceEvents", ())}
        if {"http.GET.proposals", "http.POST.rebalance"} <= have:
            break
        if time.monotonic() > deadline:
            raise AssertionError(
                f"trace {tid!r} incomplete after 5s: {sorted(have)[:8]}"
            )
        time.sleep(0.05)
    assert status == 200
    art = json.loads(json.dumps(art))
    validate(art, SCHEMAS["cc-tpu-trace/1"])
    assert art["traceId"] == tid

    slices = [e for e in art["traceEvents"] if e["ph"] == "X"]
    names = {e["name"] for e in slices}
    # the request spans (handler thread) and the async worker's execution
    assert "http.GET.proposals" in names
    assert "http.POST.rebalance" in names
    # the replan phase sits between the request and the engine
    assert "facade.replan" in names
    # ≥1 device-phase slice from the TPU engine's device spans
    assert [e for e in slices if e["cat"] == "device"]
    # ≥1 executor batch from the execution drive loop
    assert "executor.batch" in names

    # the journal records the same correlation id end to end
    instants = {e["name"] for e in art["traceEvents"] if e["ph"] == "i"}
    assert {"replan.start", "replan.end", "execute.start",
            "execute.end"} <= instants
    traced = [e for e in journal.recent() if e.get("traceId") == tid]
    assert {"replan.end", "executor.batch", "execute.end"} <= {
        e["kind"] for e in traced}
    # and an unknown id is a clean 404, not an empty 200
    try:
        _get(f"{server.url}/trace?id=no-such-trace")
    except urllib.error.HTTPError as e:
        assert e.code == 404
    else:  # pragma: no cover
        raise AssertionError("unknown trace id must 404")


def test_trace_index_and_slo_endpoint(traced_server):
    server, journal, store = traced_server
    _get(f"{server.url}/proposals", {"X-Trace-Id": "idx-1"})
    _wait_indexed(server, "idx-1")  # root spans land post-flush
    status, _, body = _get(f"{server.url}/trace")
    assert status == 200
    assert any(t["traceId"] == "idx-1" for t in body["traces"])
    # no SLO engine attached → a clean 503 naming the config key
    try:
        _get(f"{server.url}/slo")
    except urllib.error.HTTPError as e:
        assert e.code == 503
        assert "telemetry.slo.enabled" in json.loads(e.read())[
            "errorMessage"]
    else:  # pragma: no cover
        raise AssertionError("GET /slo without an engine must 503")


def test_slo_endpoint_serves_gate_table(traced_server, monkeypatch):
    server, journal, store = traced_server
    eng = SloEngine(registry=server.cc.registry,
                    events_reader=lambda: journal.recent(),
                    window_ms=1e12)
    server.slo_engine = eng
    _get(f"{server.url}/proposals", {"X-Trace-Id": "slo-req"})
    status, _, art = _get(f"{server.url}/slo")
    assert status == 200
    validate(json.loads(json.dumps(art)), SCHEMAS["cc-tpu-slo/1"])
    names = {row["name"] for row in art["slos"]}
    assert {"heal.latency.p99.ms", "serve.cached_get.p99.ms",
            "replan.warm.duty.cycle", "http.unhandled.5xx"} <= names
    assert art["hysteresis"]["evaluations"] >= 1
