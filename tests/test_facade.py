"""Facade-layer tests (upstream KafkaCruiseControl operations; SURVEY.md
§2.7): every runnable end-to-end over the simulated cluster."""

import pytest

from cruise_control_tpu.common.resources import BrokerState
from cruise_control_tpu.executor.executor import OngoingExecutionError
from cruise_control_tpu.server.progress import OperationProgress

from harness import full_stack


class TestRebalance:
    def test_dryrun_produces_proposals_without_touching_cluster(self):
        cc, backend, _ = full_stack()
        before = {p: list(st.replicas) for p, st in backend.partitions.items()}
        result = cc.rebalance(dryrun=True)
        assert result.proposals
        assert result.execution is None
        after = {p: list(st.replicas) for p, st in backend.partitions.items()}
        assert before == after

    def test_execute_applies_proposals_to_backend(self):
        cc, backend, _ = full_stack()
        result = cc.rebalance(dryrun=False)
        assert result.execution is not None and result.execution.succeeded
        # the backend now matches the plan's target placement
        for prop in result.proposals:
            st = backend.partitions[prop.partition]
            assert set(st.replicas) == set(prop.new_replicas)
            assert st.leader == prop.new_leader

    def test_improves_leader_balance(self):
        cc, backend, _ = full_stack()
        result = cc.rebalance(dryrun=False)
        leaders = [st.leader for st in backend.partitions.values()]
        # the skewed workload starts with ALL leaders on broker 0
        assert leaders.count(0) < len(leaders)
        assert result.violation_score_after <= result.violation_score_before

    def test_goal_subset_by_name(self):
        cc, _, _ = full_stack()
        result = cc.rebalance(goals=["ReplicaDistributionGoal"], dryrun=True)
        assert set(result.violations_after) == {"ReplicaDistributionGoal"}

    def test_progress_steps_recorded(self):
        cc, _, _ = full_stack()
        progress = OperationProgress("REBALANCE")
        cc.rebalance(dryrun=True, progress=progress)
        steps = [s["step"] for s in progress.to_json()["operationProgress"]]
        assert any("cluster model" in s.lower() for s in steps)
        assert any("optimizing" in s.lower() for s in steps)


class TestBrokerOperations:
    def test_add_brokers_moves_load_onto_new_broker(self):
        cc, backend, _ = full_stack(extra_brokers=(9,))
        result = cc.add_brokers([9], dryrun=False)
        assert result.execution.succeeded
        on_new = [
            p for p, st in backend.partitions.items() if 9 in st.replicas
        ]
        assert on_new, "no replicas moved onto the added broker"

    def test_remove_brokers_evacuates(self):
        cc, backend, _ = full_stack()
        result = cc.remove_brokers([3], dryrun=False)
        assert result.execution.succeeded
        for p, st in backend.partitions.items():
            assert 3 not in st.replicas, f"partition {p} still on broker 3"

    def test_demote_brokers_moves_leadership_only(self):
        cc, backend, _ = full_stack()
        before = {p: list(st.replicas) for p, st in backend.partitions.items()}
        result = cc.demote_brokers([0], dryrun=False)
        assert result.execution.succeeded
        for p, st in backend.partitions.items():
            assert st.leader != 0
            assert set(st.replicas) == set(before[p]), "replicas moved"

    def test_unknown_broker_raises(self):
        cc, _, _ = full_stack()
        with pytest.raises(ValueError, match="unknown broker"):
            cc.add_brokers([99], dryrun=True)


class TestFixOfflineReplicas:
    def test_evacuates_dead_broker(self):
        cc, backend, _ = full_stack(failed_brokers={2})
        result = cc.fix_offline_replicas(dryrun=False)
        assert result.execution is not None
        for p, st in backend.partitions.items():
            assert 2 not in st.replicas, f"partition {p} still on dead broker"


class TestProposalsCache:
    def test_cache_hit_and_invalidation(self):
        cc, _, _ = full_stack()
        r1 = cc.get_proposals()
        r2 = cc.get_proposals()
        assert r2 is r1  # served from cache
        cc.invalidate_proposal_cache()
        r3 = cc.get_proposals()
        assert r3 is not r1

    def test_ignore_cache_recomputes(self):
        cc, _, _ = full_stack()
        r1 = cc.get_proposals()
        r2 = cc.get_proposals(ignore_cache=True)
        assert r2 is not r1


class TestStateAggregate:
    def test_state_covers_all_subsystems(self):
        cc, _, _ = full_stack()
        st = cc.state()
        assert st["MonitorState"]["state"] == "RUNNING"
        assert st["ExecutorState"]["state"] == "NO_TASK_IN_PROGRESS"
        assert st["AnalyzerState"]["readyGoals"]

    def test_sampling_pause_resume_via_facade(self):
        cc, _, _ = full_stack()
        cc.pause_sampling()
        assert cc.state()["MonitorState"]["state"] == "PAUSED"
        cc.resume_sampling()
        assert cc.state()["MonitorState"]["state"] == "RUNNING"


class TestIdTranslation:
    def test_goal_subset_with_tpu_engine_falls_back_to_greedy(self):
        cc, backend, _ = full_stack(engine="tpu")
        before = {p: list(st.replicas) for p, st in backend.partitions.items()}
        result = cc.demote_brokers([0], dryrun=False, engine="tpu")
        assert result.engine == "greedy"  # subset ops pin greedy semantics
        for p, st in backend.partitions.items():
            assert st.leader != 0
            assert set(st.replicas) == set(before[p]), "replicas moved"

    def test_execution_invalidates_proposal_cache(self):
        cc, _, _ = full_stack()
        r1 = cc.get_proposals()
        cc.rebalance(dryrun=False)
        r2 = cc.get_proposals()
        assert r2 is not r1, "stale pre-execution proposals served from cache"

    def test_sparse_partition_ids_translate(self):
        import numpy as np
        from cruise_control_tpu.executor.backend import SimulatedClusterBackend
        from cruise_control_tpu.executor.executor import Executor
        from cruise_control_tpu.facade import CruiseControl
        from cruise_control_tpu.monitor.load_monitor import (
            BackendMetadataClient, LoadMonitor,
        )
        from cruise_control_tpu.monitor.sampling import (
            MetricsReporterSampler, MetricsTopic, SimulatedMetricsReporter,
            WorkloadModel,
        )

        # sparse partition keys (a deletion left holes) + sparse broker ids
        pids = [0, 2, 5, 9, 12, 17]
        brokers = [100, 101, 102]
        assignment = {p: [100, 101 + i % 2] for i, p in enumerate(pids)}
        leaders = {p: 100 for p in pids}
        n = max(pids) + 1
        rng = np.random.default_rng(5)
        w = WorkloadModel(
            bytes_in=rng.uniform(100, 1000, n),
            bytes_out=rng.uniform(100, 2000, n),
            size_mb=rng.uniform(10, 500, n),
            assignment=assignment, leaders=leaders,
        )
        backend = SimulatedClusterBackend(
            {p: list(r) for p, r in assignment.items()}, dict(leaders),
            brokers=set(brokers),
        )
        topic = MetricsTopic()
        rep = SimulatedMetricsReporter(w, topic)
        monitor = LoadMonitor(
            BackendMetadataClient(backend, {b: b % 2 for b in brokers}),
            MetricsReporterSampler(topic), window_ms=1000, num_windows=5,
        )
        for i in range(3):
            rep.report(time_ms=i * 1000 + 500)
            monitor.run_sampling_iteration((i + 1) * 1000)
        cc = CruiseControl(monitor, Executor(backend))
        result = cc.rebalance(dryrun=False)
        assert result.execution.succeeded
        # every executed proposal addressed a real external partition/broker
        leaders_now = [st.leader for st in backend.partitions.values()]
        assert leaders_now.count(100) < len(pids)
        for st in backend.partitions.values():
            assert set(st.replicas) <= set(brokers)

    def test_duplicate_external_ids_rejected(self):
        from cruise_control_tpu.models.builder import ClusterModelBuilder

        b = ClusterModelBuilder()
        b.add_broker(0, [1.0, 1.0, 1.0, 1.0], broker_id=7)
        b.add_broker(0, [1.0, 1.0, 1.0, 1.0], broker_id=7)
        with pytest.raises(ValueError, match="duplicate external broker"):
            b.build()


class TestSanityChecks:
    def test_ongoing_execution_blocks_new_operation(self):
        cc, _, _ = full_stack()
        from cruise_control_tpu.executor.executor import ExecutorStateValue

        cc.executor.state = (
            ExecutorStateValue.INTER_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS
        )
        with pytest.raises(OngoingExecutionError):
            cc.rebalance(dryrun=False)
        # dryrun is still allowed during an execution
        result = cc.rebalance(dryrun=True)
        assert result is not None


class TestProposalPrecompute:
    def test_background_precompute_fills_cache(self):
        import time as _t

        cc, backend, _ = full_stack()
        assert cc._cached_proposals is None
        pre = cc.start_proposal_precomputation(interval_s=0.01)
        deadline = _t.time() + 5.0
        while pre.runs == 0 and _t.time() < deadline:
            _t.sleep(0.02)
        cc.stop_proposal_precomputation()
        assert pre.runs > 0
        assert cc._cached_proposals is not None
        # GET /proposals is now a cache hit
        r = cc.get_proposals()
        assert r is cc._cached_proposals
        st = cc.state()["AnalyzerState"]
        assert st["isProposalReady"]

    def test_refresh_once_records_errors(self):
        cc, backend, _ = full_stack()
        from cruise_control_tpu.analyzer.precompute import (
            ProposalPrecomputingExecutor,
        )

        class Boom:
            def get_proposals(self, **kw):
                raise RuntimeError("model not ready")

        pre = ProposalPrecomputingExecutor(Boom(), interval_s=999)
        assert pre.refresh_once() is False
        assert pre.errors == 1 and "model not ready" in pre.last_error


def test_rf_increase_respects_capacity_goals():
    """VERDICT round-1 item #9's done-bar: an RF-increase that would
    overflow a broker picks a different destination via the goal chain
    (upstream TopicConfigurationRunnable routes through the optimizer)."""
    import contextlib

    from cruise_control_tpu.analyzer.goals.base import BalancingConstraint
    from cruise_control_tpu.common.resources import Resource
    from cruise_control_tpu.executor.backend import SimulatedClusterBackend
    from cruise_control_tpu.executor.executor import Executor
    from cruise_control_tpu.facade import CruiseControl
    from cruise_control_tpu.models.builder import ClusterModelBuilder

    b = ClusterModelBuilder()
    cap = {Resource.CPU: 1e4, Resource.NW_IN: 1e6, Resource.NW_OUT: 1e6,
           Resource.DISK: 100.0}
    b.add_broker("r0", cap)   # hosts X
    b.add_broker("r1", cap)   # nearly full: naive count-based pick
    b.add_broker("r2", cap)   # roomy but higher replica count
    tiny = {Resource.CPU: 1.0, Resource.NW_IN: 1.0, Resource.NW_OUT: 1.0,
            Resource.DISK: 5.0}
    b.add_partition("X", [0], {Resource.CPU: 1.0, Resource.NW_IN: 1.0,
                               Resource.NW_OUT: 1.0, Resource.DISK: 10.0})
    b.add_partition("BIG", [1], {Resource.CPU: 1.0, Resource.NW_IN: 1.0,
                                 Resource.NW_OUT: 1.0, Resource.DISK: 75.0})
    b.add_partition("S1", [2], tiny)
    b.add_partition("S2", [2], tiny)
    state = b.build()

    class StubMonitor:
        metadata = object()

        def acquire_for_model_generation(self):
            return contextlib.nullcontext()

        def cluster_model(self, requirements=None):
            return state

    backend = SimulatedClusterBackend(
        {0: [0], 1: [1], 2: [2], 3: [2]}, {0: 0, 1: 1, 2: 2, 3: 2},
        brokers={0, 1, 2},
    )
    cc = CruiseControl(StubMonitor(), Executor(backend),
                       constraint=BalancingConstraint())
    result = cc.fix_topic_replication_factor(2, dryrun=True, topic_regex="X")
    by_p = {pr.partition: pr for pr in result.proposals}
    assert 0 in by_p, result.proposals
    # broker 1 would breach disk capacity (75 + 10 > 80): the goal chain
    # must place X's new replica on broker 2 despite its higher count
    assert set(by_p[0].new_replicas) == {0, 2}
    assert set(by_p[0].old_replicas) == {0}


def test_rf_decrease_emits_removal_proposals():
    """RF decreases must produce executable removal proposals (code-review
    regression: pre-applied removals were silently dropped)."""
    cc, backend, _ = full_stack(rf=2)
    result = cc.fix_topic_replication_factor(1, dryrun=False)
    assert result.proposals, "no removal proposals emitted"
    assert result.execution is not None and result.execution.succeeded
    for p, st in backend.partitions.items():
        assert len(set(st.replicas)) == 1, (p, st)


def test_rf_decrease_keeps_data_hosting_rack_diverse_replicas():
    """Code-review regression: the keep-selection must update its rack set
    live — duplicate-rack followers are dropped before rack-distinct ones,
    so an RF decrease never forces a data copy to a fresh broker."""
    import contextlib

    from cruise_control_tpu.common.resources import Resource
    from cruise_control_tpu.executor.backend import SimulatedClusterBackend
    from cruise_control_tpu.executor.executor import Executor
    from cruise_control_tpu.facade import CruiseControl
    from cruise_control_tpu.models.builder import ClusterModelBuilder

    b = ClusterModelBuilder()
    cap = {Resource.CPU: 1e4, Resource.NW_IN: 1e6, Resource.NW_OUT: 1e6,
           Resource.DISK: 1e4}
    for r in ("r0", "r1", "r1", "r2", "r2"):
        b.add_broker(r, cap)
    # leader on r0; followers r1, r1, r2 — RF 4 -> 3 must drop one of the
    # r1 twins and KEEP broker 3 (r2), never re-copy onto broker 4
    b.add_partition("T", [0, 1, 2, 3], {Resource.DISK: 10.0})
    state = b.build()

    class StubMonitor:
        metadata = object()

        def acquire_for_model_generation(self):
            return contextlib.nullcontext()

        def cluster_model(self, requirements=None):
            return state

    backend = SimulatedClusterBackend({0: [0, 1, 2, 3]}, {0: 0})
    cc = CruiseControl(StubMonitor(), Executor(backend))
    result = cc.fix_topic_replication_factor(3, dryrun=True)
    (pr,) = result.proposals
    assert set(pr.old_replicas) == {0, 1, 2, 3}
    kept = set(pr.new_replicas)
    assert 0 in kept and 3 in kept          # leader + the rack-distinct r2
    assert len(kept & {1, 2}) == 1          # exactly one r1 twin dropped
    assert 4 not in kept                    # no data copy to a fresh broker


def test_shared_constraint_not_mutated_by_facade():
    """Advisor round-2: CruiseControl.__init__ must not strip the caller's
    name-keyed broker-set entries from a shared BalancingConstraint — it
    works on a copy."""
    from cruise_control_tpu.analyzer.goals.base import BalancingConstraint
    from cruise_control_tpu.executor.backend import SimulatedClusterBackend
    from cruise_control_tpu.executor.executor import Executor
    from cruise_control_tpu.facade import CruiseControl

    original = {"by-name": {0, 1}, 7: {2}}
    shared = BalancingConstraint(broker_sets=dict(original))
    backend = SimulatedClusterBackend({0: [0]}, {0: 0}, brokers={0, 1, 2})
    cc = CruiseControl(object(), Executor(backend), constraint=shared)
    assert shared.broker_sets == original
    assert cc.constraint is not shared
    assert cc.constraint.broker_sets == {7: {2}}


def test_rf_change_topic_regex_never_widens_silently():
    """Advisor round-2: a topic_regex matching no topic raises instead of
    silently applying the RF change to every topic."""
    cc, _, _ = full_stack()
    with pytest.raises(ValueError, match="matches no topic"):
        cc.fix_topic_replication_factor(2, dryrun=True,
                                        topic_regex="no-such-topic")
