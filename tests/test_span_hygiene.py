"""Span- and event-hygiene static checks.

docs/OBSERVABILITY.md states the rules: span names must be static — any
f-string name construction (positional name or ``sub=``) at a
``span()``/``device_span()`` call site must be guarded by
``tracing.enabled()``, so the disabled path never pays for string
formatting on a hot path.  The same discipline applies to event *kinds*
at ``events.emit()`` call sites: a dynamic kind mints unbounded journal
vocabulary (label-cardinality explosion in every ``kind=``-filtered
consumer), so an f-string kind must sit behind an ``enabled()`` guard —
and in practice should simply be a static dotted string with the dynamic
part in the payload.  This test scans every module in
``cruise_control_tpu/`` with the ast so a violation fails CI with the
offending file:line."""

import ast
import pathlib

PKG = pathlib.Path(__file__).resolve().parent.parent / "cruise_control_tpu"

SPAN_FUNCS = {"span", "device_span"}
EVENT_FUNCS = {"emit"}


def _is_enabled_call(node: ast.AST) -> bool:
    """True for any `...enabled()` call (tracing.enabled / tel.enabled /
    the bare-name import form)."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    name = f.attr if isinstance(f, ast.Attribute) else getattr(f, "id", None)
    return name == "enabled"


def _guard_tests(ancestors):
    """Yield the test expressions of every conditional construct whose
    TAKEN branch leads to the call: `if` statements (body branch only —
    an else branch is the path tracing is OFF), ternaries, and
    `cond and expr` short-circuits."""
    for parent, child in zip(ancestors, ancestors[1:] + [None]):
        if isinstance(parent, ast.If) and child in parent.body:
            yield parent.test
        elif isinstance(parent, ast.IfExp) and child is parent.body:
            yield parent.test
        elif isinstance(parent, ast.BoolOp) and isinstance(parent.op,
                                                           ast.And):
            idx = parent.values.index(child) if child in parent.values else 0
            for v in parent.values[:idx]:
                yield v


def _find_unguarded_dynamic_calls(tree: ast.AST, func_names):
    """(lineno, func_name) for every call to one of ``func_names`` that
    builds an f-string argument without an enclosing enabled() guard."""
    parents = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    offenders = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = (f.attr if isinstance(f, ast.Attribute)
                else getattr(f, "id", None))
        if name not in func_names:
            continue
        dynamic = any(
            isinstance(a, ast.JoinedStr) for a in node.args
        ) or any(
            isinstance(kw.value, ast.JoinedStr) for kw in node.keywords
        )
        if not dynamic:
            continue
        chain = [node]
        cur = node
        while cur in parents:
            cur = parents[cur]
            chain.append(cur)
        chain.reverse()  # outermost first
        guarded = any(
            any(_is_enabled_call(n) for n in ast.walk(test))
            for test in _guard_tests(chain)
        )
        if not guarded:
            offenders.append((node.lineno, name))
    return offenders


def find_unguarded_dynamic_spans(tree: ast.AST):
    """(lineno, source_hint) for every span()/device_span() call that
    builds an f-string name without an enclosing enabled() guard."""
    return _find_unguarded_dynamic_calls(tree, SPAN_FUNCS)


def find_unguarded_dynamic_event_kinds(tree: ast.AST):
    """(lineno, source_hint) for every emit() call that builds an
    f-string argument (kind or payload value) without an enabled() guard.

    Scope note: payload f-strings are flagged too — on the disabled path
    emit()'s arguments are still evaluated, so the formatting cost rule is
    the same as for span names; put dynamic values in the payload as raw
    kwargs, not pre-formatted strings."""
    return _find_unguarded_dynamic_calls(tree, EVENT_FUNCS)


def test_no_unguarded_fstring_span_names_in_package():
    violations = []
    for path in sorted(PKG.rglob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        for lineno, fn in find_unguarded_dynamic_spans(tree):
            violations.append(f"{path.relative_to(PKG.parent)}:{lineno} "
                              f"({fn} with f-string name)")
    assert not violations, (
        "f-string span names must be guarded by tracing.enabled() "
        "(docs/OBSERVABILITY.md) — pass static names and route dynamic "
        "parts through sub= inside a guard:\n" + "\n".join(violations)
    )


def test_no_unguarded_fstring_event_kinds_in_package():
    violations = []
    for path in sorted(PKG.rglob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        for lineno, fn in find_unguarded_dynamic_event_kinds(tree):
            violations.append(f"{path.relative_to(PKG.parent)}:{lineno} "
                              f"({fn} with f-string argument)")
    assert not violations, (
        "event kinds must be static dotted strings (journal cardinality "
        "stays bounded; docs/OBSERVABILITY.md) — put dynamic values in "
        "the payload as raw kwargs, inside an events.enabled() guard if "
        "formatting is unavoidable:\n" + "\n".join(violations)
    )


# ---- the checker itself is tested: it must catch what the rule forbids ----------
def test_checker_flags_unguarded_fstring():
    bad = ast.parse(
        "def f(method):\n"
        "    with tracing.span(f'http.{method}'):\n"
        "        pass\n"
    )
    assert find_unguarded_dynamic_spans(bad) == [(2, "span")]
    bad_sub = ast.parse(
        "def f(method):\n"
        "    s = tracing.span('http', sub=f'{method}.x')\n"
    )
    assert find_unguarded_dynamic_spans(bad_sub) == [(2, "span")]


def test_checker_accepts_guarded_forms():
    guarded_if = ast.parse(
        "def f(method):\n"
        "    if tracing.enabled():\n"
        "        s = tracing.span('http', sub=f'{method}')\n"
        "    else:\n"
        "        s = tracing.NOOP\n"
    )
    assert find_unguarded_dynamic_spans(guarded_if) == []
    guarded_ternary = ast.parse(
        "def f(m):\n"
        "    s = tracing.span(f'h.{m}') if tracing.enabled() else NOOP\n"
    )
    assert find_unguarded_dynamic_spans(guarded_ternary) == []
    static_name = ast.parse(
        "def f(m):\n"
        "    with tracing.span('analyzer.scan', sub=m):\n"
        "        pass\n"
    )
    assert find_unguarded_dynamic_spans(static_name) == []
    else_branch_is_not_guarded = ast.parse(
        "def f(m):\n"
        "    if tracing.enabled():\n"
        "        pass\n"
        "    else:\n"
        "        s = tracing.span(f'h.{m}')\n"
    )
    assert find_unguarded_dynamic_spans(else_branch_is_not_guarded) == [
        (5, "span")
    ]


def test_checker_flags_unguarded_fstring_event_kind():
    bad = ast.parse(
        "def f(op):\n"
        "    events.emit(f'optimize.{op}', operation=op)\n"
    )
    assert find_unguarded_dynamic_event_kinds(bad) == [(2, "emit")]
    bad_payload = ast.parse(
        "def f(op):\n"
        "    events.emit('optimize.start', detail=f'op={op}')\n"
    )
    assert find_unguarded_dynamic_event_kinds(bad_payload) == [(2, "emit")]


def test_checker_accepts_static_and_guarded_event_kinds():
    static = ast.parse(
        "def f(op):\n"
        "    events.emit('optimize.start', operation=op)\n"
    )
    assert find_unguarded_dynamic_event_kinds(static) == []
    guarded = ast.parse(
        "def f(op):\n"
        "    if events.enabled():\n"
        "        events.emit('optimize.start', detail=f'op={op}')\n"
    )
    assert find_unguarded_dynamic_event_kinds(guarded) == []
