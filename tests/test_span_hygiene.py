"""Span- and event-hygiene checks — now rule ``obs-dynamic-name`` of the
cclint framework (``cruise_control_tpu/devtools/lint/rules_obs.py``).

docs/OBSERVABILITY.md states the rules: span names must be static — any
f-string name construction (positional name or ``sub=``) at a
``span()``/``device_span()`` call site must be guarded by
``tracing.enabled()``, so the disabled path never pays for string
formatting on a hot path.  The same discipline applies to event *kinds*
at ``events.emit()`` call sites: a dynamic kind mints unbounded journal
vocabulary (label-cardinality explosion in every ``kind=``-filtered
consumer), so an f-string kind must sit behind an ``enabled()`` guard —
and in practice should simply be a static dotted string with the dynamic
part in the payload.

This file started as a one-off AST check and migrated onto the lint
framework (ISSUE 4); the original guarded/unguarded fixture cases stay
here verbatim as the rule's unit tests, and the package-wide scans are
now expressed through the framework driver (which also honors inline
suppressions — a violation fails CI with the offending file:line unless
a reviewed ``# cclint: disable=obs-dynamic-name -- reason`` sits on it).
"""

import ast
import pathlib

from cruise_control_tpu.devtools.lint import run_lint
from cruise_control_tpu.devtools.lint.rules_obs import (
    find_unguarded_dynamic_event_kinds,
    find_unguarded_dynamic_spans,
)

PKG = pathlib.Path(__file__).resolve().parent.parent / "cruise_control_tpu"


def _package_findings():
    result = run_lint(paths=[str(PKG)], rules=["obs-dynamic-name"])
    return result.findings


def test_no_unguarded_fstring_span_names_in_package():
    violations = [
        f.render() for f in _package_findings()
        if "span" in f.message or "device_span" in f.message
    ]
    assert not violations, (
        "f-string span names must be guarded by tracing.enabled() "
        "(docs/OBSERVABILITY.md) — pass static names and route dynamic "
        "parts through sub= inside a guard:\n" + "\n".join(violations)
    )


def test_no_unguarded_fstring_event_kinds_in_package():
    violations = [
        f.render() for f in _package_findings()
        if "emit" in f.message
    ]
    assert not violations, (
        "event kinds must be static dotted strings (journal cardinality "
        "stays bounded; docs/OBSERVABILITY.md) — put dynamic values in "
        "the payload as raw kwargs, inside an events.enabled() guard if "
        "formatting is unavoidable:\n" + "\n".join(violations)
    )


def test_no_dynamic_metric_names_in_package():
    """The framework extension of this file's original scope: registry
    metric names (counter/gauge/timer/histogram/meter) must be static
    too, modulo reviewed suppressions stating the cardinality bound."""
    violations = [
        f.render() for f in _package_findings()
        if "registry." in f.message
    ]
    assert not violations, (
        "metric names must be static, or carry a suppression whose "
        "reason states the bound (docs/STATIC_ANALYSIS.md):\n"
        + "\n".join(violations)
    )


# ---- the checker itself is tested: it must catch what the rule forbids ----------
def test_checker_flags_unguarded_fstring():
    bad = ast.parse(
        "def f(method):\n"
        "    with tracing.span(f'http.{method}'):\n"
        "        pass\n"
    )
    assert find_unguarded_dynamic_spans(bad) == [(2, "span")]
    bad_sub = ast.parse(
        "def f(method):\n"
        "    s = tracing.span('http', sub=f'{method}.x')\n"
    )
    assert find_unguarded_dynamic_spans(bad_sub) == [(2, "span")]


def test_checker_accepts_guarded_forms():
    guarded_if = ast.parse(
        "def f(method):\n"
        "    if tracing.enabled():\n"
        "        s = tracing.span('http', sub=f'{method}')\n"
        "    else:\n"
        "        s = tracing.NOOP\n"
    )
    assert find_unguarded_dynamic_spans(guarded_if) == []
    guarded_ternary = ast.parse(
        "def f(m):\n"
        "    s = tracing.span(f'h.{m}') if tracing.enabled() else NOOP\n"
    )
    assert find_unguarded_dynamic_spans(guarded_ternary) == []
    static_name = ast.parse(
        "def f(m):\n"
        "    with tracing.span('analyzer.scan', sub=m):\n"
        "        pass\n"
    )
    assert find_unguarded_dynamic_spans(static_name) == []
    else_branch_is_not_guarded = ast.parse(
        "def f(m):\n"
        "    if tracing.enabled():\n"
        "        pass\n"
        "    else:\n"
        "        s = tracing.span(f'h.{m}')\n"
    )
    assert find_unguarded_dynamic_spans(else_branch_is_not_guarded) == [
        (5, "span")
    ]


def test_checker_flags_unguarded_fstring_event_kind():
    bad = ast.parse(
        "def f(op):\n"
        "    events.emit(f'optimize.{op}', operation=op)\n"
    )
    assert find_unguarded_dynamic_event_kinds(bad) == [(2, "emit")]
    bad_payload = ast.parse(
        "def f(op):\n"
        "    events.emit('optimize.start', detail=f'op={op}')\n"
    )
    assert find_unguarded_dynamic_event_kinds(bad_payload) == [(2, "emit")]


def test_checker_accepts_static_and_guarded_event_kinds():
    static = ast.parse(
        "def f(op):\n"
        "    events.emit('optimize.start', operation=op)\n"
    )
    assert find_unguarded_dynamic_event_kinds(static) == []
    guarded = ast.parse(
        "def f(op):\n"
        "    if events.enabled():\n"
        "        events.emit('optimize.start', detail=f'op={op}')\n"
    )
    assert find_unguarded_dynamic_event_kinds(guarded) == []
