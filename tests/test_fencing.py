"""Concurrent-controller safety (ISSUE 15): execution fencing,
mid-flight foreign-reassignment reconciliation, per-batch topology
revalidation, and the satellites that ride with them.

The heart is the INTERLEAVING HARNESS
(:func:`test_foreign_alter_at_every_batch_boundary`): a foreign writer
injects a reassignment at EVERY batch boundary of a small plan — the
kill-at-every-checkpoint discipline applied to concurrency — under both
conflict policies, asserting placement convergence with zero
double-applied moves and zero silent-wrong placements.
"""

import contextlib
import os

import pytest

from cruise_control_tpu.analyzer.goal_optimizer import ExecutionProposal
from cruise_control_tpu.detector.detectors import ForeignReassignmentDetector
from cruise_control_tpu.executor.backend import (
    FencedClusterBackend,
    SimulatedClusterBackend,
    StaleControllerEpochError,
)
from cruise_control_tpu.executor.concurrency import ConcurrencyAdjuster
from cruise_control_tpu.executor.executor import Executor, ExecutorConfig
from cruise_control_tpu.executor.journal import ExecutionJournal, ProcessCrash
from cruise_control_tpu.telemetry import events
from cruise_control_tpu.telemetry.events import EventJournal


@contextlib.contextmanager
def capture_events():
    """Swap in a private event journal; yields a callable returning the
    captured records (kind-filterable)."""
    prev = events.JOURNAL
    events.JOURNAL = EventJournal(enabled=True, ring_size=1 << 12)
    try:
        def recs(kind=None):
            out = events.JOURNAL.recent()
            if kind is not None:
                out = [e for e in out if e["kind"] == kind]
            return out
        yield recs
    finally:
        events.JOURNAL.close()
        events.JOURNAL = prev


def _prop(p, old, new):
    return ExecutionProposal(
        partition=p, topic=0, old_leader=old[0], new_leader=new[0],
        old_replicas=tuple(old), new_replicas=tuple(new),
    )


def _fixture(move_latency=2):
    """6 partitions over 4 brokers; the plan moves partitions 0/1/4 onto
    [2, 3] (same shape as the crash-consistency harness)."""
    assignment = {p: [(p + i) % 4 for i in range(2)] for p in range(6)}
    leaders = {p: assignment[p][0] for p in range(6)}
    backend = SimulatedClusterBackend(
        {p: list(r) for p, r in assignment.items()}, dict(leaders),
        move_latency_ticks=move_latency,
    )
    plan = [_prop(p, assignment[p], [2, 3]) for p in (0, 1, 4)]
    return backend, plan


def _placement(backend):
    return {p: list(st.replicas) for p, st in backend.partitions.items()}


def _settle(backend, max_ticks=200):
    for _ in range(max_ticks):
        if not backend.ongoing_reassignments():
            return
        backend.tick()
    raise AssertionError("cluster never settled")


# ---- the fencing epoch ----------------------------------------------------------
def test_sim_backend_epoch_claim_and_verify():
    backend, _ = _fixture()
    assert backend.controller_epoch() == 0
    assert backend.claim_controller_epoch() == 1
    assert backend.claim_controller_epoch(expected=1) == 2
    with pytest.raises(StaleControllerEpochError):
        backend.claim_controller_epoch(expected=1)
    backend.verify_controller_epoch(2)  # current epoch passes
    with pytest.raises(StaleControllerEpochError):
        backend.verify_controller_epoch(1)


def test_kafka_backend_epoch_rides_cluster_config():
    from cruise_control_tpu.kafka.backend import (
        CONTROLLER_EPOCH_KEY,
        KafkaClusterBackend,
    )
    from cruise_control_tpu.kafka.wire import FakeKafkaWire

    wire = FakeKafkaWire(assignment={("t", 0): [0, 1]})
    be = KafkaClusterBackend(wire)
    assert be.controller_epoch() == 0
    assert be.claim_controller_epoch() == 1
    # the epoch is durable cluster-side state, not process memory
    assert wire.describe_configs("broker", "")[CONTROLLER_EPOCH_KEY] == "1"
    be2 = KafkaClusterBackend(wire)  # "another process"
    assert be2.claim_controller_epoch(expected=1) == 2
    with pytest.raises(StaleControllerEpochError):
        be.claim_controller_epoch(expected=1)
    with pytest.raises(StaleControllerEpochError):
        be.verify_controller_epoch(1)


def test_fenced_wrapper_refuses_every_mutating_call():
    backend, _ = _fixture()
    epoch = [1]
    fenced = FencedClusterBackend(backend, lambda: epoch[0])
    backend.claim_controller_epoch()  # cluster at 1: our epoch current
    fenced.alter_partition_reassignments({0: [2, 3]})  # passes
    backend.claim_controller_epoch()  # another controller took over (2)
    with capture_events() as recs:
        for op in (
            lambda: fenced.alter_partition_reassignments({1: [2, 3]}),
            lambda: fenced.elect_leaders({0: 2}),
            lambda: fenced.alter_replica_log_dirs({0: {2: "d1"}}),
            lambda: fenced.cancel_reassignments([0]),
            lambda: fenced.set_throttles(100.0, [0]),
            lambda: fenced.clear_throttles(),
            lambda: fenced.alter_config("broker", 0, {"k": "v"}),
        ):
            with pytest.raises(StaleControllerEpochError):
                op()
        fences = recs("executor.fenced")
    assert len(fences) == 7
    assert {f["payload"]["op"] for f in fences} == {
        "alter_partition_reassignments", "elect_leaders",
        "alter_replica_log_dirs", "cancel_reassignments",
        "set_throttles", "clear_throttles", "alter_config",
    }
    # reads stay open to the fenced-out process (observability must not
    # die with ownership)
    assert fenced.alive_brokers() == backend.alive_brokers()


def test_executor_epoch_claimed_per_execution_and_stamped_on_records(
        tmp_path):
    backend, plan = _fixture()
    path = str(tmp_path / "ckpt.jsonl")
    journal = ExecutionJournal(path)
    ex = Executor(backend, journal=journal)
    ex.execute_proposals(plan)
    assert ex.epoch == 1 == backend.controller_epoch()
    ex.execute_proposals([_prop(0, [2, 3], [0, 1])])
    assert ex.epoch == 2 == backend.controller_epoch()
    assert ex.state_summary()["fencing"]["epoch"] == 2


def test_journal_records_carry_epoch_and_load_surfaces_it(tmp_path):
    import json as _json

    path = str(tmp_path / "ckpt.jsonl")
    j = ExecutionJournal(path)
    j.set_epoch(3)
    j.append("start", executionId=1, strategy="", maxTicks=10,
             proposals=[], sizes={}, config={})
    j.append("batch", taskIds=[0], tick=1)
    j.close()
    with open(path) as f:
        for line in f:
            rec = _json.loads(line.rsplit("#", 1)[0]
                              if "#" in line else line)
            assert rec.get("epoch") == 3 or "epoch" in str(rec)
    ck = ExecutionJournal(path).load()
    assert ck is not None and ck.epoch == 3


# ---- zombie resume refusal ------------------------------------------------------
def test_zombie_resume_is_fenced_and_live_controller_completes(tmp_path):
    # reference placement from an uninterrupted run
    ref_backend, ref_plan = _fixture()
    Executor(ref_backend).execute_proposals(ref_plan)
    reference = _placement(ref_backend)

    backend, plan = _fixture()
    path = str(tmp_path / "ckpt.jsonl")
    journal = ExecutionJournal(path)
    journal.crash_after(4)  # crash mid-flight, moves dispatched
    ex_a = Executor(backend, journal=journal)
    with pytest.raises(ProcessCrash):
        ex_a.execute_proposals(plan)
    # the zombie's stale view: the checkpoint as process A left it
    stale = ExecutionJournal(path).load()
    assert stale is not None and stale.epoch == 1

    # process B recovers and completes (conditional claim: 1 -> 2)
    jb = ExecutionJournal(path)
    ex_b = Executor(backend, journal=jb)
    result = ex_b.resume(jb.load())
    assert result.succeeded and ex_b.epoch == 2

    # process A thaws and re-resumes its stale checkpoint: refused at the
    # CAS, before any mutation
    with capture_events() as recs:
        zombie = Executor(backend, journal=None)
        with pytest.raises(StaleControllerEpochError):
            zombie.resume(stale)
        fenced = recs("executor.fenced")
    assert fenced and fenced[0]["payload"]["op"] == "claim"
    assert fenced[0]["payload"]["presentedEpoch"] == 1
    assert fenced[0]["payload"]["clusterEpoch"] == 2
    assert _placement(backend) == reference, "zombie moved replicas"


def test_zombie_fenced_mid_drive_aborts_without_cluster_writes(tmp_path):
    """A zombie that got PAST startup (claimed long ago, thawed mid-plan)
    is refused at its next batch dispatch — the in-drive fence."""
    backend, plan = _fixture(move_latency=1)
    ex = Executor(backend)
    alters = []
    orig = backend.alter_partition_reassignments

    def spy(reassignments):
        alters.append(dict(reassignments))
        if len(alters) == 1:
            # another controller claims the cluster right after our
            # first batch reaches it
            backend.claim_controller_epoch()
        orig(reassignments)

    backend.alter_partition_reassignments = spy
    cfg = ex.config
    cfg.num_concurrent_partition_movements_per_broker = 1  # many batches
    with capture_events() as recs:
        with pytest.raises(StaleControllerEpochError):
            ex.execute_proposals(plan)
        assert recs("executor.fenced")
    assert not ex.has_ongoing_execution
    # exactly one batch reached the cluster; everything else aborted
    assert len(alters) == 1
    states = [t.state.value for t in ex.planner.all_tasks]
    assert "IN_PROGRESS" not in states and "PENDING" not in states


# ---- detect_ongoing_at_startup: the adopt/stop matrix ---------------------------
def _backend_with_ongoing():
    backend, _ = _fixture()
    backend.claim_controller_epoch()  # cluster epoch 1
    backend.alter_partition_reassignments({5: [2, 3]})
    assert backend.ongoing_reassignments() == {5}
    return backend


@pytest.mark.parametrize("stop", (False, True))
def test_startup_ours_by_epoch_match(stop):
    backend = _backend_with_ongoing()
    ex = Executor(backend)
    with capture_events() as recs:
        ongoing = ex.detect_ongoing_at_startup(stop=stop,
                                               checkpoint_epoch=1)
        assert not recs("executor.foreign_reassignment")
    assert ongoing == {5}
    if stop:  # ours + stop: cancelled, nothing to gate on
        assert ex.adopted_at_startup == set()
        assert backend.ongoing_reassignments() == set()
    else:  # ours + no stop: adopt and gate until drained
        assert ex.adopted_at_startup == {5}
        assert backend.ongoing_reassignments() == {5}


@pytest.mark.parametrize("stop", (False, True))
def test_startup_foreign_by_epoch_mismatch_never_cancelled(stop):
    backend = _backend_with_ongoing()
    backend.claim_controller_epoch()  # cluster epoch 2 > checkpoint 1
    ex = Executor(backend)
    with capture_events() as recs:
        ongoing = ex.detect_ongoing_at_startup(stop=stop,
                                               checkpoint_epoch=1)
        foreign = recs("executor.foreign_reassignment")
    assert ongoing == {5}
    # foreign work is NEVER cancelled — not even under stop=True: that
    # would start a reassignment war with a live controller
    assert backend.ongoing_reassignments() == {5}
    assert ex.adopted_at_startup == {5}
    assert foreign and foreign[0]["payload"]["origin"] == "startup"
    assert foreign[0]["payload"]["partitions"] == [5]


@pytest.mark.parametrize("stop", (False, True))
def test_startup_unknown_epoch_keeps_legacy_behavior(stop):
    backend = _backend_with_ongoing()
    ex = Executor(backend)  # no checkpoint epoch known
    ongoing = ex.detect_ongoing_at_startup(stop=stop)
    assert ongoing == {5}
    if stop:
        assert backend.ongoing_reassignments() == set()
        assert ex.adopted_at_startup == set()
    else:
        assert ex.adopted_at_startup == {5}


# ---- throttle leak on crash (satellite) -----------------------------------------
THROTTLE_KEYS = (
    "leader.replication.throttled.rate",
    "follower.replication.throttled.rate",
    "leader.replication.throttled.replicas",
    "follower.replication.throttled.replicas",
)


def _throttle_configs(backend):
    return {
        scope_entity: dict(cfg)
        for scope_entity, cfg in backend.dynamic_configs.items()
        if any(k in cfg for k in THROTTLE_KEYS)
    }


@pytest.mark.parametrize("resume_throttle", (1000.0, None))
def test_resume_after_crash_clears_orphaned_throttles(tmp_path,
                                                      resume_throttle):
    """Crash between set_throttles and the first batch: the dead run's
    throttle configs are orphans.  Resume re-scopes (adopts) them so its
    cleanup clears them — whether or not the restarted process itself
    throttles."""
    backend, plan = _fixture()
    path = str(tmp_path / "ckpt.jsonl")
    journal = ExecutionJournal(path)
    # appends: start(1), throttle(2); the phase record (3) crashes —
    # throttles reached the cluster, no batch did
    journal.crash_after(2)
    ex = Executor(backend, journal=journal,
                  config=ExecutorConfig(replication_throttle=1000.0))
    with pytest.raises(ProcessCrash):
        ex.execute_proposals(plan)
    orphans = _throttle_configs(backend)
    assert orphans, "fixture must leave orphaned throttle configs"

    recovered = ExecutionJournal(path)
    ck = recovered.load()
    assert ck is not None and (ck.throttle or {}).get("state") == "set"
    assert float(ck.throttle["rate"]) == 1000.0
    ex2 = Executor(
        backend, journal=recovered,
        config=ExecutorConfig(replication_throttle=resume_throttle),
    )
    result = ex2.resume(ck)
    assert result.dead == 0
    assert _throttle_configs(backend) == {}, (
        "orphaned throttle configs from the dead run survived recovery"
    )
    assert backend.throttle_history[-1] == ("clear", 0.0)


def test_resume_preserves_genuine_user_throttles(tmp_path):
    """Value-matched adoption: a user throttle at a DIFFERENT rate on a
    participating broker is not ours and must survive the cleanup."""
    backend, plan = _fixture()
    backend.alter_config("broker", 2,
                         {"leader.replication.throttled.rate": "777"})
    path = str(tmp_path / "ckpt.jsonl")
    journal = ExecutionJournal(path)
    journal.crash_after(2)
    ex = Executor(backend, journal=journal,
                  config=ExecutorConfig(replication_throttle=1000.0))
    with pytest.raises(ProcessCrash):
        ex.execute_proposals(plan)
    recovered = ExecutionJournal(path)
    ex2 = Executor(backend, journal=recovered, config=ExecutorConfig())
    ex2.resume(recovered.load())
    assert backend.describe_config("broker", 2) == {
        "leader.replication.throttled.rate": "777"
    }
    leftovers = {
        k: v for se, cfg in _throttle_configs(backend).items()
        for k, v in cfg.items() if se != ("broker", 2)
    }
    assert leftovers == {}


# ---- ConcurrencyAdjuster under foreign URPs (satellite) -------------------------
def test_adjuster_halves_under_external_urps_and_recovers():
    adj = ConcurrencyAdjuster(initial_cap=8, min_cap=1, max_cap=8,
                              healthy_ticks_before_increase=2)
    # sustained FOREIGN catch-up traffic: multiplicative decrease to the
    # floor, never below
    caps = [adj.observe({100 + i}) for i in range(5)]
    assert caps == [4, 2, 1, 1, 1]
    assert [a for a in adj.adjustments if a[0] == "decrease"]
    # the foreign moves drain: additive recovery, capped at the ceiling
    caps = [adj.observe(set()) for _ in range(16)]
    assert caps[-1] == 8
    assert sorted(set(caps)) == [1, 2, 3, 4, 5, 6, 7, 8]


def test_drive_loop_feeds_foreign_urps_to_adjuster():
    """A foreign reassignment's catch-up URPs (not our in-flight moves)
    must reach the adjuster as external stress and halve the cap."""
    backend, plan = _fixture(move_latency=30)
    # a foreign move catching up for a long time: partition 3 is not in
    # the plan, broker 1's new copy never finishes quickly
    backend.alter_partition_reassignments({3: [3, 1]})
    ex = Executor(backend, config=ExecutorConfig(
        num_concurrent_partition_movements_per_broker=4,
        concurrency_adjuster_enabled=True,
        concurrency_adjuster_min_cap=1,
        task_timeout_ticks=100,
    ))
    ex.execute_proposals(plan, max_ticks=200)
    assert ex.adjuster is not None
    assert ("decrease", 2) in ex.adjuster.adjustments


# ---- per-batch precondition revalidation ----------------------------------------
def test_deleted_partition_cancels_with_categorical_reason():
    backend, plan = _fixture()
    backend.delete_partitions([4])
    with capture_events() as recs:
        ex = Executor(backend)
        result = ex.execute_proposals(plan)
        drift = recs("executor.topology_drift")
        ends = recs("executor.end")
    # partition 4's replica task AND its sibling leader task both cancel
    # (the other two proposals complete: 2 replica + 2 leader tasks)
    assert result.completed == 4 and result.aborted == 2
    assert result.dead == 0, "deletion must not burn the retry budget"
    assert any(d["payload"]["reason"] == "topology-drift:deleted"
               and d["payload"]["partition"] == 4 for d in drift)
    assert ends[-1]["payload"]["topologyDrift"] == {"deleted": 2}


def test_rf_change_cancels_with_categorical_reason():
    backend, plan = _fixture()
    # an external tool bumped partition 1 to RF 3 before our batch
    st = backend.partitions[1]
    st.replicas = list(st.replicas) + [3]
    with capture_events() as recs:
        ex = Executor(backend)
        result = ex.execute_proposals(plan)
        drift = recs("executor.topology_drift")
    assert result.dead == 0 and result.aborted == 1
    assert any(d["payload"]["reason"] == "topology-drift:rf-changed"
               for d in drift)


def test_foreign_predispatch_conflict_yields_then_completes():
    backend, plan = _fixture(move_latency=1)
    # a foreign move already owns planned partition 0 with a DIFFERENT
    # target; at latency 1 it drains after one tick
    backend.alter_partition_reassignments({0: [1, 2]})
    with capture_events() as recs:
        ex = Executor(backend, config=ExecutorConfig(
            foreign_conflict_policy="yield",
            foreign_yield_backoff_ticks=2,
        ))
        result = ex.execute_proposals(plan)
        foreign = recs("executor.foreign_reassignment")
    assert result.completed == 6 and result.dead == 0  # 3 replica + 3 leader
    assert _placement(backend)[0] == [2, 3], "our target must win"
    assert any(f["payload"]["conflict"] and
               f["payload"]["origin"] == "pre-dispatch" for f in foreign)


def test_foreign_conflict_abort_policy_aborts_plan():
    backend, plan = _fixture(move_latency=50)
    backend.alter_partition_reassignments({0: [1, 2]})
    with capture_events() as recs:
        ex = Executor(backend, config=ExecutorConfig(
            foreign_conflict_policy="abort",
        ))
        result = ex.execute_proposals(plan)
        assert recs("executor.foreign_reassignment")
    assert result.stopped and result.dead == 0
    assert result.completed == 0


def test_disjoint_foreign_is_tolerated_and_journaled_once():
    backend, plan = _fixture(move_latency=2)
    backend.alter_partition_reassignments({3: [3, 0]})  # not in the plan
    with capture_events() as recs:
        ex = Executor(backend)
        result = ex.execute_proposals(plan)
        foreign = recs("executor.foreign_reassignment")
    assert result.completed == 6 and result.dead == 0  # 3 replica + 3 leader
    disjoint = [f for f in foreign if not f["payload"]["conflict"]]
    assert len(disjoint) == 1  # once per partition, not per tick
    assert disjoint[0]["payload"]["partitions"] == [3]


# ---- THE interleaving harness ---------------------------------------------------
@pytest.mark.parametrize("policy", ("yield", "abort"))
@pytest.mark.parametrize("conflict", (False, True))
def test_foreign_alter_at_every_batch_boundary(policy, conflict):
    """Inject a foreign alter immediately before the k-th executor batch,
    for EVERY k the plan produces (kill-at-every-checkpoint style), under
    both conflict policies: the cluster must converge with zero
    double-applied moves and zero silent-wrong placements."""
    # reference: what the foreign move alone would do to its partition
    boundaries = 0
    for k in range(0, 20):
        backend, plan = _fixture(move_latency=2)
        raw_alter = SimulatedClusterBackend.alter_partition_reassignments
        planned = {p.partition: list(p.new_replicas) for p in plan}
        originals = {p: list(st.replicas)
                     for p, st in backend.partitions.items()}
        executor_alters = []
        foreign_applied = {}
        state = {"n": 0}
        holder = {}

        def spy(reassignments, _backend=backend, _k=k, _state=state,
                _applied=foreign_applied, _log=executor_alters,
                _conflict=conflict, _holder=holder):
            # the executor's k-th batch boundary: the foreign writer
            # lands its alter FIRST (raw backend — no fence, exactly
            # like kafka-reassign-partitions)
            if _state["n"] == _k and not _applied:
                victim = sorted(
                    p for p in (reassignments if _conflict
                                else set(_backend.partitions)
                                - set(planned))
                )
                if victim:
                    p = victim[0]
                    st = _backend.partitions[p]
                    base = [b for b in st.replicas
                            if b not in st.catching_up] or list(st.replicas)
                    cand = sorted(b for b in _backend.brokers
                                  if b not in st.replicas)
                    if cand:
                        tgt = base[:-1] + [cand[0]]
                        _applied[p] = tgt
                        raw_alter(_backend, {p: tgt})
            _state["n"] += 1
            done_now = set()
            ex_live = _holder.get("ex")
            if ex_live is not None and ex_live.planner is not None:
                from cruise_control_tpu.executor.tasks import TaskState

                done_now = {
                    t.proposal.partition
                    for t in ex_live.planner.replica_tasks
                    if t.state is TaskState.COMPLETED
                }
            _log.append((dict(reassignments), done_now))
            raw_alter(_backend, reassignments)

        backend.alter_partition_reassignments = spy
        ex = Executor(backend, config=ExecutorConfig(
            num_concurrent_partition_movements_per_broker=1,  # many batches
            foreign_conflict_policy=policy,
            task_retry_max_attempts=3,
            task_retry_jitter_ticks=0,
            foreign_yield_backoff_ticks=2,
        ))
        holder["ex"] = ex
        result = ex.execute_proposals(plan, max_ticks=300)
        if state["n"] <= k and not foreign_applied:
            break  # fewer batches than k: every boundary exercised
        boundaries += 1
        _settle(backend)
        final = _placement(backend)
        # zero silent-wrong placements: every partition ends at exactly
        # one of (original, planned target, foreign target)
        for p, replicas in final.items():
            legal = [originals[p]]
            if p in planned:
                legal.append(planned[p])
            if p in foreign_applied:
                legal.append(foreign_applied[p])
            assert replicas in legal, (
                f"k={k} {policy} conflict={conflict}: partition {p} at "
                f"{replicas}, legal {legal}"
            )
        # zero double-applied moves: a COMPLETED task's partition is
        # never re-altered, and re-issues stay inside the retry budget
        counts = {}
        for batch_, done_at_call in executor_alters:
            overlap = set(batch_) & done_at_call
            assert not overlap, (
                f"k={k} {policy}: re-altered completed partition(s) "
                f"{sorted(overlap)}"
            )
            for p in batch_:
                counts[p] = counts.get(p, 0) + 1
        for p, n in counts.items():
            assert n <= 1 + 3, (p, n)
        assert result.dead == 0, (k, policy, conflict, result)
        if policy == "yield" and not conflict:
            # disjoint foreign + yield: the full plan must land
            # (a replica task + a leadership task per proposal)
            assert result.completed == 2 * len(plan)
            for p, tgt in planned.items():
                assert final[p] == tgt
    assert boundaries >= 3, "the fixture must exercise several boundaries"


# ---- the foreign-reassignment detector ------------------------------------------
def test_foreign_detector_pages_only_on_persistent_activity():
    backend, plan = _fixture(move_latency=1)

    class _CC:
        pass

    cc = _CC()
    cc.executor = Executor(backend)
    det = ForeignReassignmentDetector(cc, backend,
                                      min_consecutive_cycles=3)
    assert det.detect(0) == []
    backend.alter_partition_reassignments({3: [3, 0]})
    assert det.detect(1) == []      # cycle 1: tolerated
    assert det.detect(2) == []      # cycle 2: tolerated
    found = det.detect(3)           # cycle 3: persistent -> anomaly
    assert len(found) == 1
    a = found[0]
    assert a.anomaly_type.value == "FOREIGN_REASSIGNMENT"
    assert a.partitions == [3] and not a.fixable
    backend.tick()                  # the foreign move drains
    assert det.detect(4) == []
    assert det._streak == {}


def test_foreign_detector_ignores_our_own_execution():
    backend, plan = _fixture(move_latency=50)

    class _CC:
        pass

    cc = _CC()
    ex = Executor(backend)
    cc.executor = ex
    det = ForeignReassignmentDetector(cc, backend,
                                      min_consecutive_cycles=1)
    # adopted-at-startup moves are ours, not foreign
    backend.alter_partition_reassignments({5: [2, 3]})
    ex.adopted_at_startup = {5}
    assert det.detect(0) == []
