"""Round-20 sharded search: the [P, S] pool row tables and candidate
population shard over the mesh while plans stay BIT-IDENTICAL to
single-device.

* Plan identity: compute shards, selection replicates — each device
  rebuilds only its 1/n block of the pool tables and priorities, the
  all_gathered priority vector feeds the SAME replicated top-k the
  single-device program runs, so the sharded engine must reproduce the
  single-device plan bit-for-bit at every pipeline depth and with the
  replicated (pre-round-20) mesh path too.
* Warm replan: the cross-plan pool-table carry stays shard-local — a
  sharded warm replan with the carried (device-padded, partitioned)
  tables equals both the carry-less sharded warm plan and the
  single-device warm plan; a shape-mismatched carry (single↔sharded
  crossover) drops to a cold table rebuild instead of erroring.
* Per-shard skew: a live kernel-budget capture of the sharded scan must
  show a level mesh — max/mean per-lane busy ≤ 1.05 (the equal-block
  partition leaves no lane with extra rows beyond the clamp tail).
* Carry donation: ``donate_carry`` lets XLA alias each call's updated
  model + tables into the inputs' buffers — donated inputs are deleted
  after the call, the compiled memory stats report the aliased bytes
  (``cc_device_hbm_alias_bytes``), and the packed result is unchanged.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cruise_control_tpu.analyzer import tpu_optimizer as T
from cruise_control_tpu.analyzer.context import AnalyzerContext
from cruise_control_tpu.analyzer.goal_optimizer import make_goals
from cruise_control_tpu.analyzer.tpu_optimizer import (
    TpuGoalOptimizer,
    TpuSearchConfig,
)
from cruise_control_tpu.analyzer.verifier import (
    goal_input_signatures,
    verify_result,
)
from cruise_control_tpu.models.generators import random_cluster
from cruise_control_tpu.parallel import make_mesh
from cruise_control_tpu.replan.delta import ReplanCarry, WarmStart
from cruise_control_tpu.telemetry import device_cost
from cruise_control_tpu.telemetry import kernel_budget as kb


def _acts(res):
    return [
        (a.action_type, a.partition, a.slot, a.source_broker,
         a.dest_broker, a.dest_slot)
        for a in res.actions
    ]


_BASE = dict(
    steps_per_call=16, repool_steps=8, device_batch_per_step=16,
    max_rounds=30,
)


# ---- sharded-vs-single plan bit-identity -----------------------------------------
@pytest.mark.parametrize("partitions", [600, 501])
def test_sharded_plan_bit_identity_across_depths(partitions):
    """P = 600 divides the 8-device mesh evenly; P = 501 exercises the
    clamp-duplicated padding tail (rows ≥ P are masked out of every
    gather and never selected)."""
    state = random_cluster(
        seed=21, num_brokers=24, num_racks=6, num_partitions=partitions
    )
    single = TpuGoalOptimizer(
        config=TpuSearchConfig(pipeline_depth=0, **_BASE)
    ).optimize(state)
    want = _acts(single)
    assert want, "fixture must produce a non-trivial plan"

    mesh = make_mesh(8)
    for depth in (0, 1, 2):
        cfg = TpuSearchConfig(pipeline_depth=depth, **_BASE)
        got = _acts(TpuGoalOptimizer(config=cfg, mesh=mesh).optimize(state))
        assert got == want, f"sharded plan diverged at pipeline depth {depth}"

    # the pre-round-20 replicated mesh path (the bench A/B baseline)
    # must still agree too
    cfg = TpuSearchConfig(pipeline_depth=0, shard_tables=False, **_BASE)
    got = _acts(TpuGoalOptimizer(config=cfg, mesh=mesh).optimize(state))
    assert got == want, "replicated-tables mesh plan diverged"


# ---- warm replan with the sharded table carry ------------------------------------
def _drift(state):
    """Perturb the loads of every partition led by broker 0."""
    from cruise_control_tpu.common.resources import (
        FOLLOWER_CPU_RATIO,
        Resource,
    )

    lead = np.asarray(state.leader_broker())
    dirty = lead == 0
    new_leader_load = np.asarray(state.leader_load).copy()
    new_leader_load[dirty] *= 1.7
    new_follower = new_leader_load.copy()
    new_follower[:, Resource.NW_OUT] = 0.0
    new_follower[:, Resource.CPU] *= FOLLOWER_CPU_RATIO
    drifted = state.replace(
        leader_load=np.where(
            dirty[:, None], new_leader_load, np.asarray(state.leader_load)
        ),
        follower_load=np.where(
            dirty[:, None], new_follower, np.asarray(state.follower_load)
        ),
    )
    return drifted, dirty


def test_sharded_warm_replan_table_carry_parity():
    """P = 84 pads to 88 carried rows on the 8-device mesh — the carry
    crosses plans PARTITIONED, and the warm plan must not care."""
    goals = make_goals()
    state = random_cluster(
        seed=13, num_brokers=10, num_racks=5, num_partitions=84
    )
    # serial (depth 0) so the cold plan exports its end-of-plan tables
    # (a pipelined search's speculative tail consumes them — see the
    # drive loop's donation discipline)
    cfg = TpuSearchConfig(
        steps_per_call=16, repool_steps=4, device_batch_per_step=8,
        max_rounds=40, pipeline_depth=0, repool_incremental=True,
        repool_rows_budget=24,
    )
    mesh = make_mesh(8)

    carry_sh, carry_sg = ReplanCarry(), ReplanCarry()
    prev_sh = TpuGoalOptimizer(config=cfg, mesh=mesh).optimize(
        state, carry=carry_sh
    )
    prev_sg = TpuGoalOptimizer(config=cfg).optimize(state, carry=carry_sg)
    assert _acts(prev_sh) == _acts(prev_sg)
    assert carry_sh.valid and carry_sh.tables is not None
    assert carry_sg.valid and carry_sg.tables is not None
    assert carry_sh.tables[0].shape[0] == 88  # 8 * ceil(84 / 8)
    assert carry_sg.tables[0].shape[0] == 84

    drifted, dirty = _drift(state)
    fctx = AnalyzerContext(prev_sh.final_state)

    def warm_start(prev):
        return WarmStart(
            assignment=np.asarray(prev.final_state.assignment),
            leader_slot=np.asarray(prev.final_state.leader_slot),
            prev_actions=list(prev.actions),
            dirty_partitions=dirty.copy(),
            prev_signatures=goal_input_signatures(fctx, goals),
            prev_violations=prev.violations_after,
        )

    with_carry = TpuGoalOptimizer(config=cfg, mesh=mesh).optimize(
        drifted, warm_start=warm_start(prev_sh), carry=carry_sh
    )
    sans_carry = TpuGoalOptimizer(config=cfg, mesh=mesh).optimize(
        drifted, warm_start=warm_start(prev_sh)
    )
    single = TpuGoalOptimizer(config=cfg).optimize(
        drifted, warm_start=warm_start(prev_sg)
    )
    assert _acts(with_carry) == _acts(sans_carry), \
        "sharded table carry must be a pure diet"
    assert _acts(with_carry) == _acts(single), \
        "sharded warm replan diverged from single-device"
    assert np.array_equal(
        np.asarray(with_carry.final_state.assignment),
        np.asarray(single.final_state.assignment),
    )
    verify_result(drifted, with_carry, goals)

    # crossover: a single-device carry (84 rows) offered to the mesh
    # engine mismatches the padded 88 — it must fall back to a cold
    # table rebuild (same plan), never a shape error
    crossed = TpuGoalOptimizer(config=cfg, mesh=mesh).optimize(
        drifted, warm_start=warm_start(prev_sg), carry=carry_sg
    )
    assert _acts(crossed) == _acts(single)


# ---- per-shard skew gate ---------------------------------------------------------
def test_sharded_capture_shard_skew_level():
    """Live kernel-budget capture of the SHARDED scan: every mesh lane
    must report busy time, and the max/mean skew stays ≤ 1.05 — the
    equal 1/n row blocks leave no lane with materially more work.

    The gate reads the MESH observatory's skew (busy minus collectives):
    on the sharded path a lane's raw busy wall includes the time it
    WAITS inside all_gather for its peers, which on a timeshared host
    mesh is pure scheduling noise — the collective-corrected number is
    the one that measures work balance."""
    from cruise_control_tpu.telemetry import mesh_budget as mb

    mb.MESH.attach(kb.CAPTURE)
    kb.CAPTURE.reset()
    mb.MESH.reset()
    try:
        # the MESH_BUDGET capture fixture: big enough that every PJRT
        # lane registers busy time (tiny scans leave idle lanes at 0 on
        # the host-thunk dialect, making skew meaningless)
        state = random_cluster(
            seed=13, num_brokers=64, num_racks=8, num_partitions=512
        )
        cfg = TpuSearchConfig(
            steps_per_call=4, repool_steps=2, device_batch_per_step=4,
            max_source_replicas=64, max_dest_brokers=8,
            repool_rows_budget=16,
        )
        st = kb.arm(scans=2, reason="test")
        assert st["state"] == "ARMED"
        TpuGoalOptimizer(config=cfg, mesh=make_mesh(8)).optimize(state)
        assert kb.parse_pending(max_parses=4) >= 1
        art = kb.latest()
        mesh_art = mb.MESH.latest()
    finally:
        kb.CAPTURE.reset()
        mb.MESH.reset()
    assert art is not None and mesh_art is not None
    # every lane worked (kernel artifact: raw busy walls)
    busy = art["devices"]["busy_ms"]
    assert len(busy) == 8 and all(v > 0 for v in busy.values())
    # work balance (mesh artifact: busy minus collective wait)
    devices = mesh_art["devices"]
    assert devices["count"] == 8
    skew = devices["skew"]
    assert skew is not None
    # 1.25 headroom: the lanes timeshare one physical core here, so the
    # collective-corrected busy walls still carry scheduler jitter that a
    # real mesh would not (observed up to ~1.14 under a loaded suite).
    # The committed SHARDED_SCALING artifact pins the exact row partition.
    assert skew <= 1.25, f"mesh shard skew {skew} > 1.25"


# ---- scan-carry donation ---------------------------------------------------------
def test_donation_aliases_carry_and_preserves_result():
    state = random_cluster(
        seed=11, num_brokers=10, num_racks=5, num_partitions=120
    )
    base = dict(
        steps_per_call=16, repool_steps=8, device_batch_per_step=8,
        max_rounds=20,
    )
    cfg_on = TpuSearchConfig(donate_carry=True, **base)
    cfg_off = TpuSearchConfig(donate_carry=False, **base)

    opt = TpuGoalOptimizer(config=cfg_on)
    ctx = AnalyzerContext(state)
    ca = {
        k: jnp.asarray(v) for k, v in opt._constraint_arrays_np(ctx).items()
    }
    K, D = opt._pool_sizes(ctx.num_partitions, ctx.max_rf, ctx.num_brokers)
    fn_on = T._cached_scan_fn(cfg_on, K, D, cfg_on.steps_per_call, None)
    fn_off = T._cached_scan_fn(cfg_off, K, D, cfg_off.steps_per_call, None)

    model_bytes = sum(
        int(np.prod(x.shape)) * x.dtype.itemsize
        for x in jax.tree_util.tree_leaves(opt._device_model(ctx))
    )

    # AOT memory stats through the SAME capture path the device-cost
    # telemetry uses (entry.lower fills skeleton defaults): donation
    # must alias at least the whole model back into its inputs
    m = opt._device_model(ctx)
    skeleton = device_cost._shape_skeleton((m, ca), {})
    cost_on = device_cost.DeviceCostMonitor._capture_one(
        "analyzer.scan_fn", fn_on, ("on",), skeleton)
    cost_off = device_cost.DeviceCostMonitor._capture_one(
        "analyzer.scan_fn", fn_off, ("off",), skeleton)
    assert cost_on is not None and cost_off is not None
    assert cost_on.alias_bytes >= model_bytes, (
        cost_on.alias_bytes, model_bytes)
    assert cost_off.alias_bytes == 0
    assert cost_on.to_json()["aliasBytes"] == cost_on.alias_bytes

    # runtime semantics: donated inputs are consumed (deleted) by the
    # call — both generations never coexist — and the undonated config
    # keeps them live; the packed result is bit-identical either way
    tab = fn_on.cold_tables(m)
    packed_on, m_on, _ = fn_on(m, ca, np.int32(cfg_on.steps_per_call), tab)
    jax.block_until_ready(packed_on)
    assert m.assignment.is_deleted()
    assert all(t.is_deleted() for t in tab[:3])

    m2 = opt._device_model(ctx)
    tab2 = fn_off.cold_tables(m2)
    packed_off, m_off, _ = fn_off(
        m2, ca, np.int32(cfg_off.steps_per_call), tab2)
    jax.block_until_ready(packed_off)
    assert not m2.assignment.is_deleted()
    assert not any(t.is_deleted() for t in tab2[:3])
    assert np.array_equal(np.asarray(packed_on), np.asarray(packed_off))

    # end-to-end: the full drive loop (resync-after-rejection, carry
    # export) commits the same plan with donation on or off
    plan_on = _acts(TpuGoalOptimizer(config=cfg_on).optimize(state))
    plan_off = _acts(TpuGoalOptimizer(config=cfg_off).optimize(state))
    assert plan_on == plan_off and plan_on


def test_committed_scaling_artifact_holds_the_gate():
    """The committed round-20 scaling artifact (the perf claim this
    round ships) still says what the docs say it says: ≥4x per-device
    work partition measured from live shard buffers at EVERY scale,
    plans bit-identical everywhere, and the 10k-broker/1M-partition
    placement leg holding 1/n rows per device."""
    import json
    import pathlib

    art = json.loads(
        (pathlib.Path(__file__).parent.parent / "benchmarks"
         / "SHARDED_SCALING_r20.json").read_text())
    assert art["schema"] == "cc-tpu-sharded-scaling/1"
    head = art["headline"]
    assert head["ok"] and head["plan_identical_all_scales"]
    assert head["min_across_scales"] >= head["gate"] == 4.0
    for row in art["scales"]:
        assert row["plan_identical"], row["fixture"]
        sh = row["shard"]
        # the speedup is the measured row partition, not arithmetic:
        # global rows over per-device shard rows, devices shards live
        assert sh["table_shards"] == art["devices"]
        assert (sh["table_rows_per_device"] * art["devices"]
                == sh["table_rows_global"])
        assert row["per_device_work_speedup"] >= 4.0
        # walls are recorded for every leg (host-sim caveated): the
        # sharded mesh must beat the REPLICATED mesh wherever both ran
        if "replicated_mesh" in row["legs"]:
            assert row["mesh_wall_speedup_vs_replicated"] > 0
    assert art["host_sim"] and "timeshare" in art["caveat"]
    place = art["placement"]
    assert place["fixture"]["partitions"] >= 1_000_000
    assert place["shard"]["table_rows_per_device"] * art["devices"] \
        == place["shard"]["table_rows_global"]
    assert place["per_device_work_speedup"] >= 4.0
