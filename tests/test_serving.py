"""Overload-safe serving tests (ISSUE 8): admission control, request
deadlines, the warm proposal cache + degraded-mode serving, the analyzer
circuit breaker, /health, raw-HTTP hardening (413, slow-loris), and
graceful drain.

Server-level behavior under real concurrency is exercised end-to-end by
the serving-chaos scenarios (``tests/test_scenarios.py``) and the load
harness (``benchmarks/serve_load.py``); the tests here pin the unit
contracts those runs compose."""

import json
import socket
import threading
import time
import urllib.error
import urllib.request

import pytest

from cruise_control_tpu.analyzer.precompute import (
    AnalyzerSaturatedError,
    CircuitBreaker,
)
from cruise_control_tpu.server import admission
from cruise_control_tpu.server.admission import (
    CLASS_COMPUTE,
    CLASS_GET,
    AdmissionController,
    DeadlineExceededError,
    RequestShedError,
)
from cruise_control_tpu.server.http_server import CruiseControlHttpServer
from cruise_control_tpu.server.user_tasks import UserTaskManager

from harness import full_stack


def _get(url, headers=None):
    req = urllib.request.Request(url, headers=headers or {})
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, dict(r.headers), json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


class _FailingOptimizer:
    def optimize(self, state, options=None):
        raise RuntimeError("scripted analyzer failure")


def _fail_analyzer(cc):
    cc._make_engine = lambda engine, constraint=None: _FailingOptimizer()


def _restore_analyzer(cc):
    cc.__dict__.pop("_make_engine", None)


# ---- admission controller --------------------------------------------------------
class TestAdmission:
    def test_admits_within_limit(self):
        ctl = AdmissionController({CLASS_GET: 2}, queue_size=0)
        with ctl.admit(CLASS_GET):
            with ctl.admit(CLASS_GET):
                assert ctl.active(CLASS_GET) == 2
        assert ctl.active(CLASS_GET) == 0
        assert ctl.admitted_total == 2

    def test_queue_full_sheds_with_retry_after(self):
        ctl = AdmissionController({CLASS_GET: 1}, queue_size=0,
                                  retry_after_s=7)
        with ctl.admit(CLASS_GET):
            with pytest.raises(RequestShedError) as e:
                with ctl.admit(CLASS_GET):
                    pass
        assert e.value.retry_after_s == 7
        assert ctl.shed_total == 1

    def test_queue_timeout_sheds(self):
        ctl = AdmissionController({CLASS_GET: 1}, queue_size=4,
                                  queue_timeout_s=0.05)
        with ctl.admit(CLASS_GET):
            t0 = time.perf_counter()
            with pytest.raises(RequestShedError):
                with ctl.admit(CLASS_GET):
                    pass
            assert time.perf_counter() - t0 < 2.0

    def test_queued_request_runs_when_slot_frees(self):
        ctl = AdmissionController({CLASS_COMPUTE: 1}, queue_size=4,
                                  queue_timeout_s=5.0)
        entered = threading.Event()
        release = threading.Event()
        ran = []

        def holder():
            with ctl.admit(CLASS_COMPUTE):
                entered.set()
                release.wait(timeout=10)

        def waiter():
            with ctl.admit(CLASS_COMPUTE):
                ran.append(True)

        t1 = threading.Thread(target=holder)
        t1.start()
        assert entered.wait(timeout=5)
        t2 = threading.Thread(target=waiter)
        t2.start()
        time.sleep(0.05)
        assert ctl.queued() == 1
        release.set()
        t2.join(timeout=5)
        t1.join(timeout=5)
        assert ran == [True]

    def test_drain_sheds_queued_waiters_and_joins_inflight(self):
        ctl = AdmissionController({CLASS_GET: 1}, queue_size=4,
                                  queue_timeout_s=30.0)
        release = threading.Event()
        entered = threading.Event()
        outcomes = []

        def holder():
            with ctl.track(), ctl.admit(CLASS_GET):
                entered.set()
                release.wait(timeout=10)

        def waiter():
            try:
                with ctl.track(), ctl.admit(CLASS_GET):
                    outcomes.append("ran")
            except RequestShedError as e:
                outcomes.append(str(e))

        t1 = threading.Thread(target=holder)
        t1.start()
        assert entered.wait(timeout=5)
        t2 = threading.Thread(target=waiter)
        t2.start()
        time.sleep(0.05)
        # the holder is still in flight: drain sheds the waiter instantly
        # but must wait for (and report) the in-flight request
        done = []
        t3 = threading.Thread(
            target=lambda: done.append(ctl.drain(timeout_s=5.0)))
        t3.start()
        t2.join(timeout=5)
        assert outcomes and "draining" in outcomes[0]
        release.set()
        t3.join(timeout=10)
        t1.join(timeout=5)
        assert done == [True]
        with pytest.raises(RequestShedError):
            with ctl.admit(CLASS_GET):
                pass


# ---- request deadlines -----------------------------------------------------------
class TestDeadlines:
    def test_scope_nesting_keeps_tighter_deadline(self):
        now = time.monotonic()
        with admission.deadline_scope(now + 10):
            with admission.deadline_scope(now + 5):
                assert admission.remaining_s() < 6
            with admission.deadline_scope(now + 50):
                # the outer, tighter deadline wins
                assert admission.remaining_s() < 11
        assert admission.remaining_s() is None

    def test_expired_deadline_rejects_operation_before_analyzer(self):
        cc, _, _ = full_stack()
        with admission.deadline_scope(time.monotonic() - 0.1):
            with pytest.raises(DeadlineExceededError):
                cc.rebalance(dryrun=True)

    def test_near_expiry_clips_tpu_anytime_budget(self):
        cc, _, _ = full_stack()
        with admission.deadline_scope(time.monotonic() + 5.0):
            engine = cc._make_engine("tpu")
        assert 0 < engine.config.time_budget_s <= 5.0
        # no deadline -> no budget injected
        engine = cc._make_engine("tpu")
        assert engine.config is None or not engine.config.time_budget_s

    def test_worker_skips_task_whose_deadline_passed(self):
        mgr = UserTaskManager(max_workers=1)
        ran = []
        task = mgr.submit("rebalance", lambda p: ran.append(True),
                          deadline_monotonic=time.monotonic() - 1.0)
        with pytest.raises(DeadlineExceededError):
            task.future.result(timeout=5)
        assert not ran and task.state == "CompletedWithError"
        mgr.shutdown()

    def test_expired_deadline_maps_to_503_with_retry_after(self):
        """End to end: the worker pool is busy, the queued task's deadline
        expires before it starts, the long-poll answer is a 503 shed."""
        cc, _, _ = full_stack()
        release = threading.Event()
        mgr = UserTaskManager(max_workers=1)
        srv = CruiseControlHttpServer(cc, port=0, user_task_manager=mgr)
        srv.start()
        try:
            mgr.submit("blocker", lambda p: release.wait(timeout=30))
            code, headers, body = self._post(
                srv, "rebalance", {"dryrun": "true",
                                   "get_response_timeout_s": "10"},
                headers={"deadline-ms": "200"}, release=release,
            )
            assert code == 503
            assert "Retry-After" in headers
            assert "deadline" in body["errorMessage"].lower()
        finally:
            release.set()
            srv.stop()

    @staticmethod
    def _post(srv, endpoint, params, headers, release):
        import urllib.parse

        url = f"{srv.url}/{endpoint}?" + urllib.parse.urlencode(params)
        req = urllib.request.Request(url, method="POST", data=b"",
                                     headers=headers)

        # free the worker only after the deadline passed, so the queued
        # task deterministically starts dead
        def _free():
            time.sleep(0.5)
            release.set()

        threading.Thread(target=_free, daemon=True).start()
        try:
            with urllib.request.urlopen(req) as r:
                return r.status, dict(r.headers), json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, dict(e.headers), json.loads(e.read())


# ---- warm proposal cache + degraded serving --------------------------------------
class TestProposalCache:
    def test_generation_bump_invalidates(self):
        cc, _, reporter = full_stack()
        cc.get_proposals()
        assert cc.proposal_cache_fresh()
        result, meta = cc.serve_proposals()
        assert meta["cached"] is True and meta["stale"] is False
        # a new metric window = a new model generation: the plan is stale
        reporter.report(time_ms=3500)
        cc.load_monitor.run_sampling_iteration(4000)
        assert not cc.proposal_cache_fresh()
        _, meta = cc.serve_proposals()
        assert meta["cached"] is False  # recomputed against the new model
        assert cc.proposal_cache_fresh()

    def test_anomaly_invalidates_and_marks_reason(self):
        from types import SimpleNamespace

        from cruise_control_tpu.detector.anomalies import AnomalyType

        cc, _, _ = full_stack()
        cc.get_proposals()
        assert cc.proposal_cache_fresh()
        cc.note_anomaly(SimpleNamespace(
            anomaly_type=AnomalyType.BROKER_FAILURE))
        assert not cc.proposal_cache_fresh()
        state = cc.proposal_cache_state()
        assert state["cacheInvalidated"] == "anomaly:BROKER_FAILURE"

    def test_degrades_to_stale_on_analyzer_failure(self):
        cc, _, _ = full_stack()
        cc.get_proposals()
        baseline = cc.proposal_cache_state()["cacheGeneration"]
        from types import SimpleNamespace

        from cruise_control_tpu.detector.anomalies import AnomalyType

        cc.note_anomaly(SimpleNamespace(
            anomaly_type=AnomalyType.GOAL_VIOLATION))
        _fail_analyzer(cc)
        result, meta = cc.serve_proposals()
        assert meta["stale"] is True
        assert meta["proposalGeneration"] == baseline
        assert meta["staleReason"] == "anomaly:GOAL_VIOLATION"
        # an explicit opt-out gets the real failure instead
        with pytest.raises(RuntimeError):
            cc.serve_proposals(allow_stale=False)
        _restore_analyzer(cc)

    def test_cold_cache_failure_still_raises(self):
        cc, _, _ = full_stack()
        _fail_analyzer(cc)
        with pytest.raises(RuntimeError):
            cc.serve_proposals()

    def test_rebalance_cached_serves_warm_plan(self):
        cc, backend, _ = full_stack()
        cc.get_proposals()
        t0 = time.perf_counter()
        result = cc.rebalance_cached(dryrun=True)
        assert time.perf_counter() - t0 < 0.1  # milliseconds, not a solve
        assert result.cache_meta["cached"] is True
        assert result.proposals
        # and the cached plan actually executes
        done = cc.rebalance_cached(dryrun=False)
        assert done.execution is not None and done.execution.succeeded
        # execution invalidates the plan it just consumed
        assert not cc.proposal_cache_fresh()


# ---- circuit breaker -------------------------------------------------------------
class TestCircuitBreaker:
    def test_trip_probe_recover(self):
        clock = [0.0]
        b = CircuitBreaker(failure_threshold=2, reset_s=10.0,
                           clock=lambda: clock[0])
        assert b.allow() and b.state == "CLOSED"
        b.record_failure("boom")
        assert b.allow()  # one failure < threshold
        b.record_failure("boom")
        assert b.state == "OPEN" and not b.allow()
        clock[0] = 5.0
        assert not b.allow()  # reset_s not elapsed
        clock[0] = 10.0
        assert b.allow()      # the half-open probe
        assert not b.allow()  # only ONE probe at a time
        b.record_failure("still down")
        assert b.state == "OPEN"
        clock[0] = 25.0
        assert b.allow()
        b.record_success()
        assert b.state == "CLOSED" and b.allow()
        assert b.trips == 2

    def test_facade_breaker_refuses_compute_and_serves_stale(self):
        cc, _, _ = full_stack()
        clock = [0.0]
        cc.breaker = CircuitBreaker(failure_threshold=1, reset_s=60.0,
                                    clock=lambda: clock[0])
        cc.get_proposals()
        _fail_analyzer(cc)
        with pytest.raises(RuntimeError):
            cc.get_proposals(ignore_cache=True)
        assert cc.breaker.state == "OPEN"
        # compute refused while open: a direct rebalance is saturated...
        with pytest.raises(AnalyzerSaturatedError) as e:
            cc.rebalance(dryrun=True)
        assert e.value.retry_after_s >= 1
        # ...but proposals serving degrades to the last-good plan (made
        # stale here so the hit path can't answer first)
        cc.invalidate_proposal_cache("test")
        _, meta = cc.serve_proposals()
        assert meta["stale"] is True
        # probe after reset: analyzer recovered, breaker closes
        _restore_analyzer(cc)
        clock[0] = 60.0
        result, meta = cc.serve_proposals()
        assert meta["stale"] is False
        assert cc.breaker.state == "CLOSED"


# ---- /health + raw-HTTP hardening + drain ----------------------------------------
class TestHealthAndHardening:
    def test_health_ready(self):
        cc, _, _ = full_stack()
        srv = CruiseControlHttpServer(cc, port=0)
        srv.start()
        try:
            for path in ("/health", "/kafkacruisecontrol/health"):
                code, _, body = _get(f"http://127.0.0.1:{srv.port}{path}")
                assert code == 200
                assert body["liveness"] == "UP" and body["ready"] is True
                assert body["monitorWindows"] >= 1
        finally:
            srv.stop()

    def test_health_not_ready_without_windows(self):
        cc, _, _ = full_stack(windows=0)
        srv = CruiseControlHttpServer(cc, port=0)
        srv.start()
        try:
            code, _, body = _get(f"http://127.0.0.1:{srv.port}/health")
            assert code == 503
            assert body["liveness"] == "UP" and body["ready"] is False
        finally:
            srv.stop()

    def test_health_reports_draining_but_is_never_shed(self):
        cc, _, _ = full_stack()
        srv = CruiseControlHttpServer(cc, port=0)
        srv.start()
        try:
            srv.admission.drain(timeout_s=0.5)
            # normal requests are shed with Retry-After...
            code, headers, _ = _get(f"{srv.url}/state")
            assert code == 429 and "Retry-After" in headers
            # ...the probe still answers (ready=false tells the LB why)
            code, _, body = _get(f"http://127.0.0.1:{srv.port}/health")
            assert code == 503 and body["draining"] is True
        finally:
            srv.stop()

    def test_oversized_body_413(self):
        cc, _, _ = full_stack()
        srv = CruiseControlHttpServer(cc, port=0, max_body_bytes=1024)
        srv.start()
        try:
            req = urllib.request.Request(
                f"{srv.url}/rebalance?dryrun=true", method="POST",
                data=b"", headers={"Content-Length": str(1 << 20)},
            )
            # body deliberately NOT sent: the server must answer from the
            # declared length alone, before reading anything
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=10)
            assert e.value.code == 413
        finally:
            srv.stop()

    def test_slow_loris_connection_reaped(self):
        cc, _, _ = full_stack()
        srv = CruiseControlHttpServer(cc, port=0, read_timeout_s=0.3)
        srv.start()
        try:
            t0 = time.monotonic()
            closed = False
            with socket.create_connection(("127.0.0.1", srv.port),
                                          timeout=10) as sock:
                sock.sendall(b"GET /kafkacruisecontrol/state HTTP/1.1\r\n")
                sock.settimeout(0.1)
                deadline = time.monotonic() + 5
                while time.monotonic() < deadline:
                    try:
                        if sock.recv(4096) == b"":
                            closed = True
                            break
                    except TimeoutError:
                        continue
                    except (ConnectionError, OSError):
                        closed = True
                        break
            assert closed, "slow-loris connection was not reaped"
            assert time.monotonic() - t0 < 5
            # the server is still fine for normal clients
            code, _, _ = _get(f"http://127.0.0.1:{srv.port}/health")
            assert code == 200
        finally:
            srv.stop()

    def test_stop_drains_and_completes_inflight(self):
        cc, _, _ = full_stack()
        srv = CruiseControlHttpServer(cc, port=0, drain_timeout_s=5.0)
        srv.start()
        results = []

        def slow_get():
            results.append(_get(f"{srv.url}/proposals")[0])

        t = threading.Thread(target=slow_get)
        t.start()
        time.sleep(0.05)
        srv.stop()
        t.join(timeout=10)
        # the in-flight request was joined, not killed
        assert results == [200]


# ---- the committed SERVE_LOAD artifact -------------------------------------------
def test_committed_serve_load_artifact_passes_gates():
    """SERVE_LOAD_r08.json (benchmarks/serve_load.py output) must match
    the schema contract and hold every acceptance gate: ≥4× admission
    capacity, sheds all carrying Retry-After, zero unhandled 5xx, and
    server-side cached GET /proposals p99 ≤ 50 ms while a concurrent
    full rebalance ran."""
    import pathlib

    from test_artifact_schemas import SCHEMAS, validate

    art = json.loads(
        (pathlib.Path(__file__).parent.parent / "SERVE_LOAD_r08.json")
        .read_text()
    )
    validate(art, SCHEMAS["cc-tpu-serve-load/1"])
    for gate, ok in art["gates"].items():
        assert ok is True, f"serve-load gate failed: {gate}"
    assert art["config"]["loadFactor"] >= 4.0
    assert art["totals"]["shed"] > 0
    assert art["totals"]["shed"] == art["totals"]["shedWithRetryAfter"]
    assert art["totals"]["unhandled5xx"] == 0
    assert art["latencyMs"]["serverHandlerAdmitted"]["p99"] <= 50.0
    assert art["rebalance"]["status"] == 200


# ---- serving state surface -------------------------------------------------------
def test_state_exposes_cache_and_breaker():
    cc, _, _ = full_stack()
    cc.breaker = CircuitBreaker()
    cc.get_proposals()
    analyzer = cc.state()["AnalyzerState"]
    assert analyzer["proposalCache"]["cacheWarm"] is True
    assert analyzer["circuitBreaker"]["state"] == "CLOSED"
