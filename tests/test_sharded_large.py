"""Slow sharded-parity test at advertised shapes (round-5 item #6).

The committed artifact of record is ``SHARDED_DRYRUN_r05.json``
(produced by ``benchmarks/sharded_large_dryrun.py`` at 1k/50k).  This
test re-runs the same parity check in-suite at a reduced-but-still-
sharded shape by default, and at the full advertised shape when
``CC_TPU_SLOW=1`` (the artifact run) — keeping the suite's wall-clock
bounded while the full shape stays one env var away.
"""

import os
import subprocess
import sys
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent

FULL = os.environ.get("CC_TPU_SLOW") == "1"


@pytest.mark.slow
def test_sharded_plan_parity_large():
    shape = (
        ["--brokers", "1000", "--partitions", "50000"] if FULL
        else ["--brokers", "400", "--partitions", "12000"]
    )
    out = ROOT / ("SHARDED_DRYRUN_r05.json" if FULL
                  else "/tmp/sharded_dryrun_small.json")
    env = dict(
        os.environ,
        PYTHONPATH=str(ROOT),
        JAX_PLATFORMS="cpu",
        CC_TPU_CACHE_CPU_EXECUTABLES="1",
        PALLAS_AXON_POOL_IPS="",
    )
    proc = subprocess.run(
        [sys.executable, str(ROOT / "benchmarks" / "sharded_large_dryrun.py"),
         *shape, "--out", str(out)],
        env=env, capture_output=True, text=True, timeout=3600,
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    assert '"plan_identical": true' in proc.stdout
