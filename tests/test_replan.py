"""Delta-replan subsystem contracts (incremental re-optimization).

* Dirty tracking: the aggregator's per-entity dirty set and the monitor's
  value-diffed ``ModelDelta`` — untouched rows of a delta model are
  BIT-IDENTICAL to the previous model.
* Warm-vs-cold equivalence (property-style over seeded drift deltas): a
  warm-started plan's score stays within the parity gate's tolerance of
  the cold plan on the same model, and the plan still passes the full
  verifier.
* Budget breach: a delta beyond the dirty budget falls back to the cold
  path bit-identically (same actions, same proposals).
* Device carry: the TPU engine's warm plan with the cross-plan pool-table
  carry equals the carry-less warm plan bit-for-bit (the carried tables
  are exact, not approximate).
* Facade routing: replan decisions are journaled (``replan.start`` /
  ``replan.end``) and a warm-path failure falls back to one cold attempt.
"""

import numpy as np
import pytest

from cruise_control_tpu.analyzer.context import AnalyzerContext
from cruise_control_tpu.analyzer.goal_optimizer import (
    GoalOptimizer,
    make_goals,
)
from cruise_control_tpu.analyzer.verifier import (
    goal_input_signatures,
    partial_violations,
    verify_result,
    violation_score,
)
from cruise_control_tpu.monitor.aggregator import MetricSampleAggregator
from cruise_control_tpu.monitor.metric_defs import MetricDef
from cruise_control_tpu.replan import DeltaReplanner, ReplanConfig
from cruise_control_tpu.replan.delta import ReplanCarry, WarmStart
from cruise_control_tpu.telemetry import events

from harness import WINDOW, full_stack


def _warm_score_tolerance(cold_score: int) -> int:
    """The parity-gate discipline, one-sided: the warm plan may not be
    more than marginally worse than the cold plan on the same model."""
    return cold_score + max(1, round(0.02 * cold_score))


def _roll_windows(cc, reporter, start_window: int, n: int = 2):
    """Report + ingest ``n`` fresh windows so the drifted loads become
    COMPLETED windows the model build can see (the newest window is
    always in-progress and excluded)."""
    for k in range(start_window, start_window + n):
        reporter.report(time_ms=k * WINDOW + 500)
        cc.load_monitor.run_sampling_iteration((k + 1) * WINDOW)


def _drift_broker(reporter, broker: int, factor: float, limit=None):
    """Scale the load of partitions hosted on ``broker`` (the skewed test
    workload leads everything on broker 0, so replica membership is the
    selector that works for every broker).  ``limit`` caps the subset so
    warm-path tests stay under the dirty budget."""
    w = reporter.workload
    parts = [p for p, reps in w.assignment.items() if broker in reps]
    if limit is not None:
        parts = parts[:limit]
    for p in parts:
        w.bytes_in[p] *= factor
        w.bytes_out[p] *= factor
    return parts


# ---- aggregator dirty tracking ---------------------------------------------------
def test_aggregator_dirty_entities_since():
    from cruise_control_tpu.monitor.metric_defs import AggregationFunction

    d = MetricDef()
    d.define("m", AggregationFunction.AVG)
    d.freeze()
    agg = MetricSampleAggregator(d, num_entities=4, window_ms=100,
                                 num_windows=3)
    agg.add_sample(0, 50, [1.0])
    mark = agg.generation
    assert not agg.dirty_entities_since(mark).any()
    agg.add_sample(2, 60, [2.0])
    dirty = agg.dirty_entities_since(mark)
    assert dirty.tolist() == [False, False, True, False]
    # an eviction (window roll past retention) widens to all-True: the
    # dropped window moved every entity's mean
    for w in range(1, 6):
        agg.add_sample(1, w * 100 + 1, [1.0])
    assert agg.eviction_generation > mark
    assert agg.dirty_entities_since(mark).all()
    # new entities are dirty by construction
    mark2 = agg.generation
    agg.ensure_entities(6)
    assert agg.dirty_entities_since(mark2)[4:].all()


# ---- monitor delta build ---------------------------------------------------------
def test_cluster_model_delta_patches_only_dirty_rows():
    cc, backend, reporter = full_stack(num_partitions=24, num_brokers=4)
    mon = cc.load_monitor
    prev = mon.cluster_model()
    mark = mon.aggregation_mark()
    drifted = _drift_broker(reporter, 0, 3.0)
    _roll_windows(cc, reporter, 3)
    state, delta = mon.cluster_model_delta(prev, mark)
    assert not delta.full
    assert delta.load_changed and not delta.topology_changed
    dirty = delta.dirty_partitions
    assert set(np.nonzero(dirty)[0]) <= set(drifted)
    assert dirty.any()
    # clean rows keep the previous model's BITS; dirty rows match a
    # from-scratch build exactly
    fresh = mon._cluster_model()
    pl = np.asarray(prev.leader_load)
    nl = np.asarray(state.leader_load)
    fl = np.asarray(fresh.leader_load)
    assert np.array_equal(nl[~dirty], pl[~dirty])
    assert np.array_equal(nl[dirty], fl[dirty])
    assert np.array_equal(
        np.asarray(state.follower_load)[dirty],
        np.asarray(fresh.follower_load)[dirty],
    )


def test_cluster_model_delta_broker_death_and_add():
    cc, backend, reporter = full_stack(num_partitions=24, num_brokers=4)
    mon = cc.load_monitor
    prev = mon.cluster_model()
    mark = mon.aggregation_mark()
    backend.failed_brokers.add(3)
    _roll_windows(cc, reporter, 3)
    state, delta = mon.cluster_model_delta(prev, mark)
    assert not delta.full
    assert delta.topology_changed
    assert delta.removed_brokers == (3,)
    assert delta.dirty_brokers[3]
    # every partition with a replica on the dead broker is topology-dirty
    hosts3 = np.any(np.asarray(prev.assignment) == 3, axis=1)
    assert (delta.dirty_topology >= hosts3).all()
    # broker add: prefix-compatible axis growth, no full rebuild
    prev2 = state
    mark2 = mon.aggregation_mark()
    backend.brokers.add(4)
    mon.metadata.broker_rack[4] = 0
    _roll_windows(cc, reporter, 5)
    state2, delta2 = mon.cluster_model_delta(prev2, mark2)
    assert not delta2.full
    assert delta2.shape_changed and delta2.added_brokers == (4,)
    assert state2.num_brokers == 5
    assert np.asarray(state2.broker_capacity).shape[0] == 5


def test_cluster_model_delta_falls_back_full_on_universe_drift():
    cc, backend, reporter = full_stack(num_partitions=12, num_brokers=3)
    mon = cc.load_monitor
    prev = mon.cluster_model()
    mark = mon.aggregation_mark()
    # a brand-new partition changes the universe → full rebuild
    backend.partitions[99] = type(next(iter(backend.partitions.values())))(
        replicas=[0, 1], leader=0
    )
    state, delta = mon.cluster_model_delta(prev, mark)
    assert delta.full and delta.reason == "partition-universe-changed"
    assert state.num_partitions == 13


# ---- context reseed + partial verify ---------------------------------------------
def test_reseed_rebuilds_exact_aggregates():
    from cruise_control_tpu.models.generators import random_cluster

    state = random_cluster(seed=9, num_brokers=6, num_racks=3,
                           num_partitions=40)
    res = GoalOptimizer().optimize(state)
    ctx = AnalyzerContext(state)
    ctx.reseed(
        np.asarray(res.final_state.assignment),
        np.asarray(res.final_state.leader_slot),
    )
    ref = AnalyzerContext(res.final_state)
    assert np.allclose(ctx.broker_load, ref.broker_load)
    assert np.array_equal(ctx.broker_replica_count, ref.broker_replica_count)
    assert np.array_equal(ctx.broker_leader_count, ref.broker_leader_count)
    ctx.recompute_check()


def test_partial_violations_signature_reuse_is_exact():
    from cruise_control_tpu.models.generators import random_cluster

    state = random_cluster(seed=4, num_brokers=6, num_racks=3,
                           num_partitions=40)
    goals = make_goals()
    ctx = AnalyzerContext(state)
    sigs = goal_input_signatures(ctx, goals)
    truth = {g.name: g.violations(ctx) for g in goals}
    # identical context: everything reuses, nothing recomputes wrong
    wrong = {name: v + 100 for name, v in truth.items()}
    reused_viol, _, reused = partial_violations(ctx, goals, sigs, wrong)
    assert set(reused) == set(truth)
    assert reused_viol == wrong  # proves reuse actually happened
    # a load perturbation invalidates exactly the load-reading goals
    ctx2 = AnalyzerContext(state)
    ctx2.leader_load = ctx2.leader_load.copy()
    ctx2.leader_load[0] *= 1.5
    viol2, _, reused2 = partial_violations(ctx2, goals, sigs, wrong)
    for g in goals:
        if "loads" in g.inputs:
            assert g.name not in reused2
            assert viol2[g.name] == g.violations(ctx2)
        else:
            assert g.name in reused2
    # the safety net recomputes everything
    full_viol, _, none_reused = partial_violations(
        ctx, goals, sigs, wrong, force_full=True
    )
    assert none_reused == [] and full_viol == truth


# ---- warm-vs-cold equivalence (property-style) -----------------------------------
@pytest.mark.parametrize("seed", [1, 2, 3])
def test_warm_plan_score_within_parity_tolerance(seed):
    """Seeded drift deltas: the warm-started plan must score inside the
    parity tolerance of the cold plan computed on the SAME drifted model,
    and still pass the full verifier."""
    rng = np.random.default_rng(seed)
    cc, backend, reporter = full_stack(num_partitions=24, num_brokers=4)
    mon = cc.load_monitor
    prev = mon.cluster_model()
    mark = mon.aggregation_mark()
    opt = GoalOptimizer()
    prev_res = opt.optimize(prev)

    broker = int(rng.integers(0, 4))
    factor = float(rng.uniform(1.5, 4.0))
    _drift_broker(reporter, broker, factor, limit=4)
    _roll_windows(cc, reporter, 3)
    state, delta = mon.cluster_model_delta(prev, mark)
    assert not delta.full and delta.dirty_partitions.any()

    goals = make_goals()
    cold = GoalOptimizer().optimize(state)
    fctx = AnalyzerContext(prev_res.final_state)
    warm = GoalOptimizer().optimize(state, warm_start=WarmStart(
        assignment=np.asarray(prev_res.final_state.assignment),
        leader_slot=np.asarray(prev_res.final_state.leader_slot),
        prev_actions=list(prev_res.actions),
        dirty_partitions=delta.dirty_partitions,
        prev_signatures=goal_input_signatures(fctx, goals),
        prev_violations=prev_res.violations_after,
    ))
    verify_result(state, warm, goals)
    s_cold = violation_score(cold.final_state, goals)
    s_warm = violation_score(warm.final_state, goals)
    assert s_warm <= _warm_score_tolerance(s_cold), (seed, s_warm, s_cold)


def test_budget_breach_falls_back_cold_bit_identically():
    """A delta beyond the dirty budget must produce EXACTLY the cold
    path's plan — the fallback is the cold path, not a degraded warm."""
    def build():
        cc, backend, reporter = full_stack(num_partitions=24, num_brokers=4)
        return cc, backend, reporter

    # replanner with a zero-ish budget: every delta breaches
    cc1, b1, r1 = build()
    cc1.replanner = DeltaReplanner(
        cc1.load_monitor,
        ReplanConfig(dirty_partition_budget_ratio=0.0001),
    )
    cc2, b2, r2 = build()

    for cc, reporter in ((cc1, r1), (cc2, r2)):
        cc.get_proposals(ignore_cache=True)
        _drift_broker(reporter, 0, 3.0)
        _roll_windows(cc, reporter, 3)
    p1 = cc1.get_proposals(ignore_cache=True)
    p2 = cc2.get_proposals(ignore_cache=True)
    assert cc1.replanner.last_mode == "cold"
    assert "dirty-budget-exceeded" in cc1.replanner.last_reason
    acts = lambda r: [
        (a.action_type, a.partition, a.slot, a.source_broker, a.dest_broker,
         a.dest_slot) for a in r.actions
    ]
    assert acts(p1) == acts(p2)
    assert [pr.to_json() for pr in p1.proposals] == [
        pr.to_json() for pr in p2.proposals
    ]


# ---- TPU engine: warm start + device carry ---------------------------------------
@pytest.mark.parametrize("small_repool_budget", [True, False])
def test_tpu_warm_carry_matches_carryless_warm(small_repool_budget):
    """The cross-plan pool-table carry is a pure diet: the warm plan with
    the carried device model + tables must equal the carry-less warm plan
    bit-for-bit (actions and final placement), whether the first repool
    runs the incremental refresh (budget < P) or the full rebuild."""
    from cruise_control_tpu.analyzer.tpu_optimizer import (
        TpuGoalOptimizer,
        TpuSearchConfig,
    )
    from cruise_control_tpu.models.generators import random_cluster

    state = random_cluster(seed=13, num_brokers=10, num_racks=5,
                           num_partitions=80)
    kwargs = dict(steps_per_call=16, repool_steps=4, device_batch_per_step=8,
                  max_rounds=40)
    if small_repool_budget:
        kwargs.update(repool_incremental=True, repool_rows_budget=24)
    cfg = TpuSearchConfig(**kwargs)

    goals = make_goals()
    carry = ReplanCarry()
    opt = TpuGoalOptimizer(config=cfg)
    prev = opt.optimize(state, carry=carry)
    assert carry.valid and carry.model is not None

    # drift: perturb the loads of every partition led by broker 0
    lead = np.asarray(state.leader_broker())
    dirty = lead == 0
    new_leader_load = np.asarray(state.leader_load).copy()
    new_leader_load[dirty] *= 1.7
    new_follower = new_leader_load.copy()
    from cruise_control_tpu.common.resources import (
        FOLLOWER_CPU_RATIO,
        Resource,
    )

    new_follower[:, Resource.NW_OUT] = 0.0
    new_follower[:, Resource.CPU] *= FOLLOWER_CPU_RATIO
    drifted = state.replace(
        leader_load=np.where(
            dirty[:, None], new_leader_load, np.asarray(state.leader_load)
        ),
        follower_load=np.where(
            dirty[:, None], new_follower, np.asarray(state.follower_load)
        ),
    )

    fctx = AnalyzerContext(prev.final_state)

    def warm_start():
        return WarmStart(
            assignment=np.asarray(prev.final_state.assignment),
            leader_slot=np.asarray(prev.final_state.leader_slot),
            prev_actions=list(prev.actions),
            dirty_partitions=dirty.copy(),
            prev_signatures=goal_input_signatures(fctx, goals),
            prev_violations=prev.violations_after,
        )

    with_carry = TpuGoalOptimizer(config=cfg).optimize(
        drifted, warm_start=warm_start(), carry=carry
    )
    without_carry = TpuGoalOptimizer(config=cfg).optimize(
        drifted, warm_start=warm_start()
    )
    acts = lambda r: [
        (a.action_type, a.partition, a.slot, a.source_broker, a.dest_broker,
         a.dest_slot) for a in r.actions
    ]
    assert acts(with_carry) == acts(without_carry)
    assert np.array_equal(
        np.asarray(with_carry.final_state.assignment),
        np.asarray(without_carry.final_state.assignment),
    )
    verify_result(drifted, with_carry, goals)
    # quality: warm stays inside the parity tolerance of cold
    cold = TpuGoalOptimizer(config=cfg).optimize(drifted)
    s_cold = violation_score(cold.final_state, goals)
    s_warm = violation_score(with_carry.final_state, goals)
    assert s_warm <= _warm_score_tolerance(s_cold), (s_warm, s_cold)


# ---- committed artifact ----------------------------------------------------------
def test_committed_replan_artifact_gates_hold():
    """REPLAN_r09.json must match its checked-in schema and show every
    gate green: settled replans ≥10× on every (engine, fixture) pair,
    absorb floors met, scores inside the parity tolerance, and the
    dirty-tracking overhead within ±1%.  Regenerate via
    ``PYTHONPATH=. python benchmarks/replan_bench.py --best-of 3
    --artifact REPLAN_r09.json``."""
    import json
    import pathlib

    from jsonschema import validate

    root = pathlib.Path(__file__).parent
    schemas = json.loads((root / "schemas" / "artifacts.schema.json")
                         .read_text())
    art = json.loads((root.parent / "REPLAN_r09.json").read_text())
    validate(art, schemas["cc-tpu-replan/1"])
    assert art["gates"]["pass"] is True
    names = {(f["engine"], f["name"]) for f in art["fixtures"]}
    assert {e for e, _ in names} == {"greedy", "tpu"}
    assert {n for _, n in names} == {
        "load_perturbation", "broker_removed", "broker_added"
    }
    for f in art["fixtures"]:
        assert f["mode"] == "warm", f
        assert f["settle_speedup"] >= 10.0, f
        assert f["settle_score_ok"] and f["absorb_score_ok"], f
    # one-sided like the bench gate: negative = tracking measured FREE
    # (interleaved best-of noise on a contended box)
    assert art["overhead"]["replan_overhead_pct"] <= 1.0


# ---- facade routing --------------------------------------------------------------
def test_facade_replan_journals_warm_and_serves_cache():
    cc, backend, reporter = full_stack(num_partitions=24, num_brokers=4)
    cc.replanner = DeltaReplanner(cc.load_monitor, ReplanConfig())
    events.configure(enabled=True)
    try:
        events.JOURNAL.recent()  # touch to ensure journal exists
        cc.get_proposals(ignore_cache=True)
        _drift_broker(reporter, 0, 2.5, limit=3)
        _roll_windows(cc, reporter, 3)
        assert not cc.proposal_cache_fresh()
        cc.get_proposals(ignore_cache=True)
        ends = [
            e["payload"] for e in events.JOURNAL.recent()
            if e["kind"] == "replan.end"
        ]
        assert ends[-1]["mode"] == "warm"
        assert ends[-1]["deltaModel"] is True
        assert ends[-1]["dirtyPartitions"] > 0
        assert cc.replanner.warm_plans == 1
        # the warm plan is now the fresh cached plan the server serves
        assert cc.proposal_cache_fresh()
        result, meta = cc.serve_proposals()
        assert meta["cached"] is True and meta["stale"] is False
    finally:
        events.configure(enabled=False)
        events.reset()


def test_zero_delta_short_circuit_serves_previous_plan():
    """A generation bump over a BIT-IDENTICAL model (every drift below
    the dirty threshold) re-validates the previous plan without an
    engine call — and the full-verify safety net disables that."""
    cc, backend, reporter = full_stack(num_partitions=24, num_brokers=4)
    cc.replanner = DeltaReplanner(cc.load_monitor, ReplanConfig())
    first = cc.get_proposals(ignore_cache=True)
    _roll_windows(cc, reporter, 3)  # stable workload: zero delta
    events.configure(enabled=True)
    try:
        second = cc.get_proposals(ignore_cache=True)
        assert second is first  # the very same result object — no search
        (end,) = [e["payload"] for e in events.JOURNAL.recent()
                  if e["kind"] == "replan.end"]
        assert end["mode"] == "warm" and end.get("shortCircuit") is True
        # the snapshot re-anchored at the new generation
        assert cc.replanner.snapshot.generation == \
            cc.load_monitor.model_generation()
        # safety net: full verify forces the engine to run
        cc.replanner.config.full_verify = True
        _roll_windows(cc, reporter, 5)
        third = cc.get_proposals(ignore_cache=True)
        assert third is not second
    finally:
        events.configure(enabled=False)
        events.reset()


def test_facade_warm_failure_falls_back_cold(monkeypatch):
    cc, backend, reporter = full_stack(num_partitions=24, num_brokers=4)
    cc.replanner = DeltaReplanner(cc.load_monitor, ReplanConfig())
    cc.get_proposals(ignore_cache=True)
    _drift_broker(reporter, 1, 2.5, limit=3)
    _roll_windows(cc, reporter, 3)

    real = GoalOptimizer.optimize
    calls = {"warm": 0}

    def boom(self, state, options=None, warm_start=None, carry=None):
        if warm_start is not None:
            calls["warm"] += 1
            raise RuntimeError("scripted warm failure")
        return real(self, state, options)

    monkeypatch.setattr(GoalOptimizer, "optimize", boom)
    events.configure(enabled=True)
    try:
        res = cc.get_proposals(ignore_cache=True)
        assert calls["warm"] == 1
        assert res is not None
        assert cc.replanner.last_mode == "cold"
        assert cc.replanner.last_reason == "warm-failed"
        kinds = [e["kind"] for e in events.JOURNAL.recent()]
        assert "replan.warm_failed" in kinds
        # the replan state was reset — the NEXT plan rebuilds a snapshot
        assert cc.replanner.snapshot is not None  # committed by fallback
    finally:
        events.configure(enabled=False)
        events.reset()
