"""ops/ kernels: grid scorer must match the columnar scorer bit-for-bit."""

import jax.numpy as jnp
import numpy as np
import pytest

from cruise_control_tpu.analyzer.context import AnalyzerContext
from cruise_control_tpu.analyzer.tpu_optimizer import (
    KIND_MOVE,
    TpuGoalOptimizer,
    TpuSearchConfig,
    _build_round_candidates,
    _score_candidates,
)
from cruise_control_tpu.models.generators import random_cluster
from cruise_control_tpu.ops import move_grid_scores


def _setup(seed=3, brokers=12, racks=4, partitions=48, **kw):
    state = random_cluster(
        seed=seed, num_brokers=brokers, num_racks=racks,
        num_partitions=partitions, **kw,
    )
    opt = TpuGoalOptimizer(config=TpuSearchConfig())
    ctx = AnalyzerContext(state)
    m = opt._device_model(ctx)
    ca = opt._constraint_arrays(ctx)
    return opt, ctx, m, ca


@pytest.mark.parametrize("seed", [3, 11])
def test_grid_matches_columnar(seed):
    opt, ctx, m, ca = _setup(seed=seed)
    K, D = opt._pool_sizes(ctx.num_partitions, ctx.max_rf, ctx.num_brokers)
    kind, cp, cs, cd = _build_round_candidates(m, ca, K, D)
    n_moves = K * D
    col_scores, _ = _score_candidates(
        m, opt.config, ca, kind[:n_moves], cp[:n_moves], cs[:n_moves], cd[:n_moves]
    )
    kp = cp[:n_moves:D]
    ks = cs[:n_moves:D]
    dest_pool = cd[:D]
    grid = move_grid_scores(m, opt.config, ca, kp, ks, dest_pool)
    got = np.asarray(grid).reshape(-1)
    want = np.asarray(col_scores)
    same_inf = np.isinf(got) == np.isinf(want)
    assert same_inf.all()
    finite = ~np.isinf(want)
    np.testing.assert_allclose(got[finite], want[finite], rtol=1e-5, atol=1e-6)


def test_grid_matches_columnar_with_dead_broker():
    opt, ctx, m, ca = _setup(seed=5, brokers=10, racks=5, partitions=40)
    # padding dest (-1) must be rejected, matching columnar's dst>=0 rule
    K, D = opt._pool_sizes(ctx.num_partitions, ctx.max_rf, ctx.num_brokers)
    kind, cp, cs, cd = _build_round_candidates(m, ca, K, D)
    kp, ks = cp[: K * D : D], cs[: K * D : D]
    dest = jnp.concatenate([cd[: D - 1], jnp.array([-1], jnp.int32)])
    grid = np.asarray(move_grid_scores(m, opt.config, ca, kp, ks, dest))
    assert np.isinf(grid[:, -1]).all()


