"""Scenario suite (ISSUE 6): scripted fault timelines driven through the
REAL monitor → detector → analyzer → executor loop on a virtual clock.

The ground-truth contract: every heal-outcome assertion reads ONLY the
event journal captured by the run — :class:`ScenarioResult`'s helpers are
pure journal readers (no peeking at backend or manager state), so a
scenario passing here proves the system's *recorded decisions* tell the
true story, which is what an operator reconstructing an incident has.

Tier-1 runs the SMOKE subset plus the determinism and artifact contracts;
the full ≥10-scenario matrix is ``slow`` (the committed
``SCENARIOS_r12.json`` artifact keeps its outcomes honest in every run).
The crash/resume scenarios (ISSUE 7) prove — from the journal alone —
that a process crash mid-execution resumes without re-moving completed
partitions.
"""

import json
import pathlib

import numpy as np
import pytest

from cruise_control_tpu.models.generators import random_cluster
from cruise_control_tpu.sim import (
    SCENARIOS,
    SMOKE_SCENARIOS,
    make_artifact,
    make_scenario,
    run_scenario,
)
from cruise_control_tpu.sim.simulator import MIN_MS, ScenarioSpec
from cruise_control_tpu.sim.timeline import (
    Timeline,
    disk_failure,
    hot_partition_skew,
    restore_disk,
)
from test_artifact_schemas import SCHEMAS, validate

MIN = MIN_MS
ARTIFACT_PATH = pathlib.Path(__file__).parent.parent / "SCENARIOS_r16.json"

#: the outcome each scripted timeline must reach — also pinned against the
#: committed artifact below, so a regression shows up in tier-1 without
#: re-running the slow matrix
EXPECTED_OUTCOMES = {
    "broker_death_mid_execution": "HEALED",
    "rack_loss": "HEALED",
    "cascading_disk_failures": "HEALED",
    "hot_partition_skew_violation": "HEALED",
    "anomaly_during_cooldown": "HEALED",
    "maintenance_suppresses_self_heal": "HEALED",
    "detection_during_metric_gap": "HEALED",
    "add_broker_rebalance": "HEALED",
    "double_fault": "HEALED",
    "recovery_then_relapse": "HEALED",
    "metric_anomaly_alert_only": "ALERT_ONLY",
    "stalled_execution_retries": "HEALED",
    "crash_resume_mid_execution": "HEALED",
    "crash_completes_while_down": "HEALED",
    "crash_recovery_replans_dead_destination": "HEALED",
    "flapping_destination_retries": "HEALED",
    "degraded_serving_survives_analyzer_outage": "NO_ANOMALY",
    "request_storm_sheds_with_retry_after": "NO_ANOMALY",
    "slow_loris_connection_reaped": "NO_ANOMALY",
    "crash_mid_request_recovers_front_door": "HEALED",
    "warm_replan_after_drift": "HEALED",
    "warm_replan_after_add_broker": "HEALED",
    "slo_observatory": "HEALED",
    "poisoned_metrics_quarantined_then_healed": "HEALED",
    "checkpoint_bitflip_recovers_loudly": "HEALED",
    "engine_failure_degrades_to_greedy": "HEALED",
    "foreign_reassignment_tolerated": "HEALED",
    "foreign_conflict_yield_retries": "HEALED",
    "zombie_controller_fenced": "HEALED",
    "topology_drift_mid_execution": "HEALED",
    "proactive_beats_reactive_peak": "NO_ANOMALY",
}

_cache = {}


def result_for(name):
    """Run each scenario once per test session (results are reused by the
    per-scenario assertion, the determinism test, and the artifact test)."""
    if name not in _cache:
        _cache[name] = run_scenario(make_scenario(name))
    return _cache[name]


# ---- per-scenario journal assertions --------------------------------------------
def _check_broker_death_mid_execution(r):
    # the kill stranded in-flight moves: timeout DEADs in the first
    # execution, a clean retry at the end
    assert any(e["payload"].get("reason") == "timeout"
               for e in r.events_of("executor.task_dead"))
    assert r.dead_tasks() > 0
    assert r.executions()[0]["dead"] > 0
    assert r.executions()[-1]["dead"] == 0
    assert r.fixes_started("BROKER_FAILURE")


def _check_rack_loss(r):
    (fix,) = r.fixes_started("BROKER_FAILURE")  # one anomaly, whole rack
    assert "2" in fix["description"] and "5" in fix["description"]
    # heal latency gated through the SLO engine (ISSUE 11): the same
    # journal-order fault→fix samples the soak will consume, instead of
    # an ad-hoc detection-latency read
    rep = r.slo_report(objectives={"heal.latency.p99.ms": 2.0 * MIN,
                                   "heal.latency.p50.ms": 2.0 * MIN})
    assert rep.slo("heal.latency.p99.ms").ok is True
    assert rep.slo("heal.latency.p50.ms").ok is True
    assert r.heal_latency_percentiles()[99] <= 2 * MIN
    # the evacuated brokers never re-trigger (hosting set empty)
    assert not [p for p in r.anomalies("BROKER_FAILURE")
                if p["timeMs"] > fix["timeMs"]]
    assert r.actions_executed() > 0


def _check_cascading_disk_failures(r):
    fixes = r.fixes_started("DISK_FAILURE")
    assert len(fixes) >= 2
    b1 = [p["timeMs"] for p in fixes if "{1:" in p["description"]]
    b4 = [p["timeMs"] for p in fixes if "{4:" in p["description"]]
    assert b1 and b4 and min(b1) < min(b4)  # a cascade, not one batch
    assert r.actions_executed() > 0
    # both heals gated through the SLO engine: two samples (one per
    # cascade stage), the p99 covering the second fault's full wait
    rep = r.slo_report()
    assert rep.slo("heal.latency.p99.ms").ok is True
    pcts = r.heal_latency_percentiles()
    # the second stage waited out the first heal's cooldown, so the tail
    # is strictly slower than the median — visible from the SLO samples
    assert pcts[50] < pcts[99]


def _check_hot_partition_skew_violation(r):
    assert r.fixes_started("GOAL_VIOLATION")
    assert r.detection_latency_ms("GOAL_VIOLATION") is not None
    # healed for good: the last stretch of the run is violation-quiet
    assert not [p for p in r.anomalies("GOAL_VIOLATION")
                if p["timeMs"] > r.duration_virtual_ms - 4 * MIN]
    assert r.actions_executed() > 0


def _check_anomaly_during_cooldown(r):
    delayed = r.anomalies("DISK_FAILURE", action="FIX_DELAYED_COOLDOWN")
    assert delayed
    first_fix = min(p["timeMs"] for p in r.fixes_started("DISK_FAILURE"))
    b4_fix = [p["timeMs"] for p in r.fixes_started("DISK_FAILURE")
              if "{4:" in p["description"]]
    # the second fault's fix waited out the whole cooldown window
    assert b4_fix and min(b4_fix) >= first_fix + 6 * MIN
    assert min(p["timeMs"] for p in delayed) < min(b4_fix)


def _check_maintenance_suppresses_self_heal(r):
    (mfix,) = r.fixes_started("MAINTENANCE_EVENT")
    delayed = r.anomalies("GOAL_VIOLATION", action="FIX_DELAYED_COOLDOWN")
    # suppressed in the SAME cycle the maintenance fix ran
    assert delayed and min(p["timeMs"] for p in delayed) == mfix["timeMs"]
    # and the journal order shows priority: maintenance decided first
    kinds = [e["payload"]["anomalyType"] for e in
             r.events_of("detector.anomaly")
             if e["payload"]["anomalyType"] in ("MAINTENANCE_EVENT",
                                                "GOAL_VIOLATION")]
    assert kinds.index("MAINTENANCE_EVENT") < kinds.index("GOAL_VIOLATION")


def _check_detection_during_metric_gap(r):
    gv = r.anomalies("GOAL_VIOLATION")
    assert gv and r.fixes_started("GOAL_VIOLATION")
    # blind while the pipeline was dark: no decision before the gap closed
    gap_end = 14 * MIN
    assert all(p["timeMs"] >= gap_end for p in gv)
    assert r.detection_latency_ms("GOAL_VIOLATION") >= 8 * MIN


def _check_add_broker_rebalance(r):
    assert r.fixes_started("MAINTENANCE_EVENT")
    assert any(e.get("operation") == "ADD_BROKER"
               for e in r.events_of("optimize.start"))
    assert r.actions_executed() > 0


def _check_double_fault(r):
    bfix = r.fixes_started("BROKER_FAILURE")
    dfix = r.fixes_started("DISK_FAILURE")
    assert bfix and dfix
    # priority order: broker failure (1) healed before disk failure (2)
    assert min(p["timeMs"] for p in bfix) <= min(p["timeMs"] for p in dfix)
    assert r.anomalies("DISK_FAILURE", action="FIX_DELAYED_COOLDOWN")


def _check_recovery_then_relapse(r):
    bf = r.anomalies("BROKER_FAILURE")
    fixes = [p for p in bf if p["fixStarted"]]
    # no premature heal: the fix threshold counts from the SECOND failure
    assert fixes and min(p["timeMs"] for p in fixes) >= 20 * MIN
    assert any(p["action"] == "CHECK" for p in bf)
    # the recovered window is decision-free (first-seen was cleared)
    assert not [p for p in bf if 9 * MIN <= p["timeMs"] < 14 * MIN]


def _check_metric_anomaly_alert_only(r):
    ma = r.anomalies("METRIC_ANOMALY")
    assert ma
    assert all(p["action"] == "IGNORE" for p in ma)
    assert not any(p["fixStarted"] for p in ma)
    assert any("broker 2" in p["description"] for p in ma)
    assert r.actions_executed() == 0


def _check_stalled_execution_retries(r):
    assert any(e["payload"].get("reason") == "timeout"
               for e in r.events_of("executor.task_dead"))
    assert r.executions()[0]["dead"] > 0
    assert r.executions()[-1]["dead"] == 0
    assert not [p for p in r.anomalies("GOAL_VIOLATION")
                if p["timeMs"] > r.duration_virtual_ms - 4 * MIN]


# ---- crash-safe execution (ISSUE 7): journal-only crash/resume proofs ----------
def _post_resume_replica_moves(r):
    """Partitions dispatched in replica-move batches AFTER the resume —
    the set that must not intersect what the checkpoint already finished."""
    seen_resume = False
    moved = set()
    for e in r.journal:
        if e["kind"] == "executor.resume":
            seen_resume = True
        elif seen_resume and e["kind"] == "executor.batch":
            p = e.get("payload", {})
            if p.get("phase") == "replica_moves":
                moved |= set(p.get("partitions", ()))
    return moved


def _check_crash_resume_mid_execution(r):
    assert len(r.events_of("sim.crash")) == 1
    (resume,) = r.resume_summaries()
    done_before = set(resume["alreadyCompleted"]) \
        | set(resume["completedWhileDown"])
    # the crash landed mid-execution: some moves durably done, some not
    assert done_before and (resume["reissued"] or resume["adopted"])
    # THE acceptance criterion: zero already-completed partitions re-moved
    assert not (_post_resume_replica_moves(r) & done_before)
    (recovery,) = r.recoveries()
    assert recovery["outcome"] == "resumed" and recovery["succeeded"]
    # the recovered execution claims the self-healing cooldown (no
    # double-fire during/after recovery)
    assert r.events_of("detector.recovery_cooldown")
    # healed for good: the tail of the run is violation-quiet
    assert not [p for p in r.anomalies("GOAL_VIOLATION")
                if p["timeMs"] > r.duration_virtual_ms - 4 * MIN]


def _check_crash_completes_while_down(r):
    (resume,) = r.resume_summaries()
    # every replica move finished while the controller was down...
    assert resume["completedWhileDown"]
    assert not resume["reissued"] and not resume["replanned"]
    # ...so the resumed execution issues zero new replica batches
    assert not _post_resume_replica_moves(r)
    (recovery,) = r.recoveries()
    assert recovery["succeeded"] and recovery["ticks"] == 0


def _check_crash_recovery_replans_dead_destination(r):
    (resume,) = r.resume_summaries()
    assert resume["replanned"]  # vanished destination re-planned
    replans = [e["payload"] for e in r.events_of("executor.task_replanned")]
    assert replans and all(p["newReplicas"] for p in replans)
    (recovery,) = r.recoveries()
    assert recovery["outcome"] == "resumed" and recovery["succeeded"]
    # the corpse is detected and evacuated by the broker-failure heal
    assert r.fixes_started("BROKER_FAILURE")
    assert r.dead_tasks() == 0


def _check_flapping_destination_retries(r):
    retries = [e["payload"] for e in r.events_of("executor.task_retry")]
    assert retries
    assert all(p["reason"] == "timeout" and p["attempt"] >= 1
               for p in retries)
    assert all(p["backoffTicks"] >= 1 for p in retries)
    # the retries did their job: every drive ends with zero dead tasks
    assert r.executor_ends() and all(
        p.get("dead") == 0 for p in r.executor_ends()
    )
    assert not [e for e in r.events_of("executor.task_dead")
                if e["payload"].get("reason") == "timeout"]


# ---- overload-safe serving (ISSUE 8): journal-only front-door proofs -----------
def _check_degraded_serving_survives_analyzer_outage(r):
    reqs = r.http_responses("proposals")
    # every proposals request answered 200 across the whole outage —
    # degraded, never broken
    assert [p["status"] for p in reqs] == [200, 200, 200, 200]
    assert [bool(p["stale"]) for p in reqs] == [False, True, True, False]
    # the breaker's full story, read from the journal alone:
    # trip → half-open probe → close
    assert [p["state"] for p in r.breaker_transitions()] == \
        ["OPEN", "HALF_OPEN", "CLOSED"]
    assert r.events_of("proposals.served_stale")
    # scripted analyzer failures are on the record (the why of the trip)
    assert any("scripted analyzer outage" in str(e["payload"].get("error"))
               for e in r.events_of("optimize.failed"))
    assert r.http_responses("health")[-1]["ready"] is True


def _check_request_storm_sheds_with_retry_after(r):
    get_storm, post_storm = r.storms()
    for storm in (get_storm, post_storm):
        # THE shedding contract: overflow is shed with Retry-After, the
        # admitted requests complete, nothing 5xxes
        assert storm["admitted"] >= 1
        assert storm["shedWithRetryAfter"] > 0
        assert storm["shedMissingRetryAfter"] == 0
        assert storm["unhandled5xx"] == 0
    assert get_storm["clients"] == 16 and post_storm["clients"] == 8
    # server-side shed decisions are journaled too
    assert r.events_of("http.request_shed")
    # and the front door stays healthy afterwards
    assert r.http_responses("health")[-1]["ready"] is True


def _check_slow_loris_connection_reaped(r):
    (probe,) = [e["payload"] for e in r.events_of("sim.http_slow_client")]
    assert probe["closed"] is True
    # a normal request issued alongside the loris is served untouched
    (state_req,) = r.http_responses("state")
    assert state_req["status"] == 200
    assert r.http_responses("health")[-1]["ready"] is True


def _check_crash_mid_request_recovers_front_door(r):
    (req,) = r.http_responses("rebalance")
    # the crashed request fails EXPLICITLY (500 naming the crash), not by
    # hanging the client forever
    assert req["status"] == 500 and "ProcessCrash" in str(req["error"])
    assert len(r.events_of("sim.crash")) == 1
    # the front door is dark while the process is down, ready again after
    # the restart's checkpoint recovery
    health = r.http_responses("health")
    assert [p["status"] for p in health] == [0, 200]
    assert health[-1]["ready"] is True
    (recovery,) = r.recoveries()
    assert recovery["outcome"] == "resumed" and recovery["succeeded"]


# ---- incremental re-optimization (delta replan, ISSUE 9) -------------------------
def _check_warm_replan_after_drift(r):
    """The journal alone proves the refresh after the drift served WARM:
    the first replan that saw the drifted windows took the delta path
    (dirty partitions marked, delta model build), no refresh between the
    drift and the heal cold-recomputed, and the violation healed."""
    after = r.replans_after_fault("perturb_broker_load")
    assert after, "no replans after the drift fault"
    absorbing = [p for p in after if p.get("dirtyPartitions", 0) > 0]
    assert absorbing, "no replan ever saw the drifted windows"
    first = absorbing[0]
    assert first["mode"] == "warm" and first["deltaModel"] is True
    # the whole steady state stays warm: after the cold bootstrap plan,
    # every routed refresh — including post-drift and post-heal — served
    # from the delta path (the dirty set may also carry the heal's
    # topology rows when the fix lands between refreshes)
    assert [p["mode"] for p in r.replans()].count("cold") == 1
    assert r.fixes_started("GOAL_VIOLATION")
    assert r.actions_executed() > 0


def _check_warm_replan_after_add_broker(r):
    """Broker-axis growth stays on the delta path: the refreshes after
    the add are warm with deltaModel=True (the model was patched, not
    rebuilt), and the maintenance fix moves replicas onto the newcomer."""
    after = r.replans_after_fault("add_broker")
    assert after, "no replans after the broker add"
    assert after[0]["mode"] == "warm" and after[0]["deltaModel"] is True
    assert [p["mode"] for p in r.replans()].count("cold") == 1
    assert r.fixes_started("MAINTENANCE_EVENT")
    assert r.actions_executed() > 0


# ---- the SLO observatory (ISSUE 11): the journal yields the gate table ---------
def _check_slo_observatory(r):
    """The acceptance criterion: one scenario's journal alone produces a
    valid ``cc-tpu-slo/1`` artifact whose gate table carries heal-latency
    p99, serve p99, warm-replan duty cycle, and zero-5xx — all green.
    Wall-clock serve objectives are relaxed (virtual-clock runs measure
    real request latency on a contended test box); the virtual-clock and
    counting gates hold at their production defaults."""
    from cruise_control_tpu.sim import make_slo_artifact

    art = json.loads(json.dumps(make_slo_artifact(r, objectives={
        "serve.cached_get.p99.ms": 2000.0,
        "serve.compute.p99.ms": 60000.0,
    })))
    validate(art, SCHEMAS["cc-tpu-slo/1"])
    gates = {row["name"]: row for row in art["slos"]}
    for required in ("heal.latency.p99.ms", "serve.cached_get.p99.ms",
                     "serve.compute.p99.ms", "replan.warm.duty.cycle",
                     "http.unhandled.5xx"):
        assert gates[required]["measured"] is not None, required
        assert gates[required]["ok"] is True, required
    assert art["summary"]["allOk"] is True
    assert art["scenario"]["name"] == "slo_observatory"
    # the drift was healed through the warm-replan steady state: one cold
    # bootstrap plan, everything after warm — the duty cycle the gate saw
    assert gates["replan.warm.duty.cycle"]["measured"] >= 0.75
    assert [p["mode"] for p in r.replans()].count("cold") == 1
    assert r.fixes_started("GOAL_VIOLATION")
    # trace correlation reached the journal: the scripted requests'
    # deterministic ids ride the replan/optimize records they caused
    assert any(e.get("traceId", "").startswith("sim-trace-")
               for e in r.journal)


# ---- data-integrity hardening (ISSUE 13): journal-only byzantine proofs --------
def _check_poisoned_metrics_quarantined_then_healed(r):
    """The journal alone proves the quarantine story: poisoned samples
    were rejected (counted, attributed), the persistently-bad broker
    surfaced as a storm anomaly, NOTHING NaN-shaped broke an
    optimization, and the real skew healed on clean data."""
    q = [e["payload"] for e in r.events_of("monitor.sample_quarantined")]
    assert q, "no quarantine events — the poison was swallowed silently"
    assert all(p["reasons"].get("non-finite", 0) >= 1 for p in q)
    assert any(p["reasons"].get("unknown-broker", 0) >= 1 for p in q)
    assert all(1 in p["brokers"] for p in q)
    # quarantine is bounded to the poison window: none in the tail
    last_q = max(e["ts"] for e in r.events_of("monitor.sample_quarantined"))
    assert last_q * 1000 <= 11 * MIN
    # the storm finding: broker 1's persistent badness IS an anomaly,
    # alert-only (no automatic fix for data gone dark)
    storms = [p for p in r.anomalies("METRIC_ANOMALY")
              if "sample.quarantine.ratio" in p["description"]]
    assert storms and all(p["action"] == "IGNORE" for p in storms)
    assert any("broker 1 " in p["description"] for p in storms)
    # the REAL fault healed on clean data; no optimization ever failed
    assert r.fixes_started("GOAL_VIOLATION")
    assert not r.events_of("optimize.failed")
    assert not r.events_of("analyzer.plan_rejected")
    assert r.actions_executed() > 0
    # the quarantine SLO holds over the whole run (in-storm ratio is the
    # journal-mode measurement — bounded, not runaway)
    rep = r.slo_report(objectives={
        "monitor.sample.quarantine.ratio": 0.25})
    assert rep.slo("monitor.sample.quarantine.ratio").ok is True


def _check_checkpoint_bitflip_recovers_loudly(r):
    (corrupt,) = r.events_of("executor.checkpoint_corrupt")
    assert corrupt["severity"] == "ERROR"
    assert corrupt["payload"]["line"] == 1
    assert corrupt["payload"]["dropped"] >= 2  # mid-file, not torn tail
    # LOUD and ordered: corruption detected before recovery adopted it
    idx = {e["kind"]: i for i, e in reversed(list(enumerate(r.journal)))}
    assert idx["executor.checkpoint_corrupt"] < \
        idx["execution.recovery.start"]
    (recovery,) = r.recoveries()
    assert recovery["outcome"] == "resumed" and recovery["succeeded"]
    # reconciliation re-derived everything the corruption dropped from
    # LIVE state: moves finished while down were adopted, not re-moved
    (resume,) = r.resume_summaries()
    assert resume["completedWhileDown"] or resume["alreadyCompleted"]
    assert r.dead_tasks() == 0
    assert not [p for p in r.anomalies("GOAL_VIOLATION")
                if p["timeMs"] > r.duration_virtual_ms - 4 * MIN]


def _check_engine_failure_degrades_to_greedy(r):
    (deg,) = r.events_of("analyzer.engine_degraded")
    assert deg["payload"]["engine"] == "tpu"
    assert deg["payload"]["fallback"] == "greedy"
    assert "RESOURCE_EXHAUSTED" in deg["payload"]["error"]
    # containment: the failed TPU attempt cost ONE journal line, not a
    # failed heal — every optimization end is a greedy success and no
    # operation ever failed
    ends = [e["payload"]["engine"] for e in r.events_of("optimize.end")]
    assert ends and all(e == "greedy" for e in ends)
    assert not r.events_of("optimize.failed")
    # inside the cooldown further operations skip TPU entirely (exactly
    # one degradation for the whole run)
    assert r.fixes_started("GOAL_VIOLATION")
    assert r.actions_executed() > 0
    assert not r.events_of("analyzer.engine_recovered")


# ---- concurrent-controller safety (ISSUE 15) ------------------------------------
def _check_foreign_reassignment_tolerated(r):
    foreign = [e["payload"]
               for e in r.events_of("executor.foreign_reassignment")]
    assert foreign and all(not f["conflict"] for f in foreign)
    assert foreign[0]["origin"] == "mid-flight"
    # tolerated: the plan completed untouched, nothing died or aborted
    ends = r.executor_ends()
    assert ends[0].get("topologyDrift", {}).get("foreignObserved", 0) >= 1
    assert r.dead_tasks() == 0
    assert all(e["aborted"] == 0 for e in ends)
    assert not r.events_of("executor.fenced")


def _check_foreign_conflict_yield_retries(r):
    foreign = [e["payload"]
               for e in r.events_of("executor.foreign_reassignment")]
    assert any(f["conflict"] and f["policy"] == "yield" for f in foreign)
    retries = [e["payload"] for e in r.events_of("executor.task_retry")]
    assert any(p["reason"] == "foreign-conflict" for p in retries)
    # yielded, retried, converged: zero dead tasks, zero aborted moves,
    # the first execution's end carries the conflict tally
    ends = r.executor_ends()
    assert ends[0].get("topologyDrift", {}).get("foreignConflict", 0) >= 1
    assert r.dead_tasks() == 0
    assert all(e["aborted"] == 0 for e in ends)


def _check_zombie_controller_fenced(r):
    (fenced,) = r.events_of("executor.fenced")
    assert fenced["severity"] == "ERROR"
    assert fenced["payload"]["op"] == "claim"
    assert fenced["payload"]["presentedEpoch"] < \
        fenced["payload"]["clusterEpoch"]
    # the sim's zombie record agrees: refused, not resumed
    (zombie,) = [e["payload"] for e in r.events_of("sim.fault")
                 if e["payload"].get("fault") == "zombie_controller_resume"]
    assert zombie["zombie"] == "fenced"
    # the LIVE controller's recovery stands: resumed and completed
    (recovery,) = r.recoveries()
    assert recovery["outcome"] == "resumed" and recovery["succeeded"]
    # ordered: the zombie refusal comes after the live recovery finished
    idx = {e["kind"]: i for i, e in enumerate(r.journal)}
    assert idx["execution.recovery.end"] < idx["executor.fenced"]
    assert r.dead_tasks() == 0


def _check_topology_drift_mid_execution(r):
    drift = [e["payload"] for e in r.events_of("executor.topology_drift")]
    assert drift and all(
        d["reason"] == "topology-drift:deleted" for d in drift
    )
    # partial-graceful: the categorical cancels never burned the retry
    # budget (zero DEAD tasks, zero executor.task_retry on drift)
    ends = r.executor_ends()
    assert ends[0].get("topologyDrift", {}).get("deleted", 0) >= 1
    assert r.dead_tasks() == 0
    assert not [e for e in r.events_of("executor.task_retry")
                if e["payload"]["reason"].startswith("topology-drift")]
    # the monitor absorbed both the shrink and the later growth: no
    # detector ever failed a cycle on the drifted universe
    assert not r.events_of("detector.detect_failed")
    assert r.fixes_started("GOAL_VIOLATION")


def _check_proactive_beats_reactive_peak(r):
    # the full forecast-driven chain, in journal order: diurnal fit →
    # what-if verdict on the projected-peak future → pre-emptive
    # rebalance — all BEFORE the peak the forecast called out
    (fc,) = r.events_of("proactive.forecast")
    assert fc["payload"]["peakMultiplier"] > 1.1
    peak_s = fc["ts"] + fc["payload"]["peakInMs"] / 1000.0
    (trig,) = r.events_of("proactive.trigger")
    assert trig["payload"]["reason"] == "projected-goal-violation"
    assert trig["payload"]["overloadedBrokers"] >= 1
    (req,) = r.events_of("whatif.request")
    (ev,) = r.events_of("whatif.evaluated")
    assert ev["payload"]["violations"] >= 1
    assert req["ts"] <= trig["ts"] < peak_s
    ends = r.executor_ends()
    assert len(ends) == 1 and ends[0]["completed"] > 0
    # the point of the scenario: the detector never saw a violation —
    # the rebalance landed while current load was still legal (the
    # reactive twin with proactive off heals this same swell only
    # after a CpuCapacityGoal breach)
    assert not r.events_of("detector.anomaly")
    assert r.fixes_started("GOAL_VIOLATION") == []


CHECKS = {
    "broker_death_mid_execution": _check_broker_death_mid_execution,
    "rack_loss": _check_rack_loss,
    "cascading_disk_failures": _check_cascading_disk_failures,
    "hot_partition_skew_violation": _check_hot_partition_skew_violation,
    "anomaly_during_cooldown": _check_anomaly_during_cooldown,
    "maintenance_suppresses_self_heal":
        _check_maintenance_suppresses_self_heal,
    "detection_during_metric_gap": _check_detection_during_metric_gap,
    "add_broker_rebalance": _check_add_broker_rebalance,
    "double_fault": _check_double_fault,
    "recovery_then_relapse": _check_recovery_then_relapse,
    "metric_anomaly_alert_only": _check_metric_anomaly_alert_only,
    "stalled_execution_retries": _check_stalled_execution_retries,
    "crash_resume_mid_execution": _check_crash_resume_mid_execution,
    "crash_completes_while_down": _check_crash_completes_while_down,
    "crash_recovery_replans_dead_destination":
        _check_crash_recovery_replans_dead_destination,
    "flapping_destination_retries": _check_flapping_destination_retries,
    "degraded_serving_survives_analyzer_outage":
        _check_degraded_serving_survives_analyzer_outage,
    "request_storm_sheds_with_retry_after":
        _check_request_storm_sheds_with_retry_after,
    "slow_loris_connection_reaped": _check_slow_loris_connection_reaped,
    "crash_mid_request_recovers_front_door":
        _check_crash_mid_request_recovers_front_door,
    "warm_replan_after_drift": _check_warm_replan_after_drift,
    "warm_replan_after_add_broker": _check_warm_replan_after_add_broker,
    "slo_observatory": _check_slo_observatory,
    "poisoned_metrics_quarantined_then_healed":
        _check_poisoned_metrics_quarantined_then_healed,
    "checkpoint_bitflip_recovers_loudly":
        _check_checkpoint_bitflip_recovers_loudly,
    "engine_failure_degrades_to_greedy":
        _check_engine_failure_degrades_to_greedy,
    "foreign_reassignment_tolerated": _check_foreign_reassignment_tolerated,
    "foreign_conflict_yield_retries": _check_foreign_conflict_yield_retries,
    "zombie_controller_fenced": _check_zombie_controller_fenced,
    "topology_drift_mid_execution": _check_topology_drift_mid_execution,
    "proactive_beats_reactive_peak": _check_proactive_beats_reactive_peak,
}


def _params():
    return [
        pytest.param(
            name,
            marks=() if name in SMOKE_SCENARIOS else (pytest.mark.slow,),
        )
        for name in sorted(SCENARIOS)
    ]


@pytest.mark.parametrize("name", _params())
def test_scenario_heals_as_scripted(name):
    r = result_for(name)
    assert r.heal_outcome() == EXPECTED_OUTCOMES[name], (
        f"{name}: journal says {r.heal_outcome()}, expected "
        f"{EXPECTED_OUTCOMES[name]}"
    )
    CHECKS[name](r)


# ---- suite-level contracts ------------------------------------------------------
def test_registry_shape():
    assert len(SCENARIOS) >= 10
    assert set(SCENARIOS) == set(EXPECTED_OUTCOMES) == set(CHECKS)
    for name, factory in SCENARIOS.items():
        spec = factory()
        assert spec.name == name
        assert len(spec.timeline) >= 1
        assert spec.timeline.end_ms < spec.duration_ms
        assert spec.description


def test_same_seed_same_journal():
    """The determinism contract: a scenario re-run yields a bit-identical
    journal modulo wall-clock fields; a different seed does not."""
    name = SMOKE_SCENARIOS[0]
    first = result_for(name)
    again = run_scenario(make_scenario(name))
    assert first.fingerprint() == again.fingerprint()
    reseeded = run_scenario(make_scenario(name, seed=first.spec.seed + 1))
    assert first.fingerprint() != reseeded.fingerprint()


def test_journal_is_the_only_ground_truth():
    """ScenarioResult helpers must work from the journal records alone —
    rebuilding the result from a JSON round-trip of the journal yields the
    same derived facts."""
    from cruise_control_tpu.sim.simulator import ScenarioResult

    r = result_for(SMOKE_SCENARIOS[0])
    clone = ScenarioResult(
        spec=r.spec,
        journal=json.loads(json.dumps(r.journal, default=str)),
        ticks=r.ticks,
        duration_virtual_ms=r.duration_virtual_ms,
    )
    assert clone.heal_outcome() == r.heal_outcome()
    assert clone.detection_latency_ms() == r.detection_latency_ms()
    assert clone.actions_executed() == r.actions_executed()
    assert clone.fingerprint() == r.fingerprint()


def test_detector_events_carry_virtual_time():
    r = result_for(SMOKE_SCENARIOS[0])
    decisions = r.events_of("detector.anomaly")
    assert decisions
    tick = r.spec.tick_ms
    for e in decisions:
        t = e["payload"]["timeMs"]
        assert 0 < t <= r.duration_virtual_ms and t % tick == 0


# ---- artifact contracts ---------------------------------------------------------
def test_live_artifact_matches_schema():
    results = [result_for(n) for n in SMOKE_SCENARIOS]
    art = json.loads(json.dumps(make_artifact(results)))
    validate(art, SCHEMAS["cc-tpu-scenarios/1"])
    assert art["summary"]["numScenarios"] == len(SMOKE_SCENARIOS)


def test_committed_artifact_is_current():
    """SCENARIOS_r10.json (the CLI's output) must cover the whole registry
    with the expected heal outcomes — regenerate it via
    ``python -m cruise_control_tpu.sim --artifact SCENARIOS_r10.json``
    whenever scenarios change."""
    art = json.loads(ARTIFACT_PATH.read_text())
    validate(art, SCHEMAS["cc-tpu-scenarios/1"])
    by_name = {s["name"]: s for s in art["scenarios"]}
    assert set(by_name) == set(SCENARIOS)
    for name, expected in EXPECTED_OUTCOMES.items():
        assert by_name[name]["healOutcome"] == expected, (
            f"{name}: committed artifact says "
            f"{by_name[name]['healOutcome']}, expected {expected}"
        )
        assert by_name[name]["journalEvents"] > 0


def test_smoke_scenarios_match_committed_artifact():
    """The determinism teeth: a smoke scenario re-run today must reproduce
    the committed artifact's journal fingerprint bit for bit."""
    art = json.loads(ARTIFACT_PATH.read_text())
    by_name = {s["name"]: s for s in art["scenarios"]}
    for name in SMOKE_SCENARIOS:
        r = result_for(name)
        assert r.fingerprint() == by_name[name]["journalFingerprint"], (
            f"{name}: journal drifted from the committed artifact — "
            "behavior changed; regenerate SCENARIOS_r10.json and review"
        )


# ---- generator knobs (satellite: rack topology + skew, seed-stable) -------------
_STATE_FIELDS = (
    "assignment", "leader_slot", "leader_load", "follower_load",
    "partition_topic", "broker_capacity", "broker_rack", "broker_state",
    "replica_offline",
)


def test_random_cluster_same_seed_bit_identical():
    kwargs = dict(
        num_brokers=9, num_racks=3, num_topics=4, num_partitions=48,
        replication_factor=3, rack_aware=True, hot_partitions=6,
        hot_factor=5.0,
    )
    a = random_cluster(17, **kwargs)
    b = random_cluster(17, **kwargs)
    for f in _STATE_FIELDS:
        assert np.array_equal(np.array(getattr(a, f)),
                              np.array(getattr(b, f))), f
    c = random_cluster(18, **kwargs)
    assert not all(
        np.array_equal(np.array(getattr(a, f)), np.array(getattr(c, f)))
        for f in _STATE_FIELDS
    )


def test_rack_aware_placement_uses_distinct_racks():
    s = random_cluster(3, num_brokers=9, num_racks=3, num_partitions=60,
                       replication_factor=3, rack_aware=True)
    racks = np.array(s.broker_rack)[np.array(s.assignment)]
    for row in racks:
        assert len(set(row.tolist())) == len(row)


def test_rack_aware_rejects_impossible_rf():
    with pytest.raises(ValueError, match="rack_aware"):
        random_cluster(0, num_brokers=6, num_racks=2,
                       replication_factor=3, rack_aware=True)


def test_hot_partition_knob_skews_load():
    base = random_cluster(5, num_partitions=100, num_brokers=10)
    # 10 of 100 partitions at 10x ⇒ total ≈ 1.9x the base cluster
    hot = random_cluster(5, num_partitions=100, num_brokers=10,
                         hot_partitions=10, hot_factor=10.0)
    assert float(np.array(hot.leader_load).sum()) > \
        1.5 * float(np.array(base.leader_load).sum())


# ---- an inline custom scenario (the DSL is not registry-bound) ------------------
def test_custom_inline_scenario_runs():
    spec = ScenarioSpec(
        name="inline_disk_blip",
        description="one disk failure, healed, disk replaced",
        timeline=Timeline([
            disk_failure(2 * MIN, broker=1),
            restore_disk(6 * MIN, broker=1),
        ]),
        self_healing={"disk_failure": True},
        num_brokers=4, num_racks=2, num_partitions=12,
        duration_ms=8 * MIN,
    )
    r = run_scenario(spec)
    assert r.heal_outcome() == "HEALED"
    assert r.fixes_started("DISK_FAILURE")
    assert len(r.faults()) == 2


def test_timeline_validation():
    with pytest.raises(ValueError, match="exactly one"):
        hot_partition_skew(0, factor=2.0)
    with pytest.raises(ValueError, match="maintenance"):
        from cruise_control_tpu.sim.timeline import maintenance_event
        maintenance_event(0, "EXPLODE")
