"""Decision-provenance layer tests (PR-3): the ``cc-tpu-events/1``
structured journal (emit/filter/rotation/correlation), the lifecycle
hooks (facade, executor, detector), goal attribution on actions /
proposals / ``goalSummaries``, the ``GET /events`` server contract, and
the diagnosability contract — a failed rebalance must be reconstructable
from the events JSONL file ALONE."""

import json
import urllib.error
import urllib.request

import pytest

from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.analyzer.goal_optimizer import (
    GoalOptimizer,
    make_goals,
)
from cruise_control_tpu.analyzer.goals.base import OptimizationFailure
from cruise_control_tpu.executor.executor import Executor, ExecutorConfig
from cruise_control_tpu.server import CruiseControlHttpServer
from cruise_control_tpu.telemetry import events
from cruise_control_tpu.telemetry.events import SCHEMA, EventJournal

from harness import full_stack


@pytest.fixture
def journal(tmp_path):
    """The process-wide journal, file-backed and enabled for one test."""
    path = tmp_path / "events.jsonl"
    events.configure(enabled=True, path=str(path))
    events.reset()
    events.configure(enabled=True)  # reset() closed the file; keep path
    yield events.JOURNAL, path
    events.reset()
    events.configure(enabled=False, path="")


# ---- journal mechanics ----------------------------------------------------------
def test_emit_recent_since_kind_and_limit_filters():
    j = EventJournal(enabled=True)
    j.emit("optimize.start", operation="REBALANCE")
    j.emit("executor.batch", moves=3)
    j.emit("executor.task_dead", severity="WARNING")
    j.emit("detector.anomaly")
    assert [e["kind"] for e in j.recent(kind="executor")] == [
        "executor.batch", "executor.task_dead",
    ]
    assert [e["kind"] for e in j.recent(kind="executor.batch")] == [
        "executor.batch"
    ]
    # dotted-prefix match, not substring: "exec" is not a family
    assert j.recent(kind="exec") == []
    ts = j.recent(kind="executor.batch")[0]["ts"]
    assert all(e["ts"] > ts for e in j.recent(since=ts))
    assert len(j.recent(limit=2)) == 2
    assert j.recent(limit=2)[-1]["kind"] == "detector.anomaly"


def test_disabled_journal_is_noop_and_ring_is_bounded():
    j = EventJournal(enabled=False)
    j.emit("optimize.start")
    assert j.recent() == []
    j = EventJournal(enabled=True, ring_size=32)
    for _ in range(100):
        j.emit("executor.batch")
    assert len(j.recent()) == 32


def test_file_persistence_and_size_rotation(tmp_path):
    path = tmp_path / "ev.jsonl"
    j = EventJournal(enabled=True, path=str(path), max_bytes=4096,
                     max_files=3)
    for i in range(200):
        j.emit("executor.batch", moves=i, pad="x" * 64)
    j.close()
    rotated = sorted(p.name for p in tmp_path.iterdir())
    assert "ev.jsonl" in rotated and "ev.jsonl.1" in rotated
    assert "ev.jsonl.3" not in rotated  # max_files bounds the chain
    for p in tmp_path.iterdir():
        for line in p.read_text().strip().splitlines():
            rec = json.loads(line)  # every line is one valid record
            assert rec["schema"] == SCHEMA


def test_task_scope_correlates_thread_local_emits():
    j = EventJournal(enabled=True)
    with j.task_scope("task-42", "REBALANCE"):
        j.emit("optimize.start")
        j.emit("optimize.end", operation="EXPLICIT")
    j.emit("detector.anomaly")
    evs = j.recent()
    assert evs[0]["taskId"] == "task-42"
    assert evs[0]["operation"] == "REBALANCE"
    assert evs[1]["operation"] == "EXPLICIT"  # explicit beats scope
    assert "taskId" not in evs[2]


# ---- lifecycle hooks ------------------------------------------------------------
def test_facade_and_executor_emit_lifecycle_events(journal):
    j, path = journal
    cc, _, _ = full_stack()
    cc.rebalance(dryrun=False)
    kinds = [e["kind"] for e in j.recent()]
    for expected in ("optimize.start", "optimize.end", "execute.start",
                     "executor.start", "executor.phase", "executor.batch",
                     "executor.end", "execute.end"):
        assert expected in kinds, (expected, kinds)
    end = j.recent(kind="optimize.end")[-1]
    assert end["operation"] == "REBALANCE"
    summaries = end["payload"]["goalSummaries"]
    assert [s["goal"] for s in summaries] == [
        g.name for g in make_goals(constraint=cc.constraint)
    ]
    assert sum(s["accepted"] for s in summaries) == \
        end["payload"]["numActions"]


def test_executor_task_death_is_journaled(journal):
    j, _ = journal
    from tests.test_executor import make_backend, prop

    backend, assignment, _ = make_backend(failed_brokers={3})
    cfg = ExecutorConfig(task_timeout_ticks=5)
    p = prop(0, assignment[0], [assignment[0][0], 3])  # 3 never catches up
    result = Executor(backend, cfg).execute_proposals([p])
    assert result.dead == 1
    deaths = j.recent(kind="executor.task_dead")
    assert len(deaths) == 1
    assert deaths[0]["payload"]["reason"] == "timeout"
    assert deaths[0]["payload"]["partition"] == 0
    end = j.recent(kind="executor.end")[-1]
    assert end["severity"] == "WARNING" and end["payload"]["dead"] == 1


def test_detector_decisions_are_journaled(journal):
    j, _ = journal
    from cruise_control_tpu.detector.manager import AnomalyDetectorManager
    from cruise_control_tpu.detector.notifier import (
        AnomalyNotificationResult,
    )
    from tests.test_observability import (
        _StubAnomaly,
        _StubCC,
        _StubNotifier,
    )

    mgr = AnomalyDetectorManager(
        _StubCC(), detectors={},
        notifier=_StubNotifier(AnomalyNotificationResult.FIX),
    )
    mgr._handle(_StubAnomaly(1), now_ms=1000)            # fix succeeds
    mgr._handle(_StubAnomaly(2, fail=True), now_ms=10**9)  # fix explodes
    evs = j.recent(kind="detector.anomaly")
    assert len(evs) == 2
    assert evs[0]["payload"]["anomalyType"] == "GOAL_VIOLATION"
    assert evs[0]["payload"]["action"] == "FIX"
    assert evs[0]["payload"]["fixStarted"] is True
    assert evs[1]["severity"] == "ERROR"
    assert evs[1]["payload"]["action"] == "FIX_FAILED"
    assert "fix exploded" in evs[1]["payload"]["error"]


# ---- goal attribution -----------------------------------------------------------
def test_actions_and_proposals_carry_goal_attribution():
    cc, _, _ = full_stack()
    res = cc.rebalance(dryrun=True)
    assert res.actions, "the skewed fixture always yields moves"
    for a in res.actions:
        assert a.goal, f"untagged action {a}"
        assert a.round >= 0
    goal_names = {g.name for g in make_goals(constraint=cc.constraint)}
    assert {a.goal for a in res.actions} <= goal_names
    assert res.proposals
    for p in res.proposals:
        assert p.goals, f"unattributed proposal P{p.partition}"
        assert set(p.goals) <= goal_names
        assert p.to_json()["goals"] == list(p.goals)
    # summary carries the per-pass accounting in pass order
    s = res.summary()
    assert [e["pass"] for e in s["goalSummaries"]] == list(
        range(len(s["goalSummaries"]))
    )


def test_tpu_engine_reports_pass_summaries():
    from cruise_control_tpu.analyzer.tpu_optimizer import (
        TpuGoalOptimizer,
        TpuSearchConfig,
    )
    from cruise_control_tpu.models.generators import random_cluster

    state = random_cluster(seed=7, num_brokers=8, num_racks=4,
                           num_partitions=48)
    res = TpuGoalOptimizer(
        config=TpuSearchConfig(max_rounds=20, steps_per_call=16)
    ).optimize(state)
    assert res.goal_summaries, "engine phases must be summarized"
    assert res.goal_summaries[0]["goal"] == "TpuSearch"
    assert res.goal_summaries[0]["accepted"] == sum(
        1 for a in res.actions if a.goal == "TpuSearch"
    )
    assert {a.goal for a in res.actions} <= {
        "TpuSearch", "TpuPolish",
    } | {g.name for g in make_goals()}


def test_capacity_infeasible_greedy_reports_reject_reasons():
    """The per-pass reject accounting rides the OptimizationFailure."""
    cc, _, _ = full_stack()
    cc.constraint.capacity_threshold[Resource.DISK] = 1e-6
    with pytest.raises(OptimizationFailure) as ei:
        cc.rebalance(dryrun=True)
    summaries = ei.value.goal_summaries
    disk = next(s for s in summaries if s["goal"] == "DiskCapacityGoal")
    assert disk["rejected"].get("capacity-exceeded", 0) > 0


# ---- the diagnosability contract ------------------------------------------------
def test_failed_rebalance_is_reconstructable_from_journal_file(journal):
    """Acceptance criterion: a deliberately failed rebalance
    (capacity-infeasible fixture) is diagnosable from the events JSONL
    alone — this test reads ONLY the journal file."""
    _, path = journal
    cc, _, _ = full_stack()
    cc.constraint.capacity_threshold[Resource.DISK] = 1e-6
    with pytest.raises(OptimizationFailure):
        cc.rebalance(dryrun=False)

    recs = [json.loads(line) for line in
            path.read_text().strip().splitlines()]
    start = [r for r in recs if r["kind"] == "optimize.start"]
    failed = [r for r in recs if r["kind"] == "optimize.failed"]
    assert start and failed
    assert start[0]["operation"] == "REBALANCE"
    f = failed[0]
    assert f["severity"] == "ERROR"
    # the goal that emitted the failure is named in the error...
    assert "DiskCapacityGoal" in f["payload"]["error"]
    # ...and the reject reasons seen during its pass are recorded
    disk = next(s for s in f["payload"]["goalSummaries"]
                if s["goal"] == "DiskCapacityGoal")
    assert disk["rejected"].get("capacity-exceeded", 0) > 0
    # no execution ever started for the failed plan
    assert not any(r["kind"] == "execute.start" for r in recs)


# ---- GET /events server contract ------------------------------------------------
@pytest.fixture
def server(journal):
    cc, _, _ = full_stack()
    srv = CruiseControlHttpServer(cc, port=0)
    srv.start()
    yield srv
    srv.stop()


def _get_json(srv, path):
    with urllib.request.urlopen(f"{srv.url}/{path}") as r:
        return json.loads(r.read().decode()), r.status


def test_events_endpoint_filters_and_schema(server, journal):
    j, _ = journal
    j.emit("optimize.start", operation="REBALANCE")
    j.emit("executor.batch", moves=2)
    j.emit("executor.batch", moves=3)
    body, status = _get_json(server, "events")
    assert status == 200
    assert body["schema"] == SCHEMA
    assert body["numMatched"] == 3 and len(body["events"]) == 3
    body, _ = _get_json(server, "events?kind=executor")
    assert [e["kind"] for e in body["events"]] == [
        "executor.batch", "executor.batch",
    ]
    since = body["events"][0]["ts"]
    body, _ = _get_json(server, f"events?since={since}")
    assert all(e["ts"] > since for e in body["events"])
    body, _ = _get_json(server, "events?limit=1")
    assert body["numMatched"] == 3 and body["numReturned"] == 1
    assert body["events"][0]["payload"]["moves"] == 3  # newest kept


def test_events_endpoint_503_when_disabled(server):
    events.configure(enabled=False)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{server.url}/events")
        assert ei.value.code == 503
    finally:
        events.configure(enabled=True)


def test_async_rebalance_events_carry_user_task_id(server, journal):
    j, _ = journal
    req = urllib.request.Request(
        f"{server.url}/rebalance?dryrun=true&get_response_timeout_s=30",
        method="POST",
    )
    with urllib.request.urlopen(req) as r:
        tid = r.headers["User-Task-ID"]
        assert r.status == 200
    submitted = j.recent(kind="http.task_submitted")
    assert submitted and submitted[0]["taskId"] == tid
    assert submitted[0]["operation"] == "REBALANCE"
    for e in j.recent(kind="optimize"):
        assert e["taskId"] == tid, e  # worker-thread scope correlation


# ---- satellites -----------------------------------------------------------------
def test_executor_history_is_bounded_and_ids_stay_monotonic():
    from tests.test_executor import make_backend, prop

    backend, assignment, _ = make_backend()
    ex = Executor(backend, ExecutorConfig(history_retention=3))
    for i in range(5):
        old = [b for b in backend.partition_state(0).replicas]
        new = [old[0], (old[1] + 1) % 4]
        if new[1] in old:
            new = [old[0], (old[1] + 2) % 4]
        ex.execute_proposals([prop(0, old, new)])
    assert len(ex.history) == 3
    assert ex.history.maxlen == 3
    # executionIds keep counting past the bound
    assert ex.execution_log[-1]["executionId"] == 5


def test_flight_recorder_merges_event_journal(journal):
    j, _ = journal
    from cruise_control_tpu.telemetry.recorder import FlightRecorder
    from cruise_control_tpu.utils.metrics import MetricRegistry

    j.emit("optimize.start", operation="REBALANCE")
    rec = FlightRecorder(MetricRegistry(), interval_s=60.0,
                         events_source=lambda: j.recent(limit=10))
    art = rec.artifact()
    assert art["journal"][-1]["kind"] == "optimize.start"


def test_json_logging_shares_event_field_names(tmp_path, journal):
    import logging

    from cruise_control_tpu.utils.logging import (
        JsonLineFormatter,
        configure,
        get_logger,
    )

    log_file = tmp_path / "cc.log"
    configure(level="INFO", file=str(log_file), json_lines=True)
    try:
        get_logger("executor").warning("task %d DEAD", 7)
        for h in logging.getLogger("cruise_control_tpu").handlers:
            h.flush()
        rec = json.loads(log_file.read_text().strip().splitlines()[-1])
        # shared vocabulary with cc-tpu-events/1: ts / severity / kind
        assert rec["severity"] == "WARNING"
        assert rec["kind"] == "log.executor"
        assert isinstance(rec["ts"], float)
        assert rec["message"] == "task 7 DEAD"
        ev = events.JOURNAL
        ev.emit("executor.task_dead", severity="WARNING")
        shared = {"ts", "severity", "kind"}
        assert shared <= set(rec) and shared <= set(ev.recent()[-1])
        assert isinstance(JsonLineFormatter().format(
            logging.LogRecord("x", logging.INFO, "f", 1, "m", (), None)
        ), str)
    finally:
        configure(level="INFO")  # restore stderr handler
