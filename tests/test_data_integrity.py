"""Data-integrity hardening (ISSUE 13): metrics quarantine, checksummed
durable state, engine-failure containment.

Three fronts, one contract — garbage must never silently become state:

* the monitor's validation stage quarantines non-finite / negative /
  metadata-unknown / stale / spiking samples BEFORE aggregation (clean
  samples pass bit-identically);
* the durable JSONL logs (execution checkpoint, event journal) carry
  per-record CRC32 frames, and their loaders distinguish the torn tail
  of a real crash (tolerated) from mid-file corruption (fail loudly,
  trust only the prefix) — proven by a bit-flip fuzzer over EVERY byte
  of real files;
* the facade's engine degradation ladder contains cold TPU failures
  (greedy fallback + breaker-style cooldown) and the plan sanity gate
  refuses to emit insane OptimizerResults.
"""

import dataclasses
import json
import math
import os

import numpy as np
import pytest

from cruise_control_tpu.analyzer.degradation import (
    EngineDegradation,
    PlanSanityError,
    plan_sanity_reason,
)
from cruise_control_tpu.executor.journal import ExecutionJournal
from cruise_control_tpu.monitor.aggregator import MetricSampleAggregator
from cruise_control_tpu.monitor.load_monitor import (
    BackendMetadataClient,
    LoadMonitor,
)
from cruise_control_tpu.monitor.metric_defs import broker_metric_def
from cruise_control_tpu.monitor.sampling import (
    BrokerMetricSample,
    CruiseControlMetric,
    MetricsReporterSampler,
    MetricsTopic,
    PartitionMetricSample,
    RawMetricType,
    SampleValidationConfig,
    SampleValidator,
    SimulatedMetricsReporter,
)
from cruise_control_tpu.telemetry import events
from cruise_control_tpu.telemetry.events import (
    CorruptJournalError,
    EventJournal,
    load_records,
)
from cruise_control_tpu.utils.checksum import (
    parse_line,
    record_status,
    stamp_line,
)
from cruise_control_tpu.utils.metrics import MetricRegistry

from harness import WINDOW, full_stack


@pytest.fixture
def captured_journal():
    """Swap a private enabled EventJournal in for the test."""
    prev = events.JOURNAL
    events.JOURNAL = EventJournal(enabled=True)
    try:
        yield events.JOURNAL
    finally:
        events.JOURNAL = prev


# ---- CRC framing (utils/checksum.py) --------------------------------------------
def test_stamp_and_parse_roundtrip_both_separator_styles():
    for compact in (True, False):
        seps = (",", ":") if compact else (", ", ": ")
        base = json.dumps({"kind": "x", "payload": {"a": 1.5, "s": "p|q"}},
                          separators=seps)
        framed = stamp_line(base, compact=compact)
        rec, status = parse_line(framed)
        assert status == "ok"
        assert rec["kind"] == "x" and "crc" in rec
        assert record_status(rec) == "ok"


def test_unframed_line_is_legacy_and_garbage_is_undecodable():
    rec, status = parse_line('{"kind": "old-style"}')
    assert status == "legacy" and rec["kind"] == "old-style"
    assert parse_line("not json at all")[1] == "undecodable"
    assert parse_line('[1, 2, 3]')[1] == "undecodable"  # not an object


def test_content_flip_is_detected_as_corrupt():
    framed = stamp_line(json.dumps({"kind": "task", "v": 12345},
                                   separators=(",", ":")))
    tampered = framed.replace("12345", "12346")  # still valid JSON
    assert parse_line(tampered)[1] == "corrupt"


# ---- execution checkpoint: torn tail vs mid-file corruption ---------------------
def _small_checkpoint(path, n_tasks=3):
    j = ExecutionJournal(path)
    j.append("start", executionId=7, strategy="s", maxTicks=100,
             proposals=[[p, 0, 0, 1, [0], [1], [], []]
                        for p in range(n_tasks)],
             sizes={str(p): 10.0 for p in range(n_tasks)}, config={})
    j.append("batch", taskIds=list(range(n_tasks)), tick=1,
             phase="replica_moves", partitions=list(range(n_tasks)),
             moves=n_tasks)
    for p in range(n_tasks):
        j.append("task", taskId=p, state="COMPLETED", tick=2 + p)
    j.close()
    return j


def test_torn_final_line_is_tolerated(tmp_path, captured_journal):
    path = str(tmp_path / "ck.jsonl")
    _small_checkpoint(path)
    intact = ExecutionJournal(path).load()
    with open(path) as f:
        lines = f.read().splitlines()
    # a real crash tears the FINAL line mid-write
    with open(path, "w") as f:
        f.write("\n".join(lines[:-1]) + "\n" + lines[-1][: len(lines[-1]) // 2])
    ck = ExecutionJournal(path).load()
    assert ck is not None and ck.execution_id == intact.execution_id
    # only the torn record's state is lost (the batch watermark still
    # marks that task IN_PROGRESS); nothing journaled loudly
    assert intact.tasks[2]["state"] == "COMPLETED"
    assert ck.tasks[2]["state"] == "IN_PROGRESS"
    assert not captured_journal.recent(kind="executor.checkpoint_corrupt")


def test_mid_file_bad_line_fails_loudly_and_trusts_only_prefix(
    tmp_path, captured_journal
):
    path = str(tmp_path / "ck.jsonl")
    _small_checkpoint(path)
    with open(path) as f:
        lines = f.read().splitlines()
    # an EARLIER line goes bad (undecodable garbage, not just CRC drift)
    lines[1] = "@@@ definitely not json @@@"
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    ck = ExecutionJournal(path).load()
    # absent-after-last-good-record: only the start record survives
    assert ck is not None and ck.tasks == {}
    (ev,) = captured_journal.recent(kind="executor.checkpoint_corrupt")
    assert ev["severity"] == "ERROR"
    assert ev["payload"]["line"] == 1
    assert ev["payload"]["dropped"] == len(lines) - 1


def test_bitflipped_but_parseable_record_is_caught(tmp_path,
                                                   captured_journal):
    """THE motivating hole: a flipped digit keeps the line valid JSON —
    pre-CRC, resume reconciliation trusted it verbatim."""
    path = str(tmp_path / "ck.jsonl")
    _small_checkpoint(path)
    with open(path) as f:
        lines = f.read().splitlines()
    assert '"state":"COMPLETED"' in lines[2]
    lines[2] = lines[2].replace('"state":"COMPLETED"', '"state":"COMPLETEE"')
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    ck = ExecutionJournal(path).load()
    assert captured_journal.recent(kind="executor.checkpoint_corrupt")
    # the doctored state was never adopted
    assert all(t.get("state") != "COMPLETEE" for t in ck.tasks.values())


def test_legacy_checkpoint_without_crc_still_loads(tmp_path):
    """Format versioning: v1 logs (no crc member) load exactly as before."""
    path = str(tmp_path / "legacy.jsonl")
    recs = [
        {"schema": "cc-tpu-execution-checkpoint/1", "seq": 1,
         "kind": "start", "ts": 1.0,
         "payload": {"executionId": 3, "strategy": "", "maxTicks": 10,
                     "proposals": [[0, 0, 0, 1, [0], [1], [], []]],
                     "sizes": {}, "config": {}}},
        {"schema": "cc-tpu-execution-checkpoint/1", "seq": 2,
         "kind": "task", "ts": 2.0,
         "payload": {"taskId": 0, "state": "COMPLETED"}},
    ]
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    ck = ExecutionJournal(path).load()
    assert ck is not None and ck.execution_id == 3
    assert ck.tasks[0]["state"] == "COMPLETED"


# ---- the bit-flip fuzzer (acceptance criterion) ---------------------------------
def _prefix_checkpoints(path, tmp_path):
    """Checkpoint loaded from every line-prefix of ``path`` (the set of
    SAFE states: group commit already means a crash may lose any suffix
    of buffered records)."""
    with open(path) as f:
        lines = f.read().splitlines()
    out = []
    for k in range(len(lines) + 1):
        p = str(tmp_path / f"prefix_{k}.jsonl")
        with open(p, "w") as f:
            f.write("\n".join(lines[:k]) + ("\n" if k else ""))
        out.append(ExecutionJournal(p).load())
    return out, lines


def test_checkpoint_bitflip_fuzzer_never_silently_wrong(tmp_path,
                                                        captured_journal):
    """Flip one bit at EVERY byte offset of a real checkpoint: load()
    must either recover to a line-prefix state (the group-commit-safe
    set) or fail loudly — never return a non-prefix (silently wrong)
    checkpoint, and never silently drop MID-FILE records."""
    path = str(tmp_path / "ck.jsonl")
    _small_checkpoint(path)
    prefixes, lines = _prefix_checkpoints(path, tmp_path)
    raw = open(path, "rb").read()
    n_lines = len(lines)
    # byte offset where the final line starts (flips at/after it may
    # silently drop tail records — that IS the torn-tail contract)
    last_line_start = len(raw) - len(lines[-1].encode()) - 1
    flip_path = str(tmp_path / "flip.jsonl")
    silent_wrong = []
    for off in range(len(raw)):
        flipped = bytearray(raw)
        flipped[off] ^= 1 << (off % 8)
        with open(flip_path, "wb") as f:
            f.write(bytes(flipped))
        events.JOURNAL.reset()
        try:
            got = ExecutionJournal(flip_path).load()
        except Exception as e:  # loud is acceptable; silent-wrong is not
            pytest.fail(f"offset {off}: load() raised {e!r}")
        loud = bool(events.JOURNAL.recent(
            kind="executor.checkpoint_corrupt"))
        matches = [k for k, pk in enumerate(prefixes) if got == pk]
        if not matches:
            silent_wrong.append((off, "non-prefix state"))
            continue
        if loud:
            continue
        # silent outcomes must be explainable without mid-file damage:
        # the full file, a tail-line flip, or a flipped newline that
        # merged the final lines into one bad tail line
        k = max(matches)
        if k >= n_lines:          # identical to the intact checkpoint
            continue
        if off >= last_line_start:
            continue              # tail-region flip: torn-tail contract
        if raw[off] == 0x0A:
            continue              # merged-lines variant of a torn tail
        silent_wrong.append((off, f"silent drop to prefix {k}"))
    assert not silent_wrong, silent_wrong[:10]


def test_events_journal_bitflip_fuzzer(tmp_path):
    """Same oracle for the event journal's reader: every returned record
    list is a prefix of the originals; mid-file damage raises."""
    path = str(tmp_path / "ev.jsonl")
    j = EventJournal(enabled=True, path=path)
    for i in range(5):
        j.emit("executor.batch", moves=i, partitions=[i], tick=i,
               phase="replica_moves")
    j.close()
    original = load_records(path)
    assert len(original) == 5
    raw = open(path, "rb").read()
    lines = raw.decode().splitlines()
    last_line_start = len(raw) - len(lines[-1].encode()) - 1
    flip_path = str(tmp_path / "flip.jsonl")
    for off in range(len(raw)):
        flipped = bytearray(raw)
        flipped[off] ^= 1 << (off % 8)
        with open(flip_path, "wb") as f:
            f.write(bytes(flipped))
        try:
            got = load_records(flip_path)
        except CorruptJournalError as e:
            # loud — and the carried prefix must really be a prefix
            assert e.records == original[: len(e.records)], off
            continue
        assert got == original[: len(got)], (off, "non-prefix records")
        if len(got) < len(original) - 1:
            # >1 record silently gone: only a merged-tail flip may
            assert off >= last_line_start or raw[off] == 0x0A, off
        elif len(got) == len(original) - 1:
            assert off >= last_line_start or raw[off] == 0x0A, off


# ---- metrics quarantine: the ingest path ----------------------------------------
BROKER_M = broker_metric_def().num_metrics


def _validator(registry=None, **cfg):
    return SampleValidator(SampleValidationConfig(**cfg), registry=registry)


def test_clean_batch_passes_through_bit_identically():
    v = _validator(registry=MetricRegistry())
    p = [PartitionMetricSample(0, 100, (1.0, 2.0, 3.0, 4.0))]
    b = [BrokerMetricSample(0, 100, tuple([1.0] * BROKER_M))]
    cp, cb, report = v.validate(p, b, {0}, {0}, now_ms=200)
    assert cp is p and cb is b  # the EXACT list objects
    assert report is None


@pytest.mark.parametrize("poison,reason", [
    (float("nan"), "non-finite"),
    (float("inf"), "non-finite"),
    (-5.0, "negative"),
])
def test_nonfinite_and_negative_values_are_quarantined(poison, reason):
    reg = MetricRegistry()
    v = _validator(registry=reg)
    vals = [1.0, 2.0, 3.0, 4.0]
    vals[1] = poison
    p = [PartitionMetricSample(0, 100, tuple(vals)),
         PartitionMetricSample(1, 100, (1.0, 1.0, 1.0, 1.0))]
    bvals = [1.0] * BROKER_M
    bvals[0] = poison
    b = [BrokerMetricSample(0, 100, tuple(bvals))]
    cp, cb, report = v.validate(p, b, {0}, {0, 1}, now_ms=200)
    assert [s.partition for s in cp] == [1] and cb == []
    assert report.quarantined == 2 and report.reasons == {reason: 2}
    snap = reg.snapshot()["meters"]
    assert snap["monitor.sample.quarantined"]["count"] == 2
    assert snap["monitor.sample.accepted"]["count"] == 1
    assert v.reason_totals() == {reason: 2}


def test_unknown_entities_are_quarantined_not_grown():
    v = _validator()
    p = [PartitionMetricSample(99, 100, (1.0, 1.0, 1.0, 1.0))]
    b = [BrokerMetricSample(42, 100, tuple([1.0] * BROKER_M))]
    cp, cb, report = v.validate(p, b, {0, 1}, {0, 1}, now_ms=200)
    assert cp == [] and cb == []
    assert report.reasons == {"unknown-broker": 1, "unknown-partition": 1}


def test_stale_and_spike_checks_are_opt_in():
    v = _validator(max_age_ms=1000, spike_factor=10.0)
    b_old = BrokerMetricSample(0, 100, tuple([1.0] * BROKER_M))
    _, cb, report = v.validate([], [b_old], {0}, set(), now_ms=5000)
    assert cb == [] and report.reasons == {"stale": 1}
    # spike: baseline from an accepted sample, then a 20x jump
    base = BrokerMetricSample(0, 6000, tuple([10.0] * BROKER_M))
    _, cb, _ = v.validate([], [base], {0}, set(), now_ms=6000)
    assert cb == [base]
    spike = BrokerMetricSample(0, 7000, tuple([200.0] * BROKER_M))
    _, cb, report = v.validate([], [spike], {0}, set(), now_ms=7000)
    assert cb == [] and report.reasons == {"spike": 1}
    # the rejected spike did NOT advance the baseline
    again = BrokerMetricSample(0, 8000, tuple([200.0] * BROKER_M))
    _, cb, _ = v.validate([], [again], {0}, set(), now_ms=8000)
    assert cb == []


def test_aggregator_refuses_nonfinite_even_without_validator():
    agg = MetricSampleAggregator(broker_metric_def(), 2, 1000, 3)
    assert agg.add_sample(0, 500, [1.0] * BROKER_M) is True
    bad = [1.0] * BROKER_M
    bad[0] = float("nan")
    assert agg.add_sample(0, 600, bad) is False
    out = agg.aggregate()
    assert np.isfinite(out.values).all()


def test_full_ingest_path_quarantines_poison_and_model_stays_finite(
    captured_journal,
):
    """End to end: reporter → topic → sampler → monitor with poisoned raw
    records — NaN broker CPU spreads into the derived partition samples,
    all of it is quarantined, and the built model is finite."""
    cc, backend, reporter = full_stack(windows=3)
    monitor = cc.load_monitor
    topic = monitor.sampler.topic
    before_entities = monitor.broker_aggregator.num_entities
    # poison: NaN CPU for broker 0 (last-wins in the processor) and a
    # record for a broker metadata has never seen
    t = 3 * WINDOW + 500
    reporter.report(time_ms=t)
    topic.produce([
        CruiseControlMetric(RawMetricType.BROKER_CPU_UTIL, t, 0,
                            float("nan")),
        CruiseControlMetric(RawMetricType.BROKER_CPU_UTIL, t, 77, 50.0),
    ])
    accepted = monitor.run_sampling_iteration(4 * WINDOW)
    assert accepted > 0
    (ev,) = captured_journal.recent(kind="monitor.sample_quarantined")
    payload = ev["payload"]
    assert payload["reasons"].get("non-finite", 0) >= 1
    assert payload["reasons"].get("unknown-broker", 0) == 1
    assert 0 in payload["brokers"] and 77 in payload["brokers"]
    # no phantom broker entity was grown for id 77
    assert monitor.broker_aggregator.num_entities == before_entities
    state = monitor.cluster_model()
    assert np.isfinite(np.asarray(state.leader_load)).all()
    assert np.isfinite(np.asarray(state.follower_load)).all()


def test_stale_reporter_after_broker_removal_and_add_broker_acceptance():
    """Satellite: a reporter still emitting for a broker metadata no
    longer knows is quarantined (reason unknown-broker, no phantom
    entity); once add_broker registers a newcomer, its samples are
    accepted — and a KILLED (dead but still hosting) broker's samples
    keep flowing."""
    from cruise_control_tpu.sim.backend import ScriptedClusterBackend

    backend = ScriptedClusterBackend(
        {0: [0, 1], 1: [1, 2], 2: [2, 0]}, {0: 0, 1: 1, 2: 2},
        brokers={0, 1, 2}, broker_racks={0: 0, 1: 1, 2: 0},
    )
    topic = MetricsTopic()
    monitor = LoadMonitor(
        BackendMetadataClient(backend, backend.broker_racks),
        MetricsReporterSampler(topic),
        window_ms=1000, num_windows=3,
    )
    def b_cpu(broker, t, v=10.0):
        return CruiseControlMetric(RawMetricType.BROKER_CPU_UTIL, t,
                                   broker, v)

    entities_before = monitor.broker_aggregator.num_entities
    # broker 9 is not in metadata: quarantined, no growth
    topic.produce([b_cpu(0, 500), b_cpu(9, 500)])
    monitor.run_sampling_iteration(1000)
    assert monitor.broker_aggregator.num_entities == entities_before
    assert monitor.sample_validator.reason_totals() == {
        "unknown-broker": 1}
    # a killed broker still hosts replicas — its samples stay valid
    backend.kill_broker(2)
    monitor.metadata.invalidate()
    topic.produce([b_cpu(2, 1500)])
    assert monitor.run_sampling_iteration(2000) == 1
    # add_broker registers id 9; its samples are accepted from then on
    backend.add_broker(9, rack=1)
    monitor.metadata.invalidate()
    topic.produce([b_cpu(9, 2500)])
    assert monitor.run_sampling_iteration(3000) == 1
    assert monitor.broker_aggregator.num_entities == 10
    assert monitor.sample_validator.reason_totals() == {
        "unknown-broker": 1}


def test_quarantine_storm_surfaces_as_metric_anomaly():
    from cruise_control_tpu.detector.detectors import MetricAnomalyDetector

    cc, backend, reporter = full_stack(windows=3)
    monitor = cc.load_monitor
    monitor.sample_validator.config.storm_min_samples = 3
    monitor.sample_validator.config.storm_window_batches = 4
    topic = monitor.sampler.topic
    for i in range(4):
        t = (3 + i) * WINDOW + 500
        reporter.report(time_ms=t)
        topic.produce([CruiseControlMetric(
            RawMetricType.BROKER_CPU_UTIL, t, 1, float("nan"))])
        monitor.run_sampling_iteration((4 + i) * WINDOW)
    det = MetricAnomalyDetector(cc)
    storms = [a for a in det.detect(10_000)
              if a.metric == "sample.quarantine.ratio"]
    assert storms and storms[0].broker_id == 1
    assert not storms[0].fixable
    # the window drains on clean batches: the storm clears
    for i in range(4):
        t = (7 + i) * WINDOW + 500
        reporter.report(time_ms=t)
        monitor.run_sampling_iteration((8 + i) * WINDOW)
    assert not [a for a in det.detect(20_000)
                if a.metric == "sample.quarantine.ratio"]


def test_quarantine_ratio_slo_live_and_journal_modes():
    from cruise_control_tpu.telemetry.slo import evaluate_slos

    reg = MetricRegistry()
    reg.meter("monitor.sample.accepted").mark(95)
    reg.meter("monitor.sample.quarantined").mark(5)
    rep = evaluate_slos([], snapshot=reg.snapshot())
    row = rep.slo("monitor.sample.quarantine.ratio")
    assert row.measured == pytest.approx(0.05)
    assert row.ok is True
    journal = [{"kind": "monitor.sample_quarantined", "ts": 1.0,
                "payload": {"accepted": 1, "quarantined": 3}}]
    rep = evaluate_slos(journal, snapshot=None)
    row = rep.slo("monitor.sample.quarantine.ratio")
    assert row.measured == pytest.approx(0.75)
    assert row.ok is False
    # no data at all abstains (never flips hysteresis)
    assert evaluate_slos([], snapshot=None).slo(
        "monitor.sample.quarantine.ratio").state == "NO_DATA"


def test_quarantine_rows_on_metrics_exposition():
    from cruise_control_tpu.telemetry.exposition import render_prometheus

    reg = MetricRegistry()
    cc, _, reporter = full_stack(windows=3, registry=reg)
    monitor = cc.load_monitor
    monitor.sample_validator.registry = reg
    t = 3 * WINDOW + 500
    reporter.report(time_ms=t)
    monitor.sampler.topic.produce([CruiseControlMetric(
        RawMetricType.BROKER_CPU_UTIL, t, 0, float("nan"))])
    monitor.run_sampling_iteration(4 * WINDOW)
    rows = [({"reason": r}, float(n))
            for r, n in sorted(monitor.sample_validator.reason_totals()
                               .items())]
    text = render_prometheus(reg, extra_families=[(
        "cc_monitor_quarantined_total", "counter", "test", rows)])
    assert 'cc_monitor_quarantined_total{reason="non-finite"}' in text


# ---- engine degradation ladder + plan sanity gate -------------------------------
class _FailingTpu:
    def optimize(self, state, options=None, **kwargs):
        raise RuntimeError("XLA RESOURCE_EXHAUSTED (scripted)")


def _fail_tpu(cc):
    orig = type(cc)._make_engine

    def make(engine, constraint=None):
        if (engine or cc.default_engine) == "tpu":
            return _FailingTpu()
        return orig(cc, engine, constraint)

    cc._make_engine = make


def _tpu_as_greedy(cc):
    """'Recovered' engine: the tpu request resolves to a (real) greedy
    optimizer so the recovery probe succeeds without a device compile."""
    orig = type(cc)._make_engine

    def make(engine, constraint=None):
        if (engine or cc.default_engine) == "tpu":
            return orig(cc, "greedy", constraint)
        return orig(cc, engine, constraint)

    cc._make_engine = make


def test_engine_ladder_degrades_recovers_and_journals(captured_journal):
    clock = [0.0]
    cc, _, _ = full_stack(engine="tpu")
    cc.engine_degradation = EngineDegradation(
        cooldown_s=60.0, clock=lambda: clock[0])
    _fail_tpu(cc)
    # 1) cold TPU failure → greedy serves the SAME operation
    r = cc.rebalance(dryrun=True)
    assert r.engine == "greedy"
    (deg,) = captured_journal.recent(kind="analyzer.engine_degraded")
    assert deg["payload"]["fallback"] == "greedy"
    assert "RESOURCE_EXHAUSTED" in deg["payload"]["error"]
    assert cc.engine_degradation.active()
    # 2) inside the cooldown: straight to greedy, no new failure/degrade
    r2 = cc.rebalance(dryrun=True)
    assert r2.engine == "greedy"
    assert len(captured_journal.recent(
        kind="analyzer.engine_degraded")) == 1
    summary = cc.engine_degradation.state_summary()
    assert summary["state"] == "DEGRADED" and summary["degradations"] == 1
    assert cc.state()["AnalyzerState"]["engineDegradation"]["state"] == \
        "DEGRADED"
    # 3) past the cooldown the next attempt probes; success recovers
    clock[0] = 61.0
    _tpu_as_greedy(cc)
    cc.rebalance(dryrun=True)
    assert captured_journal.recent(kind="analyzer.engine_recovered")
    assert not cc.engine_degradation.active()


def test_engine_ladder_refailure_rearms_cooldown(captured_journal):
    clock = [0.0]
    cc, _, _ = full_stack(engine="tpu")
    cc.engine_degradation = EngineDegradation(
        cooldown_s=30.0, clock=lambda: clock[0])
    _fail_tpu(cc)
    cc.rebalance(dryrun=True)
    clock[0] = 31.0  # probe window — tpu still broken
    cc.rebalance(dryrun=True)
    assert len(captured_journal.recent(
        kind="analyzer.engine_degraded")) == 2
    assert cc.engine_degradation.active()
    assert not captured_journal.recent(kind="analyzer.engine_recovered")


def test_no_ladder_without_degradation_state(captured_journal):
    """engine_degradation=None keeps the historical behavior: a cold TPU
    failure surfaces to the caller."""
    cc, _, _ = full_stack(engine="tpu")
    assert cc.engine_degradation is None
    _fail_tpu(cc)
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        cc.rebalance(dryrun=True)
    assert captured_journal.recent(kind="optimize.failed")
    assert not captured_journal.recent(kind="analyzer.engine_degraded")


class _InsaneOptimizer:
    """Returns a structurally-valid result whose final loads are NaN."""

    def __init__(self, cc):
        self._real = type(cc)._make_engine(cc, "greedy")

    def optimize(self, state, options=None, **kwargs):
        r = self._real.optimize(state, options)
        bad = np.asarray(r.final_state.leader_load).copy()
        bad[0] = np.nan
        r.final_state = r.final_state.replace(leader_load=bad)
        return r


def test_plan_sanity_gate_refuses_nonfinite_plans(captured_journal):
    cc, _, _ = full_stack(engine="greedy")
    insane = _InsaneOptimizer(cc)
    cc._make_engine = lambda engine, constraint=None: insane
    with pytest.raises(PlanSanityError, match="non-finite-final-loads"):
        cc.rebalance(dryrun=True)
    (rej,) = captured_journal.recent(kind="analyzer.plan_rejected")
    assert rej["payload"]["reason"] == "non-finite-final-loads"
    assert captured_journal.recent(kind="optimize.failed")


def test_plan_sanity_gate_rejection_rides_the_ladder(captured_journal):
    """A TPU result failing the gate degrades to greedy like any other
    cold engine failure — the operation still succeeds."""
    cc, _, _ = full_stack(engine="tpu")
    cc.engine_degradation = EngineDegradation(cooldown_s=60.0,
                                              clock=lambda: 0.0)
    insane = _InsaneOptimizer(cc)
    orig = type(cc)._make_engine

    def make(engine, constraint=None):
        if (engine or cc.default_engine) == "tpu":
            return insane
        return orig(cc, engine, constraint)

    cc._make_engine = make
    r = cc.rebalance(dryrun=True)
    assert r.engine == "greedy"
    assert captured_journal.recent(kind="analyzer.plan_rejected")
    assert captured_journal.recent(kind="analyzer.engine_degraded")
    assert not captured_journal.recent(kind="optimize.failed")


def test_plan_sanity_reason_unit():
    class _R:
        def __init__(self, before, after, hard_b=0, hard_a=0):
            self.violations_before = {"CpuCapacityGoal": hard_b,
                                      "ReplicaDistributionGoal": before}
            self.violations_after = {"CpuCapacityGoal": hard_a,
                                     "ReplicaDistributionGoal": after}
            self.final_state = None

        @property
        def violation_score_before(self):
            return sum(self.violations_before.values())

        @property
        def violation_score_after(self):
            return sum(self.violations_after.values())

    assert plan_sanity_reason(_R(5, 0)) is None
    # soft goals may legitimately end worse (evacuations trade balance)
    assert plan_sanity_reason(_R(0, 4)) is None
    # hard violations appearing from nowhere may not
    assert plan_sanity_reason(_R(0, 0, hard_b=0, hard_a=2)) == \
        "hard-score-worse-than-pre-plan"
    assert plan_sanity_reason(_R(0, math.nan)) == \
        "non-finite-violation-score"
