"""Counterfactual what-if engine tests (ISSUE 16).

Four contracts:

* **batched = sequential, bit for bit** — every future's verdict from
  one N-wide batched dispatch equals the verdict from its own
  single-future dispatch exactly (``np.array_equal`` per output key), so
  batching is pure wall-clock engineering, never a semantics change;
* **the cache tells the truth** — verdicts are keyed
  ``model_generation × future fingerprint``: repeat queries hit, an
  invalidation or a generation bump misses, and the precompute daemon's
  freshness probe covers the per-future warm set (the satellite-2 fix),
  so a stale future never serves;
* **``POST /whatif`` honors the front-door contract** — the async
  202/long-poll protocol, admission control (429 + Retry-After), and a
  400 at the request boundary for malformed futures;
* **proactive fires BEFORE the peak** — the forecast-driven scheduler
  triggers a rebalance while the projected breach is still in the
  future (virtual clock; the full closed loop is the
  ``proactive_beats_reactive_peak`` scenario in test_scenarios).

Plus the committed ``WHATIF_r16.json`` artifact gates: N≥64 futures in
one batched dispatch under 2× a single plan search, and the proactive
twin beating the reactive twin's heal p99 — regenerate via
``python -m cruise_control_tpu.whatif --artifact WHATIF_r16.json``.
"""

import json
import pathlib

import numpy as np
import pytest

from cruise_control_tpu.models.generators import random_cluster
from cruise_control_tpu.whatif import (
    FutureSpec,
    broker_loss,
    compile_futures,
    evaluate_batch,
    hot_partitions,
    likely_futures,
    maintenance,
    rack_loss,
    topic_growth,
    traffic_scale,
)
from cruise_control_tpu.whatif.compiler import MIN_BUCKET, bucket_size
from cruise_control_tpu.whatif.engine import verdicts
from cruise_control_tpu.whatif.futures import parse_future
from cruise_control_tpu.whatif.proactive import ProactiveScheduler

from harness import WINDOW, full_stack
from test_artifact_schemas import SCHEMAS, validate

ARTIFACT_PATH = pathlib.Path(__file__).parent.parent / "WHATIF_r16.json"


def _state():
    return random_cluster(
        seed=7, num_brokers=12, num_racks=4, num_partitions=60
    )


def _mixed_futures():
    """One of every DSL kind plus a composition — the equivalence matrix."""
    return [
        FutureSpec(name="b3", events=(broker_loss(3),)),
        FutureSpec(name="rack2", events=(rack_loss(2),)),
        FutureSpec(name="x1.8", events=(traffic_scale(1.8),)),
        FutureSpec(name="maint", events=(maintenance(4, 5),)),
        FutureSpec(name="topic0", events=(topic_growth(0, 2.5),)),
        FutureSpec(name="hot", events=(hot_partitions((0, 1, 2), 3.0),)),
        FutureSpec(
            name="compound",
            events=(broker_loss(0), traffic_scale(1.5)),
        ),
    ]


# ---- batched = sequential, bit for bit ------------------------------------------
def test_batched_matches_sequential_bit_for_bit():
    state = _state()
    futures = _mixed_futures()
    batch = compile_futures(state, futures)
    raw = evaluate_batch(state, batch)
    for i, f in enumerate(futures):
        single = compile_futures(state, [f])
        raw1 = evaluate_batch(state, single)
        for key in raw:
            assert np.array_equal(raw[key][i], raw1[key][0]), (
                f"future {f.name!r} key {key!r}: batched row differs "
                "from its single-future dispatch"
            )


def test_verdict_semantics():
    state = _state()
    rows = verdicts(
        *(lambda b: (b, evaluate_batch(state, b)))(
            compile_futures(state, _mixed_futures())
        )
    )
    assert len(rows) == 7  # padding rows dropped
    by_name = {v["future"]: v for v in rows}
    # killing one broker of an rf-3 placement leaves partitions under-
    # replicated but never unavailable
    b3 = by_name["b3"]
    assert b3["survivable"] and b3["unavailablePartitions"] == 0
    assert b3["underReplicated"] > 0 and b3["movesRequired"] > 0
    # every verdict's goal count decomposes as documented
    for v in rows:
        assert v["goalViolations"] == (
            v["overloadedBrokers"] + v["rackViolations"]
        )
    # suggested actions only for futures that displace replicas
    assert b3["topActions"]
    assert all(a["from"] >= 0 and a["to"] >= 0 for a in b3["topActions"])
    assert by_name["x1.8"]["movesRequired"] == 0


def test_power_of_two_bucketing():
    assert [bucket_size(n) for n in (1, 8, 9, 16, 17, 64)] == \
        [MIN_BUCKET, 8, 16, 16, 32, 64]
    state = _state()
    batch = compile_futures(state, _mixed_futures()[:3])
    assert batch.padded_size == MIN_BUCKET
    assert batch.num_futures == 3
    assert list(batch.valid) == [True] * 3 + [False] * (MIN_BUCKET - 3)


def test_future_fingerprints_are_semantic():
    a = FutureSpec(name="a", events=(broker_loss(1),))
    b = FutureSpec(name="renamed", events=(broker_loss(1),))
    c = FutureSpec(name="a", events=(broker_loss(2),))
    assert a.fingerprint() == b.fingerprint()  # names are display-only
    assert a.fingerprint() != c.fingerprint()
    # and the JSON round trip preserves semantics
    assert parse_future(a.to_json()).fingerprint() == a.fingerprint()


def test_likely_futures_deterministic_and_load_ordered():
    state = _state()
    ranked = likely_futures(state, k=8)
    assert ranked == likely_futures(state, k=8)
    assert len(ranked) == 8
    assert all(f.events[0].kind == "rack_loss" for f in ranked[:4])


# ---- cache: hit / invalidate / generation bump ----------------------------------
def test_whatif_cache_hit_and_invalidate():
    cc, _, _ = full_stack()
    futures = [FutureSpec(name="b1", events=(broker_loss(1),))]
    first = cc.whatif(futures)
    assert not first.cached and first.batch_size == MIN_BUCKET
    again = cc.whatif(futures)
    assert again.cached and again.verdicts == first.verdicts
    cc.invalidate_proposal_cache("test")  # whatif rides the same hook
    third = cc.whatif(futures)
    assert not third.cached


def test_generation_bump_never_serves_stale_verdict():
    cc, _, reporter = full_stack()
    futures = [FutureSpec(name="b1", events=(broker_loss(1),))]
    assert not cc.whatif(futures).cached
    assert cc.whatif(futures).cached
    # a new completed window bumps model_generation: the cached verdict
    # is keyed to the old generation and must MISS, not serve stale
    gen = cc.load_monitor.model_generation()
    reporter.report(time_ms=3 * WINDOW + 500)
    cc.load_monitor.run_sampling_iteration(4 * WINDOW)
    assert cc.load_monitor.model_generation() != gen
    assert not cc.whatif(futures).cached


def test_use_cache_false_bypasses():
    cc, _, _ = full_stack()
    futures = [FutureSpec(name="b2", events=(broker_loss(2),))]
    cc.whatif(futures)
    assert not cc.whatif(futures, use_cache=False).cached


def test_whatif_max_futures_cap():
    cc, _, _ = full_stack()
    cc.whatif_max_futures = 2
    too_many = [
        FutureSpec(name=f"b{b}", events=(broker_loss(b),))
        for b in range(3)
    ]
    with pytest.raises(ValueError, match="whatif.max.futures"):
        cc.whatif(too_many)


# ---- precompute daemon covers the per-future warm set (satellite 2) -------------
def test_precompute_refreshes_stale_future_cache():
    from cruise_control_tpu.analyzer.precompute import (
        ProposalPrecomputingExecutor,
    )

    cc, _, reporter = full_stack()
    cc.whatif_precompute_futures = 4
    daemon = ProposalPrecomputingExecutor(cc, interval_s=3600)
    assert daemon.refresh_once()  # cold: fills plan AND warm futures
    assert cc.proposal_cache_fresh() and cc.whatif_cache_fresh()
    assert cc.whatif_cache_state()["entries"] == 4
    # both fresh → the daemon skips (the steady-state probe)
    assert not daemon.refresh_once()
    # generation bump: BOTH probes go stale, one refresh re-warms both
    reporter.report(time_ms=3 * WINDOW + 500)
    cc.load_monitor.run_sampling_iteration(4 * WINDOW)
    assert not cc.whatif_cache_fresh()
    assert daemon.refresh_once()
    assert cc.whatif_cache_fresh()
    # the satellite-2 fix: plan still fresh, ONLY the future set stale —
    # the old present-state-only probe would skip here and a stale
    # future could serve; the generalized probe refreshes it
    cc._whatif_cache.invalidate("test")
    assert cc.proposal_cache_fresh() and not cc.whatif_cache_fresh()
    assert daemon.refresh_once()
    assert cc.whatif_cache_fresh()
    # precomputed futures now answer whatif queries as cache hits
    from cruise_control_tpu.server.progress import OperationProgress

    state = cc._model(None, OperationProgress("TEST"))
    assert cc.whatif(likely_futures(state, 4)).cached


def test_precompute_disabled_keeps_old_semantics():
    from cruise_control_tpu.analyzer.precompute import (
        ProposalPrecomputingExecutor,
    )

    cc, _, _ = full_stack()  # whatif_precompute_futures defaults to 0
    daemon = ProposalPrecomputingExecutor(cc, interval_s=3600)
    assert daemon.refresh_once()
    assert cc.whatif_cache_fresh()  # disabled == always fresh
    assert not daemon.refresh_once()
    assert cc.whatif_cache_state()["entries"] == 0


# ---- POST /whatif behind the front-door contract --------------------------------
@pytest.fixture
def server():
    from cruise_control_tpu.server import CruiseControlHttpServer

    cc, backend, _ = full_stack()
    srv = CruiseControlHttpServer(cc, port=0)
    srv.start()
    yield srv, cc, backend
    srv.stop()


def _client(srv, **kw):
    from cruise_control_tpu.client.cccli import CruiseControlClient

    return CruiseControlClient(srv.url, **kw)


def _raw_post(srv, endpoint, **params):
    import urllib.error
    import urllib.parse
    import urllib.request

    url = f"{srv.url}/{endpoint}"
    if params:
        url += "?" + urllib.parse.urlencode(params)
    req = urllib.request.Request(url, method="POST", data=b"")
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, dict(resp.headers), json.load(resp)
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), None


class TestWhatifEndpoint:
    def test_default_futures_long_poll(self, server):
        srv, _, _ = server
        body = _client(srv).post("whatif")
        assert body["numFutures"] >= 1
        assert body["generation"]
        assert not body["cached"]
        for v in body["verdicts"]:
            assert {"future", "survivable", "goalViolations"} <= set(v)

    def test_explicit_futures_and_cache_hit(self, server):
        srv, _, _ = server
        c = _client(srv)
        spec = json.dumps([{
            "name": "lose-b1",
            "events": [{"kind": "kill_broker", "broker": 1}],
        }])
        first = c.post("whatif", futures=spec)
        assert first["numFutures"] == 1 and not first["cached"]
        assert first["verdicts"][0]["future"] == "lose-b1"
        again = c.post("whatif", futures=spec)
        assert again["cached"]
        assert again["verdicts"] == first["verdicts"]

    def test_malformed_futures_is_400(self, server):
        from cruise_control_tpu.client.cccli import CruiseControlError

        srv, _, _ = server
        for bad in ("not json", "[]",
                    '[{"events": [{"kind": "meteor_strike"}]}]'):
            with pytest.raises(CruiseControlError) as e:
                _client(srv).post("whatif", futures=bad)
            assert e.value.code == 400

    def test_deadline_202_then_completion(self, server):
        """The async deadline contract: a zero-budget long poll answers
        202 + task id immediately; re-polling the task id completes."""
        srv, _, _ = server
        code, _, body = _raw_post(srv, "whatif", get_response_timeout_s="0")
        assert code == 202
        task_id = body["UserTaskId"]
        done = _client(srv).post("whatif", user_task_id=task_id)
        assert done["numFutures"] >= 1

    def test_admission_control_429_with_retry_after(self):
        from cruise_control_tpu.server import (
            CruiseControlHttpServer,
            UserTaskManager,
        )

        cc, _, _ = full_stack()
        srv = CruiseControlHttpServer(
            cc, port=0,
            user_task_manager=UserTaskManager(max_active_tasks=0),
        )
        srv.start()
        try:
            code, headers, _ = _raw_post(srv, "whatif")
            assert code == 429
            assert headers.get("Retry-After") == "2"
        finally:
            srv.stop()


# ---- proactive: trigger fires BEFORE the virtual-clock peak ---------------------
class _FacadeStub:
    """Records the proactive scheduler's calls; returns a scripted
    verdict."""

    def __init__(self, verdict):
        self.verdict = verdict
        self.whatif_calls = []
        self.rebalances = 0

    def whatif(self, futures):
        self.whatif_calls.append(tuple(futures))

        class _R:
            verdicts = [dict(self.verdict)]

        return _R()

    def rebalance(self, dryrun):
        assert dryrun is False
        self.rebalances += 1


_BREACH = {
    "survivable": True, "goalViolations": 1, "overloadedBrokers": 1,
    "unavailablePartitions": 0,
}
_FINE = {
    "survivable": True, "goalViolations": 0, "overloadedBrokers": 0,
    "unavailablePartitions": 0,
}

HOUR_MS = 3_600_000


def _fed_scheduler(cc, period_ms=4 * HOUR_MS, until_ms=30 * 60_000,
                   amplitude=0.5, **kw):
    """A scheduler fed a clean sinusoid sampled every minute up to
    ``until_ms`` — peak at period/4 (t = 1 hour for the default)."""
    sched = ProactiveScheduler(
        cc, period_ms=period_ms, horizon_ms=2 * HOUR_MS,
        threshold=1.1, cooldown_ms=HOUR_MS, clock=lambda: until_ms, **kw,
    )
    for t in range(0, until_ms + 1, 60_000):
        mult = 1.0 + amplitude * np.sin(2 * np.pi * t / period_ms)
        sched.record(t, 1000.0 * mult)
    return sched


def test_proactive_triggers_before_projected_peak():
    cc = _FacadeStub(_BREACH)
    sched = _fed_scheduler(cc)
    now_ms = 30 * 60_000
    assert sched.maybe_trigger(now_ms)
    assert cc.rebalances == 1
    # the what-if asked about a genuine FUTURE: the projected peak (the
    # sinusoid crests at t = 1h) is still ahead of the trigger time
    (future,) = cc.whatif_calls[0]
    factor = future.events[0].arg("factor")
    assert factor > 1.1  # peak multiplier over the current one
    assert now_ms < HOUR_MS  # triggered with the peak still ahead


def test_proactive_survivable_peak_does_not_trigger():
    cc = _FacadeStub(_FINE)
    sched = _fed_scheduler(cc)
    assert not sched.maybe_trigger(30 * 60_000)
    assert cc.rebalances == 0
    assert sched.state_summary()["lastSkipReason"] == "peak-survivable"


def test_proactive_skips_without_signal():
    cc = _FacadeStub(_BREACH)
    sched = ProactiveScheduler(cc, period_ms=4 * HOUR_MS,
                               clock=lambda: 0.0)
    assert not sched.maybe_trigger(0.0)  # no samples at all
    assert sched.state_summary()["lastSkipReason"] == "insufficient-samples"
    flat = _fed_scheduler(cc, amplitude=0.0)
    assert not flat.maybe_trigger(30 * 60_000)  # constant load
    assert cc.rebalances == 0


def test_proactive_cooldown_suppresses_retrigger():
    cc = _FacadeStub(_BREACH)
    sched = _fed_scheduler(cc)
    assert sched.maybe_trigger(30 * 60_000)
    assert not sched.maybe_trigger(31 * 60_000)
    assert sched.state_summary()["lastSkipReason"] == "cooldown"
    assert cc.rebalances == 1


# ---- the forecast API shared by sim and scheduler (satellite 1) -----------------
def test_fit_diurnal_recovers_the_synthesizers_curve():
    """The forecast fit and the workload synthesizer speak ONE formula:
    fitting samples of ``diurnal_multiplier`` reproduces the curve (and
    its peak) to numerical tolerance."""
    from cruise_control_tpu.sim.workload import (
        diurnal_multiplier,
        fit_diurnal,
    )

    period, amp = 4 * HOUR_MS, 0.35
    samples = [
        (t, 100.0 * diurnal_multiplier(t, amp, period, 0.0))
        for t in range(0, 2 * HOUR_MS, 5 * 60_000)
    ]
    fc = fit_diurnal(samples, period)
    assert fc is not None
    assert fc.amplitude == pytest.approx(amp, abs=1e-6)
    for t in (0, 30 * 60_000, HOUR_MS, 3 * HOUR_MS):
        assert fc.multiplier_at(t) == pytest.approx(
            diurnal_multiplier(t, amp, period, 0.0), abs=1e-6
        )
    peak_t, peak_mult = fc.peak_within(0, period)
    assert peak_t == pytest.approx(period / 4, rel=0.01)  # sin crest
    assert peak_mult == pytest.approx(1.0 + amp, abs=1e-4)


def test_fit_diurnal_refuses_unfittable_input():
    from cruise_control_tpu.sim.workload import fit_diurnal

    assert fit_diurnal([], 1000) is None
    assert fit_diurnal([(0, 1.0)] * 3, 1000) is None          # < 4 samples
    assert fit_diurnal([(5, 1.0), (5, 2.0), (5, 3.0), (5, 4.0)],
                       1000) is None                          # zero span
    assert fit_diurnal([(0, 1.0), (1, float("nan")), (2, 1.0), (3, 1.0)],
                       1000) is None                          # non-finite


# ---- the committed artifact keeps the headline claims honest --------------------
def test_committed_whatif_artifact_gates():
    art = json.loads(ARTIFACT_PATH.read_text())
    validate(art, SCHEMAS["cc-tpu-whatif/1"])
    assert art["allOk"] and all(art["gates"].values())
    assert art["batch"]["numFutures"] >= 64
    assert art["batch"]["numDispatches"] == 1
    assert art["batch"]["ratio"] < 2.0
    pro, rea = art["proactive"]["proactive"], art["proactive"]["reactive"]
    assert pro["healP99Ms"] < rea["healP99Ms"]
    assert pro["anomalies"] == 0 and rea["fixesStarted"] > 0
    assert art["proactive"]["leadVirtualMs"] > 0
