"""Monitor-layer tests: aggregator windows/completeness/extrapolation,
capacity resolver, sample store replay, reporter→sampler→processor pipeline,
and LoadMonitor end-to-end into the analyzer (SURVEY.md §2.3, §3.3)."""

import json

import numpy as np
import pytest

from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.monitor.aggregator import (
    AggregationOptions,
    Extrapolation,
    MetricSampleAggregator,
)
from cruise_control_tpu.monitor.capacity import (
    BrokerCapacityConfigFileResolver,
    StaticCapacityResolver,
)
from cruise_control_tpu.monitor.load_monitor import (
    BackendMetadataClient,
    ClusterTopology,
    LoadMonitor,
    LoadMonitorState,
    ModelCompletenessRequirements,
    NotEnoughValidWindowsError,
    StaticMetadataClient,
)
from cruise_control_tpu.monitor.metric_defs import partition_metric_def
from cruise_control_tpu.monitor.sampling import (
    MetricsProcessor,
    MetricsReporterSampler,
    MetricsTopic,
    SimulatedMetricsReporter,
    WorkloadModel,
    estimate_partition_cpu,
    ModelParameters,
    P_CPU,
    P_NW_IN,
)
from cruise_control_tpu.monitor.sample_store import FileSampleStore

WINDOW = 1000


def make_agg(num_entities=2, num_windows=3, min_samples=1):
    return MetricSampleAggregator(
        partition_metric_def(), num_entities, WINDOW, num_windows, min_samples
    )


def vec(cpu=0.0, nw_in=0.0):
    d = partition_metric_def()
    v = [0.0] * d.num_metrics
    v[d.metric_info("CPU_USAGE").metric_id] = cpu
    v[d.metric_info("LEADER_BYTES_IN").metric_id] = nw_in
    return v


class TestAggregator:
    def test_avg_aggregation_within_window(self):
        agg = make_agg()
        agg.add_sample(0, 100, vec(cpu=10))
        agg.add_sample(0, 200, vec(cpu=20))
        agg.add_sample(0, WINDOW + 100, vec(cpu=99))  # opens window 1
        out = agg.aggregate()
        # only window 0 is complete; CPU is AVG-aggregated
        assert out.values.shape[1] == 1
        assert out.values[0, 0, P_CPU] == pytest.approx(15.0)

    def test_incomplete_window_extrapolated_avg_adjacent(self):
        agg = make_agg(num_entities=2, min_samples=1)
        for w in range(3):
            agg.add_sample(0, w * WINDOW + 1, vec(cpu=10 * (w + 1)))
        # entity 1 misses window 1
        agg.add_sample(1, 1, vec(cpu=5))
        agg.add_sample(1, 2 * WINDOW + 1, vec(cpu=7))
        agg.add_sample(0, 3 * WINDOW + 1, vec())  # complete window 2
        agg.add_sample(1, 3 * WINDOW + 1, vec())
        out = agg.aggregate()
        assert out.extrapolations[1][1] == Extrapolation.AVG_ADJACENT
        assert out.values[1, 1, P_CPU] == pytest.approx(6.0)
        assert bool(out.entity_valid[1]) is True

    def test_entity_with_no_samples_is_invalid(self):
        agg = make_agg(num_entities=2)
        agg.add_sample(0, 1, vec(cpu=10))
        agg.add_sample(0, WINDOW + 1, vec(cpu=10))
        out = agg.aggregate()
        assert not out.entity_valid[1]
        assert out.extrapolations[1][0] == Extrapolation.NO_VALID_EXTRAPOLATION
        assert out.completeness.valid_entity_ratio == pytest.approx(0.5)

    def test_too_many_extrapolations_invalidate_entity(self):
        agg = MetricSampleAggregator(
            partition_metric_def(), 1, WINDOW, 4, min_samples_per_window=1
        )
        # entity 0 present only in windows 0 and 4 → 3 extrapolated windows
        agg.add_sample(0, 1, vec(cpu=10))
        agg.add_sample(0, 4 * WINDOW + 1, vec(cpu=10))
        out = agg.aggregate(AggregationOptions(max_allowed_extrapolations=2))
        assert not out.entity_valid[0]

    def test_old_sample_outside_retention_dropped(self):
        agg = make_agg(num_windows=2)
        assert agg.add_sample(0, 10 * WINDOW, vec(cpu=1))
        assert not agg.add_sample(0, 1, vec(cpu=1))


class TestCapacity:
    def test_file_resolver_with_default_and_jbod(self, tmp_path):
        doc = {
            "brokerCapacities": [
                {"brokerId": "-1",
                 "capacity": {"CPU": "100", "NW_IN": "10000",
                              "NW_OUT": "10000", "DISK": "500000"}},
                {"brokerId": "0",
                 "capacity": {"CPU": "200", "NW_IN": "20000",
                              "NW_OUT": "20000",
                              "DISK": {"/d1": "250000", "/d2": "250000"}}},
            ]
        }
        path = tmp_path / "capacity.json"
        path.write_text(json.dumps(doc))
        r = BrokerCapacityConfigFileResolver(str(path))
        assert r.capacity_for_broker(0).capacity[Resource.CPU] == 200
        assert r.capacity_for_broker(0).capacity[Resource.DISK] == 500000
        # unknown broker falls back to the -1 default entry
        info = r.capacity_for_broker(42)
        assert info.capacity[Resource.CPU] == 100 and info.is_estimated

    def test_missing_default_entry_raises(self, tmp_path):
        path = tmp_path / "capacity.json"
        path.write_text(json.dumps({"brokerCapacities": [
            {"brokerId": "0", "capacity": {"CPU": "1"}}]}))
        with pytest.raises(ValueError, match="default"):
            BrokerCapacityConfigFileResolver(str(path))


def make_workload(num_partitions=8, brokers=(0, 1, 2)):
    rng = np.random.default_rng(7)
    assignment = {
        p: [brokers[p % len(brokers)], brokers[(p + 1) % len(brokers)]]
        for p in range(num_partitions)
    }
    leaders = {p: assignment[p][0] for p in range(num_partitions)}
    return WorkloadModel(
        bytes_in=rng.uniform(100, 1000, num_partitions),
        bytes_out=rng.uniform(100, 2000, num_partitions),
        size_mb=rng.uniform(10, 500, num_partitions),
        assignment=assignment,
        leaders=leaders,
    )


class TestSamplingPipeline:
    def test_reporter_to_sampler_roundtrip(self):
        w = make_workload()
        topic = MetricsTopic()
        SimulatedMetricsReporter(w, topic).report(time_ms=500)
        sampler = MetricsReporterSampler(topic)
        psamples, bsamples = sampler.get_samples(0, 1000)
        assert len(psamples) == 8 and len(bsamples) == 3
        by_p = {s.partition: s for s in psamples}
        assert by_p[0].values[P_NW_IN] == pytest.approx(w.bytes_in[0])
        # sampler is offset-tracking: nothing new on the second poll
        assert sampler.get_samples(0, 1000) == ([], [])

    def test_partition_cpu_estimation_shares_broker_cpu(self):
        # two partitions on one broker: CPU attributed by traffic share
        cpu_a = estimate_partition_cpu(
            50.0, 300, 0, 400, 0, ModelParameters(1.0, 0.0))
        cpu_b = estimate_partition_cpu(
            50.0, 100, 0, 400, 0, ModelParameters(1.0, 0.0))
        assert cpu_a == pytest.approx(37.5) and cpu_b == pytest.approx(12.5)

    def test_processed_cpu_reflects_linear_model(self):
        w = make_workload()
        topic = MetricsTopic()
        SimulatedMetricsReporter(w, topic).report(time_ms=500)
        psamples, _ = MetricsReporterSampler(topic).get_samples(0, 1000)
        assert all(s.values[P_CPU] > 0 for s in psamples)


class TestSampleStore:
    def test_roundtrip_replay(self, tmp_path):
        w = make_workload()
        topic = MetricsTopic()
        SimulatedMetricsReporter(w, topic).report(time_ms=500)
        psamples, bsamples = MetricsReporterSampler(topic).get_samples(0, 1000)
        store = FileSampleStore(str(tmp_path / "samples"))
        store.store_samples(psamples, bsamples)
        p2, b2 = FileSampleStore(str(tmp_path / "samples")).load_samples()
        assert p2 == psamples and b2 == bsamples


def make_monitor(tmp_path=None, num_partitions=8, windows_to_fill=3):
    w = make_workload(num_partitions)
    topic = MetricsTopic()
    reporter = SimulatedMetricsReporter(w, topic)
    topo = ClusterTopology(
        assignment=w.assignment,
        leaders=w.leaders,
        broker_rack={0: 0, 1: 1, 2: 0},
        partition_topic={p: f"t{p % 2}" for p in w.assignment},
    )
    store = FileSampleStore(str(tmp_path / "s")) if tmp_path else None
    monitor = LoadMonitor(
        StaticMetadataClient(topo),
        MetricsReporterSampler(topic),
        sample_store=store,
        window_ms=WINDOW,
        num_windows=5,
    )
    for wdx in range(windows_to_fill):
        reporter.report(time_ms=wdx * WINDOW + 500)
        monitor.run_sampling_iteration((wdx + 1) * WINDOW)
    return monitor, w, reporter


class TestLoadMonitor:
    def test_cluster_model_end_to_end(self, tmp_path):
        monitor, w, _ = make_monitor(tmp_path)
        with monitor.acquire_for_model_generation():
            state = monitor.cluster_model(
                ModelCompletenessRequirements(min_required_num_windows=2)
            )
        assert state.num_partitions == 8 and state.num_brokers == 3
        # leader loads reflect the ground-truth workload
        nw_in = np.asarray(state.leader_load)[:, Resource.NW_IN]
        assert np.allclose(nw_in, w.bytes_in, rtol=1e-4)

    def test_insufficient_windows_raises(self, tmp_path):
        monitor, _, _ = make_monitor(tmp_path, windows_to_fill=1)
        with pytest.raises(NotEnoughValidWindowsError):
            monitor.cluster_model(
                ModelCompletenessRequirements(min_required_num_windows=5)
            )

    def test_pause_resume(self, tmp_path):
        monitor, _, reporter = make_monitor(tmp_path)
        monitor.pause_sampling()
        reporter.report(time_ms=10 * WINDOW)
        assert monitor.run_sampling_iteration(11 * WINDOW) == 0
        monitor.resume_sampling()
        assert monitor.state == LoadMonitorState.RUNNING

    def test_sample_store_replay_restores_model(self, tmp_path):
        monitor, w, _ = make_monitor(tmp_path)
        # a fresh monitor over the same store sees the same windows (LOADING)
        topo = ClusterTopology(
            assignment=w.assignment, leaders=w.leaders,
            broker_rack={0: 0, 1: 1, 2: 0},
            partition_topic={p: "t0" for p in w.assignment},
        )
        m2 = LoadMonitor(
            StaticMetadataClient(topo),
            MetricsReporterSampler(MetricsTopic()),
            sample_store=FileSampleStore(str(tmp_path / "s")),
            window_ms=WINDOW, num_windows=5,
        )
        s1 = monitor.cluster_model()
        s2 = m2.cluster_model()
        assert np.allclose(
            np.asarray(s1.leader_load), np.asarray(s2.leader_load)
        )

    def test_model_feeds_optimizer(self, tmp_path):
        from cruise_control_tpu.analyzer.goal_optimizer import GoalOptimizer
        monitor, _, _ = make_monitor(tmp_path)
        opt = GoalOptimizer()
        result = opt.optimize(monitor.cluster_model())
        # on a 3-broker toy cluster soft-goal totals may legitimately rise;
        # the guarantee is that hard goals end clean
        hard_after = sum(
            result.violations_after[g.name] for g in opt.goals if g.is_hard
        )
        assert hard_after == 0

    def test_backend_metadata_client(self):
        from cruise_control_tpu.executor.backend import SimulatedClusterBackend
        backend = SimulatedClusterBackend(
            {0: [0, 1], 1: [1, 2]}, {0: 0, 1: 1}, brokers={0, 1, 2}
        )
        topo = BackendMetadataClient(backend, {0: 0, 1: 1, 2: 0}).refresh()
        assert topo.assignment == {0: [0, 1], 1: [1, 2]}
        assert topo.alive_brokers == {0, 1, 2}


class TestReviewRegressions:
    def test_metrics_topic_retention_bounds_memory(self):
        """ISSUE 12: the in-memory reporter topic has Kafka-style
        retention — a 1000-broker day produces ~22M records, and the
        unbounded log was a multi-GB leak.  Absolute offsets survive the
        trim; a consumer that aged out resumes from the oldest retained
        record."""
        from cruise_control_tpu.monitor.sampling import CruiseControlMetric
        from cruise_control_tpu.monitor.sampling import RawMetricType as RT

        def rec(i):
            return CruiseControlMetric(RT.BROKER_CPU_UTIL, i, 0, float(i))

        topic = MetricsTopic(max_records=100)
        topic.produce([rec(i) for i in range(40)])
        got, off = topic.consume_from(0)
        assert len(got) == 40 and off == 40
        topic.produce([rec(i) for i in range(40, 250)])
        # retention trimmed to the newest 100; absolute length keeps
        # counting and the stored internal list is bounded
        assert len(topic) == 250
        assert len(topic._records) == 100
        # the up-to-date consumer sees exactly the new tail
        got, off2 = topic.consume_from(off)
        assert off2 == 250
        assert [r.time_ms for r in got] == list(range(150, 250))
        # an aged-out consumer resumes from the oldest retained record
        got, _ = topic.consume_from(10)
        assert [r.time_ms for r in got] == list(range(150, 250))

    def test_sampler_retains_future_records(self):
        """Records at/after end_ms are held for the next poll, not dropped
        (code-review regression)."""
        w = make_workload()
        topic = MetricsTopic()
        SimulatedMetricsReporter(w, topic).report(time_ms=1500)
        sampler = MetricsReporterSampler(topic)
        p1, b1 = sampler.get_samples(0, 1000)
        assert p1 == [] and b1 == []
        p2, _ = sampler.get_samples(1000, 2000)
        assert len(p2) == 8

    def test_aggregator_grows_with_topology(self):
        agg = make_agg(num_entities=2)
        agg.add_sample(0, 1, vec(cpu=1))
        agg.ensure_entities(5)
        assert agg.add_sample(4, 2, vec(cpu=9))
        agg.add_sample(0, WINDOW + 1, vec())
        agg.add_sample(4, WINDOW + 1, vec())
        out = agg.aggregate()
        assert out.values.shape[0] == 5
        assert out.values[4, 0, P_CPU] == pytest.approx(9.0)

    def test_monitor_survives_new_partition(self, tmp_path):
        """A partition appearing after monitor startup neither crashes
        sampling nor model generation (code-review regression)."""
        monitor, w, reporter = make_monitor(tmp_path)
        # grow the workload: partition 8 appears on brokers [0, 1]
        w.assignment[8] = [0, 1]
        w.leaders[8] = 0
        import numpy as _np
        w.bytes_in = _np.append(w.bytes_in, 100.0)
        w.bytes_out = _np.append(w.bytes_out, 100.0)
        w.size_mb = _np.append(w.size_mb, 10.0)
        monitor.metadata.topology.assignment[8] = [0, 1]
        monitor.metadata.topology.leaders[8] = 0
        monitor.metadata.topology.partition_topic[8] = "t0"
        reporter.report(time_ms=3 * WINDOW + 500)
        monitor.run_sampling_iteration(4 * WINDOW)
        state = monitor.cluster_model(
            ModelCompletenessRequirements(min_monitored_partitions_ratio=0.0)
        )
        assert state.num_partitions == 9
