"""Perf-trajectory contract (ISSUE 11 satellite): the committed
``BENCH_TRAJECTORY.md`` table is in sync with the ``BENCH_r*.json``
artifacts, and the LATEST round's gates all still hold — asserted from
the committed records alone, no bench re-run."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmarks"))

import bench_trajectory  # noqa: E402


def test_every_round_parses():
    rounds = bench_trajectory.load_rounds()
    assert len(rounds) >= 10
    numbers = [rnd for rnd, _ in rounds]
    assert numbers == sorted(numbers)
    for _, rec in rounds:
        assert rec["metric"] == "rebalance_plan_wallclock_50b_1000p"
        assert rec["value"] > 0


def test_committed_table_is_current():
    rounds = bench_trajectory.load_rounds()
    committed = bench_trajectory.OUTPUT.read_text()
    assert committed == bench_trajectory.render(rounds), (
        "BENCH_TRAJECTORY.md drifted from the BENCH_r*.json artifacts — "
        "regenerate via PYTHONPATH=. python benchmarks/bench_trajectory.py"
    )
    # every round is a row
    for rnd, _ in rounds:
        assert f"| r{rnd:02d} |" in committed


def test_latest_round_holds_every_gate():
    rounds = bench_trajectory.load_rounds()
    latest, rec = rounds[-1]
    verdicts = bench_trajectory.gate_verdicts(rec)
    # the full gate surface exists from round 11 on (soak gate included);
    # gates born later are required only once a bench round carries them
    required = ["northstar_s", "vs_baseline", "tracing_overhead_pct",
                "recorder_overhead_pct", "events_overhead_pct",
                "checkpoint_overhead_pct", "precompute_overhead_pct",
                "replan_overhead_pct", "slo_overhead_pct",
                "profiler_overhead_pct", "mesh_overhead_pct",
                "host_profiler_overhead_pct", "whatif_batch_ratio",
                "replan_settle_speedup", "soak_smoke"]
    if latest >= 19:
        required.append("lock_witness_overhead_pct")
    if latest >= 18:
        required.append("sharded_scaling")
    for gate in required:
        assert gate in verdicts, f"round r{latest} lost the {gate} gate"
        value, ok = verdicts[gate]
        assert ok, (
            f"round r{latest} fails {gate}: measured {value} — the perf "
            "trajectory regressed; see BENCH_TRAJECTORY.md"
        )
