"""REST server + CLI client tests: real HTTP over a loopback port, driven by
the cccli client class (upstream servlet + UserTaskManager semantics;
SURVEY.md §2.7)."""

import json

import pytest

from cruise_control_tpu.client.cccli import (
    CruiseControlClient,
    CruiseControlError,
    main as cccli_main,
)
from cruise_control_tpu.server import (
    BasicSecurityProvider,
    CruiseControlHttpServer,
)

from harness import full_stack


@pytest.fixture
def server():
    cc, backend, _ = full_stack()
    srv = CruiseControlHttpServer(cc, port=0)
    srv.start()
    yield srv, cc, backend
    srv.stop()


def client_for(srv, **kw) -> CruiseControlClient:
    return CruiseControlClient(srv.url, **kw)


class TestGetEndpoints:
    def test_state(self, server):
        srv, _, _ = server
        body = client_for(srv).get("state")
        assert body["MonitorState"]["state"] == "RUNNING"
        assert body["ExecutorState"]["state"] == "NO_TASK_IN_PROGRESS"

    def test_load(self, server):
        srv, _, _ = server
        body = client_for(srv).get("load")
        assert len(body["brokers"]) == 4
        assert all("DiskMB" in b for b in body["brokers"])

    def test_partition_load_sorted(self, server):
        srv, _, _ = server
        body = client_for(srv).get("partition_load", resource="NW_IN",
                                   entries=5)
        recs = body["records"]
        assert len(recs) == 5
        vals = [r["networkInbound"] for r in recs]
        assert vals == sorted(vals, reverse=True)

    def test_kafka_cluster_state(self, server):
        srv, _, backend = server
        body = client_for(srv).get("kafka_cluster_state")
        parts = body["KafkaPartitionState"]["partitions"]
        assert len(parts) == len(backend.partitions)

    def test_unknown_endpoint_404(self, server):
        srv, _, _ = server
        with pytest.raises(CruiseControlError) as e:
            client_for(srv).get("nonsense")
        assert e.value.code == 404


class TestAsyncProtocol:
    def test_rebalance_long_poll(self, server):
        srv, _, backend = server
        body = client_for(srv).post("rebalance", dryrun="false")
        assert body["numProposals"] > 0
        assert body["execution"]["succeeded"] is True
        assert "UserTaskId" in body
        leaders = [st.leader for st in backend.partitions.values()]
        assert leaders.count(0) < len(leaders)

    def test_dryrun_returns_proposals(self, server):
        srv, _, _ = server
        body = client_for(srv).post("rebalance", dryrun="true",
                                    verbose="true")
        assert body["numProposals"] == len(body["proposals"])

    def test_user_tasks_listing(self, server):
        srv, _, _ = server
        c = client_for(srv)
        done = c.post("rebalance", dryrun="true")
        tasks = c.get("user_tasks")["userTasks"]
        assert any(
            t["UserTaskId"] == done["UserTaskId"]
            and t["Status"] == "Completed"
            for t in tasks
        )

    def test_unknown_task_404(self, server):
        srv, _, _ = server
        with pytest.raises(CruiseControlError) as e:
            client_for(srv).post("rebalance", user_task_id="nope")
        assert e.value.code == 404

    def test_task_id_bound_to_endpoint(self, server):
        srv, _, _ = server
        c = client_for(srv)
        done = c.post("rebalance", dryrun="true")
        with pytest.raises(CruiseControlError) as e:
            c.post("add_broker", user_task_id=done["UserTaskId"])
        assert e.value.code == 400
        assert "belongs to rebalance" in str(e.value)

    def test_broker_operations(self, server):
        srv, _, backend = server
        c = client_for(srv)
        c.post("remove_broker", brokerid="3", dryrun="false")
        assert all(3 not in st.replicas for st in backend.partitions.values())
        c.post("demote_broker", brokerid="0", dryrun="false")
        assert all(st.leader != 0 for st in backend.partitions.values())

    def test_missing_brokerid_400(self, server):
        srv, _, _ = server
        with pytest.raises(CruiseControlError) as e:
            client_for(srv).post("remove_broker", dryrun="true")
        assert e.value.code == 400

    def test_operation_error_reported_500(self, server):
        srv, _, _ = server
        with pytest.raises(CruiseControlError) as e:
            client_for(srv).post("add_broker", brokerid="99", dryrun="true")
        assert e.value.code == 500
        assert "unknown broker" in str(e.value)


class TestSyncEndpoints:
    def test_pause_resume_sampling(self, server):
        srv, cc, _ = server
        c = client_for(srv)
        c.post("pause_sampling")
        assert cc.state()["MonitorState"]["state"] == "PAUSED"
        c.post("resume_sampling")
        assert cc.state()["MonitorState"]["state"] == "RUNNING"

    def test_stop_proposal_execution(self, server):
        srv, _, _ = server
        assert "stop" in client_for(srv).post(
            "stop_proposal_execution")["message"]

    def test_admin_self_healing_toggle(self, server):
        srv, cc, backend = server
        from cruise_control_tpu.detector import make_detector_manager

        make_detector_manager(cc, backend=backend)
        c = client_for(srv)
        body = c.post("admin", enable_self_healing_for="goal_violation")
        assert body["selfHealingEnabledChanged"] == {"GOAL_VIOLATION": True}
        st = c.get("state")
        assert st["AnomalyDetectorState"]["selfHealingEnabled"][
            "GOAL_VIOLATION"] is True

    def test_admin_concurrency(self, server):
        srv, cc, _ = server
        client_for(srv).post(
            "admin", concurrent_partition_movements_per_broker="9"
        )
        assert (cc.executor.config.
                num_concurrent_partition_movements_per_broker == 9)

    def test_train(self, server):
        srv, _, _ = server
        body = client_for(srv).post("train")
        assert body["trained"] is True
        assert 0.0 <= body["cpuWeightBytesIn"] <= 1.0

    def test_rightsize(self, server):
        srv, _, _ = server
        body = client_for(srv).post("rightsize")
        assert body["status"] in (
            "RIGHT_SIZED", "UNDER_PROVISIONED", "OVER_PROVISIONED"
        )
        assert "UserTaskId" in body

    def test_topic_configuration(self):
        cc, backend, _ = full_stack(rf=1)
        srv = CruiseControlHttpServer(cc, port=0)
        srv.start()
        try:
            body = client_for(srv).post(
                "topic_configuration", replication_factor="2",
                dryrun="false",
            )
            assert body["numProposals"] > 0
            assert all(
                len(set(st.replicas)) >= 2
                for st in backend.partitions.values()
            )
        finally:
            srv.stop()


class TestBackpressureHeaders:
    """429/503 responses carry Retry-After so clients back off instead of
    hammering (ISSUE 7 satellite)."""

    @staticmethod
    def _raw_post(srv, endpoint, **params):
        import urllib.error
        import urllib.parse
        import urllib.request

        url = f"{srv.url}/{endpoint}"
        if params:
            url += "?" + urllib.parse.urlencode(params)
        req = urllib.request.Request(url, method="POST", data=b"")
        try:
            with urllib.request.urlopen(req) as resp:
                return resp.status, dict(resp.headers)
        except urllib.error.HTTPError as e:
            return e.code, dict(e.headers)

    def test_429_carries_retry_after(self):
        from cruise_control_tpu.server import UserTaskManager

        cc, _, _ = full_stack()
        srv = CruiseControlHttpServer(
            cc, port=0, user_task_manager=UserTaskManager(max_active_tasks=0),
        )
        srv.start()
        try:
            code, headers = self._raw_post(srv, "rebalance", dryrun="true")
            assert code == 429
            assert headers.get("Retry-After") == "2"
        finally:
            srv.stop()

    def test_monitor_not_ready_503_carries_retry_after(self):
        cc, _, _ = full_stack(windows=0)  # no valid metric windows yet
        srv = CruiseControlHttpServer(cc, port=0)
        srv.start()
        try:
            code, headers = self._raw_post(
                srv, "rebalance", dryrun="true", get_response_timeout_s="10",
            )
            assert code == 503
            assert headers.get("Retry-After") == "30"
        finally:
            srv.stop()


class TestUserTaskManagerShutdown:
    def test_shutdown_cancels_queued_and_joins_bounded(self):
        import threading
        import time as time_mod

        from cruise_control_tpu.server import UserTaskManager

        mgr = UserTaskManager(max_workers=1)
        release = threading.Event()
        running = threading.Event()

        def block(progress):
            running.set()
            release.wait(timeout=30)
            return "done"

        first = mgr.submit("rebalance", block)
        assert running.wait(timeout=5)
        queued = mgr.submit("rebalance", lambda progress: "never runs")
        t0 = time_mod.perf_counter()
        mgr.shutdown(timeout_s=0.5)
        elapsed = time_mod.perf_counter() - t0
        # bounded: the blocked worker must not wedge shutdown
        assert elapsed < 5.0
        # the queued task is terminally cancelled, not eternally ACTIVE
        assert queued.state == "CompletedWithError"
        assert queued.completed_s is not None
        release.set()
        first.future.result(timeout=5)


class TestSecurity:
    def test_basic_auth_rejects_and_accepts(self):
        cc, _, _ = full_stack()
        srv = CruiseControlHttpServer(
            cc, port=0,
            security_provider=BasicSecurityProvider({"ccop": "s3cret"}),
        )
        srv.start()
        try:
            with pytest.raises(Exception):
                client_for(srv).get("state")
            body = client_for(srv, user="ccop", password="s3cret").get("state")
            assert body["MonitorState"]
            with pytest.raises(Exception):
                client_for(srv, user="ccop", password="wrong").get("state")
        finally:
            srv.stop()


class TestTwoStepVerification:
    def test_purgatory_flow(self):
        cc, backend, _ = full_stack()
        srv = CruiseControlHttpServer(cc, port=0, two_step_verification=True)
        srv.start()
        try:
            c = client_for(srv)
            body = c.post("rebalance", dryrun="false")
            rid = body["reviewId"]
            assert body["status"] == "PENDING_REVIEW"
            board = c.get("review_board")["requestInfo"]
            assert board and board[0]["EndPoint"] == "rebalance"
            c.post("review", approve=str(rid), reason="lgtm")
            done = c.post("rebalance", dryrun="false", review_id=str(rid))
            assert done["numProposals"] > 0
            # a second execution with the same review id is rejected
            with pytest.raises(CruiseControlError) as e:
                c.post("rebalance", dryrun="false", review_id=str(rid))
            assert e.value.code == 400
        finally:
            srv.stop()

    def test_approved_params_cannot_be_smuggled(self):
        cc, backend, _ = full_stack()
        srv = CruiseControlHttpServer(cc, port=0, two_step_verification=True)
        srv.start()
        try:
            c = client_for(srv)
            before = {
                p: list(st.replicas) for p, st in backend.partitions.items()
            }
            rid = c.post("rebalance", dryrun="true")["reviewId"]
            c.post("review", approve=str(rid))
            # resubmission tries to flip dryrun=false; the approved request
            # said dryrun=true and that is what must execute
            c.post("rebalance", dryrun="false", review_id=str(rid))
            after = {
                p: list(st.replicas) for p, st in backend.partitions.items()
            }
            assert before == after, "approval bypass: cluster was mutated"
        finally:
            srv.stop()

    def test_capacity_rejection_preserves_approval(self):
        from cruise_control_tpu.server import UserTaskManager

        cc, _, _ = full_stack()
        srv = CruiseControlHttpServer(
            cc, port=0, two_step_verification=True,
            user_task_manager=UserTaskManager(max_active_tasks=0),
        )
        srv.start()
        try:
            c = client_for(srv)
            rid = c.post("rebalance", dryrun="true")["reviewId"]
            c.post("review", approve=str(rid))
            with pytest.raises(CruiseControlError) as e:
                c.post("rebalance", dryrun="true", review_id=str(rid))
            assert e.value.code == 429
            board = c.get("review_board")["requestInfo"]
            assert board[0]["Status"] == "APPROVED", \
                "429 must not consume the approval"
        finally:
            srv.stop()

    def test_discarded_request_cannot_run(self):
        cc, _, _ = full_stack()
        srv = CruiseControlHttpServer(cc, port=0, two_step_verification=True)
        srv.start()
        try:
            c = client_for(srv)
            rid = c.post("rebalance", dryrun="true")["reviewId"]
            c.post("review", discard=str(rid))
            with pytest.raises(CruiseControlError) as e:
                c.post("rebalance", dryrun="true", review_id=str(rid))
            assert e.value.code == 400
        finally:
            srv.stop()


class TestCliMain:
    def test_main_state(self, server, capsys):
        srv, _, _ = server
        rc = cccli_main(["-a", f"http://127.0.0.1:{srv.port}", "state"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["MonitorState"]["state"] == "RUNNING"

    def test_main_rebalance_defaults_to_dryrun(self, server, capsys):
        srv, _, backend = server
        before = {p: list(st.replicas) for p, st in backend.partitions.items()}
        rc = cccli_main(
            ["-a", f"http://127.0.0.1:{srv.port}", "rebalance"]
        )
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["numProposals"] >= 0
        after = {p: list(st.replicas) for p, st in backend.partitions.items()}
        assert before == after, "bare rebalance must be a dry run"

    def test_main_no_dryrun_executes(self, server, capsys):
        srv, _, backend = server
        rc = cccli_main(
            ["-a", f"http://127.0.0.1:{srv.port}", "rebalance", "--no-dryrun"]
        )
        assert rc == 0
        leaders = [st.leader for st in backend.partitions.values()]
        assert leaders.count(0) < len(leaders)

    def test_main_connection_refused_clean_error(self, capsys):
        rc = cccli_main(["-a", "http://127.0.0.1:1", "state"])
        assert rc == 1
        assert "cannot reach" in capsys.readouterr().err

    def test_main_error_exit_code(self, server, capsys):
        srv, _, _ = server
        rc = cccli_main(
            ["-a", f"http://127.0.0.1:{srv.port}", "remove_broker", ""]
        )
        assert rc == 1
