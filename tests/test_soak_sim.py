"""Long-horizon soak (ISSUE 12): the composed fault schedule, the smoke
soak's SLO-gated survival, journal/checkpoint retention under load, and
the committed ``SOAK_r12.json`` gate table.

The ground-truth contract matches the scenario suite: every survival
assertion reads the run's event journal (plus the observer's resource
samples) — and the committed day artifact is re-validated field by field
the way ``test_bench_trajectory`` pins ``BENCH_r*.json``, so a soak
regression shows up in tier-1 without re-running the day."""

import json
import os
import pathlib

import pytest

from cruise_control_tpu.sim.fault_schedule import (
    DISRUPTIVE_KINDS,
    FaultScheduleConfig,
    ScheduleError,
    generate_timeline,
    schedule_summary,
)
from cruise_control_tpu.sim.soak import (
    MIN_MS,
    SOAKS,
    build_scenario_spec,
    make_soak_artifact,
    run_soak,
    smoke_spec,
    unhealed_types,
)
from test_artifact_schemas import SCHEMAS, validate

ARTIFACT_PATH = pathlib.Path(__file__).parent.parent / "SOAK_r12.json"

_cache = {}


def smoke_result(key="first"):
    """Run the smoke soak once per variant per session (reused across the
    gate, determinism, and retention tests)."""
    if key not in _cache:
        seed = smoke_spec().seed + (1 if key == "reseeded" else 0)
        _cache[key] = run_soak(smoke_spec(seed=seed))
    return _cache[key]


def test_no_leaked_real_clock_slo_engines():
    """Tripwire: a real-clock SloEngine leaked by an earlier test keeps
    evaluating the process-wide registry and journals its breach
    transitions into whatever journal is current — including a scenario
    run's virtual-clock journal, which breaks the pinned fingerprints
    below as a ~rare race instead of a diagnosable failure.  Fail HERE,
    deterministically, naming the hygiene problem (the leaker forgot
    ``app.shutdown()`` / ``engine.stop()``)."""
    import threading

    leaked = [t for t in threading.enumerate()
              if t.name == "cc-slo-engine" and t.is_alive()]
    assert not leaked, (
        "an earlier test leaked a started SloEngine thread; find the "
        "build_app()/SloEngine.start() without a matching shutdown"
    )


# ---- the schedule generator -----------------------------------------------------
def test_schedule_same_seed_same_timeline():
    cfg = FaultScheduleConfig(seed=3, duration_ms=12 * 60 * MIN_MS,
                              num_brokers=64, num_racks=4,
                              num_partitions=256)
    a = generate_timeline(cfg)
    b = generate_timeline(cfg)
    assert [e.to_json() for e in a.events] == [e.to_json() for e in b.events]
    c = generate_timeline(
        FaultScheduleConfig(seed=4, duration_ms=12 * 60 * MIN_MS,
                            num_brokers=64, num_racks=4,
                            num_partitions=256))
    assert [e.to_json() for e in a.events] != \
        [e.to_json() for e in c.events]


def test_schedule_layout_constraints():
    cfg = FaultScheduleConfig(seed=5, duration_ms=12 * 60 * MIN_MS,
                              num_brokers=128, num_racks=8,
                              num_partitions=512)
    tl = generate_timeline(cfg)
    faults = [e for e in tl.events if e.kind in DISRUPTIVE_KINDS]
    assert faults
    # settle head and quiet tail are fault-free
    assert min(e.at_ms for e in faults) >= cfg.settle_ms
    assert max(e.at_ms for e in faults) <= \
        cfg.duration_ms - cfg.quiet_tail_ms
    # minimum spacing between PRIMARY slots (paired secondaries — the
    # skew a crash arms against, the revert of a hot spell — share their
    # primary's slot by design)
    times = sorted({e.at_ms for e in faults})
    primaries = [times[0]]
    for t in times[1:]:
        if t - primaries[-1] >= cfg.min_spacing_ms:
            primaries.append(t)
    # every configured disruptive slot exists and is fully spaced
    n_slots = sum(cfg.class_counts().values())
    assert len(primaries) == n_slots
    # paired restores: every disk failure is repaired, outages restored
    kinds = tl.kinds()
    assert kinds.get("restore_disk", 0) == kinds.get("disk_failure", 0)
    assert kinds.get("restore_analyzer", 0) == \
        kinds.get("analyzer_outage", 0)
    # the traffic floor exists and covers the day
    polls = [e for e in tl.events if e.kind == "http_request"]
    assert len(polls) > 10
    summary = schedule_summary(tl, cfg)
    assert summary["distinctFaultClasses"] >= 8
    assert summary["events"] == len(tl)


def test_schedule_rejects_impossible_density():
    with pytest.raises(ScheduleError, match="spacing"):
        generate_timeline(FaultScheduleConfig(
            seed=0, duration_ms=60 * MIN_MS, num_brokers=8, num_racks=2,
            num_partitions=32,
        ))


# ---- relaxed spacing: bounded pile-ups (ISSUE 15 satellite) ---------------------
def _relaxed_cfg(seed=5):
    return FaultScheduleConfig(
        seed=seed, duration_ms=12 * 60 * MIN_MS, num_brokers=128,
        num_racks=8, num_partitions=512, min_spacing_relaxed=True,
        pileup_max_cluster=3,
    )


def test_relaxed_schedule_keeps_layout_invariants():
    """Pile-ups are a scripted burst, not an accident of density: fault
    slots cluster into groups of ≤ pileup_max_cluster events one minute
    apart, clusters keep the full min_spacing guarantee, and the settle
    head / quiet tail stay fault-free."""
    cfg = _relaxed_cfg()
    tl = generate_timeline(cfg)
    faults = [e for e in tl.events if e.kind in DISRUPTIVE_KINDS]
    assert faults
    assert min(e.at_ms for e in faults) >= cfg.settle_ms
    assert max(e.at_ms for e in faults) <= \
        cfg.duration_ms - cfg.quiet_tail_ms
    # group primary slots into clusters (1-minute adjacency), then check
    # the bound and the inter-cluster spacing
    times = sorted({e.at_ms for e in faults})
    clusters = [[times[0]]]
    for t in times[1:]:
        if t - clusters[-1][-1] <= MIN_MS:
            clusters[-1].append(t)
        else:
            clusters.append([t])
    # secondaries (heal pairs) share their primary's slot; the distinct
    # slot count still covers every configured fault
    n_slots = sum(cfg.class_counts().values())
    assert sum(len(c) for c in clusters) >= min(n_slots, len(times))
    assert any(len(c) > 1 for c in clusters), "no pile-up ever fired"
    for c in clusters:
        assert len(c) <= cfg.pileup_max_cluster
    for a, b in zip(clusters, clusters[1:]):
        gap = b[0] - a[-1]
        # heal-pair secondaries land heal_ms after their primary and may
        # sit between clusters; the PRIMARY grid pitch still guarantees
        # cluster starts are spaced
        assert b[0] - a[0] >= cfg.min_spacing_ms or gap >= MIN_MS
    # determinism: same seed ⇒ same relaxed schedule
    again = generate_timeline(_relaxed_cfg())
    assert [e.to_json() for e in tl.events] == \
        [e.to_json() for e in again.events]


def test_relaxed_off_is_byte_identical_to_historical_layout():
    """min_spacing_relaxed=False (and pileup_max_cluster=1) must not
    move a single event of existing seeded schedules — the soak
    fingerprints pinned on them depend on it."""
    base = FaultScheduleConfig(seed=5, duration_ms=12 * 60 * MIN_MS,
                               num_brokers=128, num_racks=8,
                               num_partitions=512)
    via_k1 = FaultScheduleConfig(seed=5, duration_ms=12 * 60 * MIN_MS,
                                 num_brokers=128, num_racks=8,
                                 num_partitions=512,
                                 min_spacing_relaxed=True,
                                 pileup_max_cluster=1)
    a = generate_timeline(base)
    b = generate_timeline(via_k1)
    assert [e.to_json() for e in a.events] == \
        [e.to_json() for e in b.events]


def test_relaxed_schedule_rejects_impossible_density():
    with pytest.raises(ScheduleError, match="cluster"):
        generate_timeline(FaultScheduleConfig(
            seed=0, duration_ms=60 * MIN_MS, num_brokers=8, num_racks=2,
            num_partitions=32, min_spacing_relaxed=True,
        ))


def test_soak_registry_and_wiring():
    assert set(SOAKS) == {"soak_smoke", "soak_day", "soak_pileup"}
    for name, factory in SOAKS.items():
        spec = factory()
        assert spec.name == name
        sspec = build_scenario_spec(spec)
        # the full stack is on: warm heals, checkpointed execution, the
        # real front door, the delta replanner
        assert sspec.replan_enabled and sspec.replan_heal
        assert sspec.checkpoint and sspec.serve_http
        assert sspec.engine == spec.engine
        assert len(sspec.timeline) > 0
    day = SOAKS["soak_day"]()
    assert day.num_brokers >= 1000


# ---- the smoke soak (tier-1: a few seconds of wall clock) -----------------------
def test_smoke_soak_all_gates_green():
    r = smoke_result()
    art = json.loads(json.dumps(make_soak_artifact(r)))
    validate(art, SCHEMAS["cc-tpu-soak/1"])
    assert art["allOk"] is True, art["gates"]
    for gate, v in art["gates"].items():
        if gate != "distinctFaultClasses":
            assert v is True, f"{gate}: {v}"
    assert art["heals"]["outcome"] == "HEALED"
    assert art["heals"]["unhealedTypes"] == []
    assert not unhealed_types(r.scenario.journal)
    assert art["slo"]["summary"]["allOk"] is True
    # the SLO table carries real data for the headline gates
    by = {row["name"]: row for row in art["slo"]["slos"]}
    for name in ("heal.latency.p99.ms", "serve.cached_get.p99.ms",
                 "replan.warm.duty.cycle", "http.unhandled.5xx",
                 "journal.growth.per.min"):
        assert by[name]["measured"] is not None, name
        assert by[name]["ok"] is True, name


def test_smoke_soak_heals_warm_through_the_replanner():
    """The closed loop in anger: a detector-driven self-heal rebalance
    served WARM through the DeltaReplanner (replan.heal.enabled), proven
    from the journal alone."""
    r = smoke_result()
    heal_replans = [
        e["payload"] for e in r.scenario.journal
        if e["kind"] == "replan.end" and e.get("operation") == "REBALANCE"
    ]
    assert heal_replans, "no self-heal ever routed through the replanner"
    assert any(p["mode"] == "warm" and p["deltaModel"] for p in heal_replans)
    # and the steady state stays warm: exactly one cold bootstrap plan
    assert [p["mode"] for p in r.scenario.replans()].count("cold") == 1
    assert r.scenario.fixes_started("GOAL_VIOLATION")


def test_smoke_soak_is_deterministic():
    first = smoke_result()
    again = run_soak(smoke_spec())
    if first.fingerprint() != again.fingerprint():
        # dump both journals so the mismatch is a diff, not a hash pair
        # (this is how the leaked-SloEngine contamination was caught)
        import json as _json
        import tempfile
        d = tempfile.gettempdir()
        for tag, res in (("first", first), ("again", again)):
            with open(os.path.join(
                    d, f"soak_diverge_{tag}.jsonl"), "w") as f:
                for r in res.scenario.journal:
                    f.write(_json.dumps(
                        r, sort_keys=True, default=str) + "\n")
        pytest.fail(
            "smoke soak fingerprints diverged between two in-process "
            f"runs — journals dumped to {d}/soak_diverge_*.jsonl; "
            "diff them (a foreign real-clock emitter in the scenario "
            "journal is the usual cause)"
        )
    assert first.fingerprint() == again.fingerprint()
    reseeded = smoke_result("reseeded")
    assert first.fingerprint() != reseeded.fingerprint()


def test_smoke_soak_journal_ts_follows_virtual_clock():
    """Satellite: the scenario journal's ts field is the VIRTUAL clock
    (seconds), so ts-windowed SLO evaluation follows scenario time."""
    r = smoke_result()
    horizon_s = r.scenario.duration_virtual_ms / 1000.0
    ts = [e["ts"] for e in r.scenario.journal]
    assert ts == sorted(ts)
    assert all(0.0 <= t <= horizon_s for t in ts)
    # records carrying an explicit virtual payload agree with their ts
    for e in r.scenario.journal:
        v = e.get("payload", {}).get("virtualMs")
        if v is not None:
            assert e["ts"] == pytest.approx(v / 1000.0, abs=1e-6)


def test_smoke_soak_exercises_journal_rotation_and_checkpoint():
    """Retention under load: the smoke's file-backed journal really
    rotated (total disk exceeds one file's cap) yet stayed bounded, and
    the execution checkpoint's high-water mark is live and bounded."""
    r = smoke_result()
    art = make_soak_artifact(r)
    j = art["resources"]["journal"]
    assert j["diskBytesMax"] > smoke_spec().journal_max_bytes  # rotated
    assert j["diskBytesMax"] <= j["diskBytesCap"]
    assert j["totalEvents"] == j["ringEvents"]  # ring never clipped
    ck = art["resources"]["checkpoint"]
    assert 0 < ck["bytesMax"] <= ck["bytesCap"]


# ---- retention regression (satellite: ~10k events must bound disk) --------------
def test_event_journal_rotation_bounds_disk_over_10k_events(tmp_path):
    from cruise_control_tpu.telemetry.events import EventJournal

    path = tmp_path / "events.jsonl"
    j = EventJournal(enabled=True, path=str(path), max_bytes=65536,
                     max_files=3, ring_size=256)
    for i in range(10_000):
        j.emit("executor.batch", tick=i, partitions=[i % 7, i % 11],
               phase="replica_moves")
    j.close()
    files = [path] + [tmp_path / f"events.jsonl.{k}" for k in (1, 2)]
    total = sum(f.stat().st_size for f in files if f.exists())
    assert (tmp_path / "events.jsonl.1").exists()  # rotation really ran
    assert total <= 3 * 65536 + 4096
    assert j.total_emitted == 10_000
    assert len(j.recent()) == 256  # ring bounded independently


def test_execution_checkpoint_compaction_bounds_disk(tmp_path):
    """10k task-state records over a bounded live task set: compaction
    keeps the on-disk checkpoint at O(task set), not O(record count)."""
    from cruise_control_tpu.executor.journal import ExecutionJournal

    path = tmp_path / "execution.ckpt.jsonl"
    j = ExecutionJournal(str(path), max_bytes=32_768)
    j.append("start", executionId=1, strategy="s", maxTicks=100,
             proposals=[], sizes={}, config={})
    for i in range(10_000):
        # 200 live tasks, 50 state transitions each
        j.append("task", taskIds=[i % 200],
                 state=("IN_PROGRESS" if i % 2 else "PENDING"), tick=i)
    j.close()
    # 10k appends at ~60 bytes each is ~600KB of raw log — compaction
    # must have run (high-water crossed the budget) and bounded the file
    assert j.high_water_bytes > 32_768
    size = path.stat().st_size
    assert size <= 32_768 + 16_384, (
        f"checkpoint grew to {size} bytes — compaction no longer bounds "
        "disk under long-horizon task churn"
    )
    ck = j.load()
    assert ck is not None and len(ck.tasks) == 200
    j.append("end", executionId=1)
    assert path.stat().st_size == 0  # terminal truncation


# ---- the committed day artifact (trajectory-table style) ------------------------
def test_committed_soak_artifact_gates():
    """SOAK_r12.json: the full-day 1000-broker fault schedule survived
    with every gate green — re-validated from the committed artifact
    alone (regenerate via ``python -m cruise_control_tpu.sim.soak
    --soak soak_day --with-smoke --artifact SOAK_r12.json``)."""
    art = json.loads(ARTIFACT_PATH.read_text())
    validate(art, SCHEMAS["cc-tpu-soak/1"])
    validate(art["slo"], SCHEMAS["cc-tpu-slo/1"])
    assert art["name"] == "soak_day"
    assert art["allOk"] is True
    assert art["scale"]["brokers"] >= 1000
    assert art["horizon"]["durationVirtualMs"] >= 24 * 60 * MIN_MS
    assert art["schedule"]["distinctFaultClasses"] >= 8
    gates = art["gates"]
    for gate, v in gates.items():
        if gate != "distinctFaultClasses":
            assert v is True, f"committed day fails {gate}"
    assert art["heals"]["outcome"] in ("HEALED", "NO_ANOMALY")
    assert art["heals"]["unhealedTypes"] == []
    assert art["heals"]["fixesStarted"] > 0
    assert art["heals"]["replans"]["warm"] > art["heals"]["replans"]["cold"]
    by = {row["name"]: row for row in art["slo"]["slos"]}
    assert by["http.unhandled.5xx"]["measured"] == 0.0
    assert by["http.shed.missing.retry.after"]["measured"] == 0.0
    assert art["slo"]["summary"]["breached"] == 0
    res = art["resources"]
    assert res["journal"]["diskBytesMax"] <= res["journal"]["diskBytesCap"]
    assert res["checkpoint"]["bytesMax"] <= res["checkpoint"]["bytesCap"]
    assert res["journal"]["totalEvents"] >= 1000


def test_committed_smoke_fingerprint_is_current():
    """The determinism teeth: today's smoke soak reproduces the
    fingerprint embedded in the committed day artifact bit for bit."""
    art = json.loads(ARTIFACT_PATH.read_text())
    smoke = art["smoke"]
    assert smoke["allOk"] is True
    r = smoke_result()
    assert r.spec.seed == smoke["seed"]
    assert r.fingerprint() == smoke["journalFingerprint"], (
        "smoke soak journal drifted from the committed artifact — "
        "behavior changed; regenerate SOAK_r12.json and review"
    )


# ---- the pile-up soak (slow) ----------------------------------------------------
@pytest.mark.slow
def test_pileup_soak_survives_concurrent_faults():
    """ISSUE 15 satellite: the relaxed-spacing schedule's bounded
    multi-fault bursts run end to end through the full stack — the day
    still ends healed with the placement invariants holding."""
    r = run_soak(SOAKS["soak_pileup"]())
    art = make_soak_artifact(r)
    validate(json.loads(json.dumps(art)), SCHEMAS["cc-tpu-soak/1"])
    assert art["heals"]["outcome"] == "HEALED", art["heals"]
    assert art["gates"]["placementInvariantsHold"] is True
    assert art["gates"]["terminalConvergence"] is True
    assert art["gates"]["zeroUnhealedAnomalies"] is True
    # the schedule really piled up: at least one pair of disruptive
    # faults fired one virtual minute apart
    times = sorted(
        e.at_ms
        for e in build_scenario_spec(SOAKS["soak_pileup"]()).timeline.events
        if e.kind in DISRUPTIVE_KINDS
    )
    assert any(b - a <= MIN_MS for a, b in zip(times, times[1:]))


# ---- the full day (slow) --------------------------------------------------------
@pytest.mark.slow
def test_full_day_soak_survives():
    """The whole production day, live (~tens of minutes of wall clock):
    every gate green at >=1000-broker scale."""
    if os.environ.get("CC_TPU_SLOW") != "1":
        pytest.skip("set CC_TPU_SLOW=1 to run the full-day soak")
    r = run_soak(SOAKS["soak_day"]())
    art = make_soak_artifact(r)
    assert art["allOk"] is True, art["gates"]
    assert art["schedule"]["distinctFaultClasses"] >= 8
