"""Per-goal unit tests (upstream analyzer/goals/*Test.java tier) and
AnalyzerContext incremental-aggregate invariants."""

import numpy as np
import pytest

from cruise_control_tpu.common.resources import BrokerState, Resource
from cruise_control_tpu.analyzer.actions import ActionType, BalancingAction
from cruise_control_tpu.analyzer.context import AnalyzerContext, OptimizationOptions
from cruise_control_tpu.analyzer.goals.base import BalancingConstraint
from cruise_control_tpu.analyzer.goals.capacity import (
    DiskCapacityGoal,
    ReplicaCapacityGoal,
)
from cruise_control_tpu.analyzer.goals.distribution import (
    BrokerSetAwareGoal,
    DiskUsageDistributionGoal,
    LeaderReplicaDistributionGoal,
    MinTopicLeadersPerBrokerGoal,
    PreferredLeaderElectionGoal,
    ReplicaDistributionGoal,
)
from cruise_control_tpu.analyzer.goals.rack import RackAwareGoal
from cruise_control_tpu.models.builder import ClusterModelBuilder
from cruise_control_tpu.models.generators import (
    rack_unaware_cluster,
    random_cluster,
    small_deterministic_cluster,
)


def ctx_of(state, **kw):
    return AnalyzerContext(state, OptimizationOptions(**kw))


def test_context_aggregates_match_recount_after_moves():
    state = random_cluster(seed=11, num_brokers=12, num_partitions=200)
    ctx = ctx_of(state)
    rng = np.random.default_rng(0)
    applied = 0
    for _ in range(50):
        p = int(rng.integers(ctx.num_partitions))
        s = int(rng.integers(ctx.max_rf))
        dests = [
            b for b in range(ctx.num_brokers) if b not in ctx.assignment[p]
        ]
        if not dests:
            continue
        ctx.apply(
            BalancingAction(
                ActionType.INTER_BROKER_REPLICA_MOVEMENT,
                p, s, int(ctx.assignment[p, s]), dests[0],
            )
        )
        applied += 1
    assert applied > 30
    ctx.recompute_check()


def test_context_leadership_aggregates():
    state = small_deterministic_cluster()
    ctx = ctx_of(state)
    ctx.apply(
        BalancingAction(
            ActionType.LEADERSHIP_MOVEMENT, 0, 0,
            ctx.leader_broker(0), int(ctx.assignment[0, 1]), dest_slot=1,
        )
    )
    ctx.recompute_check()
    assert ctx.leader_broker(0) == 1


def test_rack_aware_goal_fixes_conflicts():
    state = rack_unaware_cluster()
    goal = RackAwareGoal()
    ctx = ctx_of(state)
    assert goal.violations(ctx) == 2
    goal.optimize(ctx, [])
    assert goal.violations(ctx) == 0
    ctx.recompute_check()


def test_rack_aware_acceptance_blocks_same_rack():
    state = rack_unaware_cluster()  # b0,b1 in r0; b2,b3 in r1
    goal = RackAwareGoal()
    ctx = ctx_of(state)
    # partition 2 = [b0, b2]; moving slot 0 (b0) to b1 keeps r0 free (ok),
    # moving to b3 collides with b2's rack r1
    mask = goal.accept_move(ctx, 2, 0)
    assert mask[1] and not mask[3]


def test_replica_capacity_goal():
    b = ClusterModelBuilder()
    cap = {r: 1e9 for r in Resource}
    for i in range(4):
        b.add_broker(f"r{i}", cap)
    for i in range(9):
        b.add_partition("T", [0], {Resource.DISK: 1.0})
    state = b.build()
    constraint = BalancingConstraint(max_replicas_per_broker=3)
    goal = ReplicaCapacityGoal(constraint)
    ctx = ctx_of(state)
    assert goal.violations(ctx) == 1
    goal.optimize(ctx, [])
    assert goal.violations(ctx) == 0
    assert ctx.broker_replica_count.max() <= 3
    ctx.recompute_check()


def test_disk_capacity_goal_sheds_overload():
    b = ClusterModelBuilder()
    cap = {Resource.CPU: 1e9, Resource.NW_IN: 1e9, Resource.NW_OUT: 1e9,
           Resource.DISK: 100.0}
    for i in range(3):
        b.add_broker(f"r{i}", cap)
    # 6 partitions of 20 MB all on broker 0 -> 120 > 80 (threshold .8)
    for i in range(6):
        b.add_partition("T", [0], {Resource.DISK: 20.0})
    state = b.build()
    goal = DiskCapacityGoal()
    ctx = ctx_of(state)
    assert goal.violations(ctx) == 1
    goal.optimize(ctx, [])
    assert goal.violations(ctx) == 0
    assert ctx.broker_load[0, Resource.DISK] <= 80.0 + 1e-6
    ctx.recompute_check()


def test_dead_broker_evacuation_via_hard_goal():
    state = random_cluster(seed=21, num_brokers=10, num_partitions=60,
                           dead_brokers=2)
    goal = RackAwareGoal()
    ctx = ctx_of(state)
    goal.optimize(ctx, [])
    assert not ctx.replica_offline.any()
    dead = ~ctx.broker_alive
    assert not np.isin(ctx.assignment, np.nonzero(dead)[0]).any()
    ctx.recompute_check()


def test_disk_usage_distribution_balances():
    b = ClusterModelBuilder()
    cap = {Resource.CPU: 1e9, Resource.NW_IN: 1e9, Resource.NW_OUT: 1e9,
           Resource.DISK: 1000.0}
    for i in range(4):
        b.add_broker(f"r{i % 2}", cap)
    # all load on brokers 0/1
    for i in range(8):
        b.add_partition("T%d" % (i % 2), [i % 2], {Resource.DISK: 50.0})
    state = b.build()
    goal = DiskUsageDistributionGoal()
    ctx = ctx_of(state)
    before = goal.violations(ctx)
    assert before > 0
    goal.optimize(ctx, [])
    assert goal.violations(ctx) < before
    ctx.recompute_check()


def test_replica_distribution_balances_counts():
    b = ClusterModelBuilder()
    cap = {r: 1e9 for r in Resource}
    for i in range(4):
        b.add_broker(f"r{i}", cap)
    for i in range(12):
        b.add_partition("T", [0], {Resource.DISK: 1.0})
    state = b.build()
    goal = ReplicaDistributionGoal()
    ctx = ctx_of(state)
    goal.optimize(ctx, [])
    counts = ctx.broker_replica_count
    assert counts.max() - counts.min() <= 2
    ctx.recompute_check()


def test_leader_distribution_moves_leadership():
    b = ClusterModelBuilder()
    cap = {r: 1e9 for r in Resource}
    for i in range(3):
        b.add_broker(f"r{i}", cap)
    # all leaders on broker 0, followers spread
    for i in range(9):
        b.add_partition("T", [0, 1 + i % 2], {Resource.DISK: 1.0})
    state = b.build()
    goal = LeaderReplicaDistributionGoal()
    ctx = ctx_of(state)
    before = ctx.broker_leader_count.copy()
    goal.optimize(ctx, [])
    after = ctx.broker_leader_count
    assert after.max() < before.max()
    ctx.recompute_check()


def test_preferred_leader_election():
    b = ClusterModelBuilder()
    cap = {r: 1e9 for r in Resource}
    for i in range(3):
        b.add_broker(f"r{i}", cap)
    b.add_partition("T", [0, 1], {Resource.DISK: 1.0}, leader_slot=1)
    b.add_partition("T", [1, 2], {Resource.DISK: 1.0}, leader_slot=0)
    state = b.build()
    goal = PreferredLeaderElectionGoal()
    ctx = ctx_of(state)
    assert goal.violations(ctx) == 1
    goal.optimize(ctx, [])
    assert goal.violations(ctx) == 0
    assert ctx.leader_slot[0] == 0


def test_min_topic_leaders_goal():
    b = ClusterModelBuilder()
    cap = {r: 1e9 for r in Resource}
    for i in range(2):
        b.add_broker(f"r{i}", cap)
    # topic 0 with 4 partitions, all led by broker 0, followers on broker 1
    for i in range(4):
        b.add_partition("Watched", [0, 1], {Resource.DISK: 1.0})
    state = b.build()
    constraint = BalancingConstraint(
        min_topic_leaders_per_broker=1, min_topic_leaders_topics={0}
    )
    goal = MinTopicLeadersPerBrokerGoal(constraint)
    ctx = ctx_of(state)
    assert goal.violations(ctx) == 1  # broker 1 has no leaders
    goal.optimize(ctx, [])
    assert goal.violations(ctx) == 0
    ctx.recompute_check()


def test_broker_set_aware_goal():
    b = ClusterModelBuilder()
    cap = {r: 1e9 for r in Resource}
    for i in range(4):
        b.add_broker(f"r{i}", cap)
    b.add_partition("Pinned", [0, 3], {Resource.DISK: 1.0})
    state = b.build()
    constraint = BalancingConstraint(broker_sets={0: {0, 1}})
    goal = BrokerSetAwareGoal(constraint)
    ctx = ctx_of(state)
    assert goal.violations(ctx) == 1  # replica on b3 outside {0,1}
    goal.optimize(ctx, [])
    assert goal.violations(ctx) == 0
    assert set(int(x) for x in ctx.assignment[0]) == {0, 1}


def test_excluded_topics_respected():
    state = random_cluster(seed=31, num_brokers=6, num_partitions=40,
                           num_topics=4)
    excluded = {0}
    goal = DiskUsageDistributionGoal()
    ctx = ctx_of(state, excluded_topics=excluded)
    before = ctx.assignment.copy()
    goal.optimize(ctx, [])
    topics = ctx.partition_topic
    mask = np.isin(topics, list(excluded))
    assert (ctx.assignment[mask] == before[mask]).all()


def test_capacity_goal_excluded_topic_fails_loudly():
    """Hard goal that can only be satisfied by moving excluded replicas must
    raise, not silently move them (code-review regression)."""
    from cruise_control_tpu.analyzer.goal_optimizer import GoalOptimizer, make_goals
    from cruise_control_tpu.analyzer.goals.base import OptimizationFailure

    b = ClusterModelBuilder()
    cap = {Resource.CPU: 1e9, Resource.NW_IN: 1e9, Resource.NW_OUT: 1e9,
           Resource.DISK: 100.0}
    for i in range(3):
        b.add_broker(f"r{i}", cap)
    for i in range(2):
        b.add_partition("T", [0], {Resource.DISK: 45.0})
    with pytest.raises(OptimizationFailure):
        GoalOptimizer(make_goals(["DiskCapacityGoal"])).optimize(
            b.build(), OptimizationOptions(excluded_topics={0})
        )


def test_swap_records_single_action():
    state = small_deterministic_cluster()
    ctx = ctx_of(state)
    ctx.apply(
        BalancingAction(
            ActionType.INTER_BROKER_REPLICA_SWAP,
            partition=0, slot=1, source_broker=1, dest_broker=2,
            swap_partition=2, swap_slot=0,
        )
    )
    assert len(ctx.actions) == 1
    assert ctx.actions[0].action_type == ActionType.INTER_BROKER_REPLICA_SWAP
    ctx.recompute_check()


def test_sanity_check_empty_cluster():
    from cruise_control_tpu.models.cluster_state import sanity_check

    b = ClusterModelBuilder()
    b.add_broker("r0", {r: 1.0 for r in Resource})
    sanity_check(b.build())  # brokers-only cluster is valid


# ---------------------------------------------------------------------------------
# Swap fallback (upstream ResourceDistributionGoal/CapacityGoal
# INTER_BROKER_REPLICA_SWAP semantics — VERDICT r4 missing #1)
# ---------------------------------------------------------------------------------

def _count_saturated_overload():
    """Two brokers at max.replicas.per.broker with broker 0 over disk
    capacity: every single move adds a replica to a count-full broker, so
    ONLY a swap can shed the overload."""
    b = ClusterModelBuilder()
    cap = {Resource.CPU: 1e9, Resource.NW_IN: 1e9, Resource.NW_OUT: 1e9,
           Resource.DISK: 100.0}
    b0 = b.add_broker("r0", cap)
    b1 = b.add_broker("r1", cap)

    def disk(mb):
        return {Resource.CPU: 0.1, Resource.NW_IN: 0.1,
                Resource.NW_OUT: 0.1, Resource.DISK: mb}

    b.add_partition("T", [b0], disk(60.0))   # A
    b.add_partition("T", [b0], disk(30.0))   # B -> broker0 at 90 > 80
    b.add_partition("T", [b1], disk(10.0))   # C
    b.add_partition("T", [b1], disk(5.0))    # D -> broker1 at 15
    return b.build()


def test_capacity_goal_swap_fallback_required():
    """On the count-saturated fixture the old move-only shed is stuck
    (every destination fails ReplicaCapacityGoal) — the swap fallback must
    fix the hard violation with an INTER_BROKER_REPLICA_SWAP."""
    state = _count_saturated_overload()
    constraint = BalancingConstraint(max_replicas_per_broker=2)
    ctx = ctx_of(state)
    rcap = ReplicaCapacityGoal(constraint)
    dcap = DiskCapacityGoal(constraint)
    assert dcap.violations(ctx) == 1
    # single moves genuinely impossible: the partner broker is count-full
    from cruise_control_tpu.analyzer.goals.base import accepted_move_dests
    assert not accepted_move_dests(ctx, 0, 0, dcap, [rcap]).any()
    dcap.optimize(ctx, [rcap])
    assert dcap.violations(ctx) == 0
    assert rcap.violations(ctx) == 0
    swaps = [a for a in ctx.actions
             if a.action_type == ActionType.INTER_BROKER_REPLICA_SWAP]
    assert swaps, "plan must contain a swap — moves cannot fix this fixture"
    ctx.recompute_check()


def test_distribution_goal_swap_fallback_balances():
    """Count-saturated soft-goal twin: disk-usage distribution can only
    equalize via swaps when both brokers sit at the replica limit."""
    b = ClusterModelBuilder()
    cap = {Resource.CPU: 1e9, Resource.NW_IN: 1e9, Resource.NW_OUT: 1e9,
           Resource.DISK: 1000.0}
    b0 = b.add_broker("r0", cap)
    b1 = b.add_broker("r1", cap)

    def disk(mb):
        return {Resource.CPU: 0.1, Resource.NW_IN: 0.1,
                Resource.NW_OUT: 0.1, Resource.DISK: mb}

    b.add_partition("T", [b0], disk(400.0))
    b.add_partition("T", [b0], disk(300.0))  # broker0: 700
    b.add_partition("T", [b1], disk(60.0))
    b.add_partition("T", [b1], disk(40.0))   # broker1: 100
    state = b.build()
    # the count-preserving optimum is 440/360 — widen the balance band so
    # that optimum is IN bounds and the swap path can clear the violation
    constraint = BalancingConstraint(
        max_replicas_per_broker=2,
        balance_threshold={**BalancingConstraint().balance_threshold,
                           Resource.DISK: 1.4},
    )
    ctx = ctx_of(state)
    rcap = ReplicaCapacityGoal(constraint)
    goal = DiskUsageDistributionGoal(constraint)
    before = goal.violations(ctx)
    assert before > 0
    goal.optimize(ctx, [rcap])
    assert goal.violations(ctx) < before
    swaps = [a for a in ctx.actions
             if a.action_type == ActionType.INTER_BROKER_REPLICA_SWAP]
    assert swaps, "balancing this fixture requires swaps"
    assert rcap.violations(ctx) == 0
    ctx.recompute_check()


def test_full_greedy_stack_solves_count_saturated_fixture():
    """End-to-end: the full goal stack (which previously raised
    OptimizationFailure here) now solves the fixture via the swap path and
    the verifier accepts the plan."""
    from cruise_control_tpu.analyzer.goal_optimizer import (
        GoalOptimizer,
        make_goals,
    )
    from cruise_control_tpu.analyzer.verifier import verify_result

    state = _count_saturated_overload()
    constraint = BalancingConstraint(max_replicas_per_broker=2)
    result = GoalOptimizer(constraint=constraint).optimize(state)
    verify_result(state, result, make_goals(constraint=constraint))
    assert any(a.action_type == ActionType.INTER_BROKER_REPLICA_SWAP
               for a in result.actions)
