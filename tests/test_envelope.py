"""Binary metrics-envelope tests (VERDICT round-2 item #4): golden-byte
fixtures pin the upstream layout; the sampler consumes an
upstream-addressed topic (topic names + partition numbers, topic-scope
rates) end to end."""

import pytest

from cruise_control_tpu.kafka import FakeKafkaWire
from cruise_control_tpu.kafka.envelope import (
    EnvelopeError,
    EnvelopeRecord,
    MetricClassId,
    decode_record,
    encode_record,
    is_envelope,
)
from cruise_control_tpu.kafka.sampler import (
    KafkaMetricsReporter,
    KafkaMetricsReporterSampler,
    encode_metric_json,
)
from cruise_control_tpu.monitor.sampling import (
    CruiseControlMetric,
    RawMetricType,
)

# ---- golden bytes ----------------------------------------------------------
# Layout derives from upstream MetricSerde knowledge (see envelope.py
# provenance flag); these fixtures pin it against accidental drift.

GOLDEN = [
    (
        # BROKER_CPU_UTIL (id 5) @ t=1000, broker 7, value 0.5
        EnvelopeRecord(MetricClassId.BROKER, 5, 1000, 7, 0.5),
        "00"          # class BROKER
        "00"          # version 0
        "05"          # type id 5
        "00000000000003e8"  # time 1000
        "00000007"    # broker 7
        "3fe0000000000000",  # value 0.5
    ),
    (
        # topic-scope bytes-in (id 2) @ t=2000, broker 1, topic "tp", 8.0
        EnvelopeRecord(MetricClassId.TOPIC, 2, 2000, 1, 8.0, "tp"),
        "01" "00" "02"
        "00000000000007d0"
        "00000001"
        "00000002" "7470"   # len=2, "tp"
        "4020000000000000",
    ),
    (
        # PARTITION_SIZE (id 4) @ t=3000, broker 2, ("tp", 9), 100.0
        EnvelopeRecord(MetricClassId.PARTITION, 4, 3000, 2, 100.0, "tp", 9),
        "02" "00" "04"
        "0000000000000bb8"
        "00000002"
        "00000002" "7470"
        "00000009"
        "4059000000000000",
    ),
]


@pytest.mark.parametrize("record,hexbytes", GOLDEN)
def test_golden_bytes_encode(record, hexbytes):
    assert encode_record(record).hex() == hexbytes


@pytest.mark.parametrize("record,hexbytes", GOLDEN)
def test_golden_bytes_decode(record, hexbytes):
    assert decode_record(bytes.fromhex(hexbytes)) == record


def test_roundtrip_all_classes():
    for rec, _ in GOLDEN:
        assert decode_record(encode_record(rec)) == rec


def test_malformed_bytes_raise():
    with pytest.raises(EnvelopeError):
        decode_record(bytes.fromhex(GOLDEN[2][1])[:-4])  # truncated
    with pytest.raises(EnvelopeError):
        decode_record(bytes.fromhex(GOLDEN[0][1]) + b"xx")  # trailing
    with pytest.raises(EnvelopeError, match="version"):
        decode_record(bytes.fromhex("00" "09" + GOLDEN[0][1][4:]))


def test_unknown_type_id_preserved_not_crashing():
    rec = EnvelopeRecord(MetricClassId.BROKER, 42, 1, 1, 2.0)
    back = decode_record(encode_record(rec))
    assert back.type_id == 42 and back.metric_type is None


def test_is_envelope_discriminates_json():
    assert is_envelope(encode_record(GOLDEN[0][0]))
    assert not is_envelope(encode_metric_json(
        CruiseControlMetric(RawMetricType.PARTITION_SIZE, 1, 0, 1.0, 0)))


# ---- end-to-end over the wire ----------------------------------------------


class _Meta:
    """Minimal metadata resolver: 2 topics × 2 partitions on 2 brokers."""

    def __init__(self):
        from cruise_control_tpu.executor.backend import PartitionState

        self._keys = {("a", 0): 0, ("a", 1): 1, ("b", 0): 2, ("b", 1): 3}
        self.partitions = {
            0: PartitionState([0, 1], 0, set()),
            1: PartitionState([1, 0], 1, set()),
            2: PartitionState([0, 1], 0, set()),
            3: PartitionState([0, 1], 0, set()),
        }

    def key(self, tp):
        return self._keys[tp]

    def partition_topic_names(self):
        return {v: t for (t, _), v in self._keys.items()}


def test_sampler_consumes_real_reporter_topic():
    """Records exactly as the Java plugin writes them — named topics,
    partition numbers, TOPIC-scope rates, broker metrics — build samples
    with dense ids and distributed partition rates."""
    wire = FakeKafkaWire(assignment={("a", 0): [0, 1]})
    meta = _Meta()
    sampler = KafkaMetricsReporterSampler(wire, metadata=meta)
    wire.create_topic("__CruiseControlMetrics")
    recs = [
        # broker scope
        EnvelopeRecord(MetricClassId.BROKER, 5, 500, 0, 0.4),          # CPU
        EnvelopeRecord(MetricClassId.BROKER, 0, 500, 0, 300.0),        # in
        EnvelopeRecord(MetricClassId.BROKER, 1, 500, 0, 150.0),        # out
        # partition sizes for topic b on broker 0 (keys 2, 3)
        EnvelopeRecord(MetricClassId.PARTITION, 4, 500, 0, 75.0, "b", 0),
        EnvelopeRecord(MetricClassId.PARTITION, 4, 500, 0, 25.0, "b", 1),
        # topic-scope bytes-in for b on broker 0: distributed 75/25
        EnvelopeRecord(MetricClassId.TOPIC, 2, 500, 0, 200.0, "b"),
        # topic-scope for topic a on broker 0: only key 0 leads there,
        # no sizes reported → even split over the single member
        EnvelopeRecord(MetricClassId.TOPIC, 2, 500, 0, 40.0, "a"),
        # unknown type id and unknown partition: skipped, not fatal
        EnvelopeRecord(MetricClassId.BROKER, 99, 500, 0, 1.0),
        EnvelopeRecord(MetricClassId.PARTITION, 4, 500, 0, 1.0, "zz", 7),
    ]
    wire.produce("__CruiseControlMetrics",
                 [encode_record(r) for r in recs])
    psamples, bsamples = sampler.get_samples(0, 1000)
    by_p = {s.partition: s for s in psamples}
    from cruise_control_tpu.monitor.sampling import P_DISK, P_NW_IN

    nw_in = P_NW_IN
    disk = P_DISK
    assert by_p[2].values[nw_in] == pytest.approx(150.0)  # 200 × 75/100
    assert by_p[3].values[nw_in] == pytest.approx(50.0)   # 200 × 25/100
    assert by_p[0].values[nw_in] == pytest.approx(40.0)   # even over 1
    assert by_p[2].values[disk] == 75.0
    assert len(bsamples) == 1 and bsamples[0].broker_id == 0
    # unknown partition → skipped (a problem); unknown type id →
    # unmodeled (routine on a real cluster, debug-level)
    assert sampler.skipped == 1
    assert sampler.unmodeled == 1


def test_reporter_twin_writes_upstream_addressed_records():
    """With a tp resolver the twin writes real (topic, partition) addresses
    a genuine Cruise Control could consume; round-trips through our own
    sampler via the same resolver."""
    wire = FakeKafkaWire(assignment={("a", 0): [0, 1]})
    meta = _Meta()
    tp_of = {0: ("a", 0), 1: ("a", 1), 2: ("b", 0), 3: ("b", 1)}
    reporter = KafkaMetricsReporter(wire, tp_of=lambda k: tp_of[k])
    reporter.report([
        CruiseControlMetric(RawMetricType.PARTITION_SIZE, 500, 0, 64.0,
                            partition=2),
        CruiseControlMetric(RawMetricType.BROKER_CPU_UTIL, 500, 0, 0.3),
    ])
    raw, _ = wire.consume(reporter.topic, 0)
    decoded = [decode_record(r) for r in raw]
    assert decoded[0].topic == "b" and decoded[0].partition == 0
    assert decoded[0].metric_class == MetricClassId.PARTITION
    assert decoded[1].metric_class == MetricClassId.BROKER
    sampler = KafkaMetricsReporterSampler(wire, metadata=meta)
    psamples, _ = sampler.get_samples(0, 1000)
    assert psamples[0].partition == 2


def test_reporter_twin_dense_fallback_roundtrip():
    """Without a resolver the twin uses private dense addressing (topic
    ''), which the sampler maps straight back — the simulation rigs'
    path, binary by default."""
    wire = FakeKafkaWire(assignment={("a", 0): [0, 1]})
    reporter = KafkaMetricsReporter(wire)
    sampler = KafkaMetricsReporterSampler(wire)
    reporter.report([
        CruiseControlMetric(RawMetricType.PARTITION_BYTES_IN, 500, 0, 9.0,
                            partition=3),
        CruiseControlMetric(RawMetricType.PARTITION_SIZE, 500, 0, 70.0,
                            partition=3),
    ])
    raw, _ = wire.consume(reporter.topic, 0)
    assert all(is_envelope(r) for r in raw)
    psamples, _ = sampler.get_samples(0, 1000)
    assert len(psamples) == 1 and psamples[0].partition == 3


def test_json_debug_encoding_still_supported():
    """encoding='json' writes the debug rows; the sampler auto-detects a
    MIXED topic (old rows + new envelopes) record by record."""
    wire = FakeKafkaWire(assignment={("a", 0): [0, 1]})
    json_reporter = KafkaMetricsReporter(wire, encoding="json")
    bin_reporter = KafkaMetricsReporter(wire)
    json_reporter.report([
        CruiseControlMetric(RawMetricType.PARTITION_SIZE, 400, 0, 10.0,
                            partition=0)])
    bin_reporter.report([
        CruiseControlMetric(RawMetricType.PARTITION_SIZE, 450, 0, 20.0,
                            partition=1)])
    sampler = KafkaMetricsReporterSampler(wire)
    psamples, _ = sampler.get_samples(0, 1000)
    assert {s.partition for s in psamples} == {0, 1}


def test_newer_envelope_version_skipped_not_misrouted():
    """A newer serde version must hit decode_record's version error and be
    counted as skipped — not silently misrouted to the JSON decoder."""
    wire = FakeKafkaWire(assignment={("a", 0): [0, 1]})
    sampler = KafkaMetricsReporterSampler(wire)
    wire.create_topic("__CruiseControlMetrics")
    rec = bytearray(encode_record(GOLDEN[0][0]))
    rec[1] = 9  # future version byte
    assert is_envelope(bytes(rec))
    wire.produce("__CruiseControlMetrics", [bytes(rec)])
    assert sampler.get_samples(0, 10_000) == ([], [])
    assert sampler.skipped == 1


def test_topic_rate_for_stale_partition_skipped_not_crash():
    """A dense id the fresh describe no longer knows (deleted topic still
    present in the 1h-retention metrics topic) is skipped, not a KeyError
    that kills the fetcher loop."""
    wire = FakeKafkaWire(assignment={("a", 0): [0, 1]})
    meta = _Meta()
    meta._keys[("gone", 0)] = 9   # stale mapping, no live partition state
    sampler = KafkaMetricsReporterSampler(wire, metadata=meta)
    wire.create_topic("__CruiseControlMetrics")
    wire.produce("__CruiseControlMetrics", [encode_record(
        EnvelopeRecord(MetricClassId.TOPIC, 2, 500, 0, 10.0, "gone"))])
    assert sampler.get_samples(0, 1000) == ([], [])
    assert sampler.skipped == 1


def test_entire_batch_dropped_is_loud(caplog):
    """A non-empty batch in which EVERY record is dropped is the signature
    of a wire-format divergence (one-byte layout drift would do it): the
    sampler must log at ERROR, not hide behind the rate-limited warning,
    or the monitor sits in LOADING forever with no visible cause."""
    import logging

    wire = FakeKafkaWire(assignment={("a", 0): [0, 1]})
    sampler = KafkaMetricsReporterSampler(wire)
    wire.create_topic("__CruiseControlMetrics")
    bad = bytearray(encode_record(GOLDEN[0][0]))
    bad[1] = 9  # future version byte -> undecodable
    wire.produce("__CruiseControlMetrics", [bytes(bad), bytes(bad)])
    with caplog.at_level(logging.ERROR):
        assert sampler.get_samples(0, 10_000) == ([], [])
    assert any(
        "ENTIRE batch" in r.message for r in caplog.records
        if r.levelno >= logging.ERROR
    )
    # a batch with at least one usable record stays quiet at ERROR
    caplog.clear()
    wire.produce("__CruiseControlMetrics",
                 [bytes(bad), encode_record(GOLDEN[0][0])])
    with caplog.at_level(logging.ERROR):
        psamples, bsamples = sampler.get_samples(0, 10_000)
    assert not [r for r in caplog.records if r.levelno >= logging.ERROR]
