"""Host observatory (ISSUE 18): sampling profiler, lock/queue contention
telemetry, and end-to-end critical-path decomposition.

The profiler tests run on SYNTHETIC frame streams (``(thread_name,
folded_stack)`` ticks through :meth:`HostProfiler.ingest`) so role
mapping, window bounds and the capture ladder are pinned independently of
what this box's threads happen to be doing; the lock tests use a private
:class:`ContentionRegistry` and a deterministic lock-schedule fixture
(direct ``record_acquire`` calls) so the sustained-contention detector's
streak/cooldown semantics are exact.  One live test drives the real
``GET /profile/host`` 404 → arm → 202 → 200 ladder through the real HTTP
server with the real sampler daemon.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from cruise_control_tpu.telemetry import critical_path as cp
from cruise_control_tpu.telemetry import events
from cruise_control_tpu.telemetry import host_profile as hp
from cruise_control_tpu.telemetry.events import EventJournal
from cruise_control_tpu.utils import locks
from harness import full_stack
from test_artifact_schemas import SCHEMAS, validate

#: one synthetic sampling tick: every interesting thread role at once
_TICK = [
    ("Thread-12", "server/http_server:_dispatch;facade:serve_proposals"),
    ("cc-http-1", "server/http_server:_dispatch;server/admission:admit"),
    ("user-task_3", "executor/executor:execute_proposals"),
    ("anomaly-detector", "monitor/detector:_tick"),
    ("cc-slo-engine", "telemetry/slo:_maintenance"),
    ("MainThread", "bootstrap:main"),
    ("weird-daemon", "somewhere:spin"),
]


def _seq_clock(start=500.0, step=0.05):
    state = [start]

    def clock():
        state[0] += step
        return state[0]

    return clock


# ---- role mapping + folding ------------------------------------------------------
def test_role_mapping_prefixes():
    assert hp.role_for("cc-http-3") == "http-worker"
    assert hp.role_for("Thread-17") == "http-worker"
    assert hp.role_for("user-task_0") == "executor-drive"
    assert hp.role_for("anomaly-detector") == "detector"
    assert hp.role_for("proposal-precompute") == "precompute"
    assert hp.role_for("cc-slo-engine") == "slo-tick"
    assert hp.role_for("cc-flight-recorder") == "recorder"
    assert hp.role_for("metric-fetcher-manager-0") == "fetcher"
    assert hp.role_for("whatif-proactive") == "proactive"
    assert hp.role_for("MainThread") == "main"
    assert hp.role_for("somebody-else") == "other"


def test_short_file_is_package_relative_and_extensionless():
    assert hp._short_file(
        "/x/y/cruise_control_tpu/server/http_server.py"
    ) == "server/http_server"
    assert hp._short_file("/usr/lib/python3.11/threading.py") == "threading"


def test_fold_stack_is_root_first():
    import sys

    def inner():
        return hp.fold_stack(sys._getframe())

    def outer():
        return inner()

    folded = outer()
    frames = folded.split(";")
    # root-first: the CALLER precedes the callee (flame-graph order)
    assert frames.index("test_host_profile:outer") \
        < frames.index("test_host_profile:inner")
    assert frames[-1] == "test_host_profile:inner"


def test_fold_stack_depth_bounded():
    import sys

    def deep(n):
        if n == 0:
            return hp.fold_stack(sys._getframe(), max_depth=10)
        return deep(n - 1)

    assert len(deep(50).split(";")) == 10


# ---- window bounds ---------------------------------------------------------------
def test_stack_agg_overflow_folds_the_tail():
    agg = hp._StackAgg()
    for i in range(hp._MAX_STACKS_PER_ROLE):
        agg.record("r", f"s{i}", None)
    for _ in range(8):
        agg.record("r", "one-more-distinct", None)
    per = agg.stacks["r"]
    assert len(per) == hp._MAX_STACKS_PER_ROLE + 1
    assert per[hp._OVERFLOW_STACK] == 8
    assert agg.total == hp._MAX_STACKS_PER_ROLE + 8


def test_stack_agg_decay_halves_and_drops_zeros():
    agg = hp._StackAgg()
    for _ in range(10):
        agg.record("r", "hot", 1)
    agg.record("r", "cold", 1)
    agg.decay()
    assert agg.stacks["r"] == {"hot": 5}
    assert agg.total == 5
    assert agg.samples["r"] == 5


def test_window_decays_when_full():
    p = hp.HostProfiler(clock=_seq_clock())
    for _ in range(600):  # 600 ticks x 7 samples crosses the 4096 window
        p.ingest(_TICK)
    st = p.state()
    assert p.ticks == 600
    assert st["windowSamples"] < hp._WINDOW_MAX_SAMPLES
    # lifetime counters never decay
    assert sum(p.lifetime_samples.values()) == 600 * len(_TICK)


# ---- the capture ladder ----------------------------------------------------------
def test_arm_ingest_parse_ladder_produces_schema_valid_artifact():
    p = hp.HostProfiler(interval_ms=25.0, clock=_seq_clock(),
                        id_factory=lambda: "host-capture-fixed")
    assert p.state()["state"] == "IDLE"
    st = p.arm(samples=2, reason="fixture")
    assert st["state"] == "ARMED" and st["captureId"] == "host-capture-fixed"
    # arming is idempotent while in flight
    assert p.arm(samples=99)["captureId"] == "host-capture-fixed"
    p.ingest(_TICK)
    assert p.state()["state"] == "ARMED"
    p.ingest(_TICK)
    st = p.state()
    assert st["state"] == "IDLE" and st["pendingParses"] == 1
    assert p.latest() is None  # the build is off-thread, not inline
    assert p.parse_pending() == 1
    art = p.latest()
    validate(json.loads(json.dumps(art)), SCHEMAS["cc-tpu-host-profile/1"])
    assert art["capture"]["id"] == "host-capture-fixed"
    assert art["capture"]["samplesCollected"] == 2
    assert art["totalSamples"] == 2 * len(_TICK)
    # both http-ish thread names fold into ONE role
    assert art["roles"]["http-worker"]["samples"] == 4
    assert art["roles"]["executor-drive"]["samples"] == 2
    assert art["roles"]["other"]["samples"] == 2
    # flame-graph folded lines: role as root frame, trailing count
    assert ("http-worker;server/http_server:_dispatch;"
            "facade:serve_proposals 2") in art["folded"]
    shares = [s["share"] for s in art["roles"]["http-worker"]["topStacks"]]
    assert sum(shares) == pytest.approx(1.0)


def test_parse_journals_profiler_host_parsed_deterministically():
    def run():
        journal = EventJournal(enabled=True, clock=lambda: 111.0)
        prev = events.JOURNAL
        events.JOURNAL = journal
        try:
            p = hp.HostProfiler(interval_ms=25.0)
            with p.scoped(clock=_seq_clock(),
                          id_factory=lambda: "host-capture-fixed"):
                p.arm(samples=2, reason="fixture")
                p.ingest(_TICK)
                p.ingest(_TICK)
                assert p.parse_pending() == 1
                art = p.latest()
        finally:
            events.JOURNAL = prev
        recs = [e for e in journal.recent()
                if e["kind"] == "profiler.host.parsed"]
        return recs, art

    recs1, art1 = run()
    recs2, art2 = run()
    assert len(recs1) == 1
    payload = recs1[0]["payload"]
    assert payload["captureId"] == "host-capture-fixed"
    assert payload["samples"] == 2
    assert payload["stacks"] == 2 * len(_TICK)
    assert payload["reason"] == "fixture"
    # bit-stable under the scoped clock/id factory: same bytes both runs
    assert json.dumps(recs1, sort_keys=True) == \
        json.dumps(recs2, sort_keys=True)
    assert json.dumps(art1, sort_keys=True) == \
        json.dumps(art2, sort_keys=True)


def test_real_clock_kinds_never_land_in_a_scenario_journal():
    """A bootstrap SLO engine elsewhere in the process pumps the
    contention detector / host-profile parser on REAL wall time; if one
    fires mid-scenario its emission must not reach the virtual-clock
    scenario journal, or the pinned scenario/soak fingerprints go
    nondeterministic on a loaded box."""
    from cruise_control_tpu.sim.simulator import _scenario_journal

    with _scenario_journal(clock=lambda: 42.0) as journal:
        # what a leaked maintenance tick would do mid-run
        events.emit("contention.hot_lock", severity="WARNING",
                    lock="model.semaphore", waitMs=300.0)
        events.emit("profiler.host.parsed", captureId="x", samples=1)
        events.emit("sim.scenario_start", name="t", seed=0)
    kinds = [r["kind"] for r in journal.recent()]
    assert kinds == ["sim.scenario_start"]


def test_exclude_kinds_is_per_journal_not_global():
    """The production journal still accepts both kinds (the /events and
    recorder surfaces depend on them) — exclusion is a property of the
    scenario journal alone."""
    journal = EventJournal(enabled=True, clock=lambda: 1.0)
    prev = events.JOURNAL
    events.JOURNAL = journal
    try:
        events.emit("contention.hot_lock", severity="WARNING",
                    lock="model.semaphore", waitMs=300.0)
        events.emit("profiler.host.parsed", captureId="x", samples=1)
    finally:
        events.JOURNAL = prev
    assert [r["kind"] for r in journal.recent()] == \
        ["contention.hot_lock", "profiler.host.parsed"]


def test_disabled_profiler_is_inert():
    p = hp.HostProfiler(enabled=False)
    assert p.ensure_started() is False
    assert p.arm(samples=1)["state"] == "IDLE"
    p.ingest(_TICK)
    st = p.state()
    assert st["windowSamples"] == 0 and st["samplerAlive"] is False


def test_pending_parse_queue_is_bounded():
    p = hp.HostProfiler(clock=_seq_clock())
    for _ in range(hp._MAX_PENDING_PARSES + 2):
        p.arm(samples=1, reason="x")
        p.ingest(_TICK)
    assert p.state()["pendingParses"] == hp._MAX_PENDING_PARSES
    assert p.parse_pending(max_parses=10) == hp._MAX_PENDING_PARSES


def test_profiler_families_expose_roles():
    p = hp.HostProfiler(clock=_seq_clock())
    assert p.families() == []  # nothing sampled yet: no empty families
    p.ingest(_TICK)
    fams = {f[0]: f[3] for f in p.families()}
    samples = dict((tuple(sorted(lbl.items()))[0][1], v)
                   for lbl, v in fams["cc_host_samples_total"])
    assert samples["http-worker"] == 2.0
    assert samples["main"] == 1.0


# ---- instrumented locks ----------------------------------------------------------
def test_instrumented_lock_measures_wait_and_hold():
    reg = locks.ContentionRegistry()
    lk = locks.InstrumentedLock("t.hot", registry=reg)
    entered = threading.Event()

    def worker():
        entered.set()
        with lk:
            pass

    with lk:
        t = threading.Thread(target=worker)
        t.start()
        assert entered.wait(5)
        time.sleep(0.15)  # make the worker's blocked wait measurable
    t.join(5)
    snap = reg.snapshot()["t.hot"]
    assert snap["acquisitions"] == 2
    assert snap["contended"] >= 1
    assert snap["waitMs"] > 0
    assert snap["holdMs"] >= 100  # we held it through the sleep
    assert snap["waitMaxMs"] <= snap["waitMs"] or snap["contended"] == 1


def test_instrumented_lock_timeout_abandon_records_the_wait():
    reg = locks.ContentionRegistry()
    lk = locks.InstrumentedLock("t.abandon", registry=reg)
    assert lk.acquire()
    out = []
    t = threading.Thread(target=lambda: out.append(
        lk.acquire(timeout=0.05)))
    t.start()
    t.join(5)
    lk.release()
    assert out == [False]
    snap = reg.snapshot()["t.abandon"]
    # the wait was real, the acquisition never happened
    assert snap["acquisitions"] == 1
    assert snap["contended"] == 1
    assert snap["waitMs"] >= 40
    assert not lk.locked()


def test_instrumented_lock_condition_interop_no_phantom_acquisitions():
    reg = locks.ContentionRegistry()
    cond = threading.Condition(locks.InstrumentedLock("t.cond",
                                                      registry=reg))
    waiting = threading.Event()
    got = []

    def waiter():
        with cond:
            waiting.set()
            got.append(cond.wait(timeout=5))

    t = threading.Thread(target=waiter)
    t.start()
    assert waiting.wait(5)
    with cond:
        cond.notify_all()
    t.join(5)
    assert got == [True]
    # exactly 3 acquisitions: waiter enter, notifier enter, waiter
    # re-acquire after notify — _is_owned kept Condition off the
    # nonblocking-probe fallback, so no phantom counts
    assert reg.snapshot()["t.cond"]["acquisitions"] == 3


def test_instrumented_semaphore_cross_thread_release_records_no_hold():
    reg = locks.ContentionRegistry()
    sem = locks.InstrumentedSemaphore(2, name="t.sem", registry=reg)
    with sem:
        time.sleep(0.02)
    same_thread_hold = reg.snapshot()["t.sem"]["holdMs"]
    assert same_thread_hold >= 10
    # a permit released by a DIFFERENT thread must not invent a hold
    assert sem.acquire()
    t = threading.Thread(target=sem.release)
    t.start()
    t.join(5)
    snap = reg.snapshot()["t.sem"]
    assert snap["acquisitions"] == 2
    assert snap["holdMs"] == same_thread_hold


def test_contention_detector_sustain_streak_and_cooldown():
    now = [1000.0]
    reg = locks.ContentionRegistry(threshold_ms=250.0, sustain_windows=2,
                                   cooldown_s=300.0, clock=lambda: now[0])
    st = reg.stats("server.hot")
    journal = EventJournal(enabled=True, clock=lambda: 42.0)
    prev = events.JOURNAL
    events.JOURNAL = journal
    try:
        # one hot window is a blip, not an event
        st.record_acquire(0.3)
        assert reg.check_pending() == 0
        # the second consecutive hot window journals exactly once
        st.record_acquire(0.3)
        assert reg.check_pending() == 1
        # still hot, but inside the cooldown: streak builds, no event
        st.record_acquire(0.3)
        assert reg.check_pending() == 0
        st.record_acquire(0.3)
        assert reg.check_pending() == 0
        # past the cooldown the sustained streak fires again
        now[0] += 301.0
        st.record_acquire(0.3)
        assert reg.check_pending() == 1
        # a quiet window resets the streak entirely
        st.record_acquire(0.1)
        assert reg.check_pending() == 0
        st.record_acquire(0.3)
        assert reg.check_pending() == 0
        assert reg.hot_events == 2
    finally:
        events.JOURNAL = prev
    recs = [e for e in journal.recent()
            if e["kind"] == "contention.hot_lock"]
    assert len(recs) == 2
    assert recs[0]["severity"] == "WARNING"
    payload = recs[0]["payload"]
    assert payload["lock"] == "server.hot"
    assert payload["windowWaitMs"] == pytest.approx(300.0)
    assert payload["windowAcquisitions"] == 1
    assert payload["sustainedWindows"] == 2
    assert payload["totalWaitMs"] >= payload["windowWaitMs"]
    assert "totalHoldMs" in payload


def test_lock_families_render_in_prometheus_exposition():
    from cruise_control_tpu.telemetry.exposition import render_prometheus
    from cruise_control_tpu.telemetry.tracing import Telemetry
    from cruise_control_tpu.utils.metrics import MetricRegistry

    # the journal's own lock is instrumented, so the row always exists
    events.JOURNAL._lock.acquire()
    events.JOURNAL._lock.release()
    fams = {f[0] for f in locks.CONTENTION.families()}
    assert fams == {"cc_lock_wait_ms", "cc_lock_hold_ms",
                    "cc_lock_acquisitions_total"}
    body = render_prometheus(MetricRegistry(), Telemetry(enabled=True))
    assert 'cc_lock_wait_ms{lock="journal.events"}' in body
    assert 'cc_lock_hold_ms{lock="journal.events"}' in body


# ---- per-request critical path ---------------------------------------------------
def test_phase_clock_partitions_the_wall_exactly():
    ticks = iter([i * 0.25 for i in range(100)])
    clock = cp.PhaseClock(clock=lambda: next(ticks))
    clock.mark("parse")
    clock.mark("auth")
    clock.mark("handler")
    clock.mark("handler")  # repeated names accumulate
    clock.mark("flush")
    phases = clock.phases()
    assert phases == {"parse": 0.25, "auth": 0.25,
                      "handler": 0.5, "flush": 0.25}
    assert sum(phases.values()) == clock.wall_s()  # exact, by construction


def test_request_scope_is_thread_local_and_safe_outside():
    cp.mark("nowhere")  # no active scope: safe no-op
    cp.set_endpoint("nowhere")
    store = cp.CriticalPathStore()
    with cp.request_scope(store=store):
        cp.set_endpoint("state")
        cp.mark("parse")
        cp.mark("handler")
    assert store.recorded == 1
    block = store.decompose("state")
    assert set(block["meanPhasesMs"]) == {"parse", "handler"}
    assert block["reconciliationPct"] == 100.0


def test_store_skips_requests_that_never_marked():
    store = cp.CriticalPathStore()
    with cp.request_scope(store=store):
        pass  # e.g. the /ui short-circuit: no marks, no wall
    assert store.recorded == 0 and store.snapshot() == {}


def test_decompose_percentiles_and_ring_bound():
    store = cp.CriticalPathStore(keep=64)
    ticks = iter([i * 0.001 for i in range(100000)])

    def one(extra_ms):
        clock = cp.PhaseClock(clock=lambda: next(ticks))
        clock.endpoint = "proposals"
        clock.mark("parse")
        for _ in range(extra_ms):
            clock.mark("handler")
        clock.mark("flush")
        store.record(clock)

    for i in range(100):
        one(1 + (i % 10))
    block = store.decompose("proposals")
    assert block["requests"] == 64  # ring-bounded
    assert block["wallP99Ms"] >= block["wallP50Ms"]
    assert block["p99"]["reconciliationPct"] == 100.0
    assert block["reconciliationPct"] == 100.0
    assert sum(block["p99"]["phasesMs"].values()) == \
        pytest.approx(block["p99"]["wallMs"])


# ---- per-heal critical path ------------------------------------------------------
_HEAL_JOURNAL = [
    {"ts": 100.0, "kind": "sim.fault"},
    {"ts": 101.5, "kind": "detector.anomaly"},
    {"ts": 101.6, "kind": "detector.recovery_cooldown"},
    {"ts": 103.0, "kind": "optimize.start"},
    {"ts": 105.0, "kind": "optimize.end"},
    {"ts": 105.2, "kind": "executor.start"},
    {"ts": 109.0, "kind": "executor.end"},
]


def test_heal_episode_exact_partition():
    eps = cp.heal_episodes(list(_HEAL_JOURNAL))
    assert len(eps) == 1
    ep = eps[0]
    assert ep["faultTs"] == 100.0 and ep["wallS"] == 9.0
    assert ep["phasesS"] == {
        "detection": 1.5, "admission": 0.1, "cooldownWait": 1.4,
        "planCompute": 2.0, "executionPrep": 0.2, "executionTicks": 3.8,
    }
    assert sum(ep["phasesS"].values()) == pytest.approx(ep["wallS"])
    assert ep["reconciliationPct"] == pytest.approx(100.0)


def test_heal_cooldown_anchor_is_optional():
    entries = [e for e in _HEAL_JOURNAL
               if e["kind"] != "detector.recovery_cooldown"]
    eps = cp.heal_episodes(entries)
    assert len(eps) == 1
    phases = eps[0]["phasesS"]
    assert "admission" not in phases
    assert phases["cooldownWait"] == 1.5  # anomaly → optimize.start
    assert eps[0]["reconciliationPct"] == pytest.approx(100.0)


def test_heal_incomplete_episode_skipped_and_next_fault_bounds():
    entries = [
        {"ts": 50.0, "kind": "sim.fault"},
        {"ts": 51.0, "kind": "detector.anomaly"},
        # heal still in flight when the next fault lands
    ] + list(_HEAL_JOURNAL)
    eps = cp.heal_episodes(entries)
    assert len(eps) == 1
    assert eps[0]["faultTs"] == 100.0


def test_build_artifact_reconciliation_is_worst_of_parts():
    serve = {"reconciliationPct": 99.5, "p99": {"reconciliationPct": 98.0}}
    heal = [{"reconciliationPct": 97.2}, {"reconciliationPct": 100.0}]
    art = cp.build_artifact(serve=serve, heal=heal, now=1234.0)
    assert art["schema"] == cp.SCHEMA
    assert art["reconciliationPct"] == 97.2
    assert cp.build_artifact(now=1.0)["reconciliationPct"] == 0.0


# ---- end-to-end through the real server ------------------------------------------
def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=30) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def test_arm_sample_poll_e2e_through_http_server():
    """Acceptance (ISSUE 18): GET /profile/host?arm=true → 202, the REAL
    sampler daemon collects the requested ticks, the (test-pumped)
    maintenance tick builds, and the poll returns a schema-valid
    cc-tpu-host-profile/1 artifact whose roles include the live server's
    own threads."""
    from cruise_control_tpu.server.http_server import (
        CruiseControlHttpServer,
    )
    from cruise_control_tpu.utils.metrics import MetricRegistry

    hp.PROFILER.reset()
    hp.configure(enabled=True, interval_ms=10.0)
    cc, _backend, _reporter = full_stack(registry=MetricRegistry())
    server = CruiseControlHttpServer(cc, port=0, access_log=False)
    server.start()
    try:
        status, _ = _get(f"{server.url}/profile/host")
        assert status == 404  # nothing captured yet
        status, body = _get(f"{server.url}/profile/host?arm=true&samples=3")
        assert status == 202
        assert body["capture"]["state"] == "ARMED"
        assert body["capture"]["samplesRequested"] == 3
        deadline = time.monotonic() + 30
        while hp.PROFILER.state()["pendingParses"] < 1:
            assert time.monotonic() < deadline, "sampler never completed"
            status, _ = _get(f"{server.url}/profile/host")
            assert status == 202  # armed / building — poll semantics
            time.sleep(0.02)
        # production pumps this from the SLO tick; tests pump directly
        assert hp.parse_pending() == 1
        status, art = _get(f"{server.url}/profile/host")
        assert status == 200
        validate(art, SCHEMAS["cc-tpu-host-profile/1"])
        assert art["capture"]["reason"] == "http"
        assert art["capture"]["samplesCollected"] == 3
        assert art["totalSamples"] > 0
        # the serving thread answering our polls is visible to itself
        assert "http-worker" in art["roles"]
    finally:
        server.stop()
        hp.PROFILER.stop()
        hp.PROFILER.reset()
        hp.configure(interval_ms=50.0)


def test_profile_host_503_when_disabled():
    from cruise_control_tpu.server.http_server import (
        CruiseControlHttpServer,
    )
    from cruise_control_tpu.utils.metrics import MetricRegistry

    cc, _backend, _reporter = full_stack(registry=MetricRegistry())
    server = CruiseControlHttpServer(cc, port=0, access_log=False)
    server.start()
    hp.configure(enabled=False)
    try:
        status, body = _get(f"{server.url}/profile/host")
        assert status == 503
        assert "telemetry.host.enabled" in body["errorMessage"]
    finally:
        hp.configure(enabled=True)
        server.stop()


def test_host_blocks_merge_into_flight_recorder_artifact():
    from cruise_control_tpu.telemetry.recorder import FlightRecorder
    from cruise_control_tpu.utils.metrics import MetricRegistry

    p = hp.HostProfiler(clock=_seq_clock())
    p.ingest(_TICK)
    reg = locks.ContentionRegistry()
    locks.InstrumentedLock("t.rec", registry=reg).acquire()
    store = cp.CriticalPathStore()
    with cp.request_scope(store=store):
        cp.set_endpoint("state")
        cp.mark("handler")
    rec = FlightRecorder(MetricRegistry(), interval_s=60.0, retention=8,
                         host_profile_source=p.summary,
                         contention_source=reg.snapshot,
                         critical_path_source=store.snapshot)
    art = rec.artifact()
    assert art["hostProfile"]["window"]["totalSamples"] == len(_TICK)
    assert art["lockContention"]["t.rec"]["acquisitions"] == 1
    assert art["criticalPath"]["state"]["requests"] == 1


# ---- committed critical-path artifact ---------------------------------------------
def test_committed_r18_artifact_decomposes_serve_and_heal():
    """The committed CRITICAL_PATH_r18 (``PYTHONPATH=. python
    benchmarks/critical_path.py``) is schema-valid, decomposes BOTH the
    cached-GET serve p99 and a soak heal episode into named phases, and
    every decomposition reconciles to >=95% of its measured wall — the
    ISSUE 18 acceptance gate."""
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "CRITICAL_PATH_r18.json")
    with open(path) as f:
        art = json.load(f)
    validate(art, SCHEMAS["cc-tpu-critical-path/1"])
    assert art["reconciliationPct"] >= 95.0
    serve = art["serve"]
    assert serve["endpoint"] == "proposals"
    assert serve["requests"] >= 100
    p99 = serve["p99"]
    assert sum(p99["phasesMs"].values()) == pytest.approx(
        p99["wallMs"], rel=0.05)
    assert art["heal"], "no heal episode decomposed"
    for ep in art["heal"]:
        assert ep["reconciliationPct"] >= 95.0
        assert sum(ep["phasesS"].values()) == pytest.approx(
            ep["wallS"], rel=0.05)
    scrape = art["metricsScrape"]
    # the satellite-1 before/after number: snapshot-then-render must
    # reduce registry-lock wait per scrape vs render-inside-lock
    assert (scrape["snapshotThenRender"]["lockWaitPerScrapeMs"]
            < scrape["renderInsideRegistryLock"]["lockWaitPerScrapeMs"])
