"""End-to-end optimizer tests on random clusters (upstream
RandomClusterTest + OptimizationVerifier tier; SURVEY.md §4 tier-1)."""

import numpy as np
import pytest

from cruise_control_tpu.analyzer.context import OptimizationOptions
from cruise_control_tpu.analyzer.goal_optimizer import (
    DEFAULT_GOAL_ORDER,
    GoalOptimizer,
    make_goals,
)
from cruise_control_tpu.analyzer.verifier import (
    verify_result,
    violation_score,
)
from cruise_control_tpu.models.generators import (
    Distribution,
    random_cluster,
)


@pytest.mark.parametrize(
    "dist", [Distribution.UNIFORM, Distribution.LINEAR, Distribution.EXPONENTIAL]
)
def test_full_stack_random_cluster(dist):
    state = random_cluster(
        seed=17, num_brokers=20, num_racks=5, num_partitions=300,
        distribution=dist, mean_utilization=0.4,
    )
    goals = make_goals()
    opt = GoalOptimizer(goals)
    result = opt.optimize(state)
    verify_result(state, result, goals)
    assert violation_score(result.final_state, goals) <= violation_score(state, goals)


def test_self_healing_dead_broker_replan():
    """BASELINE.json config #4: remove_broker / dead-broker replan."""
    state = random_cluster(
        seed=23, num_brokers=12, num_racks=4, num_partitions=150,
        dead_brokers=2,
    )
    goals = make_goals()
    opt = GoalOptimizer(goals)
    result = opt.optimize(state)
    verify_result(state, result, goals)
    # every replica off the dead brokers
    fa = np.array(result.final_state.assignment)
    assert not np.isin(fa, [10, 11]).any()


def test_add_broker_replan():
    state = random_cluster(
        seed=29, num_brokers=10, num_racks=5, num_partitions=120,
        new_brokers=2,
    )
    goals = make_goals()
    result = GoalOptimizer(goals).optimize(state)
    verify_result(state, result, goals)
    # new brokers (8, 9) received replicas
    fa = np.array(result.final_state.assignment)
    assert np.isin(fa, [8, 9]).sum() > 0


def test_remove_brokers_option():
    state = random_cluster(seed=37, num_brokers=8, num_racks=4, num_partitions=80)
    goals = make_goals()
    options = OptimizationOptions(brokers_to_remove={7})
    result = GoalOptimizer(goals).optimize(state, options)
    verify_result(state, result, goals, options)
    fa = np.array(result.final_state.assignment)
    assert not (fa == 7).any()


def test_proposals_roundtrip_and_summary():
    state = random_cluster(seed=41, num_brokers=10, num_partitions=100)
    goals = make_goals()
    result = GoalOptimizer(goals).optimize(state)
    verify_result(state, result, goals)
    s = result.summary()
    assert s["engine"] == "greedy"
    assert s["numProposals"] == len(result.proposals)
    for prop in result.proposals:
        d = prop.to_json()
        assert d["newReplicas"][0] == d["newLeader"]


def test_hard_goals_only_stack():
    """BASELINE.json config #2 goal subset."""
    state = random_cluster(seed=43, num_brokers=15, num_racks=5, num_partitions=200)
    goals = make_goals(
        ["RackAwareGoal", "ReplicaCapacityGoal", "DiskCapacityGoal"]
    )
    result = GoalOptimizer(goals).optimize(state)
    verify_result(state, result, goals)
