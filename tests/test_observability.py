"""PR-2 observability layer tests: Prometheus histogram exposition
contract, bounded O(1) Meter, flight-recorder sampling/retention/artifact
schema, compile/retrace detection, the bounded anomaly journal, and the
``GET /diagnostics`` server contract."""

import json
import re
import time
import urllib.error
import urllib.request
from collections import deque

import jax.numpy as jnp
import numpy as np
import pytest

from cruise_control_tpu.detector.anomalies import AnomalyType
from cruise_control_tpu.detector.manager import AnomalyDetectorManager
from cruise_control_tpu.detector.notifier import AnomalyNotificationResult
from cruise_control_tpu.server import CruiseControlHttpServer
from cruise_control_tpu.telemetry import device_stats, tracing
from cruise_control_tpu.telemetry.exposition import render_prometheus
from cruise_control_tpu.telemetry.recorder import SCHEMA, FlightRecorder
from cruise_control_tpu.utils.metrics import (
    DEFAULT_DURATION_BUCKETS,
    Histogram,
    Meter,
    MetricRegistry,
)

from harness import full_stack


# ---- histogram metric + exposition contract -------------------------------------
def test_histogram_buckets_are_cumulative_and_exhaustive():
    h = Histogram()
    for v in (0.0005, 0.003, 0.003, 0.2, 50.0, 1e6):  # incl. out-of-range
        h.update(v)
    buckets = h.cumulative_buckets()
    assert buckets[-1][0] == float("inf")
    assert buckets[-1][1] == h.count == 6
    cums = [c for _, c in buckets]
    assert cums == sorted(cums), "cumulative counts must be monotone"
    snap = h.snapshot()
    assert snap["buckets"]["+Inf"] == 6
    assert snap["count"] == 6
    assert snap["sum"] == pytest.approx(1000050.2065, abs=1e-3)
    assert snap["max"] == 1e6


def test_histogram_bounds_are_fixed_and_log_spaced():
    b = DEFAULT_DURATION_BUCKETS
    assert b[0] == pytest.approx(0.001) and b[-1] == pytest.approx(100.0)
    ratios = [b[i + 1] / b[i] for i in range(len(b) - 1)]
    for r in ratios:  # 3 per decade => constant ratio 10^(1/3)
        assert r == pytest.approx(10 ** (1 / 3), rel=1e-6)


def test_prometheus_histogram_family_contract():
    reg = MetricRegistry()
    hist = reg.histogram("queue.wait.seconds")
    for v in (0.002, 0.002, 0.3, 7.0):
        hist.update(v)
    text = render_prometheus(reg)
    assert "# TYPE cc_queue_wait_seconds histogram" in text
    assert 'cc_queue_wait_seconds_bucket{le="+Inf"} 4.0' in text
    assert "cc_queue_wait_seconds_count 4.0" in text
    assert "cc_queue_wait_seconds_sum" in text
    # every bucket line's cumulative count is monotone in le order
    pat = re.compile(r'cc_queue_wait_seconds_bucket\{le="([^"]+)"\} (\S+)')
    rows = [(float("inf") if le == "+Inf" else float(le), float(v))
            for le, v in pat.findall(text)]
    assert rows == sorted(rows), rows
    assert len(rows) == len(DEFAULT_DURATION_BUCKETS) + 1


def test_timer_emits_buckets_and_max_gauge():
    reg = MetricRegistry()
    reg.timer("op").update(0.05)
    reg.timer("op").update(2.0)
    text = render_prometheus(reg)
    assert "# TYPE cc_op_seconds histogram" in text
    assert 'cc_op_seconds_bucket{le="+Inf"} 2.0' in text
    assert "cc_op_seconds_count 2.0" in text
    assert "cc_op_seconds_max 2.0" in text
    snap = reg.snapshot()["timers"]["op"]
    assert snap["sumSec"] == pytest.approx(2.05)
    assert snap["p99Sec"] >= snap["p50Sec"]


# ---- Meter: O(1) bounded recent window ------------------------------------------
def test_meter_bursty_mark_is_bounded():
    m = Meter()
    for _ in range(50):
        m.mark(10_000)  # 500k events, one wall-clock second
    assert len(m._buckets) <= Meter._WINDOW_S
    # all 500k events collapse into (at most a few) per-second buckets
    assert len(m._buckets) <= 2
    snap = m.snapshot()
    assert snap["count"] == 500_000
    assert snap["fiveMinCount"] == 500_000


def test_meter_window_expires_old_seconds():
    m = Meter()
    m.mark(5)
    # age the bucket beyond the window and add a fresh one
    m._buckets[0][0] -= Meter._WINDOW_S + 10
    m.mark(3)
    snap = m.snapshot()
    assert snap["count"] == 8
    assert snap["fiveMinCount"] == 3


# ---- gauge hardening ------------------------------------------------------------
def test_snapshot_survives_raising_gauge():
    reg = MetricRegistry()
    reg.gauge("ok", lambda: 1.0)
    reg.gauge("boom", lambda: 1 / 0)
    snap = reg.snapshot()  # must not raise (GET /state JSON path)
    assert snap["gauges"]["ok"] == 1.0
    assert str(snap["gauges"]["boom"]).startswith("error:")
    # the exposition path skips the broken gauge entirely
    text = render_prometheus(reg)
    assert "cc_ok 1.0" in text
    assert "boom" not in text


# ---- flight recorder ------------------------------------------------------------
def test_recorder_samples_gauges_and_counter_rates():
    reg = MetricRegistry()
    reg.gauge("depth", lambda: 7.0)
    c = reg.counter("events")
    rec = FlightRecorder(reg, interval_s=1.0, retention=16)
    rec.sample_once(now=1000.0)      # baseline
    c.inc(30)
    rec.sample_once(now=1010.0)      # 30 events / 10 s
    series = rec.series_snapshot()
    assert series["gauge:depth"]["points"] == [[1000.0, 7.0], [1010.0, 7.0]]
    assert series["rate:events"]["points"] == [[1010.0, 3.0]]


def test_recorder_retention_bounds_series():
    reg = MetricRegistry()
    reg.gauge("g", lambda: 1.0)
    rec = FlightRecorder(reg, interval_s=1.0, retention=4)
    for i in range(10):
        rec.sample_once(now=float(i))
    pts = rec.series_snapshot()["gauge:g"]["points"]
    assert len(pts) == 4
    assert pts[0][0] == 6.0  # oldest retained point


def test_recorder_artifact_schema_and_journal_merge(tmp_path):
    reg = MetricRegistry()
    reg.gauge("g", lambda: 2.0)
    journal = [
        {"action": "IGNORE", "timeMs": 2000},
        {"action": "FIX", "timeMs": 1000},
    ]
    rec = FlightRecorder(
        reg, interval_s=1.0, retention=8,
        journal_source=lambda: list(journal),
        extra_sources=[lambda: {"jit.compiles": 5.0}],
        dump_dir=str(tmp_path),
        device_stats_source=lambda: {"enabled": True},
    )
    rec.sample_once(now=0.0)
    art = rec.artifact()
    assert art["schema"] == SCHEMA == "cc-tpu-flight-recorder/1"
    assert art["interval_s"] == 1.0 and art["retention"] == 8
    assert "gauge:g" in art["series"]
    # journal is merged TIME-ORDERED regardless of source order
    assert [e["timeMs"] for e in art["events"]] == [1000, 2000]
    assert art["deviceStats"] == {"enabled": True}
    json.dumps(art)  # crash-readable = JSON-serializable
    # dump-to-file carries the reason and the same schema
    path = rec.dump("FIX_FAILED:GOAL_VIOLATION")
    assert path is not None
    dumped = json.loads(open(path).read())
    assert dumped["schema"] == SCHEMA
    assert dumped["dumpReason"] == "FIX_FAILED:GOAL_VIOLATION"


def test_recorder_background_thread_samples_and_restarts():
    reg = MetricRegistry()
    reg.gauge("g", lambda: 1.0)
    rec = FlightRecorder(reg, interval_s=0.02, retention=64)
    rec.start()
    deadline = time.monotonic() + 5
    while (time.monotonic() < deadline
           and len(rec.series_snapshot().get("gauge:g", {})
                   .get("points", [])) < 2):
        time.sleep(0.02)
    rec.stop()
    n = len(rec.series_snapshot()["gauge:g"]["points"])
    assert n >= 2
    rec.start()  # bench interleaving restarts the same instance
    rec.stop()


# ---- compile / retrace detection ------------------------------------------------
def test_retrace_detector_flags_shape_churn():
    mon = device_stats.DeviceStatsMonitor(enabled=True, retrace_threshold=2)
    import jax

    fn = mon.instrument("test.fn", jax.jit(lambda x: x * 2))
    for n in (1, 2, 3, 4):
        np.testing.assert_allclose(
            np.asarray(fn(jnp.ones(n))), 2 * np.ones(n))
    st = mon.per_function()["test.fn"]
    assert st["compiles"] == 4
    assert st["distinctShapes"] == 4
    # shapes 3 and 4 exceeded the threshold of 2
    assert st["retraces"] == 2
    assert st["compileSec"] > 0
    # repeat shapes hit the jit cache: no new compile counted
    fn(jnp.ones(2))
    assert mon.per_function()["test.fn"]["compiles"] == 4
    totals = mon.totals()
    assert totals["jit.compiles"] == 4.0 and totals["jit.retraces"] == 2.0


def test_disabled_monitor_passes_through():
    mon = device_stats.DeviceStatsMonitor(enabled=False)
    import jax

    fn = mon.instrument("test.off", jax.jit(lambda x: x + 1))
    fn(jnp.ones(3))
    assert mon.per_function() == {}
    assert mon.live_buffer_stats() == (0, 0)


def test_instrumented_fn_delegates_attributes():
    import jax

    mon = device_stats.DeviceStatsMonitor(enabled=True)
    fn = mon.instrument("test.attr", jax.jit(lambda x: x))
    assert fn._cache_size() == 0  # pjit private API reachable through wrap
    fn(jnp.ones(2))
    assert fn._cache_size() == 1


# ---- bounded anomaly journal ----------------------------------------------------
class _StubAnomaly:
    def __init__(self, ts, fail=False):
        self.anomaly_type = AnomalyType.GOAL_VIOLATION
        self.detected_ms = ts
        self.description = f"stub@{ts}"
        self._fail = fail

    def to_json(self):
        return {"description": self.description}

    def fix(self, cc, progress):
        if self._fail:
            raise RuntimeError("fix exploded")


class _StubNotifier:
    def __init__(self, action):
        self._action = action

    def on_anomaly(self, anomaly, now_ms):
        return self._action

    def self_healing_enabled(self):
        return {}


class _StubExecutor:
    has_ongoing_execution = False


class _StubCC:
    def __init__(self):
        self.executor = _StubExecutor()


def test_anomaly_journal_is_bounded_and_counts_actions():
    mgr = AnomalyDetectorManager(
        _StubCC(), detectors={},
        notifier=_StubNotifier(AnomalyNotificationResult.IGNORE),
        history_size=5,
    )
    for i in range(20):
        mgr._handle(_StubAnomaly(i), now_ms=i)
    journal = mgr.journal()
    assert len(journal) == 5, "journal must stay bounded"
    assert [e["timeMs"] for e in journal] == [15, 16, 17, 18, 19]
    assert mgr.action_counts()["IGNORE"] == 20  # counters see every event
    assert isinstance(mgr._history, deque) and mgr._history.maxlen == 5


def test_fix_failed_dumps_flight_recorder(tmp_path):
    reg = MetricRegistry()
    reg.gauge("g", lambda: 1.0)
    rec = FlightRecorder(reg, interval_s=1.0, retention=8,
                         dump_dir=str(tmp_path))
    mgr = AnomalyDetectorManager(
        _StubCC(), detectors={},
        notifier=_StubNotifier(AnomalyNotificationResult.FIX),
        fix_cooldown_ms=0, flight_recorder=rec,
    )
    mgr._handle(_StubAnomaly(1, fail=True), now_ms=10)
    assert mgr.action_counts()["FIX_FAILED"] == 1
    dumps = list(tmp_path.glob("flight-recorder-*.json"))
    assert len(dumps) == 1
    art = json.loads(dumps[0].read_text())
    assert art["dumpReason"] == "FIX_FAILED:GOAL_VIOLATION"


# ---- GET /diagnostics + /metrics server contract --------------------------------
@pytest.fixture
def diag_server():
    # a PRIVATE registry: this fixture's tests assert exact counter
    # values, and the process-wide default registry accumulates state
    # from every other test in the run (the long-documented ordering
    # flake was exactly that cross-test leakage)
    cc, backend, _ = full_stack(registry=MetricRegistry())
    mgr = AnomalyDetectorManager(
        cc, detectors={},
        notifier=_StubNotifier(AnomalyNotificationResult.IGNORE),
        history_size=16,
    )
    mgr._handle(_StubAnomaly(1), now_ms=1000)
    device_stats.install_gauges(cc.registry)
    rec = FlightRecorder(cc.registry, interval_s=60.0, retention=32,
                         journal_source=mgr.journal,
                         device_stats_source=device_stats.MONITOR.summary)
    rec.sample_once()
    srv = CruiseControlHttpServer(cc, port=0, flight_recorder=rec)
    srv.start()
    yield srv
    srv.stop()
    rec.stop()


def _get(srv, path):
    with urllib.request.urlopen(f"{srv.url}/{path}") as r:
        return r.read().decode(), r.status


def test_diagnostics_serves_flight_recorder_artifact(diag_server):
    body, status = _get(diag_server, "diagnostics")
    assert status == 200
    art = json.loads(body)
    assert art["schema"] == "cc-tpu-flight-recorder/1"
    assert len(art["series"]) >= 2, sorted(art["series"])
    for series in art["series"].values():
        assert series["points"], "every retained series carries points"
    assert [e["timeMs"] for e in art["events"]] == [1000]
    assert "functions" in art["deviceStats"]


def test_metrics_exposes_compile_and_anomaly_action_families(diag_server):
    body, status = _get(diag_server, "metrics")
    assert status == 200
    assert 'cc_jit_compile_seconds_total{fn="all"}' in body
    assert 'cc_jit_retraces_total{fn="all"}' in body
    # EXACT count: the fixture's registry (and detector manager) are
    # private to this test, so the one _handle() in the fixture is the
    # only possible IGNORE — the old leak-tolerant >=1.0 assert papered
    # over cross-test registry leakage the isolated registry removes
    assert 'cc_anomaly_actions_total{action="IGNORE"} 1.0' in body
    assert "cc_jax_live_buffers" in body
    # request timers emit buckets (the migrated HTTP timer family).  The
    # endpoint timer is updated in the handler's `finally` AFTER the
    # response bytes are flushed, so an immediate re-GET can render the
    # exposition before the first request's update lands on a busy box —
    # poll briefly instead of racing it.
    import time as time_mod

    deadline = time_mod.time() + 5.0
    body2 = ""
    while time_mod.time() < deadline:
        body2, _ = _get(diag_server, "metrics")
        if "cc_http_GET_metrics_seconds_bucket" in body2:
            break
        time_mod.sleep(0.05)
    assert "cc_http_GET_metrics_seconds_bucket" in body2


def test_diagnostics_without_recorder_is_503():
    cc, _, _ = full_stack()
    srv = CruiseControlHttpServer(cc, port=0)
    srv.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{srv.url}/diagnostics")
        assert err.value.code == 503
    finally:
        srv.stop()


def test_state_still_200_with_raising_gauge():
    cc, _, _ = full_stack()
    cc.registry.gauge("boom.gauge", lambda: 1 / 0)
    srv = CruiseControlHttpServer(cc, port=0)
    srv.start()
    try:
        body, status = _get(srv, "state")
        assert status == 200
        st = json.loads(body)
        assert str(st["Metrics"]["gauges"]["boom.gauge"]).startswith("error:")
    finally:
        srv.stop()
