"""Multi-host validation (round-2 VERDICT missing #3): two real OS
processes under jax.distributed (CPU, 4 virtual devices each) run the
resident sharded search over the global 2×4 mesh and must produce the
identical plan to the single-process 8-device run.

Runs in subprocesses, so the suite's in-process jax state is untouched.
"""

import os
import sys

import jax
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmarks"))


@pytest.mark.skipif(
    jax.default_backend() == "cpu",
    reason='jax.distributed over two OS processes needs a real multi-device '
           'platform: XLA fails with "Multiprocess computations aren\'t '
           'implemented on the CPU backend" (the subprocesses inherit this '
           'host\'s platform, so the parent backend is the precise guard)',
)
def test_two_process_search_matches_single_process():
    # smoke scale keeps the suite fast; the parity-gate-scale (200b/5k)
    # run is exercised by __graft_entry__.dryrun_multihost and recorded
    # in the committed MULTIHOST_r04.json artifact
    from multihost_dryrun import DEVICES_PER_PROC, run_parent

    summary = run_parent(num_processes=2, scale="smoke")
    assert summary["num_processes"] == 2
    assert summary["devices_per_process"] == DEVICES_PER_PROC
    assert summary["actions"] > 0
