"""MetricFetcherManager / partition assignor / Prometheus sampler tests
(upstream MetricFetcherManagerTest tier; SURVEY.md §2.3)."""

import sys

from harness import full_stack, skewed_workload, WINDOW

from cruise_control_tpu.monitor.fetcher import (
    MetricFetcherManager,
    MetricSamplerPartitionAssignor,
)
from cruise_control_tpu.monitor.prometheus import (
    PrometheusMetricSampler,
    parse_exposition,
)
from cruise_control_tpu.monitor.sampling import (
    MetricsReporterSampler,
    RawMetricType,
)


def test_assignor_round_robin_deterministic():
    a = MetricSamplerPartitionAssignor()
    got = a.assign([5, 1, 3, 2, 4, 0], 3)
    assert got == [{0, 3}, {1, 4}, {2, 5}]
    assert a.assign([1, 2], 5)[:2] == [{1}, {2}]


def test_fetcher_manager_covers_universe_without_double_count():
    cc, backend, reporter = full_stack(num_partitions=12, num_brokers=4)
    monitor = cc.load_monitor
    topic = monitor.sampler.topic
    mgr = MetricFetcherManager(
        monitor,
        sampler_factory=lambda: MetricsReporterSampler(topic),
        num_fetchers=3,
        sampling_interval_ms=WINDOW,
    )
    reporter.report(time_ms=WINDOW * 10 + 1)
    n = mgr.fetch_once(now_ms=WINDOW * 10 + 2)
    # every partition sampled exactly once + broker samples once
    assert n == 12 + 4
    # a second interval with no new reports adds nothing
    assert mgr.fetch_once(now_ms=WINDOW * 11) == 0


def test_fetcher_manager_threaded_start_stop():
    cc, backend, reporter = full_stack(num_partitions=6, num_brokers=3)
    mgr = MetricFetcherManager(cc.load_monitor)
    mgr.start(tick_s=0.01)
    import time as _t

    deadline = _t.time() + 2.0
    while mgr.fetch_count == 0 and _t.time() < deadline:
        _t.sleep(0.01)
    mgr.stop()
    assert mgr.fetch_count > 0


EXPO = """\
# HELP kafka_server_broker_cpu_util cpu
kafka_server_broker_cpu_util{broker="0"} 42.5
kafka_server_brokertopicmetrics_bytesin_total{broker="0"} 900.0
kafka_server_brokertopicmetrics_bytesout_total{broker="0"} 300.0
kafka_partition_bytesin_rate{broker="0",partition="7"} 600.0
kafka_partition_bytesin_rate{broker="0",partition="8"} 300.0
kafka_partition_bytesout_rate{broker="0",partition="7"} 300.0
kafka_log_log_size{broker="0",partition="7"} 123.0
not_a_mapped_metric{broker="0"} 1.0
malformed line without value
"""


def test_parse_exposition():
    rows = parse_exposition(EXPO)
    names = [r[0] for r in rows]
    assert "kafka_server_broker_cpu_util" in names
    assert all("malformed" not in n for n in names)
    cpu = next(r for r in rows if r[0] == "kafka_server_broker_cpu_util")
    assert cpu[1] == {"broker": "0"} and cpu[2] == 42.5


def test_prometheus_sampler_end_to_end():
    urls = []

    def fake_get(url):
        urls.append(url)
        return EXPO

    sampler = PrometheusMetricSampler(fake_get, endpoint="http://x/metrics")
    psamples, bsamples = sampler.get_samples(0, 10_000)
    assert urls == ["http://x/metrics"]
    assert {s.partition for s in psamples} == {7, 8}
    assert len(bsamples) == 1 and bsamples[0].broker_id == 0
    # CPU attribution ran through the shared MetricsProcessor: partition 7
    # has 2/3 of bytes-in and all bytes-out -> the larger share
    p7 = next(s for s in psamples if s.partition == 7)
    p8 = next(s for s in psamples if s.partition == 8)
    from cruise_control_tpu.monitor.sampling import P_CPU

    assert p7.values[P_CPU] > p8.values[P_CPU] > 0
