"""Mesh observatory (ISSUE 17): collective & transfer accounting,
dispatch-gap attribution, the replication audit, and the end-to-end
arm → scan → poll loop on ``GET /profile/mesh``.

The parser tests run on SYNTHETIC traces of BOTH profiler dialects so
the priority-sweep partition (collective > transfer > busy; uncovered =
host gap) and the exact ``busy + collective + transfer + host_gap ==
wall`` reconciliation are pinned independently of this box's profiler.
The live tests ride the SAME session capture as the kernel suite
(``test_kernel_budget._live_capture`` — the mesh observatory is attached
at import time, before any test triggers it), and the committed
``MESH_BUDGET_r17.json`` gate pins the 8-device sharding-loss
decomposition the artifact was built to explain.
"""

import gzip
import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cruise_control_tpu.telemetry import events
from cruise_control_tpu.telemetry import kernel_budget as kb
from cruise_control_tpu.telemetry import mesh_budget as mb
from harness import full_stack
from test_artifact_schemas import SCHEMAS, validate

#: attach BEFORE any test runs: pytest imports every collected module
#: first, so whichever suite triggers the session's one live capture,
#: the mesh observatory rides it (one capture, two artifacts)
mb.MESH.attach(kb.CAPTURE)

BUDGET_PATH = os.path.join(
    os.path.dirname(__file__), "budgets", "mesh_budget.json"
)
R17_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "benchmarks", "MESH_BUDGET_r17.json",
)


# ---- synthetic traces ------------------------------------------------------------
def _write_trace(tmp_path, events_list):
    d = tmp_path / "plugins" / "profile" / "run1"
    d.mkdir(parents=True)
    path = d / "host.trace.json.gz"
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": events_list}, f)
    return str(tmp_path)


def _device_meta(pid, name):
    return {"ph": "M", "pid": pid, "name": "process_name",
            "args": {"name": name}}


def test_device_dialect_collectives_transfers_and_exact_reconciliation(
        tmp_path):
    """TPU-dialect semantics, pinned: collective HLOs classify under the
    closed op vocabulary with per-device time+bytes, host-copy events on
    a device pid charge that device as transfer, an async kernel
    OVERLAPPING a collective is counted once (collective wins), and the
    four terms partition each device's window exactly."""
    def dev(pid, name, cat, ts, dur, byts=0):
        return {"ph": "X", "pid": pid, "tid": 1, "name": name,
                "ts": ts, "dur": dur,
                "args": {"hlo_category": cat,
                         "device_duration_ps": dur * 1e6,
                         "bytes_accessed": byts}}

    trace_dir = _write_trace(tmp_path, [
        _device_meta(7, "/device:TPU:0"),
        _device_meta(8, "/device:TPU:1"),
        # device 0: 40us busy kernel, then a 30us all-reduce with an
        # overlapping async 20us fusion INSIDE it (double-count bait)
        dev(7, "fusion.1", "fusion", 0, 40, 128),
        dev(7, "all-reduce.2", "all-reduce", 50, 30, 512),
        dev(7, "fusion.3", "fusion", 60, 20, 64),
        # a memcpy stream event on the device pid (no hlo_category):
        # charges device 0 as transfer AND tallies the d2h ledger
        {"ph": "X", "pid": 7, "tid": 9, "name": "MemcpyD2H (dyn)",
         "ts": 90, "dur": 10, "args": {"bytes_transferred": 256}},
        # device 1: one flat 100us kernel — fully busy
        dev(8, "fusion.4", "fusion", 0, 100, 320),
    ])
    parsed = mb.parse_mesh_trace(kb.newest_trace(trace_dir))
    assert parsed.dialect == "device"
    assert parsed.skew_source == "busy"
    assert parsed.window_us == pytest.approx(100.0)
    # collective accounting: op, count, time, bytes
    assert set(parsed.collectives) == {"all-reduce"}
    col = parsed.collectives["all-reduce"]
    assert col["count"] == 1
    assert col["time_us"] == pytest.approx(30.0)
    assert col["bytes"] == 512
    # transfer accounting from the trace
    assert set(parsed.transfers) == {"d2h"}
    assert parsed.transfers["d2h"] == {
        "count": 1, "time_us": pytest.approx(10.0), "bytes": 256}
    d0 = parsed.devices["/device:TPU:0"]
    # busy [0,40); collective [50,80) — the overlapped fusion.3 slice is
    # charged ONCE, to the collective; transfer [90,100); gap = the rest
    assert d0.busy_us == pytest.approx(40.0)
    assert d0.collective_us == pytest.approx(30.0)
    assert d0.transfer_us == pytest.approx(10.0)
    assert d0.gap_us == pytest.approx(20.0)
    d1 = parsed.devices["/device:TPU:1"]
    assert d1.busy_us == pytest.approx(100.0)
    assert d1.gap_us == pytest.approx(0.0)
    # THE invariant: the terms partition each device's wall EXACTLY
    for d in parsed.devices.values():
        assert d.busy_us + d.collective_us + d.transfer_us + d.gap_us \
            == pytest.approx(d.wall_us, abs=1e-9)
    # and the artifact's mean-over-devices wall block reconciles to 100%
    art = mb.build_mesh_artifact(parsed, units=2, backend="tpu",
                                 source="benchmark")
    assert art["wall"]["reconciliation_pct"] == pytest.approx(100.0)
    assert art["collectives"]["by_op"]["all-reduce"]["count_per_unit"] \
        == pytest.approx(0.5)
    validate(json.loads(json.dumps(art)), SCHEMAS["cc-tpu-mesh-budget/1"])


def test_thunk_dialect_lane_clipping_and_out_of_lane_host_gap(tmp_path):
    """XLA:CPU dialect: per-device lanes are the client threads' Execute
    walls; collective/transfer intervals count only where they intersect
    the lane (provably blocked), out-of-lane time is host gap, and async
    ``-start`` halves classify under the base op."""
    def thunk(name, ts, dur, byts=0):
        return {"ph": "X", "pid": 1, "tid": 5, "name": name,
                "ts": ts, "dur": dur,
                "args": {"hlo_module": "jit_run", "hlo_op": name,
                         "bytes_accessed": byts}}

    trace_dir = _write_trace(tmp_path, [
        {"ph": "M", "pid": 1, "tid": 21, "name": "thread_name",
         "args": {"name": "tf_XLATfrtCpuClient/21"}},
        {"ph": "M", "pid": 1, "tid": 22, "name": "thread_name",
         "args": {"name": "tf_XLATfrtCpuClient/22"}},
        thunk("while.1", 0, 400),
        thunk("all-reduce.5", 100, 60, 2048),
        thunk("all-gather-start.6", 200, 40, 1024),
        # an H2D copy landing partly OUTSIDE both lanes
        {"ph": "X", "pid": 1, "tid": 30, "name": "TransferToDevice",
         "ts": 380, "dur": 40, "args": {"bytes": 4096}},
        {"ph": "X", "pid": 1, "tid": 21, "ts": 0, "dur": 300,
         "name": "ThunkExecutor::Execute (wait for completion)"},
        {"ph": "X", "pid": 1, "tid": 22, "ts": 0, "dur": 100,
         "name": "ThunkExecutor::Execute (wait for completion)"},
    ])
    parsed = mb.parse_mesh_trace(kb.newest_trace(trace_dir))
    assert parsed.dialect == "host-thunk"
    assert parsed.skew_source == "busy_minus_collectives"
    # async -start half classifies under the base op
    assert set(parsed.collectives) == {"all-reduce", "all-gather"}
    assert parsed.collectives["all-gather"]["time_us"] == pytest.approx(40)
    assert parsed.transfers["h2d"]["bytes"] == 4096
    # window spans thunks + transfers + lanes: [0, 420)
    assert parsed.window_us == pytest.approx(420.0)
    lane0 = parsed.devices["cpu-lane-0"]
    # lane [0,300): both collectives intersect → 100us collective-wait,
    # busy 200; the transfer [380,420) is OUT of lane → host gap
    assert lane0.collective_us == pytest.approx(100.0)
    assert lane0.busy_us == pytest.approx(200.0)
    assert lane0.transfer_us == pytest.approx(0.0)
    assert lane0.gap_us == pytest.approx(120.0)
    lane1 = parsed.devices["cpu-lane-1"]
    # lane [0,100): collectives start AT 100 — zero overlap, all busy
    assert lane1.collective_us == pytest.approx(0.0)
    assert lane1.busy_us == pytest.approx(100.0)
    assert lane1.gap_us == pytest.approx(320.0)
    for d in parsed.devices.values():
        assert d.busy_us + d.collective_us + d.transfer_us + d.gap_us \
            == pytest.approx(d.wall_us, abs=1e-9)
    # skew over collective-corrected busy: 200 / mean(200, 100)
    assert parsed.skew() == pytest.approx(200.0 / 150.0)


def test_kernel_parser_thunk_skew_subtracts_collective_wait(tmp_path):
    """Satellite (host-thunk skew fix): the KERNEL parser's per-lane
    busy now subtracts collective-wait overlap — a lane blocked in an
    all-reduce is waiting, not working — and records ``skew_source`` so
    r14-era artifacts (pure Execute walls) stay honestly labeled."""
    def thunk(name, ts, dur):
        return {"ph": "X", "pid": 1, "tid": 5, "name": name,
                "ts": ts, "dur": dur,
                "args": {"hlo_module": "jit_run", "hlo_op": name}}

    events_list = [
        {"ph": "M", "pid": 1, "tid": 21, "name": "thread_name",
         "args": {"name": "tf_XLATfrtCpuClient/21"}},
        {"ph": "M", "pid": 1, "tid": 22, "name": "thread_name",
         "args": {"name": "tf_XLATfrtCpuClient/22"}},
        thunk("while.1", 0, 400),
        thunk("all-reduce.5", 100, 60),
        {"ph": "X", "pid": 1, "tid": 21, "ts": 0, "dur": 300,
         "name": "ThunkExecutor::Execute (wait for completion)"},
        {"ph": "X", "pid": 1, "tid": 22, "ts": 0, "dur": 100,
         "name": "ThunkExecutor::Execute (wait for completion)"},
    ]
    parsed = kb.parse_trace(kb.newest_trace(
        _write_trace(tmp_path, events_list)))
    assert parsed.skew_source == "busy_minus_collectives"
    # lane 0's 60us all-reduce overlap is subtracted: 300-60; lane 1
    # never overlaps it
    assert parsed.device_busy_us == pytest.approx(
        {"cpu-lane-0": 240.0, "cpu-lane-1": 100.0})
    assert parsed.device_collective_us == pytest.approx(
        {"cpu-lane-0": 60.0, "cpu-lane-1": 0.0})
    assert parsed.skew() == pytest.approx(240.0 / 170.0)
    art = kb.build_artifact(parsed, units=1, backend="cpu")
    assert art["devices"]["skew_source"] == "busy_minus_collectives"
    validate(json.loads(json.dumps(art)), SCHEMAS["cc-tpu-kernel-budget/2"])
    # without collectives the source stays "busy" (r14 semantics)
    parsed2 = kb.parse_trace(kb.newest_trace(_write_trace(
        tmp_path / "b",
        [e for e in events_list if "all-reduce" not in e.get("name", "")])))
    assert parsed2.skew_source == "busy"
    assert parsed2.device_busy_us["cpu-lane-0"] == pytest.approx(300.0)


def test_collective_and_transfer_vocabularies():
    assert kb.classify_collective("all-reduce.1") == "all-reduce"
    assert kb.classify_collective("all-gather-start.2") == "all-gather"
    assert kb.classify_collective("reduce-scatter-done.3") \
        == "reduce-scatter"
    assert kb.classify_collective("collective-permute.4") \
        == "collective-permute"
    assert kb.classify_collective("all-to-all.9") == "all-to-all"
    assert kb.classify_collective("fusion.1") is None
    assert kb.classify_collective("reduce.7") is None  # not a collective
    assert mb.classify_transfer("MemcpyH2D (stream)") == "h2d"
    assert mb.classify_transfer("TransferToDevice") == "h2d"
    assert mb.classify_transfer("BufferFromHostBuffer") == "h2d"
    assert mb.classify_transfer("MemcpyD2H") == "d2h"
    assert mb.classify_transfer("TransferFromDevice") == "d2h"
    assert mb.classify_transfer("ToLiteral") == "d2h"
    assert mb.classify_transfer("fusion.1") is None
    assert mb.classify_transfer("copy.3") is None  # intra-device copy


def test_sweep_priority_and_interval_helpers():
    """The sweep's priority order and exactness on pathological overlap:
    nested, staggered, and duplicated intervals still partition."""
    split = mb._sweep((0.0, 100.0), [
        (0, 60, "busy"), (20, 40, "collective"), (30, 50, "transfer"),
        (0, 60, "busy"),          # duplicate busy must not double-count
    ])
    # collective [20,40); transfer [40,50) (clipped by priority);
    # busy [0,20)+[50,60); gap [60,100)
    assert split.collective_us == pytest.approx(20.0)
    assert split.transfer_us == pytest.approx(10.0)
    assert split.busy_us == pytest.approx(30.0)
    assert split.gap_us == pytest.approx(40.0)
    assert split.busy_us + split.collective_us + split.transfer_us \
        + split.gap_us == pytest.approx(split.wall_us, abs=1e-12)
    assert kb.merge_intervals([(5, 7), (0, 2), (1, 3)]) == [(0, 3), (5, 7)]
    assert mb._intersect([(0, 10), (20, 30)], [(5, 25)]) \
        == [(5, 10), (20, 25)]
    assert kb.overlap_us([(0, 10)], [(5, 25)]) == pytest.approx(5.0)


# ---- the transfer ledger ---------------------------------------------------------
def test_transfer_ledger_windows_and_instrumented_entry_points():
    led = mb.TransferLedger()
    led.note("h2d", "upload", 1000, 0.001)
    baseline = led.snapshot()
    out = led.device_put(np.ones(8, np.float32), fn="upload")
    assert int(jnp.sum(out)) == 8  # it really went through jax
    fetched = led.fetch(jnp.arange(4), fn="drain")
    assert isinstance(fetched, np.ndarray)
    delta = mb.TransferLedger.delta(led.snapshot(), baseline)
    # the pre-baseline note is windowed OUT; both entry points are in
    assert delta["upload"]["h2d_count"] == 1
    assert delta["upload"]["h2d_bytes"] == 32
    assert delta["drain"]["d2h_count"] == 1
    assert delta["drain"]["d2h_bytes"] == fetched.nbytes
    assert "h2d" not in {
        k for k, v in delta.get("upload", {}).items()
        if k == "h2d_bytes" and v == 1000
    }
    # disabled: zero accounting, the copy itself still happens
    led2 = mb.TransferLedger(enabled=False)
    led2.fetch(jnp.arange(4), fn="x")
    led2.note("h2d", "x", 1, 0.0)
    assert led2.snapshot() == {}
    led.reset()
    assert led.snapshot() == {}


def test_replication_audit_counts_replicated_vs_sharded_bytes():
    keep = jnp.arange(64, dtype=jnp.float32)  # 256 bytes, single device
    dead = jnp.ones(16, jnp.float32)
    jax.block_until_ready((keep, dead))
    dead.delete()
    audit = mb.audit_replication(max_arrays=100_000)
    assert audit["devices"] >= 1
    assert audit["arrays"] >= 1
    assert audit["logical_bytes"] >= keep.nbytes
    assert audit["stored_bytes"] >= keep.nbytes
    # CPU single-device arrays never store extra copies
    assert audit["replicated_bytes"] >= 0
    assert audit["single_device_bytes"] >= keep.nbytes
    assert audit["stored_bytes"] == (
        audit["replicated_bytes"] + audit["sharded_bytes"]
        + audit["single_device_bytes"])
    # deleted arrays are skipped, not fatal (the audit runs mid-flight)
    assert audit["skipped"] >= 0
    # truncation bound honors max_arrays
    tiny = mb.audit_replication(max_arrays=1)
    assert tiny["arrays"] <= 1


# ---- observatory plumbing --------------------------------------------------------
def test_observer_registration_survives_capture_reset():
    cap = kb.CaptureManager()
    obs = mb.MeshObservatory()
    obs.attach(cap)
    obs.attach(cap)  # idempotent
    assert cap._observers.count(obs) == 1
    cap.reset()
    assert obs in cap._observers
    obs.reset()
    assert obs in cap._observers  # mesh reset drops state, not wiring


def test_mesh_budget_gate_semantics():
    art = mb.build_mesh_artifact(
        mb.MeshParse(dialect="host-thunk"), units=2, backend="cpu",
        ledger={"analyzer.scan_fetch": {
            "h2d_count": 0, "h2d_bytes": 0, "h2d_us": 0.0,
            "d2h_count": 8, "d2h_bytes": 1024, "d2h_us": 5.0}},
    )
    art["collectives"]["by_op"]["all-reduce"] = {
        "count": 8, "count_per_unit": 4.0, "time_ms": 1.0, "bytes": 0}
    art["transfers"]["trace"]["h2d"] = {
        "count": 8, "count_per_unit": 4.0, "time_ms": 0.1, "bytes": 0}
    budget = {
        "tolerance_pct": 25,
        "collective_ops": {"all-reduce": 4.0},
        "transfer_trace": {"h2d": 4.0},
        "ledger_fns": {"analyzer.scan_fetch": {
            "h2d_count_per_unit": 0.0, "d2h_count_per_unit": 4.0}},
    }
    assert mb.compare_mesh_budget(art, budget) == []
    # growth past the ceiling, a novel op, and a novel fn all violate
    art["collectives"]["by_op"]["all-reduce"]["count_per_unit"] = 5.1
    art["collectives"]["by_op"]["all-to-all"] = {
        "count": 1, "count_per_unit": 0.5, "time_ms": 0.1, "bytes": 0}
    art["transfers"]["ledger"]["by_fn"]["rogue.fetch"] = {
        "h2d_count": 0, "h2d_bytes": 0, "h2d_ms": 0.0,
        "d2h_count": 2, "d2h_bytes": 64, "d2h_ms": 0.1}
    violations = mb.compare_mesh_budget(art, budget)
    assert len(violations) == 3
    assert any("all-reduce" in v for v in violations)
    assert any("all-to-all" in v for v in violations)
    assert any("rogue.fetch" in v for v in violations)
    # fixture drift fails loudly
    bad = dict(budget, fixture={"seed": 999})
    assert any("fixture" in v
               for v in mb.compare_mesh_budget(
                   dict(art, fixture={"seed": 7}), bad))


# ---- live capture (shared with the kernel suite) ---------------------------------
_MESH_LIVE = {}


def _live_mesh():
    """Snapshot the mesh side of the session's ONE live capture the
    first time any test asks (tkb._live_capture drives it)."""
    if _MESH_LIVE:
        return _MESH_LIVE
    import test_kernel_budget as tkb

    live = tkb._live_capture()
    _MESH_LIVE.update(
        artifact=mb.MESH.latest(), kernel=live["artifact"],
        journal=live["journal"], state=mb.MESH.state(),
        audit=mb.MESH.summary()["lastAudit"],
    )
    return _MESH_LIVE


def test_live_capture_produces_schema_valid_mesh_artifact():
    live = _live_mesh()
    art = live["artifact"]
    assert art is not None, "mesh observer missed the session capture"
    validate(json.loads(json.dumps(art)), SCHEMAS["cc-tpu-mesh-budget/1"])
    assert art["source"] == "live-capture"
    assert art["unit"] == "scan-call"
    assert art["units"] == live["kernel"]["units"]
    assert art["capture"]["id"] == live["kernel"]["capture"]["id"]
    # the decomposition reconciles EXACTLY (well inside the 5% gate)
    assert art["wall"]["reconciliation_pct"] == pytest.approx(
        100.0, abs=0.5)
    assert art["wall"]["window_ms"] > 0
    assert art["wall"]["busy_ms"] > 0
    for label, d in art["devices"]["per_device"].items():
        assert d["busy_ms"] + d["collective_ms"] + d["transfer_ms"] \
            + d["gap_ms"] == pytest.approx(d["wall_ms"], abs=0.01)
    # the drive loop's instrumented fetches landed in the window
    by_fn = art["transfers"]["ledger"]["by_fn"]
    assert "analyzer.scan_fetch" in by_fn
    assert by_fn["analyzer.scan_fetch"]["d2h_count"] > 0
    assert by_fn["analyzer.scan_fetch"]["d2h_bytes"] > 0
    # the capture-finish replication audit ran on live device state
    assert art["replication"]["arrays"] > 0
    assert art["replication"]["stored_bytes"] > 0


def test_live_capture_journals_mesh_parse_deterministically():
    live = _live_mesh()
    parsed_events = [e for e in live["journal"]
                     if e["kind"] == "profiler.mesh.parsed"]
    assert parsed_events, "mesh parse was not journaled"
    payload = parsed_events[0]["payload"]
    assert payload["captureId"] == live["kernel"]["capture"]["id"]
    assert payload["dialect"] == live["artifact"]["dialect"]
    assert payload["units"] == live["artifact"]["units"]
    assert payload["collectiveOps"] == sorted(
        live["artifact"]["collectives"]["by_op"])
    # the audit kind is NOT emitted by the capture hook (fingerprints)
    assert not any(e["kind"] == "profiler.mesh.audit"
                   for e in live["journal"])


def test_mesh_families_render_in_prometheus_exposition():
    _live_mesh()
    fams = {f[0] for f in mb.MESH.families()}
    assert "cc_transfer_bytes" in fams
    assert "cc_transfer_ms" in fams
    assert "cc_mesh_host_gap_ms" in fams
    assert "cc_mesh_replicated_bytes" in fams
    from cruise_control_tpu.telemetry.exposition import render_prometheus
    from cruise_control_tpu.telemetry.tracing import Telemetry
    from cruise_control_tpu.utils.metrics import MetricRegistry

    body = render_prometheus(MetricRegistry(), Telemetry(enabled=True))
    assert 'cc_transfer_bytes{direction="' in body
    assert 'fn="analyzer.scan_fetch"' in body
    assert "cc_mesh_host_gap_ms" in body
    assert "cc_mesh_replicated_bytes" in body


def test_mesh_summary_merges_into_flight_recorder_artifact():
    _live_mesh()
    from cruise_control_tpu.telemetry.recorder import FlightRecorder
    from cruise_control_tpu.utils.metrics import MetricRegistry

    rec = FlightRecorder(MetricRegistry(), interval_s=60.0, retention=8,
                         mesh_budget_source=mb.MESH.summary)
    art = rec.artifact()
    assert "meshBudget" in art
    assert art["meshBudget"]["enabled"] is True
    latest = art["meshBudget"]["latest"]
    if latest is not None:  # a later test may have reset the singleton
        assert latest["schema"] == mb.SCHEMA
    validate(json.loads(json.dumps(art)),
             SCHEMAS["cc-tpu-flight-recorder/1"])


# ---- the budget regression gate --------------------------------------------------
def write_budget() -> None:
    """Regenerate the checked-in mesh-budget count gate (run on an
    INTENDED transfer/collective-profile change): ``JAX_PLATFORMS=cpu
    python -c "import tests.test_mesh_budget as t; t.write_budget()"``
    from the repo root."""
    import test_kernel_budget as tkb

    art = _live_mesh()["artifact"]
    budget = {
        "unit": art["unit"],
        "fixture": dict(tkb._FIXTURE, scans=tkb._CAPTURE_SCANS,
                        **tkb._CAPTURE_CFG),
        "backend": art["backend"],
        "tolerance_pct": 25,
        "collective_ops": {
            op: v["count_per_unit"]
            for op, v in sorted(art["collectives"]["by_op"].items())
        },
        "transfer_trace": {
            d: v["count_per_unit"]
            for d, v in sorted(art["transfers"]["trace"].items())
        },
        "ledger_fns": {
            fn: {
                "h2d_count_per_unit": round(
                    row["h2d_count"] / art["units"], 2),
                "d2h_count_per_unit": round(
                    row["d2h_count"] / art["units"], 2),
            }
            for fn, row in sorted(
                art["transfers"]["ledger"]["by_fn"].items())
        },
    }
    os.makedirs(os.path.dirname(BUDGET_PATH), exist_ok=True)
    with open(BUDGET_PATH, "w") as f:
        json.dump(budget, f, indent=1, sort_keys=True)
        f.write("\n")


def test_mesh_budget_gate():
    """Per-term counts of the live capture may not grow more than 25%
    over the pinned budget, and the collective-op / ledger-fn
    vocabularies are CLOSED — a new collective in the scan program or a
    new un-budgeted transfer site fails until deliberately regenerated
    (:func:`write_budget`)."""
    assert os.path.exists(BUDGET_PATH), (
        f"missing {BUDGET_PATH} — generate it with the command in "
        "write_budget's docstring"
    )
    with open(BUDGET_PATH) as f:
        budget = json.load(f)
    art = _live_mesh()["artifact"]
    violations = mb.compare_mesh_budget(art, budget)
    assert not violations, (
        "mesh budget regressed (regenerate via write_budget() ONLY for "
        "an intended change):\n" + "\n".join(violations)
    )


# ---- committed sharded artifact --------------------------------------------------
def test_committed_r17_artifact_decomposes_the_sharding_loss():
    """The committed MESH_BUDGET_r17 (``benchmarks/sharded_large_dryrun
    .py --mesh-out``, 8-device CPU mesh) is schema-valid, reconciles to
    the measured wall within the 5% acceptance bound, and charges the
    single→sharded slowdown to NAMED terms that sum to the loss."""
    with open(R17_PATH) as f:
        art = json.load(f)
    validate(art, SCHEMAS["cc-tpu-mesh-budget/1"])
    assert art["source"] == "benchmark"
    assert art["backend"] == "cpu"           # NOT comparable to a TPU run
    assert art["devices"]["count"] == 8
    assert abs(art["wall"]["reconciliation_pct"] - 100.0) <= 5.0
    for d in art["devices"]["per_device"].values():
        assert d["busy_ms"] + d["collective_ms"] + d["transfer_ms"] \
            + d["gap_ms"] == pytest.approx(d["wall_ms"], abs=0.05)
    loss = art["sharding_loss"]
    assert loss["wall_sharded_s"] > loss["wall_single_s"] > 0
    assert loss["loss_s"] == pytest.approx(
        loss["wall_sharded_s"] - loss["wall_single_s"], abs=0.01)
    # the by-term charge covers the loss (within the same 5% bound)
    assert set(loss["by_term_s"]) <= {"busy_scaling", "collective",
                                      "transfer", "host_gap"}
    assert sum(loss["by_term_s"].values()) == pytest.approx(
        loss["loss_s"], rel=0.05)
    # shares are the per-term fraction of the loss
    assert sum(loss["attributed_share"].values()) == pytest.approx(
        1.0, abs=0.01)
    # the replication audit rode the same run
    assert art["replication"]["devices"] == 8


def test_committed_r20_artifact_rides_the_sharded_path():
    """The round-20 recapture (same fixture, same protocol, AFTER the
    pool tables + candidate population shard): schema-valid, with the
    busy_scaling share strictly below r17's replicated-spec share.  The absolute term stays large on this host
    ON PURPOSE — host-thunk lane busy is executor thread wall on a
    timeshared core — and the artifact says so in its
    ``busy_term_caveat``; the clean per-device work measurement lives
    in SHARDED_SCALING_r20.json."""
    r20 = os.path.join(os.path.dirname(R17_PATH), "MESH_BUDGET_r20.json")
    with open(r20) as f:
        art = json.load(f)
    with open(R17_PATH) as f:
        r17 = json.load(f)
    validate(art, SCHEMAS["cc-tpu-mesh-budget/1"])
    assert art["source"] == "benchmark"
    assert art["devices"]["count"] == 8
    assert art["fixture"]["brokers"] == r17["fixture"]["brokers"]
    assert art["fixture"]["partitions"] == r17["fixture"]["partitions"]
    loss, loss17 = art["sharding_loss"], r17["sharding_loss"]
    assert loss["attributed_share"]["busy_scaling"] \
        < loss17["attributed_share"]["busy_scaling"]
    assert "SHARDED_SCALING_r20" in loss["busy_term_caveat"]
    assert sum(loss["by_term_s"].values()) == pytest.approx(
        loss["loss_s"], rel=0.05)


# ---- end-to-end through the real server ------------------------------------------
def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=30) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def test_profile_mesh_arm_poll_audit_ladder_through_http_server():
    """Acceptance (ISSUE 17): GET /profile/mesh?arm=true → 202, a
    rebalance runs the scan under the shared capture, the pumped parse
    yields a schema-valid cc-tpu-mesh-budget/1, and ?audit=true runs
    the replication audit inline."""
    from cruise_control_tpu.server.http_server import (
        CruiseControlHttpServer,
    )
    from cruise_control_tpu.utils.metrics import MetricRegistry

    _live_mesh()  # snapshot the session artifact BEFORE resetting
    kb.CAPTURE.reset()
    mb.MESH.reset()
    cc, backend, reporter = full_stack(engine="tpu",
                                       registry=MetricRegistry())
    server = CruiseControlHttpServer(cc, port=0, access_log=False)
    server.start()
    try:
        status, body = _get(f"{server.url}/profile/mesh")
        assert status == 404  # nothing captured yet
        status, body = _get(f"{server.url}/profile/mesh?arm=true&scans=1")
        assert status == 202
        assert body["mesh"]["capture"]["state"] == "ARMED"
        status, body = _get(f"{server.url}/profile/mesh")
        assert status == 202  # armed, no artifact yet — poll semantics
        req = urllib.request.Request(
            f"{server.url}/rebalance?dryrun=true"
            "&get_response_timeout_s=120",
            method="POST", data=b"",
        )
        with urllib.request.urlopen(req, timeout=150) as resp:
            assert resp.status == 200
        # production pumps this from the SLO tick; tests pump directly
        assert kb.parse_pending(max_parses=4) >= 1
        status, art = _get(f"{server.url}/profile/mesh")
        assert status == 200
        validate(art, SCHEMAS["cc-tpu-mesh-budget/1"])
        assert art["capture"]["reason"] == "http"
        assert art["wall"]["reconciliation_pct"] == pytest.approx(
            100.0, abs=0.5)
        # the explicit audit is served inline; pin one live array so the
        # walk has something to count (the finished rebalance released
        # its device state)
        pin = jnp.arange(8)
        jax.block_until_ready(pin)
        status, audit = _get(f"{server.url}/profile/mesh?audit=true")
        assert status == 200
        assert audit["arrays"] > 0
        del pin
        # disabling either observatory 503s the endpoint
        mb.MESH.configure(enabled=False)
        status, body = _get(f"{server.url}/profile/mesh")
        assert status == 503
        assert "mesh" in body["errorMessage"]
    finally:
        mb.MESH.configure(enabled=True)
        server.stop()
        kb.CAPTURE.reset()
        mb.MESH.reset()
