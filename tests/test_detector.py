"""Detector / self-healing tests (upstream AnomalyDetectorManagerTest /
SelfHealingNotifierTest semantics; SURVEY.md §2.8, §5.3, call stack §3.4)."""

import pytest

from cruise_control_tpu.detector import (
    AnomalyNotificationResult,
    AnomalyType,
    BrokerFailureDetector,
    BrokerFailures,
    GoalViolationDetector,
    MaintenanceEventReader,
    MetricAnomaly,
    PercentileMetricAnomalyFinder,
    SelfHealingNotifier,
    make_detector_manager,
)

from harness import full_stack

MIN = 60_000


def healing_notifier(alert_ms=0, heal_ms=0, **types):
    enabled = {AnomalyType[k.upper()]: v for k, v in types.items()}
    return SelfHealingNotifier(
        enabled=enabled,
        broker_failure_alert_threshold_ms=alert_ms,
        broker_failure_self_healing_threshold_ms=heal_ms,
    )


class TestGoalViolationDetector:
    def test_detects_violations_on_skewed_cluster(self):
        cc, _, _ = full_stack()
        det = GoalViolationDetector(cc)
        anomalies = det.detect(now_ms=0)
        assert len(anomalies) == 1
        assert anomalies[0].violated_goals

    def test_clean_after_rebalance(self):
        cc, _, _ = full_stack()
        cc.rebalance(dryrun=False)
        det = GoalViolationDetector(cc)
        anomalies = det.detect(now_ms=0)
        # leader-bytes-in balance may remain slightly off; hard goals must not
        for a in anomalies:
            for name in a.violated_goals:
                assert "Capacity" not in name and "RackAware" not in name


class TestBrokerFailureDetector:
    def test_first_seen_persisted_across_restart(self, tmp_path):
        cc, backend, _ = full_stack()
        path = str(tmp_path / "failed_brokers.json")
        det = BrokerFailureDetector(cc, path)
        assert det.detect(now_ms=1000) == []
        backend.failed_brokers.add(2)
        (anomaly,) = det.detect(now_ms=2000)
        assert anomaly.failed_brokers == {2: 2000}
        # a new detector instance (post-restart) keeps the first-seen time
        det2 = BrokerFailureDetector(cc, path)
        (anomaly2,) = det2.detect(now_ms=9000)
        assert anomaly2.failed_brokers == {2: 2000}

    def test_recovered_broker_cleared(self, tmp_path):
        cc, backend, _ = full_stack()
        det = BrokerFailureDetector(cc, str(tmp_path / "f.json"))
        backend.failed_brokers.add(2)
        det.detect(now_ms=2000)
        backend.failed_brokers.clear()
        assert det.detect(now_ms=3000) == []


class TestSelfHealingNotifier:
    def test_broker_failure_escalation(self):
        n = healing_notifier(alert_ms=10 * MIN, heal_ms=30 * MIN,
                             broker_failure=True)
        a = BrokerFailures(0, {1: 0})
        assert n.on_anomaly(a, 5 * MIN) == AnomalyNotificationResult.CHECK
        assert not n.alerts
        assert n.on_anomaly(a, 15 * MIN) == AnomalyNotificationResult.CHECK
        assert n.alerts and not n.alerts[-1]["autoFixTriggered"]
        assert n.on_anomaly(a, 31 * MIN) == AnomalyNotificationResult.FIX
        assert n.alerts[-1]["autoFixTriggered"]

    def test_healing_disabled_never_fixes(self):
        n = healing_notifier(alert_ms=0, heal_ms=0, broker_failure=False)
        a = BrokerFailures(0, {1: 0})
        assert n.on_anomaly(a, 10 * MIN) == AnomalyNotificationResult.IGNORE

    def test_unfixable_anomaly_alerts_only(self):
        n = healing_notifier(metric_anomaly=True)
        a = MetricAnomaly(0, broker_id=1, metric="CPU", current=9.0,
                          threshold=1.0)
        assert n.on_anomaly(a, 0) == AnomalyNotificationResult.IGNORE
        assert n.alerts


class TestPercentileFinder:
    def test_flags_spike_against_own_history(self):
        import numpy as np

        finder = PercentileMetricAnomalyFinder(upper_percentile=95, margin=1.5)
        vals = np.ones((2, 6, 1))
        vals[1, -1, 0] = 10.0  # broker 1 spikes in the newest window
        out = finder.find(0, vals, ["CPU"])
        assert [a.broker_id for a in out] == [1]
        assert out[0].metric == "CPU"

    def test_insufficient_history_silent(self):
        import numpy as np

        finder = PercentileMetricAnomalyFinder(min_windows=3)
        assert finder.find(0, np.ones((2, 2, 1)), ["CPU"]) == []


class TestManagerEndToEnd:
    def test_goal_violation_self_heals(self):
        cc, backend, _ = full_stack()
        mgr = make_detector_manager(
            cc, backend=backend,
            notifier=healing_notifier(goal_violation=True),
        )
        assert cc.anomaly_detector is mgr
        handled = mgr.run_detection_cycle(now_ms=0)
        assert any(
            a.anomaly_type == AnomalyType.GOAL_VIOLATION for a in handled
        )
        # the fix actually rebalanced the backend
        leaders = [st.leader for st in backend.partitions.values()]
        assert leaders.count(0) < len(leaders)
        st = mgr.state_summary()
        assert st["metrics"]["FIX"] >= 1
        assert st["recentAnomalies"][-1]["fixStarted"] or any(
            r["fixStarted"] for r in st["recentAnomalies"]
        )

    def test_broker_failure_self_heals_after_threshold(self, tmp_path):
        cc, backend, _ = full_stack(failed_brokers={2})
        mgr = make_detector_manager(
            cc, backend=backend,
            notifier=healing_notifier(alert_ms=MIN, heal_ms=3 * MIN,
                                      broker_failure=True),
            broker_failure_persist_path=str(tmp_path / "f.json"),
            detection_interval_ms=MIN,
        )
        mgr.run_detection_cycle(now_ms=0)       # first seen at 0; CHECK
        assert all(2 in st.replicas or True for st in backend.partitions.values())
        assert any(2 in st.replicas for st in backend.partitions.values())
        mgr.run_detection_cycle(now_ms=4 * MIN)  # past healing threshold: FIX
        assert all(
            2 not in st.replicas for st in backend.partitions.values()
        ), "failed broker not evacuated"

    def test_fix_cooldown_blocks_second_fix(self):
        cc, backend, _ = full_stack()
        mgr = make_detector_manager(
            cc, backend=backend,
            notifier=healing_notifier(goal_violation=True,
                                      maintenance_event=True),
            fix_cooldown_ms=10 * MIN,
            detection_interval_ms=0,
        )
        mgr.run_detection_cycle(now_ms=0)
        reader = mgr.detectors[AnomalyType.MAINTENANCE_EVENT].reader
        reader.submit("REBALANCE")
        mgr.run_detection_cycle(now_ms=MIN)  # within cooldown
        st = mgr.state_summary()
        assert any(
            r["action"] == "FIX_DELAYED_COOLDOWN" for r in st["recentAnomalies"]
        )

    def test_recovered_execution_claims_cooldown(self):
        """note_recovery (ISSUE 7): a resumed checkpoint counts as the
        last fix — the first post-recovery cycle starts the cooldown, so
        self-healing cannot double-fire on top of the recovery."""
        cc, backend, _ = full_stack()
        mgr = make_detector_manager(
            cc, backend=backend,
            notifier=healing_notifier(goal_violation=True),
            fix_cooldown_ms=10 * MIN,
            detection_interval_ms=0,
        )
        mgr.note_recovery()
        mgr.run_detection_cycle(now_ms=MIN)  # claims the cooldown at MIN
        st = mgr.state_summary()
        assert st["lastFixMs"] == MIN
        assert st["metrics"].get("FIX", 0) == 0
        assert any(
            r["action"] == "FIX_DELAYED_COOLDOWN"
            for r in st["recentAnomalies"]
        ), "the violation fix should have been delayed by the recovery"
        # cooldown over: the delayed fix proceeds normally
        mgr.run_detection_cycle(now_ms=12 * MIN)
        assert mgr.state_summary()["metrics"]["FIX"] >= 1

    def test_maintenance_event_remove_broker(self):
        cc, backend, _ = full_stack()
        reader = MaintenanceEventReader()
        mgr = make_detector_manager(
            cc, backend=backend, maintenance_reader=reader,
            notifier=healing_notifier(maintenance_event=True),
        )
        reader.submit("REMOVE_BROKER", brokers=[3])
        mgr.run_detection_cycle(now_ms=0)
        assert all(3 not in st.replicas for st in backend.partitions.values())

    def test_disk_failure_detector_sees_injected_offline_dirs(self):
        cc, backend, _ = full_stack()
        mgr = make_detector_manager(cc, backend=backend)
        backend.offline_dirs = {1: ["/data/d1"]}
        handled = mgr.run_detection_cycle(now_ms=0)
        disk = [a for a in handled
                if a.anomaly_type == AnomalyType.DISK_FAILURE]
        assert len(disk) == 1 and disk[0].failed_disks == {1: ["/data/d1"]}

    def test_disk_failure_self_heal_evacuates_broker_replicas(self):
        cc, backend, _ = full_stack()
        mgr = make_detector_manager(
            cc, backend=backend,
            notifier=healing_notifier(disk_failure=True),
        )
        # broker 1 loses its only dir; every replica there becomes offline
        backend.offline_dirs = {1: ["/data/d1"]}
        assert any(1 in st.replicas for st in backend.partitions.values())
        mgr.run_detection_cycle(now_ms=0)
        assert all(
            1 not in st.replicas for st in backend.partitions.values()
        ), "replicas not moved off the failed disk's broker"

    def test_partial_disk_failure_evacuates_only_mapped_replicas(self):
        cc, backend, _ = full_stack()
        # pin every replica on broker 1 to /d1 except one partition on /d2
        on_b1 = [p for p, st in backend.partitions.items() if 1 in st.replicas]
        keep = on_b1[0]
        for p in on_b1:
            backend.replica_dir[(p, 1)] = "/d2" if p == keep else "/d1"
        backend.offline_dirs = {1: ["/d1"]}
        mgr = make_detector_manager(
            cc, backend=backend,
            notifier=healing_notifier(disk_failure=True),
        )
        mgr.run_detection_cycle(now_ms=0)
        assert 1 in backend.partitions[keep].replicas, "healthy-disk replica moved"
        # nothing is left (or newly placed) on the dead dir; broker 1 may
        # still host replicas — on its healthy /d2
        assert backend.offline_replicas() == {}
        for (p, b), d in backend.replica_dir.items():
            if b == 1 and 1 in backend.partitions[p].replicas:
                assert d == "/d2"

    def test_detector_exception_does_not_kill_cycle(self):
        cc, backend, _ = full_stack()
        mgr = make_detector_manager(
            cc, backend=backend,
            notifier=healing_notifier(goal_violation=True),
        )

        class Broken:
            def detect(self, now_ms):
                raise RuntimeError("metadata unavailable")

        mgr.detectors[AnomalyType.TOPIC_ANOMALY] = Broken()
        handled = mgr.run_detection_cycle(now_ms=0)
        # the goal-violation detector still ran and healed
        assert any(
            a.anomaly_type == AnomalyType.GOAL_VIOLATION for a in handled
        )
        assert any(
            r.get("action") == "DETECT_FAILED"
            for r in mgr.state_summary()["recentAnomalies"]
        )

    def test_delayed_maintenance_event_retried_after_cooldown(self):
        cc, backend, _ = full_stack()
        reader = MaintenanceEventReader()
        mgr = make_detector_manager(
            cc, backend=backend, maintenance_reader=reader,
            notifier=healing_notifier(goal_violation=True,
                                      maintenance_event=True),
            fix_cooldown_ms=5 * MIN,
            detection_interval_ms=0,
        )
        mgr.run_detection_cycle(now_ms=0)  # goal-violation fix starts cooldown
        reader.submit("REMOVE_BROKER", brokers=[3])
        mgr.run_detection_cycle(now_ms=MIN)  # delayed by cooldown
        assert any(3 in st.replicas for st in backend.partitions.values())
        mgr.run_detection_cycle(now_ms=7 * MIN)  # retried from pending queue
        assert all(3 not in st.replicas for st in backend.partitions.values())

    def test_detection_interval_respected(self):
        cc, backend, _ = full_stack()
        mgr = make_detector_manager(
            cc, backend=backend, detection_interval_ms=5 * MIN,
        )
        mgr.run_detection_cycle(now_ms=0)
        n1 = sum(mgr.state_summary()["metrics"].values())
        mgr.run_detection_cycle(now_ms=MIN)  # too soon; nothing runs
        assert sum(mgr.state_summary()["metrics"].values()) == n1


class TestTopicAnomaly:
    def test_rf_fix_raises_replication_factor(self):
        cc, backend, _ = full_stack(rf=1)
        mgr = make_detector_manager(
            cc, backend=backend, target_rf=2,
            notifier=healing_notifier(topic_anomaly=True),
        )
        handled = mgr.run_detection_cycle(now_ms=0)
        assert any(a.anomaly_type == AnomalyType.TOPIC_ANOMALY for a in handled)
        for p, st in backend.partitions.items():
            assert len(set(st.replicas)) >= 2, f"partition {p} still RF<2"

    def test_rf_fix_is_rack_aware_when_possible(self):
        cc, backend, _ = full_stack(rf=1)
        result = cc.fix_topic_replication_factor(2, dryrun=False)
        assert result.execution is not None
        rack = {b: b % 2 for b in range(4)}  # harness broker_rack
        multi_rack = sum(
            1 for st in backend.partitions.values()
            if len({rack[b] for b in st.replicas}) > 1
        )
        assert multi_rack == len(backend.partitions)


class TestStateIntegration:
    def test_facade_state_includes_detector(self):
        cc, backend, _ = full_stack()
        make_detector_manager(cc, backend=backend)
        st = cc.state()
        assert "AnomalyDetectorState" in st
        assert set(st["AnomalyDetectorState"]["selfHealingEnabled"]) == {
            t.value for t in AnomalyType
        }


class TestDetectorTuningKnobs:
    """The anomaly-detector config long tail (VERDICT #8): every knob is
    consumed by the detector it names and reachable from the key surface."""

    def test_goal_violation_threshold_multiplier_widens_tolerance(self):
        cc, _, _ = full_stack()
        strict = GoalViolationDetector(cc)
        loose = GoalViolationDetector(cc, threshold_multiplier=1000.0)
        (strict_anomaly,) = strict.detect(now_ms=0)
        loose_found = loose.detect(now_ms=0)
        loose_goals = (
            set(loose_found[0].violated_goals) if loose_found else set()
        )
        # the multiplier widens only balance gaps: distribution violations
        # the strict detector sees must vanish under a huge multiplier
        assert set(strict_anomaly.violated_goals) - loose_goals
        assert not any("Distribution" in g for g in loose_goals)

    def test_metric_finder_lower_percentile_flags_collapse(self):
        import numpy as np

        vals = np.full((2, 6, 1), 10.0)
        vals[1, -1, 0] = 0.5  # broker 1 goes quiet in the newest window
        upper_only = PercentileMetricAnomalyFinder()
        assert upper_only.find(0, vals, ["NW_IN"]) == []
        both = PercentileMetricAnomalyFinder(lower_percentile=5.0)
        (anomaly,) = both.find(0, vals, ["NW_IN"])
        assert anomaly.broker_id == 1 and anomaly.current == 0.5

    def test_topic_anomaly_min_bad_partitions_tolerance(self):
        from cruise_control_tpu.detector.detectors import (
            TopicReplicationFactorAnomalyFinder,
        )

        cc, _, _ = full_stack(rf=1)
        topo = cc.load_monitor.metadata.refresh()
        bad = len(topo.assignment)  # every partition below RF 2
        tolerant = TopicReplicationFactorAnomalyFinder(
            2, min_bad_partitions=bad + 1
        )
        assert tolerant.find(0, topo) == []
        firing = TopicReplicationFactorAnomalyFinder(
            2, min_bad_partitions=bad
        )
        assert firing.find(0, topo)

    def test_disk_failure_min_offline_dirs_tolerance(self):
        from cruise_control_tpu.detector.detectors import DiskFailureDetector

        cc, backend, _ = full_stack()
        backend.offline_dirs = {1: ["/d1"], 2: ["/d1", "/d2"]}
        tolerant = DiskFailureDetector(cc, backend, min_offline_dirs=2)
        (anomaly,) = tolerant.detect(now_ms=0)
        assert set(anomaly.failed_disks) == {2}
        default = DiskFailureDetector(cc, backend)
        (anomaly,) = default.detect(now_ms=0)
        assert set(anomaly.failed_disks) == {1, 2}

    def test_knobs_wired_from_config(self, tmp_path):
        from cruise_control_tpu.bootstrap import build_app
        from cruise_control_tpu.config.cruise_control_config import (
            CruiseControlConfig,
        )

        cfg = CruiseControlConfig({
            "goal.violation.distribution.threshold.multiplier": 2.5,
            "metric.anomaly.percentile.lower.threshold": 10.0,
            "topic.anomaly.min.bad.partitions": 3,
            "disk.failure.min.offline.dirs": 2,
            "self.healing.target.topic.replication.factor": 2,
            "webserver.http.port": 0,
            "use.tpu.optimizer": False,
            "telemetry.recorder.enabled": False,
        })
        app = build_app(cfg, port=0)
        try:
            dets = app.detector_manager.detectors
            assert dets[AnomalyType.GOAL_VIOLATION].threshold_multiplier \
                == 2.5
            assert dets[AnomalyType.METRIC_ANOMALY].finder.lower_percentile \
                == 10.0
            assert dets[AnomalyType.TOPIC_ANOMALY].finder.min_bad_partitions \
                == 3
            assert dets[AnomalyType.DISK_FAILURE].min_offline_dirs == 2
        finally:
            app.shutdown()
