"""Security providers, /ui dashboard, and standalone bootstrap tests
(upstream servlet/security + KafkaCruiseControlMain tier; SURVEY.md §2.7)."""

import json
import time
import urllib.request

from cruise_control_tpu.server.security import (
    BasicSecurityProvider,
    JwtSecurityProvider,
    SpnegoSecurityProvider,
    TrustedProxySecurityProvider,
)


class Headers(dict):
    def get(self, k, default=None):  # case-exact is fine for tests
        return super().get(k, default)


def test_jwt_provider_roundtrip():
    p = JwtSecurityProvider(b"secret", audience="cc")
    tok = JwtSecurityProvider.issue(
        b"secret", {"sub": "op", "aud": "cc", "exp": time.time() + 60}
    )
    assert p.authenticate_request(
        Headers({"Authorization": f"Bearer {tok}"}), ("127.0.0.1", 1)
    )
    # wrong secret / expired / wrong audience / garbage all fail
    bad = JwtSecurityProvider.issue(b"other", {"aud": "cc"})
    assert not p.authenticate_request(
        Headers({"Authorization": f"Bearer {bad}"}), None
    )
    expired = JwtSecurityProvider.issue(
        b"secret", {"aud": "cc", "exp": time.time() - 1}
    )
    assert not p.authenticate_request(
        Headers({"Authorization": f"Bearer {expired}"}), None
    )
    wrong_aud = JwtSecurityProvider.issue(
        b"secret", {"aud": "nope", "exp": time.time() + 60}
    )
    assert not p.authenticate_request(
        Headers({"Authorization": f"Bearer {wrong_aud}"}), None
    )
    assert not p.authenticate_request(
        Headers({"Authorization": "Bearer not.a.jwt"}), None
    )


def test_trusted_proxy_provider():
    p = TrustedProxySecurityProvider(
        {"10.0.0.1"}, allowed_users=["alice"]
    )
    h = Headers({"X-Forwarded-User": "alice"})
    assert p.authenticate_request(h, ("10.0.0.1", 999))
    assert not p.authenticate_request(h, ("10.0.0.2", 999))
    assert not p.authenticate_request(Headers({}), ("10.0.0.1", 999))
    assert not p.authenticate_request(
        Headers({"X-Forwarded-User": "mallory"}), ("10.0.0.1", 999)
    )


def test_spnego_fails_closed():
    p = SpnegoSecurityProvider()
    assert not p.authenticate_request(Headers({}), ("127.0.0.1", 1))


def test_basic_provider_spi_signature():
    p = BasicSecurityProvider({"u": "pw"})
    import base64

    h = Headers(
        {"Authorization": "Basic " + base64.b64encode(b"u:pw").decode()}
    )
    assert p.authenticate_request(h, ("127.0.0.1", 1))


def test_bootstrap_serves_rest_and_ui():
    """Full standalone app: build, start, drive REST + /ui, shut down."""
    from cruise_control_tpu.bootstrap import build_app
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )

    cfg = CruiseControlConfig({
        "simulation.num.brokers": 6,
        "simulation.num.partitions": 24,
        "metric.sampling.interval.ms": 1000,
        "partition.metrics.window.ms": 1000,
        "use.tpu.optimizer": "false",
    })
    app = build_app(cfg, port=0)
    try:
        app.server.start()
        # feed a few metric windows so the model is generatable
        for w in range(3):
            app.reporter.report(time_ms=w * 1000 + 500)
        app.fetcher_manager.fetch_once(now_ms=4000)
        base = app.server.url

        state = json.load(urllib.request.urlopen(f"{base}/state"))
        assert state["MonitorState"]["state"] == "RUNNING"

        ui = urllib.request.urlopen(
            base.replace("/kafkacruisecontrol", "/ui")
        ).read().decode()
        assert "<title>cruise-control</title>" in ui

        proposals = json.load(
            urllib.request.urlopen(f"{base}/proposals?json=true")
        )
        assert "proposals" in proposals or "summary" in proposals
    finally:
        app.shutdown()


def test_load_properties(tmp_path):
    from cruise_control_tpu.bootstrap import load_properties

    f = tmp_path / "cc.properties"
    f.write_text(
        "# comment\n! other comment\n\nwebserver.http.port=1234\n"
        "default.goals=A,B\n"
    )
    props = load_properties(str(f))
    assert props == {"webserver.http.port": "1234", "default.goals": "A,B"}


def test_bootstrap_reads_capacity_and_cluster_configs_files(tmp_path):
    """capacity.config.file drives the file resolver; cluster.configs.file
    seeds the topic-anomaly detector's target RF (upstream
    config/capacity.json + config/clusterConfigs.json side-files)."""
    import json

    from cruise_control_tpu.bootstrap import build_app
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.detector.anomalies import AnomalyType
    from cruise_control_tpu.monitor.capacity import (
        BrokerCapacityConfigFileResolver,
    )

    cap = tmp_path / "capacity.json"
    cap.write_text(json.dumps({
        "brokerCapacities": [
            {"brokerId": -1, "capacity": {
                "DISK": 1e9, "CPU": 1e9, "NW_IN": 1e9, "NW_OUT": 1e9}},
        ],
    }))
    cl = tmp_path / "clusterConfigs.json"
    cl.write_text(json.dumps({"replication.factor": 3}))
    cfg = CruiseControlConfig({
        "capacity.config.file": str(cap),
        "cluster.configs.file": str(cl),
    })
    app = build_app(cfg, port=0)
    try:
        assert isinstance(
            app.cruise_control.load_monitor.capacity_resolver,
            BrokerCapacityConfigFileResolver,
        )
        topic_det = \
            app.detector_manager.detectors[AnomalyType.TOPIC_ANOMALY]
        assert topic_det.finder.target_rf == 3
    finally:
        # a leaked app keeps its real-clock SLO engine evaluating the
        # process-wide registry for the rest of the session; its breach
        # emissions then land in whatever journal is current — including
        # a later scenario run's virtual-clock journal, breaking the
        # pinned soak fingerprints (caught in the wild: three slo.breach
        # records mid-soak, measured off suite-accumulated registry rows)
        app.shutdown()
