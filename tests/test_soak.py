"""Full-server soak with fault injection (round-3 VERDICT #7; SURVEY.md §4
tier-3, upstream ``CCKafkaIntegrationTestHarness`` semantics).

Everything the server runs in production runs here CONCURRENTLY against
the simulated cluster — REST traffic over a real loopback socket,
background proposal precompute, the anomaly-detector thread with
self-healing enabled, the metrics pipeline, and the executor — through a
compressed schedule of injected faults:

  A. a broker dies MID-execution (its in-flight moves go DEAD),
  B. a JBOD log dir goes offline on another broker,
  C. an operator stops a running evacuation.

Asserts: no deadlock (every wait is bounded and every thread joins), the
server answers throughout, the executor recovers after each injected
kill, user tasks do not leak past their TTL, and the terminal state is
hard-goal clean (no replica on the dead broker or an offline dir, full
replication, live leaders).

Wall-clock budget ~60-120 s — slow, deliberately: this is the one test
that runs the WHOLE server at once.
"""

import threading
import time

import pytest

from cruise_control_tpu.client.cccli import (
    CruiseControlClient,
    CruiseControlError,
)
from cruise_control_tpu.detector.manager import make_detector_manager
from cruise_control_tpu.executor.backend import SimulatedClusterBackend
from cruise_control_tpu.server import CruiseControlHttpServer
from cruise_control_tpu.server.user_tasks import UserTaskManager

from harness import WINDOW, full_stack
from test_detector import healing_notifier

DEAD_BROKER = 3
DISK_BROKER = 2
EVAC_BROKER = 5


def _wait(predicate, timeout_s: float, what: str) -> None:
    """Bounded wait — a soak must never hang; it fails loudly instead."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.05)
    raise AssertionError(f"soak timed out after {timeout_s}s waiting for: "
                         f"{what}")


def _post_retry(client, endpoint: str, timeout_s: float = 60.0, **params):
    """Admin mutating POST that tolerates losing the ongoing-execution
    race against a concurrent self-healing fix — the operator retries,
    bounded."""
    deadline = time.monotonic() + timeout_s
    while True:
        try:
            return client.post(endpoint, **params)
        except CruiseControlError as e:
            retriable = "OngoingExecution" in str(e) or e.code == 429
            if not retriable or time.monotonic() > deadline:
                raise
            time.sleep(0.2)


class _Traffic(threading.Thread):
    """Continuous REST reads + periodic dryrun rebalances.  Server-side
    errors (model not ready, ongoing execution, task-cap 429s) are part
    of a healthy soak; transport failures are not."""

    def __init__(self, url: str, stop: threading.Event, name: str):
        super().__init__(name=name, daemon=True)
        self.client = CruiseControlClient(url)
        # below the teardown join timeout (20 s): an in-flight long-poll
        # must expire before the join does, or a healthy run trips the
        # deadlock assertion
        self.client.timeout_s = 10
        self.stop_event = stop
        self.ok = 0
        self.rejected = 0
        self.fatal: Exception | None = None

    def run(self) -> None:
        ops = ("state", "load", "proposals", "kafka_cluster_state",
               "user_tasks", "partition_load")
        i = 0
        while not self.stop_event.is_set():
            try:
                if i % 11 == 10:
                    self.client.post("rebalance", dryrun="true")
                else:
                    self.client.get(ops[i % len(ops)])
                self.ok += 1
            except CruiseControlError:
                self.rejected += 1  # server answered: still alive
            except Exception as e:  # noqa: BLE001 - transport failure
                self.fatal = e
                return
            i += 1
            time.sleep(0.02)


class _Sampler(threading.Thread):
    """Keeps metric windows flowing so the monitor stays model-ready."""

    def __init__(self, reporter, monitor, stop: threading.Event,
                 first_window: int):
        super().__init__(name="soak-sampler", daemon=True)
        self.reporter, self.monitor = reporter, monitor
        self.stop_event = stop
        self.w = first_window
        self.fatal: Exception | None = None

    def run(self) -> None:
        while not self.stop_event.is_set():
            try:
                self.reporter.report(time_ms=self.w * WINDOW + 500)
                self.monitor.run_sampling_iteration((self.w + 1) * WINDOW)
            except Exception as e:  # noqa: BLE001
                self.fatal = e
                return
            self.w += 1
            time.sleep(0.1)


def test_full_server_soak_with_fault_injection():
    cc, backend, reporter = full_stack(
        num_partitions=32, num_brokers=6, rf=2, extra_brokers=(6,),
        jbod_disks={"/d1": 50_000.0, "/d2": 50_000.0},
    )
    # slow the simulated cluster down to human speed so executions are
    # RUNNING when faults land (each tick = one progress-check interval)
    orig_tick = SimulatedClusterBackend.tick

    def slow_tick(self):
        time.sleep(0.02)
        orig_tick(self)

    backend.tick = slow_tick.__get__(backend)
    backend.move_latency_ticks = 3
    cc.executor.config.task_timeout_ticks = 10

    # TTL must sit comfortably above the clients' 0.2 s poll gap (a GIL
    # stall during a first-shape compile can stretch one gap to seconds;
    # an expired-but-successful task would 404 the poller)
    utm = UserTaskManager(max_active_tasks=8, completed_task_ttl_s=5.0,
                          max_workers=4, max_cached_completed=50)
    srv = CruiseControlHttpServer(cc, port=0, user_task_manager=utm,
                                  access_log=False)
    srv.start()
    # goal-violation healing joins only for the churn phase (enabled via
    # the admin endpoint below): with it off, the only execution phases
    # A-C can observe is the one THEY started — the latches are specific
    mgr = make_detector_manager(
        cc, backend=backend,
        notifier=healing_notifier(broker_failure=True, disk_failure=True),
        detection_interval_ms=200,
        fix_cooldown_ms=200,
        per_type_interval_ms={},
    )

    def pause_detector():
        # operator-style quiesce: bounded stop of the detector thread so
        # an admin cancel can't strip a fix that started a moment ago
        mgr.stop()

    def resume_detector():
        mgr.start(tick_s=0.25)
    stop = threading.Event()
    threads = [
        _Traffic(srv.url, stop, "soak-traffic-1"),
        _Traffic(srv.url, stop, "soak-traffic-2"),
        _Sampler(reporter, cc.load_monitor, stop, first_window=3),
    ]
    admin = CruiseControlClient(srv.url)
    admin.timeout_s = 60
    try:
        for t in threads:
            t.start()
        mgr.start(tick_s=0.25)
        cc.start_proposal_precomputation(interval_s=0.5)

        # ---- phase A: broker death mid-execution --------------------------
        exec_err: list = []

        def run_rebalance():
            try:
                admin_a = CruiseControlClient(srv.url)
                admin_a.timeout_s = 60
                _post_retry(admin_a, "rebalance", dryrun="false")
            except (CruiseControlError, TimeoutError) as e:
                exec_err.append(e)  # dead-broker moves may fail the op

        reb = threading.Thread(target=run_rebalance, daemon=True)
        reb.start()
        _wait(lambda: cc.executor.has_ongoing_execution, 30,
              "phase A execution to start")
        backend.failed_brokers.add(DEAD_BROKER)
        reb.join(timeout=60)
        assert not reb.is_alive(), "phase A rebalance thread hung"
        assert not exec_err, f"phase A rebalance never ran: {exec_err}"
        _wait(lambda: not cc.executor.has_ongoing_execution, 40,
              "executor recovery after broker death")
        # operator settles the dead tasks' in-flight reassignments (the
        # admin path the broker-death soak in test_executor documents) —
        # detector quiesced so the cancel can't strip a racing fix's adds
        pause_detector()
        backend.cancel_reassignments(list(backend.ongoing_reassignments()))
        resume_detector()
        # self-healing (detector thread) evacuates the dead broker
        _wait(lambda: all(
            DEAD_BROKER not in st.replicas
            for st in backend.partitions.values()
        ), 60, "self-healing evacuation of the dead broker")

        # ---- phase B: JBOD dir failure ------------------------------------
        backend.offline_dirs[DISK_BROKER] = ["/d1"]
        _wait(lambda: not backend.offline_replicas(), 60,
              "self-healing to clear replicas off the offline dir")
        _wait(lambda: not cc.executor.has_ongoing_execution, 40,
              "executor recovery after disk healing")

        # ---- phase C: operator stop of a running evacuation ---------------
        evac_err: list = []

        def run_evac():
            try:
                admin_c = CruiseControlClient(srv.url)
                admin_c.timeout_s = 60
                _post_retry(admin_c, "remove_broker",
                            brokerid=str(EVAC_BROKER), dryrun="false")
            except (CruiseControlError, TimeoutError) as e:
                evac_err.append(e)  # the stop may surface as an error

        evac = threading.Thread(target=run_evac, daemon=True)
        evac.start()
        _wait(lambda: cc.executor.has_ongoing_execution, 30,
              "phase C evacuation to start")
        admin.post("stop_proposal_execution")
        _wait(lambda: not cc.executor.has_ongoing_execution, 40,
              "executor to honor the operator stop")
        evac.join(timeout=60)
        assert not evac.is_alive(), "phase C evacuation thread hung"
        # an operator stop abandons the executor's tasks but leaves their
        # reassignments in flight on the cluster (upstream semantics);
        # the operator cancels them — same quiesced admin path as phase A
        pause_detector()
        backend.cancel_reassignments(list(backend.ongoing_reassignments()))
        resume_detector()

        # ---- goal-violation healing joins for the churn phase -------------
        body = admin.post("admin",
                          enable_self_healing_for="goal_violation")
        assert body["selfHealingEnabledChanged"] == {
            "GOAL_VIOLATION": True}

        # ---- phase D: sustained churn -------------------------------------
        # a compressed multi-hour schedule: repeated full evacuations and
        # re-adds of a broker, executed through REST while the detector,
        # precompute, and read traffic keep running concurrently
        # (placement is not asserted mid-churn: goal-violation healing
        # legitimately races these operations — the churn's job is
        # sustained concurrent execution, the terminal drain asserts state)
        for cycle in range(8):
            _post_retry(admin, "remove_broker",
                        brokerid=str(EVAC_BROKER), dryrun="false")
            _wait(lambda: not cc.executor.has_ongoing_execution, 60,
                  f"churn cycle {cycle}: evacuation to finish")
            _post_retry(admin, "add_broker",
                        brokerid=str(EVAC_BROKER), dryrun="false")
            _wait(lambda: not cc.executor.has_ongoing_execution, 60,
                  f"churn cycle {cycle}: re-add to finish")

        # ---- drain: faults over, let healing settle the hard goals --------
        # the skewed workload model never balances (the reporter replays
        # it forever), so goal-violation healing would churn indefinitely;
        # the operator turns it off — through the admin endpoint, which
        # this also exercises — while broker/disk healing stays on
        body = admin.post("admin",
                          disable_self_healing_for="goal_violation")
        assert body["selfHealingEnabledChanged"] == {
            "GOAL_VIOLATION": False}

        last_reason = ["unchecked"]

        def hard_goal_clean() -> bool:
            if cc.executor.has_ongoing_execution:
                last_reason[0] = "execution still ongoing"
                return False
            if backend.offline_replicas():
                last_reason[0] = (
                    f"offline replicas: {backend.offline_replicas()}"
                )
                return False
            for p, st in backend.partitions.items():
                reps = st.replicas
                if DEAD_BROKER in reps or len(reps) != len(set(reps)):
                    last_reason[0] = f"p{p} on dead broker/dup: {reps}"
                    return False
                if len(reps) != 2 or st.leader not in reps:
                    last_reason[0] = (
                        f"p{p} rf/leader broken: {reps} leader {st.leader}"
                    )
                    return False
                if st.leader in backend.failed_brokers:
                    last_reason[0] = f"p{p} leader dead: {st.leader}"
                    return False
                if st.catching_up:
                    last_reason[0] = f"p{p} catching up: {st.catching_up}"
                    return False
            return True

        try:
            _wait(hard_goal_clean, 90, "hard-goal-clean terminal state")
        except AssertionError as e:
            raise AssertionError(f"{e} (last reason: {last_reason[0]})")

        # the server is still fully responsive after everything it went
        # through (checked before teardown stops it)
        state = admin.get("state")
        assert "MonitorState" in state and "ExecutorState" in state
    finally:
        stop.set()
        cc.stop_proposal_precomputation()
        mgr.stop()
        for t in threads:
            t.join(timeout=20)
        alive = [t.name for t in threads if t.is_alive()]
        srv.stop()
        assert not alive, f"soak threads failed to stop (deadlock?): {alive}"

    # ---- post-mortem assertions -------------------------------------------
    for t in threads:
        assert t.fatal is None, f"{t.name} transport failure: {t.fatal!r}"
    for t in threads[:2]:
        assert t.ok >= 50, (
            f"{t.name} starved: {t.ok} ok / {t.rejected} rejected"
        )

    # no user-task leak: everything completes and expires past its TTL
    from cruise_control_tpu.server.user_tasks import UserTaskState

    _wait(lambda: not any(
        t.state == UserTaskState.ACTIVE for t in utm.tasks()
    ), 30, "active user tasks to drain")
    time.sleep(5.5)  # > completed_task_ttl_s
    listing = utm.tasks()  # tasks() expires TTL-passed entries first
    active = [t.task_id for t in listing
              if t.state == UserTaskState.ACTIVE]
    assert not active, f"leaked active tasks: {active}"
    assert not listing, (
        f"completed tasks survived their TTL: {len(listing)}"
    )
