"""TPU engine tests: greedy parity, hard-goal safety, sharded search
(BASELINE.json configs #2/#3 semantics at test scale)."""

import jax
import numpy as np
import pytest

from cruise_control_tpu.analyzer.context import OptimizationOptions
from cruise_control_tpu.analyzer.goal_optimizer import GoalOptimizer, make_goals
from cruise_control_tpu.analyzer.tpu_optimizer import (
    TpuGoalOptimizer,
    TpuSearchConfig,
)
from cruise_control_tpu.analyzer.verifier import verify_result, violation_score
from cruise_control_tpu.models.generators import Distribution, random_cluster

FAST = TpuSearchConfig(max_rounds=40, topk_per_round=128, max_moves_per_round=32)


def test_tpu_engine_beats_or_matches_greedy():
    """The parity bar: violation score ≤ greedy on the same input."""
    state = random_cluster(
        seed=3, num_brokers=20, num_racks=5, num_partitions=300,
        distribution=Distribution.EXPONENTIAL, mean_utilization=0.4,
    )
    goals = make_goals()
    greedy = GoalOptimizer(goals).optimize(state)
    tpu = TpuGoalOptimizer(config=FAST).optimize(state)
    verify_result(state, tpu, goals)
    g_score = violation_score(greedy.final_state, goals)
    t_score = violation_score(tpu.final_state, goals)
    assert t_score <= g_score + 2, (g_score, t_score)


def test_tpu_engine_dead_broker_replan():
    """BASELINE config #4: self-healing replan under hard goals."""
    state = random_cluster(
        seed=5, num_brokers=12, num_racks=4, num_partitions=120, dead_brokers=2,
    )
    goals = make_goals()
    res = TpuGoalOptimizer(config=FAST).optimize(state)
    verify_result(state, res, goals)
    fa = np.array(res.final_state.assignment)
    assert not np.isin(fa, [10, 11]).any()


def test_tpu_engine_excluded_topics():
    state = random_cluster(seed=7, num_brokers=10, num_partitions=80, num_topics=4)
    goals = make_goals()
    options = OptimizationOptions(excluded_topics={1})
    res = TpuGoalOptimizer(config=FAST).optimize(state, options)
    verify_result(state, res, goals, options)


def test_tpu_engine_sharded_mesh():
    """Device-resident search sharded over the 8-device CPU mesh: the
    rescore shards inside the while_loop (not the score-only fallback), and
    the plan matches single-device tightly — with K divisible by the mesh
    size the two programs are arithmetically identical."""
    from jax.sharding import Mesh

    devices = np.array(jax.devices()[:8])
    mesh = Mesh(devices, axis_names=("search",))
    state = random_cluster(
        seed=9, num_brokers=16, num_racks=4, num_partitions=128,
        mean_utilization=0.45,
    )
    goals = make_goals()
    res = TpuGoalOptimizer(config=FAST, mesh=mesh).optimize(state)
    verify_result(state, res, goals)
    res_1 = TpuGoalOptimizer(config=FAST).optimize(state)
    s_mesh = violation_score(res.final_state, goals)
    s_one = violation_score(res_1.final_state, goals)
    assert abs(s_mesh - s_one) <= max(1, int(0.02 * max(s_mesh, s_one)))


def test_tpu_engine_sharded_mesh_at_scale():
    """VERDICT round-1 item #1's done-bar: the device-RESIDENT path (not a
    fallback) runs under the mesh at 1k brokers / 20k partitions on the
    virtual 8-CPU mesh, with a tight quality tolerance vs single-device.

    The search config is the production default (steps_per_call > 0 ⇒
    resident while_loop engine); plan equality is expected because the
    sharded rescore is arithmetically identical when the mesh size divides
    K, so the tolerance only allows for XLA reduction-order drift."""
    from jax.sharding import Mesh

    devices = np.array(jax.devices()[:8])
    mesh = Mesh(devices, axis_names=("search",))
    state = random_cluster(
        seed=42, num_brokers=1000, num_racks=20, num_partitions=20000,
        mean_utilization=0.4,
    )
    goals = make_goals()
    cfg = TpuSearchConfig()
    assert cfg.steps_per_call > 0  # resident engine, not score-only rounds
    res_m = TpuGoalOptimizer(config=cfg, mesh=mesh).optimize(state)
    verify_result(state, res_m, goals)
    res_1 = TpuGoalOptimizer(config=cfg).optimize(state)
    s_mesh = violation_score(res_m.final_state, goals)
    s_one = violation_score(res_1.final_state, goals)
    assert abs(s_mesh - s_one) <= max(2, int(0.02 * max(s_mesh, s_one))), (
        s_mesh, s_one,
    )


def test_graft_entry_single_chip():
    import __graft_entry__

    fn, args = __graft_entry__.entry()
    scores, kind, cp, cs, cd = jax.jit(fn)(*args)
    assert scores.shape[0] > 0
    assert np.isfinite(np.asarray(scores)).any()


def test_graft_entry_multichip():
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)


def test_tpu_engine_raises_on_impossible_hard_goal():
    """Same contract as greedy: infeasible hard goals raise, never a silent
    hard-violating plan (code-review regression)."""
    from cruise_control_tpu.analyzer.goals.base import OptimizationFailure
    from cruise_control_tpu.models.builder import ClusterModelBuilder
    from cruise_control_tpu.common.resources import Resource, BrokerState

    b = ClusterModelBuilder()
    cap = {r: 1e9 for r in Resource}
    b.add_broker("r0", cap)
    b.add_broker("r0", cap)
    b.add_partition("T", [0, 1], {Resource.DISK: 1.0})  # same rack, RF 2
    with pytest.raises(OptimizationFailure):
        TpuGoalOptimizer(config=FAST).optimize(b.build())


def test_tpu_engine_evacuates_excluded_topic_offline_replicas():
    """Offline replicas of excluded topics still evacuate (parity with
    greedy's evacuate_offline_replicas; code-review regression)."""
    state = random_cluster(seed=61, num_brokers=10, num_racks=5,
                           num_partitions=60, num_topics=3, dead_brokers=1)
    goals = make_goals()
    options = OptimizationOptions(excluded_topics={0, 1, 2})
    res = TpuGoalOptimizer(config=FAST).optimize(state, options)
    verify_result(state, res, goals, options)
    fa = np.array(res.final_state.assignment)
    assert not (fa == 9).any()


def test_tpu_engine_heterogeneous_capacity():
    """Budgeted-cohort safety under heterogeneous broker capacities
    (advisor round-1 medium finding: the water-filling budgets must use
    the capacity-normalized pivot condition, or same-destination cohorts
    can commit a net-worsening batch that both the device score and the
    snapshot recheck accept)."""
    from cruise_control_tpu.models.generators import DEFAULT_CAPACITY

    B = 24
    rng = np.random.default_rng(11)
    scale = rng.uniform(0.4, 2.5, size=(B, 1)).astype(np.float32)
    cap = (DEFAULT_CAPACITY[None, :] * scale).astype(np.float32)
    state = random_cluster(
        seed=11, num_brokers=B, num_racks=6, num_partitions=320,
        capacity=cap, mean_utilization=0.4,
        distribution=Distribution.EXPONENTIAL,
    )
    goals = make_goals()
    greedy = GoalOptimizer(goals).optimize(state)
    tpu = TpuGoalOptimizer(config=FAST).optimize(state)
    verify_result(state, tpu, goals)
    g_score = violation_score(greedy.final_state, goals)
    t_score = violation_score(tpu.final_state, goals)
    assert t_score <= g_score + 2, (g_score, t_score)


def test_commit_batch_trims_cumulative_destination_breach():
    """A cohort batch whose per-action checks pass but whose cumulative
    per-destination load breaches the capacity threshold must be trimmed in
    commit_batch, not explode later in _finalize (advisor round-1 medium)."""
    from cruise_control_tpu.analyzer.context import AnalyzerContext
    from cruise_control_tpu.analyzer.tpu_optimizer import (
        KIND_MOVE,
        _HostEvaluator,
    )
    from cruise_control_tpu.models.builder import ClusterModelBuilder
    from cruise_control_tpu.common.resources import Resource

    b = ClusterModelBuilder()
    cap = {Resource.CPU: 100.0, Resource.NW_IN: 1e6, Resource.NW_OUT: 1e6,
           Resource.DISK: 100.0}
    # four source brokers each hold one 30-DISK partition; one destination
    # broker with 100 DISK capacity (threshold 0.8 → 80 headroom): any two
    # moves fit individually and cumulatively, three breach cumulatively
    racks = ["r0", "r1", "r2", "r3", "r4"]
    for r in racks:
        b.add_broker(r, cap)
    load = {Resource.CPU: 1.0, Resource.NW_IN: 1.0, Resource.NW_OUT: 1.0,
            Resource.DISK: 30.0}
    for src in range(4):
        b.add_partition(f"T{src}", [src], load)
    state = b.build()
    ctx = AnalyzerContext(state)
    opt = TpuGoalOptimizer(config=FAST)
    can = opt._constraint_arrays_np(ctx)
    ev = _HostEvaluator(ctx, opt.config, can)
    kind = np.full(4, KIND_MOVE, np.int32)
    p = np.arange(4, dtype=np.int32)
    s = np.zeros(4, np.int32)
    d = np.full(4, 4, np.int32)          # all into broker 4
    acts, n_rej = ev.commit_batch(kind, p, s, d)
    thr = float(can["cap_threshold"][Resource.DISK])
    assert ctx.broker_load[4, Resource.DISK] <= 100.0 * thr + 1e-6
    # every accepted action fits; at least one was trimmed
    assert len(acts) + n_rej == 4
    assert n_rej >= 1


def test_host_device_cost_parity():
    """_np_broker_cost (host commit criterion) must match _broker_cost (device
    score) term-for-term: drift would make the host reject every device
    proposal or commit unfavored actions (code-review finding)."""
    import jax.numpy as jnp
    from cruise_control_tpu.analyzer.context import AnalyzerContext
    from cruise_control_tpu.analyzer.tpu_optimizer import (
        TpuGoalOptimizer,
        _broker_cost,
        _np_broker_cost,
    )

    state = random_cluster(seed=17, num_brokers=12, num_racks=4, num_partitions=80)
    opt = TpuGoalOptimizer()
    ctx = AnalyzerContext(state)
    can = opt._constraint_arrays_np(ctx)
    ca = {k: jnp.asarray(v) for k, v in can.items()}
    m = opt._device_model(ctx)

    rng = np.random.default_rng(3)
    for b in rng.integers(0, ctx.num_brokers, size=8):
        b = int(b)
        load = ctx.broker_load[b] * rng.uniform(0.5, 1.5)
        lnwin = float(ctx.broker_leader_load[b][2]) * 1.1
        pot = float(ctx.broker_potential_nw_out[b]) * 0.9
        rc = float(ctx.broker_replica_count[b]) + 1
        lc = float(ctx.broker_leader_count[b])
        dev = float(
            _broker_cost(
                m, opt.config, ca,
                jnp.asarray(load, jnp.float32), jnp.float32(lnwin),
                jnp.float32(pot), jnp.float32(rc), jnp.float32(lc),
                jnp.int32(b),
            )
        )
        host = _np_broker_cost(
            opt.config, can, ctx.broker_capacity[b],
            load, lnwin, pot, rc, lc,
        )
        assert abs(dev - host) <= 1e-3 * max(1.0, abs(dev)), (b, dev, host)


@pytest.mark.parametrize("scoring", ["columnar", "grid"])
def test_engine_scoring_paths_agree(scoring):
    """All three scoring paths must produce verifiable plans of equal quality
    (same scores → same committed actions, modulo f32 tie-breaks)."""
    from cruise_control_tpu.analyzer.goal_optimizer import make_goals
    from cruise_control_tpu.analyzer.tpu_optimizer import (
        TpuGoalOptimizer,
        TpuSearchConfig,
    )
    from cruise_control_tpu.analyzer.verifier import verify_result

    state = random_cluster(seed=29, num_brokers=16, num_racks=4,
                           num_partitions=96, mean_utilization=0.45)
    result = TpuGoalOptimizer(
        config=TpuSearchConfig(max_rounds=40, topk_per_round=64,
                               scoring=scoring)
    ).optimize(state)
    verify_result(state, result, make_goals())


def test_tpu_engine_drains_large_dead_broker_device_path():
    """A dead broker holding many replicas must fully evacuate through the
    device-resident path: evacuations serialize to one per step (each needs
    a fresh rescore — see _match_batch), so the call budget must scale
    with the step-counted action budget, not bare max_rounds (code-review
    regression)."""
    state = random_cluster(
        seed=17, num_brokers=12, num_racks=4, num_partitions=600,
        dead_brokers=1,
    )
    cfg = TpuSearchConfig(
        max_rounds=6, topk_per_round=256, max_moves_per_round=512,
        steps_per_call=4, device_batch_per_step=16,
    )
    res = TpuGoalOptimizer(config=cfg).optimize(state)
    verify_result(state, res, make_goals())
    fa = np.array(res.final_state.assignment)
    assert not (fa == 11).any()


def test_score_only_path_drains_large_dead_broker():
    """The score-only (steps_per_call=0) path keeps per-source candidate
    rows, so a dead broker exposes ALL its replicas per round — the
    per-src-broker reduction is a device-scan-only concept (code-review
    regression)."""
    state = random_cluster(
        seed=17, num_brokers=12, num_racks=4, num_partitions=600,
        dead_brokers=1,
    )
    cfg = TpuSearchConfig(max_rounds=150, steps_per_call=0, scoring="grid")
    res = TpuGoalOptimizer(config=cfg).optimize(state)
    verify_result(state, res, make_goals())
    fa = np.array(res.final_state.assignment)
    assert not (fa == 11).any()


def test_match_batch_disjoint_and_best_first():
    """_match_batch invariants: taken actions are disjoint on src broker,
    dst broker, and partition; every taken score beats tol; and a candidate
    whose provisional winner was eliminated keeps (not skips) its best
    still-free destination."""
    import jax.numpy as jnp

    from cruise_control_tpu.analyzer.tpu_optimizer import _match_batch

    B, P = 8, 16
    # 4 candidates: 0 and 1 fight for dst 5 (0 wins on score); 2 shares
    # src with nobody but proposes dst 6; 3 duplicates partition of 2.
    cand_score = jnp.array([
        [-3.0, -1.0],
        [-2.0, -0.5],
        [-1.5, -0.2],
        [-1.0, -0.9],
    ])
    cand_dst = jnp.array([
        [5, 6],
        [5, 7],
        [6, 4],
        [3, 2],
    ], dtype=jnp.int32)
    cand_src = jnp.array([0, 1, 2, 3], dtype=jnp.int32)
    cand_p = jnp.array([10, 11, 12, 12], dtype=jnp.int32)
    take, win_score, win_dst = _match_batch(
        cand_score, cand_dst, cand_src, cand_p, tol=-1e-4, B=B, P=P,
    )
    take = np.asarray(take)
    win_dst = np.asarray(win_dst)
    win_score = np.asarray(win_score)
    assert take[0] and take[2]            # best per contested dst wins
    assert take[1]                        # loser falls back to alt dst 7
    assert win_dst[0] == 5 and win_dst[1] == 7 and win_dst[2] == 6
    assert not take[3] or win_dst[3] != win_dst[2]  # partition 12 dedup
    taken = np.flatnonzero(take)
    # disjointness across the taken set
    assert len({int(cand_src[i]) for i in taken}) == len(taken)
    assert len({int(win_dst[i]) for i in taken}) == len(taken)
    assert len({int(cand_p[i]) for i in taken}) == len(taken)
    assert (win_score[take] < -1e-4).all()


def test_time_budget_still_satisfies_hard_goals():
    """The anytime budget may cut soft-goal refinement short but never hard
    goals: a near-zero budget must still produce a verified plan (dead
    broker drained, rack repairs done) rather than OptimizationFailure
    (code-review regression)."""
    state = random_cluster(
        seed=23, num_brokers=12, num_racks=4, num_partitions=200,
        dead_brokers=1,
    )
    cfg = TpuSearchConfig(max_rounds=60, time_budget_s=1e-6)
    res = TpuGoalOptimizer(config=cfg).optimize(state)
    verify_result(state, res, make_goals())
    assert not (np.array(res.final_state.assignment) == 11).any()


def test_commit_batch_matches_sequential_replay():
    """The vectorized host recheck (_HostEvaluator.commit_batch) must accept
    the same actions with the same context mutations as the scalar
    evaluate/apply replay it replaced, on a mixed batch of feasible,
    infeasible, and non-improving candidates."""
    from cruise_control_tpu.analyzer.context import AnalyzerContext
    from cruise_control_tpu.analyzer.tpu_optimizer import (
        _HostEvaluator,
        KIND_LEADERSHIP,
        KIND_MOVE,
    )

    state = random_cluster(seed=31, num_brokers=10, num_racks=5,
                           num_partitions=120, dead_brokers=1)
    cfg = TpuSearchConfig()
    opt = TpuGoalOptimizer(config=cfg)

    rng = np.random.default_rng(7)
    n = 64
    kind = rng.integers(0, 2, n).astype(np.int32)
    p = rng.integers(0, 120, n).astype(np.int32)
    s = rng.integers(0, state.assignment.shape[1], n).astype(np.int32)
    d = rng.integers(-1, 10, n).astype(np.int32)
    # a batch must be disjoint in partitions AND endpoint brokers (the
    # matcher guarantees all three) — filter the random candidates the
    # same way, consulting the pristine context for endpoints
    ctx0 = AnalyzerContext(state)
    keep, used_p, used_b = [], set(), set()
    for i in range(p.shape[0]):
        pi, si, di = int(p[i]), int(s[i]), int(d[i])
        if si >= ctx0.assignment.shape[1]:
            continue
        slot_b = int(ctx0.assignment[pi, si])
        if kind[i] == KIND_MOVE:
            src, dst = slot_b, di
        else:
            src, dst = ctx0.leader_broker(pi), slot_b
        if pi in used_p or src in used_b or dst in used_b:
            continue
        keep.append(i)
        used_p.add(pi)
        used_b.update((src, dst))
    keep = np.array(keep)
    kind, p, s, d = kind[keep], p[keep], s[keep], d[keep]

    # sequential reference
    ctx_a = AnalyzerContext(state)
    ev_a = _HostEvaluator(ctx_a, cfg, opt._constraint_arrays_np(ctx_a))
    accepted_a = []
    for i in range(p.shape[0]):
        action, delta = ev_a.evaluate(int(kind[i]), int(p[i]), int(s[i]),
                                      int(d[i]))
        if action is not None and delta < cfg.improvement_tol:
            ctx_a.apply(action)
            accepted_a.append(action)

    ctx_b = AnalyzerContext(state)
    ev_b = _HostEvaluator(ctx_b, cfg, opt._constraint_arrays_np(ctx_b))
    accepted_b, _ = ev_b.commit_batch(kind, p, s, d)

    # NOTE: sequential replay sees earlier in-batch actions applied, so on
    # rare overlapping-broker batches the two could differ; this batch is
    # seeded to be conflict-light and must agree exactly.
    assert [(a.action_type, a.partition, a.slot, a.source_broker,
             a.dest_broker, a.dest_slot) for a in accepted_a] == \
           [(a.action_type, a.partition, a.slot, a.source_broker,
             a.dest_broker, a.dest_slot) for a in accepted_b]
    np.testing.assert_allclose(ctx_a.broker_load, ctx_b.broker_load,
                               atol=1e-6)
    np.testing.assert_array_equal(ctx_a.assignment, ctx_b.assignment)
    np.testing.assert_array_equal(ctx_a.leader_slot, ctx_b.leader_slot)
    np.testing.assert_array_equal(ctx_a.broker_leader_count,
                                  ctx_b.broker_leader_count)
    np.testing.assert_array_equal(ctx_a.broker_topic_leader_count,
                                  ctx_b.broker_topic_leader_count)
    np.testing.assert_allclose(ctx_a.broker_leader_load,
                               ctx_b.broker_leader_load, atol=1e-6)
    np.testing.assert_allclose(ctx_a.broker_potential_nw_out,
                               ctx_b.broker_potential_nw_out, atol=1e-6)
    ctx_b.recompute_check()


def test_seg_prefix_fits():
    """Segmented budget-prefix acceptance: rows in score order, per-id
    cumulative load gated by the id's budget, ineligible rows contribute
    nothing."""
    import jax.numpy as jnp

    from cruise_control_tpu.analyzer.tpu_optimizer import _seg_prefix_fits

    ids = jnp.array([5, 5, 2, 5, 2], dtype=jnp.int32)
    vec = jnp.array([[1.0], [1.0], [2.0], [1.0], [2.0]])
    budget = jnp.zeros((8, 1)).at[5, 0].set(2.0).at[2, 0].set(3.0)
    eligible = jnp.array([True, True, True, True, True])
    fits = np.asarray(_seg_prefix_fits(ids, vec, budget, eligible))
    # id 5: rows 0,1 fill the budget of 2; row 3 (third unit) is rejected
    # id 2: row 2 fits (2 <= 3); row 4 would make 4 > 3 -> rejected
    assert list(fits) == [True, True, True, False, False]

    # an ineligible better row must not consume budget
    eligible2 = jnp.array([False, True, True, True, True])
    fits2 = np.asarray(_seg_prefix_fits(ids, vec, budget, eligible2))
    assert list(fits2) == [False, True, True, True, False]


def test_reoptimize_converged_cluster_is_quiet():
    """Optimizing an already-optimized cluster must produce a near-empty
    plan: the improvement tolerance gates micro-moves, so convergence is a
    fixed point rather than an oscillation (upstream parity: a second
    /rebalance right after one completes proposes ~nothing)."""
    state = random_cluster(seed=11, num_brokers=16, num_racks=4,
                           num_partitions=240, mean_utilization=0.4)
    res1 = TpuGoalOptimizer(config=FAST).optimize(state)
    res2 = TpuGoalOptimizer(config=FAST).optimize(res1.final_state)
    assert len(res2.actions) <= max(8, len(res1.actions) // 10), (
        len(res1.actions), len(res2.actions))


def test_topq_rows_per_src():
    """Per-broker top-Q selection: ordered by score, K-padded when a broker
    has fewer rows, infinite-score rows never selected."""
    import jax.numpy as jnp

    from cruise_control_tpu.analyzer.tpu_optimizer import _topq_rows_per_src

    sb = jnp.array([0, 0, 0, 1, 1, 2], dtype=jnp.int32)
    score = jnp.array([-5.0, -9.0, -7.0, -1.0, -2.0, jnp.inf])
    K = 6
    rows, scores = _topq_rows_per_src(sb, score, B=4, Q=2)
    rows = np.asarray(rows)
    # the returned scores are exactly the selected rows' scores (inf at
    # invalid slots) — callers use them as the sort key without re-gather
    sc = np.asarray(scores)
    for q in range(2):
        for b in range(4):
            if rows[q, b] < len(np.asarray(score)):
                assert sc[q, b] == np.asarray(score)[rows[q, b]]
            else:
                assert np.isinf(sc[q, b])
    # broker 0: rows 1 (-9) then 2 (-7); broker 1: rows 4 (-2) then 3 (-1);
    # broker 2: only an inf row -> never selected; broker 3: no rows
    assert rows[0, 0] == 1 and rows[1, 0] == 2
    assert rows[0, 1] == 4 and rows[1, 1] == 3
    assert rows[0, 2] == K and rows[1, 2] == K
    assert rows[0, 3] == K and rows[1, 3] == K


def test_budget_accept_recovers_starved_segment():
    """An oversized best-scored row must not permanently starve its
    segment: the multi-round acceptance drops individually-unfittable rows
    and admits the smaller rows behind them, while never overshooting the
    budget."""
    import jax.numpy as jnp

    from cruise_control_tpu.analyzer.tpu_optimizer import _budget_accept

    # all rows target dst 5 from distinct srcs; loads [3, 1, 1], deficit 2
    dst = jnp.array([5, 5, 5], dtype=jnp.int32)
    src = jnp.array([0, 1, 2], dtype=jnp.int32)
    vec = jnp.array([[3.0], [1.0], [1.0]])
    dstb = jnp.zeros((8, 1)).at[5, 0].set(2.0)
    srcb = jnp.full((8, 1), 10.0)
    acc = np.asarray(_budget_accept(dst, src, vec, dstb, srcb,
                                    jnp.ones(3, bool)))
    assert list(acc) == [False, True, True]


@pytest.mark.parametrize("seed", [101, 202, 303, 404])
def test_fuzz_engine_invariants(seed):
    """Randomized cross-engine invariants: for varied topologies
    (replication factors, rack counts, dead brokers, exclusions, skewed
    loads), the TPU engine must produce a verifiable plan (hard goals
    hold, proposals consistent) whose violation score is within tolerance
    of the greedy oracle's."""
    rng = np.random.default_rng(seed)
    num_brokers = int(rng.integers(8, 24))
    state = random_cluster(
        seed=seed,
        num_brokers=num_brokers,
        num_racks=int(rng.integers(3, 6)),
        num_partitions=int(rng.integers(60, 240)),
        num_topics=int(rng.integers(2, 6)),
        dead_brokers=int(rng.integers(0, 2)),
        replication_factor=int(rng.integers(2, 4)),
        distribution=rng.choice(list(Distribution)),
        mean_utilization=float(rng.uniform(0.25, 0.5)),
    )
    options = OptimizationOptions(
        excluded_topics=(
            {int(rng.integers(2))} if rng.random() < 0.5 else set()
        )
    )
    goals = make_goals()
    tpu = TpuGoalOptimizer(config=FAST).optimize(state, options)
    verify_result(state, tpu, goals, options)
    greedy = GoalOptimizer(goals).optimize(state, options)
    g = violation_score(greedy.final_state, goals)
    t = violation_score(tpu.final_state, goals)
    assert t <= g + max(3, g // 10), (seed, g, t)


def test_parity_gate_midscale():
    """The continuous parity harness at in-suite scale (VERDICT round-1
    item #4): TPU violation score <= greedy on a 100-broker/2000-partition
    fixture, via the same benchmarks/parity_gate.py entry the driver can
    run at 200/5000 on real hardware (where it also enforces the 10x
    wall-clock gate; CPU test rigs only assert quality + faster-than)."""
    import sys

    sys.path.insert(0, "benchmarks")
    try:
        from parity_gate import run
    finally:
        sys.path.pop(0)
    result = run(num_brokers=100, num_partitions=2000, min_speedup=1.0)
    assert result["quality_gate"], result
    assert result["speed_gate"], result  # at least faster than greedy


@pytest.fixture(scope="module")
def greedy_60b_baseline():
    """One greedy oracle on the shared 60b/1200p fixture for every
    non-default-engine-knob quality-bar test (multi-second CPU cost)."""
    state = random_cluster(seed=21, num_brokers=60, num_racks=6,
                           num_partitions=1200)
    goals = make_goals()
    greedy = GoalOptimizer(goals).optimize(state)
    return state, goals, violation_score(greedy.final_state, goals)


@pytest.mark.parametrize("cfg", [
    # the round-3 exact-conservative stacked cohort
    TpuSearchConfig(cohort_mode="corrected"),
    # round-4 commit-ordering guard (the only path tracing the
    # stacked/guard branch)
    TpuSearchConfig(cohort_mode="corrected", cohort_stack_tol=0.25),
    # narrowed selection problem size (< (Q+1)*B = 300 rows)
    TpuSearchConfig(selection_rows=64),
])
def test_non_default_engine_knobs_hold_quality_bar(cfg, greedy_60b_baseline):
    """Non-default engine knobs must compile and hold the same quality
    bar as the default: violation score <= greedy on the same input."""
    state, goals, greedy_score = greedy_60b_baseline
    tpu = TpuGoalOptimizer(config=cfg).optimize(state)
    verify_result(state, tpu, goals)
    assert violation_score(tpu.final_state, goals) <= greedy_score, cfg


def test_tpu_engine_count_saturated_swap_repair():
    """The device vocabulary (moves + leadership) cannot fix a
    count-saturated over-capacity fixture — the host swap-repair pass must
    kick in with INTER_BROKER_REPLICA_SWAP instead of raising
    OptimizationFailure (VERDICT r4 missing #1, engine side)."""
    from cruise_control_tpu.analyzer.actions import ActionType
    from cruise_control_tpu.analyzer.goals.base import BalancingConstraint
    from cruise_control_tpu.common.resources import Resource
    from cruise_control_tpu.models.builder import ClusterModelBuilder

    b = ClusterModelBuilder()
    cap = {Resource.CPU: 1e9, Resource.NW_IN: 1e9, Resource.NW_OUT: 1e9,
           Resource.DISK: 100.0}
    b0 = b.add_broker("r0", cap)
    b1 = b.add_broker("r1", cap)

    def disk(mb):
        return {Resource.CPU: 0.1, Resource.NW_IN: 0.1,
                Resource.NW_OUT: 0.1, Resource.DISK: mb}

    b.add_partition("T", [b0], disk(60.0))
    b.add_partition("T", [b0], disk(30.0))   # broker0: 90 > 80 (hard)
    b.add_partition("T", [b1], disk(10.0))
    b.add_partition("T", [b1], disk(5.0))    # broker1: 15, count-full
    state = b.build()
    constraint = BalancingConstraint(max_replicas_per_broker=2)
    goals = make_goals(constraint=constraint)
    res = TpuGoalOptimizer(config=FAST, constraint=constraint).optimize(state)
    verify_result(state, res, goals)
    assert any(a.action_type == ActionType.INTER_BROKER_REPLICA_SWAP
               for a in res.actions)


def test_anytime_budget_per_step_deadline():
    """`time_budget_s` binds at STEP granularity — asserted on the
    deterministic ``diag["steps_run"]`` contract (round-5 VERDICT next #3:
    the old wall-clock bound raced concurrent CPU load and flaked):

    * a device call invoked with step cap ``t_cap`` executes at most
      ``t_cap`` steps;
    * every cap value shares ONE compiled executable (the host always
      passes ``t_cap`` as a traced scalar — a second capped variant would
      pollute the probe call's step-rate sample with compile time);
    * a budgeted end-to-end run still commits work with hard goals held.
    """
    import jax.numpy as jnp

    from cruise_control_tpu.analyzer import tpu_optimizer as T
    from cruise_control_tpu.analyzer.context import AnalyzerContext

    state = random_cluster(
        seed=11, num_brokers=24, num_racks=6, num_partitions=300,
        distribution=Distribution.EXPONENTIAL, mean_utilization=0.45,
    )
    cfg = TpuSearchConfig(steps_per_call=48, device_batch_per_step=8)
    opt = TpuGoalOptimizer(config=cfg)
    ctx = AnalyzerContext(state)
    m = opt._device_model(ctx)
    ca = {
        k: jnp.asarray(v) for k, v in opt._constraint_arrays_np(ctx).items()
    }
    K, D = opt._pool_sizes(ctx.num_partitions, ctx.max_rf, ctx.num_brokers)
    scan_fn = T._cached_scan_fn(cfg, K, D, cfg.steps_per_call, None)
    for cap in (1, 7, cfg.steps_per_call):
        # donate_carry: a call consumes its input model, so thread the
        # returned (undonated) model into the next capped call
        packed, m, _tab = scan_fn(m, ca, jnp.asarray(cap, jnp.int32))
        diag = T._fetch_scan_result(packed, cfg.steps_per_call)[-1]
        assert 0 < diag["steps_run"] <= cap, (cap, diag["steps_run"])
    cache_size = getattr(scan_fn, "_cache_size", None)
    if cache_size is not None:  # jax-version tolerant
        assert cache_size() == 1, "capped calls must share one executable"

    res = TpuGoalOptimizer(
        config=TpuSearchConfig(time_budget_s=0.5, steps_per_call=48)
    ).optimize(state)
    assert res.actions, "budgeted run must still commit work"
    final_ctx = AnalyzerContext(res.final_state)
    for g in make_goals():
        if g.is_hard:
            assert g.violations(final_ctx) == 0, g.name
