"""Drive-loop pipelining + pool-rebuild diet contracts.

* Plan identity: the pipelined drive loop (speculative device calls in
  flight) must produce a BIT-IDENTICAL plan to serial mode — the
  speculative call k+1 runs on call k's device-updated model, which is
  exactly the model the serial loop would have dispatched on whenever the
  host validated call k cleanly.
* Pool-rebuild diet: the incrementally refreshed pool row tables
  (ops.pools) must equal a from-scratch recompute bit-for-bit, and the
  engine must produce the same plan with the diet on or off (including
  the budget-breach fallback).
* Perf regression guard: the compiled scan step's primitive count is
  budgeted (tests/budgets/scan_jaxpr_budget.json) so kernel-count
  regressions are caught on CPU CI without a TPU.
"""

import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cruise_control_tpu.analyzer import tpu_optimizer as T
from cruise_control_tpu.analyzer.context import AnalyzerContext
from cruise_control_tpu.analyzer.tpu_optimizer import (
    TpuGoalOptimizer,
    TpuSearchConfig,
)
from cruise_control_tpu.models.generators import Distribution, random_cluster
from cruise_control_tpu.ops.pools import (
    pool_row_tables,
    pool_row_tables_update,
)

BUDGET_PATH = os.path.join(
    os.path.dirname(__file__), "budgets", "scan_jaxpr_budget.json"
)


def _action_tuples(result):
    return [
        (a.action_type, a.partition, a.slot, a.source_broker,
         a.dest_broker, a.dest_slot)
        for a in result.actions
    ]


def test_pipelined_drive_loop_plan_identity_seeded():
    """Seeded 50b/1k (the driver-bench fixture) with small per-call step
    budgets so the search takes MANY device calls — the regime where the
    pipeline actually consumes speculative results."""
    state = random_cluster(
        seed=42, num_brokers=50, num_racks=10, num_partitions=1000
    )
    base = dict(
        steps_per_call=16, repool_steps=8, device_batch_per_step=16,
        max_rounds=40,
    )
    plans = {}
    for depth in (0, 1, 3):
        cfg = TpuSearchConfig(pipeline_depth=depth, **base)
        res = TpuGoalOptimizer(config=cfg).optimize(state)
        plans[depth] = _action_tuples(res)
    assert plans[1] == plans[0], "depth-1 pipeline must match serial plan"
    assert plans[3] == plans[0], "depth-3 pipeline must match serial plan"
    assert plans[0], "fixture must produce a non-trivial plan"


def test_pipelined_drive_loop_plan_identity_saturated():
    """Count-saturated over-capacity fixture: the run ends in host-side
    swap repair after rejections/hard-goal residue — exactly the paths
    that must discard the speculative tail instead of consuming it."""
    from cruise_control_tpu.analyzer.goals.base import BalancingConstraint
    from cruise_control_tpu.common.resources import Resource
    from cruise_control_tpu.models.builder import ClusterModelBuilder

    b = ClusterModelBuilder()
    cap = {Resource.CPU: 1e9, Resource.NW_IN: 1e9, Resource.NW_OUT: 1e9,
           Resource.DISK: 100.0}
    b0 = b.add_broker("r0", cap)
    b1 = b.add_broker("r1", cap)

    def disk(mb):
        return {Resource.CPU: 0.1, Resource.NW_IN: 0.1,
                Resource.NW_OUT: 0.1, Resource.DISK: mb}

    b.add_partition("T", [b0], disk(60.0))
    b.add_partition("T", [b0], disk(30.0))
    b.add_partition("T", [b1], disk(10.0))
    b.add_partition("T", [b1], disk(5.0))
    state = b.build()
    constraint = BalancingConstraint(max_replicas_per_broker=2)
    base = dict(max_rounds=40, topk_per_round=128, max_moves_per_round=32)
    plans = {}
    for depth in (0, 2):
        cfg = TpuSearchConfig(pipeline_depth=depth, **base)
        res = TpuGoalOptimizer(config=cfg, constraint=constraint).optimize(
            state
        )
        plans[depth] = _action_tuples(res)
    assert plans[2] == plans[0]


def test_incremental_pool_row_tables_bit_identical():
    """After a batch of placement mutations, refreshing only the touched
    rows must reproduce the from-scratch tables bit-for-bit, and the pools
    selected from them must be identical."""
    state = random_cluster(
        seed=17, num_brokers=20, num_racks=5, num_partitions=300,
        distribution=Distribution.EXPONENTIAL,
    )
    opt = TpuGoalOptimizer()
    ctx = AnalyzerContext(state)
    m = opt._device_model(ctx)
    ca = opt._constraint_arrays(ctx)
    size0, base0 = pool_row_tables(m)

    # N applied batches: random replica moves + leadership flips touching
    # a known partition set (table maintenance only cares about placement,
    # not feasibility)
    rng = np.random.default_rng(0)
    P, S = ctx.num_partitions, ctx.max_rf
    touched = np.zeros(P, bool)
    assignment = np.array(m.assignment)
    leader_slot = np.array(m.leader_slot)
    for _ in range(4):  # 4 batches of 12 mutations
        ps = rng.choice(P, size=12, replace=False)
        for p in ps:
            s = int(rng.integers(0, S))
            if rng.random() < 0.5 and assignment[p, s] >= 0:
                assignment[p, s] = int(rng.integers(0, ctx.num_brokers))
            occupied = np.nonzero(assignment[p] >= 0)[0]
            if occupied.size:
                leader_slot[p] = int(rng.choice(occupied))
            touched[p] = True
    m2 = dataclasses.replace(
        m,
        assignment=jnp.asarray(assignment),
        leader_slot=jnp.asarray(leader_slot),
    )

    full_size, full_base = pool_row_tables(m2)
    incr_size, incr_base = pool_row_tables_update(
        m2, size0, base0, jnp.asarray(touched), rows_budget=64
    )
    assert np.array_equal(np.asarray(incr_size), np.asarray(full_size))
    assert np.array_equal(np.asarray(incr_base), np.asarray(full_base))

    K, D = opt._pool_sizes(P, S, ctx.num_brokers)
    m2 = T._recompute_aggregates(m2)
    ref = T._build_round_pools(m2, ca, K, D)
    via_tables = T._build_round_pools(
        m2, ca, K, D, tables=(incr_size, incr_base)
    )
    for a, b in zip(ref, via_tables):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_incremental_repool_scan_equivalence():
    """The device scan with the diet ON (small row budget, so both the
    incremental path and the breach fallback execute) commits the same
    actions as the diet OFF — the packed results' action columns and
    convergence meta are identical."""
    state = random_cluster(
        seed=3, num_brokers=20, num_racks=5, num_partitions=300,
        distribution=Distribution.EXPONENTIAL, mean_utilization=0.4,
    )
    opt = TpuGoalOptimizer()
    ctx = AnalyzerContext(state)
    m = opt._device_model(ctx)
    ca = {
        k: jnp.asarray(v) for k, v in opt._constraint_arrays_np(ctx).items()
    }
    K, D = opt._pool_sizes(ctx.num_partitions, ctx.max_rf, ctx.num_brokers)
    # device_batch_per_step must exceed the per-step commit rate or the
    # slot budget (repool window x batch cap) ends every call exactly at
    # one window and the in-call incremental rebuild never runs
    base = dict(steps_per_call=32, repool_steps=4, device_batch_per_step=32)
    packs = {}
    diags = {}
    # budgets must be < P or the diet is statically compiled out; 128
    # covers every 4-step window's touched set (<= 32 partitions), 24
    # forces breach fallbacks
    for incr, budget in ((False, 8192), (True, 24), (True, 128)):
        cfg = TpuSearchConfig(
            repool_incremental=incr, repool_rows_budget=budget, **base
        )
        scan_fn = T._cached_scan_fn(cfg, K, D, cfg.steps_per_call, None)
        # donate_carry consumes the input model — fresh (bit-identical)
        # upload per variant so every variant starts from the same state
        packed, _, _tab = scan_fn(
            opt._device_model(ctx), ca, np.int32(cfg.steps_per_call))
        arr = np.asarray(packed)
        res = T._fetch_scan_result(packed, cfg.steps_per_call)
        packs[(incr, budget)] = arr
        diags[(incr, budget)] = res[-1]
    T_ = base["steps_per_call"]
    slots = packs[(False, 8192)].shape[1] - (T_ + 2)
    for key in ((True, 24), (True, 128)):
        ref, got = packs[(False, 8192)], packs[key]
        # action columns + counts/total/done meta must match exactly; the
        # row-3 tail cell is the incremental-rebuild count and may differ
        assert np.array_equal(ref[:, :slots], got[:, :slots]), key
        assert np.array_equal(ref[0, slots:], got[0, slots:]), key
    # the tiny budget (24 rows against ~60-80 touched partitions per
    # 4-step window) must exercise BOTH regimes; the 128-row budget stays
    # incremental
    assert diags[(True, 128)]["n_incremental_repool"] > 0
    roomy = diags[(True, 128)]["n_incremental_repool"]
    tight = diags[(True, 24)]["n_incremental_repool"]
    assert tight <= roomy, "breach must fall back to full rebuilds"


def test_engine_plan_identity_with_pool_diet():
    """End-to-end: diet on vs off produces identical plans through the
    full engine (host recheck, resync, swap repair and all)."""
    state = random_cluster(
        seed=5, num_brokers=12, num_racks=4, num_partitions=120,
        dead_brokers=2,
    )
    base = dict(
        max_rounds=40, topk_per_round=128, max_moves_per_round=32,
        steps_per_call=32, repool_steps=4, device_batch_per_step=8,
    )
    on = TpuGoalOptimizer(
        config=TpuSearchConfig(repool_incremental=True,
                               repool_rows_budget=16, **base)
    ).optimize(state)
    off = TpuGoalOptimizer(
        config=TpuSearchConfig(repool_incremental=False, **base)
    ).optimize(state)
    assert _action_tuples(on) == _action_tuples(off)


# ---------------------------------------------------------------------------------
# Perf regression guard: jaxpr primitive budget of the scan step
# ---------------------------------------------------------------------------------

def _count_primitives(jaxpr) -> dict:
    """Recursive primitive census of a (Closed)Jaxpr, descending into
    control-flow/pjit sub-jaxprs."""
    core = jax.core
    counts: dict = {}

    def walk(j):
        j = getattr(j, "jaxpr", j)
        for eqn in j.eqns:
            counts[eqn.primitive.name] = counts.get(eqn.primitive.name, 0) + 1
            for v in eqn.params.values():
                for sub in (v if isinstance(v, (list, tuple)) else (v,)):
                    if isinstance(sub, (core.Jaxpr, core.ClosedJaxpr)):
                        walk(sub)

    walk(jaxpr)
    return counts


#: the fixed shape the budget is taken at — tiny (trace cost only), but
#: the program structure (while/cond bodies, incremental-repool branch)
#: is shape-independent
_BUDGET_CFG = dict(
    steps_per_call=4, repool_steps=2, device_batch_per_step=4,
    max_source_replicas=64, max_dest_brokers=8, repool_rows_budget=16,
)


def _scan_jaxpr_counts() -> dict:
    state = random_cluster(seed=7, num_brokers=8, num_racks=4,
                           num_partitions=40)
    cfg = TpuSearchConfig(**_BUDGET_CFG)
    opt = TpuGoalOptimizer(config=cfg)
    ctx = AnalyzerContext(state)
    m = opt._device_model(ctx)
    ca = {
        k: jnp.asarray(v) for k, v in opt._constraint_arrays_np(ctx).items()
    }
    K, D = opt._pool_sizes(ctx.num_partitions, ctx.max_rf, ctx.num_brokers)
    scan_fn = T._cached_scan_fn(cfg, K, D, cfg.steps_per_call, None)
    jaxpr = jax.make_jaxpr(
        lambda mm, cc, tc: scan_fn(mm, cc, tc)
    )(m, ca, jnp.int32(cfg.steps_per_call))
    return _count_primitives(jaxpr)


def write_budget() -> None:
    """Regenerate the checked-in budget (run on an INTENDED program
    change): ``python -c "import tests.test_drive_loop as t;
    t.write_budget()"`` from the repo root."""
    counts = _scan_jaxpr_counts()
    os.makedirs(os.path.dirname(BUDGET_PATH), exist_ok=True)
    with open(BUDGET_PATH, "w") as f:
        json.dump(
            {"total": sum(counts.values()), "by_primitive": counts},
            f, indent=1, sort_keys=True,
        )
        f.write("\n")


def test_scan_step_primitive_budget():
    """The scan program's primitive count must not grow more than 10%
    over the checked-in budget — the CPU-CI proxy for the kernel-count
    regressions KERNEL_BUDGET_r04.md tracks on the TPU.  On an intended
    program change, regenerate with :func:`write_budget`."""
    assert os.path.exists(BUDGET_PATH), (
        f"missing {BUDGET_PATH} — generate it with the command in this "
        "test's docstring"
    )
    with open(BUDGET_PATH) as f:
        budget = json.load(f)
    counts = _scan_jaxpr_counts()
    total = sum(counts.values())
    ceiling = int(budget["total"] * 1.10)
    if total > ceiling:
        grown = {
            k: (v, budget["by_primitive"].get(k, 0))
            for k, v in sorted(counts.items())
            if v > budget["by_primitive"].get(k, 0)
        }
        pytest.fail(
            f"scan program grew to {total} primitives "
            f"(budget {budget['total']}, +10% ceiling {ceiling}); "
            f"grown primitives (now, budget): {grown}"
        )
