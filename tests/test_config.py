"""Config registry tests (upstream KafkaCruiseControlConfig semantics)."""

import pytest

from cruise_control_tpu.config.cruise_control_config import (
    ConfigException,
    CruiseControlConfig,
    resolve_class,
)


def test_defaults_materialize():
    cfg = CruiseControlConfig()
    assert cfg.get_int("num.partition.metrics.windows") == 5
    assert cfg.get_double("cpu.capacity.threshold") == 0.7
    assert cfg.get_boolean("use.tpu.optimizer") is True
    goals = cfg.get_list("default.goals")
    assert goals[0] == "RackAwareGoal" and len(goals) == 15


def test_type_coercion_from_strings():
    cfg = CruiseControlConfig({
        "webserver.http.port": "8080",
        "self.healing.enabled": "true",
        "cpu.balance.threshold": "1.25",
        "hard.goals": "RackAwareGoal, DiskCapacityGoal",
    })
    assert cfg.get_int("webserver.http.port") == 8080
    assert cfg.get_boolean("self.healing.enabled") is True
    assert cfg.get_double("cpu.balance.threshold") == 1.25
    assert cfg.get_list("hard.goals") == ["RackAwareGoal", "DiskCapacityGoal"]


def test_unknown_key_rejected():
    with pytest.raises(ConfigException, match="unknown config keys"):
        CruiseControlConfig({"no.such.key": 1})


def test_validator_rejects_out_of_range():
    with pytest.raises(ConfigException, match="must be"):
        CruiseControlConfig({"cpu.capacity.threshold": 1.5})
    with pytest.raises(ConfigException, match="must be"):
        CruiseControlConfig({"num.partition.metrics.windows": 0})


def test_pluggable_class_instantiation():
    cfg = CruiseControlConfig()
    from cruise_control_tpu.monitor.sample_store import NoopSampleStore
    cfg2 = CruiseControlConfig({
        "sample.store.class":
            "cruise_control_tpu.monitor.sample_store.NoopSampleStore",
    })
    assert isinstance(cfg2.get_configured_instance("sample.store.class"),
                      NoopSampleStore)
    # goal short-names resolve through the goal registry
    from cruise_control_tpu.analyzer.goals.rack import RackAwareGoal
    assert resolve_class("RackAwareGoal") is RackAwareGoal


def test_bad_class_path_raises():
    with pytest.raises(ConfigException, match="cannot resolve"):
        resolve_class("no.such.module.Klass")
