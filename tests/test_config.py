"""Config registry tests (upstream KafkaCruiseControlConfig semantics)."""

import pytest

from cruise_control_tpu.config.cruise_control_config import (
    DEFAULT_CONFIG_DEF,
    ConfigException,
    CruiseControlConfig,
    resolve_class,
)


def test_defaults_materialize():
    cfg = CruiseControlConfig()
    assert cfg.get_int("num.partition.metrics.windows") == 5
    assert cfg.get_double("cpu.capacity.threshold") == 0.7
    assert cfg.get_boolean("use.tpu.optimizer") is True
    goals = cfg.get_list("default.goals")
    assert goals[0] == "RackAwareGoal" and len(goals) == 15


def test_type_coercion_from_strings():
    cfg = CruiseControlConfig({
        "webserver.http.port": "8080",
        "self.healing.enabled": "true",
        "cpu.balance.threshold": "1.25",
        "hard.goals": "RackAwareGoal, DiskCapacityGoal",
    })
    assert cfg.get_int("webserver.http.port") == 8080
    assert cfg.get_boolean("self.healing.enabled") is True
    assert cfg.get_double("cpu.balance.threshold") == 1.25
    assert cfg.get_list("hard.goals") == ["RackAwareGoal", "DiskCapacityGoal"]


def test_unknown_key_rejected():
    with pytest.raises(ConfigException, match="unknown config keys"):
        CruiseControlConfig({"no.such.key": 1})


def test_validator_rejects_out_of_range():
    with pytest.raises(ConfigException, match="must be"):
        CruiseControlConfig({"cpu.capacity.threshold": 1.5})
    with pytest.raises(ConfigException, match="must be"):
        CruiseControlConfig({"num.partition.metrics.windows": 0})


def test_pluggable_class_instantiation():
    cfg = CruiseControlConfig()
    from cruise_control_tpu.monitor.sample_store import NoopSampleStore
    cfg2 = CruiseControlConfig({
        "sample.store.class":
            "cruise_control_tpu.monitor.sample_store.NoopSampleStore",
    })
    assert isinstance(cfg2.get_configured_instance("sample.store.class"),
                      NoopSampleStore)
    # goal short-names resolve through the goal registry
    from cruise_control_tpu.analyzer.goals.rack import RackAwareGoal
    assert resolve_class("RackAwareGoal") is RackAwareGoal


def test_bad_class_path_raises():
    with pytest.raises(ConfigException, match="cannot resolve"):
        resolve_class("no.such.module.Klass")


def test_config_surface_size():
    """VERDICT round-1 item #3's floor: the key surface covers every
    subsystem's tunables (upstream has ~300 keys; ours is >= 150 with every
    key consumed by a constructor)."""
    assert len(DEFAULT_CONFIG_DEF.keys()) >= 150


def test_boot_from_properties_overriding_each_subsystem(tmp_path):
    """Boot the whole server from a properties file that overrides one key
    per subsystem and verify each override lands on the built component
    (the VERDICT done-bar for the config item)."""
    from cruise_control_tpu.bootstrap import build_app, load_properties
    from cruise_control_tpu.detector.anomalies import AnomalyType

    props_file = tmp_path / "cc.properties"
    props_file.write_text("\n".join([
        "# one override per subsystem",
        "num.partition.metrics.windows=7",                    # monitor
        "capacity.estimation.percentile=90",                  # monitor (model)
        "cpu.balance.threshold=1.33",                         # analyzer
        "max.replicas.per.broker=5000",                       # analyzer
        "default.goals=RackAwareGoal,DiskCapacityGoal,ReplicaCapacityGoal",
        "hard.goals=RackAwareGoal",
        "tpu.search.max.rounds=99",                           # tpu engine
        "tpu.search.time.budget.s=12.5",
        "num.concurrent.partition.movements.per.broker=9",    # executor
        "concurrency.adjuster.enabled=true",
        "default.replica.movement.strategies="
        "cruise_control_tpu.executor.tasks.PrioritizeLargeReplicaMovementStrategy,"
        "cruise_control_tpu.executor.tasks.PostponeUrpReplicaMovementStrategy",
        "anomaly.detection.interval.ms=123000",               # detector
        "goal.violation.detection.interval.ms=60000",
        "self.healing.enabled=true",
        "self.healing.metric.anomaly.enabled=false",
        "metric.anomaly.percentile.upper.threshold=80",
        "self.healing.goals=RackAwareGoal,DiskCapacityGoal",
        "max.active.user.tasks=3",                            # user tasks
        "user.task.executor.threads=2",
        "max.cached.completed.user.tasks=11",
        "webserver.api.urlprefix=/cc",                        # webserver
        "webserver.http.cors.enabled=true",
        "webserver.http.cors.origin=https://ops.example",
        "two.step.purgatory.retention.time.ms=60000",
        "topics.excluded.from.partition.movement=topic_0",
        "simulation.num.brokers=6",                           # simulation
        "simulation.num.partitions=24",
    ]))
    app = build_app(CruiseControlConfig(load_properties(str(props_file))),
                    port=0)
    try:
        # monitor
        assert app.cruise_control.load_monitor.partition_aggregator.num_windows == 7
        assert app.cruise_control.load_monitor.capacity_estimation_percentile == 90
        # analyzer constraint
        from cruise_control_tpu.common.resources import Resource
        c = app.cruise_control.constraint
        assert c.balance_threshold[Resource.CPU] == 1.33
        assert c.max_replicas_per_broker == 5000
        # goal stacks: greedy default stack + hardness override
        engine = app.cruise_control._make_engine("greedy")
        assert [g.name for g in engine.goals] == [
            "RackAwareGoal", "DiskCapacityGoal", "ReplicaCapacityGoal"]
        hardness = {g.name: g.is_hard for g in engine.goals}
        assert hardness == {"RackAwareGoal": True, "DiskCapacityGoal": False,
                            "ReplicaCapacityGoal": False}
        # tpu engine config
        tc = app.cruise_control.tpu_config
        assert tc.max_rounds == 99 and tc.time_budget_s == 12.5
        # executor
        ec = app.cruise_control.executor.config
        assert ec.num_concurrent_partition_movements_per_broker == 9
        assert ec.concurrency_adjuster_enabled is True
        st = app.cruise_control.executor.default_strategy
        assert st.name == ("PrioritizeLargeReplicaMovementStrategy"
                           "+PostponeUrpReplicaMovementStrategy")
        # detector
        dm = app.detector_manager
        assert dm.detection_interval_ms == 123000
        assert dm.per_type_interval_ms[AnomalyType.GOAL_VIOLATION] == 60000
        enabled = dm.notifier.self_healing_enabled()
        assert enabled[AnomalyType.BROKER_FAILURE] is True
        assert enabled[AnomalyType.METRIC_ANOMALY] is False
        gv = dm.detectors[AnomalyType.GOAL_VIOLATION]
        assert gv.fix_goal_names == ["RackAwareGoal", "DiskCapacityGoal"]
        mf = dm.detectors[AnomalyType.METRIC_ANOMALY].finder
        assert mf.upper_percentile == 80
        # user tasks
        tasks = app.server.tasks
        assert tasks.max_active_tasks == 3
        assert tasks.max_cached_completed == 11
        # webserver
        assert app.server.prefix == "/cc"
        assert app.server.cors_enabled and \
            app.server.cors_origin == "https://ops.example"
        assert app.server.purgatory.retention_s == 60.0
        # facade topic exclusion regex resolves per model
        app.reporter.report(time_ms=500)
        app.cruise_control.load_monitor.run_sampling_iteration(3_600_000)
        from cruise_control_tpu.analyzer.context import OptimizationOptions
        with app.cruise_control.load_monitor.acquire_for_model_generation():
            state = app.cruise_control.load_monitor.cluster_model()
        opts = OptimizationOptions()
        app.cruise_control._resolved_constraint(state, opts)
        assert opts.excluded_topics == {
            i for i, n in enumerate(state.topic_names) if n == "topic_0"}
        # simulation
        assert len(app.backend.alive_brokers()) == 6
    finally:
        app.shutdown()
