"""Logging coverage (VERDICT round-1 item #7): a rebalance — and a failed
one — must be diagnosable from logs alone."""

import logging

import pytest

from cruise_control_tpu.analyzer.goals.base import OptimizationFailure
from cruise_control_tpu.models.generators import random_cluster
from cruise_control_tpu.utils.logging import ROOT, configure, get_logger


def test_configure_writes_file(tmp_path):
    log_file = tmp_path / "cc.log"
    configure("DEBUG", str(log_file))
    try:
        get_logger("engine").debug("hello from the engine")
        for h in logging.getLogger(ROOT).handlers:
            h.flush()
        text = log_file.read_text()
        assert "hello from the engine" in text
        assert "cruise_control_tpu.engine" in text
    finally:
        configure("WARNING", None)


def test_rebalance_and_failure_are_diagnosable_from_logs(tmp_path, caplog):
    from cruise_control_tpu.analyzer.tpu_optimizer import (
        TpuGoalOptimizer,
        TpuSearchConfig,
    )

    # undo any configure() from other tests: caplog needs propagation
    root = logging.getLogger(ROOT)
    for h in list(root.handlers):
        root.removeHandler(h)
    root.propagate = True

    cfg = TpuSearchConfig(max_rounds=30, topk_per_round=64,
                          max_moves_per_round=16)
    state = random_cluster(seed=3, num_brokers=12, num_racks=4,
                           num_partitions=100, mean_utilization=0.4)
    with caplog.at_level(logging.DEBUG, logger=ROOT):
        TpuGoalOptimizer(config=cfg).optimize(state)
    text = caplog.text
    assert "resident search" in text          # engine round summary
    assert "TPU search done" in text          # final summary with counts

    # a failing optimization leaves an ERROR trail naming the hard goal
    caplog.clear()
    from cruise_control_tpu.common.resources import Resource
    from cruise_control_tpu.models.builder import ClusterModelBuilder

    b = ClusterModelBuilder()
    cap = {r: 1e9 for r in Resource}
    b.add_broker("r0", cap)
    b.add_broker("r0", cap)
    b.add_partition("T", [0, 1], {Resource.DISK: 1.0})  # same rack, RF 2
    with caplog.at_level(logging.DEBUG, logger=ROOT):
        with pytest.raises(OptimizationFailure):
            TpuGoalOptimizer(config=cfg).optimize(b.build())
    assert any(
        r.levelno >= logging.ERROR and "RackAwareGoal" in r.getMessage()
        for r in caplog.records
    )
