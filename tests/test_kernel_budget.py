"""Kernel observatory (ISSUE 14): trace parsing, bucket accounting,
capture lifecycle, budget regression gate, and the end-to-end
arm → scan → poll loop through the real HTTP server.

The parser tests run on SYNTHETIC traces (both profiler dialects, crafted
byte-for-byte) so the self-time / region-nesting / per-device semantics
are pinned independently of what this box's profiler happens to emit; the
live tests capture the REAL scan program at the same tiny fixture
``test_drive_loop`` budgets (one shared compile per session) and pin the
reconciliation invariant — bucket self-times partition device busy time —
plus the per-bucket kernel-count budget
(``tests/budgets/kernel_budget.json``, ``write_budget()`` regenerator).
"""

import dataclasses
import gzip
import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax.numpy as jnp  # noqa: F401  (jax initialized before optimizer)

import cruise_control_tpu.analyzer.tpu_optimizer as T
from cruise_control_tpu.models.generators import random_cluster
from cruise_control_tpu.telemetry import events
from cruise_control_tpu.telemetry import kernel_budget as kb
from cruise_control_tpu.telemetry.events import EventJournal
from harness import full_stack
from test_artifact_schemas import SCHEMAS, validate

BUDGET_PATH = os.path.join(
    os.path.dirname(__file__), "budgets", "kernel_budget.json"
)

#: the same knobs test_drive_loop's jaxpr budget pins — ONE compiled scan
#: per test session serves both suites
_CAPTURE_CFG = dict(
    steps_per_call=4, repool_steps=2, device_batch_per_step=4,
    max_source_replicas=64, max_dest_brokers=8, repool_rows_budget=16,
)
_FIXTURE = dict(seed=7, num_brokers=8, num_racks=4, num_partitions=40)
_CAPTURE_SCANS = 2


# ---- synthetic traces ------------------------------------------------------------
def _write_trace(tmp_path, events_list):
    d = tmp_path / "plugins" / "profile" / "run1"
    d.mkdir(parents=True)
    path = d / "host.trace.json.gz"
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": events_list}, f)
    return str(tmp_path)


def _device_meta(pid, name):
    return {"ph": "M", "pid": pid, "name": "process_name",
            "args": {"name": name}}


def test_device_dialect_self_time_bytes_and_shard_split(tmp_path):
    """TPU-dialect semantics, pinned: a while region's interval covers its
    body kernel on the same thread — self time subtracts the child, bytes
    count leaves only — and per-device busy sums per ``/device:`` pid,
    giving the skew ratio."""
    def dev_event(pid, name, cat, ts, dur, dur_ps, byts):
        return {"ph": "X", "pid": pid, "tid": 1, "name": name,
                "ts": ts, "dur": dur,
                "args": {"hlo_category": cat,
                         "device_duration_ps": dur_ps,
                         "bytes_accessed": byts}}

    trace_dir = _write_trace(tmp_path, [
        _device_meta(7, "/device:TPU:0"),
        _device_meta(8, "/device:TPU:1"),
        # device 0: a 100us while whose 60us body kernel nests inside it;
        # the region re-aggregates its body's bytes (leaf-only counting)
        dev_event(7, "while.9", "while", 0, 100, 100e6, 640),
        dev_event(7, "fusion.1", "fusion", 10, 60, 60e6, 640),
        # device 1: one flat 30us kernel
        dev_event(8, "fusion.2", "fusion", 0, 30, 30e6, 320),
    ])
    parsed = kb.parse_trace(kb.newest_trace(trace_dir))
    assert parsed.dialect == "device"
    rows = {r.name: r for r in parsed.rows}
    assert rows["while.9"].time_us == pytest.approx(40.0)   # 100 - 60
    assert rows["fusion.1"].time_us == pytest.approx(60.0)
    assert rows["while.9"].bytes == 0                       # region: leaf-only
    assert parsed.total_bytes == 960
    assert parsed.total_time_us == pytest.approx(130.0)
    assert parsed.device_busy_us == pytest.approx(
        {"/device:TPU:0": 100.0, "/device:TPU:1": 30.0})
    # skew: max 100 / mean 65
    assert parsed.skew() == pytest.approx(100.0 / 65.0)
    # bucket semantics: body kernel inside ONE while = step body
    assert rows["while.9"].bucket == "scan_loop"
    assert rows["fusion.1"].bucket == "long_tail"


def test_thunk_dialect_lanes_and_buckets(tmp_path):
    """XLA:CPU dialect: thunk events carry ``hlo_op`` and wall ``dur``;
    nested whiles bucket as auction rounds, conditionals as pool rebuild,
    and per-device lanes come from the PJRT client threads'
    ThunkExecutor::Execute walls."""
    def thunk(name, ts, dur, tid=5):
        return {"ph": "X", "pid": 1, "tid": tid, "name": name,
                "ts": ts, "dur": dur,
                "args": {"hlo_module": "jit_run", "hlo_op": name}}

    trace_dir = _write_trace(tmp_path, [
        {"ph": "M", "pid": 1, "tid": 21, "name": "thread_name",
         "args": {"name": "tf_XLATfrtCpuClient/21"}},
        {"ph": "M", "pid": 1, "tid": 22, "name": "thread_name",
         "args": {"name": "tf_XLATfrtCpuClient/22"}},
        # outer scan while [0, 400) > inner auction while [50, 150) >
        # body scatter [60, 80); plus a conditional region with a gather
        thunk("while.1", 0, 400),
        thunk("while.2", 50, 100),
        thunk("add.3", 60, 20),
        thunk("conditional.4", 200, 80),
        thunk("bitcast_gather_fusion.5", 210, 40),
        thunk("sort.6", 300, 30),
        thunk("maximum_gather_fusion.7", 340, 20),
        # per-device lanes: two client threads, skewed 3:1
        {"ph": "X", "pid": 1, "tid": 21, "ts": 0, "dur": 300,
         "name": "ThunkExecutor::Execute (wait for completion)"},
        {"ph": "X", "pid": 1, "tid": 22, "ts": 0, "dur": 100,
         "name": "ThunkExecutor::Execute (wait for completion)"},
    ])
    parsed = kb.parse_trace(kb.newest_trace(trace_dir))
    assert parsed.dialect == "host-thunk"
    rows = {r.name: r for r in parsed.rows}
    # NAME-ONLY buckets on this dialect (deterministic under the thunk
    # executor's scheduling; the auction split needs the device dialect)
    assert rows["while.2"].bucket == "scan_loop"
    assert rows["add.3"].bucket == "long_tail"
    assert rows["conditional.4"].bucket == "pool_rebuild"
    assert rows["bitcast_gather_fusion.5"].bucket == "move_vec_build"
    assert rows["sort.6"].bucket == "grid_topk"
    assert rows["maximum_gather_fusion.7"].bucket == "move_vec_build"
    assert rows["while.1"].bucket == "scan_loop"
    # self time: outer while 400 - (100 + 80 + 30 + 20) = 170
    assert rows["while.1"].time_us == pytest.approx(170.0)
    assert parsed.device_busy_us == pytest.approx(
        {"cpu-lane-0": 300.0, "cpu-lane-1": 100.0})
    assert parsed.skew() == pytest.approx(1.5)
    # the artifact's buckets partition total busy exactly
    art = kb.build_artifact(parsed, units=1, backend="cpu")
    bucket_sum = sum(v["us_per_unit"] for v in art["by_bucket"].values())
    assert bucket_sum == pytest.approx(
        art["per_unit"]["device_busy_ms"] * 1e3, rel=1e-6, abs=0.05)
    validate(json.loads(json.dumps(art)), SCHEMAS["cc-tpu-kernel-budget/2"])


def test_classify_bucket_vocabulary_is_closed():
    cases = [
        ("fusion.1", "fusion", ("while", "while"), "auction"),
        ("while.2", "while", ("while",), "auction"),
        ("while.0", "while", (), "scan_loop"),
        ("anything", "fusion", ("conditional",), "pool_rebuild"),
        ("sort.3", "sort", ("while",), "grid_topk"),
        ("top_k_fusion", "fusion", (), "grid_topk"),
        ("reduce-window.2", "reduce-window", ("while",), "grid_topk"),
        ("concatenate_gather_fusion", "fusion", ("while",),
         "move_vec_build"),
        ("add.9", "add", ("while",), "long_tail"),
    ]
    for name, cat, enclosing, expected in cases:
        assert kb.classify_bucket(name, cat, enclosing) == expected, \
            (name, cat, enclosing)
    assert {b for *_x, b in cases} <= set(kb.BUCKETS)


# ---- live capture on the real scan program ---------------------------------------
_LIVE = {}


def _live_capture():
    """Arm → optimize → parse ONCE per session on the pinned tiny
    fixture; every live test reads the same artifact + journal."""
    if _LIVE:
        return _LIVE
    journal = EventJournal(enabled=True)
    prev = events.JOURNAL
    events.JOURNAL = journal
    try:
        kb.CAPTURE.reset()
        state = random_cluster(**_FIXTURE)
        opt = T.TpuGoalOptimizer(
            config=T.TpuSearchConfig(**_CAPTURE_CFG))
        st = kb.arm(scans=_CAPTURE_SCANS, reason="test")
        assert st["state"] == "ARMED"
        result = opt.optimize(state)
        parsed = kb.parse_pending(max_parses=4)
    finally:
        events.JOURNAL = prev
    _LIVE.update(
        artifact=kb.latest(), parsed=parsed, result=result,
        journal=journal.recent(), state=kb.CAPTURE.state(),
    )
    return _LIVE


def test_live_capture_produces_schema_valid_reconciling_artifact():
    live = _live_capture()
    art = live["artifact"]
    assert art is not None and live["parsed"] == 1
    validate(json.loads(json.dumps(art)), SCHEMAS["cc-tpu-kernel-budget/2"])
    assert art["source"] == "live-capture"
    assert art["unit"] == "scan-call"
    assert art["units"] == _CAPTURE_SCANS
    assert art["capture"]["scansTraced"] == _CAPTURE_SCANS
    # nonzero categories: the scan program populates several buckets
    populated = [b for b, v in art["by_bucket"].items()
                 if v["count_per_unit"] > 0]
    assert len(populated) >= 3
    assert art["per_unit"]["device_busy_ms"] > 0
    # THE reconciliation invariant: bucket self-times partition busy
    bucket_ms = sum(v["us_per_unit"]
                    for v in art["by_bucket"].values()) / 1e3
    assert bucket_ms == pytest.approx(
        art["per_unit"]["device_busy_ms"], rel=1e-3)
    # shares sum to 1
    assert sum(v["share_of_busy"]
               for v in art["by_bucket"].values()) == pytest.approx(
        1.0, abs=1e-2)


def test_live_capture_journals_lifecycle_and_exports_families():
    live = _live_capture()
    kinds = {e["kind"]: e for e in live["journal"]}
    start = kinds["profiler.capture.start"]
    end = kinds["profiler.capture.end"]
    assert start["payload"]["scans"] == _CAPTURE_SCANS
    assert start["payload"]["captureId"] == end["payload"]["captureId"]
    assert end["payload"]["scansTraced"] == _CAPTURE_SCANS
    assert end["payload"]["stopReason"] == "scans-complete"
    fams = {f[0] for f in kb.CAPTURE.families()}
    assert {"cc_kernel_busy_ms", "cc_kernel_count", "cc_kernel_bytes",
            "cc_kernel_hbm_utilization_measured"} <= fams
    # host-thunk lanes exist even single-device (dispatch wall per lane)
    assert "cc_shard_busy_ms" in fams
    # and the exposition renders them
    from cruise_control_tpu.telemetry.exposition import render_prometheus
    from cruise_control_tpu.telemetry.tracing import Telemetry
    from cruise_control_tpu.utils.metrics import MetricRegistry

    body = render_prometheus(MetricRegistry(), Telemetry(enabled=True))
    assert 'cc_kernel_busy_ms{category="' in body
    assert "cc_kernel_hbm_utilization_measured" in body


def test_live_capture_merges_into_flight_recorder_artifact():
    live = _live_capture()
    assert live["artifact"] is not None
    from cruise_control_tpu.telemetry.recorder import FlightRecorder
    from cruise_control_tpu.utils.metrics import MetricRegistry

    rec = FlightRecorder(MetricRegistry(), interval_s=60.0, retention=8,
                         kernel_budget_source=kb.CAPTURE.summary)
    art = rec.artifact()
    assert art["kernelBudget"]["latest"]["schema"] == kb.SCHEMA
    validate(json.loads(json.dumps(art)),
             SCHEMAS["cc-tpu-flight-recorder/1"])


# ---- the budget regression gate --------------------------------------------------
def write_budget() -> None:
    """Regenerate the checked-in per-bucket kernel-count budget (run on
    an INTENDED scan-program change): ``JAX_PLATFORMS=cpu python -c
    "import tests.test_kernel_budget as t; t.write_budget()"`` from the
    repo root — the same discipline as ``scan_jaxpr_budget.json``."""
    art = _live_capture()["artifact"]
    budget = {
        "unit": art["unit"],
        "fixture": dict(_FIXTURE, scans=_CAPTURE_SCANS, **_CAPTURE_CFG),
        "backend": art["backend"],
        "tolerance_pct": 10,
        "total_kernels_per_unit": art["per_unit"]["kernels"],
        "by_bucket": {
            b: {"count_per_unit": v["count_per_unit"]}
            for b, v in sorted(art["by_bucket"].items())
        },
    }
    os.makedirs(os.path.dirname(BUDGET_PATH), exist_ok=True)
    with open(BUDGET_PATH, "w") as f:
        json.dump(budget, f, indent=1, sort_keys=True)
        f.write("\n")


def test_kernel_count_budget_gate():
    """Per-bucket kernel counts of the live capture may not grow more
    than 10% over the pinned budget — the CPU-CI regression gate for the
    kernel-storm class KERNEL_BUDGET_r04 tracked by hand (counts are
    deterministic for a fixed program; timings are not pinnable on a
    shared host).  On an intended program change regenerate with
    :func:`write_budget`."""
    assert os.path.exists(BUDGET_PATH), (
        f"missing {BUDGET_PATH} — generate it with the command in "
        "write_budget's docstring"
    )
    with open(BUDGET_PATH) as f:
        budget = json.load(f)
    art = _live_capture()["artifact"]
    violations = kb.compare_budget(art, budget)
    assert not violations, (
        "kernel budget regressed (regenerate via write_budget() ONLY "
        "for an intended program change):\n" + "\n".join(violations)
    )


# ---- compile-cache discipline ----------------------------------------------------
def test_profiler_trace_dir_is_not_a_compile_cache_key(tmp_path):
    """Arming the observatory (or setting the legacy trace dir) must be
    device-free: the scan executable is shared bit-for-bit, so the cfg
    normalization keeps profiler knobs out of the lru key."""
    _live_capture()  # scan compiled + cache populated for this cfg
    before = T._cached_scan_fn.cache_info()
    state = random_cluster(**_FIXTURE)
    cfg = T.TpuSearchConfig(
        **_CAPTURE_CFG, profiler_trace_dir=str(tmp_path / "legacy"))
    opt = T.TpuGoalOptimizer(config=cfg)
    opt.optimize(state)
    after = T._cached_scan_fn.cache_info()
    assert after.currsize == before.currsize, (
        "profiler_trace_dir leaked into the scan compile-cache key — "
        "a capture would recompile the program it is trying to measure"
    )
    # the legacy hook is SUBSUMED: the whole-search trace fed the
    # observatory's parse queue and the dir stays TensorBoard-viewable
    assert kb.CAPTURE.state()["pendingParses"] >= 1
    assert kb.parse_pending(max_parses=4) >= 1
    art = kb.latest()
    assert art["source"] == "legacy-trace-dir"
    assert os.path.exists(kb.newest_trace(str(tmp_path / "legacy")))
    kb.CAPTURE.reset()


# ---- end-to-end through the real server ------------------------------------------
def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=30) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def test_arm_scan_poll_e2e_through_http_server():
    """Acceptance (ISSUE 14): GET /profile/kernels?arm=true → 202, a
    rebalance runs the scan, the (test-pumped) maintenance tick parses,
    and the poll returns a schema-valid cc-tpu-kernel-budget/2 artifact
    with nonzero per-category accounting that reconciles."""
    from cruise_control_tpu.server.http_server import (
        CruiseControlHttpServer,
    )
    from cruise_control_tpu.utils.metrics import MetricRegistry

    kb.CAPTURE.reset()
    cc, backend, reporter = full_stack(engine="tpu",
                                       registry=MetricRegistry())
    server = CruiseControlHttpServer(cc, port=0, access_log=False)
    server.start()
    try:
        status, body = _get(f"{server.url}/profile/kernels")
        assert status == 404  # nothing captured yet
        status, body = _get(f"{server.url}/profile/kernels?arm=true&scans=1")
        assert status == 202
        assert body["capture"]["state"] == "ARMED"
        status, body = _get(f"{server.url}/profile/kernels")
        assert status == 202  # armed, no artifact yet — poll semantics
        # drive one optimization through the front door (the scan calls
        # under it are the traced window)
        req = urllib.request.Request(
            f"{server.url}/rebalance?dryrun=true"
            "&get_response_timeout_s=120",
            method="POST", data=b"",
        )
        with urllib.request.urlopen(req, timeout=150) as resp:
            assert resp.status == 200
        # production pumps this from the SLO tick; tests pump directly
        assert kb.parse_pending(max_parses=4) >= 1
        status, art = _get(f"{server.url}/profile/kernels")
        assert status == 200
        validate(art, SCHEMAS["cc-tpu-kernel-budget/2"])
        assert art["capture"]["reason"] == "http"
        populated = [b for b, v in art["by_bucket"].items()
                     if v["count_per_unit"] > 0]
        assert populated, "capture parsed but saw no kernels"
        bucket_ms = sum(v["us_per_unit"]
                        for v in art["by_bucket"].values()) / 1e3
        assert bucket_ms == pytest.approx(
            art["per_unit"]["device_busy_ms"], rel=1e-3)
    finally:
        server.stop()
        kb.CAPTURE.reset()


def test_profile_kernels_503_when_disabled():
    from cruise_control_tpu.server.http_server import (
        CruiseControlHttpServer,
    )
    from cruise_control_tpu.utils.metrics import MetricRegistry

    cc, _backend, _reporter = full_stack(registry=MetricRegistry())
    server = CruiseControlHttpServer(cc, port=0, access_log=False)
    server.start()
    kb.configure(enabled=False)
    try:
        status, body = _get(f"{server.url}/profile/kernels")
        assert status == 503
        assert "telemetry.kernel.enabled" in body["errorMessage"]
    finally:
        kb.configure(enabled=True)
        server.stop()


# ---- committed sharded artifact --------------------------------------------------
def test_committed_r14_artifact_carries_shard_split():
    """The committed KERNEL_BUDGET_r14 refresh (generated via the new
    shared parser, ``--devices 8`` CPU mesh) is schema-valid, names its
    backend so r04 (v5e) comparisons stay honest, and carries the
    per-device busy split + shard-skew number ROADMAP item 1 needs."""
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmarks", "KERNEL_BUDGET_r14.json")
    with open(path) as f:
        art = json.load(f)
    validate(art, SCHEMAS["cc-tpu-kernel-budget/2"])
    assert art["unit"] == "step"
    assert art["source"] == "benchmark"
    assert art["backend"] == "cpu"          # NOT comparable to r04's v5e
    assert art["dialect"] == "host-thunk"
    assert art["devices"]["count"] >= 2
    assert len(art["devices"]["busy_ms"]) == art["devices"]["count"]
    assert art["devices"]["skew"] >= 1.0
    assert art["per_unit"]["device_busy_ms"] > 0


# ---- deterministic capture in scenario mode --------------------------------------
@pytest.mark.slow
def test_scenario_kernel_capture_is_fingerprint_stable():
    """A scenario that arms the observatory journals deterministic
    profiler.capture.* records (virtual clock, sim-capture-N ids): two
    runs of the same seed fingerprint bit-identically, with the capture
    present in both journals."""
    from cruise_control_tpu.sim import ScenarioSpec, run_scenario
    from cruise_control_tpu.sim.timeline import Timeline, hot_partition_skew

    def spec():
        return ScenarioSpec(
            name="kernel_capture_probe",
            description="deterministic capture under a warm heal",
            timeline=Timeline([hot_partition_skew(
                2 * 60_000, factor=12.0, partitions=[0, 1, 2, 3])]),
            self_healing={"goal_violation": True},
            engine="tpu",
            kernel_capture_scans=1,
            duration_ms=10 * 60_000,
        )

    a = run_scenario(spec())
    b = run_scenario(spec())
    kinds_a = [e["kind"] for e in a.journal]
    assert "profiler.capture.start" in kinds_a
    assert "profiler.capture.end" in kinds_a
    start = next(e for e in a.journal
                 if e["kind"] == "profiler.capture.start")
    assert start["payload"]["captureId"] == "sim-capture-1"
    assert a.fingerprint() == b.fingerprint()


# ---- /diagnostics deviceCost detail (satellite 2) --------------------------------
def test_device_cost_summary_detail_breaks_out_executables():
    """The diagnostics dump's deviceCost block carries the per-fn
    per-executable (and, where the backend reports it, per-device)
    breakdown, not just the worst-case aggregate."""
    import jax

    from cruise_control_tpu.telemetry.device_cost import DeviceCostMonitor

    mon = DeviceCostMonitor()
    fn = jax.jit(lambda x: (x * 2.0).sum())
    x = np.ones(16, np.float32)
    mon.note_call("probe_fn")
    mon.note_compile("probe_fn", fn, ("f32[16]",), (x,), {})
    assert mon.capture_pending(max_captures=1) == 1
    summary = mon.summary(detail=True)
    entry = summary["functions"]["probe_fn"]
    per = entry["perExecutable"]
    assert len(per) == 1
    assert per[0]["signature"] == repr(("f32[16]",))
    assert per[0]["devices"] >= 1
    assert "bytesAccessed" in per[0]
    # the default (metrics-path) view stays lean
    assert "perExecutable" not in mon.summary()["functions"]["probe_fn"]
