"""Production-wire unit tests (VERDICT round-2 item #3): every RPC
translation of :class:`ConfluentKafkaWire` exercised against a mocked
``confluent_kafka`` injected in ``sys.modules`` (future-based API, real
attribute names), plus the error-mapping contract."""

import pytest

import mock_confluent
from mock_confluent import MockKafkaError

from cruise_control_tpu.kafka.wire import (
    FatalWireError,
    RetriableWireError,
    UnsupportedRpcError,
    WireError,
    WireTimeoutError,
    real_wire,
)

SERVERS = "mock:9092"


@pytest.fixture
def broker():
    b = mock_confluent.install()
    yield b
    mock_confluent.uninstall()


@pytest.fixture
def wire(broker):
    from cruise_control_tpu.kafka.confluent_wire import ConfluentKafkaWire

    return ConfluentKafkaWire(SERVERS, timeout_s=2.0)


def test_real_wire_returns_confluent_wire_when_lib_importable(broker):
    from cruise_control_tpu.kafka.confluent_wire import ConfluentKafkaWire

    w = real_wire(SERVERS)
    assert isinstance(w, ConfluentKafkaWire)


def test_real_wire_raises_without_client_lib():
    with pytest.raises(RuntimeError, match="no Kafka client library"):
        real_wire("srv:9092")


def test_describe_cluster_maps_nodes_and_null_racks(broker, wire):
    assert wire.describe_cluster() == {
        0: {"rack": "r0"}, 1: {"rack": "r1"}, 2: {"rack": ""},
    }


def test_describe_topics_maps_partition_rows(broker, wire):
    broker.add_topic("t", partitions=2, leader=1, replicas=(1, 0))
    rows = wire.describe_topics()["t"]
    assert rows == [
        {"partition": 0, "leader": 1, "replicas": [1, 0], "isr": [1, 0]},
        {"partition": 1, "leader": 1, "replicas": [1, 0], "isr": [1, 0]},
    ]


def test_alter_and_list_partition_reassignments(broker, wire):
    broker.add_topic("t", partitions=2, replicas=(0, 1))
    wire.alter_partition_reassignments({("t", 0): [1, 2], ("t", 1): None})
    rpc, payload = broker.calls[-1]
    assert rpc == "alter_partition_reassignments"
    assert payload == {("t", 0): [1, 2], ("t", 1): None}
    listing = wire.list_partition_reassignments()
    assert listing == {("t", 0): {
        "replicas": [0, 1, 2], "adding": [2], "removing": [0],
    }}
    # cancel drops it from the in-flight listing
    wire.alter_partition_reassignments({("t", 0): None})
    assert wire.list_partition_reassignments() == {}


def test_elect_leaders_preferred_and_election_not_needed(broker, wire):
    broker.add_topic("t", partitions=2, leader=1, replicas=(0, 1))
    wire.elect_leaders([("t", 0)])
    assert broker.calls[-1] == ("elect_leaders", "preferred", [("t", 0)])
    assert broker.topics["t"][0]["leader"] == 0
    # already-preferred → per-partition ELECTION_NOT_NEEDED is success
    wire.elect_leaders([("t", 0)])


def test_config_roundtrip_set_and_delete(broker, wire):
    wire.incremental_alter_configs(
        "broker", "7", {"leader.replication.throttled.rate": "1000"})
    assert wire.describe_configs("broker", "7") == {
        "leader.replication.throttled.rate": "1000"}
    wire.incremental_alter_configs(
        "broker", "7", {"leader.replication.throttled.rate": None})
    assert wire.describe_configs("broker", "7") == {}
    # op types crossed the seam as SET / DELETE
    ops = [c for c in broker.calls if c[0] == "incremental_alter_configs"]
    assert ops[0][3] == [("leader.replication.throttled.rate", "1000", "SET")]
    assert ops[1][3][0][2] == "DELETE"


def test_log_dir_rpcs(broker, wire):
    broker.add_topic("t", partitions=1, replicas=(0, 1))
    broker.log_dirs[0] = {"/d1": {"error": None, "replicas": [("t", 0)]}}
    wire.alter_replica_log_dirs({("t", 0, 0): "/d2"})
    dirs = wire.describe_log_dirs()
    assert dirs[0]["/d2"]["replicas"] == [("t", 0)]
    assert dirs[0]["/d1"]["replicas"] == []
    assert not dirs[0]["/d2"]["offline"]


def test_create_topic_is_idempotent(broker, wire):
    wire.create_topic("logs", replication_factor=2,
                      configs={"cleanup.policy": "compact"})
    assert broker.topic_configs["logs"] == {"cleanup.policy": "compact"}
    wire.create_topic("logs")  # TOPIC_ALREADY_EXISTS swallowed
    creates = [c for c in broker.calls if c[0] == "create_topics"]
    assert len(creates) == 2


def test_produce_consume_roundtrip_with_cursor_resume(broker, wire):
    wire.create_topic("m")
    wire.produce("m", [b"a", b"b"])
    records, nxt = wire.consume("m", 0)
    assert records == [b"a", b"b"] and nxt == 2
    records, nxt2 = wire.consume("m", nxt)
    assert records == [] and nxt2 == 2
    wire.produce("m", [b"c"])
    records, nxt3 = wire.consume("m", nxt2)
    assert records == [b"c"] and nxt3 == 3
    # restart semantics: offset 0 re-reads everything
    records, _ = wire.consume("m", 0)
    assert records == [b"a", b"b", b"c"]


def test_consume_foreign_cursor_skips_prefix(broker, wire):
    """A cursor from a previous process (unknown to this wire) re-reads
    from earliest and drops the first `offset` records."""
    wire.create_topic("m")
    wire.produce("m", [b"a", b"b", b"c"])
    records, nxt = wire.consume("m", 2)
    assert records == [b"c"] and nxt == 3


def test_consume_multi_partition_drains_all(broker, wire):
    broker.add_topic("mp", partitions=3)
    wire.produce("mp", [b"r0", b"r1", b"r2", b"r3", b"r4", b"r5"])
    records, nxt = wire.consume("mp", 0)
    assert sorted(records) == [b"r0", b"r1", b"r2", b"r3", b"r4", b"r5"]
    assert nxt == 6
    wire.produce("mp", [b"r6"])
    records, nxt = wire.consume("mp", nxt)
    assert records == [b"r6"] and nxt == 7


def test_consume_missing_topic_is_empty(broker, wire):
    assert wire.consume("nope", 0) == ([], 0)


# ---- error mapping ---------------------------------------------------------


def test_timeout_code_maps_to_wire_timeout(broker, wire):
    broker.add_topic("t")
    broker.fail_next["alter_partition_reassignments"] = MockKafkaError(
        7, "REQUEST_TIMED_OUT", retriable=True)
    with pytest.raises(WireTimeoutError):
        wire.alter_partition_reassignments({("t", 0): [1, 2]})


def test_retriable_maps_to_retriable(broker, wire):
    broker.fail_next["describe_cluster"] = MockKafkaError(
        9, "REPLICA_NOT_AVAILABLE", retriable=True)
    with pytest.raises(RetriableWireError):
        wire.describe_cluster()


def test_fatal_maps_to_fatal(broker, wire):
    broker.add_topic("t")
    broker.fail_next["elect_leaders"] = MockKafkaError(
        87, "fenced", fatal=True)
    with pytest.raises(FatalWireError):
        wire.elect_leaders([("t", 0)])


def test_unknown_error_maps_to_base_wire_error(broker, wire):
    broker.fail_next["create_topics"] = MockKafkaError(
        29, "TOPIC_AUTHORIZATION_FAILED")
    with pytest.raises(WireError) as ei:
        wire.create_topic("secret")
    assert type(ei.value) is WireError


def test_missing_client_method_raises_unsupported(broker, wire):
    del mock_confluent.MockAdminClient.alter_partition_reassignments
    try:
        with pytest.raises(UnsupportedRpcError, match="KIP"):
            wire.alter_partition_reassignments({("t", 0): [1]})
    finally:
        mock_confluent.MockAdminClient.alter_partition_reassignments = (
            MockAdminClientAlter)


MockAdminClientAlter = mock_confluent.MockAdminClient.alter_partition_reassignments


# ---- adapter stack over the production wire --------------------------------


def test_metrics_reporter_and_sampler_over_production_wire(broker, wire):
    """The reporter twin and the consumer-side sampler run unchanged over
    the production wire (same code path a real cluster would use)."""
    from cruise_control_tpu.kafka.sampler import (
        KafkaMetricsReporter,
        KafkaMetricsReporterSampler,
    )
    from cruise_control_tpu.monitor.sampling import (
        CruiseControlMetric,
        RawMetricType,
    )

    reporter = KafkaMetricsReporter(wire)
    sampler = KafkaMetricsReporterSampler(wire)
    reporter.report([
        CruiseControlMetric(RawMetricType.PARTITION_BYTES_IN, 500, 0, 9.0,
                            partition=3),
        CruiseControlMetric(RawMetricType.PARTITION_SIZE, 500, 0, 70.0,
                            partition=3),
    ])
    psamples, _ = sampler.get_samples(0, 1000)
    assert len(psamples) == 1 and psamples[0].partition == 3
    # incremental: nothing new on the next poll
    assert sampler.get_samples(1000, 2000) == ([], [])


def test_sample_store_over_production_wire(broker, wire):
    from cruise_control_tpu.kafka.sample_store import KafkaSampleStore
    from cruise_control_tpu.monitor.sampling import PartitionMetricSample

    store = KafkaSampleStore(wire, loading_threads=4)
    samples = [PartitionMetricSample(p, 10 * p, (1.0, 2.0, 3.0, 4.0))
               for p in range(5)]
    store.store_samples(samples, [])
    psamples, bsamples = store.load_samples()
    assert psamples == samples and bsamples == []


def test_compacted_topic_requires_keys(broker, wire):
    """Real brokers reject keyless writes to compacted topics; the sample
    store must key its records (code-review round-3 finding)."""
    wire.create_topic("compacted", configs={"cleanup.policy": "compact"})
    with pytest.raises(WireError, match="INVALID_RECORD"):
        wire.produce("compacted", [b"v"])
    wire.produce("compacted", [b"v"], keys=[b"k"])
    assert wire.consume("compacted", 0)[0] == [b"v"]


def test_concurrent_samplers_resume_independent_cursors(broker, wire):
    """Snapshot-keyed cursors: two independent consumers of one topic each
    resume exactly from the cursor they were handed."""
    wire.create_topic("m")
    wire.produce("m", [b"a", b"b"])
    _, c1 = wire.consume("m", 0)     # consumer 1 caught up at 2
    wire.produce("m", [b"c"])
    _, c2 = wire.consume("m", 0)     # consumer 2 catches up at 3
    assert (c1, c2) == (2, 3)
    wire.produce("m", [b"d"])
    r1, _ = wire.consume("m", c1)    # consumer 1 resumes its own snapshot
    r2, _ = wire.consume("m", c2)
    assert r1 == [b"c", b"d"]
    assert r2 == [b"d"]


def test_cursor_collision_merges_conservatively(broker, wire):
    """Two consumers can land on the SAME virtual offset with DIFFERENT
    per-partition positions (a produce racing the drains on a
    multi-partition topic).  The snapshot store must not let the later
    insert silently clobber the earlier one: on collision the positions
    merge per-partition-minimum, so the worst outcome is a re-read
    (records carry timestamps), never a skip."""
    broker.add_topic("mp", partitions=2)
    wire.produce("mp", [b"a", b"b", b"c", b"d"])  # keyless: 2 per partition
    _, nxt = wire.consume("mp", 0)
    assert nxt == 4
    assert wire._cursors[("mp", 4)] == {0: 2, 1: 2}
    # simulate the racing consumer's snapshot already stored at virtual 4:
    # it had read 1 from p0 and 3 from p1
    wire._cursors[("mp", 4)] = {0: 1, 1: 3}
    # a foreign-cursor consume that also lands at virtual 4 collides with it
    records, nxt2 = wire.consume("mp", 1)
    assert nxt2 == 4 and len(records) == 3
    # merged per-partition minimum: neither consumer's unread data is lost
    assert wire._cursors[("mp", 4)] == {0: 1, 1: 2}
    # resuming from the merged snapshot via a PLAIN int re-reads p0's
    # record rather than skipping it — and the returned cursor does NOT
    # inflate past the count of records ever produced (4), or a later
    # restart's count-based skip would drop live records
    records, nxt3 = wire.consume("mp", 4)
    assert len(records) == 1 and nxt3 == 4
    # the returned cursor carries this consumer's exact positions, so its
    # own resume is exact (no repeat of the conservative re-read)
    assert nxt3.starts == {0: 2, 1: 2}
    records, nxt4 = wire.consume("mp", nxt3)
    assert records == [] and nxt4 == 4
    # a partition absent from one colliding snapshot (added after that
    # consumer's drain) merges to 0 — resume re-reads it from earliest —
    # never to the other consumer's position, which would skip records
    wire._cursors[("mp", 4)] = {0: 1}
    records, _ = wire.consume("mp", 1)
    assert wire._cursors[("mp", 4)] == {0: 1, 1: 0}
    # a persisted cursor round-trips with its exact positions (int's
    # default __getnewargs__ would crash VirtualOffset.__new__)
    import copy
    import pickle

    thawed = pickle.loads(pickle.dumps(nxt3))
    assert thawed == 4 and thawed.starts == nxt3.starts
    assert copy.deepcopy(nxt3).starts == nxt3.starts


def test_foreign_cursor_on_trimmed_topic_does_not_double_drop(broker, wire):
    """Restart-with-cursor on a retention-trimmed topic: records the broker
    deleted count toward the cursor, so live records are not skipped."""
    broker.add_topic("m", partitions=1)
    wire.produce("m", [b"a", b"b", b"c", b"d"])
    broker.trim("m", 0, 2)  # retention deleted a, b: earliest offset = 2
    records, nxt = wire.consume("m", 2)
    assert records == [b"c", b"d"] and nxt == 4
    # and a cursor pointing below the trim point skips nothing live
    records, _ = wire.consume("m", 1)
    assert records == [b"c", b"d"]


def test_list_reassignments_degrades_when_client_lacks_rpc(broker, wire):
    """Startup recovery calls list_partition_reassignments unconditionally;
    a client without KIP-455 support must degrade to 'none in flight'
    (warn once), not crash the boot — while an actual MOVE stays loud."""
    saved = mock_confluent.MockAdminClient.list_partition_reassignments
    del mock_confluent.MockAdminClient.list_partition_reassignments
    try:
        assert wire.list_partition_reassignments() == {}
        assert wire.list_partition_reassignments() == {}  # warns once only
    finally:
        mock_confluent.MockAdminClient.list_partition_reassignments = saved


def test_store_topics_are_retention_bounded(broker, wire):
    """Sample-store topics use delete+retention.ms (unique samples would
    defeat compaction — the topics and startup replay must stay bounded)."""
    from cruise_control_tpu.kafka.sample_store import KafkaSampleStore

    KafkaSampleStore(wire, retention_ms=7_200_000)
    cfgs = broker.topic_configs["__KafkaCruiseControlPartitionMetricSamples"]
    assert cfgs["cleanup.policy"] == "delete"
    assert cfgs["retention.ms"] == "7200000"


def test_produce_drains_on_local_queue_full(broker, wire):
    """Batches larger than the client's local queue drain via poll() and
    retry instead of leaking BufferError past the typed hierarchy."""
    broker.produce_buffer_limit = 10
    wire.create_topic("m")
    wire.produce("m", [bytes([i]) for i in range(25)])
    records, _ = wire.consume("m", 0)
    assert len(records) == 25


def test_per_rpc_timeout_overrides_reach_the_client(broker, wire):
    """CONFIG_DELTA §1 closure: the per-RPC *.timeout.ms family — an
    override steers only its RPC class; everything else keeps the
    consolidated default."""
    from cruise_control_tpu.kafka.confluent_wire import ConfluentKafkaWire

    w = ConfluentKafkaWire(
        SERVERS, timeout_s=2.0,
        timeouts={"describe_cluster": 7.0, "logdirs": 9.0},
    )
    captured = {}
    orig = w._admin.describe_cluster

    def recording(request_timeout=None):
        captured["describe_cluster"] = request_timeout
        return orig(request_timeout=request_timeout)

    w._admin.describe_cluster = recording
    w.describe_cluster()
    assert captured["describe_cluster"] == 7.0
    assert w._t("logdirs") == 9.0
    assert w._t("metadata") == 2.0  # un-overridden class: default


def test_unknown_timeout_class_rejected(broker):
    from cruise_control_tpu.kafka.confluent_wire import ConfluentKafkaWire

    with pytest.raises(ValueError, match="unknown RPC timeout class"):
        ConfluentKafkaWire(SERVERS, timeouts={"bogus": 1.0})


def test_rpc_timeouts_from_config_keys(broker):
    """The ConfigDef keys feed the wire: 0 inherits the consolidated
    default, a positive value becomes a per-class override in seconds."""
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.kafka import rpc_timeouts_from_config

    cfg = CruiseControlConfig({
        "logdir.response.timeout.ms": 45000,
        "consume.timeout.ms": 1500,
    })
    assert rpc_timeouts_from_config(cfg) == {
        "logdirs": 45.0, "consume": 1.5,
    }
    w = real_wire(
        SERVERS,
        timeout_s=cfg.get_int("default.api.timeout.ms") / 1000.0,
        timeouts=rpc_timeouts_from_config(cfg),
    )
    assert w._t("logdirs") == 45.0 and w._t("reassignment") == 30.0


def test_timeout_class_registries_agree(broker):
    """RPC_TIMEOUT_KEYS (config side) and TIMEOUT_CLASSES (wire side) are
    two views of the same vocabulary — drift would only surface at
    runtime when a key is first configured."""
    from cruise_control_tpu.kafka import RPC_TIMEOUT_KEYS
    from cruise_control_tpu.kafka.confluent_wire import ConfluentKafkaWire

    assert set(RPC_TIMEOUT_KEYS.values()) == set(
        ConfluentKafkaWire.TIMEOUT_CLASSES)
