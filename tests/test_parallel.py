"""parallel/ mesh utilities: sharded scoring must match unsharded exactly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cruise_control_tpu.parallel import (
    auto_mesh,
    make_mesh,
    pad_axis,
    sharded_columnar_topk,
)


def test_make_mesh_sizes():
    mesh = make_mesh(4)
    assert mesh.shape["search"] == 4
    assert auto_mesh() is not None  # conftest forces 8 CPU devices


def test_pad_axis():
    x = jnp.arange(10)
    assert pad_axis(x, 8).shape[0] == 16
    assert pad_axis(x, 5).shape[0] == 10
    assert int(pad_axis(x, 8, fill=-1)[-1]) == -1


def test_sharded_topk_matches_unsharded():
    mesh = make_mesh(8)
    rng = np.random.default_rng(0)
    vals = jnp.asarray(rng.normal(size=103).astype(np.float32))
    idx = jnp.arange(103, dtype=jnp.int32)
    bias = jnp.float32(2.0)

    def score_pack(bias, vals, idx):
        s = vals + bias
        top, i = jax.lax.top_k(-s, 4)
        return jnp.stack([-top, idx[i].astype(jnp.float32)])

    packed = sharded_columnar_topk(
        mesh,
        score_pack,
        replicated_args=(bias,),
        columnar_args=(vals, idx),
        pad_fills=(np.float32(np.inf), -1),
    )
    assert packed.shape == (2, 8 * 4)
    got = np.asarray(packed)
    # global best of the merged per-device top-ks == true global best
    best = got[1][np.argmin(got[0])]
    want = int(np.argmin(np.asarray(vals) + 2.0))
    assert int(best) == want
