"""Tensor cluster model unit tests (upstream ClusterModelTest's role)."""

import jax.numpy as jnp
import numpy as np
import pytest

from cruise_control_tpu.common.resources import (
    EMPTY_SLOT,
    BrokerState,
    Resource,
)
from cruise_control_tpu.models.builder import ClusterModelBuilder
from cruise_control_tpu.models.cluster_state import (
    apply_leadership,
    apply_move,
    apply_swap,
    broker_leader_count,
    broker_leader_load,
    broker_load,
    broker_potential_nw_out,
    broker_replica_count,
    broker_topic_leader_count,
    broker_topic_replica_count,
    replica_load,
    replica_rack,
    sanity_check,
    set_broker_state,
)
from cruise_control_tpu.models.generators import (
    Distribution,
    random_cluster,
    small_deterministic_cluster,
)
from cruise_control_tpu.models.stats import cluster_stats


@pytest.fixture
def small():
    return small_deterministic_cluster()


def test_builder_shapes(small):
    sanity_check(small)
    assert small.num_partitions == 4
    assert small.num_brokers == 3
    assert small.max_replication_factor == 2
    assert small.num_topics == 2


def test_replica_load_leader_vs_follower(small):
    rl = np.asarray(replica_load(small))
    # partition 0: leader slot 0 serves NW_OUT=10, follower slot 1 serves 0
    assert rl[0, 0, Resource.NW_OUT] == pytest.approx(10.0)
    assert rl[0, 1, Resource.NW_OUT] == pytest.approx(0.0)
    # follower CPU is scaled by the default ratio 0.2
    assert rl[0, 1, Resource.CPU] == pytest.approx(10.0 * 0.2)
    # disk replicated fully
    assert rl[0, 1, Resource.DISK] == pytest.approx(50.0)


def test_broker_load_totals(small):
    # global conservation: sum of broker loads == sum of replica loads
    bl = np.asarray(broker_load(small))
    rl = np.asarray(replica_load(small))
    np.testing.assert_allclose(bl.sum(0), rl.sum((0, 1)), rtol=1e-5)
    # b0 hosts: leader of P0(T1), follower of P2(T2), leader of P3(T2)
    assert np.asarray(broker_replica_count(small)).tolist() == [3, 3, 2]
    assert np.asarray(broker_leader_count(small)).tolist() == [2, 1, 1]


def test_topic_counts(small):
    trc = np.asarray(broker_topic_replica_count(small))
    assert trc.shape == (3, 2)
    # topic T1 (id 0): P0 on (b0,b1), P1 on (b1,b2)
    assert trc[:, 0].tolist() == [1, 2, 1]
    tlc = np.asarray(broker_topic_leader_count(small))
    assert tlc[:, 0].tolist() == [1, 1, 0]


def test_apply_move_conserves_load(small):
    bl0 = np.asarray(broker_load(small))
    # move partition 0 slot 1 (b1) -> b2
    moved = apply_move(small, 0, 1, 2)
    sanity_check(moved)
    bl1 = np.asarray(broker_load(moved))
    np.testing.assert_allclose(bl0.sum(0), bl1.sum(0), rtol=1e-5)
    delta = bl1 - bl0
    fl = np.asarray(small.follower_load[0])
    np.testing.assert_allclose(delta[1], -fl, atol=1e-5)
    np.testing.assert_allclose(delta[2], fl, atol=1e-5)
    np.testing.assert_allclose(delta[0], 0.0, atol=1e-5)


def test_apply_leadership_moves_nw_out(small):
    moved = apply_leadership(small, 0, 1)
    bl = np.asarray(broker_load(moved))
    bl0 = np.asarray(broker_load(small))
    # NW_OUT of partition 0 (10.0) moves from b0 to b1
    assert bl0[0, Resource.NW_OUT] - bl[0, Resource.NW_OUT] == pytest.approx(10.0)
    assert bl[1, Resource.NW_OUT] - bl0[1, Resource.NW_OUT] == pytest.approx(10.0)
    assert np.asarray(broker_leader_count(moved)).tolist() == [1, 2, 1]


def test_apply_swap(small):
    # swap P0 slot1 (b1) with P2 slot0 (b2): P0 -> [b0,b2], P2 -> [b1,b0]
    swapped = apply_swap(small, 0, 1, 2, 0)
    sanity_check(swapped)
    a = np.asarray(swapped.assignment)
    assert a[0, 1] == 2
    assert a[2, 0] == 1


def test_set_broker_state_dead_marks_offline(small):
    dead = set_broker_state(small, 1, BrokerState.DEAD)
    off = np.asarray(dead.replica_offline)
    a = np.asarray(dead.assignment)
    assert (off == (a == 1)).all()
    assert not np.asarray(dead.broker_alive())[1]
    # alive brokers unchanged
    assert np.asarray(dead.broker_alive())[[0, 2]].all()


def test_leader_load_and_potential_nw_out(small):
    ll = np.asarray(broker_leader_load(small))
    assert ll[0, Resource.NW_IN] == pytest.approx(20.0)  # leads P0, P3
    pot = np.asarray(broker_potential_nw_out(small))
    # every broker hosts replicas whose leadership bandwidth is 10 each
    counts = np.asarray(broker_replica_count(small))
    np.testing.assert_allclose(pot, counts * 10.0, rtol=1e-5)


def test_replica_rack(small):
    rr = np.asarray(replica_rack(small))
    assert rr[0].tolist() == [0, 0]  # b0,b1 in rack 0
    assert rr[1].tolist() == [0, 1]


def test_random_cluster_seeded_reproducible():
    a = random_cluster(seed=7, num_brokers=10, num_partitions=100)
    b = random_cluster(seed=7, num_brokers=10, num_partitions=100)
    assert (np.asarray(a.assignment) == np.asarray(b.assignment)).all()
    np.testing.assert_array_equal(
        np.asarray(a.leader_load), np.asarray(b.leader_load)
    )
    sanity_check(a)


@pytest.mark.parametrize(
    "dist", [Distribution.UNIFORM, Distribution.LINEAR, Distribution.EXPONENTIAL]
)
def test_random_cluster_mean_utilization(dist):
    state = random_cluster(
        seed=3, num_brokers=20, num_partitions=500, distribution=dist,
        mean_utilization=0.35,
    )
    bl = np.asarray(broker_load(state))
    cap = np.asarray(state.broker_capacity)
    util = bl.sum(0) / cap.sum(0)
    np.testing.assert_allclose(util, 0.35, rtol=0.1)


def test_random_cluster_dead_brokers_offline():
    state = random_cluster(seed=5, num_brokers=10, num_partitions=50, dead_brokers=2)
    alive = np.asarray(state.broker_alive())
    assert alive.sum() == 8
    off = np.asarray(state.replica_offline)
    a = np.asarray(state.assignment)
    assert (off == np.isin(a, [8, 9])).all()


def test_cluster_stats(small):
    stats = cluster_stats(small)
    bl = np.asarray(broker_load(small))
    np.testing.assert_allclose(
        np.asarray(stats.resource_mean), bl.mean(0), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(stats.resource_std), bl.std(0), rtol=1e-5
    )
    assert int(stats.num_alive_brokers) == 3
    assert float(stats.replica_count_mean) == pytest.approx(8 / 3)


def test_stats_exclude_dead_brokers(small):
    dead = set_broker_state(small, 2, BrokerState.DEAD)
    stats = cluster_stats(dead)
    assert int(stats.num_alive_brokers) == 2
    bl = np.asarray(broker_load(dead))
    np.testing.assert_allclose(
        np.asarray(stats.resource_mean), bl[:2].mean(0), rtol=1e-5
    )


def test_host_level_topology():
    """Upstream rack -> host -> broker (model/Host.java): hosts are
    addressable on the model, and for rackless brokers the host stands in
    as the rack so co-hosted brokers never share a partition's replicas."""
    from cruise_control_tpu.common.resources import Resource
    from cruise_control_tpu.models.builder import ClusterModelBuilder

    cap = {r: 1e6 for r in Resource}
    b = ClusterModelBuilder()
    b.add_broker(None, cap, host="h0")
    b.add_broker(None, cap, host="h0")   # co-hosted with broker 0
    b.add_broker(None, cap, host="h1")
    b.add_partition("T", [0, 2], {Resource.DISK: 1.0})
    state = b.build()
    assert state.broker_host is not None
    hosts = list(np.asarray(state.broker_host))
    assert hosts[0] == hosts[1] != hosts[2]
    # host-as-rack fallback: co-hosted brokers share a rack id
    racks = list(np.asarray(state.broker_rack))
    assert racks[0] == racks[1] != racks[2]

    # rack-aware placement therefore refuses the co-hosted pair
    from cruise_control_tpu.analyzer.context import AnalyzerContext
    from cruise_control_tpu.analyzer.goals.rack import RackAwareGoal

    ctx = AnalyzerContext(state)
    ok = RackAwareGoal().accept_move(ctx, 0, 1)  # move T's replica on b2
    assert not ok[1]   # broker 1 shares broker 0's host
    # explicit rack + host coexist: rack wins for placement, host recorded
    b2 = ClusterModelBuilder()
    b2.add_broker("r0", cap, host="hA")
    b2.add_broker("r1", cap, host="hA")
    s2 = b2.add_partition("T", [0, 1], {Resource.DISK: 1.0})
    st2 = b2.build()
    assert list(np.asarray(st2.broker_rack)) == [0, 1]
    assert list(np.asarray(st2.broker_host)) == [0, 0]
