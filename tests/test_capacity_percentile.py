"""Per-window load series + percentile capacity estimation (VERDICT round-1
item #5; upstream ``model/Load.java`` carries resource × window series into
the model and capacity estimation provisions for peak, not mean).

The core fixture everywhere: two partitions whose window series are
correlated-bursty, placed on one broker — the MEAN placement fits the
capacity threshold while the PEAK (p100 over windows) breaches it.  With
``capacity_percentile`` set the capacity goals must reject/repair it; with
the percentile off (round-1 behavior) the placement is legal.
"""

import numpy as np
import pytest

from cruise_control_tpu.analyzer.context import AnalyzerContext
from cruise_control_tpu.analyzer.goal_optimizer import GoalOptimizer, make_goals
from cruise_control_tpu.analyzer.goals.base import OptimizationFailure
from cruise_control_tpu.analyzer.goals.capacity import DiskCapacityGoal
from cruise_control_tpu.analyzer.tpu_optimizer import (
    TpuGoalOptimizer,
    TpuSearchConfig,
)
from cruise_control_tpu.analyzer.verifier import verify_result
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.models.builder import ClusterModelBuilder
from cruise_control_tpu.models.cluster_state import capacity_loads

FAST = TpuSearchConfig(max_rounds=40, topk_per_round=128, max_moves_per_round=32)


def bursty_state(percentile: float = 100.0, num_spare: int = 2):
    """Broker 0 hosts two RF-1 partitions: disk windows [60, 10] and
    [55, 5] (means 35/30 — 65 < limit 80; peaks 60/55 — 115 > 80).
    Spare brokers on other racks are empty."""
    b = ClusterModelBuilder()
    cap = {Resource.CPU: 1e4, Resource.NW_IN: 1e6, Resource.NW_OUT: 1e6,
           Resource.DISK: 100.0}
    b.add_broker("r0", cap)
    for i in range(num_spare):
        b.add_broker(f"r{i + 1}", cap)
    tiny = 1.0
    b.add_partition("A", [0], {Resource.CPU: tiny, Resource.NW_IN: tiny,
                               Resource.NW_OUT: tiny, Resource.DISK: 35.0})
    b.add_partition("B", [0], {Resource.CPU: tiny, Resource.NW_IN: tiny,
                               Resource.NW_OUT: tiny, Resource.DISK: 30.0})
    state = b.build()
    P = state.num_partitions
    W = 2
    lw = np.repeat(np.asarray(state.leader_load)[:, None, :], W, axis=1)
    lw[0, :, Resource.DISK] = [60.0, 10.0]
    lw[1, :, Resource.DISK] = [55.0, 5.0]
    fw = lw.copy()
    fw[:, :, Resource.NW_OUT] = 0.0
    return state.replace(
        leader_load_windows=lw.astype(np.float32),
        follower_load_windows=fw.astype(np.float32),
        capacity_percentile=percentile,
    )


def test_capacity_loads_percentile_math():
    state = bursty_state(percentile=100.0)
    lcap, fcap = capacity_loads(state)
    assert lcap[0, Resource.DISK] == pytest.approx(60.0)
    assert lcap[1, Resource.DISK] == pytest.approx(55.0)
    # mean loads untouched
    assert np.asarray(state.leader_load)[0, Resource.DISK] == pytest.approx(35.0)
    # percentile off → aliases of the mean loads
    off = bursty_state(percentile=0.0)
    l0, f0 = capacity_loads(off)
    assert l0 is off.leader_load and f0 is off.follower_load


def test_mean_balanced_peak_violating_placement_is_violating():
    """The VERDICT done-bar: mean-balanced but peak-violating placement is
    rejected by the capacity goals (violations > 0, and the greedy optimize
    sheds it); with the percentile off the same placement is legal."""
    goal = DiskCapacityGoal()
    on = AnalyzerContext(bursty_state(percentile=100.0))
    assert goal.violations(on) == 1
    off = AnalyzerContext(bursty_state(percentile=0.0))
    assert goal.violations(off) == 0

    # greedy repair: one partition leaves broker 0
    goals = make_goals()
    res = GoalOptimizer(goals).optimize(bursty_state(percentile=100.0))
    ctx = AnalyzerContext(res.final_state)
    assert goal.violations(ctx) == 0
    on_b0 = (np.asarray(res.final_state.assignment) == 0).sum()
    assert on_b0 == 1  # the placement split across brokers


def test_accept_move_rejects_peak_breach():
    """A move that fits by mean but breaches by percentile is rejected."""
    state = bursty_state(percentile=100.0, num_spare=2)
    # move partition B onto a broker that already peaks at 60:
    # first move A to broker 1; then broker 1 has peak 60, mean 35.
    ctx = AnalyzerContext(state)
    goal = DiskCapacityGoal()
    from cruise_control_tpu.analyzer.actions import ActionType, BalancingAction

    ctx.apply(BalancingAction(
        ActionType.INTER_BROKER_REPLICA_MOVEMENT, 0, 0, 0, 1
    ))
    ok = goal.accept_move(ctx, 1, 0)   # destinations for partition B
    # broker 1 (peak 60 + 55 = 115 > 80) must be rejected; broker 2 accepted
    assert not ok[1]
    assert ok[2]
    # with percentile off both fit (mean 35 + 30 = 65 < 80)
    ctx_off = AnalyzerContext(bursty_state(percentile=0.0))
    ctx_off.apply(BalancingAction(
        ActionType.INTER_BROKER_REPLICA_MOVEMENT, 0, 0, 0, 1
    ))
    assert goal.accept_move(ctx_off, 1, 0)[1]


def test_tpu_engine_respects_capacity_percentile():
    """The TPU engine repairs the peak violation (device pools prioritize
    percentile-over-capacity brokers; host gates enforce exactly)."""
    state = bursty_state(percentile=100.0)
    goals = make_goals()
    res = TpuGoalOptimizer(config=FAST).optimize(state)
    verify_result(state, res, goals)
    ctx = AnalyzerContext(res.final_state)
    assert DiskCapacityGoal().violations(ctx) == 0
    assert (np.asarray(res.final_state.assignment) == 0).sum() == 1


def test_tpu_engine_impossible_peak_raises():
    """No spare broker can absorb the peak → OptimizationFailure, never a
    silently peak-violating plan."""
    state = bursty_state(percentile=100.0, num_spare=0)
    with pytest.raises(OptimizationFailure):
        TpuGoalOptimizer(config=FAST).optimize(state)


def test_monitor_carries_window_series(tmp_path):
    from tests.test_monitor import make_monitor

    monitor, w, _ = make_monitor(tmp_path)
    monitor.capacity_estimation_percentile = 95.0
    from cruise_control_tpu.monitor.load_monitor import (
        ModelCompletenessRequirements,
    )

    with monitor.acquire_for_model_generation():
        state = monitor.cluster_model(
            ModelCompletenessRequirements(min_required_num_windows=2)
        )
    assert state.leader_load_windows is not None
    assert state.capacity_percentile == 95.0
    P, W, R = state.leader_load_windows.shape
    assert P == state.num_partitions and W >= 2
    # constant simulated workload → every window equals the mean
    assert np.allclose(
        state.leader_load_windows.mean(axis=1), state.leader_load, rtol=1e-4
    )
    # follower series derivation matches the mean derivation
    assert np.allclose(
        state.follower_load_windows[:, :, Resource.NW_OUT], 0.0
    )
