"""JBOD intra-broker disk model + goal tests (upstream
``analyzer/goals/intrabroker`` + ``model/Disk.java`` semantics;
SURVEY.md §2.4/§2.5)."""

import numpy as np
import pytest

from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.analyzer.context import AnalyzerContext
from cruise_control_tpu.analyzer.goal_optimizer import (
    INTRA_BROKER_GOAL_ORDER,
    GoalOptimizer,
    make_goals,
)
from cruise_control_tpu.analyzer.goals.intrabroker import (
    IntraBrokerDiskCapacityGoal,
    IntraBrokerDiskUsageDistributionGoal,
)
from cruise_control_tpu.models.builder import ClusterModelBuilder

from harness import full_stack

CAP = {Resource.CPU: 100.0, Resource.NW_IN: 1e5, Resource.NW_OUT: 1e5,
       Resource.DISK: 2000.0}


def jbod_cluster(loads, disks=2, disk_cap=1000.0, offline=()):
    """One broker with `disks` disks; every replica starts on disk 0 unless
    its entry in `loads` is a (load, disk) pair."""
    b = ClusterModelBuilder()
    b.add_broker(
        0, CAP,
        disks=[(f"/d{i}", disk_cap, i in offline) for i in range(disks)],
    )
    for i, item in enumerate(loads):
        load, disk = item if isinstance(item, tuple) else (item, 0)
        b.add_partition(
            "t", [0], {Resource.DISK: load, Resource.NW_IN: 1.0},
            disks=[disk],
        )
    return b.build()


class TestDiskModel:
    def test_builder_assembles_disk_tensors(self):
        state = jbod_cluster([100.0, (200.0, 1)])
        assert state.has_disks and state.max_disks == 2
        assert state.disk_names == (("/d0", "/d1"),)
        rd = np.asarray(state.replica_disk)
        assert rd[0, 0] == 0 and rd[1, 0] == 1

    def test_context_disk_load_aggregates(self):
        state = jbod_cluster([100.0, (200.0, 1), 50.0])
        ctx = AnalyzerContext(state)
        assert ctx.disk_load[0, 0] == pytest.approx(150.0)
        assert ctx.disk_load[0, 1] == pytest.approx(200.0)

    def test_offline_disk_marks_replicas_offline(self):
        state = jbod_cluster([100.0, (200.0, 1)], offline=(1,))
        off = np.asarray(state.replica_offline)
        assert not off[0, 0] and off[1, 0]

    def test_intra_action_updates_aggregates(self):
        from cruise_control_tpu.analyzer.goals.intrabroker import _intra_action

        state = jbod_cluster([100.0])
        ctx = AnalyzerContext(state)
        ctx.apply(_intra_action(ctx, 0, 0, 1))
        assert ctx.disk_load[0, 0] == pytest.approx(0.0)
        assert ctx.disk_load[0, 1] == pytest.approx(100.0)
        assert ctx.replica_disk[0, 0] == 1


class TestIntraBrokerGoals:
    def test_capacity_goal_relieves_overloaded_disk(self):
        # disk 0 holds 900/1000 against threshold 0.8 → must shed ≥100
        state = jbod_cluster([500.0, 250.0, 150.0])
        goal = make_goals(["IntraBrokerDiskCapacityGoal"])[0]
        ctx = AnalyzerContext(state)
        assert goal.violations(ctx) == 1
        goal.optimize(ctx, [])
        assert goal.violations(ctx) == 0
        assert ctx.disk_load[0, 0] <= 800.0 + 1e-6

    def test_capacity_goal_evacuates_offline_disk(self):
        state = jbod_cluster([(300.0, 1), 100.0], offline=(1,))
        goal = make_goals(["IntraBrokerDiskCapacityGoal"])[0]
        ctx = AnalyzerContext(state)
        goal.optimize(ctx, [])
        assert ctx.disk_load[0, 1] == pytest.approx(0.0)
        assert ctx.replica_disk[0, 0] == 0
        assert not ctx.replica_offline[0, 0]

    def test_distribution_goal_balances_disks(self):
        state = jbod_cluster([300.0, 280.0, 290.0, 30.0])  # all on disk 0
        goal = make_goals(["IntraBrokerDiskUsageDistributionGoal"])[0]
        ctx = AnalyzerContext(state)
        assert goal.violations(ctx) > 0
        goal.optimize(ctx, [])
        utils = ctx.disk_load[0] / 1000.0
        assert abs(utils[0] - utils[1]) < 0.35

    def test_distribution_respects_capacity_goal_chaining(self):
        # disk 1 is tiny: distribution pressure must not push it past the
        # 0.8 capacity threshold the hard goal enforced first
        b = ClusterModelBuilder()
        b.add_broker(0, CAP, disks=[("/big", 10_000.0), ("/small", 100.0)])
        for load in [400.0, 400.0, 300.0, 60.0, 50.0]:
            b.add_partition("t", [0], {Resource.DISK: load}, disks=[0])
        state = b.build()
        opt = GoalOptimizer(goals=make_goals(INTRA_BROKER_GOAL_ORDER))
        result = opt.optimize(state)
        ctx = AnalyzerContext(result.final_state)
        assert ctx.disk_load[0, 1] <= 100.0 * 0.8 + 1e-6, \
            "distribution goal overfilled the small disk past the hard cap"

    def test_intra_moves_complete_with_async_backend(self):
        # a backend that applies dir moves only after a tick must still
        # complete (executor polls instead of checking synchronously)
        from cruise_control_tpu.executor.backend import SimulatedClusterBackend

        class SlowDirBackend(SimulatedClusterBackend):
            def __init__(self, *a, **kw):
                super().__init__(*a, **kw)
                self._pending_dirs = {}

            def alter_replica_log_dirs(self, moves):
                self._pending_dirs.update(
                    {(p, b): d for p, by in moves.items()
                     for b, d in by.items()}
                )

            def tick(self):
                super().tick()
                for (p, b), d in self._pending_dirs.items():
                    self.replica_dir[(p, b)] = d
                self._pending_dirs = {}

        cc, backend, _ = full_stack(
            jbod_disks={"/d0": 50_000.0, "/d1": 50_000.0}
        )
        slow = SlowDirBackend(
            {p: list(st.replicas) for p, st in backend.partitions.items()},
            {p: st.leader for p, st in backend.partitions.items()},
            brokers=backend.brokers,
        )
        slow.replica_dir = dict(backend.replica_dir)
        cc.executor.backend = slow
        result = cc.rebalance(rebalance_disk=True, dryrun=False)
        assert result.execution.succeeded, result.execution
        assert any(d == "/d1" for d in slow.replica_dir.values())

    def test_builder_default_placement_skips_offline_disks(self):
        b = ClusterModelBuilder()
        b.add_broker(0, CAP, disks=[("/ok", 1000.0), ("/dead", 1000.0, True)])
        for load in [10.0, 20.0, 30.0]:
            b.add_partition("t", [0], {Resource.DISK: load})  # no disks=
        state = b.build()
        rd = np.asarray(state.replica_disk)
        assert (rd[:, 0] == 0).all(), "default placement used an offline disk"
        assert not np.asarray(state.replica_offline).any()

    def test_goals_vacuous_without_disk_model(self):
        from harness import skewed_workload
        from cruise_control_tpu.models.generators import random_cluster

        state = random_cluster(seed=3, num_brokers=6, num_racks=3,
                               num_partitions=32)
        for cls in (IntraBrokerDiskCapacityGoal,
                    IntraBrokerDiskUsageDistributionGoal):
            goal = make_goals([cls.name])[0]
            ctx = AnalyzerContext(state)
            assert goal.violations(ctx) == 0
            goal.optimize(ctx, [])
            assert ctx.actions == []


class TestIntraProposalsAndExecution:
    def test_optimizer_emits_disk_move_proposals(self):
        state = jbod_cluster([500.0, 250.0, 150.0])
        opt = GoalOptimizer(goals=make_goals(INTRA_BROKER_GOAL_ORDER))
        result = opt.optimize(state)
        assert result.proposals
        for pr in result.proposals:
            assert pr.has_disk_move
            assert not pr.has_replica_change and not pr.has_leader_change
            for b, old_d, new_d in pr.disk_moves:
                assert old_d != new_d

    def test_end_to_end_rebalance_disk(self):
        cc, backend, _ = full_stack(
            jbod_disks={"/d0": 50_000.0, "/d1": 50_000.0}
        )
        # everything starts on /d0
        assert all(d == "/d0" for d in backend.replica_dir.values())
        result = cc.rebalance(rebalance_disk=True, dryrun=False)
        assert result.execution is not None and result.execution.succeeded
        assert result.proposals, "no disk moves planned"
        moved = [d for d in backend.replica_dir.values() if d == "/d1"]
        assert moved, "no replica physically moved to /d1"
        # replica placement untouched — intra moves only
        for pr in result.proposals:
            assert not pr.has_replica_change

    def test_disk_moves_translated_to_dir_names(self):
        cc, _, _ = full_stack(jbod_disks={"/d0": 50_000.0, "/d1": 50_000.0})
        result = cc.rebalance(rebalance_disk=True, dryrun=True)
        for pr in result.proposals:
            for b, old_dir, new_dir in pr.disk_moves:
                assert old_dir.startswith("/d") and new_dir.startswith("/d")

    def test_inter_broker_rebalance_unaffected_by_disk_model(self):
        cc, backend, _ = full_stack(
            jbod_disks={"/d0": 50_000.0, "/d1": 50_000.0}
        )
        result = cc.rebalance(dryrun=False)
        assert result.execution.succeeded
        leaders = [st.leader for st in backend.partitions.values()]
        assert leaders.count(0) < len(leaders)
