"""Persistent-compile-cache hardening (round-2 VERDICT weak #5)."""

import jax


def test_cache_dir_is_host_fingerprinted(tmp_path):
    """A shared cache dir must never serve an AOT blob compiled on a
    different machine: the configured dir gains a host-keyed suffix."""
    from cruise_control_tpu.utils import jit_cache

    fp = jit_cache.host_fingerprint()
    assert fp == jit_cache.host_fingerprint()  # stable within a host
    assert len(fp) == 16
    before = jax.config.jax_compilation_cache_dir
    try:
        jit_cache.enable(str(tmp_path))
        configured = jax.config.jax_compilation_cache_dir
        assert configured == str(tmp_path / fp)
    finally:
        jax.config.update("jax_compilation_cache_dir", before)
