"""Lock-order graph (ISSUE 19): the runtime acquisition-order witness
on the contention registry, the committed ``cc-tpu-lock-graph/1``
artifact, and the reconciliation between them.

Three layers:

* **witness unit tests** — a private :class:`ContentionRegistry` and
  wrapper locks pin the recorder's semantics exactly: nested
  acquisition → edge, off → zero recording, bounded distinct edges
  with a ``dropped`` counter, ``reset()`` clears and disables, and
  ``Condition``/semaphore interop (both delegate to the instrumented
  ``acquire``/``release``, so they witness for free).

* **committed-artifact gate** — ``LOCK_GRAPH_r19.json`` validates
  against the closed ``cc-tpu-lock-graph/1`` schema, matches what
  cclint's flow-sensitive analysis derives from the live tree (locks,
  edges, cycles), and is ACYCLIC — the static side of the deadlock
  contract.

* **runtime reconciliation** — drive the real stack (proposals,
  rebalance, a maintenance scenario) with the witness on: every
  observed acquisition order between NAMED locks must be an edge of
  the committed static graph.  A dynamic edge the static analysis
  cannot see is exactly the blind spot that turns into an
  unexplainable production deadlock — the factory-context propagation
  in lockflow exists because this test demanded it
  (``proposal.single_flight → model.semaphore`` through
  ``ModelGenerationLock``).
"""

import json
import pathlib
import threading

from cruise_control_tpu.devtools.lint.driver import run_lint
from cruise_control_tpu.devtools.lint.rules_lockorder import (
    SCHEMA,
    build_lock_graph,
)
from cruise_control_tpu.utils import locks
from cruise_control_tpu.utils.locks import (
    ContentionRegistry,
    InstrumentedLock,
    InstrumentedSemaphore,
)
from harness import full_stack
from test_artifact_schemas import SCHEMAS, validate

ROOT = pathlib.Path(__file__).resolve().parent.parent
PKG = ROOT / "cruise_control_tpu"
ARTIFACT = ROOT / "LOCK_GRAPH_r19.json"


def _pair(reg):
    return (InstrumentedLock("w.outer", registry=reg),
            InstrumentedLock("w.inner", registry=reg))


# ---- witness unit tests ---------------------------------------------------------
def test_nested_acquisition_records_an_edge():
    reg = ContentionRegistry()
    outer, inner = _pair(reg)
    reg.enable_order_witness()
    for _ in range(3):
        with outer:
            with inner:
                pass
    w = reg.order_witness()
    assert w["enabled"] is True
    assert w["dropped"] == 0
    assert w["edges"] == [{"from": "w.outer", "to": "w.inner", "count": 3}]


def test_witness_off_records_nothing():
    reg = ContentionRegistry()
    outer, inner = _pair(reg)
    with outer:
        with inner:
            pass
    w = reg.order_witness()
    assert w["enabled"] is False
    assert w["edges"] == []
    # acquisitions still hit the contention stats — the witness is an
    # overlay, not a replacement
    assert reg.stats("w.outer").acquisitions >= 1


def test_witness_bound_drops_new_edges_but_counts_known_ones():
    reg = ContentionRegistry()
    a = InstrumentedLock("w.a", registry=reg)
    b = InstrumentedLock("w.b", registry=reg)
    c = InstrumentedLock("w.c", registry=reg)
    reg.enable_order_witness(bound=1)
    with a:
        with b:
            pass
    with a:  # known edge: count accumulates despite the full table
        with b:
            pass
    with b:  # NEW distinct edge: over the bound, dropped
        with c:
            pass
    w = reg.order_witness()
    assert w["edges"] == [{"from": "w.a", "to": "w.b", "count": 2}]
    assert w["dropped"] == 1


def test_reset_clears_edges_and_disables():
    reg = ContentionRegistry()
    outer, inner = _pair(reg)
    reg.enable_order_witness()
    with outer:
        with inner:
            pass
    reg.reset()
    w = reg.order_witness()
    assert w == {"enabled": False, "edges": [], "dropped": 0}


def test_semaphore_participates_in_the_order_vocabulary():
    reg = ContentionRegistry()
    lock = InstrumentedLock("w.lock", registry=reg)
    sem = InstrumentedSemaphore(2, name="w.sem", registry=reg)
    reg.enable_order_witness()
    with lock:
        sem.acquire()
        sem.release()
    w = reg.order_witness()
    assert w["edges"] == [{"from": "w.lock", "to": "w.sem", "count": 1}]


def test_condition_interop_witnesses_through_the_inner_lock():
    # threading.Condition calls the wrapped lock's acquire/release, so
    # a Condition over an InstrumentedLock witnesses with no extra
    # plumbing — the admission-queue idiom
    reg = ContentionRegistry()
    outer = InstrumentedLock("w.outer", registry=reg)
    cond = threading.Condition(InstrumentedLock("w.cond", registry=reg))
    reg.enable_order_witness()
    with outer:
        with cond:
            pass
    w = reg.order_witness()
    assert w["edges"] == [{"from": "w.outer", "to": "w.cond", "count": 1}]


def test_reacquiring_same_name_is_not_a_self_edge():
    reg = ContentionRegistry()
    a1 = InstrumentedLock("w.same", registry=reg)
    a2 = InstrumentedLock("w.same", registry=reg)  # distinct instance
    reg.enable_order_witness()
    with a1:
        with a2:
            pass
    assert reg.order_witness()["edges"] == []


# ---- the committed artifact -----------------------------------------------------
def test_committed_lock_graph_matches_schema_and_live_tree():
    committed = json.loads(ARTIFACT.read_text())
    validate(committed, SCHEMAS[SCHEMA], ARTIFACT.name)
    result = run_lint(paths=[str(PKG)], rules=["lock-order"])
    live = build_lock_graph(result.project)
    assert committed["locks"] == live["locks"], (
        "the named-lock vocabulary drifted — regenerate via "
        "python -m cruise_control_tpu.devtools.lint --lock-graph "
        "LOCK_GRAPH_r19.json cruise_control_tpu"
    )
    assert ([(e["from"], e["to"]) for e in committed["edges"]]
            == [(e["from"], e["to"]) for e in live["edges"]]), (
        "the acquisition-order edge set drifted — regenerate the "
        "committed artifact and review the new ordering"
    )
    # the deadlock contract itself
    assert committed["cycles"] == [] and live["cycles"] == []
    # every edge carries a reviewable file:line witness chain
    for e in committed["edges"]:
        assert e["witness"], f"edge {e['from']}→{e['to']} has no witness"
        for hop in e["witness"]:
            assert hop["line"] >= 1


# ---- runtime ⊆ static reconciliation --------------------------------------------
def test_runtime_witnessed_orders_are_static_edges():
    """Every acquisition order the live stack exhibits must be an edge
    the static analysis already knows.  Scope: edges between locks in
    the committed vocabulary (unnamed locks are a documented blind
    spot), self-edges excluded (distinct instances sharing a name)."""
    committed = json.loads(ARTIFACT.read_text())
    vocab = set(committed["locks"])
    static_edges = {(e["from"], e["to"]) for e in committed["edges"]}

    locks.CONTENTION.reset()
    locks.CONTENTION.enable_order_witness()
    try:
        from cruise_control_tpu.sim import make_scenario, run_scenario

        cc, backend, reporter = full_stack(engine="greedy")
        cc.get_proposals()
        cc.rebalance(dryrun=False)
        run_scenario(make_scenario("add_broker_rebalance"))
        w = locks.CONTENTION.order_witness()
    finally:
        locks.CONTENTION.reset()

    witnessed = {(e["from"], e["to"]) for e in w["edges"]}
    assert witnessed, "the drive witnessed no edges — the probe is vacuous"
    assert w["dropped"] == 0
    checkable = {(a, b) for a, b in witnessed
                 if a in vocab and b in vocab and a != b}
    # non-vacuous: the serve path's known nestings must show up
    assert ("proposal.single_flight", "model.semaphore") in checkable
    missing = sorted(checkable - static_edges)
    assert not missing, (
        f"runtime acquisition order(s) {missing} are NOT edges of the "
        "committed static lock graph — the flow-sensitive analysis has "
        "a blind spot (or the artifact is stale); regenerate "
        "LOCK_GRAPH_r19.json and close the gap in lockflow.py"
    )
