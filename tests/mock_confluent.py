"""A mock ``confluent_kafka`` module (+ ``.admin``) for unit-testing the
production wire without the client library or a network.

Mimics the client's future-based API shapes the wire uses: synchronous
futures over a shared in-memory broker, ``KafkaError`` objects with
``code()/retriable()/fatal()``, metadata objects with the real attribute
names (``isrs``, ``adding_replicas``), and scriptable per-RPC failures
(``broker.fail_next[...]``).  Install with :func:`install` (returns the
broker handle) and remove with :func:`uninstall`.
"""

from __future__ import annotations

import sys
import types
from concurrent.futures import Future
from types import SimpleNamespace


def _done(value=None, exc=None) -> Future:
    f = Future()
    if exc is not None:
        f.set_exception(exc)
    else:
        f.set_result(value)
    return f


class MockKafkaError:
    def __init__(self, code, msg="", retriable=False, fatal=False):
        self._code, self._msg = code, msg
        self._retriable, self._fatal = retriable, fatal

    def code(self):
        return self._code

    def str(self):
        return self._msg

    def retriable(self):
        return self._retriable

    def fatal(self):
        return self._fatal

    def __repr__(self):
        return f"MockKafkaError({self._code}, {self._msg!r})"


class MockKafkaException(Exception):
    pass


class MockTopicPartition:
    def __init__(self, topic, partition=-1, offset=-1001):
        self.topic, self.partition, self.offset = topic, partition, offset

    def __hash__(self):
        return hash((self.topic, self.partition))

    def __eq__(self, other):
        return (self.topic, self.partition) == (other.topic, other.partition)

    def __repr__(self):
        return f"MockTopicPartition({self.topic}, {self.partition})"


class MockBroker:
    """Shared in-memory cluster state, keyed by bootstrap.servers."""

    def __init__(self):
        self.nodes = {0: "r0", 1: "r1", 2: None}       # id → rack
        self.topics = {}      # name → {pid: {"leader","replicas","isrs"}}
        self.logs = {}        # name → {pid: [bytes]}
        self.log_bases = {}   # (name, pid) → earliest offset (retention)
        self.topic_configs = {}
        self.configs = {}     # (rtype, name) → {key: value}
        self.reassignments = {}  # (t, p) → {"replicas","adding","removing"}
        self.log_dirs = {}    # broker → {dir: {"error","replicas":[(t,p)]}}
        self.calls = []       # (rpc, payload) log
        self.fail_next = {}   # rpc name → MockKafkaError (one-shot)
        #: True = reassignments complete instantly (a fast cluster);
        #: False = they stay listed in-flight until completed by the test
        self.auto_complete = False

    def add_topic(self, name, partitions=1, leader=0, replicas=(0, 1)):
        self.topics[name] = {
            p: {"leader": leader, "replicas": list(replicas),
                "isrs": list(replicas)}
            for p in range(partitions)
        }
        self.logs[name] = {p: [] for p in range(partitions)}

    def trim(self, topic, pid, new_earliest):
        """Retention: the broker deletes records below ``new_earliest``."""
        base = self.log_bases.get((topic, pid), 0)
        drop = max(0, new_earliest - base)
        del self.logs[topic][pid][:drop]
        self.log_bases[(topic, pid)] = base + drop

    def _fail(self, rpc):
        err = self.fail_next.pop(rpc, None)
        if err is not None:
            return MockKafkaException(err)
        return None


_BROKERS = {}


def broker_for(servers: str) -> MockBroker:
    return _BROKERS.setdefault(servers, MockBroker())


class MockAdminClient:
    def __init__(self, conf):
        self.conf = conf
        self.b = broker_for(conf["bootstrap.servers"])

    # -- metadata --
    def describe_cluster(self, request_timeout=None):
        exc = self.b._fail("describe_cluster")
        if exc:
            return _done(exc=exc)
        nodes = [SimpleNamespace(id=i, rack=r) for i, r in self.b.nodes.items()]
        return _done(SimpleNamespace(nodes=nodes))

    def list_topics(self, topic=None, timeout=None):
        exc = self.b._fail("list_topics")
        if exc:
            raise exc
        topics = {}
        names = [topic] if topic is not None else list(self.b.topics)
        for name in names:
            parts = self.b.topics.get(name)
            if parts is None:
                continue
            topics[name] = SimpleNamespace(
                error=None,
                partitions={
                    p: SimpleNamespace(
                        id=p, leader=row["leader"],
                        replicas=list(row["replicas"]),
                        isrs=list(row["isrs"]), error=None,
                    )
                    for p, row in parts.items()
                },
            )
        return SimpleNamespace(
            brokers={i: SimpleNamespace(id=i) for i in self.b.nodes},
            topics=topics,
        )

    # -- reassignment --
    def alter_partition_reassignments(self, req, request_timeout=None):
        self.b.calls.append(("alter_partition_reassignments", {
            (tp.topic, tp.partition): (None if new is None else list(new))
            for tp, new in req.items()
        }))
        exc = self.b._fail("alter_partition_reassignments")
        out = {}
        for tp, new in req.items():
            if exc:
                out[tp] = _done(exc=exc)
                continue
            key = (tp.topic, tp.partition)
            if new is None:
                self.b.reassignments.pop(key, None)
            elif self.b.auto_complete:
                row = self.b.topics[tp.topic][tp.partition]
                row["replicas"] = list(new)
                row["isrs"] = list(new)
                if row["leader"] not in new:
                    row["leader"] = new[0]
            else:
                row = self.b.topics[tp.topic][tp.partition]
                adding = [x for x in new if x not in row["replicas"]]
                removing = [x for x in row["replicas"] if x not in new]
                self.b.reassignments[key] = {
                    "replicas": list(dict.fromkeys(row["replicas"] + adding)),
                    "adding": adding, "removing": removing,
                }
            out[tp] = _done(None)
        return out

    def list_partition_reassignments(self, request_timeout=None):
        exc = self.b._fail("list_partition_reassignments")
        if exc:
            return _done(exc=exc)
        return _done({
            MockTopicPartition(t, p): SimpleNamespace(
                replicas=list(st["replicas"]),
                adding_replicas=list(st["adding"]),
                removing_replicas=list(st["removing"]),
            )
            for (t, p), st in self.b.reassignments.items()
        })

    def elect_leaders(self, election_type, partitions):
        self.b.calls.append(("elect_leaders", election_type, [
            (tp.topic, tp.partition) for tp in partitions
        ]))
        exc = self.b._fail("elect_leaders")
        if exc:
            return _done(exc=exc)
        result = {}
        for tp in partitions:
            row = self.b.topics[tp.topic][tp.partition]
            if row["leader"] == row["replicas"][0]:
                # the real client wraps per-partition errors in
                # KafkaException — callers must unwrap
                result[tp] = MockKafkaException(
                    MockKafkaError(84, "ELECTION_NOT_NEEDED"))
            else:
                row["leader"] = row["replicas"][0]
                result[tp] = None
        return _done(result)

    # -- configs --
    def describe_configs(self, resources):
        out = {}
        for res in resources:
            exc = self.b._fail("describe_configs")
            if exc:
                out[res] = _done(exc=exc)
                continue
            cfg = self.b.configs.get((res.rtype_name, res.name), {})
            out[res] = _done({
                k: SimpleNamespace(name=k, value=v) for k, v in cfg.items()
            })
        return out

    def incremental_alter_configs(self, resources):
        out = {}
        for res in resources:
            self.b.calls.append(("incremental_alter_configs",
                                 res.rtype_name, res.name, [
                                     (e.name, e.value, e.incremental_operation)
                                     for e in res.incremental_configs
                                 ]))
            exc = self.b._fail("incremental_alter_configs")
            if exc:
                out[res] = _done(exc=exc)
                continue
            cfg = self.b.configs.setdefault((res.rtype_name, res.name), {})
            for e in res.incremental_configs:
                if e.incremental_operation == MockAlterConfigOpType.DELETE:
                    cfg.pop(e.name, None)
                else:
                    cfg[e.name] = e.value
            out[res] = _done(None)
        return out

    # -- log dirs --
    def alter_replica_log_dirs(self, req):
        self.b.calls.append(("alter_replica_log_dirs", dict(req)))
        out = {}
        for (t, p, broker), d in req.items():
            exc = self.b._fail("alter_replica_log_dirs")
            if exc:
                out[(t, p, broker)] = _done(exc=exc)
                continue
            dirs = self.b.log_dirs.setdefault(broker, {})
            for info in dirs.values():
                info["replicas"] = [
                    x for x in info["replicas"] if x != (t, p)
                ]
            dirs.setdefault(d, {"error": None, "replicas": []})
            dirs[d]["replicas"].append((t, p))
            out[(t, p, broker)] = _done(None)
        return out

    def describe_log_dirs(self, brokers, request_timeout=None):
        out = {}
        for broker in brokers:
            exc = self.b._fail("describe_log_dirs")
            if exc:
                out[broker] = _done(exc=exc)
                continue
            out[broker] = _done({
                d: SimpleNamespace(
                    error=info["error"],
                    replicas=[
                        MockTopicPartition(t, p) for t, p in info["replicas"]
                    ],
                )
                for d, info in self.b.log_dirs.get(broker, {}).items()
            })
        return out

    # -- topics --
    def create_topics(self, new_topics):
        out = {}
        for nt in new_topics:
            self.b.calls.append(("create_topics", nt.topic,
                                 nt.num_partitions, nt.replication_factor,
                                 dict(nt.config)))
            exc = self.b._fail("create_topics")
            if exc:
                out[nt.topic] = _done(exc=exc)
                continue
            if nt.topic in self.b.topics:
                out[nt.topic] = _done(exc=MockKafkaException(
                    MockKafkaError(36, "TOPIC_ALREADY_EXISTS")))
                continue
            self.b.add_topic(nt.topic, partitions=nt.num_partitions)
            self.b.topic_configs[nt.topic] = dict(nt.config)
            out[nt.topic] = _done(None)
        return out


class MockProducer:
    def __init__(self, conf):
        self.b = broker_for(conf["bootstrap.servers"])
        self._pending = []

    def produce(self, topic, value=None, key=None, on_delivery=None):
        limit = getattr(self.b, "produce_buffer_limit", None)
        if limit is not None and len(self._pending) >= limit:
            raise BufferError("Local: Queue full")
        self._pending.append((topic, key, value, on_delivery))

    def poll(self, timeout=None):
        n = len(self._pending)
        self.flush(timeout)
        return n

    def flush(self, timeout=None):
        import zlib

        err = self.b.fail_next.pop("produce", None)
        for topic, key, value, cb in self._pending:
            if err is not None:
                if cb:
                    cb(err, None)
                continue
            # real-broker behavior: compacted topics reject keyless records
            if key is None and self.b.topic_configs.get(topic, {}).get(
                    "cleanup.policy") == "compact":
                if cb:
                    cb(MockKafkaError(
                        87, "INVALID_RECORD: compacted topic requires key",
                    ), None)
                continue
            if topic not in self.b.logs:
                self.b.add_topic(topic)
            parts = self.b.logs[topic]
            if key is not None:
                target = zlib.crc32(key) % len(parts)
            else:
                target = min(parts, key=lambda p: len(parts[p]))
            parts[target].append(value)
            if cb:
                cb(None, SimpleNamespace(topic=topic))
        self._pending = []
        return 0


class _MockMessage:
    def __init__(self, topic, partition, offset, value):
        self._t, self._p, self._o, self._v = topic, partition, offset, value

    def error(self):
        return None

    def topic(self):
        return self._t

    def partition(self):
        return self._p

    def offset(self):
        return self._o

    def value(self):
        return self._v


class MockConsumer:
    def __init__(self, conf):
        self._servers = conf["bootstrap.servers"]
        self.b = broker_for(self._servers)
        self._queue = []
        self._closed = False

    def list_topics(self, topic=None, timeout=None):
        return MockAdminClient(
            {"bootstrap.servers": self._servers}
        ).list_topics(topic=topic, timeout=timeout)

    def get_watermark_offsets(self, tp, timeout=None):
        log = self.b.logs.get(tp.topic, {}).get(tp.partition, [])
        base = self.b.log_bases.get((tp.topic, tp.partition), 0)
        return base, base + len(log)

    def assign(self, tps):
        for tp in tps:
            log = self.b.logs.get(tp.topic, {}).get(tp.partition, [])
            base = self.b.log_bases.get((tp.topic, tp.partition), 0)
            for idx in range(max(tp.offset, base) - base, len(log)):
                self._queue.append(
                    _MockMessage(tp.topic, tp.partition, base + idx, log[idx])
                )

    def poll(self, timeout=None):
        assert not self._closed
        return self._queue.pop(0) if self._queue else None

    def close(self):
        self._closed = True


class MockConfigResource:
    class Type:
        TOPIC = "topic"
        BROKER = "broker"

    def __init__(self, restype, name, incremental_configs=None):
        self.rtype_name = restype
        self.name = name
        self.incremental_configs = incremental_configs or []

    def __hash__(self):
        return hash((self.rtype_name, self.name))


class MockConfigEntry:
    def __init__(self, name, value, incremental_operation=None):
        self.name, self.value = name, value
        self.incremental_operation = incremental_operation


class MockAlterConfigOpType:
    SET = "SET"
    DELETE = "DELETE"


class MockNewTopic:
    def __init__(self, topic, num_partitions=1, replication_factor=1,
                 config=None):
        self.topic = topic
        self.num_partitions = num_partitions
        self.replication_factor = replication_factor
        self.config = config or {}


def install() -> MockBroker:
    """Inject the mock modules into sys.modules → the shared broker."""
    _BROKERS.clear()
    mod = types.ModuleType("confluent_kafka")
    mod.Producer = MockProducer
    mod.Consumer = MockConsumer
    mod.TopicPartition = MockTopicPartition
    mod.KafkaException = MockKafkaException
    mod.KafkaError = MockKafkaError
    mod.ElectionType = SimpleNamespace(PREFERRED="preferred")
    admin = types.ModuleType("confluent_kafka.admin")
    admin.AdminClient = MockAdminClient
    admin.NewTopic = MockNewTopic
    admin.ConfigResource = MockConfigResource
    admin.ConfigEntry = MockConfigEntry
    admin.AlterConfigOpType = MockAlterConfigOpType
    mod.admin = admin
    sys.modules["confluent_kafka"] = mod
    sys.modules["confluent_kafka.admin"] = admin
    return broker_for("mock:9092")


def uninstall() -> None:
    sys.modules.pop("confluent_kafka", None)
    sys.modules.pop("confluent_kafka.admin", None)
    _BROKERS.clear()
