"""Executor layer tests (upstream ExecutorTest / ExecutionTaskPlannerTest /
ExecutionTaskManagerTest tier, against the simulated backend)."""

import os

import numpy as np
import pytest

from cruise_control_tpu.analyzer.goal_optimizer import (
    ExecutionProposal,
    GoalOptimizer,
    make_goals,
)
from cruise_control_tpu.executor.backend import SimulatedClusterBackend
from cruise_control_tpu.executor.executor import (
    Executor,
    ExecutorConfig,
    ExecutorStateValue,
    OngoingExecutionError,
)
from cruise_control_tpu.executor.tasks import (
    ExecutionTask,
    ExecutionTaskPlanner,
    PostponeUrpReplicaMovementStrategy,
    PrioritizeLargeReplicaMovementStrategy,
    PrioritizeMinIsrWithOfflineReplicasStrategy,
    PrioritizeSmallReplicaMovementStrategy,
    TaskState,
    TaskType,
)
from cruise_control_tpu.models.generators import random_cluster


def make_backend(num_partitions=6, rf=2, brokers=4, **kw):
    assignment = {
        p: [(p + i) % brokers for i in range(rf)] for p in range(num_partitions)
    }
    leaders = {p: assignment[p][0] for p in range(num_partitions)}
    return SimulatedClusterBackend(assignment, leaders, **kw), assignment, leaders


def prop(p, old, new, old_leader=None, new_leader=None):
    return ExecutionProposal(
        partition=p, topic=0,
        old_leader=old_leader if old_leader is not None else old[0],
        new_leader=new_leader if new_leader is not None else new[0],
        old_replicas=tuple(old), new_replicas=tuple(new),
    )


def test_simple_move_completes():
    backend, assignment, _ = make_backend()
    ex = Executor(backend)
    p = prop(0, assignment[0], [2, 3])
    result = ex.execute_proposals([p])
    # one replica task + one leader task (leader moves 0 -> 2)
    assert result.succeeded and result.completed == 2
    assert backend.partitions[0].replicas == [2, 3]
    assert backend.partitions[0].leader == 2
    assert ex.state == ExecutorStateValue.NO_TASK_IN_PROGRESS


def test_leadership_only_move():
    backend, assignment, _ = make_backend()
    p = prop(1, assignment[1], assignment[1], new_leader=assignment[1][1])
    result = Executor(backend).execute_proposals([p])
    assert result.succeeded
    assert backend.partitions[1].leader == assignment[1][1]


def test_per_broker_concurrency_cap():
    backend, assignment, _ = make_backend(num_partitions=8, move_latency_ticks=3)
    cfg = ExecutorConfig(num_concurrent_partition_movements_per_broker=1)
    ex = Executor(backend, cfg)
    # all proposals add replicas to broker 3 -> serialized by the cap
    proposals = [
        prop(p, assignment[p], [assignment[p][0], 3])
        for p in range(3)
        if 3 not in assignment[p]
    ]
    result = ex.execute_proposals(proposals)
    assert result.succeeded
    # with latency 3 and cap 1 at broker 3, must take ~3x single-move ticks
    assert result.ticks >= 3 * len(proposals)


def test_task_timeout_marks_dead():
    backend, assignment, _ = make_backend(failed_brokers={3})
    cfg = ExecutorConfig(task_timeout_ticks=5)
    p = prop(0, assignment[0], [assignment[0][0], 3])  # 3 never catches up
    result = Executor(backend, cfg).execute_proposals([p])
    assert result.dead == 1 and not result.succeeded


def test_stop_execution_aborts_pending():
    backend, assignment, _ = make_backend(num_partitions=8, move_latency_ticks=50)
    cfg = ExecutorConfig(num_concurrent_partition_movements_per_broker=1,
                         task_timeout_ticks=1000)
    ex = Executor(backend, cfg)
    proposals = [
        prop(p, assignment[p], [assignment[p][0], 3])
        for p in range(4)
        if 3 not in assignment[p]
    ]
    # request stop after the first tick via notifier trick: run in a thread-free
    # way by pre-setting stop after start — use a tick-hook on the backend
    orig_tick = backend.tick
    def hooked():
        orig_tick()
        if backend.ticks == 2:
            ex.stop_execution()
    backend.tick = hooked
    result = ex.execute_proposals(proposals)
    assert result.stopped
    assert result.aborted > 0
    assert ex.state == ExecutorStateValue.NO_TASK_IN_PROGRESS


def test_single_writer_guard():
    backend, assignment, _ = make_backend()
    ex = Executor(backend)
    ex.state = ExecutorStateValue.STARTING_EXECUTION
    with pytest.raises(OngoingExecutionError):
        ex.execute_proposals([prop(0, assignment[0], [2, 3])])


def test_throttle_set_and_cleared():
    backend, assignment, _ = make_backend()
    cfg = ExecutorConfig(replication_throttle=1e6)
    result = Executor(backend, cfg).execute_proposals(
        [prop(0, assignment[0], [2, 3])]
    )
    assert result.succeeded
    assert backend.throttle_rate is None  # cleared after execution
    assert backend.throttle_history[0] == ("set", 1e6)
    assert backend.throttle_history[-1][0] == "clear"


def test_movement_strategies_order():
    planner = ExecutionTaskPlanner(PrioritizeLargeReplicaMovementStrategy())
    proposals = [prop(p, [0, 1], [0, 2]) for p in range(3)]
    planner.add_proposals(proposals)
    sizes = {0: 10.0, 1: 30.0, 2: 20.0}
    batch = planner.next_replica_batch({}, 100, sizes, set())
    assert [t.proposal.partition for t in batch] == [1, 2, 0]
    planner2 = ExecutionTaskPlanner(PrioritizeSmallReplicaMovementStrategy())
    planner2.add_proposals(proposals)
    batch2 = planner2.next_replica_batch({}, 100, sizes, set())
    assert [t.proposal.partition for t in batch2] == [0, 2, 1]


def test_postpone_urp_strategy():
    planner = ExecutionTaskPlanner(PostponeUrpReplicaMovementStrategy())
    proposals = [prop(p, [0, 1], [0, 2]) for p in range(3)]
    planner.add_proposals(proposals)
    batch = planner.next_replica_batch({}, 100, {}, urp={0})
    assert [t.proposal.partition for t in batch] == [1, 2, 0]


def test_task_state_machine_rejects_illegal():
    t = ExecutionTask(0, TaskType.INTER_BROKER_REPLICA_ACTION,
                      prop(0, [0, 1], [0, 2]))
    with pytest.raises(ValueError):
        t.transition(TaskState.COMPLETED)  # PENDING -> COMPLETED illegal
    t.transition(TaskState.IN_PROGRESS)
    t.transition(TaskState.COMPLETED)
    with pytest.raises(ValueError):
        t.transition(TaskState.DEAD)


def test_end_to_end_optimizer_to_executor():
    """Full slice: random cluster -> greedy plan -> simulated execution ->
    final backend placement matches the optimizer's final state."""
    state = random_cluster(seed=51, num_brokers=8, num_racks=4, num_partitions=60)
    goals = make_goals()
    result = GoalOptimizer(goals).optimize(state)
    a = np.array(state.assignment)
    ls = np.array(state.leader_slot)
    assignment = {p: [int(b) for b in a[p] if b >= 0] for p in range(a.shape[0])}
    leaders = {p: int(a[p, ls[p]]) for p in range(a.shape[0])}
    backend = SimulatedClusterBackend(assignment, leaders)
    ex = Executor(backend)
    res = ex.execute_proposals(result.proposals)
    assert res.succeeded
    fa = np.array(result.final_state.assignment)
    fls = np.array(result.final_state.leader_slot)
    for p in range(fa.shape[0]):
        want = set(int(b) for b in fa[p] if b >= 0)
        assert set(backend.partitions[p].replicas) == want
        assert backend.partitions[p].leader == int(fa[p, fls[p]])

def test_stop_during_leader_phase():
    """stop_execution during the leader phase aborts pending leader tasks
    (code-review regression)."""
    backend, assignment, _ = make_backend(num_partitions=6)
    cfg = ExecutorConfig(num_concurrent_leader_movements=1)
    ex = Executor(backend, cfg)
    # leadership-only proposals; stop after the first election batch
    proposals = [
        prop(p, assignment[p], assignment[p], new_leader=assignment[p][1])
        for p in range(4)
    ]
    orig = backend.elect_leaders
    def hooked(elections):
        orig(elections)
        ex.stop_execution()
    backend.elect_leaders = hooked
    result = ex.execute_proposals(proposals)
    assert result.stopped
    assert result.aborted == 3 and result.completed == 1


def test_tick_budget_exhaustion_reports_failure():
    """Exhausting max_ticks must not report success: in-flight moves go DEAD,
    unstarted ones ABORTED (code-review regression)."""
    backend, assignment, _ = make_backend(move_latency_ticks=50)
    ex = Executor(backend)
    result = ex.execute_proposals([prop(0, assignment[0], [2, 3])], max_ticks=5)
    assert not result.succeeded
    # the replica move goes DEAD; so does the dependent leader election
    # (new leader never joined the ISR)
    assert result.dead == 2
    replica_states = {t.state for t in ex.planner.replica_tasks}
    assert replica_states == {TaskState.DEAD}


def test_max_inter_broker_moves_ceiling():
    """The safety ceiling aborts replica moves beyond the cap up front
    (code-review regression: field used to be unread)."""
    backend, assignment, _ = make_backend(num_partitions=6)
    cfg = ExecutorConfig(max_inter_broker_moves=2)
    ex = Executor(backend, cfg)
    # skip partition 2, whose assignment is already [2, 3] (no-op proposal)
    proposals = [prop(p, assignment[p], [2, 3]) for p in (0, 1, 3, 4)]
    result = ex.execute_proposals(proposals)
    assert not result.succeeded
    aborted = [t for t in ex.planner.replica_tasks if t.state == TaskState.ABORTED]
    done = [t for t in ex.planner.replica_tasks if t.state == TaskState.COMPLETED]
    assert len(aborted) == 2 and len(done) == 2


def test_alive_brokers_includes_empty_broker():
    """A live broker hosting zero replicas is still alive (code-review
    regression: liveness used to be inferred from placement)."""
    backend = SimulatedClusterBackend(
        {0: [0, 1]}, {0: 0}, brokers={0, 1, 2, 3}, failed_brokers={1}
    )
    assert backend.alive_brokers() == {0, 2, 3}


def test_device_model_tree_flatten_no_copy():
    """DeviceModel.tree_flatten must return array references, not copies
    (code-review regression: astuple deep-copied every array per round)."""
    import jax
    from cruise_control_tpu.analyzer.context import AnalyzerContext
    from cruise_control_tpu.analyzer.tpu_optimizer import TpuGoalOptimizer

    state = random_cluster(seed=3, num_brokers=4, num_racks=2, num_partitions=8)
    m = TpuGoalOptimizer()._device_model(AnalyzerContext(state))
    leaves, _ = jax.tree_util.tree_flatten(m)
    assert leaves[0] is m.assignment


def test_move_ceiling_respects_strategy_order():
    """The max_inter_broker_moves cap keeps the strategy's highest-priority
    moves, not raw insertion order (code-review regression)."""
    from cruise_control_tpu.executor.tasks import (
        PrioritizeSmallReplicaMovementStrategy,
    )
    backend, assignment, _ = make_backend(num_partitions=6)
    cfg = ExecutorConfig(max_inter_broker_moves=1)
    ex = Executor(backend, cfg)
    proposals = [prop(p, assignment[p], [2, 3]) for p in (0, 1)]
    sizes = {0: 500.0, 1: 5.0}  # partition 1 is the small (preferred) move
    ex.execute_proposals(
        proposals, strategy=PrioritizeSmallReplicaMovementStrategy(),
        partition_sizes=sizes,
    )
    by_p = {t.proposal.partition: t for t in ex.planner.replica_tasks}
    assert by_p[1].state == TaskState.COMPLETED
    assert by_p[0].state == TaskState.ABORTED


def test_throttles_exclude_aborted_moves():
    """Partitions whose moves were capped away are not throttled
    (code-review regression)."""
    backend, assignment, _ = make_backend(num_partitions=6)
    cfg = ExecutorConfig(max_inter_broker_moves=1, replication_throttle=1e6)
    ex = Executor(backend, cfg)
    proposals = [prop(p, assignment[p], [2, 3]) for p in (0, 1)]
    ex.execute_proposals(proposals)
    set_events = [e for e in backend.throttle_history if e[0] == "set"]
    assert set_events and len(backend.throttled_partitions) == 0  # cleared


def test_throttle_helper_sets_and_removes_dynamic_configs():
    """ReplicationThrottleHelper writes rate configs on participating brokers
    and throttled-replica lists on moving partitions, then removes exactly
    what it set — preserving a pre-existing user throttle."""
    from cruise_control_tpu.executor.throttle import (
        LEADER_RATE, ReplicationThrottleHelper,
    )

    backend, assignment, _ = make_backend(num_partitions=4)
    # user throttle on broker 0 must survive the execution
    backend.alter_config("broker", 0, {LEADER_RATE: "123"})
    cfg = ExecutorConfig(replication_throttle=5e6)
    ex = Executor(backend, cfg)
    p = prop(0, assignment[0], [assignment[0][0], 3])
    result = ex.execute_proposals([p])
    assert result.succeeded
    # helper cleaned up after itself...
    for (scope, ent), cfgs in backend.dynamic_configs.items():
        assert (scope, ent) == ("broker", 0), (scope, ent, cfgs)
    # ...but the user's pre-existing rate survived
    assert backend.describe_config("broker", 0) == {LEADER_RATE: "123"}


def test_throttle_configs_present_during_execution():
    from cruise_control_tpu.executor.throttle import (
        FOLLOWER_REPLICAS, LEADER_RATE, ReplicationThrottleHelper,
    )

    backend, assignment, _ = make_backend(num_partitions=4)
    helper = ReplicationThrottleHelper(backend, 7e6)
    p = prop(1, assignment[1], [assignment[1][0], 3])
    helper.set_throttles([p])
    assert backend.describe_config("broker", 3)[LEADER_RATE] == "7000000.0"
    assert backend.describe_config("partition", 1)[FOLLOWER_REPLICAS] == "3"
    helper.clear_throttles()
    assert not backend.dynamic_configs


def test_concurrency_adjuster_aimd():
    from cruise_control_tpu.executor.concurrency import ConcurrencyAdjuster

    adj = ConcurrencyAdjuster(initial_cap=4, min_cap=1, max_cap=8,
                              healthy_ticks_before_increase=2)
    assert adj.observe({10}) == 2      # stress → halve
    assert adj.observe({10}) == 1      # halve again, floored at min
    assert adj.observe({10}) == 1
    assert adj.observe(set()) == 1     # healthy streak building
    assert adj.observe(set()) == 2     # additive increase
    assert adj.observe(set()) == 2
    assert adj.observe(set()) == 3
    for _ in range(20):
        adj.observe(set())
    assert adj.cap == 8                # capped at ceiling


def test_executor_notifier_spi():
    from cruise_control_tpu.executor.notifier import ExecutorNotifier

    events = []

    class Spy(ExecutorNotifier):
        def on_execution_finished(self, result):
            events.append(("finished", result.completed))

        def on_execution_stopped(self, result):
            events.append(("stopped", result.completed))

    backend, assignment, _ = make_backend(num_partitions=4)
    ex = Executor(backend, notifier=Spy())
    p = prop(0, assignment[0], [assignment[0][0], 3])
    result = ex.execute_proposals([p])
    assert events == [("finished", result.completed)]


def test_detect_ongoing_at_startup_adopts_or_stops():
    """Upstream executor recovery: reassignments left by a dead instance are
    detected at startup and either surfaced (adopted) or cancelled."""
    from cruise_control_tpu.executor.backend import SimulatedClusterBackend
    from cruise_control_tpu.executor.executor import Executor

    backend = SimulatedClusterBackend(
        {0: [0, 1], 1: [1, 2]}, {0: 0, 1: 1}, brokers={0, 1, 2},
    )
    backend.alter_partition_reassignments({0: [0, 2]})
    ex = Executor(backend)
    assert ex.detect_ongoing_at_startup() == {0}
    assert ex.adopted_at_startup == {0}
    # stop=True cancels in the cluster
    assert ex.detect_ongoing_at_startup(stop=True) == {0}
    assert backend.ongoing_reassignments() == set()


def test_adopted_reassignments_gate_new_plans():
    """A new plan must be refused while reassignments adopted at startup are
    still in flight (conflicting targets otherwise), allowed again once they
    drain, and stop=True clears the gate immediately (nothing left in
    flight to adopt)."""
    import pytest

    from cruise_control_tpu.executor.backend import SimulatedClusterBackend
    from cruise_control_tpu.executor.executor import (
        Executor,
        OngoingExecutionError,
    )

    def fresh_backend():
        b = SimulatedClusterBackend(
            {0: [0, 1], 1: [1, 2]}, {0: 0, 1: 1}, brokers={0, 1, 2},
        )
        b.alter_partition_reassignments({0: [0, 2]})
        return b

    backend = fresh_backend()
    ex = Executor(backend)
    ex.detect_ongoing_at_startup()
    plan = [prop(1, [1, 2], [1, 0])]
    with pytest.raises(OngoingExecutionError, match="adopted at startup"):
        ex.execute_proposals(plan)
    # drain the adopted reassignment, then the same call succeeds
    while backend.ongoing_reassignments():
        backend.tick()
    result = ex.execute_proposals(plan)
    assert result.completed == 1
    assert ex.adopted_at_startup == set()

    # stop=True cancels in-cluster work: no gate, and state() has nothing
    # adopted to report
    backend2 = fresh_backend()
    ex2 = Executor(backend2)
    ex2.detect_ongoing_at_startup(stop=True)
    assert ex2.adopted_at_startup == set()
    assert ex2.execute_proposals(plan).completed == 1


def test_executor_scales_to_large_plans():
    """A north-star-scale plan (tens of thousands of proposals) must drive
    to completion in seconds, not minutes — the task planner, batcher, and
    simulated backend all stay vectorized/O(plan) (measured ~28k
    proposals/s; this guards against a quadratic regression)."""
    import time

    import numpy as np

    from cruise_control_tpu.executor.backend import SimulatedClusterBackend
    from cruise_control_tpu.executor.executor import Executor, ExecutorConfig
    from cruise_control_tpu.executor.tasks import ExecutionProposal

    rng = np.random.default_rng(0)
    B, P = 500, 20000
    assignment = {
        p: list(rng.choice(B, size=3, replace=False)) for p in range(P)
    }
    leaders = {p: assignment[p][0] for p in range(P)}
    backend = SimulatedClusterBackend(
        assignment, leaders, brokers=set(range(B))
    )
    props = []
    for p in range(0, P, 4):  # 5k proposals
        old = assignment[p]
        new = list(old)
        new[2] = int((old[2] + 1 + rng.integers(0, B - 3)) % B)
        while new[2] in old:
            new[2] = (new[2] + 1) % B
        props.append(ExecutionProposal(
            partition=p, topic=0, old_leader=old[0], new_leader=old[0],
            old_replicas=tuple(old), new_replicas=tuple(new)))

    ex = Executor(backend, config=ExecutorConfig(max_inter_broker_moves=10**6))
    t0 = time.perf_counter()
    result = ex.execute_proposals(props, max_ticks=10**6)
    dt = time.perf_counter() - t0
    assert result.completed == len(props)
    assert dt < 30.0, f"executor took {dt:.1f}s for {len(props)} proposals"


def test_broker_death_mid_execution_kills_tasks_then_self_heals():
    """Soak: a destination broker dies MID-execution — its in-flight moves
    go DEAD (not silently complete), the broker-failure detector sees the
    death, and self-healing evacuates the broker end-to-end."""
    from cruise_control_tpu.detector.anomalies import AnomalyType
    from cruise_control_tpu.detector.manager import make_detector_manager
    from tests.harness import full_stack
    from tests.test_detector import healing_notifier

    cc, backend, reporter = full_stack(
        num_partitions=12, num_brokers=4, rf=2, extra_brokers=(4,),
    )
    # retarget the backend's moves to take a while, and kill broker 3 two
    # ticks in: moves landing on 3 must die, the rest complete
    backend.move_latency_ticks = 4
    backend.kill_broker = 3
    backend.kill_at_tick = 2
    orig_tick = SimulatedClusterBackend.tick

    def tick(self):
        orig_tick(self)
        if self.ticks == self.kill_at_tick:
            self.failed_brokers.add(self.kill_broker)
    backend.tick = tick.__get__(backend)
    cc.executor.config.task_timeout_ticks = 6

    proposals = [
        # one move INTO the doomed broker, one into a healthy one
        ExecutionProposal(0, 0, 0, 0, tuple(backend.partitions[0].replicas),
                          (backend.partitions[0].replicas[0], 3)),
        ExecutionProposal(1, 0, 1, 1, tuple(backend.partitions[1].replicas),
                          (backend.partitions[1].replicas[0], 4)),
    ]
    result = cc.executor.execute_proposals(proposals, max_ticks=60)
    assert result.dead == 1 and result.completed >= 1, result
    assert 3 not in backend.partitions[1].replicas

    # upstream semantics: a DEAD task's reassignment stays in flight on
    # the cluster; cancel it (the stop/admin path) so the healing replan
    # starts from a settled placement
    backend.cancel_reassignments(list(backend.ongoing_reassignments()))
    # detector sees the death and self-healing evacuates broker 3
    mgr = make_detector_manager(
        cc, backend=backend,
        notifier=healing_notifier(broker_failure=True),
    )
    from tests.harness import WINDOW
    reporter.report(time_ms=4 * WINDOW + 500)
    cc.load_monitor.run_sampling_iteration(5 * WINDOW)
    handled = mgr.run_detection_cycle(now_ms=10)
    assert any(a.anomaly_type == AnomalyType.BROKER_FAILURE for a in handled)
    for p, st in backend.partitions.items():
        assert 3 not in st.replicas, (p, st)


# ---- crash-safe execution (ISSUE 7) ---------------------------------------------
def _crash_prop(p, old, new):
    return ExecutionProposal(
        partition=p, topic=0, old_leader=old[0], new_leader=new[0],
        old_replicas=tuple(old), new_replicas=tuple(new),
    )


def _crash_fixture():
    """Small deterministic plan over a 4-broker cluster: 3 replica moves
    (each with a leader change) at latency 2 — several checkpoint records
    per phase, several batches worth of boundaries."""
    assignment = {p: [(p + i) % 4 for i in range(2)] for p in range(6)}
    leaders = {p: assignment[p][0] for p in range(6)}
    backend = SimulatedClusterBackend(
        {p: list(r) for p, r in assignment.items()}, dict(leaders),
        move_latency_ticks=2,
    )
    plan = [_crash_prop(p, assignment[p], [2, 3]) for p in (0, 1, 4)]
    return backend, plan


def _placement(backend):
    return {
        p: (list(st.replicas), st.leader)
        for p, st in backend.partitions.items()
    }


def test_crash_consistency_at_every_checkpoint_boundary(tmp_path):
    """THE crash-consistency harness (ISSUE 7 satellite): kill the
    executor at EVERY checkpoint-write boundary of a small plan, recover
    with a fresh process, and assert reconciliation converges to the same
    final replica placement as the uninterrupted run.  A crash before the
    ``start`` record leaves nothing durable — the cluster is untouched
    and re-detection re-plans, which must converge too."""
    from cruise_control_tpu.executor.journal import (
        ExecutionJournal,
        ProcessCrash,
    )

    backend, plan = _crash_fixture()
    Executor(backend).execute_proposals(plan)
    reference = _placement(backend)

    path = str(tmp_path / "execution.ckpt.jsonl")
    boundaries = 0
    for n in range(0, 200):
        backend, plan = _crash_fixture()
        if os.path.exists(path):
            os.remove(path)
        journal = ExecutionJournal(path)
        journal.crash_after(n)
        ex = Executor(backend, journal=journal)
        try:
            ex.execute_proposals(plan)
            break  # n >= total records: the plan completed crash-free
        except ProcessCrash:
            boundaries += 1
        # the "restarted process": fresh executor, same checkpoint path
        recovered = ExecutionJournal(path)
        checkpoint = recovered.load()
        ex2 = Executor(backend, journal=recovered)
        if checkpoint is None:
            # crash before the start record: nothing durable, cluster
            # untouched — re-detection re-plans the same proposals
            assert not backend.ongoing_reassignments()
            result = ex2.execute_proposals(plan)
        else:
            result = ex2.resume(checkpoint)
        assert result.dead == 0 and result.aborted == 0, (n, result)
        assert _placement(backend) == reference, f"diverged at boundary {n}"
        assert recovered.load() is None, f"checkpoint not cleared at {n}"
    else:
        raise AssertionError("plan never completed without crashing")
    assert boundaries >= 6  # the fixture really has that many boundaries


def test_resume_never_removes_completed_partitions(tmp_path):
    """Recovery marks moves that finished (before or during the outage)
    COMPLETED and the resumed drive never re-issues them — asserted from
    the backend's observed alter calls."""
    from cruise_control_tpu.executor.journal import (
        ExecutionJournal,
        ProcessCrash,
    )

    backend, plan = _crash_fixture()
    path = str(tmp_path / "ckpt.jsonl")
    journal = ExecutionJournal(path)
    # crash right after the first batch's completions are recorded
    # (start, phase, batch, then task records)
    journal.crash_after(5)
    ex = Executor(backend, journal=journal)
    with pytest.raises(ProcessCrash):
        ex.execute_proposals(plan)
    completed_before = {
        p for p, st in backend.partitions.items()
        if [2, 3] == list(st.replicas)
    }
    assert completed_before  # the fixture crashes after real progress
    while backend.ongoing_reassignments():
        backend.tick()  # the cluster finishes in-flight work while down

    realtered = []
    original = backend.alter_partition_reassignments

    def spy(reassignments):
        realtered.extend(reassignments)
        original(reassignments)

    backend.alter_partition_reassignments = spy
    recovered = ExecutionJournal(path)
    ex2 = Executor(backend, journal=recovered)
    result = ex2.resume(recovered.load())
    assert result.dead == 0
    assert not (set(realtered) & completed_before), (
        realtered, completed_before)
    summary = ex2.state_summary()["recovery"]["lastRecovery"]
    assert summary["executionId"] == 1
    assert summary["alreadyCompleted"] + summary["completedWhileDown"] >= 1


def test_resume_replans_vanished_destination(tmp_path):
    """A destination broker that died during the outage is re-planned
    onto a live broker; the resumed execution completes."""
    from cruise_control_tpu.executor.journal import (
        ExecutionJournal,
        ProcessCrash,
    )

    backend, plan = _crash_fixture()
    backend.move_latency_ticks = 50  # nothing completes before the crash
    path = str(tmp_path / "ckpt.jsonl")
    journal = ExecutionJournal(path)
    # start, phase, batch persist; the 4th write (the first timeout's task
    # record, task_timeout=3) crashes — moves are dispatched and in flight
    journal.crash_after(3)
    ex = Executor(backend, journal=journal,
                  config=ExecutorConfig(task_timeout_ticks=3))
    with pytest.raises(ProcessCrash):
        ex.execute_proposals(plan)
    assert backend.ongoing_reassignments()  # really crashed mid-flight
    backend.failed_brokers.add(3)  # destination 3 dies while we are down

    recovered = ExecutionJournal(path)
    ex2 = Executor(backend, journal=recovered)
    backend.move_latency_ticks = 1
    result = ex2.resume(recovered.load())
    assert result.dead == 0 and result.completed > 0
    for p in (0, 1, 4):
        assert 3 not in backend.partitions[p].replicas
        assert 2 in backend.partitions[p].replicas
    summary = ex2.state_summary()["recovery"]["lastRecovery"]
    assert summary["replanned"] == 3


def test_retry_with_backoff_recovers_transient_failure():
    """A move that times out while its destination is down is retried
    with exponential backoff and completes once the broker returns."""
    backend, assignment, _ = make_backend(move_latency_ticks=1)
    backend.failed_brokers.add(3)
    revive_at = {"tick": 12}
    orig_tick = backend.tick

    def tick():
        orig_tick()
        if backend.ticks >= revive_at["tick"]:
            backend.failed_brokers.discard(3)
    backend.tick = tick
    cfg = ExecutorConfig(
        task_timeout_ticks=3,
        task_retry_max_attempts=4,
        task_retry_backoff_base_ticks=2,
        task_retry_backoff_max_ticks=16,
        task_retry_jitter_ticks=0,
    )
    ex = Executor(backend, cfg)
    p = prop(0, assignment[0], [assignment[0][0], 3])
    result = ex.execute_proposals([p], max_ticks=200)
    assert result.succeeded, result
    task = ex.planner.replica_tasks[0]
    assert task.attempts >= 1  # it really went through the retry path
    assert 3 in backend.partitions[0].replicas


def test_retry_budget_exhaustion_goes_dead():
    """The retry budget is a bound: a permanently failing destination
    exhausts it and the task lands DEAD, not in an endless loop."""
    backend, assignment, _ = make_backend(failed_brokers={3})
    cfg = ExecutorConfig(
        task_timeout_ticks=2,
        task_retry_max_attempts=2,
        task_retry_backoff_base_ticks=1,
        task_retry_backoff_max_ticks=2,
        task_retry_jitter_ticks=0,
    )
    ex = Executor(backend, cfg)
    p = prop(0, assignment[0], [assignment[0][0], 3])
    result = ex.execute_proposals([p], max_ticks=200)
    assert result.dead == 1
    assert ex.planner.replica_tasks[0].attempts == 2


def test_dest_exclusion_feeds_replanning():
    """Repeated failures charge the destination; once excluded, later
    dispatches re-plan onto a different broker and succeed."""
    backend, assignment, _ = make_backend(
        num_partitions=8, failed_brokers={3}
    )
    cfg = ExecutorConfig(
        task_timeout_ticks=2,
        task_retry_max_attempts=3,
        task_retry_backoff_base_ticks=1,
        task_retry_backoff_max_ticks=2,
        task_retry_jitter_ticks=0,
        dest_exclusion_threshold=2,
    )
    ex = Executor(backend, cfg)
    p = prop(0, assignment[0], [assignment[0][0], 3])
    result = ex.execute_proposals([p], max_ticks=200)
    # after 2 failures broker 3 is excluded; the next retry re-plans and
    # the move completes elsewhere
    assert result.succeeded, result
    assert 3 in ex.excluded_destinations
    assert 3 not in backend.partitions[0].replicas
    assert ex.state_summary()["retries"]["excludedDestinations"] == [3]


def test_watchdog_escalates_stop_abort_unrecoverable():
    """With every destination dead and no retry budget... the watchdog
    first halts dispatch, then aborts in-flight moves instead of burning
    the full tick budget."""
    backend, assignment, _ = make_backend(failed_brokers={3})
    cfg = ExecutorConfig(
        task_timeout_ticks=10_000,  # timeouts never fire: watchdog must
        watchdog_stuck_ticks=5,
    )
    ex = Executor(backend, cfg)
    p = prop(0, assignment[0], [assignment[0][0], 3])
    result = ex.execute_proposals([p], max_ticks=10_000)
    assert not result.succeeded
    assert result.dead == 1
    assert result.ticks <= 12  # 2 * watchdog + slack, NOT the tick budget
    # the aborted reassignment was cancelled on the backend
    assert not backend.ongoing_reassignments()


def test_checkpoint_compaction_preserves_recovery(tmp_path):
    """Rotation (max_bytes exceeded) compacts to a snapshot atomically;
    a crash after compaction still recovers the full picture."""
    from cruise_control_tpu.executor.journal import (
        ExecutionJournal,
        ProcessCrash,
    )

    def fixture():
        assignment = {
            p: [(p + i) % 4 for i in range(2)] for p in range(48)
        }
        leaders = {p: assignment[p][0] for p in range(48)}
        b = SimulatedClusterBackend(
            {p: list(r) for p, r in assignment.items()}, dict(leaders),
            move_latency_ticks=2,
        )
        # p % 4 in (0, 1): old replicas differ from [2, 3] → real moves
        return b, [_crash_prop(p, assignment[p], [2, 3])
                   for p in range(48) if p % 4 < 2]

    backend, plan = fixture()
    path = str(tmp_path / "ckpt.jsonl")
    journal = ExecutionJournal(path, max_bytes=1024)  # rotate constantly
    compactions = {"n": 0}
    orig_compact = journal._compact

    def counting_compact():
        compactions["n"] += 1
        orig_compact()

    journal._compact = counting_compact
    journal.crash_after(12)
    ex = Executor(backend, journal=journal)
    with pytest.raises(ProcessCrash):
        ex.execute_proposals(plan)
    assert compactions["n"] >= 1, "fixture never rotated the checkpoint"
    recovered = ExecutionJournal(path)
    checkpoint = recovered.load()
    assert checkpoint is not None
    assert len(checkpoint.proposals) == len(plan)
    result = Executor(backend, journal=recovered).resume(checkpoint)
    assert result.dead == 0

    reference_backend, reference_plan = fixture()
    Executor(reference_backend).execute_proposals(reference_plan)
    assert _placement(backend) == _placement(reference_backend)


def test_min_isr_strategy_prioritizes_urp_fixes_end_to_end():
    """PrioritizeMinIsrWithOfflineReplicas orders under-replicated fixes
    first through the live planner (not just the sort key)."""
    backend = SimulatedClusterBackend(
        {p: [p % 3, (p + 1) % 3] for p in range(6)},
        {p: p % 3 for p in range(6)},
        brokers={0, 1, 2, 3},
    )
    # partition 5 is under-replicated (catching up)
    backend.partitions[5].catching_up.add((5 + 1) % 3)
    ex = Executor(backend, ExecutorConfig(
        num_concurrent_partition_movements_per_broker=1,
    ), default_strategy=PrioritizeMinIsrWithOfflineReplicasStrategy())
    proposals = [
        ExecutionProposal(p, 0, p % 3, p % 3,
                          tuple(backend.partitions[p].replicas),
                          (p % 3, 3))
        for p in (2, 5)
    ]
    planner = ExecutionTaskPlanner(ex.default_strategy)
    planner.add_proposals(proposals)
    ordered = planner.strategy.order(
        planner.replica_tasks, {}, backend.under_replicated_partitions()
    )
    assert ordered[0].proposal.partition == 5  # URP fix first
    result = ex.execute_proposals(proposals, max_ticks=60)
    assert result.succeeded
