#!/usr/bin/env bash
# Standalone server launcher (upstream kafka-cruise-control-start.sh).
# Usage: bin/cruise-control-start.sh [config/cruisecontrol.properties] [port]
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m cruise_control_tpu "${1:-config/cruisecontrol.properties}" "${@:2}"
