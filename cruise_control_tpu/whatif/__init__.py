"""Counterfactual what-if engine (ISSUE 16).

Compiles hypothetical futures — broker/rack loss, traffic ×k, planned
maintenance, topic growth, expressed in the timeline-DSL vocabulary —
into perturbed device-model batches, evaluates every future in ONE
batched device dispatch (a vmapped verdict kernel over a stacked
leading futures axis, padded to a power of two so request sizes share
compiled executables), and feeds the same machinery forward: the
precompute daemon keeps the top-k likely futures warm, and the
proactive scheduler projects the workload's diurnal peak and rebalances
*before* the projected breach (``whatif.*`` / ``proactive.*`` journal
kinds; ``POST /whatif``; ``docs/ARCHITECTURE.md`` "Counterfactual
what-if engine").
"""

from cruise_control_tpu.whatif.cache import WhatifCache
from cruise_control_tpu.whatif.compiler import FutureBatch, compile_futures
from cruise_control_tpu.whatif.engine import evaluate_batch, verdicts
from cruise_control_tpu.whatif.futures import (
    FutureEvent,
    FutureSpec,
    broker_loss,
    hot_partitions,
    likely_futures,
    maintenance,
    parse_future,
    rack_loss,
    topic_growth,
    traffic_scale,
)
from cruise_control_tpu.whatif.proactive import ProactiveScheduler

__all__ = [
    "FutureBatch",
    "FutureEvent",
    "FutureSpec",
    "ProactiveScheduler",
    "WhatifCache",
    "broker_loss",
    "compile_futures",
    "evaluate_batch",
    "hot_partitions",
    "likely_futures",
    "maintenance",
    "parse_future",
    "rack_loss",
    "topic_growth",
    "traffic_scale",
    "verdicts",
]
