"""Per-future verdict cache, keyed ``model_generation × fingerprint``.

The key IS the staleness story: a verdict computed against generation
``w3.e1000`` can never answer for ``w4.e1000`` — :meth:`get` misses on a
generation bump without any TTL bookkeeping.  Invalidation (anomaly,
execution, explicit) additionally *drops* entries: unlike the warm plan
— which degrades to a marked-stale answer — a stale counterfactual has
no degraded-serving value, it is simply wrong.

``fresh_for(generation)`` is the precompute daemon's probe (the
satellite-2 fix): True only while the warm set was filled at exactly the
probed generation and nothing invalidated it since — so a
model-generation bump wakes the daemon to re-evaluate the top-k futures
alongside the warm plan.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional, Tuple


class WhatifCache:
    """Bounded, thread-safe verdict store (FIFO eviction)."""

    def __init__(self, max_entries: int = 256):
        self.max_entries = max(1, int(max_entries))
        self._entries: "OrderedDict[Tuple[str, str], dict]" = OrderedDict()
        self._lock = threading.Lock()
        #: generation the warm (precomputed) set was filled at; None =
        #: never filled or invalidated since
        self._warm_generation: Optional[str] = None
        self._last_invalidated: Optional[str] = None
        self.hits = 0
        self.misses = 0

    def get(self, generation: str, fingerprint: str) -> Optional[dict]:
        with self._lock:
            verdict = self._entries.get((generation, fingerprint))
            if verdict is None:
                self.misses += 1
                return None
            self.hits += 1
            return dict(verdict)

    def put(self, generation: str, fingerprint: str, verdict: dict) -> None:
        with self._lock:
            self._entries[(generation, fingerprint)] = dict(verdict)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)

    def mark_warm(self, generation: str) -> None:
        """The precompute daemon filled its top-k set at ``generation``."""
        with self._lock:
            self._warm_generation = generation

    def fresh_for(self, generation: str) -> bool:
        with self._lock:
            return (self._warm_generation is not None
                    and self._warm_generation == generation)

    def invalidate(self, reason: str = "invalidated") -> None:
        """Drop everything: a stale counterfactual must never serve."""
        with self._lock:
            self._entries.clear()
            self._warm_generation = None
            self._last_invalidated = reason

    def state_summary(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "warmGeneration": self._warm_generation,
                "lastInvalidated": self._last_invalidated,
                "hits": self.hits,
                "misses": self.misses,
            }
