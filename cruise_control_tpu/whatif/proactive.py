"""Forecast-driven proactive control — rebalance BEFORE the peak.

The reactive loop heals after a fault: detect → fix → settle.  This
scheduler closes the other half of ROADMAP item 5: it fits the workload
synthesizer's own diurnal model (:func:`sim.workload.fit_diurnal`) to
observed load samples, projects the next peak inside its horizon, asks
the what-if engine whether the cluster SURVIVES that peak (a
``traffic_scale`` future at the projected multiplier), and — when the
verdict says a goal breaks — triggers a full rebalance while there is
still headroom, journaled as ``proactive.*`` so an operator can
reconstruct why the cluster moved with no anomaly in sight.

Clock discipline: every decision takes ``now_ms`` (the sim drives a
virtual clock); production wiring injects nothing and the guarded
fallback reads wall time.  Skip decisions are journaled once per reason
change, not per tick — the journal records decisions, not idling.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

from cruise_control_tpu.telemetry import events
from cruise_control_tpu.utils.logging import get_logger
from cruise_control_tpu.whatif.futures import FutureSpec, traffic_scale

LOG = get_logger("whatif.proactive")


class ProactiveScheduler:
    """Projects the diurnal peak and pre-empts the breach.

    ``clock`` (→ milliseconds) makes every decision virtual-clock
    drivable; ``sample_fn`` is the production pull source (the sim
    pushes via :meth:`record` instead).
    """

    def __init__(
        self,
        cc,
        period_ms: int,
        horizon_ms: int = 3_600_000,
        threshold: float = 1.1,
        cooldown_ms: int = 1_800_000,
        min_samples: int = 8,
        max_samples: int = 512,
        sample_fn: Optional[Callable[[], float]] = None,
        clock: Optional[Callable[[], float]] = None,
    ):
        self.cc = cc
        self.period_ms = max(1, int(period_ms))
        self.horizon_ms = max(1, int(horizon_ms))
        self.threshold = float(threshold)
        self.cooldown_ms = max(0, int(cooldown_ms))
        self.min_samples = max(4, int(min_samples))
        self._samples: deque = deque(maxlen=max(8, int(max_samples)))
        self._sample_fn = sample_fn
        self._clock = clock
        self._last_trigger_ms: Optional[float] = None
        self._last_skip_reason: Optional[str] = None
        self.triggers = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ---- clock + samples --------------------------------------------------------
    def _now_ms(self) -> float:
        if self._clock is not None:
            return float(self._clock())
        return time.time() * 1000.0

    def record(self, now_ms: float, value: float) -> None:
        """Feed one observed ``(time, total load)`` sample."""
        self._samples.append((float(now_ms), float(value)))

    # ---- the decision -----------------------------------------------------------
    def _skip(self, reason: str) -> None:
        # journal transitions, not idle ticks: a 500-tick quiet stretch
        # is one record, and the fingerprint stays insensitive to length
        if reason != self._last_skip_reason:
            events.emit("proactive.skip", reason=reason)
            self._last_skip_reason = reason

    def maybe_trigger(self, now_ms: Optional[float] = None) -> bool:
        """One scheduling decision at ``now_ms``; True = a proactive
        rebalance was kicked off."""
        # import at use-site: the forecast API lives next to the workload
        # synthesizer it mirrors (sim/workload.py), and sim's package
        # import closes a cycle through the facade at module-import time
        from cruise_control_tpu.sim.workload import fit_diurnal

        now_ms = self._now_ms() if now_ms is None else float(now_ms)
        if len(self._samples) < self.min_samples:
            self._skip("insufficient-samples")
            return False
        forecast = fit_diurnal(list(self._samples), self.period_ms)
        if forecast is None or forecast.amplitude < 1e-6:
            self._skip("no-diurnal-signal")
            return False
        peak_t, peak_mult = forecast.peak_within(now_ms, self.horizon_ms)
        now_mult = forecast.multiplier_at(now_ms)
        ratio = peak_mult / max(now_mult, 1e-9)
        if ratio < self.threshold:
            self._skip("peak-below-threshold")
            return False
        if self._last_trigger_ms is not None and \
                now_ms - self._last_trigger_ms < self.cooldown_ms:
            self._skip("cooldown")
            return False
        factor = round(ratio, 4)
        peak_in_ms = int(round(peak_t - now_ms))
        events.emit(
            "proactive.forecast",
            peakMultiplier=round(peak_mult, 4), peakInMs=peak_in_ms,
            amplitude=round(forecast.amplitude, 4),
            samples=len(self._samples),
        )
        future = FutureSpec(
            name="projected-peak", events=(traffic_scale(factor),),
            horizon_ms=self.horizon_ms,
        )
        try:
            result = self.cc.whatif([future])
        except Exception as e:
            LOG.warning("proactive what-if failed: %r", e)
            self._skip("whatif-failed")
            return False
        v = result.verdicts[0]
        if v["survivable"] and v["goalViolations"] == 0:
            self._skip("peak-survivable")
            return False
        reason = (
            "projected-unavailability" if not v["survivable"]
            else "projected-goal-violation"
        )
        events.emit(
            "proactive.trigger", severity="WARNING", reason=reason,
            peakInMs=peak_in_ms, peakMultiplier=round(peak_mult, 4),
            overloadedBrokers=v["overloadedBrokers"],
            unavailablePartitions=v["unavailablePartitions"],
        )
        self._last_trigger_ms = now_ms
        self._last_skip_reason = None
        self.triggers += 1
        try:
            self.cc.rebalance(dryrun=False)
        except Exception as e:
            # the trigger stands in the journal; the failed attempt is
            # the analyzer's story (breaker, degradation) — retry lands
            # after the cooldown
            LOG.warning("proactive rebalance failed: %r", e)
            self._skip("rebalance-failed")
            return False
        return True

    def tick(self) -> bool:
        """Pull one sample (production mode) and decide."""
        if self._sample_fn is not None:
            try:
                value = float(self._sample_fn())
            except Exception as e:
                LOG.debug("proactive sample pull failed: %r", e)
                self._skip("sample-unavailable")
                return False
            self.record(self._now_ms(), value)
        return self.maybe_trigger()

    # ---- production daemon ------------------------------------------------------
    def start(self, interval_s: float = 60.0) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.tick()
                except Exception as e:  # the daemon must outlive one bad tick
                    LOG.warning("proactive tick failed: %r", e)

        self._thread = threading.Thread(
            target=loop, name="whatif-proactive", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._thread = None

    def state_summary(self) -> dict:
        return {
            "samples": len(self._samples),
            "triggers": self.triggers,
            "lastSkipReason": self._last_skip_reason,
            "running": self._thread is not None,
        }
