"""The batched verdict evaluator — N futures, ONE device dispatch.

One jitted program: a per-future verdict kernel vmapped over the stacked
``(dead[N, B], scale[N, P])`` perturbation axis the compiler built.  The
base model arrays enter unbatched (``in_axes=None``) so XLA hoists them
— N futures share every gather/one-hot the kernel builds from the
placement.  Shapes are static per ``(P, S, B)`` × futures-bucket, so a
cluster sees a handful of executables over its whole lifetime (the PR-9
bucketing contract extended to the futures axis).

A verdict is *dry-run semantics*, not a plan search: survivability
(every partition keeps ≥1 live replica; aggregate load still fits the
surviving capacity), goal-violation counts (per-broker capacity
breaches, rack co-location after loss), the projected plan cost of
healing the future (replica + leadership moves, data to shuttle), and
the top suggested actions.  That is what makes N=64 futures affordable
in well under one plan search's wall time.
"""

from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.whatif.compiler import FutureBatch

#: suggested actions returned per future
TOP_ACTIONS = 4

#: resources a traffic multiplier applies to (rates); DISK is an
#: integral, not a rate — the workload synthesizer's rule
_RATE_MASK = (1.0, 1.0, 1.0, 0.0)


def _verdict_one(assignment, leader_slot, leader_load, follower_load,
                 capacity, rack, alive0, dead, scale):
    """Verdict for ONE future; vmapped over ``(dead, scale)``."""
    P, S = assignment.shape
    B = capacity.shape[0]
    exists = assignment >= 0                      # [P, S]
    bid = jnp.clip(assignment, 0)                 # [P, S]
    alive = alive0 & ~dead                        # [B]
    slot_alive = exists & alive[bid]              # [P, S]
    rf = exists.sum(axis=1)                       # [P]
    alive_replicas = slot_alive.sum(axis=1)       # [P]
    has = rf > 0
    unavailable = jnp.sum(has & (alive_replicas == 0))
    under_replicated = jnp.sum(
        has & (alive_replicas > 0) & (alive_replicas < rf)
    )

    rmask = jnp.asarray(_RATE_MASK, jnp.float32)
    lscale = 1.0 + (scale[:, None] - 1.0) * rmask[None, :]   # [P, R]
    lead = leader_load * lscale
    fol = follower_load * lscale
    is_lead = jnp.arange(S)[None, :] == leader_slot[:, None]  # [P, S]
    slot_load = jnp.where(
        is_lead[..., None], lead[:, None, :], fol[:, None, :]
    ) * exists[..., None]                          # [P, S, R]

    # hosted load per SURVIVING broker (dead/empty slots fall into the
    # overflow segment B and are dropped)
    seg = jnp.where(slot_alive, bid, B).reshape(-1)
    hosted = jax.ops.segment_sum(
        slot_load.reshape(P * S, -1), seg, num_segments=B + 1
    )[:B]                                          # [B, R]

    total = slot_load.sum(axis=(0, 1))             # [R] incl. orphaned load
    cap_alive = jnp.sum(capacity * alive[:, None], axis=0)
    infeasible = jnp.any(total > cap_alive)
    over = jnp.any(hosted > capacity, axis=1) & alive
    overloaded = jnp.sum(over)

    # rack co-location among SURVIVING replicas (S is small: pairwise)
    rk = jnp.where(slot_alive, rack[bid], -1 - jnp.arange(S)[None, :])
    dup = jnp.zeros(P, bool)
    for i in range(S):
        for j in range(i + 1, S):
            dup = dup | (
                slot_alive[:, i] & slot_alive[:, j]
                & (rk[:, i] == rk[:, j])
            )
    rack_violations = jnp.sum(dup)

    offline = exists & ~slot_alive                 # [P, S] replicas to re-place
    moves = jnp.sum(offline)
    leader_dead = ~jnp.take_along_axis(
        slot_alive, leader_slot[:, None], axis=1
    )[:, 0] & has
    leadership_moves = jnp.sum(leader_dead)
    data_move_mb = jnp.sum(
        slot_load[:, :, Resource.DISK] * offline
    )

    # top suggested actions: the heaviest replicas needing re-placement
    # (by data to move, then ingress), all pointed at the least utilized
    # surviving broker — advisory, the real plan search refines this
    prio = offline * (
        slot_load[:, :, Resource.DISK]
        + slot_load[:, :, Resource.NW_IN]
        + 1.0
    )
    top_val, top_idx = jax.lax.top_k(prio.reshape(-1), TOP_ACTIONS)
    top_part = (top_idx // S).astype(jnp.int32)
    top_src = bid.reshape(-1)[top_idx].astype(jnp.int32)
    util = jnp.max(
        hosted / jnp.maximum(capacity, 1e-9), axis=1
    )
    util = jnp.where(alive, util, jnp.inf)
    dst = jnp.argmin(util).astype(jnp.int32)
    top_part = jnp.where(top_val > 0, top_part, -1)
    top_src = jnp.where(top_val > 0, top_src, -1)

    survivable = (unavailable == 0) & ~infeasible
    return {
        "survivable": survivable,
        "unavailablePartitions": unavailable.astype(jnp.int32),
        "underReplicated": under_replicated.astype(jnp.int32),
        "capacityInfeasible": infeasible,
        "overloadedBrokers": overloaded.astype(jnp.int32),
        "rackViolations": rack_violations.astype(jnp.int32),
        "movesRequired": moves.astype(jnp.int32),
        "leadershipMoves": leadership_moves.astype(jnp.int32),
        "dataMoveMB": data_move_mb.astype(jnp.float32),
        "maxBrokerUtilization": jnp.max(
            jnp.where(alive, jnp.max(
                hosted / jnp.maximum(capacity, 1e-9), axis=1
            ), 0.0)
        ).astype(jnp.float32),
        "topActionPartition": top_part,
        "topActionSource": top_src,
        "topActionDestination": jnp.full(TOP_ACTIONS, dst, jnp.int32),
    }


_EVALUATE = jax.jit(jax.vmap(
    _verdict_one,
    in_axes=(None, None, None, None, None, None, None, 0, 0),
))


def evaluate_batch(state, batch: FutureBatch,
                   capacity_scale=None) -> Dict[str, np.ndarray]:
    """Evaluate every future in ``batch`` in ONE batched dispatch.

    ``capacity_scale`` is an optional per-resource usable-fraction vector
    (the analyzer's capacity thresholds) applied to ``broker_capacity``
    before evaluation, so overload/infeasibility verdicts share the
    capacity goals' bar instead of raw hardware limits.

    Returns the stacked raw verdict arrays (padded rows included — use
    :func:`verdicts` for the per-future JSON view)."""
    capacity = np.asarray(state.broker_capacity, np.float32)
    if capacity_scale is not None:
        capacity = capacity * np.asarray(capacity_scale,
                                         np.float32)[None, :]
    out = _EVALUATE(
        jnp.asarray(state.assignment),
        jnp.asarray(state.leader_slot),
        jnp.asarray(state.leader_load, jnp.float32),
        jnp.asarray(state.follower_load, jnp.float32),
        jnp.asarray(capacity),
        jnp.asarray(state.broker_rack),
        jnp.asarray(state.broker_alive()),
        jnp.asarray(batch.dead),
        jnp.asarray(batch.scale),
    )
    return {k: np.asarray(v) for k, v in out.items()}


def verdicts(batch: FutureBatch,
             raw: Dict[str, np.ndarray]) -> List[dict]:
    """Per-future JSON verdicts (valid rows only, padding dropped)."""
    out = []
    for i, future in enumerate(batch.futures):
        actions = []
        for k in range(TOP_ACTIONS):
            p = int(raw["topActionPartition"][i, k])
            if p < 0:
                continue
            actions.append({
                "partition": p,
                "from": int(raw["topActionSource"][i, k]),
                "to": int(raw["topActionDestination"][i, k]),
            })
        out.append({
            "future": future.name,
            "fingerprint": future.fingerprint(),
            "horizonMs": int(future.horizon_ms),
            "survivable": bool(raw["survivable"][i]),
            "unavailablePartitions": int(raw["unavailablePartitions"][i]),
            "underReplicated": int(raw["underReplicated"][i]),
            "capacityInfeasible": bool(raw["capacityInfeasible"][i]),
            "overloadedBrokers": int(raw["overloadedBrokers"][i]),
            "rackViolations": int(raw["rackViolations"][i]),
            "goalViolations": int(raw["overloadedBrokers"][i])
            + int(raw["rackViolations"][i]),
            "movesRequired": int(raw["movesRequired"][i]),
            "leadershipMoves": int(raw["leadershipMoves"][i]),
            "dataMoveMB": round(float(raw["dataMoveMB"][i]), 3),
            "maxBrokerUtilization": round(
                float(raw["maxBrokerUtilization"][i]), 4
            ),
            "topActions": actions,
        })
    return out
