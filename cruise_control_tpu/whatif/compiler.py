"""Future → perturbation compiler.

Lowers a list of :class:`FutureSpec`\\ s against one built model into two
dense perturbation arrays the batched evaluator consumes:

* ``dead[N, B]``  — brokers offline in that future (loss, rack loss,
  maintenance),
* ``scale[N, P]`` — per-partition traffic multiplier (traffic ×k, topic
  growth, hot partitions); rates only — the evaluator applies it to
  CPU/NW and leaves DISK alone, matching the workload synthesizer's
  "disk is an integral" rule.

The futures axis is padded to a power of two (``valid`` masks the tail)
— the PR-9 bucketing discipline applied to a new axis: every request
size in a bucket shares one compiled executable, so an operator's ad-hoc
3-future query rides the same program as the daemon's precomputed 8.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence, Tuple

import numpy as np

from cruise_control_tpu.whatif.futures import FutureSpec

#: smallest futures-axis bucket; buckets go 8, 16, 32, … so the compiled
#: program count stays O(log N) across every request mix
MIN_BUCKET = 8


def bucket_size(n: int) -> int:
    """Next power-of-two bucket ≥ ``n`` (≥ :data:`MIN_BUCKET`)."""
    n2 = MIN_BUCKET
    while n2 < n:
        n2 <<= 1
    return n2


@dataclasses.dataclass(frozen=True)
class FutureBatch:
    """Compiled perturbations for one batched dispatch."""

    futures: Tuple[FutureSpec, ...]
    dead: np.ndarray   # bool [N2, B]
    scale: np.ndarray  # f32  [N2, P]
    valid: np.ndarray  # bool [N2]

    @property
    def num_futures(self) -> int:
        return len(self.futures)

    @property
    def padded_size(self) -> int:
        return int(self.dead.shape[0])


def _topic_id(state, topic) -> int:
    if isinstance(topic, str):
        names = state.topic_names
        if topic in names:
            return names.index(topic)
        raise ValueError(f"unknown topic {topic!r}")
    t = int(topic)
    if not 0 <= t < max(1, state.num_topics):
        raise ValueError(f"topic id {t} out of range")
    return t


def _compile_one(state, future: FutureSpec, dead: np.ndarray,
                 scale: np.ndarray) -> None:
    """Fold one future's events into its ``dead[B]`` / ``scale[P]`` rows
    (events compound: two ×2 traffic events make ×4)."""
    racks = np.asarray(state.broker_rack)
    topics = np.asarray(state.partition_topic)
    B = dead.shape[0]
    for ev in future.events:
        if ev.kind == "kill_broker":
            b = int(ev.arg("broker"))
            if not 0 <= b < B:
                raise ValueError(f"broker {b} out of range")
            dead[b] = True
        elif ev.kind == "rack_loss":
            r = int(ev.arg("rack"))
            hit = racks == r
            if not hit.any():
                raise ValueError(f"no brokers on rack {r}")
            dead[hit] = True
        elif ev.kind == "maintenance_event":
            for b in ev.arg("brokers"):
                b = int(b)
                if not 0 <= b < B:
                    raise ValueError(f"broker {b} out of range")
                dead[b] = True
        elif ev.kind == "traffic_scale":
            scale *= float(ev.arg("factor"))
        elif ev.kind == "topic_growth":
            t = _topic_id(state, ev.arg("topic"))
            scale[topics == t] *= float(ev.arg("factor"))
        elif ev.kind == "hot_partition_skew":
            idx = np.asarray([int(p) for p in ev.arg("partitions")], int)
            if idx.size and (idx.min() < 0 or idx.max() >= scale.shape[0]):
                raise ValueError("hot_partition_skew partition out of range")
            scale[idx] *= float(ev.arg("factor"))
        else:
            raise ValueError(f"unknown future event kind {ev.kind!r}")


def compile_futures(state, futures: Sequence[FutureSpec]) -> FutureBatch:
    """Lower ``futures`` against ``state`` into one padded batch."""
    futures = tuple(futures)
    if not futures:
        raise ValueError("compile_futures needs at least one future")
    n = len(futures)
    n2 = bucket_size(n)
    B = state.num_brokers
    P = state.num_partitions
    dead = np.zeros((n2, B), bool)
    scale = np.ones((n2, P), np.float32)
    valid = np.zeros(n2, bool)
    for i, f in enumerate(futures):
        _compile_one(state, f, dead[i], scale[i])
        valid[i] = True
    return FutureBatch(futures=futures, dead=dead, scale=scale, valid=valid)
