"""``python -m cruise_control_tpu.whatif --artifact WHATIF_r16.json`` —
run the what-if subsystem's two gated measurements (the N≥64 batched
sweep and the proactive-vs-reactive scenario twins) and write/print the
``cc-tpu-whatif/1`` artifact.  Exits 1 when any gate fails."""

from __future__ import annotations

import argparse
import json
import sys

from cruise_control_tpu.whatif.artifact import (
    MIN_FUTURES,
    make_artifact,
    measure_batch,
    measure_proactive,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m cruise_control_tpu.whatif",
        description="what-if subsystem artifact (cc-tpu-whatif/1)",
    )
    parser.add_argument("--artifact", metavar="PATH",
                        help="write the artifact JSON here")
    parser.add_argument("--futures", type=int, default=MIN_FUTURES,
                        help="batched sweep size (default %(default)s)")
    parser.add_argument("--best-of", type=int, default=3,
                        help="timing repetitions (default %(default)s)")
    args = parser.parse_args(argv)

    batch = measure_batch(num_futures=args.futures, best_of=args.best_of)
    proactive = measure_proactive()
    art = make_artifact(batch, proactive)
    blob = json.dumps(art, indent=1, sort_keys=True)
    if args.artifact:
        with open(args.artifact, "w") as f:
            f.write(blob + "\n")
        print(f"artifact written: {args.artifact}")
    else:
        print(blob)
    for gate, ok in sorted(art["gates"].items()):
        print(f"  {'PASS' if ok else 'FAIL'} {gate}")
    return 0 if art["allOk"] else 1


if __name__ == "__main__":
    sys.exit(main())
