"""The ``cc-tpu-whatif/1`` artifact — the subsystem's two headline
claims, measured and gated.

**Batch**: N ≥ 64 futures — every rack loss, every broker loss, a ladder
of traffic multipliers, maintenance pairs — compiled against the
50-broker/1000-partition bench fixture and evaluated in ONE batched
device dispatch; the wall cost must stay under 2× a single TPU plan
search on the same model (``batchRatioUnder2x``).  That ratio is the
whole point of the vmapped verdict kernel: an operator buys a complete
survivability sweep for less than two plan searches.

**Proactive**: the ``proactive_beats_reactive_peak`` scenario run twice
— forecast-driven proactive control ON, then its reactive twin (same
seed, same timeline, proactive off).  The proactive run must end with
zero detector anomalies and zero reactive fixes (the rebalance landed
before the breach), and its heal p99 must beat the reactive twin's
(``proactiveBeatsReactiveHealP99``).

The checked-in contract lives in ``tests/schemas/artifacts.schema.json``
(closed records — field drift fails CI); the committed instance is
``WHATIF_r16.json``, regenerated via
``python -m cruise_control_tpu.whatif --artifact WHATIF_r16.json``.
"""

from __future__ import annotations

import dataclasses
import time
from typing import List, Optional

import numpy as np

from cruise_control_tpu.whatif.compiler import compile_futures
from cruise_control_tpu.whatif.engine import evaluate_batch, verdicts
from cruise_control_tpu.whatif.futures import (
    FutureSpec,
    likely_futures,
    maintenance,
    traffic_scale,
)

SCHEMA = "cc-tpu-whatif/1"

#: the acceptance floor on the batched sweep
MIN_FUTURES = 64

#: batched sweep wall must stay under this multiple of one plan search
RATIO_GATE = 2.0

#: traffic multipliers appended past the likely-futures set to fill the
#: batch deterministically (the likely set tops out at R racks +
#: B brokers + 2 growth steps)
_EXTRA_FACTORS = (1.1, 1.2, 1.25, 1.3, 1.4, 1.6, 1.75, 1.8, 2.2, 2.5,
                  2.75, 3.0)


def artifact_futures(state, n: int = MIN_FUTURES) -> List[FutureSpec]:
    """A deterministic ``n``-future sweep over ``state``: the model's
    likely futures (every rack loss, every broker loss, growth steps),
    then extra traffic multipliers, then rolling maintenance pairs."""
    futures = list(likely_futures(state, k=n))
    for f in _EXTRA_FACTORS:
        if len(futures) >= n:
            break
        futures.append(FutureSpec(
            name=f"traffic-x{f:g}", events=(traffic_scale(f),),
        ))
    b = 0
    num_brokers = int(state.num_brokers)
    while len(futures) < n:
        futures.append(FutureSpec(
            name=f"maintenance-{b}-{(b + 1) % num_brokers}",
            events=(maintenance(b, (b + 1) % num_brokers),),
        ))
        b = (b + 2) % num_brokers
    return futures[:n]


def _best_of(n: int, fn) -> float:
    best = np.inf
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def measure_batch(num_futures: int = MIN_FUTURES, best_of: int = 3,
                  seed: int = 42, num_brokers: int = 50,
                  num_racks: int = 10, num_partitions: int = 1000) -> dict:
    """The batched-sweep measurement: ``num_futures`` futures against the
    bench fixture, ONE :func:`evaluate_batch` dispatch, timed best-of
    against a single warm TPU plan search on the same model."""
    from cruise_control_tpu.analyzer.tpu_optimizer import TpuGoalOptimizer
    from cruise_control_tpu.models.generators import random_cluster

    state = random_cluster(
        seed=seed, num_brokers=num_brokers, num_racks=num_racks,
        num_partitions=num_partitions,
    )
    futures = artifact_futures(state, num_futures)
    batch = compile_futures(state, futures)
    raw = evaluate_batch(state, batch)          # warm-up: compiles
    batched_s = _best_of(best_of, lambda: evaluate_batch(state, batch))

    opt = TpuGoalOptimizer()
    opt.optimize(state)                         # warm-up: compiles
    plan_s = _best_of(best_of, lambda: opt.optimize(state))

    rows = verdicts(batch, raw)
    survivable = sum(1 for v in rows if v["survivable"])
    return {
        "numFutures": len(futures),
        "batchSize": batch.padded_size,
        "numDispatches": 1,
        "scale": {
            "brokers": num_brokers,
            "partitions": num_partitions,
            "racks": num_racks,
        },
        "batchedWallS": round(batched_s, 4),
        "singlePlanWallS": round(plan_s, 4),
        "ratio": round(batched_s / plan_s, 4),
        "perFutureWallMs": round(batched_s / len(futures) * 1000.0, 4),
        "verdicts": {
            "survivable": survivable,
            "unsurvivable": len(rows) - survivable,
            "goalViolations": sum(v["goalViolations"] for v in rows),
        },
    }


def _scenario_side(result, mitigation_ms) -> dict:
    """One twin's journal collapsed into the artifact record.
    ``mitigation_ms`` is the side's first-mitigation virtual time: the
    proactive trigger for the proactive run, the first started fix for
    the reactive one."""
    pcts = result.heal_latency_percentiles()
    return {
        "outcome": result.heal_outcome(),
        "anomalies": len(result.anomalies()),
        "fixesStarted": len(result.fixes_started()),
        "healP99Ms": int(pcts.get(99, 0)),
        "mitigationVirtualMs": (
            None if mitigation_ms is None else int(mitigation_ms)
        ),
        "journalFingerprint": result.fingerprint(),
    }


def measure_proactive(scenario: str = "proactive_beats_reactive_peak"):
    """Run the scenario with proactive control ON and its reactive twin
    (identical spec, proactive off) — the forecast's time-lead is the
    only variable."""
    from cruise_control_tpu.sim import make_scenario, run_scenario

    spec = make_scenario(scenario)
    pro = run_scenario(spec)
    rea = run_scenario(dataclasses.replace(
        spec, name=f"{scenario}__reactive_twin", proactive_enabled=False,
    ))
    trig = pro.events_of("proactive.trigger")
    fixes = rea.fixes_started()
    pro_side = _scenario_side(
        pro, trig[0]["ts"] * 1000.0 if trig else None,
    )
    rea_side = _scenario_side(
        rea, fixes[0]["timeMs"] if fixes else None,
    )
    lead = None
    if (pro_side["mitigationVirtualMs"] is not None
            and rea_side["mitigationVirtualMs"] is not None):
        lead = (rea_side["mitigationVirtualMs"]
                - pro_side["mitigationVirtualMs"])
    return {
        "scenario": scenario,
        "proactive": pro_side,
        "reactive": rea_side,
        "leadVirtualMs": lead,
    }


def make_artifact(batch: dict, proactive: dict,
                  now: Optional[float] = None) -> dict:
    """Assemble the gated artifact from the two measurements."""
    now = time.time() if now is None else now
    pro, rea = proactive["proactive"], proactive["reactive"]
    gates = {
        "singleDispatch": batch["numDispatches"] == 1,
        "atLeast64Futures": batch["numFutures"] >= MIN_FUTURES,
        "batchRatioUnder2x": batch["ratio"] < RATIO_GATE,
        "proactiveNoBreach": (
            pro["anomalies"] == 0 and pro["fixesStarted"] == 0
        ),
        "proactiveBeatsReactiveHealP99": (
            pro["healP99Ms"] < rea["healP99Ms"]
            and rea["healP99Ms"] > 0
        ),
    }
    return {
        "schema": SCHEMA,
        "generated_unix": round(now, 3),
        "batch": batch,
        "proactive": proactive,
        "gates": gates,
        "allOk": all(gates.values()),
    }
