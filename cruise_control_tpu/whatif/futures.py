"""The hypothetical-future DSL.

A :class:`FutureSpec` is a tiny, deterministic description of a
counterfactual — "rack 2 dies", "traffic grows 1.8×", "topic `clicks`
triples" — built from the same event vocabulary the scenario timeline
speaks (``sim/timeline.py``), plus two load-shape kinds the timeline has
no need for (``traffic_scale`` / ``topic_growth``: the sim *synthesizes*
load, a what-if only *projects* it).

Every spec fingerprints to a stable hex id (sha256 over the canonical
event tuples), which — crossed with the monitor's ``model_generation()``
— keys the per-future verdict cache: a fingerprint never collides across
semantically different futures, and a generation bump silently retires
every cached verdict (the satellite-2 staleness fix).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Optional, Sequence, Tuple

import numpy as np

from cruise_control_tpu.common.resources import Resource

#: the closed kind vocabulary; the compiler rejects anything else
FUTURE_KINDS = (
    "kill_broker",
    "rack_loss",
    "maintenance_event",
    "traffic_scale",
    "topic_growth",
    "hot_partition_skew",
)

#: horizon a future defaults to when the caller names none (1 virtual hour)
DEFAULT_HORIZON_MS = 3_600_000


@dataclasses.dataclass(frozen=True)
class FutureEvent:
    """One hypothetical perturbation: ``kind`` + sorted ``(key, value)``
    args — hashable and canonical, mirroring ``TimelineEvent``."""

    kind: str
    args: tuple

    def arg(self, name, default=None):
        return dict(self.args).get(name, default)

    def to_json(self) -> dict:
        return {"kind": self.kind, **dict(self.args)}


def _event(kind: str, **args) -> FutureEvent:
    if kind not in FUTURE_KINDS:
        raise ValueError(f"unknown future event kind {kind!r}")
    return FutureEvent(kind, tuple(sorted(args.items())))


def broker_loss(broker: int) -> FutureEvent:
    """Broker ``broker`` (internal dense index) dies."""
    return _event("kill_broker", broker=int(broker))


def rack_loss(rack: int) -> FutureEvent:
    """Every broker on rack ``rack`` dies at once."""
    return _event("rack_loss", rack=int(rack))


def maintenance(*brokers: int) -> FutureEvent:
    """Planned maintenance: the named brokers are drained/offline for the
    future's horizon (same placement consequences as loss, different
    operator intent)."""
    if not brokers:
        raise ValueError("maintenance needs at least one broker")
    return _event("maintenance_event",
                  brokers=tuple(int(b) for b in brokers))


def traffic_scale(factor: float) -> FutureEvent:
    """Cluster-wide traffic multiplier ×``factor`` (rates only; disk is
    an integral, not a rate — matching the workload synthesizer)."""
    if factor <= 0:
        raise ValueError(f"traffic_scale factor must be > 0, got {factor}")
    return _event("traffic_scale", factor=round(float(factor), 6))


def topic_growth(topic, factor: float) -> FutureEvent:
    """Traffic on one topic (name or dense id) grows ×``factor``."""
    if factor <= 0:
        raise ValueError(f"topic_growth factor must be > 0, got {factor}")
    return _event("topic_growth", topic=topic,
                  factor=round(float(factor), 6))


def hot_partitions(partitions: Sequence[int], factor: float) -> FutureEvent:
    """A partition subset runs ×``factor`` hot (the timeline's
    ``hot_partition_skew``, projected instead of injected)."""
    return _event("hot_partition_skew",
                  partitions=tuple(int(p) for p in partitions),
                  factor=round(float(factor), 6))


@dataclasses.dataclass(frozen=True)
class FutureSpec:
    """One named hypothetical future: a composition of events projected
    over ``horizon_ms``."""

    name: str
    events: Tuple[FutureEvent, ...]
    horizon_ms: int = DEFAULT_HORIZON_MS

    def __post_init__(self):
        if not self.events:
            raise ValueError(f"future {self.name!r} has no events")
        object.__setattr__(self, "events", tuple(self.events))

    def fingerprint(self) -> str:
        """Stable id over the future's SEMANTICS (events + horizon; the
        display name is free to change without invalidating caches)."""
        doc = {
            "events": [e.to_json() for e in self.events],
            "horizonMs": int(self.horizon_ms),
        }
        blob = json.dumps(doc, sort_keys=True, default=str)
        return hashlib.sha256(blob.encode()).hexdigest()[:16]

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "horizonMs": int(self.horizon_ms),
            "events": [e.to_json() for e in self.events],
            "fingerprint": self.fingerprint(),
        }


def parse_future(obj: dict) -> FutureSpec:
    """``POST /whatif`` body element → :class:`FutureSpec` (strict: an
    unknown kind or missing arg is a 400 at the request boundary)."""
    if not isinstance(obj, dict):
        raise ValueError(f"future must be an object, got {type(obj).__name__}")
    raw_events = obj.get("events")
    if not isinstance(raw_events, list) or not raw_events:
        raise ValueError("future needs a non-empty 'events' list")
    events = []
    for ev in raw_events:
        if not isinstance(ev, dict) or "kind" not in ev:
            raise ValueError(f"future event needs a 'kind': {ev!r}")
        kind = ev["kind"]
        args = {k: v for k, v in ev.items() if k != "kind"}
        if kind == "kill_broker":
            events.append(broker_loss(args["broker"]))
        elif kind == "rack_loss":
            events.append(rack_loss(args["rack"]))
        elif kind == "maintenance_event":
            events.append(maintenance(*args["brokers"]))
        elif kind == "traffic_scale":
            events.append(traffic_scale(args["factor"]))
        elif kind == "topic_growth":
            events.append(topic_growth(args["topic"], args["factor"]))
        elif kind == "hot_partition_skew":
            events.append(hot_partitions(args["partitions"], args["factor"]))
        else:
            raise ValueError(f"unknown future event kind {kind!r}")
    horizon = int(obj.get("horizonMs", obj.get("horizon_ms",
                                               DEFAULT_HORIZON_MS)))
    name = str(obj.get("name") or f"future-{len(events)}ev")
    return FutureSpec(name=name, events=tuple(events), horizon_ms=horizon)


def likely_futures(state, k: int = 8) -> Tuple[FutureSpec, ...]:
    """The deterministic top-``k`` futures an operator most plausibly
    asks about, derived from the built model: rack losses ordered by
    hosted ingress (heaviest rack first), single-broker losses likewise,
    then cluster-wide traffic growth steps.  Ties break on the smaller
    id, so the list is stable for a given model — the precompute daemon
    keys its warm set on exactly this ordering."""
    k = max(0, int(k))
    if k == 0:
        return ()
    assignment = np.asarray(state.assignment)
    leader_slot = np.asarray(state.leader_slot)
    lead_in = np.asarray(state.leader_load)[:, Resource.NW_IN]
    racks = np.asarray(state.broker_rack)
    num_brokers = int(state.num_brokers)
    # hosted ingress per broker: each existing replica slot contributes
    # the leader rate on the leader slot (followers replicate it too, but
    # the ordering heuristic only needs a stable, load-shaped ranking)
    hosted = np.zeros(num_brokers, np.float64)
    P, S = assignment.shape
    for s in range(S):
        col = assignment[:, s]
        ok = col >= 0
        np.add.at(hosted, col[ok], lead_in[ok])
    futures = []
    rack_ids = sorted(set(int(r) for r in racks.tolist()))
    rack_load = {r: float(hosted[racks == r].sum()) for r in rack_ids}
    for r in sorted(rack_ids, key=lambda r: (-rack_load[r], r)):
        futures.append(FutureSpec(
            name=f"rack-{r}-loss", events=(rack_loss(r),),
        ))
    for b in sorted(range(num_brokers),
                    key=lambda b: (-float(hosted[b]), b)):
        futures.append(FutureSpec(
            name=f"broker-{b}-loss", events=(broker_loss(b),),
        ))
    for factor in (1.5, 2.0):
        futures.append(FutureSpec(
            name=f"traffic-x{factor:g}", events=(traffic_scale(factor),),
        ))
    return tuple(futures[:k])


def parse_futures_param(
    raw: Optional[str], state=None, max_futures: int = 256, top_k: int = 8
) -> Tuple[FutureSpec, ...]:
    """The ``futures`` request parameter: a JSON list of future objects;
    absent → the model's :func:`likely_futures` (requires ``state``)."""
    if raw is None or raw == "":
        if state is None:
            raise ValueError(
                "no 'futures' parameter and no model to derive defaults"
            )
        return likely_futures(state, top_k)
    try:
        doc = json.loads(raw)
    except ValueError as e:
        raise ValueError(f"futures parameter is not valid JSON: {e}") from None
    if isinstance(doc, dict):
        doc = [doc]
    if not isinstance(doc, list) or not doc:
        raise ValueError("futures parameter must be a non-empty JSON list")
    if len(doc) > max_futures:
        raise ValueError(
            f"{len(doc)} futures > cap {max_futures} (whatif.max.futures)"
        )
    return tuple(parse_future(d) for d in doc)
