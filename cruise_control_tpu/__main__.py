"""``python -m cruise_control_tpu [config.properties] [port]`` — the
standalone server entry point (upstream ``kafka-cruise-control-start.sh`` →
``KafkaCruiseControlMain.main``; SURVEY.md §3.1).

Starts the REST server (with /ui), metric sampling, anomaly detection, and
proposal precomputation over the simulated cluster, then serves until
SIGINT/SIGTERM.
"""

from __future__ import annotations

import signal
import sys
import threading
import time

from cruise_control_tpu.bootstrap import build_app, load_properties
from cruise_control_tpu.config.cruise_control_config import CruiseControlConfig


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    props = load_properties(argv[0]) if argv else {}
    port = int(argv[1]) if len(argv) > 1 else None
    cfg = CruiseControlConfig(props)
    from cruise_control_tpu.utils.logging import configure

    configure(cfg.get("logging.level"), cfg.get("logging.file"))
    app = build_app(cfg, port=port)

    app.server.start()
    app.fetcher_manager.start()
    app.detector_manager.start()
    app.cruise_control.start_proposal_precomputation(
        interval_s=app.config.get("proposal.precompute.interval.ms") / 1000,
        engine=app.config.get("proposal.precompute.engine"),
    )
    # the simulated brokers report on the sampling cadence (a real
    # cluster's broker-side reporters push to __CruiseControlMetrics on
    # their own schedule — no loop needed in Kafka mode)
    stop = threading.Event()
    if app.reporter is not None:
        def report_loop() -> None:
            interval = app.config.get("metric.sampling.interval.ms") / 1000
            while not stop.wait(min(interval, 5.0)):
                app.reporter.report(time_ms=int(time.time() * 1000))

        threading.Thread(target=report_loop, daemon=True,
                         name="simulated-reporters").start()

    print(f"cruise-control listening on {app.server.url} (UI at /ui)")
    done = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: done.set())
    done.wait()
    stop.set()
    app.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
