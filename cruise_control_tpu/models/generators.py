"""Synthetic cluster generators — the test/bench workload fixtures.

Equivalents of the reference's ``RandomCluster`` and ``DeterministicCluster``
test fixtures (upstream
``cruise-control/src/test/java/com/linkedin/kafka/cruisecontrol/analyzer/RandomCluster.java``
and ``DeterministicCluster.java``; SURVEY.md §4) — seeded, so every test and
benchmark is reproducible.  Generation is host-side numpy (it feeds fixtures,
not the hot path).

Workload shapes mirror upstream ``TestConstants.Distribution``:

* ``UNIFORM``     — iid uniform loads per partition.
* ``LINEAR``      — load grows linearly with partition index.
* ``EXPONENTIAL`` — a few hot partitions dominate (load ∝ exp decay).
"""

from __future__ import annotations

import enum
from typing import Optional

import numpy as np

from cruise_control_tpu.common.resources import (
    EMPTY_SLOT,
    FOLLOWER_CPU_RATIO,
    NUM_RESOURCES,
    BrokerState,
    Resource,
)
from cruise_control_tpu.models.builder import ClusterModelBuilder
from cruise_control_tpu.models.cluster_state import ClusterState


class Distribution(enum.Enum):
    UNIFORM = "uniform"
    LINEAR = "linear"
    EXPONENTIAL = "exponential"


#: Capacity of every broker in generated clusters, in upstream units
#: (CPU %, NW KB/s, DISK MB) — mirrors TestConstants broker capacity.
DEFAULT_CAPACITY = np.array(
    [100.0, 200_000.0, 200_000.0, 1_000_000.0], np.float32
)


def random_cluster(
    seed: int,
    num_brokers: int = 50,
    num_racks: int = 10,
    num_topics: int = 20,
    num_partitions: int = 1000,
    replication_factor: int = 3,
    distribution: Distribution = Distribution.UNIFORM,
    capacity: Optional[np.ndarray] = None,
    mean_utilization: float = 0.35,
    dead_brokers: int = 0,
    new_brokers: int = 0,
    rack_aware: bool = False,
    hot_partitions: int = 0,
    hot_factor: float = 8.0,
) -> ClusterState:
    """Generate a random-but-seeded cluster in upstream RandomCluster's spirit.

    Placement is random-but-legal (no duplicate broker per partition); loads
    are scaled so mean broker utilization ≈ ``mean_utilization`` per resource.
    ``dead_brokers`` marks the *last* k brokers DEAD (their replicas become
    offline) and ``new_brokers`` marks the preceding k NEW — the self-healing
    fixtures in BASELINE.json config #4.

    ``rack_aware=True`` places each partition's replicas on distinct racks
    (the fault-injection simulator needs RackAwareGoal-clean initial
    placements, so rack-loss timelines start from a legal cluster).
    ``hot_partitions``/``hot_factor`` multiply the load of a seeded random
    partition subset — the skew knob for hot-partition scenarios.  All knobs
    are seed-stable: the same arguments yield a bit-identical ClusterState.
    """
    rng = np.random.default_rng(seed)
    rf = min(replication_factor, num_brokers)
    cap = np.asarray(
        capacity if capacity is not None else DEFAULT_CAPACITY, np.float32
    )

    # topology: brokers round-robin across racks
    broker_rack = np.arange(num_brokers, dtype=np.int32) % num_racks
    # capacity may be [R] (homogeneous) or [B, R] (heterogeneous brokers)
    broker_capacity = np.broadcast_to(cap, (num_brokers, NUM_RESOURCES)).copy()

    # placement: per-partition random RF-subset of brokers, vectorized
    # (a per-partition Python loop dominates generation at 1M partitions).
    # Rack-aware regime: a uniform permutation of racks per row picks rf
    # distinct racks, then a uniform member within each — no two replicas
    # share a rack.  Dense regime (rf close to num_brokers): random-keys
    # argsort — a uniform permutation per row, first rf entries.  Sparse
    # regime: rejection sampling (resample rows with duplicate brokers) —
    # uniform over distinct tuples like choice(replace=False), geometric
    # convergence when collisions are rare.
    if rack_aware:
        if rf > num_racks:
            raise ValueError(
                f"rack_aware placement needs rf <= num_racks "
                f"(rf={rf}, num_racks={num_racks})"
            )
        members = [
            np.flatnonzero(broker_rack == r).astype(np.int32)
            for r in range(num_racks)
        ]
        width = max(m.size for m in members)
        table = np.zeros((num_racks, width), np.int32)
        counts = np.zeros(num_racks, np.int64)
        for r, m in enumerate(members):
            table[r, : m.size] = m
            counts[r] = m.size
        rack_keys = rng.random((num_partitions, num_racks))
        racks_sel = np.argsort(rack_keys, axis=1)[:, :rf]       # [P, rf]
        within = rng.integers(0, 1 << 30, size=(num_partitions, rf))
        assignment = table[
            racks_sel, within % counts[racks_sel]
        ].astype(np.int32)
    elif 2 * rf >= num_brokers:
        keys = rng.random((num_partitions, num_brokers))
        assignment = np.argsort(keys, axis=1)[:, :rf].astype(np.int32)
    else:
        def _dup_rows(a: np.ndarray) -> np.ndarray:
            srt = np.sort(a, axis=1)
            return (srt[:, 1:] == srt[:, :-1]).any(axis=1)

        assignment = rng.integers(
            0, num_brokers, size=(num_partitions, rf)
        ).astype(np.int32)
        bad = _dup_rows(assignment)
        while bad.any():
            assignment[bad] = rng.integers(
                0, num_brokers, size=(int(bad.sum()), rf)
            )
            still = _dup_rows(assignment[bad])
            nxt = np.zeros_like(bad)
            nxt[np.flatnonzero(bad)[still]] = True
            bad = nxt
    leader_slot = np.zeros(num_partitions, np.int32)

    # workload shape across partitions
    if distribution is Distribution.UNIFORM:
        shape = rng.uniform(0.5, 1.5, size=num_partitions)
    elif distribution is Distribution.LINEAR:
        shape = np.linspace(0.1, 2.0, num_partitions)
    else:  # EXPONENTIAL
        shape = np.exp(-np.linspace(0.0, 5.0, num_partitions)) * 5.0
    shape = shape / shape.mean()
    if hot_partitions:
        hot = rng.choice(num_partitions, size=min(hot_partitions,
                                                  num_partitions),
                         replace=False)
        shape = shape.copy()
        shape[hot] *= hot_factor

    # per-resource leader load, scaled to hit the target mean broker utilization:
    # sum_p load[p] * contribution ≈ mean_util * sum_b capacity[b, r]
    leader_load = np.empty((num_partitions, NUM_RESOURCES), np.float32)
    noise = rng.uniform(0.8, 1.2, size=(num_partitions, NUM_RESOURCES))
    for r in Resource:
        # replicas contributing to resource r per partition
        if r == Resource.NW_OUT:
            contrib = 1.0  # leader only
        elif r == Resource.CPU:
            contrib = 1.0 + FOLLOWER_CPU_RATIO * (rf - 1)
        else:
            contrib = float(rf)  # disk/nw_in replicated to all
        total = mean_utilization * float(broker_capacity[:, r].sum())
        leader_load[:, r] = shape * noise[:, r] * total / (num_partitions * contrib)

    follower_load = leader_load.copy()
    follower_load[:, Resource.NW_OUT] = 0.0
    follower_load[:, Resource.CPU] *= FOLLOWER_CPU_RATIO

    partition_topic = rng.integers(0, num_topics, size=num_partitions).astype(np.int32)

    broker_state = np.zeros(num_brokers, np.int8)
    if new_brokers:
        broker_state[num_brokers - dead_brokers - new_brokers : num_brokers - dead_brokers] = (
            BrokerState.NEW
        )
    if dead_brokers:
        broker_state[num_brokers - dead_brokers :] = BrokerState.DEAD
    dead_mask = broker_state == BrokerState.DEAD
    replica_offline = dead_mask[assignment] & (assignment != EMPTY_SLOT)

    return ClusterState(
        assignment=np.asarray(assignment),
        leader_slot=np.asarray(leader_slot),
        leader_load=np.asarray(leader_load),
        follower_load=np.asarray(follower_load),
        partition_topic=np.asarray(partition_topic),
        broker_capacity=np.asarray(broker_capacity),
        broker_rack=np.asarray(broker_rack),
        broker_state=np.asarray(broker_state),
        replica_offline=np.asarray(replica_offline),
        num_topics=num_topics,
    )


def small_deterministic_cluster() -> ClusterState:
    """Hand-built 2-rack / 3-broker / 2-topic fixture for exact assertions
    (upstream DeterministicCluster's role)."""
    b = ClusterModelBuilder()
    cap = {Resource.CPU: 100.0, Resource.NW_IN: 100.0, Resource.NW_OUT: 100.0, Resource.DISK: 1000.0}
    b0 = b.add_broker("r0", cap)
    b1 = b.add_broker("r0", cap)
    b2 = b.add_broker("r1", cap)
    load = {Resource.CPU: 10.0, Resource.NW_IN: 10.0, Resource.NW_OUT: 10.0, Resource.DISK: 50.0}
    b.add_partition("T1", [b0, b1], load)
    b.add_partition("T1", [b1, b2], load)
    b.add_partition("T2", [b2, b0], load)
    b.add_partition("T2", [b0, b1], load)
    return b.build()


def rack_unaware_cluster() -> ClusterState:
    """Fixture whose partitions violate rack-awareness (both replicas share a
    rack) — the RackAwareGoal unit-test case."""
    b = ClusterModelBuilder()
    cap = {Resource.CPU: 100.0, Resource.NW_IN: 100.0, Resource.NW_OUT: 100.0, Resource.DISK: 1000.0}
    b0 = b.add_broker("r0", cap)
    b1 = b.add_broker("r0", cap)
    b2 = b.add_broker("r1", cap)
    b3 = b.add_broker("r1", cap)
    load = {Resource.CPU: 5.0, Resource.NW_IN: 5.0, Resource.NW_OUT: 5.0, Resource.DISK: 20.0}
    b.add_partition("T1", [b0, b1], load)  # both in r0 → violation
    b.add_partition("T1", [b2, b3], load)  # both in r1 → violation
    b.add_partition("T2", [b0, b2], load)  # ok
    return b.build()
