"""Vectorized ``ClusterModelStats`` (upstream ``model/ClusterModelStats.java``).

Per-resource mean / stddev / coefficient-of-variation of broker utilization,
replica/leader/topic-replica count distributions, and potential NW-out — the
numbers the distribution goals balance and ``OptimizerResult`` reports
before/after.  Everything is a masked reduction over the dense broker axis, so
a single jitted call replaces upstream's full model walk.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from flax import struct

from cruise_control_tpu.models.cluster_state import (
    ClusterState,
    broker_leader_count,
    broker_load,
    broker_potential_nw_out,
    broker_replica_count,
    broker_topic_replica_count,
)
from cruise_control_tpu.telemetry import device_stats


@struct.dataclass
class ClusterStats:
    """All fields are per-alive-broker statistics.

    ``resource_*`` arrays are indexed by :class:`Resource` on the last axis.
    """

    resource_mean: jax.Array        # f32 [R]
    resource_std: jax.Array         # f32 [R]
    resource_cv: jax.Array          # f32 [R]  std/mean (upstream "coefficient of variation")
    utilization_mean: jax.Array     # f32 [R]  mean of load/capacity
    utilization_std: jax.Array      # f32 [R]
    replica_count_mean: jax.Array   # f32 []
    replica_count_std: jax.Array    # f32 []
    leader_count_mean: jax.Array    # f32 []
    leader_count_std: jax.Array     # f32 []
    topic_replica_std_mean: jax.Array  # f32 [] mean over topics of per-topic replica-count std
    potential_nw_out_mean: jax.Array   # f32 []
    potential_nw_out_std: jax.Array    # f32 []
    num_alive_brokers: jax.Array    # int32 []


def _masked_mean_std(values: jax.Array, mask: jax.Array):
    """Mean/std over axis 0 where ``mask`` (broadcastable) is true."""
    mask_f = mask.astype(values.dtype)
    while mask_f.ndim < values.ndim:
        mask_f = mask_f[..., None]
    n = jnp.maximum(jnp.sum(mask_f, axis=0), 1.0)
    mean = jnp.sum(values * mask_f, axis=0) / n
    var = jnp.sum(((values - mean) ** 2) * mask_f, axis=0) / n
    return mean, jnp.sqrt(var)


def cluster_stats(state: ClusterState) -> ClusterStats:
    """Jit-compiled in one XLA program per (P, S, B, T) shape.

    Stats are recomputed at every optimize() entry/exit and by several REST
    responses; running this eagerly costs one XLA compilation *per primitive*
    on TPU backends, so the whole reduction graph is compiled once instead.
    The jit key deliberately excludes the non-array metadata (broker_ids /
    partition_ids / disk_names) — only ``num_topics`` shapes the program.

    ClusterState is host-first (numpy): when the arrays are not already on
    an accelerator, the program is pinned to the CPU backend so a stats
    call never ships ~50MB of model over the accelerator link (seconds on
    a tunneled dev TPU, and the reductions are bandwidth-bound anyway).
    """
    args = (
        state.assignment,
        state.leader_slot,
        state.leader_load,
        state.follower_load,
        state.partition_topic,
        state.broker_capacity,
        state.broker_state,
    )
    if any(isinstance(a, jax.Array) for a in args):
        return _cluster_stats_jit(*args, state.num_topics)
    try:
        # local_devices, not devices: under a multi-controller deployment
        # (jax.distributed) global device 0 belongs to process 0 only —
        # pinning to it would make every other process's stats output
        # unfetchable ("not fully addressable")
        cpu = jax.local_devices(backend="cpu")[0]
    except RuntimeError:  # CPU backend disabled (e.g. JAX_PLATFORMS=tpu)
        return _cluster_stats_jit(*args, state.num_topics)
    with jax.default_device(cpu):
        return _cluster_stats_jit(*args, state.num_topics)


@functools.partial(jax.jit, static_argnums=(7,))
def _cluster_stats_jit(
    assignment,
    leader_slot,
    leader_load,
    follower_load,
    partition_topic,
    broker_capacity,
    broker_state,
    num_topics: int,
) -> ClusterStats:
    state = ClusterState(
        assignment=assignment,
        leader_slot=leader_slot,
        leader_load=leader_load,
        follower_load=follower_load,
        partition_topic=partition_topic,
        broker_capacity=broker_capacity,
        broker_rack=jnp.zeros(broker_capacity.shape[0], jnp.int32),
        broker_state=broker_state,
        replica_offline=jnp.zeros(assignment.shape, bool),
        num_topics=num_topics,
    )
    alive = state.broker_alive()
    load = broker_load(state)                               # [B, R]
    cap = jnp.maximum(state.broker_capacity, 1e-9)
    util = load / cap

    res_mean, res_std = _masked_mean_std(load, alive)
    util_mean, util_std = _masked_mean_std(util, alive)
    cv = res_std / jnp.maximum(res_mean, 1e-9)

    rc = broker_replica_count(state).astype(jnp.float32)
    lc = broker_leader_count(state).astype(jnp.float32)
    rc_mean, rc_std = _masked_mean_std(rc, alive)
    lc_mean, lc_std = _masked_mean_std(lc, alive)

    trc = broker_topic_replica_count(state).astype(jnp.float32)  # [B, T]
    _, trc_std = _masked_mean_std(trc, alive)                    # [T]
    trc_std_mean = jnp.mean(trc_std) if state.num_topics else jnp.float32(0.0)

    pot = broker_potential_nw_out(state)
    pot_mean, pot_std = _masked_mean_std(pot, alive)

    return ClusterStats(
        resource_mean=res_mean,
        resource_std=res_std,
        resource_cv=cv,
        utilization_mean=util_mean,
        utilization_std=util_std,
        replica_count_mean=rc_mean,
        replica_count_std=rc_std,
        leader_count_mean=lc_mean,
        leader_count_std=lc_std,
        topic_replica_std_mean=jnp.asarray(trc_std_mean),
        potential_nw_out_mean=pot_mean,
        potential_nw_out_std=pot_std,
        num_alive_brokers=jnp.sum(alive.astype(jnp.int32)),
    )


# compile observability: stats recompile per (P, S, B, T) shape — exactly
# the shape-churn the retrace detector exists to flag
_cluster_stats_jit = device_stats.instrument(
    "models.cluster_stats", _cluster_stats_jit
)


def stats_summary(stats: ClusterStats) -> dict:
    """Host-side dict for JSON responses (servlet/response parity)."""
    import numpy as np

    from cruise_control_tpu.common.resources import Resource

    # ONE device transfer: device_get on the 13-leaf pytree issues a fetch
    # per leaf (~30ms each over the tunneled link); concatenating on device
    # first makes it a single round-trip
    leaves, treedef = jax.tree_util.tree_flatten(stats)
    if any(isinstance(x, jax.Array) for x in leaves):
        sizes = [int(np.prod(np.shape(x))) for x in leaves]
        packed = np.asarray(
            jnp.concatenate(
                [jnp.ravel(x).astype(jnp.float32) for x in leaves]
            )
        )
        out, off = [], 0
        for x, n in zip(leaves, sizes):
            out.append(packed[off:off + n].reshape(np.shape(x)))
            off += n
        stats = jax.tree_util.tree_unflatten(treedef, out)

    def f(x):
        return np.asarray(x).tolist()

    return {
        "numAliveBrokers": int(stats.num_alive_brokers),
        "resources": {
            r.name: {
                "mean": f(stats.resource_mean[r]),
                "std": f(stats.resource_std[r]),
                "cv": f(stats.resource_cv[r]),
                "utilizationMean": f(stats.utilization_mean[r]),
                "utilizationStd": f(stats.utilization_std[r]),
            }
            for r in Resource
        },
        "replicaCount": {
            "mean": f(stats.replica_count_mean),
            "std": f(stats.replica_count_std),
        },
        "leaderCount": {
            "mean": f(stats.leader_count_mean),
            "std": f(stats.leader_count_std),
        },
        "topicReplicaStdMean": f(stats.topic_replica_std_mean),
        "potentialNwOut": {
            "mean": f(stats.potential_nw_out_mean),
            "std": f(stats.potential_nw_out_std),
        },
    }
