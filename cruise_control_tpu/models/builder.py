"""Object-level builder for readable ClusterState construction.

The tensor model (:mod:`cluster_state`) is the compute representation; tests,
fixtures, and the monitor assemble clusters through this builder (the role of
upstream ``ClusterModel.createBroker``/``createReplica`` incremental
construction, model/ClusterModel.java) and then snapshot to dense arrays.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from cruise_control_tpu.common.resources import (
    EMPTY_SLOT,
    FOLLOWER_CPU_RATIO,
    NUM_RESOURCES,
    BrokerState,
    Resource,
)
from cruise_control_tpu.models.cluster_state import ClusterState


def _resource_vec(x: Dict[Resource, float] | Sequence[float]) -> np.ndarray:
    """Dict or sequence → f32[NUM_RESOURCES] vector."""
    if isinstance(x, dict):
        out = np.zeros(NUM_RESOURCES, np.float32)
        for r, v in x.items():
            out[int(r)] = v
        return out
    out = np.asarray(x, np.float32)
    assert out.shape == (NUM_RESOURCES,)
    return out


@dataclasses.dataclass
class _Broker:
    rack: int
    capacity: np.ndarray
    state: BrokerState = BrokerState.ALIVE
    #: JBOD: (name, capacity MB, offline) per disk; empty = no disk modeling
    disks: List[tuple] = dataclasses.field(default_factory=list)
    #: host id (upstream model/Host.java: rack → host → broker); -1 = the
    #: broker is its own host
    host: int = -1


@dataclasses.dataclass
class _Partition:
    topic: int
    brokers: List[int]
    leader_slot: int
    leader_load: np.ndarray
    follower_load: np.ndarray
    offline: List[bool]
    disks: Optional[List[int]] = None  # disk index per replica slot


def patch_cluster_state(
    prev_state: ClusterState,
    *,
    assignment: np.ndarray,
    leader_slot: np.ndarray,
    replica_offline: np.ndarray,
    load_dirty: np.ndarray,
    new_leader_load: np.ndarray,
    broker_state: np.ndarray,
    broker_ids: Sequence[int],
    added_capacity: Optional[np.ndarray] = None,
    added_racks: Optional[np.ndarray] = None,
) -> ClusterState:
    """Delta model build: produce the next :class:`ClusterState` by
    patching the previous one's arrays instead of re-running the
    per-partition builder loop (the monitor's ``cluster_model_delta``
    front half computes the diffs; this is the assemble step).

    The exactness contract the warm-start path relies on: rows NOT in
    ``load_dirty`` keep the previous load tables' bits verbatim (follower
    loads are re-derived only for dirty rows, with the same formula the
    full builder uses), so resident device tables refreshed for exactly
    the dirty rows equal a from-scratch rebuild bit-for-bit.  The broker
    axis may only ever grow by appending (``added_capacity`` /
    ``added_racks``) — an insert would shift internal indices, which the
    caller must detect and route to the full builder.
    """
    prev_load = np.asarray(prev_state.leader_load, np.float32)
    leader_load = np.where(
        load_dirty[:, None], new_leader_load.astype(np.float32), prev_load
    )
    fol = leader_load.copy()
    fol[:, Resource.NW_OUT] = 0.0
    fol[:, Resource.CPU] = leader_load[:, Resource.CPU] * FOLLOWER_CPU_RATIO
    follower_load = np.where(
        load_dirty[:, None], fol,
        np.asarray(prev_state.follower_load, np.float32),
    )
    capacity = np.asarray(prev_state.broker_capacity, np.float32)
    rack = np.asarray(prev_state.broker_rack, np.int32)
    if added_capacity is not None and len(added_capacity):
        capacity = np.concatenate([capacity, added_capacity.astype(
            np.float32)], axis=0)
        rack = np.concatenate([rack, added_racks.astype(np.int32)])
    return prev_state.replace(
        assignment=np.asarray(assignment, np.int32),
        leader_slot=np.asarray(leader_slot, np.int32),
        leader_load=leader_load,
        follower_load=follower_load,
        replica_offline=np.asarray(replica_offline, bool),
        broker_capacity=capacity,
        broker_rack=rack,
        broker_state=np.asarray(broker_state, np.int8),
        broker_ids=tuple(broker_ids),
    )


class ClusterModelBuilder:
    """Accumulates brokers/partitions, emits a dense :class:`ClusterState`."""

    def __init__(self) -> None:
        self._brokers: List[_Broker] = []
        self._broker_ids: List[int] = []
        self._partitions: List[_Partition] = []
        self._partition_ids: List[int] = []
        self._topics: Dict[str, int] = {}
        self._racks: Dict[str, int] = {}
        self._hosts: Dict[str, int] = {}

    # ---- topology ---------------------------------------------------------------
    def add_rack(self, name: str) -> int:
        return self._racks.setdefault(name, len(self._racks))

    def add_host(self, name: str) -> int:
        return self._hosts.setdefault(name, len(self._hosts))

    def add_broker(
        self,
        rack: str | int | None,
        capacity: Dict[Resource, float] | Sequence[float],
        state: BrokerState = BrokerState.ALIVE,
        broker_id: Optional[int] = None,
        disks: Optional[Sequence[tuple]] = None,
        host: str | int | None = None,
    ) -> int:
        """``broker_id`` is the *external* (Kafka) id; defaults to the dense
        internal index.  ``disks`` (JBOD): sequence of ``(name, capacity_mb)``
        or ``(name, capacity_mb, offline)``.  ``host`` places the broker on
        a physical host (upstream rack → host → broker topology,
        ``model/Host.java``); when ``rack`` is None the host stands in as
        the rack — upstream's exact fallback, so co-hosted brokers without
        rack info never share a partition's replicas.  Returns the internal
        index."""
        if rack is None:
            if host is None:
                raise ValueError("add_broker needs a rack or a host")
            rack_id = self.add_rack(f"host:{host}")
        else:
            rack_id = (
                self.add_rack(rack) if isinstance(rack, str) else int(rack)
            )
            if rack_id >= 1 << 24:
                # raw int rack ids must stay f32-exact: the device engine
                # rides rack ids through an f32 row-gather (pool-priority
                # fusion), where ids ≥ 2^24 would silently collide.  Use
                # string rack names (densified) for hashed/sparse ids.
                raise ValueError(
                    f"integer rack id {rack_id} >= 2^24; pass rack as a "
                    "string (names are densified to small ids)"
                )
        host_id = -1
        if host is not None:
            host_id = (
                self.add_host(host) if isinstance(host, str) else int(host)
            )
        internal = len(self._brokers)
        disk_list = [
            (d[0], float(d[1]), bool(d[2]) if len(d) > 2 else False)
            for d in (disks or [])
        ]
        self._brokers.append(
            _Broker(rack_id, _resource_vec(capacity), state, disk_list,
                    host=host_id)
        )
        self._broker_ids.append(internal if broker_id is None else int(broker_id))
        return internal

    def topic_id(self, topic: str) -> int:
        return self._topics.setdefault(topic, len(self._topics))

    def add_partition(
        self,
        topic: str,
        brokers: Sequence[int],
        leader_load: Dict[Resource, float] | Sequence[float],
        follower_load: Optional[Dict[Resource, float] | Sequence[float]] = None,
        leader_slot: int = 0,
        offline: Optional[Sequence[bool]] = None,
        partition_id: Optional[int] = None,
        disks: Optional[Sequence[int]] = None,
    ) -> int:
        # Default follower load per upstream semantics: replicates bytes-in
        # and disk, serves no bytes-out, and costs a fraction of leader CPU.
        ll = _resource_vec(leader_load)
        if follower_load is None:
            fl = ll.copy()
            fl[Resource.NW_OUT] = 0.0
            fl[Resource.CPU] = ll[Resource.CPU] * FOLLOWER_CPU_RATIO
        else:
            fl = _resource_vec(follower_load)
        self._partitions.append(
            _Partition(
                topic=self.topic_id(topic),
                brokers=list(brokers),
                leader_slot=leader_slot,
                leader_load=ll,
                follower_load=fl,
                offline=list(offline) if offline is not None else [False] * len(brokers),
                disks=list(disks) if disks is not None else None,
            )
        )
        internal = len(self._partitions) - 1
        self._partition_ids.append(
            internal if partition_id is None else int(partition_id)
        )
        return internal

    def set_broker_state(self, broker: int, state: BrokerState) -> None:
        self._brokers[broker].state = state

    # ---- snapshot ---------------------------------------------------------------
    def build(self) -> ClusterState:
        for label, ids in (("broker", self._broker_ids),
                           ("partition", self._partition_ids)):
            if len(set(ids)) != len(ids):
                dupes = sorted({i for i in ids if ids.count(i) > 1})
                raise ValueError(f"duplicate external {label} ids: {dupes}")
        num_b = len(self._brokers)
        num_p = len(self._partitions)
        max_rf = max((len(p.brokers) for p in self._partitions), default=1)

        assignment = np.full((num_p, max_rf), EMPTY_SLOT, np.int32)
        leader_slot = np.zeros(num_p, np.int32)
        leader_load = np.zeros((num_p, NUM_RESOURCES), np.float32)
        follower_load = np.zeros((num_p, NUM_RESOURCES), np.float32)
        topic = np.zeros(num_p, np.int32)
        offline = np.zeros((num_p, max_rf), bool)

        for i, part in enumerate(self._partitions):
            assignment[i, : len(part.brokers)] = part.brokers
            leader_slot[i] = part.leader_slot
            leader_load[i] = part.leader_load
            follower_load[i] = part.follower_load
            topic[i] = part.topic
            offline[i, : len(part.brokers)] = part.offline

        # Dead brokers' replicas are offline by construction (upstream
        # ClusterModel marks replicas on dead brokers as immigrants to move).
        dead = np.array(
            [b.state in (BrokerState.DEAD, BrokerState.REMOVED) for b in self._brokers]
        )
        if dead.any():
            on_dead = np.isin(assignment, np.nonzero(dead)[0])
            offline |= on_dead

        # JBOD disk tensors (only when any broker declared disks)
        replica_disk = disk_capacity = disk_offline = None
        disk_names: tuple = ()
        if any(b.disks for b in self._brokers):
            D = max(len(b.disks) for b in self._brokers) or 1
            disk_capacity = np.zeros((num_b, D), np.float32)
            disk_offline_arr = np.zeros((num_b, D), bool)
            names = []
            for bi, b in enumerate(self._brokers):
                row = []
                for di, (name, cap_mb, off) in enumerate(b.disks):
                    disk_capacity[bi, di] = cap_mb
                    disk_offline_arr[bi, di] = off
                    row.append(name)
                names.append(tuple(row))
            disk_names = tuple(names)
            replica_disk = np.full((num_p, max_rf), -1, np.int32)
            default_disk_counts: dict = {}
            for i, part in enumerate(self._partitions):
                if part.disks is not None:
                    replica_disk[i, : len(part.disks)] = part.disks
                else:
                    # default placement: healthy disk with the fewest
                    # replicas so far (never an offline disk)
                    for s, bi in enumerate(part.brokers):
                        healthy = [
                            di for di, (_, _, off) in
                            enumerate(self._brokers[bi].disks) if not off
                        ]
                        if healthy:
                            counts = default_disk_counts.setdefault(
                                bi, dict.fromkeys(healthy, 0)
                            )
                            di = min(healthy, key=lambda d: counts[d])
                            counts[di] += 1
                            replica_disk[i, s] = di
            # replicas on offline disks are offline (same immigrant semantics
            # as dead brokers)
            for i in range(num_p):
                for s in range(max_rf):
                    bi, di = assignment[i, s], replica_disk[i, s]
                    if bi != EMPTY_SLOT and di >= 0 and disk_offline_arr[bi, di]:
                        offline[i, s] = True
            disk_offline = disk_offline_arr

        return ClusterState(
            assignment=np.asarray(assignment),
            leader_slot=np.asarray(leader_slot),
            leader_load=np.asarray(leader_load),
            follower_load=np.asarray(follower_load),
            partition_topic=np.asarray(topic),
            broker_capacity=np.asarray(
                np.stack([b.capacity for b in self._brokers])
                if self._brokers
                else np.zeros((0, NUM_RESOURCES), np.float32)
            ),
            broker_rack=np.asarray(
                np.array([b.rack for b in self._brokers], np.int32)
            ),
            broker_state=np.asarray(
                np.array([int(b.state) for b in self._brokers], np.int8)
            ),
            broker_host=(
                np.array([b.host for b in self._brokers], np.int32)
                if any(b.host >= 0 for b in self._brokers) else None
            ),
            replica_offline=np.asarray(offline),
            num_topics=max(len(self._topics), 1),
            topic_names=tuple(self._topics),
            broker_ids=tuple(self._broker_ids),
            partition_ids=tuple(self._partition_ids),
            replica_disk=(
                None if replica_disk is None else np.asarray(replica_disk)
            ),
            disk_capacity=(
                None if disk_capacity is None else np.asarray(disk_capacity)
            ),
            disk_offline=(
                None if disk_offline is None else np.asarray(disk_offline)
            ),
            disk_names=disk_names,
        )
