"""Dense-tensor cluster model — the TPU-native ``ClusterModel``.

Re-expresses the reference's mutable object graph (upstream
``cruise-control/src/main/java/com/linkedin/kafka/cruisecontrol/model/ClusterModel.java``
— racks → brokers → replicas with per-entity ``Load`` roll-ups; SURVEY.md §2.4)
as an immutable pytree of dense arrays, so the analyzer's inner loop becomes
vectorized tensor algebra instead of pointer-chasing:

* ``assignment[p, s]``       int32   broker id hosting replica slot ``s`` of
                                     partition ``p`` (``EMPTY_SLOT`` = -1 pads
                                     partitions with RF below the slot axis).
* ``leader_slot[p]``         int32   which slot currently leads partition ``p``.
* ``leader_load[p, r]``      float32 per-resource load the *leader* replica puts
                                     on its broker.
* ``follower_load[p, r]``    float32 per-resource load each *follower* replica
                                     puts on its broker (NW_OUT ≈ 0, CPU scaled
                                     — computed upstream by the monitor's
                                     linear model, here supplied by the
                                     monitor/generators).
* ``partition_topic[p]``     int32   topic id (for topic-scoped goals).
* ``broker_capacity[b, r]``  float32 per-broker resource capacity.
* ``broker_rack[b]``         int32   rack id.
* ``broker_state[b]``        int8    :class:`BrokerState`.
* ``replica_offline[p, s]``  bool    replica lives on a broken disk / dead
                                     broker and must be evacuated.

The upstream mutators ``relocateReplica`` / ``relocateLeadership`` become pure
functions (:func:`apply_move`, :func:`apply_leadership`, :func:`apply_swap`)
returning a new state — one ``.at[].set``; the expensive per-broker load
roll-up upstream keeps incrementally is a single segment-sum here
(:func:`broker_load`), which XLA turns into one scatter-add over the MXU-fed
arrays.  All shapes are static (P, S, B, T fixed per compilation), so every
function is jit/vmap/shard_map-friendly.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from flax import struct

from cruise_control_tpu.common.resources import (
    EMPTY_SLOT,
    NUM_RESOURCES,
    BrokerState,
)


@struct.dataclass
class ClusterState:
    """Immutable snapshot of a cluster's placement + workload.

    Static (non-pytree) metadata: ``num_topics`` — needed for one-hot
    topic reductions with static output shapes.
    """

    assignment: jax.Array      # int32 [P, S]
    leader_slot: jax.Array     # int32 [P]
    leader_load: jax.Array     # f32   [P, R]
    follower_load: jax.Array   # f32   [P, R]
    partition_topic: jax.Array # int32 [P]
    broker_capacity: jax.Array # f32   [B, R]
    broker_rack: jax.Array     # int32 [B]
    broker_state: jax.Array    # int8  [B]
    replica_offline: jax.Array # bool  [P, S]
    #: int32 [B] physical host per broker (upstream model/Host.java: the
    #: rack → host → broker level); None = one broker per host.  When a
    #: broker has no rack info the builder substitutes its host as the
    #: rack (upstream's fallback), so rack-aware goals already enforce
    #: host-disjoint placement for rackless topologies; host ids here keep
    #: the level addressable for stats and host-scoped operations.
    broker_host: Optional[jax.Array] = None
    num_topics: int = struct.field(pytree_node=False, default=0)
    #: External (Kafka) broker id per internal index; () = identity.  Kafka
    #: broker ids need not be contiguous (e.g. 1001..1050), but every tensor
    #: here is dense — the monitor re-indexes and records the mapping so the
    #: facade can translate proposals back to external ids for the executor.
    broker_ids: tuple = struct.field(pytree_node=False, default=())
    #: Same mapping for partitions (external key per dense row; () = identity).
    #: Static tuple is fine: the TPU hot path jits over the extracted
    #: DeviceModel arrays, not ClusterState, so this never hits a jit cache key
    #: on the scale-critical path.
    partition_ids: tuple = struct.field(pytree_node=False, default=())
    #: Topic name per dense topic id (() = unnamed); lets the facade resolve
    #: name/regex-scoped options (topics.excluded.from.partition.movement,
    #: topics.with.min.leaders.per.broker) against the built model.
    topic_names: tuple = struct.field(pytree_node=False, default=())
    # ---- per-window load series (upstream model/Load.java carries
    # resource × window time series into the model; SURVEY.md §2.4) --------
    #: f32 [P, W, R] leader load per aggregation window; None = the monitor
    #: collapsed windows (or the state was built without series).  The
    #: ``leader_load``/``follower_load`` fields above remain the expected
    #: (mean) loads that balance goals optimize; the window series feeds
    #: percentile-based capacity estimation (:func:`capacity_loads`).
    leader_load_windows: Optional[jax.Array] = None
    #: f32 [P, W, R] follower twin of ``leader_load_windows``
    follower_load_windows: Optional[jax.Array] = None
    #: capacity-estimation percentile over the window axis (upstream
    #: ``capacity.estimation``-style semantics): 0 = disabled (capacity
    #: goals use the mean loads — round-1 behavior); e.g. 95 makes every
    #: capacity goal check peak (p95-over-windows) loads while balance
    #: goals keep optimizing the mean.  Carried on the state (set by the
    #: monitor from config) so every consumer — greedy goals, TPU engine
    #: host gates, verifier — derives identical capacity loads.
    capacity_percentile: float = struct.field(pytree_node=False, default=0.0)
    # ---- JBOD (upstream model/Disk.java); None = no per-disk modeling -------
    #: int32 [P, S] disk index (within hosting broker) of each replica; -1 =
    #: unknown/none
    replica_disk: Optional[jax.Array] = None
    #: f32 [B, D] per-disk capacity MB, 0 where the disk slot doesn't exist
    disk_capacity: Optional[jax.Array] = None
    #: bool [B, D] offline (failed) disks
    disk_offline: Optional[jax.Array] = None
    #: log-dir name per (broker, disk index) for executor translation
    disk_names: tuple = struct.field(pytree_node=False, default=())

    @property
    def has_disks(self) -> bool:
        return self.disk_capacity is not None

    @property
    def max_disks(self) -> int:
        return 0 if self.disk_capacity is None else self.disk_capacity.shape[1]

    # ---- static shape accessors -------------------------------------------------
    @property
    def num_partitions(self) -> int:
        return self.assignment.shape[0]

    @property
    def max_replication_factor(self) -> int:
        return self.assignment.shape[1]

    @property
    def num_brokers(self) -> int:
        return self.broker_capacity.shape[0]

    @property
    def num_racks(self) -> int:
        # Racks are dense ids assigned at build time; max+1 is not static, so
        # builders should pass rack ids in [0, num_brokers).  Goals that need a
        # static rack axis use num_brokers as the upper bound.
        return self.num_brokers

    # ---- masks ------------------------------------------------------------------
    def slot_exists(self) -> jax.Array:
        """bool [P, S] — true where a replica actually occupies the slot."""
        return self.assignment != EMPTY_SLOT

    def replication_factor(self) -> jax.Array:
        """int32 [P] — actual RF per partition."""
        return jnp.sum(self.slot_exists(), axis=1).astype(jnp.int32)

    def broker_alive(self) -> jax.Array:
        """bool [B] — broker can *host* load (upstream: state != DEAD)."""
        return (self.broker_state != BrokerState.DEAD) & (
            self.broker_state != BrokerState.REMOVED
        )

    def broker_is_new(self) -> jax.Array:
        return self.broker_state == jnp.int8(BrokerState.NEW)

    def broker_is_demoted(self) -> jax.Array:
        return self.broker_state == jnp.int8(BrokerState.DEMOTED)

    def leader_broker(self) -> jax.Array:
        """int32 [P] — broker id of each partition's leader."""
        return jnp.take_along_axis(
            self.assignment, self.leader_slot[:, None], axis=1
        )[:, 0]


# ---------------------------------------------------------------------------------
# Derived loads (upstream Load roll-ups, model/Load.java + ClusterModel caches)
# ---------------------------------------------------------------------------------

def capacity_loads(state: ClusterState):
    """(leader_cap_load, follower_cap_load) — f32 [P, R] loads capacity goals
    must budget for.

    With a window series and ``capacity_percentile`` > 0: the per-partition
    percentile over the window axis (host numpy — this feeds context
    construction, not the jitted hot path).  Per-partition percentile then
    summed per broker is the conservative side of the per-broker-sum
    percentile (subadditivity of upper quantiles in the bursty regimes that
    matter), matching the provision-for-peak intent of upstream
    ``model/Load.java``'s window series.  Otherwise: the mean loads —
    capacity and balance semantics coincide (round-1 behavior).
    """
    if state.leader_load_windows is None or state.capacity_percentile <= 0:
        return state.leader_load, state.follower_load
    q = float(state.capacity_percentile)
    lw = np.asarray(state.leader_load_windows, np.float32)
    fw = np.asarray(state.follower_load_windows, np.float32)
    return (
        np.percentile(lw, q, axis=1).astype(np.float32),
        np.percentile(fw, q, axis=1).astype(np.float32),
    )


def replica_load(state: ClusterState) -> jax.Array:
    """f32 [P, S, R] — load each replica slot puts on its broker.

    Leader slot carries ``leader_load``; follower slots carry
    ``follower_load``; empty slots carry zero.
    """
    is_leader = (
        jnp.arange(state.max_replication_factor)[None, :]
        == state.leader_slot[:, None]
    )  # [P, S]
    load = jnp.where(
        is_leader[:, :, None],
        state.leader_load[:, None, :],
        state.follower_load[:, None, :],
    )
    return jnp.where(state.slot_exists()[:, :, None], load, 0.0)


def _segment_sum_by_broker(
    values: jax.Array, assignment: jax.Array, num_brokers: int
) -> jax.Array:
    """Sum ``values[p, s, ...]`` into ``out[b, ...]`` grouped by ``assignment[p, s]``.

    Empty slots (id -1) are routed to a dump bucket ``B`` and dropped.  This is
    the scatter-add at the heart of the tensorized model (SURVEY.md §2.4
    "relocateReplica ⇒ index update + two scatter-adds").
    """
    ids = jnp.where(assignment >= 0, assignment, num_brokers).reshape(-1)
    flat = values.reshape((ids.shape[0],) + values.shape[2:])
    out = jax.ops.segment_sum(flat, ids, num_segments=num_brokers + 1)
    return out[:num_brokers]


def broker_load(
    state: ClusterState, rload: Optional[jax.Array] = None
) -> jax.Array:
    """f32 [B, R] — total per-resource load on each broker."""
    if rload is None:
        rload = replica_load(state)
    return _segment_sum_by_broker(rload, state.assignment, state.num_brokers)


def broker_replica_count(state: ClusterState) -> jax.Array:
    """int32 [B] — number of replicas hosted per broker."""
    ones = state.slot_exists().astype(jnp.int32)[:, :, None]
    return _segment_sum_by_broker(ones, state.assignment, state.num_brokers)[:, 0]


def broker_leader_count(state: ClusterState) -> jax.Array:
    """int32 [B] — number of leader replicas per broker."""
    lb = state.leader_broker()
    ids = jnp.where(lb >= 0, lb, state.num_brokers)
    ones = jnp.ones_like(ids)
    return jax.ops.segment_sum(ones, ids, num_segments=state.num_brokers + 1)[
        : state.num_brokers
    ]


def broker_leader_load(state: ClusterState) -> jax.Array:
    """f32 [B, R] — load contributed only by leader replicas (for leader-scoped
    goals, e.g. LeaderBytesInDistributionGoal)."""
    lb = state.leader_broker()
    ids = jnp.where(lb >= 0, lb, state.num_brokers)
    out = jax.ops.segment_sum(
        state.leader_load, ids, num_segments=state.num_brokers + 1
    )
    return out[: state.num_brokers]


def broker_potential_nw_out(state: ClusterState) -> jax.Array:
    """f32 [B] — upstream "potential network outbound": the NW_OUT a broker
    would serve if it led *every* replica it hosts (model/Load.java potential
    bytes-out; used by PotentialNwOutGoal)."""
    from cruise_control_tpu.common.resources import Resource

    pot = state.leader_load[:, Resource.NW_OUT]  # [P] leadership bandwidth
    per_slot = jnp.broadcast_to(pot[:, None], state.assignment.shape)
    per_slot = jnp.where(state.slot_exists(), per_slot, 0.0)
    return _segment_sum_by_broker(
        per_slot[:, :, None], state.assignment, state.num_brokers
    )[:, 0]


def broker_topic_replica_count(state: ClusterState) -> jax.Array:
    """int32 [B, T] — replicas of each topic per broker (TopicReplicaDistributionGoal)."""
    t = state.num_topics
    topic_per_slot = jnp.broadcast_to(
        state.partition_topic[:, None], state.assignment.shape
    )
    onehot = jax.nn.one_hot(topic_per_slot, t, dtype=jnp.int32)  # [P, S, T]
    onehot = jnp.where(state.slot_exists()[:, :, None], onehot, 0)
    return _segment_sum_by_broker(onehot, state.assignment, state.num_brokers)


def broker_topic_leader_count(state: ClusterState) -> jax.Array:
    """int32 [B, T] — leaders of each topic per broker (MinTopicLeadersPerBrokerGoal)."""
    lb = state.leader_broker()
    ids = jnp.where(lb >= 0, lb, state.num_brokers)
    onehot = jax.nn.one_hot(state.partition_topic, state.num_topics, dtype=jnp.int32)
    out = jax.ops.segment_sum(onehot, ids, num_segments=state.num_brokers + 1)
    return out[: state.num_brokers]


def replica_rack(state: ClusterState) -> jax.Array:
    """int32 [P, S] — rack id of each replica's broker (-1 for empty slots)."""
    racks = jnp.where(
        state.assignment >= 0,
        state.broker_rack[jnp.clip(state.assignment, 0)],
        -1,
    )
    return racks


# ---------------------------------------------------------------------------------
# Mutators → pure functions (upstream ClusterModel.relocateReplica / ...Leadership)
# ---------------------------------------------------------------------------------

def _functional_set(arr, idx, val):
    """Pure single-element update for either array family: ``.at[].set``
    on jax arrays (incl. tracers under jit), copy-assign on host numpy —
    ClusterState is host-first, but these mutators must stay jittable."""
    if isinstance(arr, jax.Array):
        return arr.at[idx].set(val)
    out = arr.copy()
    out[idx] = val
    return out


def apply_move(
    state: ClusterState, partition: jax.Array, slot: jax.Array, dest_broker: jax.Array
) -> ClusterState:
    """Inter-broker replica movement: move ``(partition, slot)`` to ``dest_broker``.

    Upstream ``ClusterModel.relocateReplica``.  Offline flag clears: a moved
    replica lands on a healthy broker/disk.
    """
    return state.replace(
        assignment=_functional_set(
            state.assignment, (partition, slot),
            dest_broker.astype(state.assignment.dtype)
            if isinstance(dest_broker, jax.Array)
            else np.int32(dest_broker),
        ),
        replica_offline=_functional_set(
            state.replica_offline, (partition, slot), False
        ),
    )


def apply_leadership(
    state: ClusterState, partition: jax.Array, new_leader_slot: jax.Array
) -> ClusterState:
    """Leadership movement (upstream ``ClusterModel.relocateLeadership``)."""
    return state.replace(
        leader_slot=_functional_set(
            state.leader_slot, partition,
            new_leader_slot.astype(state.leader_slot.dtype)
            if isinstance(new_leader_slot, jax.Array)
            else np.int32(new_leader_slot),
        )
    )


def apply_swap(
    state: ClusterState,
    partition_a: jax.Array,
    slot_a: jax.Array,
    partition_b: jax.Array,
    slot_b: jax.Array,
) -> ClusterState:
    """Inter-broker replica swap: replica A and replica B trade brokers.

    Upstream ``ActionType.INTER_BROKER_REPLICA_SWAP``.
    """
    broker_a = state.assignment[partition_a, slot_a]
    broker_b = state.assignment[partition_b, slot_b]
    assignment = _functional_set(state.assignment, (partition_a, slot_a), broker_b)
    assignment = _functional_set(assignment, (partition_b, slot_b), broker_a)
    offline = _functional_set(state.replica_offline, (partition_a, slot_a), False)
    offline = _functional_set(offline, (partition_b, slot_b), False)
    return state.replace(assignment=assignment, replica_offline=offline)


def set_broker_state(
    state: ClusterState, broker: jax.Array, new_state: BrokerState
) -> ClusterState:
    """Upstream ``ClusterModel.setBrokerState``.  Marking a broker DEAD also
    marks its replicas offline (they become the "immigrants" hard goals must
    evacuate, SURVEY.md §5.3)."""
    bs = _functional_set(state.broker_state, broker, np.int8(new_state))
    offline = state.replica_offline
    if new_state in (BrokerState.DEAD, BrokerState.REMOVED):
        offline = offline | (state.assignment == broker)
    return state.replace(broker_state=bs, replica_offline=offline)


# ---------------------------------------------------------------------------------
# Validation (host-side; upstream ClusterModel.sanityCheck)
# ---------------------------------------------------------------------------------

def sanity_check(state: ClusterState) -> None:
    """Host-side structural checks; raises AssertionError on violation."""
    import numpy as np

    a = np.asarray(state.assignment)
    p, s = a.shape
    assert state.leader_slot.shape == (p,)
    assert state.leader_load.shape == (p, NUM_RESOURCES)
    assert state.follower_load.shape == (p, NUM_RESOURCES)
    assert state.partition_topic.shape == (p,)
    assert state.replica_offline.shape == (p, s)
    b = state.num_brokers
    assert state.broker_rack.shape == (b,)
    assert state.broker_state.shape == (b,)
    if a.size:
        assert a.max() < b, "assignment references unknown broker"
        assert a.min() >= EMPTY_SLOT
    ls = np.asarray(state.leader_slot)
    assert (ls >= 0).all() and (ls < s).all()
    # leader slot must be occupied
    if a.size:
        leader_brokers = np.take_along_axis(a, ls[:, None], axis=1)[:, 0]
        assert (leader_brokers != EMPTY_SLOT).all(), "leader on empty slot"
    # no duplicate brokers within a partition (ignoring empty slots)
    for row in a:
        occ = row[row != EMPTY_SLOT]
        assert len(set(occ.tolist())) == len(occ), "duplicate broker in partition"
    topics = np.asarray(state.partition_topic)
    if p:
        assert topics.max() < max(state.num_topics, 1)


def dataclass_summary(state: ClusterState) -> str:
    return (
        f"ClusterState(P={state.num_partitions}, S={state.max_replication_factor}, "
        f"B={state.num_brokers}, T={state.num_topics})"
    )
