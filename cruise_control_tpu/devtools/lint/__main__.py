"""``python -m cruise_control_tpu.devtools.lint`` / the ``cclint``
console script.  Exit status: 0 = clean, 1 = findings, 2 = usage."""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from cruise_control_tpu.devtools.lint.driver import (
    RULES,
    default_target,
    render,
    run_lint,
)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="cclint",
        description="repo-native static analysis: lock discipline, JAX "
                    "hot-path hygiene, config/doc/metric drift "
                    "(docs/STATIC_ANALYSIS.md)",
    )
    parser.add_argument(
        "paths", nargs="*",
        help=f"files or directories to lint (default: {default_target()})",
    )
    parser.add_argument(
        "--format", choices=("text", "json", "sarif"), default="text",
        help="output format (json follows tests/schemas/lint.schema.json; "
             "sarif is the 2.1.0 profile in tests/schemas/"
             "sarif.schema.json for editor/CI annotation)",
    )
    parser.add_argument(
        "--stats", action="store_true",
        help="print phase accounting (files parsed, cache hits, graph "
             "build ms) — the budget test asserts on these",
    )
    parser.add_argument(
        "--rule", action="append", default=None, metavar="ID[,ID]",
        help="run only these rule ids (repeatable or comma-separated)",
    )
    parser.add_argument(
        "--changed-only", action="store_true",
        help="lint only files changed vs git HEAD (plus untracked) — "
             "the fast pre-commit mode",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule ids and summaries, then exit",
    )
    parser.add_argument(
        "--lock-graph", metavar="PATH", default=None,
        help="write the global lock-order graph (cc-tpu-lock-graph/1) "
             "to PATH after linting — the committed LOCK_GRAPH_r*.json "
             "artifacts are generated this way",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule_id, rule in sorted(RULES.items()):
            print(f"{rule_id}: {rule.summary}")
        return 0

    rules = None
    if args.rule:
        rules = [r.strip() for spec in args.rule for r in spec.split(",")
                 if r.strip()]
        unknown = sorted(set(rules) - set(RULES))
        if unknown:
            print(f"cclint: unknown rule(s) {unknown}; known: "
                  f"{sorted(RULES)}", file=sys.stderr)
            return 2

    result = run_lint(paths=args.paths or None, rules=rules,
                      changed_only=args.changed_only)
    if args.lock_graph:
        import json
        import pathlib

        from cruise_control_tpu.devtools.lint.rules_lockorder import (
            build_lock_graph,
        )

        artifact = build_lock_graph(result.project)
        pathlib.Path(args.lock_graph).write_text(
            json.dumps(artifact, indent=1, sort_keys=True) + "\n")
    print(render(result, args.format, show_stats=args.stats))
    return 1 if result.findings else 0


if __name__ == "__main__":
    sys.exit(main())
