"""swallowed-exception — daemon loops must not eat errors silently.

The service's daemon loops (flight-recorder sampler, detector
scheduler, executor drive phases, fetcher manager) all follow the same
pattern: catch broadly so one bad iteration cannot kill the thread,
**but say so** — log the exception or journal it.  A ``try/except
Exception: pass`` inside a loop converts a persistent failure into a
silent flatline: the thread looks alive, the work never happens, and
nothing points at why (exactly how the pre-telemetry Meter races hid).

Flagged: an ``except`` handler that (a) catches ``Exception``,
``BaseException``, or everything (bare), (b) sits lexically inside a
``for``/``while`` loop, and (c) neither re-raises nor records —
no logging call (``LOG.exception(...)``, ``logger.warning(...)``, …),
no ``events.emit(...)``, no metric ``.inc()``/``.mark()``.
"""

from __future__ import annotations

import ast
from typing import List

from cruise_control_tpu.devtools.lint.context import FileContext
from cruise_control_tpu.devtools.lint.findings import Finding

RULE_ID = "swallowed-exception"

_BROAD = {"Exception", "BaseException"}
_RECORDING_CALLS = {"exception", "warning", "error", "critical", "info",
                    "debug", "log", "emit", "inc", "mark"}


def _catches_broadly(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    for n in names:
        name = n.attr if isinstance(n, ast.Attribute) else getattr(
            n, "id", None)
        if name in _BROAD:
            return True
    return False


def _records(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else getattr(
                f, "id", None)
            if name in _RECORDING_CALLS:
                return True
    return False


def find_swallowed_in_loops(tree: ast.AST, parents=None, nodes=None):
    """(lineno,) for every broad, silent handler inside a loop."""
    if nodes is None:
        nodes = list(ast.walk(tree))
    if parents is None:
        parents = {}
        for node in nodes:
            for child in ast.iter_child_nodes(node):
                parents[child] = node
    out = []
    for node in nodes:
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _catches_broadly(node) or _records(node):
            continue
        cur = node
        in_loop = False
        while cur in parents:
            cur = parents[cur]
            if isinstance(cur, (ast.For, ast.While)):
                in_loop = True
                break
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break  # loop outside the enclosing function doesn't count
        if in_loop:
            out.append(node.lineno)
    return out


class SwallowedExceptionRule:
    id = RULE_ID
    summary = ("broad except handlers inside daemon loops must log, "
               "journal, or re-raise — silent flatlines are undebuggable")

    def check_file(self, ctx: FileContext) -> List[Finding]:
        return [
            Finding(
                ctx.path, lineno, self.id,
                "broad except inside a loop neither logs, journals, nor "
                "re-raises — a persistent failure here becomes a silent "
                "flatline; add LOG.exception(...)/events.emit(...) or "
                "narrow the catch",
            )
            for lineno in find_swallowed_in_loops(ctx.tree, ctx.parents,
                                                  ctx.all_nodes)
        ]
