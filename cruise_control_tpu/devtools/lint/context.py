"""Per-file parse context: one ``ast.parse`` per file, shared by every
rule (the driver's single-parse contract — the wall-clock budget in
``tests/test_cclint.py`` holds the pass to < 5 s over the package).

Besides the tree itself the context memoizes the two traversal products
every rule wants — the flat node list and the child → parent map — so
the N rules of the pass pay for ONE full walk instead of N (profiling
showed repeated ``ast.walk`` dominating the per-file cost once the rule
pack grew past a handful of rules)."""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional


@dataclasses.dataclass
class FileContext:
    path: str                 # as reported in findings
    text: str
    lines: List[str]
    tree: ast.Module
    _parents: Optional[Dict[ast.AST, ast.AST]] = None
    _all_nodes: Optional[List[ast.AST]] = None

    @classmethod
    def parse(cls, path: str, text: str) -> "FileContext":
        return cls(path=path, text=text, lines=text.splitlines(),
                   tree=ast.parse(text, filename=path))

    @property
    def all_nodes(self) -> List[ast.AST]:
        """Every node of the tree in ``ast.walk`` (BFS) order, computed
        once per file.  Rules iterate this instead of re-walking."""
        if self._all_nodes is None:
            self._all_nodes = list(ast.walk(self.tree))
        return self._all_nodes

    @property
    def parents(self) -> Dict[ast.AST, ast.AST]:
        """child → parent map, built lazily once per file."""
        if self._parents is None:
            parents: Dict[ast.AST, ast.AST] = {}
            for node in self.all_nodes:
                for child in ast.iter_child_nodes(node):
                    parents[child] = node
            self._parents = parents
        return self._parents

    def ancestors(self, node: ast.AST) -> List[ast.AST]:
        """Path from ``node`` up to the module, nearest parent first."""
        out = []
        cur = node
        parents = self.parents
        while cur in parents:
            cur = parents[cur]
            out.append(cur)
        return out
