"""Per-file parse context: one ``ast.parse`` per file, shared by every
rule (the driver's single-parse contract — the wall-clock budget in
``tests/test_cclint.py`` holds the pass to < 5 s over the package)."""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional


@dataclasses.dataclass
class FileContext:
    path: str                 # as reported in findings
    text: str
    lines: List[str]
    tree: ast.Module
    _parents: Optional[Dict[ast.AST, ast.AST]] = None

    @classmethod
    def parse(cls, path: str, text: str) -> "FileContext":
        return cls(path=path, text=text, lines=text.splitlines(),
                   tree=ast.parse(text, filename=path))

    @property
    def parents(self) -> Dict[ast.AST, ast.AST]:
        """child → parent map, built lazily once per file."""
        if self._parents is None:
            parents: Dict[ast.AST, ast.AST] = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    parents[child] = node
            self._parents = parents
        return self._parents

    def ancestors(self, node: ast.AST) -> List[ast.AST]:
        """Path from ``node`` up to the module, nearest parent first."""
        out = []
        cur = node
        parents = self.parents
        while cur in parents:
            cur = parents[cur]
            out.append(cur)
        return out
