"""bounded-resource — growable runtime resources need an explicit bound.

This PR's front door bounds every queue between a client and the
analyzer (admission queue, per-class concurrency limits, worker pool);
this rule keeps the rest of the tree honest to the same discipline.  An
unbounded buffer is the classic overload failure: under sustained
pressure it converts load into memory growth and tail latency instead of
backpressure, and the process falls over minutes *after* the overload
began — the journal then blames the victim allocation, not the queue.

Flagged constructions (non-test code):

* ``queue.Queue()`` / ``LifoQueue()`` / ``PriorityQueue()`` with no
  ``maxsize`` (positional or keyword), and ``SimpleQueue()`` which has
  no bound at all;
* ``collections.deque(...)`` with no ``maxlen=``;
* ``ThreadPoolExecutor(...)`` with no ``max_workers`` (the default
  scales with CPU count — an implicit, machine-dependent bound is still
  a reviewed decision; say it explicitly).

A bound passed as a variable counts (the rule checks presence, not
value).  Deliberate unbounded structures take the usual
``# cclint: disable=bounded-resource -- reason`` with a MANDATORY
reason.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from cruise_control_tpu.devtools.lint.context import FileContext
from cruise_control_tpu.devtools.lint.findings import Finding

RULE_ID = "bounded-resource"

#: constructor name → (bound kwarg, positional index of the bound, hint)
_BOUNDED_CTORS = {
    "Queue": ("maxsize", 0, "queue.Queue(maxsize=N)"),
    "LifoQueue": ("maxsize", 0, "queue.LifoQueue(maxsize=N)"),
    "PriorityQueue": ("maxsize", 0, "queue.PriorityQueue(maxsize=N)"),
    "deque": ("maxlen", 1, "deque(maxlen=N)"),
    "ThreadPoolExecutor": ("max_workers", 0,
                           "ThreadPoolExecutor(max_workers=N)"),
}


def _ctor_name(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _module_of(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        return f.value.id
    return None


def find_unbounded(tree: ast.AST, nodes=None) -> List[tuple]:
    """(lineno, message) per unbounded construction."""
    out: List[tuple] = []
    for node in (nodes if nodes is not None else ast.walk(tree)):
        if not isinstance(node, ast.Call):
            continue
        name = _ctor_name(node)
        if name == "SimpleQueue":
            mod = _module_of(node)
            if mod in (None, "queue"):
                out.append((
                    node.lineno,
                    "queue.SimpleQueue has no capacity bound — use "
                    "queue.Queue(maxsize=N) so overload backpressures "
                    "instead of growing memory",
                ))
            continue
        spec = _BOUNDED_CTORS.get(name)
        if spec is None:
            continue
        kwarg, pos, hint = spec
        # a Queue()-named constructor from an unrelated module (e.g.
        # multiprocessing) still deserves the bound; only obvious
        # non-library attributes (self.Queue) are skipped
        if isinstance(node.func, ast.Attribute) and not isinstance(
                node.func.value, ast.Name):
            continue
        if any(kw.arg == kwarg for kw in node.keywords):
            continue
        if any(kw.arg is None for kw in node.keywords):
            continue  # **kwargs may carry the bound — benefit of the doubt
        if len(node.args) > pos:
            # positional bound present — but an explicit None is unbounded
            arg = node.args[pos]
            if not (isinstance(arg, ast.Constant) and arg.value is None):
                continue
        out.append((
            node.lineno,
            f"{name}(...) without an explicit bound — pass {hint} (or "
            f"suppress with a reason if unbounded is a reviewed decision)",
        ))
    return out


class BoundedResourceRule:
    id = RULE_ID
    summary = ("growable resources (Queue/deque/ThreadPoolExecutor) must "
               "declare an explicit bound")

    def check_file(self, ctx: FileContext) -> List[Finding]:
        return [
            Finding(ctx.path, lineno, self.id, message)
            for lineno, message in find_unbounded(ctx.tree, ctx.all_nodes)
        ]
