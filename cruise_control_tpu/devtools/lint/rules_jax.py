"""jax-hot-path — host-sync and retrace hygiene inside jitted code.

The engine's throughput story depends on jitted functions staying on
device: one host sync inside a traced function serializes every step
behind a device→host transfer, and a retrace (new static-arg value or
new shape) pays seconds-to-minutes of XLA compile time on what looks
like an innocent call.  The compile/retrace telemetry in
``telemetry/device_stats.py`` catches these at runtime; this rule
catches the textual patterns before they ship.

Jit contexts: functions decorated with ``jax.jit``/``pjit`` (including
``functools.partial(jax.jit, ...)``), functions passed to a
``jax.jit(...)`` call by name (the ``jax.jit(run)`` /
``device_stats.instrument("name", jax.jit(run))`` idiom), and defs
nested inside either (closures trace too).

Flags, inside a jit context:

* host syncs — ``.item()``, ``.tolist()``, ``.block_until_ready()``,
  ``jax.device_get(...)``, ``np.asarray``/``np.array`` on traced
  values, and ``print`` (use ``jax.debug.print``);
* ``float()``/``int()``/``bool()`` applied directly to a traced
  parameter (concretization — crashes under trace or silently syncs);
* Python ``if``/``while``/``assert`` whose test references a traced
  (non-static) parameter directly — data-dependent control flow
  belongs in ``lax.cond``/``lax.while_loop``/``jnp.where``.

Flags, at call sites of known-jitted callables:

* an f-string argument (a distinct cache key per distinct string —
  retraces forever) or a dict literal argument (unhashable as a static
  arg, a fresh pytree structure otherwise).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from cruise_control_tpu.devtools.lint.context import FileContext
from cruise_control_tpu.devtools.lint.findings import Finding

RULE_ID = "jax-hot-path"

_JIT_NAMES = {"jit", "pjit"}
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_NP_MODULES = {"np", "numpy", "onp"}
_NP_SYNC_FUNCS = {"asarray", "array"}
_CONCRETIZERS = {"float", "int", "bool"}


def _dotted_tail(func: ast.expr) -> Optional[str]:
    """`jax.jit` → 'jit', `jit` → 'jit', `functools.partial` → 'partial'."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _jit_call(node: ast.expr) -> Optional[ast.Call]:
    """The ``jax.jit(...)``/``pjit(...)`` Call inside ``node``, seeing
    through ``functools.partial(jax.jit, ...)``.  Returns the call whose
    keywords carry static_argnums/static_argnames."""
    if not isinstance(node, ast.Call):
        return None
    tail = _dotted_tail(node.func)
    if tail in _JIT_NAMES:
        return node
    if tail == "partial" and node.args:
        if _dotted_tail(node.args[0]) in _JIT_NAMES:
            return node
    return None


def _static_params(fn: ast.AST, jit: Optional[ast.Call]) -> Set[str]:
    """Parameter names excluded from tracing by static_argnums/names."""
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return set()
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    static: Set[str] = set()
    for kw in (jit.keywords if jit is not None else ()):
        if kw.arg == "static_argnums":
            vals = (kw.value.elts
                    if isinstance(kw.value, (ast.Tuple, ast.List))
                    else [kw.value])
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(v.value, int) \
                        and 0 <= v.value < len(params):
                    static.add(params[v.value])
        elif kw.arg == "static_argnames":
            vals = (kw.value.elts
                    if isinstance(kw.value, (ast.Tuple, ast.List))
                    else [kw.value])
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    static.add(v.value)
    return static


def find_jit_functions(tree: ast.Module, nodes=None):
    """[(FunctionDef, static_param_names)] for every jit context in the
    module: decorated defs, defs passed by name to a jit call, and defs
    nested inside either."""
    jitted = {}
    if nodes is None:
        nodes = list(ast.walk(tree))

    # decorator form
    for node in nodes:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            jit = _jit_call(dec)
            if jit is not None or _dotted_tail(dec) in _JIT_NAMES:
                jitted[node] = _static_params(node, jit)

    # jax.jit(f) on a local def — match by name, nearest def wins
    defs_by_name = {}
    for node in nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, node)
    for node in nodes:
        jit = _jit_call(node)
        if jit is None or jit is not node:
            continue
        args = node.args[1:] if _dotted_tail(node.func) == "partial" \
            else node.args
        for a in args[:1]:
            if isinstance(a, ast.Name) and a.id in defs_by_name:
                fn = defs_by_name[a.id]
                jitted.setdefault(fn, _static_params(fn, jit))

    # nested defs trace with their parent
    for fn in list(jitted):
        for node in ast.walk(fn):
            if node is not fn and isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                jitted.setdefault(node, set())
    return [(fn, static) for fn, static in jitted.items()]


def find_jitted_names(tree: ast.Module, nodes=None) -> Set[str]:
    """Names bound to jit-wrapped callables at module/function level:
    ``f = jax.jit(g)``, ``self._x = jax.jit(g)`` (attr tail), and
    decorated defs."""
    names: Set[str] = set()
    for node in (nodes if nodes is not None else ast.walk(tree)):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_jit_call(d) is not None or _dotted_tail(d) in _JIT_NAMES
                   for d in node.decorator_list):
                names.add(node.name)
        elif isinstance(node, ast.Assign) and _jit_call(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    names.add(tgt.id)
                elif isinstance(tgt, ast.Attribute):
                    names.add(tgt.attr)
    return names


def _traced_name_in_test(test: ast.expr, params: Set[str]) -> Optional[str]:
    """The first traced-parameter name a branch test depends on, if any.

    Names inside ``x is None`` / ``x is not None`` comparisons are
    exempt: None-ness is pytree STRUCTURE, resolved at trace time (the
    ``if t_cap is None: t_cap = jnp.int32(T)`` default-argument idiom),
    not a data-dependent branch."""
    structural = set()
    for node in ast.walk(test):
        if (isinstance(node, ast.Compare)
                and all(isinstance(op, (ast.Is, ast.IsNot))
                        for op in node.ops)
                and all(isinstance(c, ast.Constant) and c.value is None
                        for c in node.comparators)):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name):
                    structural.add(sub)
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and node.id in params \
                and node not in structural:
            return node.id
    return None


def _walk_own_body(fn: ast.AST):
    """Walk ``fn`` without descending into nested defs (those are their
    own jit contexts in :func:`find_jit_functions` — walking them here
    too would double-report every finding)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def find_host_syncs(tree: ast.Module, nodes=None):
    """(lineno, description) for host-sync / traced-branching patterns
    inside jit contexts."""
    out = []
    for fn, static in find_jit_functions(tree, nodes):
        params = {a.arg for a in fn.args.posonlyargs + fn.args.args
                  + fn.args.kwonlyargs} - static
        for node in _walk_own_body(fn):
            if isinstance(node, ast.Call):
                f = node.func
                tail = _dotted_tail(f)
                if isinstance(f, ast.Attribute) and f.attr in _SYNC_METHODS:
                    out.append((node.lineno,
                                f".{f.attr}() host sync"))
                elif isinstance(f, ast.Attribute) \
                        and f.attr == "device_get" :
                    out.append((node.lineno, "jax.device_get host sync"))
                elif (isinstance(f, ast.Attribute)
                      and f.attr in _NP_SYNC_FUNCS
                      and isinstance(f.value, ast.Name)
                      and f.value.id in _NP_MODULES):
                    out.append((node.lineno,
                                f"{f.value.id}.{f.attr}() materializes the "
                                "traced value on host"))
                elif tail == "print" and isinstance(f, ast.Name):
                    out.append((node.lineno,
                                "print() inside a jitted function (runs at "
                                "trace time only, or syncs — use "
                                "jax.debug.print)"))
                elif (tail in _CONCRETIZERS and isinstance(f, ast.Name)
                      and len(node.args) == 1
                      and isinstance(node.args[0], ast.Name)
                      and node.args[0].id in params):
                    out.append((node.lineno,
                                f"{tail}() concretizes traced parameter "
                                f"'{node.args[0].id}'"))
            elif isinstance(node, (ast.If, ast.While)):
                name = _traced_name_in_test(node.test, params)
                if name is not None:
                    out.append((
                        node.lineno,
                        "Python branching on traced parameter "
                        f"'{name}' — use lax.cond/lax.while_loop/"
                        "jnp.where",
                    ))
            elif isinstance(node, ast.Assert):
                name = _traced_name_in_test(node.test, params)
                if name is not None:
                    out.append((
                        node.lineno,
                        f"assert on traced parameter '{name}' "
                        "(concretizes under trace)",
                    ))
    return out


def find_retrace_risks(tree: ast.Module, nodes=None):
    """(lineno, description) for calls to known-jitted callables passing
    f-string or dict-literal arguments."""
    jitted = find_jitted_names(tree, nodes)
    out = []
    for node in (nodes if nodes is not None else ast.walk(tree)):
        if not isinstance(node, ast.Call):
            continue
        tail = _dotted_tail(node.func)
        if tail not in jitted:
            continue
        for a in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(a, ast.JoinedStr):
                out.append((node.lineno,
                            f"f-string argument to jitted '{tail}' — a new "
                            "jit cache key per distinct string (retrace "
                            "risk); hoist the string or make it static "
                            "data"))
            elif isinstance(a, ast.Dict):
                out.append((node.lineno,
                            f"dict-literal argument to jitted '{tail}' — "
                            "unhashable as a static arg and a fresh pytree "
                            "otherwise; pass a hashable/frozen structure"))
    return out


class JaxHotPathRule:
    id = RULE_ID
    summary = ("no host syncs, traced-value branching, or retrace-risk "
               "arguments inside/at jitted functions")

    def check_file(self, ctx: FileContext) -> List[Finding]:
        out = []
        nodes = ctx.all_nodes
        for lineno, desc in find_host_syncs(ctx.tree, nodes):
            out.append(Finding(ctx.path, lineno, self.id, desc))
        for lineno, desc in find_retrace_risks(ctx.tree, nodes):
            out.append(Finding(ctx.path, lineno, self.id, desc))
        return out
