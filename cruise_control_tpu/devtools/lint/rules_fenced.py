"""fenced-backend-discipline — mutating admin calls go through the fence.

ISSUE 15 made execution safe under concurrent controllers: every
mutating ``ClusterBackend`` call (``alter_partition_reassignments``,
``elect_leaders``, ``alter_replica_log_dirs``, ``cancel_reassignments``,
``set_throttles``, ``clear_throttles``, ``alter_config``) presents the
owner's controller epoch via
:class:`cruise_control_tpu.executor.backend.FencedClusterBackend`, so a
zombie process is refused at the cluster seam instead of double-moving
replicas.  A mutating call issued anywhere else on a RAW backend
reference reopens the hole: the write skips the epoch check, and a
fenced-out process can still corrupt placements through that one path.

Findings: any call whose callee tail is a mutating admin method,
outside the backend implementations themselves
(``executor/backend.py`` — the wrapper and the simulated cluster;
``kafka/backend.py`` — the wire adapter; ``sim/backend.py`` — the
scripted cluster's fault machinery, which *plays* the foreign writer on
purpose), unless the receiver is one of the blessed fenced routes:

* ``self.backend`` — the executor's (and throttle helper's) handle,
  which IS the fenced wrapper at runtime;
* ``self.throttle_helper`` — the helper whose same-named lifecycle
  methods route through its fenced ``self.backend``.

Aliasing past the fence (``raw = self.backend.inner; raw.alter_...``,
``SimulatedClusterBackend.alter_...(b, ...)`` via a direct-name import,
a bare ``backend`` parameter) all land on a non-blessed receiver and
are flagged.  Evaluated over the phase-1 summaries (no re-parse).
"""

from __future__ import annotations

import pathlib
from typing import List

from cruise_control_tpu.devtools.lint.findings import Finding

RULE_ID = "fenced-backend-discipline"

#: the mutating admin surface that must present the controller epoch
_MUTATING = frozenset((
    "alter_partition_reassignments",
    "elect_leaders",
    "alter_replica_log_dirs",
    "cancel_reassignments",
    "set_throttles",
    "clear_throttles",
    "alter_config",
))

#: modules allowed to touch the raw admin surface (the implementations)
_ALLOWED_SUFFIXES = (
    ("executor", "backend.py"),
    ("kafka", "backend.py"),
    ("sim", "backend.py"),
)

#: receivers that ARE the fenced route at runtime
_ALLOWED_RECEIVERS = frozenset(("self.backend", "self.throttle_helper"))


class FencedBackendDisciplineRule:
    id = RULE_ID
    summary = ("mutating ClusterBackend admin calls outside the backend "
               "implementations must go through the fenced wrapper "
               "(self.backend / self.throttle_helper) — raw-reference "
               "mutations skip the controller-epoch check")
    project_rule = True

    def check_project(self, project) -> List[Finding]:
        findings: List[Finding] = []
        for s in project.summaries:
            parts = pathlib.PurePath(s.path).parts
            if parts[-2:] in [tuple(sfx) for sfx in _ALLOWED_SUFFIXES]:
                continue
            for fn in s.functions.values():
                for call in fn.calls:
                    head, _, tail = call.callee.rpartition(".")
                    if tail not in _MUTATING or not head:
                        continue  # bare names are locals, not backends
                    if head in _ALLOWED_RECEIVERS:
                        continue
                    findings.append(Finding(
                        path=s.path, line=call.lineno, rule=self.id,
                        message=(
                            f"mutating backend call {call.callee}() in "
                            f"{fn.name or '<module>'} bypasses the "
                            "execution fence — route it through the "
                            "executor's fenced wrapper (self.backend, a "
                            "FencedClusterBackend) so the controller "
                            "epoch is presented; a raw-reference write "
                            "lets a fenced-out zombie double-move "
                            "replicas"
                        ),
                    ))
        return findings
