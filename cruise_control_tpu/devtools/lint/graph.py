"""Phase 1 of the whole-program pass: the project symbol graph.

``cclint`` grew up as a per-file rule pack; the interprocedural rules
(``cross-module-lock``, ``jax-transitive``, ``deadline-propagation``,
``journal-schema``) need a view that crosses the function and file
boundary.  This module extracts ONE picklable :class:`ModuleSummary`
per file — imports, classes (locks, attribute types), functions (call
sites with held-context info, attribute accesses, host-sync ops, jit
membership, event emits), config keys — and assembles the summaries
into a :class:`SymbolGraph` with import resolution and reverse
dependencies.  ``callgraph.py`` layers call edges and reachability on
top.

Summaries are pure data (no AST references), so they cache: the driver
stores them under ``.cclint_cache/`` keyed by file content hash, salted
with a hash of the lint package's own sources (editing any rule
invalidates everything).  A warm run re-extracts nothing and re-parses
only changed files; the whole-program phase then rebuilds the graph
from summaries in milliseconds, which is how the package-wide pass
stays inside the < 5 s budget in ``tests/test_cclint.py``.

Approximations (documented in docs/STATIC_ANALYSIS.md): receiver types
come from constructor assignments (``x = ClassName(...)``,
``self._y = ClassName(...)``), parameter annotations, and
``var = self`` aliasing — not from dataflow; calls through containers,
dynamic dispatch, and monkey-patching are invisible."""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import pathlib
from typing import Dict, List, Optional, Sequence, Set, Tuple

from cruise_control_tpu.devtools.lint import cfg as cfg_mod
from cruise_control_tpu.devtools.lint import rules_config

#: bump (or just edit any lint source — the salt covers it) to drop
#: cached summaries whose shape this module no longer understands
SUMMARY_VERSION = 2

_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_NP_MODULES = {"np", "numpy", "onp"}
_NP_SYNC_FUNCS = {"asarray", "array"}
_LOCK_CTORS = {"Lock", "RLock", "Condition", "InstrumentedLock"}
_SAFE_CTORS = {"Event", "Semaphore", "BoundedSemaphore", "Barrier",
               "InstrumentedSemaphore",
               "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
               "ThreadPoolExecutor", "ProcessPoolExecutor"}
_MUTATORS = {"append", "appendleft", "extend", "extendleft", "insert",
             "add", "update", "setdefault", "pop", "popleft", "popitem",
             "remove", "discard", "clear", "sort", "reverse", "rotate"}
#: callee-name pattern for compile-cache-key factories whose config
#: argument is normalized via dataclasses.replace(...)
_CACHE_FN_HINTS = ("_cached_", "_fn_cache", "cache_key")


# ---- summary records (all picklable, no AST) ------------------------------------
@dataclasses.dataclass(frozen=True)
class CallSite:
    callee: str                  # dotted as written: "f", "mod.f", "self._x.m"
    lineno: int
    nargs: int                   # positional arg count
    kwargs: Tuple[str, ...]      # keyword names present
    none_kwargs: Tuple[str, ...]  # keywords whose value is literal None
    arg_exprs: Tuple[str, ...]   # dotted reprs of the first args ("" = complex)
    with_ctxs: Tuple[str, ...]   # dotted with-contexts held at this site
    first_arg_false: bool = False  # first positional arg is literal False
    spawned: bool = False        # synthesized Thread(target=...) edge —
    #                              the callee runs on ANOTHER thread


@dataclasses.dataclass(frozen=True)
class AttrAccess:
    recv: str                    # "self", "x", "self._y" (dotted receiver)
    attr: str
    write: bool
    lineno: int
    with_ctxs: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class EmitSite:
    callee: str                  # "events.emit", "emit", "self._journal.emit"
    lineno: int
    kind: Optional[str]          # literal kind, None when dynamic
    fields: Tuple[str, ...]      # payload keyword names
    star: bool                   # **kwargs present → field set unknown
    severity: Optional[str]      # literal severity keyword, if any


@dataclasses.dataclass(frozen=True)
class BlockingOp:
    """One potentially blocking operation (I/O, unbounded wait, host
    sync).  ``kind`` gates applicability: "" is unconditional, "queue"
    requires the receiver to resolve to a queue type, "wait" marks a
    wait that releases its own condition lock while blocked."""

    lineno: int
    callee: str                  # dotted as written ("self._fh.flush")
    desc: str
    kind: str = ""


@dataclasses.dataclass
class FuncSummary:
    name: str                    # "f", "C.m", "start>Handler.do_GET"
    cls: Optional[str]           # innermost enclosing class name
    lineno: int
    params: Tuple[str, ...]
    annotations: Dict[str, str]  # param → dotted type as written
    calls: List[CallSite] = dataclasses.field(default_factory=list)
    accesses: List[AttrAccess] = dataclasses.field(default_factory=list)
    var_types: Dict[str, str] = dataclasses.field(default_factory=dict)
    sync_ops: List[Tuple[int, str]] = dataclasses.field(default_factory=list)
    attr_reads: List[Tuple[str, str, int]] = dataclasses.field(
        default_factory=list)          # (recv Name, attr, lineno)
    is_jit: bool = False
    static_params: Tuple[str, ...] = ()
    #: local/global lock bindings: var name → InstrumentedLock name literal
    lock_names: Dict[str, str] = dataclasses.field(default_factory=dict)
    blocking_ops: List[BlockingOp] = dataclasses.field(default_factory=list)
    #: ``return <call>(...)`` facts: (dotted callee, dotted first
    #: positional arg or None) — lockflow resolves context-manager
    #: factories (the model-generation-lock idiom) through these
    returns_calls: List[Tuple[str, Optional[str]]] = dataclasses.field(
        default_factory=list)
    #: control-flow graph, present only for functions with lock events
    cfg: Optional[cfg_mod.CFG] = None


@dataclasses.dataclass
class ClassSummary:
    name: str
    lineno: int
    bases: Tuple[str, ...]
    lock_attrs: Set[str] = dataclasses.field(default_factory=set)
    safe_attrs: Set[str] = dataclasses.field(default_factory=set)
    attr_types: Dict[str, str] = dataclasses.field(default_factory=dict)
    methods: Set[str] = dataclasses.field(default_factory=set)
    #: attr → InstrumentedLock/Semaphore name literal (Condition-wrapped
    #: locks resolve to the wrapped lock's name)
    lock_names: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class WallClockSite:
    """One ``time.time()``/``time.monotonic()``/argless ``datetime.now()``
    call, with the context the wall-clock-discipline rule scopes on."""

    lineno: int
    call: str                    # dotted callee as written
    func: str                    # innermost enclosing function name ("" =
    #                              module level)
    clock_param: bool            # an enclosing function takes an injected
    #                              clock/now parameter
    guarded: bool                # the documented `X if X is None else X`
    #                              wall-clock-as-fallback idiom


@dataclasses.dataclass
class ModuleSummary:
    path: str                                   # repo-relative (driver sets)
    module: Optional[str]                       # dotted name (driver sets)
    #: raw import records: (level, from_module or None, name, alias)
    imports: List[Tuple[int, Optional[str], str, str]] = dataclasses.field(
        default_factory=list)
    functions: Dict[str, FuncSummary] = dataclasses.field(
        default_factory=dict)
    classes: Dict[str, ClassSummary] = dataclasses.field(
        default_factory=dict)
    config_keys: List[Tuple[str, int]] = dataclasses.field(
        default_factory=list)
    emits: List[EmitSite] = dataclasses.field(default_factory=list)
    #: compile-cache-key normalization sites: (lineno, excluded key names)
    normalized_keys: List[Tuple[int, Tuple[str, ...]]] = dataclasses.field(
        default_factory=list)
    #: wall-clock reads (rules_wallclock consumes these in phase 2)
    wallclock_sites: List[WallClockSite] = dataclasses.field(
        default_factory=list)


# ---- dotted-expression helpers --------------------------------------------------
def dotted(node: ast.expr) -> Optional[str]:
    """``a.b.c`` → "a.b.c" for pure Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def anno_to_dotted(node: ast.expr) -> Optional[str]:
    """Annotation → dotted type: plain chains, forward-ref strings
    ("CruiseControlFacade"), and Optional[X] unwrapped to X."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        v = node.value.strip()
        return v if v.replace(".", "").replace("_", "").isalnum() else None
    if isinstance(node, ast.Subscript):
        head = dotted(node.value)
        if head and head.rsplit(".", 1)[-1] == "Optional":
            return anno_to_dotted(node.slice)
        return None
    return dotted(node)


def _lock_name_of(value: ast.expr) -> Optional[str]:
    """The name literal of an ``InstrumentedLock("name")`` /
    ``InstrumentedSemaphore(n, name="name")`` constructor, unwrapping
    ``Condition(InstrumentedLock("name"))`` — the named-lock vocabulary
    the concurrency rules order on."""
    if not isinstance(value, ast.Call):
        return None
    f = dotted(value.func)
    if f is None:
        return None
    tail = f.rsplit(".", 1)[-1]
    if tail in ("InstrumentedLock", "InstrumentedSemaphore"):
        for kw in value.keywords:
            if kw.arg == "name" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                return kw.value.value
    if tail == "InstrumentedLock":
        if value.args and isinstance(value.args[0], ast.Constant) \
                and isinstance(value.args[0].value, str):
            return value.args[0].value
    elif tail == "InstrumentedSemaphore":
        if len(value.args) >= 2 and isinstance(value.args[1], ast.Constant) \
                and isinstance(value.args[1].value, str):
            return value.args[1].value
    elif tail == "Condition" and value.args:
        return _lock_name_of(value.args[0])
    return None


def _with_ctx_expr(item: ast.withitem) -> Optional[str]:
    """The dotted string a with-item holds: a plain dotted expr for
    ``with self._lock:``, the call's dotted func for
    ``with deadline_scope(...):`` / ``with self.admission.admit(c):``."""
    expr = item.context_expr
    d = dotted(expr)
    if d is not None:
        return d
    if isinstance(expr, ast.Call):
        return dotted(expr.func)
    return None


def module_name_for(path: pathlib.Path) -> Tuple[Optional[str], pathlib.Path]:
    """(dotted module name, package root dir) by ascending while
    ``__init__.py`` exists — works for the real package and for fixture
    packages in tmp dirs alike.  A bare file outside any package gets its
    stem as module name and its parent as root."""
    path = path.resolve()
    parts = [path.stem] if path.name != "__init__.py" else []
    cur = path.parent
    while (cur / "__init__.py").exists():
        parts.insert(0, cur.name)
        nxt = cur.parent
        if nxt == cur:
            break
        cur = nxt
    if not parts:
        parts = [path.parent.name]
    return ".".join(parts), cur


# ---- extraction -----------------------------------------------------------------
class _Extractor:
    """One pass over a module tree producing a ModuleSummary."""

    def __init__(self, tree: ast.Module, jit_funcs=None):
        self.summary = ModuleSummary(path="", module=None)
        #: AST FunctionDef → (static param names) for jit contexts, from
        #: rules_jax.find_jit_functions (shared, single source of truth)
        self._jit: Dict[ast.AST, Set[str]] = dict(jit_funcs or ())
        self._scan_module(tree)

    # -- scope walk -------------------------------------------------------------
    # Function keys encode the lexical nesting: a method is
    # ``ClassKey.name``, a nested def is ``parentkey>name``, a class
    # defined inside a function keys as ``parentkey>ClassName`` (so the
    # Handler-inside-start() idiom resolves).  Closure lookups ascend by
    # splitting on ``>``.
    _MODULE_KEY = "<module>"

    def _scan_module(self, tree: ast.Module) -> None:
        for stmt in tree.body:
            if isinstance(stmt, (ast.Import, ast.ImportFrom)):
                self._record_import(stmt)
            elif isinstance(stmt, ast.If):
                # `if TYPE_CHECKING:` (and try/except import fallbacks
                # one level down) still bind names the resolver needs
                for sub in stmt.body + stmt.orelse:
                    if isinstance(sub, (ast.Import, ast.ImportFrom)):
                        self._record_import(sub)
                rec = self._module_func()
                self._scan_stmt(stmt, rec, (), cls_key=None,
                                func_key=self._MODULE_KEY)
            elif isinstance(stmt, ast.ClassDef):
                self._scan_class(stmt, prefix="")
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_function(stmt, cls_key=None, prefix="", sep="")
            else:
                rec = self._module_func()
                self._scan_stmt(stmt, rec, (), cls_key=None,
                                func_key=self._MODULE_KEY)

    def _module_func(self) -> FuncSummary:
        key = self._MODULE_KEY
        if key not in self.summary.functions:
            self.summary.functions[key] = FuncSummary(
                name=key, cls=None, lineno=0, params=(), annotations={})
        return self.summary.functions[key]

    def _record_import(self, stmt) -> None:
        if isinstance(stmt, ast.Import):
            for a in stmt.names:
                alias = a.asname or a.name.split(".")[0]
                target = a.name if a.asname else a.name.split(".")[0]
                self.summary.imports.append((0, None, target, alias))
        else:
            mod = stmt.module or ""
            for a in stmt.names:
                if a.name == "*":
                    continue
                self.summary.imports.append(
                    (stmt.level, mod, a.name, a.asname or a.name))

    def _scan_class(self, cls: ast.ClassDef, prefix: str) -> None:
        key = f"{prefix}>{cls.name}" if prefix else cls.name
        rec = ClassSummary(
            name=key, lineno=cls.lineno,
            bases=tuple(d for d in (dotted(b) for b in cls.bases) if d),
        )
        self.summary.classes[key] = rec
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                rec.methods.add(stmt.name)
                self._scan_function(stmt, cls_key=key, prefix=key, sep=".")
            elif isinstance(stmt, ast.ClassDef):
                self._scan_class(stmt, prefix)

    def _scan_function(self, fn, cls_key: Optional[str], prefix: str,
                       sep: str) -> None:
        key = f"{prefix}{sep}{fn.name}" if prefix else fn.name
        args = fn.args
        params = tuple(a.arg for a in args.posonlyargs + args.args
                       + args.kwonlyargs)
        annos = {
            a.arg: d
            for a in args.posonlyargs + args.args + args.kwonlyargs
            if a.annotation is not None
            and (d := anno_to_dotted(a.annotation)) is not None
        }
        rec = FuncSummary(name=key, cls=cls_key, lineno=fn.lineno,
                          params=params, annotations=annos)
        if fn in self._jit:
            rec.is_jit = True
            rec.static_params = tuple(sorted(self._jit[fn]))
        self.summary.functions[key] = rec
        for stmt in fn.body:
            self._scan_stmt(stmt, rec, (), cls_key=cls_key, func_key=key)
        # flow-sensitive rules need real control flow wherever locks are
        # touched; everything else stays summary-only (held = ∅)
        if cfg_mod.has_lock_events(fn):
            rec.cfg = cfg_mod.build_cfg(fn)

    # -- statement walk with held with-contexts --
    def _scan_stmt(self, node: ast.stmt, rec: FuncSummary,
                   held: Tuple[str, ...], cls_key: Optional[str],
                   func_key: str) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: its body runs later on whatever thread calls it
            self._scan_function(node, cls_key=cls_key, prefix=func_key,
                                sep=">")
            return
        if isinstance(node, ast.ClassDef):
            self._scan_class(node, prefix=func_key)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            ctxs = tuple(c for c in (_with_ctx_expr(i) for i in node.items)
                         if c)
            for i in node.items:
                self._scan_expr(i.context_expr, rec, held)
            inner = held + ctxs
            for stmt in node.body:
                self._scan_stmt(stmt, rec, inner, cls_key, func_key)
            return
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            value = node.value
            if value is not None:
                self._scan_expr(value, rec, held)
                self._note_binding(targets, value, rec)
            for tgt in targets:
                self._scan_target(tgt, rec, held)
            return
        if isinstance(node, ast.Delete):
            for tgt in node.targets:
                self._scan_target(tgt, rec, held)
            return
        if isinstance(node, ast.Return):
            # record `return Ctor(arg, ...)` so lockflow can resolve
            # context-manager factories (a function that wraps a lock in
            # a guard object and returns it — the model-generation-lock
            # idiom) back to the lock the guard's __enter__ acquires
            if isinstance(node.value, ast.Call):
                f = dotted(node.value.func)
                if f is not None:
                    arg = (dotted(node.value.args[0])
                           if node.value.args else None)
                    rec.returns_calls.append((f, arg))
            if node.value is not None:
                self._scan_expr(node.value, rec, held)
            return
        # compound statements: recurse with the same held set
        for field in ("body", "orelse", "finalbody"):
            for stmt in getattr(node, field, ()):
                self._scan_stmt(stmt, rec, held, cls_key, func_key)
        for handler in getattr(node, "handlers", ()):
            for stmt in handler.body:
                self._scan_stmt(stmt, rec, held, cls_key, func_key)
        for field in ("test", "iter", "value", "exc", "msg"):
            child = getattr(node, field, None)
            if isinstance(child, ast.expr):
                self._scan_expr(child, rec, held)

    def _note_binding(self, targets, value: ast.expr,
                      rec: FuncSummary) -> None:
        """Record receiver-type facts: ``x = ClassName(...)``,
        ``self._y = Lock()`` (class attr kinds), ``alias = self``,
        ``self.tasks = param or Ctor()`` (either operand types it), and
        ``self.cc = param`` when the parameter is annotated."""
        if isinstance(value, ast.BoolOp):
            operand = next(
                (v for v in value.values if isinstance(v, ast.Call)),
                next((v for v in value.values
                      if isinstance(v, ast.Name)), None))
            if operand is not None:
                self._note_binding(targets, operand, rec)
            return
        ctor = None
        if isinstance(value, ast.Call):
            ctor = dotted(value.func)
        elif isinstance(value, ast.Name) and value.id in rec.params:
            ctor = rec.annotations.get(value.id)
        is_self = isinstance(value, ast.Name) and value.id == "self"
        lock_name = _lock_name_of(value)
        for tgt in targets:
            if isinstance(tgt, ast.Name):
                if ctor is not None:
                    rec.var_types[tgt.id] = ctor
                elif is_self:
                    rec.var_types[tgt.id] = "<self>"
                if lock_name is not None:
                    rec.lock_names[tgt.id] = lock_name
            elif isinstance(tgt, ast.Attribute):
                d = dotted(tgt)
                if d is None or ctor is None:
                    continue
                if d.startswith("self.") and d.count(".") == 1 \
                        and rec.cls is not None:
                    attr = d.split(".", 1)[1]
                    csum = self.summary.classes.get(rec.cls)
                    if csum is not None:
                        tail = ctor.rsplit(".", 1)[-1]
                        if lock_name is not None:
                            csum.lock_names.setdefault(attr, lock_name)
                        if tail in _LOCK_CTORS:
                            csum.lock_attrs.add(attr)
                        elif tail in _SAFE_CTORS:
                            csum.safe_attrs.add(attr)
                            # the ctor is still a type fact: the
                            # blocking rule needs queue-typed receivers
                            csum.attr_types.setdefault(attr, ctor)
                        else:
                            csum.attr_types.setdefault(attr, ctor)

    def _scan_target(self, tgt: ast.expr, rec: FuncSummary,
                     held: Tuple[str, ...]) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._scan_target(el, rec, held)
            return
        node = tgt
        while isinstance(node, ast.Subscript):
            self._scan_expr(node.slice, rec, held)
            node = node.value
        d = dotted(node)
        if d is not None and "." in d:
            recv, attr = d.rsplit(".", 1)
            rec.accesses.append(AttrAccess(recv, attr, True,
                                           tgt.lineno, held))

    # -- expression walk --
    def _scan_expr(self, expr: ast.expr, rec: FuncSummary,
                   held: Tuple[str, ...]) -> None:
        nodes = list(ast.walk(expr))
        # a call's func attribute is the call site, not an attribute
        # read (self._shed(...) must not make _shed a "guarded attr")
        call_funcs = {id(n.func) for n in nodes
                      if isinstance(n, ast.Call)}
        for node in nodes:
            if isinstance(node, ast.Call):
                self._note_call(node, rec, held)
            elif isinstance(node, ast.Attribute) \
                    and id(node) not in call_funcs \
                    and isinstance(node.ctx, ast.Load):
                d = dotted(node)
                if d is None:
                    continue
                recv, attr = d.rsplit(".", 1)
                if recv == "self":
                    rec.accesses.append(AttrAccess(recv, attr, False,
                                                   node.lineno, held))
                elif "." not in recv:
                    rec.attr_reads.append((recv, attr, node.lineno))
            elif isinstance(node, (ast.Lambda,)):
                pass  # lambdas stay opaque (documented blind spot)

    def _note_call(self, node: ast.Call, rec: FuncSummary,
                   held: Tuple[str, ...]) -> None:
        callee = dotted(node.func)
        if callee is None:
            return
        tail = callee.rsplit(".", 1)[-1]
        # mutator calls on a dotted receiver are attribute writes
        if tail in _MUTATORS and "." in callee:
            base = callee.rsplit(".", 1)[0]
            if "." in base:
                recv, attr = base.rsplit(".", 1)
                rec.accesses.append(AttrAccess(recv, attr, True,
                                               node.lineno, held))
        kwargs = tuple(kw.arg for kw in node.keywords if kw.arg)
        none_kwargs = tuple(
            kw.arg for kw in node.keywords
            if kw.arg and isinstance(kw.value, ast.Constant)
            and kw.value.value is None
        )
        arg_exprs = tuple(dotted(a) or "" for a in node.args[:4])
        first_false = bool(
            node.args and isinstance(node.args[0], ast.Constant)
            and node.args[0].value is False
        )
        rec.calls.append(CallSite(
            callee=callee, lineno=node.lineno, nargs=len(node.args),
            kwargs=kwargs, none_kwargs=none_kwargs, arg_exprs=arg_exprs,
            with_ctxs=held, first_arg_false=first_false,
        ))
        # Thread(target=f): surface the target as an arg expr so the
        # call graph can treat it as called (kwarg order-independent)
        if tail == "Thread":
            for kw in node.keywords:
                if kw.arg == "target" and (d := dotted(kw.value)):
                    rec.calls.append(CallSite(
                        callee=d, lineno=node.lineno, nargs=0, kwargs=(),
                        none_kwargs=(), arg_exprs=(), with_ctxs=(),
                        spawned=True,
                    ))
        self._note_blocking(node, callee, tail, kwargs, rec)
        # host-sync ops, recorded for EVERY function: the transitive
        # jax rule decides whether a jit context reaches them
        if tail in _SYNC_METHODS and isinstance(node.func, ast.Attribute):
            rec.sync_ops.append((node.lineno, f".{tail}() host sync"))
        elif tail == "device_get" and "." in callee:
            rec.sync_ops.append((node.lineno, "jax.device_get host sync"))
        elif tail in _NP_SYNC_FUNCS and "." in callee \
                and callee.split(".", 1)[0] in _NP_MODULES:
            rec.sync_ops.append(
                (node.lineno, f"{callee}() materializes on host"))
        # events.emit(...) sites for the journal-schema rule
        if tail == "emit":
            kind = None
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                kind = node.args[0].value
            severity = None
            fields = []
            for kw in node.keywords:
                if kw.arg == "severity":
                    if isinstance(kw.value, ast.Constant):
                        severity = kw.value.value
                elif kw.arg in ("operation", "task_id", "kind"):
                    if kw.arg == "kind" and kind is None \
                            and isinstance(kw.value, ast.Constant):
                        kind = kw.value.value
                elif kw.arg is not None:
                    fields.append(kw.arg)
            if len(node.args) >= 2 and severity is None \
                    and isinstance(node.args[1], ast.Constant):
                severity = node.args[1].value
            self.summary.emits.append(EmitSite(
                callee=callee, lineno=node.lineno, kind=kind,
                fields=tuple(fields),
                star=any(kw.arg is None for kw in node.keywords),
                severity=severity,
            ))
        # config getter call sites (rules_config consumes these)
        if isinstance(node.func, ast.Attribute):
            claimed = tail in rules_config._TYPED_GETTERS
            if not claimed and tail == "get":
                recv = node.func.value
                name = (recv.id if isinstance(recv, ast.Name)
                        else recv.attr if isinstance(recv, ast.Attribute)
                        else None)
                claimed = name in rules_config._CONFIG_RECEIVERS
            if claimed and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                self.summary.config_keys.append(
                    (node.args[0].value, node.args[0].lineno))
        # compile-cache-key normalization: a *_cached_* factory taking a
        # dataclasses.replace(cfg, k=..., ...) argument declares k
        # excluded from the compile cache key
        if any(h in callee for h in _CACHE_FN_HINTS):
            for a in node.args:
                if isinstance(a, ast.Call) \
                        and dotted(a.func) in ("dataclasses.replace",
                                               "replace"):
                    keys = tuple(kw.arg for kw in a.keywords if kw.arg)
                    if keys:
                        self.summary.normalized_keys.append(
                            (node.lineno, keys))


    #: socket-shaped method tails (blocking network I/O)
    _SOCKET_TAILS = frozenset((
        "sendall", "recv", "recvfrom", "accept", "connect", "sendto",
    ))

    def _note_blocking(self, node: ast.Call, callee: str, tail: str,
                       kwargs: Tuple[str, ...], rec: FuncSummary) -> None:
        """Record the blocking-op vocabulary for rules_blocking: journal
        flush/fsync, socket I/O, host syncs, unbounded waits/joins and
        queue ops.  Bounded variants (a timeout argument) don't block
        indefinitely and are not recorded."""
        lineno = node.lineno
        ops = rec.blocking_ops
        if "." not in callee and tail != "sleep":
            return
        if tail == "flush" and not node.args:
            ops.append(BlockingOp(lineno, callee, "flush() file I/O"))
        elif tail == "fsync":
            ops.append(BlockingOp(lineno, callee, "fsync() disk barrier"))
        elif tail in self._SOCKET_TAILS:
            ops.append(BlockingOp(lineno, callee,
                                  f".{tail}() socket I/O"))
        elif callee == "time.sleep" or (tail == "sleep"
                                        and callee.endswith("time.sleep")):
            ops.append(BlockingOp(lineno, callee, "time.sleep()"))
        elif tail == "device_get":
            ops.append(BlockingOp(lineno, callee,
                                  "jax.device_get host sync"))
        elif tail == "block_until_ready":
            ops.append(BlockingOp(lineno, callee,
                                  ".block_until_ready() host sync"))
        elif tail == "wait" and not node.args and "timeout" not in kwargs:
            ops.append(BlockingOp(lineno, callee, "unbounded .wait()",
                                  kind="wait"))
        elif tail == "join" and not node.args and "timeout" not in kwargs:
            # zero-arg filter excludes str.join / os.path.join
            ops.append(BlockingOp(lineno, callee, "unbounded .join()"))
        elif tail == "result" and not node.args \
                and "timeout" not in kwargs:
            ops.append(BlockingOp(lineno, callee,
                                  "unbounded Future.result()"))
        elif tail == "get" and not node.args and not kwargs:
            ops.append(BlockingOp(lineno, callee, "blocking queue get()",
                                  kind="queue"))
        elif tail == "put" and "block" not in kwargs \
                and not node.keywords:
            ops.append(BlockingOp(lineno, callee, "blocking queue put()",
                                  kind="queue"))


#: parameter names that mark a function as receiving an injected clock —
#: inside such a function a direct wall-clock read is drift by definition
CLOCK_PARAMS = frozenset((
    "now", "now_ms", "now_s", "time_ms", "clock", "time_fn", "wall_clock",
))

#: the wall-clock reads the discipline rule cares about
_WALL_CALLS = frozenset(("time.time", "time.monotonic"))


def _is_wall_call(node: ast.Call) -> Optional[str]:
    d = dotted(node.func)
    if d is None:
        return None
    if d in _WALL_CALLS:
        return d
    # argless datetime.now() / datetime.datetime.now()
    if d.endswith("datetime.now") or d == "datetime.now":
        if not node.args and not node.keywords:
            return d
    return None


def _is_none_test(test: ast.expr) -> bool:
    return (
        isinstance(test, ast.Compare)
        and len(test.ops) == 1
        and isinstance(test.ops[0], (ast.Is, ast.IsNot))
        and any(isinstance(c, ast.Constant) and c.value is None
                for c in [test.left] + list(test.comparators))
    )


def _extract_wallclock(tree: ast.Module) -> List[WallClockSite]:
    """One recursive pass tracking the enclosing-function stack and the
    ``is None``-guard stack (the wall-clock-as-fallback idiom)."""
    sites: List[WallClockSite] = []

    def walk(node, funcs, clock_param, guarded):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = node.args
            params = {p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)}
            funcs = funcs + [node.name]
            clock_param = clock_param or bool(params & CLOCK_PARAMS)
        elif isinstance(node, ast.Lambda):
            a = node.args
            params = {p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)}
            clock_param = clock_param or bool(params & CLOCK_PARAMS)
        elif isinstance(node, (ast.IfExp, ast.If)) \
                and _is_none_test(node.test):
            guarded = True
        elif isinstance(node, ast.Call):
            call = _is_wall_call(node)
            if call is not None:
                sites.append(WallClockSite(
                    lineno=node.lineno, call=call,
                    func=funcs[-1] if funcs else "",
                    clock_param=clock_param, guarded=guarded,
                ))
        for child in ast.iter_child_nodes(node):
            walk(child, funcs, clock_param, guarded)

    walk(tree, [], False, False)
    return sites


def extract_summary(tree: ast.Module, nodes=None) -> ModuleSummary:
    """Build a ModuleSummary for one parsed file.  ``nodes`` is the
    FileContext's memoized flat node list (used only to find jit
    contexts without an extra walk)."""
    from cruise_control_tpu.devtools.lint.rules_jax import (
        find_jit_functions,
    )

    jit = [(fn, set(static)) for fn, static in
           find_jit_functions(tree, nodes)]
    summary = _Extractor(tree, jit).summary
    # the scope/guard walk only runs on files that read a wall clock at
    # all (the flat node list answers that in one cheap scan)
    if any(isinstance(n, ast.Call) and _is_wall_call(n) is not None
           for n in (nodes if nodes is not None else ast.walk(tree))):
        summary.wallclock_sites = _extract_wallclock(tree)
    return summary


# ---- the assembled graph --------------------------------------------------------
@dataclasses.dataclass
class SymbolGraph:
    """All module summaries plus resolution helpers."""

    modules: Dict[str, ModuleSummary]          # dotted module → summary
    by_path: Dict[str, ModuleSummary]          # finding path → summary
    package_roots: Dict[str, pathlib.Path]     # dotted module → pkg root

    def __post_init__(self):
        self._import_map: Dict[str, Dict[str, str]] = {}
        self._class_index: Dict[str, Tuple[str, ClassSummary]] = {}
        for mod, s in self.modules.items():
            for cname, csum in s.classes.items():
                self._class_index.setdefault(f"{mod}.{cname}", (mod, csum))

    # -- import resolution --
    def import_aliases(self, module: str) -> Dict[str, str]:
        """alias → absolute dotted target for one module."""
        cached = self._import_map.get(module)
        if cached is not None:
            return cached
        s = self.modules.get(module)
        out: Dict[str, str] = {}
        if s is not None:
            pkg_parts = module.split(".")[:-1]
            for level, from_mod, name, alias in s.imports:
                if level == 0 and from_mod is None:
                    out[alias] = name
                    continue
                if level == 0:
                    base = from_mod
                else:
                    up = pkg_parts[: len(pkg_parts) - (level - 1)]
                    base = ".".join(up + ([from_mod] if from_mod else []))
                out[alias] = f"{base}.{name}" if base else name
        self._import_map[module] = out
        return out

    def module_deps(self, module: str) -> Set[str]:
        """Project modules this module imports (for the import graph)."""
        out: Set[str] = set()
        for target in self.import_aliases(module).values():
            # target may be a module or a module attribute — try both
            if target in self.modules:
                out.add(target)
            else:
                parent = target.rsplit(".", 1)[0] if "." in target else None
                if parent in self.modules:
                    out.add(parent)
        out.discard(module)
        return out

    def reverse_deps(self) -> Dict[str, Set[str]]:
        """module → set of modules importing it (direct)."""
        rev: Dict[str, Set[str]] = {m: set() for m in self.modules}
        for m in self.modules:
            for dep in self.module_deps(m):
                if dep in rev:
                    rev[dep].add(m)
        return rev

    def dependents_closure(self, seeds: Set[str]) -> Set[str]:
        """seeds plus every module that transitively imports one."""
        rev = self.reverse_deps()
        out, stack = set(), list(seeds)
        while stack:
            m = stack.pop()
            if m in out:
                continue
            out.add(m)
            stack.extend(rev.get(m, ()))
        return out

    # -- symbol resolution --
    def resolve_class(self, module: str,
                      name: str) -> Optional[Tuple[str, ClassSummary]]:
        """A dotted class name as written in ``module`` → (defining
        module, ClassSummary), following import aliases."""
        s = self.modules.get(module)
        if s is None:
            return None
        if name in s.classes:
            return module, s.classes[name]
        aliases = self.import_aliases(module)
        head, _, rest = name.partition(".")
        target = aliases.get(head)
        if target is None:
            return self._class_index.get(name)
        full = f"{target}.{rest}" if rest else target
        hit = self._class_index.get(full)
        if hit is not None:
            return hit
        # alias may name a module: "mod.Class"
        if rest and target in self.modules:
            csum = self.modules[target].classes.get(rest)
            if csum is not None:
                return target, csum
        return None

    def class_method(self, module: str, csum: ClassSummary,
                     method: str, _depth=0):
        """(module, FuncSummary) for a method, ascending base classes
        (project classes only, left-to-right, depth-capped)."""
        s = self.modules.get(module)
        if s is not None:
            fs = s.functions.get(f"{csum.name}.{method}")
            if fs is not None:
                return module, fs
        if _depth >= 4:
            return None
        for base in csum.bases:
            hit = self.resolve_class(module, base)
            if hit is not None:
                found = self.class_method(hit[0], hit[1], method,
                                          _depth + 1)
                if found is not None:
                    return found
        return None

    def class_of_receiver(self, module: str, func: FuncSummary,
                          recv: str) -> Optional[Tuple[str, ClassSummary]]:
        """Best-effort class of a receiver expression inside ``func``:
        ``self`` → enclosing class; locals via constructor assignment /
        annotation / ``alias = self``; ``self._y`` via the class's
        constructor-assigned attribute types."""
        head, _, rest = recv.partition(".")
        if head == "self":
            if func.cls is None:
                return None
            s = self.modules.get(module)
            csum = s.classes.get(func.cls) if s else None
            hit = (module, csum) if csum is not None else None
        else:
            ctor = func.var_types.get(head) or func.annotations.get(head)
            if ctor == "<self>":
                hit = self.class_of_receiver(module, func, "self")
            elif ctor is not None:
                hit = self.resolve_class(module, ctor)
            elif ">" in func.name:
                # closure lookup: ascend enclosing functions by key
                s = self.modules.get(module)
                parent_key = func.name.rsplit(">", 1)[0]
                parent = s.functions.get(parent_key) if s else None
                if parent is None and "." in parent_key:
                    # the parent key may cross a class boundary
                    parent = s.functions.get(
                        parent_key.rsplit(".", 1)[0]) if s else None
                hit = (self.class_of_receiver(module, parent, head)
                       if parent is not None else None)
            else:
                hit = None
            if (hit is None and head.isupper()
                    and head not in func.params
                    and head not in func.var_types):
                # module-level singleton: ``JOURNAL = EventJournal()``
                # at module scope types the receiver in every function
                # of the module.  ALL_CAPS only — the constant
                # convention makes local shadowing implausible, which
                # keeps the fallback under-approximate
                s = self.modules.get(module)
                mfunc = (s.functions.get(_Extractor._MODULE_KEY)
                         if s is not None else None)
                if mfunc is not None and mfunc is not func:
                    ctor = mfunc.var_types.get(head)
                    if ctor is not None and ctor != "<self>":
                        hit = self.resolve_class(module, ctor)
        # descend attribute chains through constructor-typed attrs:
        # app.worker → App.attr_types["worker"] → Worker
        while hit is not None and rest:
            attr, _, rest = rest.partition(".")
            cmod, csum = hit
            ctor = csum.attr_types.get(attr)
            hit = self.resolve_class(cmod, ctor) if ctor else None
        return hit


def file_hash(text: str) -> str:
    return hashlib.sha256(text.encode()).hexdigest()


def lint_sources_salt() -> str:
    """Hash of the lint package's own sources — editing any rule or this
    module invalidates every cached summary and cached finding."""
    pkg = pathlib.Path(__file__).resolve().parent
    h = hashlib.sha256(str(SUMMARY_VERSION).encode())
    for p in sorted(pkg.glob("*.py")):
        h.update(p.name.encode())
        h.update(p.read_bytes())
    return h.hexdigest()


def build_graph(summaries: Sequence[ModuleSummary]) -> SymbolGraph:
    modules: Dict[str, ModuleSummary] = {}
    by_path: Dict[str, ModuleSummary] = {}
    roots: Dict[str, pathlib.Path] = {}
    for s in summaries:
        if s.module is not None:
            modules.setdefault(s.module, s)
        by_path[s.path] = s
    for s in summaries:
        if s.module is not None and s.path:
            p = pathlib.Path(s.path)
            depth = s.module.count(".")
            root = p
            for _ in range(depth + 1):
                root = root.parent
            roots[s.module] = root
    return SymbolGraph(modules=modules, by_path=by_path,
                       package_roots=roots)
