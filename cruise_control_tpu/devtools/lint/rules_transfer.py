"""transfer-discipline — host↔device copies go through the ledger.

ISSUE 17's mesh observatory (``telemetry/mesh_budget.py``) accounts
every host↔device transfer: the trace shows copies as anonymous events,
the :class:`~cruise_control_tpu.telemetry.mesh_budget.TransferLedger`
names them per logical fn (``cc_transfer_bytes{direction=,fn=}``), and
the committed mesh budget gates their counts.  A raw ``jax.device_put``
— or an implicit D2H via ``np.asarray`` on a device array — outside the
sanctioned modules reopens the hole: the copy happens, the ledger stays
blind, and the budget gate can no longer prove where the transfer bytes
went.

Findings, outside the sanctioned modules (``ops/`` and ``telemetry/``
wholesale, plus ``models/builder.py`` — the device-model upload — and
``parallel/mesh.py`` — the sharding layout layer, whose device_put IS
the placement primitive):

* calls resolving to ``jax.device_put`` — dotted through a jax module
  alias (``jax.device_put(...)``, ``import jax as j; j.device_put``)
  or a direct-name import (``from jax import device_put``);
* ``np.asarray``/``np.array`` (any numpy module alias) whose first
  argument roots in a parameter annotated with a device-array type
  (``jax.Array``, ``jnp.ndarray``, ``jax.numpy.ndarray``,
  ``*DeviceModel``) — a provable implicit D2H fetch.

Route them through ``mesh_budget.device_put(x, fn=...)`` /
``mesh_budget.fetch(x, fn=...)`` (or ``note_transfer`` for sites that
perform the copy themselves).  Evaluated over the phase-1 summaries
(no re-parse).
"""

from __future__ import annotations

import pathlib
from typing import List, Set

from cruise_control_tpu.devtools.lint.findings import Finding

RULE_ID = "transfer-discipline"

#: numpy module names whose asarray/array materialize a device array
_NP_MODULES = frozenset(("np", "numpy", "onp"))

#: annotations (as written) that prove a param is a device array
_DEVICE_ANNOTATIONS = frozenset(
    ("jax.Array", "jnp.ndarray", "jax.numpy.ndarray"))

#: modules allowed to move bytes raw: the kernel/transfer layers
#: themselves plus the device-model upload and the sharding layout
_ALLOWED_DIRS = ("ops", "telemetry")
_ALLOWED_FILES = (
    ("models", "builder.py"),
    ("parallel", "mesh.py"),
)


def _allowed(path: str) -> bool:
    parts = pathlib.PurePath(path).parts
    if len(parts) >= 2 and parts[-2] in _ALLOWED_DIRS:
        return True
    return parts[-2:] in [tuple(sfx) for sfx in _ALLOWED_FILES]


def _is_device_annotation(ann: str) -> bool:
    return ann in _DEVICE_ANNOTATIONS or ann.endswith("DeviceModel")


class TransferDisciplineRule:
    id = RULE_ID
    summary = ("raw jax.device_put / implicit np.asarray on a device "
               "array outside ops/, telemetry/, models/builder.py and "
               "parallel/mesh.py — route transfers through the mesh "
               "observatory's ledger entry points (mesh_budget."
               "device_put / fetch) so cc_transfer_bytes{fn=} can name "
               "what the copy costs")
    project_rule = True

    def check_project(self, project) -> List[Finding]:
        findings: List[Finding] = []
        for s in project.summaries:
            if _allowed(s.path):
                continue
            jax_modules: Set[str] = set()
            np_modules: Set[str] = set(_NP_MODULES)
            direct_put: Set[str] = set()
            for _level, from_mod, name, alias in s.imports:
                if from_mod is None and name == "jax":
                    jax_modules.add(alias)
                elif from_mod is None and name == "numpy":
                    np_modules.add(alias)
                elif from_mod == "jax" and name == "device_put":
                    direct_put.add(alias)
            for fn in s.functions.values():
                for call in fn.calls:
                    head, _, tail = call.callee.rpartition(".")
                    if (call.callee in direct_put
                            or (tail == "device_put"
                                and (head in jax_modules
                                     or head == "jax"))):
                        findings.append(Finding(
                            path=s.path, line=call.lineno, rule=self.id,
                            message=(
                                f"raw {call.callee}() in "
                                f"{fn.name or '<module>'} bypasses the "
                                "transfer ledger — use telemetry/"
                                "mesh_budget.device_put(x, fn=...) so "
                                "the H2D bytes are charged to a named "
                                "fn in cc_transfer_bytes"
                            ),
                        ))
                        continue
                    if (tail in ("asarray", "array")
                            and head in np_modules and call.arg_exprs
                            and call.arg_exprs[0]):
                        root = call.arg_exprs[0].split(".", 1)[0]
                        ann = fn.annotations.get(root, "")
                        if root in fn.params and _is_device_annotation(ann):
                            findings.append(Finding(
                                path=s.path, line=call.lineno,
                                rule=self.id,
                                message=(
                                    f"{call.callee}({call.arg_exprs[0]}) "
                                    f"in {fn.name or '<module>'} "
                                    f"materializes a device array "
                                    f"({root}: {ann}) host-side outside "
                                    "the ledger — use telemetry/"
                                    "mesh_budget.fetch(x, fn=...) so "
                                    "the D2H bytes are charged to a "
                                    "named fn"
                                ),
                            ))
        return findings
