"""Flow-sensitive lock analysis shared by the three concurrency rules
(``lock-order``, ``blocking-under-lock``, ``lock-release-safety``).

Built once per run (lazily, cached on :class:`ProjectContext`), in
three passes:

1. **Intra** — for every function with a CFG, run the must-lockset
   analysis (``dataflow.must_locksets``) and record (a) each lock
   acquisition with the set of NAMED locks already held, (b) each call
   site executed while a named lock is held, and (c) each blocking op
   with the locks held at it.  Lock identity comes from the PR-10
   receiver-typing machinery: ``self._x`` resolves through
   ``ClassSummary.lock_names`` (captured from
   ``InstrumentedLock("name")`` constructor literals, including
   ``Condition(InstrumentedLock(...))`` wrapping), locals/globals
   through ``FuncSummary.lock_names``.  Unnamed locks are invisible to
   the ordering vocabulary (documented blind spot).

2. **Transitive fixpoints** — project acquisitions and blocking ops
   through the callgraph (skipping ``spawn`` edges: work handed to a
   thread or pool does not run under the caller's locks), keeping a
   representative witness chain per (function, lock) / (function, op).
   A ``with X:`` over a project context-manager class (e.g. the model
   generation lock wrapping the instrumented semaphore) is treated as
   a call to its ``__enter__``.

3. **Global edges** — every acquisition of ``B`` while ``A`` is held
   (directly or through a projected call) becomes an edge ``A → B``
   with a file:line witness chain.  Same-name self-edges are dropped:
   distinct instances sharing a name (every EventJournal is
   "journal.events") are indistinguishable statically.

The polarity everywhere is UNDER-approximation: must-locksets only
report a lock held when it is held on every path, and unresolved
receivers contribute nothing — the rules miss edges rather than invent
them.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Set, Tuple

from cruise_control_tpu.devtools.lint import cfg as cfg_mod
from cruise_control_tpu.devtools.lint import dataflow
from cruise_control_tpu.devtools.lint.callgraph import fid
from cruise_control_tpu.devtools.lint.graph import (
    BlockingOp,
    FuncSummary,
)

#: receiver constructor tails that make a zero-arg ``.get()`` /
#: ``.put()`` a blocking queue op
_QUEUE_CTORS = frozenset((
    "Queue", "LifoQueue", "PriorityQueue", "SimpleQueue", "JoinableQueue",
))

#: witness-chain depth cap (renders stay readable; deeper chains add
#: nothing a reviewer can act on)
_CHAIN_CAP = 8


@dataclasses.dataclass(frozen=True)
class Acq:
    """One lock acquisition with flow-sensitive context."""

    lock: str
    path: str
    line: int
    held: frozenset            # named locks held BEFORE this acquire
    via: str                   # "with" | "call"


@dataclasses.dataclass(frozen=True)
class BlockSite:
    """One blocking op, resolved and filtered for applicability."""

    path: str
    line: int
    desc: str
    #: lock the op itself releases while blocked (Condition.wait) —
    #: subtracted from the held set before reporting
    own: Optional[str] = None


def _label(function_id: str) -> str:
    return function_id.split(":", 1)[1]


class LockFlow:
    def __init__(self, project) -> None:
        t0 = time.perf_counter()
        self.graph = project.graph
        self.cg = project.callgraph
        #: fid → direct acquisitions (named locks only)
        self.acquires: Dict[str, List[Acq]] = {}
        #: fid → (callee fid, line, held) for call sites under a lock
        self.calls_held: Dict[str, List[Tuple[str, int, frozenset]]] = {}
        #: fid → (site, held-at-op) for every applicable blocking op
        self.direct_blocking: Dict[str, List[Tuple[BlockSite,
                                                   frozenset]]] = {}
        #: (A, B) → witness chain of (path, line, note) for "A held
        #: while B acquired"; first witness wins, count accumulates
        self.edge_witness: Dict[Tuple[str, str], Tuple] = {}
        self.edge_count: Dict[Tuple[str, str], int] = {}
        #: every named lock seen anywhere (graph nodes incl. isolated)
        self.lock_vocab: Set[str] = set()
        self._resolve_memo: Dict[Tuple[str, str, str], Optional[str]] = {}
        #: factory-resolution cycle breaker: keys currently mid-resolve
        self._resolving: Set[Tuple[str, str, str]] = set()
        #: synthesized call edges: with-statement → __enter__
        self._synth: Dict[str, List[Tuple[str, int]]] = {}
        self._build_intra()
        self.trans_acquires = self._fix_acquires()
        self.trans_blocking = self._fix_blocking()
        self._project_edges()
        self.build_ms = (time.perf_counter() - t0) * 1000.0

    # ---- lock identity ----------------------------------------------------------
    def resolve_lock(self, module: str, func: FuncSummary,
                     obj: str) -> Optional[str]:
        """Dotted lock expression as written → named-lock id, or None
        when unnamed/unresolvable."""
        key = (module, func.name, obj)
        if key in self._resolve_memo:
            return self._resolve_memo[key]
        out: Optional[str] = None
        s = self.graph.modules.get(module)
        if "." not in obj:
            out = func.lock_names.get(obj)
            if out is None and s is not None:
                mod_fn = s.functions.get("<module>")
                if mod_fn is not None:
                    out = mod_fn.lock_names.get(obj)
            if out is None and key not in self._resolving:
                self._resolving.add(key)
                try:
                    out = self._factory_lock(module, func, obj)
                finally:
                    self._resolving.discard(key)
        else:
            recv, attr = obj.rsplit(".", 1)
            hit = self.graph.class_of_receiver(module, func, recv)
            if hit is not None:
                out = hit[1].lock_names.get(attr)
        self._resolve_memo[key] = out  # cclint: disable=cache-key-discipline -- analysis-lifetime memo: a LockFlow is built once per lint run over an immutable SymbolGraph and discarded with it; nothing can go stale
        return out

    def _factory_lock(self, module: str, func: FuncSummary,
                      var: str) -> Optional[str]:
        """``lock = factory(); with lock:`` — resolve through a
        context-manager factory.  The bound callee must be a project
        function whose every ``return`` constructs the SAME
        ``Guard(lock_expr, ...)``, where ``Guard.__enter__`` performs
        an acquire; the first constructor argument is then resolved as
        a lock expression in the factory's own scope (the
        model-generation-lock idiom in ``monitor/load_monitor.py``).
        Anything short of that exact shape yields None — the
        under-approximation the must-lockset polarity requires."""
        callee = func.var_types.get(var)
        if not callee:
            return None
        target = self.cg._resolve(module, func, callee)
        if target is None:
            return None
        tmod, tkey = target.split(":", 1)
        ts = self.graph.modules.get(tmod)
        tfunc = ts.functions.get(tkey) if ts is not None else None
        if tfunc is None:
            return None
        shapes = set(tfunc.returns_calls)
        if len(shapes) != 1:
            return None
        ctor, arg = next(iter(shapes))
        if arg is None:
            return None
        hit = self.graph.resolve_class(tmod, ctor)
        if hit is None:
            return None
        found = self.graph.class_method(hit[0], hit[1], "__enter__")
        if found is None or found[1].cfg is None:
            return None
        if not any(e.kind == cfg_mod.ACQUIRE
                   for b in found[1].cfg.blocks for e in b.events):
            return None
        return self.resolve_lock(tmod, tfunc, arg)

    def _factory_enter(self, module: str, func: FuncSummary,
                       obj: str) -> Optional[str]:
        """``with factory_call(...):`` — fid of the returned guard's
        ``__enter__``, when the callee is a project function whose
        every ``return`` constructs the SAME project class (the
        progress-step idiom: ``with progress.step(...)``).  Lock state
        projects through the __enter__ like any other call."""
        target = self.cg._resolve(module, func, obj)
        if target is None:
            return None
        tmod, tkey = target.split(":", 1)
        ts = self.graph.modules.get(tmod)
        tfunc = ts.functions.get(tkey) if ts is not None else None
        if tfunc is None or not tfunc.returns_calls:
            return None
        ctors = {c for c, _ in tfunc.returns_calls}
        if len(ctors) != 1:
            return None
        hit = self.graph.resolve_class(tmod, next(iter(ctors)))
        if hit is None:
            return None
        found = self.graph.class_method(hit[0], hit[1], "__enter__")
        if found is None:
            return None
        t = fid(found[0], found[1].name)
        return t if t in self.cg.funcs else None

    def _recv_type(self, module: str, func: FuncSummary,
                   recv: str) -> Optional[str]:
        """Constructor-dotted type of a receiver expression (queue
        detection) — locals first, then class attribute types."""
        if "." not in recv:
            return func.var_types.get(recv) or func.annotations.get(recv)
        owner, attr = recv.rsplit(".", 1)
        hit = self.graph.class_of_receiver(module, func, owner)
        if hit is None:
            return None
        return hit[1].attr_types.get(attr)

    def _enter_target(self, module: str, func: FuncSummary,
                      obj: str) -> Optional[str]:
        """``with X:`` over a project context-manager class → the fid
        of its ``__enter__`` (lock state projects through it)."""
        hit = self.graph.class_of_receiver(module, func, obj)
        if hit is None:
            return None
        found = self.graph.class_method(hit[0], hit[1], "__enter__")
        if found is None:
            return None
        target = fid(found[0], found[1].name)
        return target if target in self.cg.funcs else None

    # ---- pass 1: intra-procedural -----------------------------------------------
    def _build_intra(self) -> None:
        for mod, s in self.graph.modules.items():
            for csum in s.classes.values():
                self.lock_vocab.update(csum.lock_names.values())
            for fkey, func in s.functions.items():
                self.lock_vocab.update(func.lock_names.values())
                f_id = fid(mod, fkey)
                held_at_call: Dict[Tuple[str, int], frozenset] = {}
                if func.cfg is not None:
                    self._scan_cfg(mod, s.path, f_id, func, held_at_call)
                for op in func.blocking_ops:
                    site = self._blocking_site(mod, s.path, func, op)
                    if site is None:
                        continue
                    held = held_at_call.get((op.callee, op.lineno),
                                            frozenset())
                    self.direct_blocking.setdefault(f_id, []).append(
                        (site, held))

    def _scan_cfg(self, mod: str, path: str, f_id: str, func: FuncSummary,
                  held_at_call: Dict[Tuple[str, int], frozenset]) -> None:
        states = dataflow.must_locksets(
            func.cfg, lambda e: self.resolve_lock(mod, func, e.obj))
        for (b, i), held in sorted(states.items()):
            event = func.cfg.blocks[b].events[i]
            if event.kind == cfg_mod.ACQUIRE:
                lid = self.resolve_lock(mod, func, event.obj)
                if lid is not None:
                    self.acquires.setdefault(f_id, []).append(
                        Acq(lid, path, event.lineno, held, event.via))
                    for h in sorted(held):
                        self._edge(h, lid, (
                            (path, event.lineno, f"acquires {lid}"),))
                elif event.via == "with":
                    target = self._enter_target(mod, func, event.obj)
                    if target is not None:
                        self._synth.setdefault(f_id, []).append(
                            (target, event.lineno))
                        if held:
                            self.calls_held.setdefault(f_id, []).append(
                                (target, event.lineno, held))
            elif event.kind == cfg_mod.CALL:
                held_at_call[(event.obj, event.lineno)] = held
                if held:
                    target = self.cg._resolve(mod, func, event.obj)
                    if target is not None and target in self.cg.funcs:
                        self.calls_held.setdefault(f_id, []).append(
                            (target, event.lineno, held))
                if event.via == "with":
                    # `with factory(...):` — the returned guard is
                    # entered unconditionally; project its __enter__
                    enter = self._factory_enter(mod, func, event.obj)
                    if enter is not None:
                        self._synth.setdefault(f_id, []).append(
                            (enter, event.lineno))
                        if held:
                            self.calls_held.setdefault(f_id, []).append(
                                (enter, event.lineno, held))

    def _blocking_site(self, mod: str, path: str, func: FuncSummary,
                       op: BlockingOp) -> Optional[BlockSite]:
        if op.kind == "queue":
            recv = op.callee.rsplit(".", 1)[0]
            if "." not in op.callee:
                return None
            t = self._recv_type(mod, func, recv)
            if t is None or t.rsplit(".", 1)[-1] not in _QUEUE_CTORS:
                return None
            return BlockSite(path, op.lineno, op.desc)
        if op.kind == "wait":
            recv = op.callee.rsplit(".", 1)[0]
            own = (self.resolve_lock(mod, func, recv)
                   if "." in op.callee else None)
            return BlockSite(path, op.lineno, op.desc, own=own)
        return BlockSite(path, op.lineno, op.desc)

    # ---- pass 2: callgraph fixpoints --------------------------------------------
    def _edges_from(self, caller: str):
        for e in self.cg.edges.get(caller, ()):
            if not e.spawn:
                yield e.callee, e.lineno
        for callee, line in self._synth.get(caller, ()):
            yield callee, line

    def _caller_path(self, caller: str) -> str:
        s = self.graph.modules.get(caller.split(":", 1)[0])
        return s.path if s is not None else ""

    def _fix_acquires(self) -> Dict[str, Dict[str, Tuple]]:
        ta: Dict[str, Dict[str, Tuple]] = {}
        for f_id, acqs in self.acquires.items():
            d = ta.setdefault(f_id, {})
            for a in acqs:
                d.setdefault(a.lock,
                             ((a.path, a.line, f"acquires {a.lock}"),))
        callers = sorted(set(self.cg.edges) | set(self._synth))
        changed = True
        while changed:
            changed = False
            for caller in callers:
                cpath = self._caller_path(caller)
                d = ta.get(caller)
                for callee, line in self._edges_from(caller):
                    sub = ta.get(callee)
                    if not sub:
                        continue
                    if d is None:
                        d = ta.setdefault(caller, {})
                    for lock, chain in sub.items():
                        if lock not in d and len(chain) < _CHAIN_CAP:
                            d[lock] = ((cpath, line,
                                        f"→ {_label(callee)}"),) + chain
                            changed = True
        return ta

    def _fix_blocking(self) -> Dict[str, Dict[Tuple[str, int],
                                              Tuple[BlockSite, Tuple]]]:
        tb: Dict[str, Dict[Tuple[str, int], Tuple[BlockSite, Tuple]]] = {}
        for f_id, sites in self.direct_blocking.items():
            d = tb.setdefault(f_id, {})
            for site, _held in sites:
                d.setdefault((site.path, site.line), (site, ()))
        callers = sorted(set(self.cg.edges) | set(self._synth))
        changed = True
        while changed:
            changed = False
            for caller in callers:
                cpath = self._caller_path(caller)
                d = tb.get(caller)
                for callee, line in self._edges_from(caller):
                    sub = tb.get(callee)
                    if not sub:
                        continue
                    if d is None:
                        d = tb.setdefault(caller, {})
                    for key, (site, chain) in sub.items():
                        if key not in d and len(chain) < _CHAIN_CAP \
                                and len(d) < 64:
                            d[key] = (site, ((cpath, line,
                                              f"→ {_label(callee)}"),)
                                      + chain)
                            changed = True
        return tb

    # ---- pass 3: the global lock-order graph ------------------------------------
    def _edge(self, a: str, b: str, witness: Tuple) -> None:
        if a == b:
            return  # same-name self-edges: distinct instances, dropped
        self.lock_vocab.update((a, b))
        key = (a, b)
        self.edge_count[key] = self.edge_count.get(key, 0) + 1
        self.edge_witness.setdefault(key, witness)

    def _project_edges(self) -> None:
        for f_id in sorted(self.calls_held):
            path = self._caller_path(f_id)
            for callee, line, held in self.calls_held[f_id]:
                sub = self.trans_acquires.get(callee)
                if not sub:
                    continue
                for lock, chain in sorted(sub.items()):
                    for h in sorted(held):
                        self._edge(h, lock, (
                            (path, line, f"→ {_label(callee)}"),) + chain)

    # ---- cycle detection --------------------------------------------------------
    def cycles(self) -> List[List[str]]:
        """Elementary cycles of the lock-order graph, one representative
        per strongly connected component, nodes in cycle order."""
        adj: Dict[str, List[str]] = {}
        for a, b in self.edge_witness:
            adj.setdefault(a, []).append(b)
        for v in adj.values():
            v.sort()
        sccs = _tarjan(adj)
        out: List[List[str]] = []
        for comp in sccs:
            if len(comp) < 2:
                continue
            cyc = _cycle_in(sorted(comp), adj)
            if cyc:
                out.append(cyc)
        out.sort()
        return out

    def witness_chain(self, a: str, b: str) -> Tuple:
        return self.edge_witness.get((a, b), ())


def _tarjan(adj: Dict[str, List[str]]) -> List[List[str]]:
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]
    nodes = sorted(set(adj) | {b for vs in adj.values() for b in vs})

    def strongconnect(v: str) -> None:
        # iterative DFS (the package graph is small, but recursion
        # limits are not a correctness budget)
        work = [(v, iter(adj.get(v, ())))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(adj.get(w, ()))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp: List[str] = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                sccs.append(comp)

    for v in nodes:
        if v not in index:
            strongconnect(v)
    return sccs


def _cycle_in(comp: List[str], adj: Dict[str, List[str]]) -> List[str]:
    """Shortest simple cycle through the SCC's smallest node (BFS back
    to the start; the closing edge is last → first)."""
    members = set(comp)
    start = comp[0]
    parent: Dict[str, Optional[str]] = {start: None}
    frontier = [start]
    while frontier:
        nxt: List[str] = []
        for node in frontier:
            for w in adj.get(node, ()):
                if w == start:
                    path: List[str] = []
                    n: Optional[str] = node
                    while n is not None:
                        path.append(n)
                        n = parent[n]
                    return list(reversed(path))
                if w in members and w not in parent:
                    parent[w] = node
                    nxt.append(w)
        frontier = nxt
    return []
