"""cache-key-discipline — model-state caches must carry a freshness term.

The delta-replan subsystem (this PR) lives and dies on cache freshness:
a plan, a memo, a table cached against the *model* is only servable while
the model generation it was computed against still describes the cluster.
The stale-plan-served-as-fresh bug — a cache keyed on nothing, or an
attribute cache with no version/TTL companion — is invisible in review
and catastrophic in production (the executor happily executes a plan for
a cluster that no longer exists).  This rule makes the discipline
checkable at lint time.

Flagged constructions (non-test code):

* **Keyed cache stores** ``self.<X>[key] = value`` where ``X`` looks like
  a cache (``*cache*``/``*memo*`` in the attribute name) and neither
  holds: the key expression carries a generation-ish term (an identifier
  or attribute containing ``gen``/``generation``/``version``/``epoch``/
  ``seq``/``window``/``mark``/``fingerprint``), or the enclosing class
  clears/reassigns that cache inside a method named like
  ``invalidate``/``clear``/``reset``/``evict``/``expire`` (clear-on-
  mutation is version-keying by other means).
* **Attribute cache stores** ``self.<X> = value`` where ``X`` starts with
  ``cache``/``cached`` (modulo a leading underscore) and none of: a
  sibling store in the same method records freshness (an attribute whose
  name carries a generation-ish term or ends in ``_at``/``_at_ms``/
  ``_time``/``_ms``), the stored value's constructor call carries a
  generation-ish keyword (e.g. ``CachedPlan(generation=...)``), or the
  class has an invalidate-style method reassigning/clearing it.

Never flagged: stores of ``None``/empty literals (that IS invalidation),
lock/semaphore attributes, and non-``self`` locals (a function-local dict
dies with the call — it cannot serve stale across model generations).
Deliberate exceptions take the usual
``# cclint: disable=cache-key-discipline -- reason``.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional

from cruise_control_tpu.devtools.lint.context import FileContext
from cruise_control_tpu.devtools.lint.findings import Finding

RULE_ID = "cache-key-discipline"

_CACHE_SUBSCRIPT = re.compile(r"(cache|memo)", re.IGNORECASE)
_CACHE_ATTR = re.compile(r"^_?(cache|cached)(_|$)", re.IGNORECASE)
_FRESHNESS = re.compile(
    r"(gen|generation|version|epoch|seq|window|mark|fingerprint)",
    re.IGNORECASE,
)
_SIBLING_FRESH = re.compile(
    r"(gen|generation|version|epoch|seq|mark|fingerprint)|(_at|_at_ms|_time|_ms)$",
    re.IGNORECASE,
)
_INVALIDATOR = re.compile(
    r"(invalidate|clear|reset|evict|expire)", re.IGNORECASE
)
_LOCK_CTORS = {"Lock", "RLock", "Semaphore", "BoundedSemaphore", "Condition"}


def _names_in(node: ast.AST) -> List[str]:
    out = []
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            out.append(n.id)
        elif isinstance(n, ast.Attribute):
            out.append(n.attr)
        elif isinstance(n, ast.Constant) and isinstance(n.value, str):
            out.append(n.value)
    return out


def _is_self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _is_trivial_value(value: ast.AST) -> bool:
    """None / empty literal stores are invalidation, not caching."""
    if isinstance(value, ast.Constant) and value.value is None:
        return True
    if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.Tuple)):
        return not getattr(value, "keys", None) and not getattr(
            value, "elts", None
        )
    return False


def _is_lock_value(value: ast.AST) -> bool:
    if not isinstance(value, ast.Call):
        return False
    f = value.func
    name = f.attr if isinstance(f, ast.Attribute) else getattr(f, "id", "")
    return name in _LOCK_CTORS


def _class_invalidates(cls: ast.ClassDef, attr: str) -> bool:
    """True when some invalidate-style method clears / reassigns /
    deletes ``self.<attr>`` — the clear-on-mutation version key."""
    for item in ast.walk(cls):
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not _INVALIDATOR.search(item.name):
            continue
        for n in ast.walk(item):
            # self.<attr>.clear()
            if (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "clear"
                and _is_self_attr(n.func.value) == attr
            ):
                return True
            # self.<attr> = <anything> (reassignment drops the cache)
            if isinstance(n, ast.Assign) and any(
                _is_self_attr(t) == attr for t in n.targets
            ):
                return True
            # del self.<attr>[...] / del self.<attr>
            if isinstance(n, ast.Delete):
                for t in n.targets:
                    base = t.value if isinstance(t, ast.Subscript) else t
                    if _is_self_attr(base) == attr:
                        return True
    return False


def _value_has_fresh_kwarg(value: ast.AST) -> bool:
    return isinstance(value, ast.Call) and any(
        kw.arg and _FRESHNESS.search(kw.arg) for kw in value.keywords
    )


def find_undisciplined_caches(tree: ast.AST, nodes=None) -> List[tuple]:
    out: List[tuple] = []
    for cls in (nodes if nodes is not None else ast.walk(tree)):
        if not isinstance(cls, ast.ClassDef):
            continue
        for fn in [
            n for n in ast.walk(cls)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]:
            fresh_sibling = any(
                isinstance(st, ast.Assign)
                and any(
                    (a := _is_self_attr(t)) is not None
                    and _SIBLING_FRESH.search(a)
                    for t in st.targets
                )
                for st in ast.walk(fn)
            )
            for st in ast.walk(fn):
                if not isinstance(st, ast.Assign):
                    continue
                for target in st.targets:
                    # self.<cache>[key] = value
                    if isinstance(target, ast.Subscript):
                        attr = _is_self_attr(target.value)
                        if attr is None or not _CACHE_SUBSCRIPT.search(attr):
                            continue
                        if _is_trivial_value(st.value):
                            continue
                        key_ok = any(
                            _FRESHNESS.search(nm)
                            for nm in _names_in(target.slice)
                        )
                        if key_ok or _class_invalidates(cls, attr):
                            continue
                        out.append((
                            st.lineno,
                            f"cache store self.{attr}[...] is keyed on "
                            "model state but carries no generation/version "
                            "term and the class never invalidates it — a "
                            "stale entry will be served as fresh (add a "
                            "generation component to the key, or clear the "
                            "cache in an invalidate()-style method)",
                        ))
                        continue
                    # self.<cached_x> = value
                    attr = _is_self_attr(target)
                    if attr is None or not _CACHE_ATTR.search(attr):
                        continue
                    if attr.endswith("_lock") or _is_lock_value(st.value):
                        continue
                    if _is_trivial_value(st.value):
                        continue
                    if fresh_sibling or _value_has_fresh_kwarg(st.value):
                        continue
                    if _class_invalidates(cls, attr):
                        continue
                    out.append((
                        st.lineno,
                        f"cached attribute self.{attr} is stored with no "
                        "freshness companion (no generation/TTL sibling "
                        "store, no generation field on the cached value, "
                        "no invalidate path) — nothing can ever tell this "
                        "cache is stale",
                    ))
    return out


class CacheKeyDisciplineRule:
    id = RULE_ID
    summary = (
        "caches/memos of model-derived state must carry a generation/"
        "version term (or a clear-on-invalidate path)"
    )

    def check_file(self, ctx: FileContext) -> List[Finding]:
        return [
            Finding(ctx.path, lineno, self.id, message)
            for lineno, message in find_undisciplined_caches(ctx.tree,
                                                 ctx.all_nodes)
        ]
